"""The SLO-aware request gateway: admission, scheduling, lifecycle, streaming.

``ServingGateway`` wraps — never replaces — a :class:`~..serving.ContinuousBatcher`.
The engine stays a pure throughput machine (slots, compiled prefill/decode); the
gateway owns everything a loaded service needs above it:

- **Admission control / backpressure** — a bounded queue (``max_queue``) and a
  cost-estimated token budget (``max_queued_tokens``). Over a bound, either the
  newcomer is REJECTED with a machine-readable reason, or (``overload="shed"``)
  the least-urgent queued request is shed in its favor, lowest-priority-first.
  Cost estimation calls the engine's own ``_plan_prefill`` — the same bucket
  ladder the compile cache warms — so admission can never route a request to a
  compile shape the engine wouldn't itself pick.
- **Scheduling** — one pluggable :class:`~.policies.SchedulerPolicy` (fifo /
  priority-with-aging / EDF / WFQ) decides admission order into free slots. The
  gateway only hands the engine as many requests as it has free lanes, so the
  engine's internal FIFO never reorders a policy decision.
- **Lifecycle** — per-request deadlines (queued requests expire, running ones are
  evicted mid-decode and their lane admits new work on the very next ``step()``),
  cooperative ``cancel(uid)``, optional priority preemption with a bounded
  retry-on-eviction budget, and an ``on_token`` streaming callback fed in exact
  generation order.
- **SLO observability** — per-request queue-wait/TTFT/TPOT and gateway
  p50/p95/p99 summaries (``telemetry.slo``), emitted as telemetry records and
  surfaced in ``stats()``.

The gateway adds no device programs: every jit dispatch still happens inside the
engine, so a gateway-fronted run compiles exactly what an engine-only run does
(asserted by ``tests/test_serving_gateway.py`` via ``CompileMonitor``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

import numpy as np

from ..generation import GenerationConfig
from ..serving import KVBudgetError, normalize_submit
from ..telemetry.clocks import resolve_clock
from ..telemetry.slo import (
    GATEWAY_REQUEST_SCHEMA,
    GATEWAY_SLO_SCHEMA,
    slo_summary,
)
from ..utils.dataclasses import GatewayConfig
from .policies import make_policy

__all__ = [
    "CircuitBreaker",
    "GatewayRequest",
    "ServingGateway",
    "QUEUED",
    "RUNNING",
    "DONE",
    "REJECTED",
    "SHED",
    "CANCELLED",
    "EVICTED",
    "EXPIRED",
    "FAILED",
    "TERMINAL_STATUSES",
]

# ---------------------------------------------------------------- status model
QUEUED = "queued"        # held by the scheduler policy
RUNNING = "running"      # admitted into an engine slot
DONE = "done"            # finished normally (EOS / max_new_tokens)
REJECTED = "rejected"    # refused at admission (reason: queue_full/token_budget/
#                          kv_budget/unservable/circuit_open/circuit_probe/
#                          fleet_down)
SHED = "shed"            # removed from the queue by overload shedding
CANCELLED = "cancelled"  # withdrawn by cancel(uid) (reason says queued vs running)
EVICTED = "evicted"      # lost its slot (preemption) with no retry budget left
EXPIRED = "expired"      # deadline passed (reason says queued vs running)
FAILED = "failed"        # quarantined by the engine's fault boundary (reason:
#                          step_fault:<kind>/prefill_fault:<kind>/...)

TERMINAL_STATUSES = frozenset(
    {DONE, REJECTED, SHED, CANCELLED, EVICTED, EXPIRED, FAILED}
)

_UNSET = object()  # submit() sentinel: "apply the config default"


@dataclasses.dataclass
class GatewayRequest:
    """One request's full gateway lifecycle (scheduling inputs, state, SLO times).

    ``status`` walks queued → running → one of :data:`TERMINAL_STATUSES`; requests
    refused at admission are born terminal (``rejected``/``shed`` with a
    machine-readable ``reason``) rather than raising — overload is an operating
    condition, not a caller bug. Timestamps come from the gateway's clock;
    ``ttft_s`` includes queue wait AND prefill (the client-visible first-token
    latency), ``tpot_s`` is the mean inter-token gap after the first."""

    uid: int
    prompt: np.ndarray
    gen: GenerationConfig
    rng: Optional[object] = None
    priority: int = 0
    deadline_at: Optional[float] = None   # absolute, on the gateway clock
    tenant: str = "default"
    on_token: Optional[Callable[[int], None]] = None
    on_retry: Optional[Callable[[], None]] = None  # stream-reset signal on preemption retry
    max_retries: int = 0
    cost: int = 0                         # estimated cache tokens (padded prefill + budget)
    # lifecycle
    status: str = QUEUED
    reason: Optional[str] = None
    tokens: list = dataclasses.field(default_factory=list)
    retries_used: int = 0
    # Recovery accounting: in-engine crash-recovery re-admissions this request
    # survived (copied off the engine request), and whole-gateway replay
    # attempts after an engine restart (reattach_engine) — replays do NOT
    # consume the preemption retry budget (a restart is not the request's
    # fault), but they do advance the trace attempt index.
    recoveries: int = 0
    replays: int = 0
    # SLO timestamps (gateway clock)
    t_submit: float = 0.0
    t_enqueued: float = 0.0               # this attempt's queue entry (== t_submit
    #                                       until a preemption retry requeues)
    t_admit: Optional[float] = None
    t_first_token: Optional[float] = None
    t_last_token: Optional[float] = None
    t_done: Optional[float] = None
    n_streamed: int = 0
    _engine_req: Optional[object] = dataclasses.field(default=None, repr=False)
    _trace: Optional[object] = dataclasses.field(default=None, repr=False)
    #: Replica id currently serving this request (fleet routing only; None on
    #: a single-engine gateway and while queued).
    _rid: Optional[int] = dataclasses.field(default=None, repr=False)

    # ------------------------------------------------------------ SLO metrics
    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.t_admit is None:
            return None
        return self.t_admit - self.t_submit

    @property
    def ttft_s(self) -> Optional[float]:
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_submit

    @property
    def tpot_s(self) -> Optional[float]:
        if self.t_first_token is None or self.t_last_token is None or self.n_streamed < 2:
            return None
        return (self.t_last_token - self.t_first_token) / (self.n_streamed - 1)

    @property
    def deadline_met(self) -> Optional[bool]:
        if self.deadline_at is None or self.t_done is None:
            return None
        return self.status == DONE and self.t_done <= self.deadline_at

    @property
    def terminal(self) -> bool:
        return self.status in TERMINAL_STATUSES


class CircuitBreaker:
    """The closed → open → half-open failure-isolation state machine, extracted
    so ONE implementation fronts both the single-engine gateway (where OPEN
    gates the whole front door) and each fleet replica (where OPEN isolates one
    replica while the router keeps dispatching to the healthy ones —
    ``serving_gateway.fleet``).

    Pure state over an injected notion of time: the owner feeds it failure
    deltas (:meth:`record_failures`) and admission attempts (:meth:`gate`) and
    acts on the verdicts — the breaker never touches engines, queues or
    telemetry, so the owner's side effects (records, degradation rungs,
    failover) ride the transitions it reports rather than hiding inside it."""

    def __init__(self, threshold: int, window_s: float, cooldown_s: float):
        self.threshold = int(threshold)
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.state = "closed"
        #: The one request admitted while half-open; its terminal fate decides
        #: the next state (owner calls back through its probe-verdict hook).
        self.probe_uid: Optional[int] = None
        self.openings = 0
        self.closings = 0
        self._fail_times: List[float] = []
        self._opened_at = 0.0

    @property
    def enabled(self) -> bool:
        return self.threshold > 0

    def gate(self, uid: int, now: float) -> Optional[str]:
        """Gate one admission/routing decision for request ``uid``: None admits
        (assigning ``uid`` as the probe when half-open with none outstanding);
        otherwise the machine-readable refusal reason — ``circuit_open`` while
        the cooldown runs, ``circuit_probe`` while another request IS the
        outstanding probe. The reasons are distinct on purpose: probe
        contention (healthy-looking, waiting on one verdict) and a hard-open
        breaker (cooling down after failures) call for different operator
        responses, and a shared reason string hid which one was happening."""
        if not self.enabled or self.state == "closed":
            return None
        if self.state == "open":
            if now - self._opened_at >= self.cooldown_s:
                self.state = "half_open"
                self.probe_uid = None
            else:
                return "circuit_open"
        if self.probe_uid is None:
            self.probe_uid = uid
            return None
        return "circuit_probe"

    def record_failures(self, delta: int, now: float) -> bool:
        """Feed the failure delta observed since the last read; True when the
        observation crossed the open threshold (>= ``threshold`` failures
        inside ``window_s`` while closed, or ANY failure during a half-open
        probe period) — the caller then performs :meth:`open` so its own
        side effects ride the transition."""
        if not self.enabled or delta <= 0:
            return False
        self._fail_times.extend([now] * delta)
        self._fail_times = [t for t in self._fail_times
                            if now - t <= self.window_s]
        if self.state == "half_open":
            return True
        return (self.state == "closed"
                and len(self._fail_times) >= self.threshold)

    def open(self, now: float) -> None:
        self.state = "open"
        self._opened_at = now
        self.probe_uid = None
        self.openings += 1

    def close(self, now: float) -> None:
        self.state = "closed"
        self._fail_times = []
        self.probe_uid = None
        self.closings += 1

    def force_half_open(self) -> None:
        """Jump straight to half-open with a clean slate — the fleet's restart
        re-admission warm-up: a freshly restarted replica earns full routing by
        completing one probe request, exactly like a cooled-down breaker."""
        self.state = "half_open"
        self.probe_uid = None
        self._fail_times = []

    def __repr__(self) -> str:
        return (f"CircuitBreaker(state={self.state!r}, "
                f"openings={self.openings}, closings={self.closings})")


class ServingGateway:
    """Admission + scheduling + lifecycle tier above one ``ContinuousBatcher``.

    ``clock`` defaults to the sanctioned wall clock (``telemetry.clocks``);
    tests inject a manual clock to make deadlines/aging deterministic.
    ``telemetry`` accepts the same ``Telemetry`` object the engine takes
    (records share its sinks)."""

    def __init__(self, engine, config: Optional[GatewayConfig] = None,
                 telemetry=None, clock: Optional[Callable[[], float]] = None,
                 tracer=None):
        if config is None:
            config = GatewayConfig(enabled=True)
        # Resolve the time domain FIRST: everything the gateway builds or
        # adopts below (tracer, metrics plane, recorder, breakers, replicas)
        # inherits this one clock.
        clock = resolve_clock(clock)
        self.engine = engine
        self.config = config
        # Multi-step decode pairing (config.decode_steps, docs/
        # multistep_decode.md): the engine owns the super-step depth — it
        # shapes the compiled programs — so the gateway only verifies the
        # config matches the engine it was handed. Failing here (not at first
        # step) keeps a mis-stamped deployment from serving with the wrong
        # streaming granularity/deadline overshoot characteristics.
        if config.decode_steps > 1 and getattr(
            engine, "multi_step", 1
        ) != config.decode_steps:
            raise ValueError(
                f"GatewayConfig.decode_steps={config.decode_steps} but the "
                f"engine runs decode_steps={getattr(engine, 'multi_step', 1)}: "
                "construct the ContinuousBatcher with the same decode_steps "
                "(the engine owns the knob; the gateway only validates it)"
            )
        self.telemetry = telemetry
        # Request-scoped tracing (``telemetry.tracing``): the gateway OPENS the
        # trace at submit (trace_id = gateway uid + monotonic start) and emits the
        # scheduling-side spans (queue, shed, preempt/retry, terminal); the engine
        # — handed the SAME tracer — emits the execution-side spans (admit,
        # prefill, decode rounds) against the binding made at admission.
        self.tracer = tracer
        if tracer is not None:
            if getattr(engine, "tracer", None) is None:
                engine.tracer = tracer  # one tracer threads the whole lifecycle
            # Spans must share the gateway's timeline: deadlines, ttft_s and
            # every gateway-side span time come from this clock, and the engine
            # stamps its prefill/decode spans off the tracer's. A tracer left on
            # a different clock (e.g. default monotonic vs an injected virtual
            # clock) would split one trace across two time domains. (A disabled
            # tracer never reads its clock — leave it as built.)
            if tracer.enabled:
                tracer._clock = clock
        self._clock = clock
        # Live metrics plane (config.metrics): a Telemetry SINK folding the
        # record stream — the records the gateway/engine/fleet already emit,
        # zero new emit sites — into live counters/gauges/sliding-window
        # histograms on the gateway's own clock (virtual-clock replays get
        # virtual-time windows). ``stats()`` exposes the snapshot; alert
        # engines (telemetry.alerts) attach to the plane, not the gateway.
        self.metrics = None
        if config.metrics and telemetry is not None and getattr(
            telemetry, "enabled", False
        ):
            from ..telemetry.metrics import MetricsPlane

            self.metrics = MetricsPlane(
                telemetry, clock=clock, window_s=config.metrics_window_s
            )
        # Flight-recorder wiring (config.capsule_state): when the telemetry
        # carries a FlightRecorder, the gateway registers its own state
        # snapshot as a capsule state provider — queue/counters, breaker,
        # engine lane table, fault-plan firing log — and binds the recorder
        # to the metrics plane so ring evictions are drop-accounted. Inert
        # when no recorder is configured.
        recorder = getattr(telemetry, "recorder", None)
        if (config.capsule_state and recorder is not None
                and getattr(recorder, "enabled", False)):
            if self.metrics is not None:
                recorder.bind_metrics(self.metrics)
            recorder.bind_clock(self._clock)
            recorder.add_state_provider("gateway", self._capsule_state)
        self._policy = make_policy(config)
        self._uid = 0
        self._queued_cost = 0
        self._running: Dict[int, GatewayRequest] = {}  # engine uid → gateway request
        self._all: Dict[int, GatewayRequest] = {}      # gateway uid → request
        self._terminal: List[GatewayRequest] = []      # terminal order (SLO summaries)
        self.counters = {
            "submitted": 0, "admitted": 0, "done": 0, "rejected": 0, "shed": 0,
            "cancelled": 0, "expired": 0, "evicted": 0, "retried": 0,
            "failed": 0, "replayed": 0,
        }
        # Circuit breaker (docs/resilience.md): closed → open after
        # breaker_threshold engine step-failures inside breaker_window_s;
        # open → half_open after the cooldown (one probe request admitted);
        # probe DONE closes it, probe FAILED re-opens. Failure signal = the
        # engine's own step_failures counter, read as a delta after each step.
        self._breaker = CircuitBreaker(
            config.breaker_threshold, config.breaker_window_s,
            config.breaker_cooldown_s,
        )
        self._engine_failures_seen = getattr(engine, "step_failures", 0)
        # Graceful degradation rungs (config.degrade): each breaker OPEN —
        # including a re-open after a failed probe — escalates (1: speculative
        # decoding off; 2: admission bounds halved); a CLOSE (proven-healthy
        # probe) restores the full configuration.
        self.degrade_level = 0
        self._admission_scale = 1.0

    # ------------------------------------------------------------------ submit
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               gen: Optional[GenerationConfig] = None,
               rng=None, priority: Optional[int] = None,
               deadline_s=_UNSET, tenant: str = "default",
               on_token: Optional[Callable[[int], None]] = None,
               on_retry: Optional[Callable[[], None]] = None,
               max_retries: Optional[int] = None) -> GatewayRequest:
        """Queue a request under the gateway's policy; ALWAYS returns a
        ``GatewayRequest`` — admission refusals come back as a terminal
        ``rejected`` status with a machine-readable ``reason``, never an
        exception. API misuse raises the engine's exact exceptions (one shared
        ``serving.normalize_submit``, so gateway and engine cannot drift).

        ``deadline_s`` is relative to now (``None`` disables even the config
        default); ``priority``: higher = more urgent; ``tenant`` feeds WFQ;
        ``max_retries`` bounds retry-on-preemption for this request;
        ``on_retry`` fires when a preemption retry restarts the stream (the
        signal for a streaming consumer to reset its buffer before ``on_token``
        replays from the first token)."""
        now = self._clock()
        prompt, gen = normalize_submit(prompt, max_new_tokens, eos_token_id, gen, rng)

        if deadline_s is _UNSET:
            deadline_s = self.config.deadline_s
        greq = GatewayRequest(
            uid=self._uid, prompt=prompt, gen=gen, rng=rng,
            priority=self.config.default_priority if priority is None else priority,
            deadline_at=None if deadline_s is None else now + float(deadline_s),
            tenant=tenant, on_token=on_token, on_retry=on_retry,
            max_retries=self.config.max_retries if max_retries is None else max_retries,
            t_submit=now, t_enqueued=now,
        )
        self._uid += 1
        self._all[greq.uid] = greq
        self.counters["submitted"] += 1
        if self.tracer is not None:
            # Trace opens HERE — queue wait is client-visible latency, so the
            # trace must start before admission control can refuse or defer.
            greq._trace = self.tracer.start(greq.uid, tenant=tenant, t=now)

        # Health gate: while the breaker is OPEN every submission is
        # shed-and-rejected with the machine-readable reason ``circuit_open``
        # (an operating condition, like queue_full); after the cooldown ONE
        # probe request passes through (half-open, its fate decides the state)
        # and the others are refused as ``circuit_probe``. The fleet router
        # overrides this hook: its breakers are per-replica and gate ROUTING,
        # so a submission is only refused when no replica could ever serve it.
        gate_reason = self._admission_gate(greq, now)
        if gate_reason is not None:
            return self._refuse(greq, now, gate_reason)

        # Servability + cost: the engine's own KV pricing (``kv_demand`` — the
        # prefill planner's padded width + budget on a dense engine, PAGE-granular
        # demand on a paged one) is the single source of memory truth, so the
        # queue budget accounts what the cache will actually charge. Unservable
        # geometry is an admission refusal, not a crash; a request whose demand
        # exceeds the paged engine's whole page pool gets the machine-readable
        # ``kv_budget`` reason (it could never be admitted, no matter the queue).
        try:
            greq.cost = self._admission_cost(len(prompt), gen.max_new_tokens)
        except KVBudgetError as e:
            return self._refuse(greq, now, "kv_budget", str(e))
        except ValueError as e:
            return self._refuse(greq, now, "unservable", str(e))

        if not self._make_room(greq, now):
            return greq  # _make_room already marked it rejected
        self._policy.push(greq)
        self._queued_cost += greq.cost
        return greq

    def _admission_cost(self, prompt_len: int, max_new: int) -> int:
        """Cache-token cost one request charges the queue budget — the
        engine's own KV pricing (``kv_demand``), so admission accounts what
        the cache will actually charge. Raises ``KVBudgetError``/``ValueError``
        for never-servable requests. The disagg router overrides this: a
        request there is priced by the DECODE side's adoption demand (context
        + budget) while the prefill side validates context-only servability —
        pricing both phases at full prompt+budget would double-count KV."""
        return int(self.engine.kv_demand(prompt_len, max_new))

    def _refuse(self, greq: GatewayRequest, now: float, reason: str,
                detail: Optional[str] = None) -> GatewayRequest:
        """Mark an incoming request terminally REJECTED (shedding of already-queued
        requests is finalized inline by ``_make_room``)."""
        self.counters["rejected"] += 1
        self._finalize(greq, REJECTED, reason if detail is None else f"{reason}:{detail}", now)
        return greq

    def _effective_bounds(self) -> tuple:
        """(max_queue, max_queued_tokens) after the degradation scale — rung 2
        halves both; 0 (unbounded) stays unbounded."""
        mq = self.config.max_queue
        mt = self.config.max_queued_tokens
        if self._admission_scale != 1.0:
            mq = max(1, int(mq * self._admission_scale)) if mq else 0
            mt = max(1, int(mt * self._admission_scale)) if mt else 0
        return mq, mt

    def _over_budget(self, incoming_cost: int) -> Optional[str]:
        max_queue, max_tokens = self._effective_bounds()
        if max_queue and len(self._policy) + 1 > max_queue:
            return "queue_full"
        if (max_tokens
                and self._queued_cost + incoming_cost > max_tokens):
            return "token_budget"
        return None

    def _make_room(self, greq: GatewayRequest, now: float) -> bool:
        """Enforce the admission bounds for one incoming request. Returns True when
        it may be queued; False after marking it rejected. ``overload="shed"``
        sheds strictly-less-urgent queued requests (lowest first) — **atomically**:
        the victim set is planned first and shed only if it actually makes room,
        so a blocked newcomer can never destroy queued work and then be rejected
        anyway. A newcomer can never shed its equal."""
        reason = self._over_budget(greq.cost)
        if reason is None:
            return True
        max_queue, max_tokens = self._effective_bounds()
        if (self.config.overload != "shed"
                or (max_tokens and greq.cost > max_tokens)):
            # reject mode, or a newcomer over the budget even against an EMPTY
            # queue — no victim set could ever make room.
            self._refuse(greq, now, reason)
            return False
        new_urgency = self._policy.urgency(greq, now)
        pool = sorted(
            (i for i in self._policy.items()
             if self._policy.urgency(i, now) < new_urgency),
            key=lambda i: (self._policy.urgency(i, now), -i.uid),
        )
        victims = []
        qlen, qcost = len(self._policy), self._queued_cost

        def fits():
            len_ok = not max_queue or qlen + 1 <= max_queue
            tok_ok = not max_tokens or qcost + greq.cost <= max_tokens
            return len_ok, tok_ok
        for victim in pool:
            len_ok, tok_ok = fits()
            if len_ok and tok_ok:
                break
            victims.append(victim)
            qlen -= 1
            qcost -= victim.cost
        len_ok, tok_ok = fits()
        if not (len_ok and tok_ok):
            self._refuse(greq, now, "queue_full" if not len_ok else "token_budget")
            return False
        for victim in victims:
            self._policy.remove(victim.uid)
            self._queued_cost -= victim.cost
            self.counters["shed"] += 1
            if self.tracer is not None:
                self.tracer.event(victim._trace, "shed", t=now, shed_for=greq.uid)
            self._finalize(victim, SHED, "overload_shed", now)
        return True

    # ------------------------------------------------------------------ control
    def cancel(self, uid: int) -> bool:
        """Cooperatively withdraw request ``uid``. Queued requests never reach a
        slot; a running request's lane is freed immediately (reusable by the next
        ``step()``). Returns False for unknown/already-terminal uids."""
        greq = self._all.get(uid)
        if greq is None or greq.terminal:
            return False
        now = self._clock()
        if greq.status == QUEUED:
            self._policy.remove(greq.uid)
            self._queued_cost -= greq.cost
            self.counters["cancelled"] += 1
            self._finalize(greq, CANCELLED, "cancelled_queued", now)
            return True
        # running — engine.cancel, not evict_slot: a reentrant cancel (from
        # another request's on_token mid-step) can catch the engine Request
        # still in the engine's internal queue, where only cancel() finds it.
        self.engine.cancel(greq._engine_req.uid)
        self._running.pop(greq._engine_req.uid, None)
        greq.tokens = list(greq._engine_req.tokens)
        self.counters["cancelled"] += 1
        self._finalize(greq, CANCELLED, "cancelled_running", now)
        return True

    # ------------------------------------------------------------------ stepping
    def step(self) -> List[GatewayRequest]:
        """One gateway cycle: expire/evict deadline violators, preempt, admit into
        free lanes, advance the engine one decode step. Returns every request that
        reached a terminal state during this call (submission order)."""
        now = self._clock()
        events: List[GatewayRequest] = []

        # 1) queued deadline expiry — never occupies a slot.
        for item in self._policy.items():
            if item.deadline_at is not None and now > item.deadline_at:
                self._policy.remove(item.uid)
                self._queued_cost -= item.cost
                self.counters["expired"] += 1
                self._finalize(item, EXPIRED, "deadline_queued", now)
                events.append(item)

        # 2) running deadline eviction — the lane frees NOW, so this same step's
        #    admission (below) can refill it: eviction-to-reuse is one step().
        #    SUPER-STEP granularity: with engine.multi_step = N > 1 this check
        #    runs once per super-step, so a deadline that lands mid-dispatch is
        #    observed up to N-1 tokens late — the documented streaming-
        #    granularity trade (docs/multistep_decode.md). Budgets never
        #    overshoot: the engine clamps drained emissions per request.
        #    cancel(), not evict_slot(): engine recovery may have PARKED the
        #    request back in its internal queue (rebuild requeue) or bisect
        #    hold, where only cancel() finds it — evict_slot would miss it and
        #    the engine would re-admit a request the gateway already finalized.
        for greq in list(self._running.values()):
            if greq.deadline_at is not None and now > greq.deadline_at:
                self.engine.cancel(greq._engine_req.uid)
                self._running.pop(greq._engine_req.uid, None)
                greq.tokens = list(greq._engine_req.tokens)
                self.counters["expired"] += 1
                self._finalize(greq, EXPIRED, "deadline_running", now)
                events.append(greq)

        # 3) priority preemption (opt-in): a strictly more urgent queued request
        #    may take the lane of the least urgent running one; the evictee
        #    retries from scratch while its budget lasts.
        if self.config.preempt:
            events.extend(self._preempt(now))

        # 4) admit exactly as many requests as there are free lanes, in policy
        #    order — the engine's internal FIFO then admits them all this step.
        free = self._free_lanes()
        while free > 0 and len(self._policy):
            item = self._policy.pop(now)
            self._queued_cost -= item.cost
            self._admit(item, now)
            free -= 1

        # 5) one engine decode step; map engine completions back to gateway state.
        #    A request the engine's fault boundary quarantined comes back with a
        #    machine-readable ``failed`` reason → terminal FAILED (retrying a
        #    poison request would just re-poison the batch).
        for ereq in self.engine.step():
            greq = self._running.pop(ereq.uid, None)
            if greq is None:
                continue  # engine-direct submission, not gateway-managed
            greq.tokens = list(ereq.tokens)
            greq.recoveries = getattr(ereq, "recoveries", 0)
            t_done = self._clock()
            failed_reason = getattr(ereq, "failed", None)
            if failed_reason is not None:
                self.counters["failed"] += 1
                self._finalize(greq, FAILED, failed_reason, t_done)
            else:
                self.counters["done"] += 1
                self._finalize(greq, DONE, None, t_done)
            events.append(greq)

        # 6) circuit breaker: observe this step's engine failure delta.
        if self.config.breaker_threshold:
            self._breaker_observe(now)
        return sorted(events, key=lambda r: r.uid)

    # ------------------------------------------------------------ circuit breaker
    def _admission_gate(self, greq: GatewayRequest, now: float) -> Optional[str]:
        """Pre-queue health gate: a machine-readable refusal reason, or None to
        let the request queue. The single-engine implementation is the breaker;
        the fleet router replaces it with replica routability (its per-replica
        breakers gate dispatch instead of the front door)."""
        return self._breaker.gate(greq.uid, now)

    @property
    def _breaker_state(self) -> str:
        return self._breaker.state

    @property
    def breaker_openings(self) -> int:
        return self._breaker.openings

    @property
    def breaker_closings(self) -> int:
        return self._breaker.closings

    def _breaker_observe(self, now: float) -> None:
        failures = getattr(self.engine, "step_failures", 0)
        delta = failures - self._engine_failures_seen
        self._engine_failures_seen = failures
        if self._breaker.record_failures(delta, now):
            # Threshold crossed — or the half-open probe period saw a failure
            # (whatever request tripped it, the engine is not healthy: re-open
            # for another cooldown, and escalate another rung — a failed probe
            # IS repeated pressure).
            self._breaker_open(now)

    def _breaker_open(self, now: float) -> None:
        self._breaker.open(now)
        self._escalate()
        self._emit_breaker_record("circuit_open", now)

    def _breaker_close(self, now: float) -> None:
        self._breaker.close(now)
        # A close is a PROVEN-healthy probe: restore the full configuration.
        # (One-rung-per-close would ratchet permanently — re-opens can outnumber
        # closes, so levels left over after the episode ends would never clear.)
        while self.degrade_level:
            self._deescalate()
        self._emit_breaker_record("circuit_close", now)

    def _emit_breaker_record(self, action: str, now: float) -> None:
        tel = self.telemetry
        if tel is None or not tel.enabled:
            return
        from ..telemetry.schemas import RECOVERY_SCHEMA

        tel.emit({
            "schema": RECOVERY_SCHEMA, "action": action, "t": now,
            "openings": self.breaker_openings,
            "closings": self.breaker_closings,
            "degrade_level": self.degrade_level,
        })

    # ------------------------------------------------------- graceful degradation
    def _escalate(self) -> None:
        """One rung down under pressure: speculative decoding off first (pure
        throughput machinery, zero correctness impact), admission bounds
        halved second (shed load earlier) — each breaker OPEN steps one rung."""
        if not self.config.degrade or self.degrade_level >= 2:
            return
        self.degrade_level += 1
        if self.degrade_level == 1:
            if getattr(self.engine, "spec_k", 0):
                self.engine.set_spec_enabled(False)
        else:
            self._admission_scale = 0.5

    def _deescalate(self) -> None:
        """One rung back up, mirroring the escalation order (the breaker close
        loops this until the full configuration is restored)."""
        if not self.config.degrade or self.degrade_level == 0:
            return
        if self.degrade_level == 2:
            self._admission_scale = 1.0
        elif getattr(self.engine, "spec_k", 0):
            self.engine.set_spec_enabled(True)
        self.degrade_level -= 1

    # ------------------------------------------------------------- request replay
    def reattach_engine(self, engine=None, reason: str = "engine_restart") -> list:
        """Recover from an engine death/restart: optionally swap in the fresh
        engine, then re-queue every in-flight request for idempotent replay —
        each fires its ``on_retry`` stream reset (the consumer drops its
        buffer; ``on_token`` then re-delivers from the first token, so the
        final transcript is byte-identical to an undisturbed run) and re-enters
        the queue under the normal policy. Replays do NOT consume the
        preemption retry budget; returns the replayed requests."""
        now = self._clock()
        if engine is not None:
            if self.tracer is not None and getattr(engine, "tracer", None) is None:
                engine.tracer = self.tracer
            self.engine = engine
            self._engine_failures_seen = getattr(engine, "step_failures", 0)
        replayed = []
        for greq in list(self._running.values()):
            self._replay_requeue(greq, now, reason)
            replayed.append(greq)
        self._running.clear()
        tel = self.telemetry
        if tel is not None and tel.enabled:
            from ..telemetry.schemas import RECOVERY_SCHEMA

            tel.emit({
                "schema": RECOVERY_SCHEMA, "action": "replay", "t": now,
                "reason": reason, "replayed": len(replayed),
            })
        return replayed

    def _replay_requeue(self, greq: GatewayRequest, now: float,
                        cause: str) -> None:
        """Reset one in-flight request for idempotent replay and requeue it
        under the normal policy: the ``on_retry`` stream reset fires (the
        consumer drops its buffer; ``on_token`` then re-delivers from the first
        token, so the final transcript is byte-identical to an undisturbed
        run). Shared by ``reattach_engine`` (whole-engine restart) and the
        fleet router's per-replica failover/drain migration. Replays do NOT
        consume the preemption retry budget — a replica death is not the
        request's fault."""
        greq.replays += 1
        self.counters["replayed"] += 1
        greq.status = QUEUED
        greq.tokens = []
        greq._engine_req = None
        greq._rid = None
        greq.t_admit = greq.t_first_token = greq.t_last_token = None
        greq.t_enqueued = now  # the replay's queue wait starts HERE
        greq.n_streamed = 0
        if greq.on_retry is not None:
            greq.on_retry()
        if self.tracer is not None and greq._trace is not None:
            greq._trace.attempt = greq.retries_used + greq.replays
            self.tracer.event(greq._trace, "retry", t=now,
                              attempt=greq._trace.attempt, cause=cause)
        self._policy.push(greq)
        self._queued_cost += greq.cost

    def _free_lanes(self) -> int:
        """Lanes the engine can fill this step: open slots minus requests already
        sitting in the engine's internal queue (admitted this step, e.g. by a
        preemption) — those lanes are spoken for."""
        return (
            self.engine.max_slots
            - sum(r is not None for r in self.engine.slot_req)
            - len(self.engine.queue)
        )

    @property
    def queue_depth(self) -> int:
        """Queued request count — cheap (no SLO summary built, unlike ``stats()``)."""
        return len(self._policy)

    @property
    def running_count(self) -> int:
        """Requests currently holding an engine lane — cheap."""
        return len(self._running)

    def run(self, report_slo: bool = False):
        """Drain the queue and every lane. Returns all requests that reached a
        terminal state during the drain; with ``report_slo`` also emits the
        aggregate ``gateway.slo/v1`` telemetry record and returns
        ``(requests, summary)``."""
        out: List[GatewayRequest] = []
        while self.queue_depth or self.running_count:
            out.extend(self.step())
        if report_slo:
            return out, self.emit_slo_record()
        return out

    def _admit(self, greq: GatewayRequest, now: float) -> None:
        greq.status = RUNNING
        greq.t_admit = now
        self.counters["admitted"] += 1
        ereq = self.engine.submit(
            greq.prompt, gen=greq.gen,
            rng=greq.rng if greq.gen.temperature > 0.0 else None,
            on_token=self._stream_cb(greq),
        )
        greq._engine_req = ereq
        self._running[ereq.uid] = greq
        tr = self.tracer
        if tr is not None:
            # Queue span covers THIS attempt's wait (t_enqueued, not t_submit:
            # a retry's span must measure the re-queue wait alone, or
            # trace-report's retry_s would re-count the first wait plus the
            # pre-preemption running time) and closes at the scheduling
            # decision; the engine-side binding lets prefill/decode spans
            # attribute to this trace.
            tr.span(greq._trace, "queue", greq.t_enqueued, now,
                    attempt=greq.retries_used + greq.replays,
                    outcome="admitted")
            tr.bind_engine(greq._trace, ereq.uid)

    def _stream_cb(self, greq: GatewayRequest) -> Callable[[int], None]:
        def deliver(tok: int) -> None:
            t = self._clock()
            if greq.t_first_token is None:
                greq.t_first_token = t
                if self.tracer is not None:
                    # The SAME clock read ttft_s derives from — trace-report's
                    # reconstructed TTFT (first_token.t1 - queue.t0) equals the
                    # gateway's to the digit.
                    self.tracer.event(greq._trace, "first_token", t=t)
            greq.t_last_token = t
            greq.n_streamed += 1
            if greq.on_token is not None:
                greq.on_token(tok)

        return deliver

    def _preempt(self, now: float) -> List[GatewayRequest]:
        """Evict the least-urgent running request when a strictly higher-priority
        one is queued and no lane is free — and admit the preemptor into the freed
        lane DIRECTLY. (Leaving the lane to the normal admission pass would let a
        non-priority policy pop the just-requeued victim back into it — an
        evict-readmit churn that burns the victim's retry budget and a prefill
        per step while the preemptor waits.) Raw ``priority`` is the preemption
        currency under every policy — preempting on queue-discipline urgency
        would let mere aging evict live work."""
        events: List[GatewayRequest] = []
        while len(self._policy) and self._running:
            if self._free_lanes() > 0:
                break
            top = max(self._policy.items(), key=lambda i: (i.priority, -i.uid))
            victim = min(self._running.values(), key=lambda r: (r.priority, -r.uid))
            if victim.priority >= top.priority:
                break
            # cancel(), not evict_slot(): a recovery-parked victim (engine
            # queue / bisect hold) would otherwise survive as a zombie copy
            # generating tokens for a request the gateway requeued.
            self.engine.cancel(victim._engine_req.uid)
            self._running.pop(victim._engine_req.uid, None)
            if self.tracer is not None:
                self.tracer.event(victim._trace, "preempt", t=now,
                                  preempted_by=top.uid,
                                  tokens_lost=len(victim._engine_req.tokens))
            # take(), not remove(): the preemptor is being SERVED — WFQ must
            # charge its tenant and advance the virtual clock, not refund it.
            self._policy.take(top.uid, now)
            self._queued_cost -= top.cost
            self._admit(top, now)
            evicted = self._preempt_victim_requeue(victim, now)
            if evicted is not None:
                events.append(evicted)
        return events

    def _preempt_victim_requeue(self, victim: GatewayRequest,
                                now: float) -> Optional[GatewayRequest]:
        """A preempted victim's fate: retry (requeued under the policy, stream
        reset) while its budget lasts, else terminal eviction. Returns the
        victim when it was terminally evicted (a step event), None when
        requeued. ONE copy shared by the single-engine and fleet preempt paths
        so the retry bookkeeping cannot drift between them."""
        if victim.retries_used < victim.max_retries:
            victim.retries_used += 1
            self.counters["retried"] += 1
            victim.status = QUEUED
            victim.tokens = []
            victim._engine_req = None
            victim._rid = None
            victim.t_admit = victim.t_first_token = victim.t_last_token = None
            victim.t_enqueued = now  # the retry's queue wait starts HERE
            victim.n_streamed = 0
            if victim.on_retry is not None:
                # Stream-reset signal: on_token is about to replay from the
                # first token; without this a streaming consumer's transcript
                # would contain the pre-eviction prefix twice.
                victim.on_retry()
            if self.tracer is not None and victim._trace is not None:
                victim._trace.attempt = victim.retries_used
                self.tracer.event(victim._trace, "retry", t=now,
                                  attempt=victim.retries_used)
            self._policy.push(victim)
            self._queued_cost += victim.cost
            return None
        # Terminal eviction keeps the partial transcript — it was already
        # streamed to the client and the SLO record must account for it
        # (same contract as cancel/deadline eviction).
        if victim._engine_req is not None:
            victim.tokens = list(victim._engine_req.tokens)
        self.counters["evicted"] += 1
        self._finalize(victim, EVICTED, "preempted", now)
        return victim

    def _probe_verdict(self, greq: GatewayRequest, status: str,
                       now: float) -> None:
        """Terminal-state hook deciding a half-open breaker's fate when the
        finished request was its probe (fleet: checked per replica)."""
        if self._breaker.probe_uid is None or greq.uid != self._breaker.probe_uid:
            return
        if status == DONE:
            self._breaker_close(now)
        elif status == FAILED:
            self._breaker_open(now)  # a failed probe re-opens + escalates
        else:
            self._breaker.probe_uid = None  # probe never ran (cancel/expiry): re-probe

    # ------------------------------------------------------------------ reporting
    def _finalize(self, greq: GatewayRequest, status: str, reason: Optional[str],
                  now: float) -> None:
        greq.status = status
        greq.reason = reason
        greq.t_done = now
        greq._engine_req = None  # release the engine Request (and its prompt/cache refs)
        # Half-open probe verdict: the probe's fate decides the breaker.
        self._probe_verdict(greq, status, now)
        tr = self.tracer
        if tr is not None and greq._trace is not None:
            if status in (FAILED, EXPIRED, SHED) or (
                status == DONE and greq.deadline_met is False
            ):
                # Tail promotion: a request that ended badly (quarantined by
                # the fault boundary, deadline-expired, shed, or done-but-
                # deadline-breached) gets its buffered spans replayed BEFORE
                # the closing queue span / terminal event below — the handle
                # flips sampled, so the promoted stream is chronological and
                # reconstructs TTFT to the digit from spans alone.
                tr.promote(greq._trace)
            if greq.t_admit is None:
                # Still queued at its end: close this attempt's queue span
                # (t_enqueued — the retry requeue time after a preemption) so
                # every trace has one, whatever its fate.
                tr.span(greq._trace, "queue", greq.t_enqueued, now,
                        attempt=greq.retries_used + greq.replays, outcome=status)
            tr.event(greq._trace, "terminal", t=now, status=status,
                     reason=reason, n_tokens=len(greq.tokens),
                     retries_used=greq.retries_used,
                     queue_wait_s=greq.queue_wait_s, ttft_s=greq.ttft_s,
                     tpot_s=greq.tpot_s)
            tr.finish(greq._trace)
        self._terminal.append(greq)
        self._emit_request_record(greq)
        # Bounded history (TelemetryConfig.max_records analog): a long-running
        # service must not grow per-request state forever. Counters stay
        # cumulative; slo_summary() covers the retained window.
        cap = self.config.max_terminal
        if cap and len(self._terminal) > cap:
            for old in self._terminal[: len(self._terminal) - cap]:
                self._all.pop(old.uid, None)
            del self._terminal[: len(self._terminal) - cap]

    def _emit_request_record(self, greq: GatewayRequest) -> None:
        tel = self.telemetry
        if tel is None or not tel.enabled or not self.config.emit_per_request:
            return
        tel.emit({
            "schema": GATEWAY_REQUEST_SCHEMA,
            "uid": greq.uid,
            "status": greq.status,
            "reason": greq.reason,
            "tenant": greq.tenant,
            "priority": greq.priority,
            "n_tokens": len(greq.tokens),
            "retries_used": greq.retries_used,
            "queue_wait_s": greq.queue_wait_s,
            "ttft_s": greq.ttft_s,
            "tpot_s": greq.tpot_s,
            "deadline_met": greq.deadline_met,
        })

    def slo_summary(self) -> dict:
        """p50/p95/p99 (+count/mean) blocks over the retained terminal requests'
        queue-wait/TTFT/TPOT (the last ``max_terminal``, a sliding SLO window),
        plus terminal counts by status within that window. Requests that never
        produced a token simply don't contribute latencies (count says how many
        did); the cumulative totals live in ``counters``."""
        done = self._terminal
        summary = slo_summary({
            "queue_wait_s": [r.queue_wait_s for r in done],
            "ttft_s": [r.ttft_s for r in done],
            "tpot_s": [r.tpot_s for r in done],
        })
        summary["by_status"] = {
            s: sum(r.status == s for r in done)
            for s in sorted(TERMINAL_STATUSES)
        }
        return summary

    def emit_slo_record(self) -> dict:
        """Build (and, when telemetry is attached, emit) the aggregate SLO record."""
        record = {
            "schema": GATEWAY_SLO_SCHEMA,
            "policy": self._policy.name,
            **{k: v for k, v in self.counters.items()},
            "slo": self.slo_summary(),
        }
        tel = self.telemetry
        if tel is not None and tel.enabled:
            tel.emit(record)
        return record

    def _capsule_state(self) -> dict:
        """The incident-capsule state snapshot (flight-recorder state
        provider): everything ``stats()`` exposes plus the raw engine lane
        table and the fault-plan firing log — the post-hoc questions a capsule
        must answer without the process alive ('which uid held lane 3 when the
        breaker opened?', 'which injected faults had fired by then?')."""
        state = self.stats()
        state["lanes"] = [
            None if r is None else getattr(r, "uid", None)
            for r in getattr(self.engine, "slot_req", [])
        ]
        faults = getattr(self.engine, "faults", None)
        if faults is not None:
            state["faults"] = {**faults.stats(), "fired": list(faults.fired)}
        return state

    def stats(self) -> dict:
        """Gateway + nested engine observability snapshot."""
        out = {
            "policy": self._policy.name,
            "queued": len(self._policy),
            "queued_cost_tokens": self._queued_cost,
            "running": len(self._running),
            **dict(self.counters),
            "breaker_state": self._breaker_state,
            "breaker_openings": self.breaker_openings,
            "breaker_closings": self.breaker_closings,
            "degrade_level": self.degrade_level,
            "slo": self.slo_summary(),
            "engine": self.engine.stats(),
        }
        if self.metrics is not None:
            out["metrics"] = self.metrics.stats()
        return out

    def __repr__(self) -> str:
        return (
            f"ServingGateway(policy={self._policy.name!r}, queued={len(self._policy)}, "
            f"running={len(self._running)}, terminal={len(self._terminal)})"
        )
