"""SLO-aware serving gateway: admission control, scheduling, deadlines, streaming.

A request-scheduling tier that wraps (never replaces) the continuous-batching
engine (``accelerate_tpu.serving.ContinuousBatcher``). The engine stays a pure
throughput machine; the gateway owns queue policy (fifo / priority-with-aging /
EDF / weighted fair queueing), bounded-queue admission with explicit REJECTED
results and shed-lowest-priority-first overload handling, per-request deadlines
with mid-decode eviction, cooperative cancellation, bounded retry-on-preemption,
token streaming, and p50/p95/p99 SLO summaries through the telemetry pipeline.

Off by default: nothing here is imported by the engine, and a gateway-fronted
run compiles exactly the programs an engine-only run does (docs/serving_gateway.md).

Fleet tier (``fleet.FleetRouter``, docs/resilience.md): the same machinery over
N engine replicas — health-driven routing, per-replica circuit breakers,
lossless failover via request replay, drain-on-restart / rolling restart.

Disaggregated tier (``disagg.DisaggRouter``, docs/disaggregated_serving.md):
replicas get ROLES — prefill replicas chunk-prefill and export KV page-list
handoffs, decode replicas adopt them read-only (COW at the write boundary) and
run decode-only lanes at high occupancy; failover stays lossless (re-prefill on
a dead prefill replica, re-adoption from still-refcounted pages on a dead
decode replica).

Autoscaling tier (``autoscaler.Autoscaler``, docs/autoscaling.md): alert
transitions become scale actions — closed-loop fleet sizing with hysteresis
scale-down, predictive scale-up and role-ratio control for disagg fleets,
deterministic under virtual-clock replay.

Enable via ``GatewayConfig`` / ``ACCELERATE_GATEWAY`` and build with::

    gw = ServingGateway(engine, GatewayConfig(enabled=True, policy="edf"))
    req = gw.submit(prompt, max_new_tokens=64, deadline_s=0.5, on_token=print)
    gw.run()
"""

from .autoscaler import (
    Autoscaler,
    default_autoscale_rules,
)
from .disagg import (
    DisaggRouter,
    parse_roles,
)
from .fleet import (
    ACTIVE,
    DRAINING,
    RESTARTING,
    RETIRED,
    FleetRouter,
    Replica,
)
from .gateway import (
    CANCELLED,
    DONE,
    EVICTED,
    EXPIRED,
    FAILED,
    QUEUED,
    REJECTED,
    RUNNING,
    SHED,
    TERMINAL_STATUSES,
    CircuitBreaker,
    GatewayRequest,
    ServingGateway,
)
from .policies import (
    POLICIES,
    EdfPolicy,
    FifoPolicy,
    PriorityPolicy,
    SchedulerPolicy,
    WfqPolicy,
    make_policy,
)
from .workload import (
    GENERATORS,
    WORKLOAD_TRACE_SCHEMA,
    TraceRequest,
    VirtualClock,
    generate_workload,
    load_trace,
    replay_trace,
    save_trace,
    trace_hash,
)

__all__ = [
    "GENERATORS",
    "WORKLOAD_TRACE_SCHEMA",
    "TraceRequest",
    "VirtualClock",
    "generate_workload",
    "load_trace",
    "replay_trace",
    "save_trace",
    "trace_hash",
    "ServingGateway",
    "GatewayRequest",
    "CircuitBreaker",
    "Autoscaler",
    "default_autoscale_rules",
    "DisaggRouter",
    "parse_roles",
    "FleetRouter",
    "Replica",
    "ACTIVE",
    "DRAINING",
    "RESTARTING",
    "RETIRED",
    "SchedulerPolicy",
    "FifoPolicy",
    "PriorityPolicy",
    "EdfPolicy",
    "WfqPolicy",
    "POLICIES",
    "make_policy",
    "QUEUED",
    "RUNNING",
    "DONE",
    "REJECTED",
    "SHED",
    "CANCELLED",
    "EVICTED",
    "EXPIRED",
    "FAILED",
    "TERMINAL_STATUSES",
]
