"""Workload traces: recorded/generated arrival processes for serving replay.

serve-bench's historical workload was ONE synthetic paced-arrival burst —
useful for apples-to-apples policy rows, nothing like production traffic, which
is bursty, heavy-tailed and multi-tenant. This module is the trace layer under
ROADMAP item 5:

- **Format** — one request per JSONL line: ``arrival_s`` (relative to trace
  start), ``prompt_len``/``output_len`` (tokens), ``tenant``, ``priority``,
  ``deadline_s`` (relative to arrival; None = no deadline). A header line
  (``schema = accelerate_tpu.serving.workload/v1``) records the generator and
  seed. Token *ids* are intentionally not in the trace — replay synthesizes
  them deterministically from the trace seed, so a trace stays model-agnostic
  (lengths and arrival structure are what serving performance depends on).
- **Generators** — deterministic-by-seed builders of the canonical hard
  arrival processes: ``poisson`` (bursty Poisson arrivals), ``diurnal``
  (sinusoidal rate ramp), ``swing`` (diurnal parameterized by peak:trough
  ratio — the autoscale bench's 4× load swing), ``heavy_tail`` (Pareto
  prompt/output lengths — the long-context tail that wrecks padded-width
  admission), ``tenant_flood``
  (an adversarial tenant dumping a flood into otherwise-normal traffic — the
  WFQ isolation scenario).
- **Replay** — :func:`replay_trace` drives a ``ServingGateway`` on a VIRTUAL
  clock (one ``step()`` = ``step_dt`` seconds), submitting each request when
  the clock passes its arrival. Offered load is swept by time-compression
  (``load=2.0`` replays arrivals twice as fast against the same engine
  capacity), which is how the SLO-attainment-vs-offered-load curves in
  ``BENCH_TRACE.json`` are produced (``commands/serve_bench.run_trace_curves``).
- **Identity** — :func:`trace_hash` content-hashes the rows; curve artifacts
  stamp it beside the git/config provenance so a curve names the exact arrival
  process that produced it.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Callable, Dict, List, Optional

__all__ = [
    "WORKLOAD_TRACE_SCHEMA",
    "TraceRequest",
    "GENERATORS",
    "generate_workload",
    "save_trace",
    "load_trace",
    "trace_hash",
    "replay_trace",
]

#: Header-line schema id of a workload-trace JSONL file (not a telemetry record).
WORKLOAD_TRACE_SCHEMA = "accelerate_tpu.serving.workload/v1"


@dataclasses.dataclass
class TraceRequest:
    """One arrival in a workload trace (times in seconds, lengths in tokens)."""

    arrival_s: float
    prompt_len: int
    output_len: int
    tenant: str = "default"
    priority: int = 0
    deadline_s: Optional[float] = None  # relative to arrival; None = no deadline

    def to_json(self) -> dict:
        return {
            "arrival_s": round(float(self.arrival_s), 6),
            "prompt_len": int(self.prompt_len),
            "output_len": int(self.output_len),
            "tenant": self.tenant,
            "priority": int(self.priority),
            "deadline_s": (
                None if self.deadline_s is None else round(float(self.deadline_s), 6)
            ),
        }

    @classmethod
    def from_json(cls, row: dict) -> "TraceRequest":
        return cls(
            arrival_s=float(row["arrival_s"]),
            prompt_len=int(row["prompt_len"]),
            output_len=int(row["output_len"]),
            tenant=str(row.get("tenant", "default")),
            priority=int(row.get("priority", 0)),
            deadline_s=(
                None if row.get("deadline_s") is None else float(row["deadline_s"])
            ),
        )


def _lengths(rng, n, prompt_range, output_range):
    prompts = rng.integers(prompt_range[0], prompt_range[1] + 1, n)
    outputs = rng.integers(output_range[0], output_range[1] + 1, n)
    return prompts, outputs


def _class_attrs(rng, high_frac, tenants, deadline_tight, deadline_loose):
    is_high = bool(rng.random() < high_frac)
    return {
        "tenant": f"tenant{int(rng.integers(0, tenants))}",
        "priority": 2 if is_high else 0,
        "deadline_s": deadline_tight if is_high else deadline_loose,
    }


def poisson_burst(
    n: int, seed: int = 0, mean_iat_s: float = 1.0, burst_every: int = 12,
    burst_size: int = 6, prompt_range=(3, 24), output_range=(4, 16),
    high_frac: float = 0.25, tenants: int = 3,
    deadline_tight: float = 30.0, deadline_loose: float = 240.0,
) -> List[TraceRequest]:
    """Poisson arrivals punctuated by bursts: every ``burst_every``-th arrival
    brings ``burst_size`` extra requests at the SAME instant (retry storms, page
    reloads, fan-out callers) — the queue-depth spikes paced arrivals never show."""
    import numpy as np

    rng = np.random.default_rng(seed)
    out: List[TraceRequest] = []
    t = 0.0
    k = 0
    while len(out) < n:
        t += float(rng.exponential(mean_iat_s))
        k += 1
        group = 1 + (burst_size if burst_every and k % burst_every == 0 else 0)
        for _ in range(min(group, n - len(out))):
            p, o = _lengths(rng, 1, prompt_range, output_range)
            out.append(TraceRequest(
                arrival_s=t, prompt_len=int(p[0]), output_len=int(o[0]),
                **_class_attrs(rng, high_frac, tenants, deadline_tight,
                               deadline_loose),
            ))
    return out


def diurnal_ramp(
    n: int, seed: int = 0, mean_iat_s: float = 1.0, period_s: float = 120.0,
    depth: float = 0.8, prompt_range=(3, 24), output_range=(4, 16),
    high_frac: float = 0.25, tenants: int = 3,
    deadline_tight: float = 30.0, deadline_loose: float = 240.0,
) -> List[TraceRequest]:
    """Sinusoidal rate modulation (period ``period_s``, peak/trough ratio set by
    ``depth``): the diurnal traffic shape that makes static capacity planning
    either wasteful at trough or shedding at peak."""
    import numpy as np

    rng = np.random.default_rng(seed)
    out: List[TraceRequest] = []
    t = 0.0
    two_pi = 2.0 * 3.141592653589793
    for _ in range(n):
        # rate(t) = base * (1 + depth*sin) → iat scales inversely.
        rate_scale = 1.0 + depth * float(np.sin(two_pi * t / period_s))
        iat = mean_iat_s / max(rate_scale, 1e-3)
        t += float(rng.exponential(iat))
        p, o = _lengths(rng, 1, prompt_range, output_range)
        out.append(TraceRequest(
            arrival_s=t, prompt_len=int(p[0]), output_len=int(o[0]),
            **_class_attrs(rng, high_frac, tenants, deadline_tight, deadline_loose),
        ))
    return out


def swing(
    n: int, seed: int = 0, mean_iat_s: float = 1.0, period_s: float = 120.0,
    swing_ratio: float = 4.0, prompt_range=(3, 24), output_range=(4, 16),
    high_frac: float = 0.25, tenants: int = 3,
    deadline_tight: float = 30.0, deadline_loose: float = 240.0,
) -> List[TraceRequest]:
    """Diurnal ramp parameterized by PEAK:TROUGH ratio instead of modulation
    depth — ``swing_ratio=4.0`` is the canonical 4× load swing the autoscale
    bench replays (``serve-bench --autoscale``). A ratio R maps to
    ``depth=(R-1)/(R+1)`` on :func:`diurnal_ramp`'s sinusoid, so the trace is
    seeded, hash-stable and reproducible from ``--trace-gen swing`` alone."""
    if swing_ratio < 1.0:
        raise ValueError(f"swing_ratio={swing_ratio} must be >= 1.0")
    depth = (swing_ratio - 1.0) / (swing_ratio + 1.0)
    return diurnal_ramp(
        n, seed=seed, mean_iat_s=mean_iat_s, period_s=period_s, depth=depth,
        prompt_range=prompt_range, output_range=output_range,
        high_frac=high_frac, tenants=tenants,
        deadline_tight=deadline_tight, deadline_loose=deadline_loose,
    )


def heavy_tail(
    n: int, seed: int = 0, mean_iat_s: float = 1.0, alpha: float = 1.3,
    prompt_range=(3, 48), output_range=(4, 32), high_frac: float = 0.25,
    tenants: int = 3, deadline_tight: float = 30.0, deadline_loose: float = 240.0,
) -> List[TraceRequest]:
    """Poisson arrivals with Pareto(``alpha``) prompt/output lengths (clamped to
    the ranges): most requests are short chat turns, the tail is long-context —
    the mix where padded-width admission and per-request KV pricing diverge."""
    import numpy as np

    rng = np.random.default_rng(seed)

    def pareto_len(lo, hi, size):
        raw = lo * (1.0 + rng.pareto(alpha, size))
        return np.clip(raw, lo, hi).astype(int)

    out: List[TraceRequest] = []
    t = 0.0
    prompts = pareto_len(prompt_range[0], prompt_range[1], n)
    outputs = pareto_len(output_range[0], output_range[1], n)
    for i in range(n):
        t += float(rng.exponential(mean_iat_s))
        out.append(TraceRequest(
            arrival_s=t, prompt_len=int(prompts[i]), output_len=int(outputs[i]),
            **_class_attrs(rng, high_frac, tenants, deadline_tight, deadline_loose),
        ))
    return out


def tenant_flood(
    n: int, seed: int = 0, mean_iat_s: float = 1.0, flood_frac: float = 0.4,
    flood_at_frac: float = 0.35, flood_span_s: float = 2.0,
    prompt_range=(3, 24), output_range=(4, 16), high_frac: float = 0.25,
    tenants: int = 3, deadline_tight: float = 30.0, deadline_loose: float = 240.0,
) -> List[TraceRequest]:
    """Adversarial tenant flood: normal multi-tenant Poisson traffic, then ONE
    tenant (``"flood"``, priority 0, no deadline pressure of its own) dumps
    ``flood_frac`` of the trace into a ``flood_span_s`` window — the isolation
    scenario where WFQ/priority must keep the other tenants' SLOs alive while
    FIFO serves the flood in arrival order."""
    import numpy as np

    rng = np.random.default_rng(seed)
    n_flood = int(n * flood_frac)
    n_bg = n - n_flood
    out: List[TraceRequest] = []
    t = 0.0
    for _ in range(n_bg):
        t += float(rng.exponential(mean_iat_s))
        p, o = _lengths(rng, 1, prompt_range, output_range)
        out.append(TraceRequest(
            arrival_s=t, prompt_len=int(p[0]), output_len=int(o[0]),
            **_class_attrs(rng, high_frac, tenants, deadline_tight, deadline_loose),
        ))
    flood_at = flood_at_frac * t
    for _ in range(n_flood):
        p, o = _lengths(rng, 1, prompt_range, output_range)
        out.append(TraceRequest(
            arrival_s=flood_at + float(rng.random()) * flood_span_s,
            prompt_len=int(p[0]), output_len=int(o[0]),
            tenant="flood", priority=0, deadline_s=deadline_loose,
        ))
    out.sort(key=lambda r: r.arrival_s)
    return out


#: Generator registry (``serve-bench --trace-gen <name>``).
GENERATORS: Dict[str, Callable[..., List[TraceRequest]]] = {
    "poisson": poisson_burst,
    "diurnal": diurnal_ramp,
    "swing": swing,
    "heavy_tail": heavy_tail,
    "tenant_flood": tenant_flood,
}


def generate_workload(kind: str, n: int, seed: int = 0, **kwargs) -> List[TraceRequest]:
    """Build ``n`` requests with the named generator (deterministic per seed)."""
    if kind not in GENERATORS:
        raise ValueError(
            f"unknown workload generator {kind!r} (known: {sorted(GENERATORS)})"
        )
    return GENERATORS[kind](n, seed=seed, **kwargs)


# ----------------------------------------------------------------- file format
def save_trace(path: str, trace: List[TraceRequest], generator: str = "custom",
               seed: Optional[int] = None) -> None:
    """Write a trace as JSONL: one header line (schema/generator/seed/n), then
    one request per line in arrival order."""
    with open(path, "w", encoding="utf-8") as f:
        f.write(json.dumps({
            "schema": WORKLOAD_TRACE_SCHEMA,
            "generator": generator,
            "seed": seed,
            "n": len(trace),
        }) + "\n")
        for row in trace:
            f.write(json.dumps(row.to_json()) + "\n")


def load_trace(path: str) -> List[TraceRequest]:
    """Read a JSONL workload trace (header line optional; rows sorted by arrival)."""
    rows: List[TraceRequest] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            if "schema" in obj and "arrival_s" not in obj:
                if obj["schema"] != WORKLOAD_TRACE_SCHEMA:
                    raise ValueError(
                        f"{path}: unknown workload trace schema {obj['schema']!r} "
                        f"(expected {WORKLOAD_TRACE_SCHEMA})"
                    )
                continue
            rows.append(TraceRequest.from_json(obj))
    rows.sort(key=lambda r: r.arrival_s)
    return rows


def trace_hash(trace: List[TraceRequest]) -> str:
    """Content hash of the rows (order-sensitive): the identity a curve artifact
    stamps so "same trace" means same bytes, not same filename."""
    h = hashlib.blake2b(digest_size=12)
    for row in trace:
        h.update(json.dumps(row.to_json(), sort_keys=True).encode())
    return h.hexdigest()


# ----------------------------------------------------------------------- replay
class VirtualClock:
    """Manual monotonic clock for deterministic replay (inject into the gateway,
    its tracer, AND :func:`replay_trace` so deadlines, spans and arrivals share
    one timeline)."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def replay_trace(
    gateway,
    trace: List[TraceRequest],
    vocab_size: int,
    clock: VirtualClock,
    step_dt: float = 1.0,
    load: float = 1.0,
    seed: int = 0,
    max_steps: Optional[int] = None,
    on_token_factory: Optional[Callable[[int], object]] = None,
) -> list:
    """Replay ``trace`` through ``gateway`` on the virtual clock; returns the
    ``GatewayRequest`` per trace row (submission order).

    Each loop iteration submits every request whose (load-compressed) arrival
    time has passed, runs ONE ``gateway.step()``, and advances the clock by
    ``step_dt`` — so "offered load" has a precise meaning: ``load=2.0`` presents
    the same arrival process at twice the rate against identical engine capacity
    (steps per virtual second is fixed). Prompt token ids are synthesized
    deterministically from ``seed`` + row index; deadlines come from the trace
    (relative to arrival, on the same virtual clock the gateway enforces them
    with)."""
    import numpy as np

    if load <= 0:
        raise ValueError(f"load={load} must be > 0")
    prompt_rng = np.random.default_rng(seed)
    prompts = [
        prompt_rng.integers(1, vocab_size, row.prompt_len).astype(np.int32)
        for row in trace
    ]
    greqs = []
    i = 0
    steps = 0
    cap = max_steps if max_steps is not None else 200 * max(1, len(trace))
    while i < len(trace) or gateway.queue_depth or gateway.running_count:
        while i < len(trace) and trace[i].arrival_s / load <= clock.t:
            row = trace[i]
            kwargs = {}
            if on_token_factory is not None:
                # Per-request streaming capture (and its on_retry stream
                # reset): the chaos bench's byte-parity evidence hangs off it.
                cbs = on_token_factory(i)
                if isinstance(cbs, tuple):
                    kwargs["on_token"], kwargs["on_retry"] = cbs
                else:
                    kwargs["on_token"] = cbs
            greqs.append(gateway.submit(
                prompts[i],
                max_new_tokens=row.output_len,
                priority=row.priority,
                deadline_s=row.deadline_s,
                tenant=row.tenant,
                **kwargs,
            ))
            i += 1
        gateway.step()
        clock.advance(step_dt)
        steps += 1
        if steps >= cap:
            raise RuntimeError(
                f"replay exceeded {cap} steps with {len(trace) - i} arrivals "
                "pending — engine stalled or step_dt/load pathological"
            )
    return greqs
