"""Closed-loop fleet sizing: alert transitions become scale actions.

PR 13 built the trigger surface (``AlertEngine`` rules firing ``alert/v1``
off the live ``MetricsPlane``) and PRs 10/12 built the actuators
(``FleetRouter``/``DisaggRouter`` with ``engine_factory``, ``drain``,
``rolling_restart``) — this module connects them (ROADMAP item 1). An
:class:`Autoscaler` subscribes to ``alert/v1`` transitions on the router's
telemetry stream and drives the fleet through the machinery that already
exists, never around it:

- **Scale-up** — ``spawn_replica()``: a fresh replica from the restart
  ``engine_factory``, admitted to routing through the same half-open probe
  warm-up a restarted replica earns its way back with. Spawned engines ride
  the warmed bucket ladder / AOT cache (same factory the bench pre-warms), so
  growth compiles ZERO new programs.
- **Scale-down** — ``decommission()``: always a drain, so in-flight requests
  finish or migrate via the replay path (byte-identical streams, never
  stranded), then a retirement that charges NO supervisor restart budget — a
  planned exit is not a failure.
- **Thrash guards** — per-direction cooldowns, min/max fleet bounds, and the
  scale-down trigger is the PR-20 ``sustained_low`` hysteresis rule kind
  (fire needs the full window below, clear needs the value back above a
  DISTINCT higher bound), so the controller cannot flap on the threshold
  that fired it.
- **Role-ratio control** (disagg fleets) — sustained handoff-backlog per
  decode replica (or router-queue depth per prefill replica with an empty
  handoff backlog) shifts the prefill:decode ratio by spawning one role and
  retiring the other: fleet size holds, the ratio follows the prompt-length
  mix.
- **Predictive layer** — reactive rules catch what already went wrong; the
  forecaster anticipates. Offered load for the next window is extrapolated
  from the trace's OWN arrival history (two consecutive windowed arrival
  rates, linear extrapolation — no wall clocks, no new deps), divided by an
  online per-replica service-rate estimate, and the deficit spawns ahead of
  the ramp.

Every decision is one ``fleet.scale/v1`` record on the router's (virtual)
clock, carrying the action, the triggering reason, the post-action per-role
census and the cumulative replica-hours — the audit trail
``serve-bench --autoscale`` replays deterministically under ``VirtualClock``
(docs/autoscaling.md).
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..telemetry.alerts import AlertEngine, AlertRule
from ..telemetry.clocks import resolve_clock
from ..telemetry.metrics import (
    M_REPLICA_ACTIVE_SLOTS,
    M_REPLICA_QUEUED,
    M_REQUESTS_TOTAL,
)
from ..telemetry.schemas import ALERT_SCHEMA, FLEET_SCALE_SCHEMA
from .fleet import ACTIVE, RETIRED, FleetRouter, Replica

__all__ = ["Autoscaler", "default_autoscale_rules", "FLEET_SCALE_SCHEMA"]


def default_autoscale_rules(
    queue_high: float = 4.0,
    queue_window_s: float = 30.0,
    idle_lane_floor: float = 1.0,
    idle_clear: Optional[float] = None,
    idle_window_s: float = 45.0,
    objective: float = 0.9,
    fast_window_s: float = 30.0,
    slow_window_s: float = 120.0,
    burn_threshold: float = 3.0,
) -> Tuple[List[AlertRule], List[AlertRule]]:
    """The stock ``(up_rules, down_rules)`` pair the autoscale bench arms.

    Up: the SLO burn rate (attainment actively bleeding), windowed
    expired/shed terminals (router-level backpressure — a fleet's ENGINE
    queues stay near-empty by construction, so overload surfaces as deadline
    expiry and shed, not engine queue depth), and per-replica engine queue
    depth for mixed/single topologies. Down: the ``sustained_low`` hysteresis
    rule on the FLEET-WIDE sum of active decode lanes — the fleet must stay
    below ``idle_lane_floor`` busy lanes for the full ``idle_window_s``, and
    the rule only re-arms once the sum climbs to ``idle_clear`` (default: one
    above the floor)."""
    if idle_clear is None:
        idle_clear = idle_lane_floor + 1.0
    up = [
        AlertRule("scale-up-slo-burn", kind="burn_rate", severity="page",
                  objective=objective, fast_window_s=fast_window_s,
                  slow_window_s=slow_window_s, burn_threshold=burn_threshold),
        AlertRule("scale-up-expired", metric=M_REQUESTS_TOTAL,
                  labels={"status": "expired"}, threshold=0.0,
                  window_s=queue_window_s, severity="page"),
        AlertRule("scale-up-shed", metric=M_REQUESTS_TOTAL,
                  labels={"status": "shed"}, threshold=0.0,
                  window_s=queue_window_s, severity="page"),
        AlertRule("scale-up-queue", metric=M_REPLICA_QUEUED,
                  threshold=queue_high, window_s=queue_window_s,
                  severity="ticket"),
    ]
    down = [
        AlertRule("scale-down-idle", kind="sustained_low",
                  metric=M_REPLICA_ACTIVE_SLOTS, threshold=idle_lane_floor,
                  clear_threshold=idle_clear, window_s=idle_window_s,
                  reduce="sum", severity="ticket"),
    ]
    return up, down


class Autoscaler:
    """Alert-driven fleet-size controller over one :class:`FleetRouter`.

    ``up_rules`` / ``down_rules`` are :class:`AlertRule` objects (armed on
    the router's metrics plane as one :class:`AlertEngine`) or bare rule
    NAMES (armed elsewhere — the autoscaler only needs to recognize their
    transitions). Either way the controller acts on the firing LEVEL folded
    from ``alert/v1`` transition records: a persistently-firing up rule keeps
    ramping one replica per cooldown until it resolves or ``max_replicas``
    binds.

    The router polls the controller at the end of every ``step()`` (after
    health emission, so decisions read this step's signals), on the router's
    own clock — fully deterministic under ``VirtualClock`` replay. No wall
    clocks, no randomness, no background threads.
    """

    def __init__(self, router: FleetRouter, *,
                 min_replicas: int = 1,
                 max_replicas: int = 4,
                 cooldown_s: float = 30.0,
                 down_cooldown_s: Optional[float] = None,
                 eval_interval_s: float = 1.0,
                 up_rules: Optional[Sequence] = None,
                 down_rules: Optional[Sequence] = None,
                 drain_deadline_s: Optional[float] = None,
                 predictive: bool = True,
                 forecast_window_s: float = 30.0,
                 forecast_util_floor: float = 0.85,
                 forecast_warmup: int = 3,
                 headroom: float = 1.25,
                 queue_backlog_per_replica: float = 4.0,
                 rebalance_window_s: float = 20.0,
                 backlog_per_decode: float = 2.0,
                 queue_per_prefill: float = 4.0,
                 default_role: str = "decode",
                 telemetry=None,
                 clock: Optional[Callable[[], float]] = None):
        if min_replicas < 1:
            raise ValueError(f"min_replicas={min_replicas} must be >= 1 "
                             "(a fleet of zero serves nobody)")
        if max_replicas < min_replicas:
            raise ValueError(f"max_replicas={max_replicas} must be >= "
                             f"min_replicas={min_replicas}")
        if router.engine_factory is None:
            raise ValueError(
                "Autoscaler needs the router built with an engine_factory — "
                "scale-up spawns replicas through it"
            )
        self.router = router
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.cooldown_s = float(cooldown_s)
        self.down_cooldown_s = (self.cooldown_s if down_cooldown_s is None
                                else float(down_cooldown_s))
        self.eval_interval_s = float(eval_interval_s)
        self.drain_deadline_s = drain_deadline_s
        self.predictive = bool(predictive)
        self.forecast_window_s = float(forecast_window_s)
        self.forecast_util_floor = float(forecast_util_floor)
        self.forecast_warmup = int(forecast_warmup)
        self.headroom = float(headroom)
        self.queue_backlog_per_replica = float(queue_backlog_per_replica)
        self.rebalance_window_s = float(rebalance_window_s)
        self.backlog_per_decode = float(backlog_per_decode)
        self.queue_per_prefill = float(queue_per_prefill)
        self.default_role = default_role
        # One clock domain: explicitly injected wins, else the router's.
        self._clock = resolve_clock(clock, getattr(router, "_clock", None))
        self.telemetry = telemetry if telemetry is not None else router.telemetry

        self._up_names: set = set()
        self._down_names: set = set()
        rule_objs: List[AlertRule] = []
        for rule in (up_rules or []):
            if isinstance(rule, AlertRule):
                rule_objs.append(rule)
                self._up_names.add(rule.name)
            else:
                self._up_names.add(str(rule))
        for rule in (down_rules or []):
            if isinstance(rule, AlertRule):
                rule_objs.append(rule)
                self._down_names.add(str(rule.name))
            else:
                self._down_names.add(str(rule))
        self.engine: Optional[AlertEngine] = None
        if rule_objs:
            if router.metrics is None:
                raise ValueError(
                    "AlertRule objects need the router's metrics plane — "
                    "build the router with GatewayConfig(metrics=True), or "
                    "pass rule NAMES armed on an external engine"
                )
            self.engine = AlertEngine(router.metrics, rule_objs,
                                      telemetry=self.telemetry,
                                      eval_interval_s=eval_interval_s)

        #: rule name → currently-firing level, folded from transitions.
        self._firing: Dict[str, bool] = {}
        #: Every ``fleet.scale/v1`` record emitted, in order.
        self.events: List[dict] = []
        self._last_eval: Optional[float] = None
        self._last_up_t: Optional[float] = None
        self._last_down_t: Optional[float] = None
        #: Predictive state: (t, submitted, done) samples one forecast window
        #: apart, busy-lane accumulator between samples, and the per-LANE
        #: service-rate EMA fitted from them. Per-lane (completions over mean
        #: BUSY lanes), not per-replica (completions over fleet size): the
        #: latter is utilization-bound and makes every underloaded fleet
        #: forecast a deficit of headroom x size.
        self._samples: List[Tuple[float, int, int]] = []
        self._lane_acc: List[float] = [0.0, 0.0]
        self._mu: Optional[float] = None
        self._mu_updates = 0
        self._last_util: float = 0.0
        #: Forecast persistence anchor: a deficit must survive one full
        #: forecast window before the controller acts on it — one noisy
        #: arrival window must not buy a replica.
        self._deficit_since: Optional[float] = None
        #: Role-ratio dwell anchors (router-clock time pressure started).
        self._decode_pressure_since: Optional[float] = None
        self._prefill_pressure_since: Optional[float] = None

        if self.telemetry is not None and getattr(self.telemetry, "enabled",
                                                  False):
            self.telemetry.sinks.append(self._on_record)
        router._autoscaler = self

    # ----------------------------------------------------------- alert intake
    def _on_record(self, record) -> None:
        """Telemetry sink: fold ``alert/v1`` transitions into firing levels.
        Only the rules this controller was told about participate — an
        unrelated page must not resize the fleet."""
        if record.get("schema") != ALERT_SCHEMA:
            return
        rule = record.get("rule")
        if rule in self._up_names or rule in self._down_names:
            self._firing[rule] = record.get("state") == "firing"

    # -------------------------------------------------------------- census
    def _live(self) -> List[Replica]:
        """Replicas that count toward fleet size: not retired, not already
        on their way out (a decommissioned replica stops counting the moment
        the decision lands, so bounds see the POST-action size)."""
        return [rep for rep in self.router._replicas
                if rep.state != RETIRED and not rep.retire_on_drain]

    def replicas_by_role(self) -> Dict[str, int]:
        census: Dict[str, int] = {}
        for rep in self._live():
            role = getattr(rep.engine, "role", "mixed")
            census[role] = census.get(role, 0) + 1
        return census

    # ------------------------------------------------------------- main loop
    def poll(self, now: Optional[float] = None) -> None:
        """One control evaluation (the router calls this at the end of every
        ``step()``), throttled to ``eval_interval_s`` of router-clock time.
        At most ONE action per evaluation — a controller that scales twice in
        one tick cannot attribute either move to a signal."""
        now = self._clock() if now is None else now
        if (self._last_eval is not None
                and now - self._last_eval < self.eval_interval_s):
            return
        self._last_eval = now
        self._observe(now)
        if self._maybe_scale_up(now):
            return
        if self._maybe_rebalance(now):
            return
        self._maybe_scale_down(now)

    # ------------------------------------------------------------ predictive
    def _observe(self, now: float) -> None:
        """Sample the arrival/completion counters one forecast window apart
        and refit the per-lane service-rate EMA from completions over the
        mean number of BUSY lanes in the window."""
        if not self.predictive:
            return
        self._lane_acc[0] += float(sum(len(rep.running)
                                       for rep in self._live()))
        self._lane_acc[1] += 1.0
        if (self._samples
                and now - self._samples[-1][0] < self.forecast_window_s):
            return
        counters = self.router.counters
        mean_busy = (self._lane_acc[0] / self._lane_acc[1]
                     if self._lane_acc[1] else 0.0)
        self._lane_acc = [0.0, 0.0]
        replicas = self.router._replicas
        slots = getattr(replicas[0].engine, "max_slots", 1) if replicas else 1
        self._last_util = mean_busy / max(1.0,
                                          float(slots * len(self._live())))
        self._samples.append((now, int(counters.get("submitted", 0)),
                              int(counters.get("done", 0))))
        if len(self._samples) > 3:
            self._samples.pop(0)
        if len(self._samples) >= 2 and mean_busy > 0:
            (t0, _s0, d0), (t1, _s1, d1) = self._samples[-2], self._samples[-1]
            dt = t1 - t0
            if dt > 0:
                mu_lane = (d1 - d0) / dt / mean_busy
                if mu_lane > 0:
                    self._mu = (mu_lane if self._mu is None
                                else 0.5 * self._mu + 0.5 * mu_lane)
                    self._mu_updates += 1

    def _forecast_deficit(self, now: float) -> Optional[str]:
        """Predictive scale-up reason: linear extrapolation of the windowed
        arrival rate says next window's offered load (× headroom) exceeds
        what the current fleet clears at the fitted per-lane service rate.

        Two sanity gates keep the forecaster honest: the service-rate EMA
        must have ``forecast_warmup`` updates behind it (cold-start windows
        produce garbage estimates), and the fleet's busy-lane share over the
        last window must be at least ``forecast_util_floor`` — predictive
        spawning is about staying ahead of a ramp that is already FILLING the
        lanes; while there is slack, the reactive rules own the decision."""
        if len(self._samples) < 3 or not self._mu:
            return None
        if (self._mu_updates < self.forecast_warmup
                or self._last_util < self.forecast_util_floor):
            return None
        (ta, sa, _), (tb, sb, _), (tc, sc, _) = self._samples
        if tb <= ta or tc <= tb:
            return None
        r_prev = (sb - sa) / (tb - ta)
        r_last = (sc - sb) / (tc - tb)
        forecast = max(0.0, r_last + (r_last - r_prev))
        replicas = self.router._replicas
        slots = getattr(replicas[0].engine, "max_slots", 1) if replicas else 1
        capacity = self._mu * max(1, slots)
        needed = math.ceil(forecast * self.headroom / capacity)
        if needed > len(self._live()):
            return (f"forecast:rate={round(forecast, 4)}"
                    f",mu_lane={round(self._mu, 4)},needed={needed}")
        return None

    # ----------------------------------------------------------------- actions
    def _maybe_scale_up(self, now: float) -> bool:
        reason = next((name for name in sorted(self._up_names)
                       if self._firing.get(name)), None)
        if reason is None:
            # Built-in backlog signal: the controller owns the router, and
            # the router's own queue depth is the purest overload evidence —
            # arrival extrapolation goes blind to a standing backlog the
            # moment the arrival rate turns back down.
            depth = self.router.queue_depth
            bound = self.queue_backlog_per_replica * max(1, len(self._live()))
            if depth > bound:
                reason = f"queue_backlog:depth={depth},bound={round(bound, 1)}"
        if reason is None and self.predictive:
            forecast = self._forecast_deficit(now)
            if forecast is None:
                self._deficit_since = None
            else:
                if self._deficit_since is None:
                    self._deficit_since = now
                if now - self._deficit_since >= self.forecast_window_s:
                    reason = forecast
        if reason is None:
            return False
        if (self._last_up_t is not None
                and now - self._last_up_t < self.cooldown_s):
            return False
        if len(self._live()) >= self.max_replicas:
            return False
        role = (self.default_role
                if getattr(self.router, "roles", None) is not None else None)
        rep = self.router.spawn_replica(role)
        self._record("scale_up", reason, now, replica=rep.rid, role=role)
        self._last_up_t = now
        self._deficit_since = None
        return True

    def _maybe_scale_down(self, now: float) -> bool:
        reason = next((name for name in sorted(self._down_names)
                       if self._firing.get(name)), None)
        if reason is None:
            return False
        if (self._last_down_t is not None
                and now - self._last_down_t < self.down_cooldown_s):
            return False
        if len(self._live()) <= self.min_replicas:
            return False
        victim = self._pick_victim(now)
        if victim is None:
            return False
        role = (getattr(victim.engine, "role", "mixed")
                if getattr(self.router, "roles", None) is not None else None)
        self.router.decommission(victim.rid, self.drain_deadline_s)
        self._record("scale_down", reason, now, replica=victim.rid, role=role)
        self._last_down_t = now
        return True

    def _pick_victim(self, now: float,
                     role: Optional[str] = None) -> Optional[Replica]:
        """Cheapest planned exit: an ACTIVE replica (optionally of one role),
        fewest in-flight requests first (least to drain/migrate), highest rid
        on ties; replica 0 is spared while any alternative exists (the base
        gateway's cost model reads its engine)."""
        candidates = [rep for rep in self.router._replicas
                      if rep.state == ACTIVE and not rep.retire_on_drain]
        if role is not None:
            candidates = [rep for rep in candidates
                          if getattr(rep.engine, "role", "mixed") == role]
        nonzero = [rep for rep in candidates if rep.rid != 0]
        if nonzero:
            candidates = nonzero
        if not candidates:
            return None
        return min(candidates, key=lambda rep: (len(rep.running), -rep.rid))

    def _maybe_rebalance(self, now: float) -> bool:
        """Disagg role-ratio control: sustained handoff backlog per decode
        replica trades a prefill replica for a decode one; sustained router
        queue per prefill replica with an EMPTY handoff backlog trades the
        other way. Spawn-then-drain, so capacity never dips mid-shift."""
        router = self.router
        if getattr(router, "roles", None) is None:
            return False
        census = self.replicas_by_role()
        n_prefill = sum(n for role, n in census.items()
                        if role in ("prefill", "mixed"))
        n_decode = sum(n for role, n in census.items()
                       if role in ("decode", "mixed"))
        backlog = len(getattr(router, "_handoffs", ()))
        queue_depth = len(router._policy)
        if backlog / max(1, n_decode) > self.backlog_per_decode:
            if self._decode_pressure_since is None:
                self._decode_pressure_since = now
        else:
            self._decode_pressure_since = None
        if backlog == 0 and queue_depth / max(1, n_prefill) > self.queue_per_prefill:
            if self._prefill_pressure_since is None:
                self._prefill_pressure_since = now
        else:
            self._prefill_pressure_since = None

        grow, shrink, since, why = None, None, None, None
        if (self._decode_pressure_since is not None
                and census.get("prefill", 0) > 1):
            grow, shrink = "decode", "prefill"
            since, why = self._decode_pressure_since, "decode_backlog"
        elif (self._prefill_pressure_since is not None
                and census.get("decode", 0) > 1):
            grow, shrink = "prefill", "decode"
            since, why = self._prefill_pressure_since, "prefill_queue"
        if grow is None or now - since < self.rebalance_window_s:
            return False
        if (self._last_up_t is not None
                and now - self._last_up_t < self.cooldown_s):
            return False
        victim = self._pick_victim(now, role=shrink)
        if victim is None:
            return False
        rep = router.spawn_replica(grow)
        router.decommission(victim.rid, self.drain_deadline_s)
        self._record("rebalance", why, now, replica=rep.rid, role=grow,
                     retired_replica=victim.rid, retired_role=shrink)
        self._last_up_t = now
        self._last_down_t = now
        self._decode_pressure_since = None
        self._prefill_pressure_since = None
        return True

    # ----------------------------------------------------------------- record
    def _record(self, action: str, reason: str, now: float, **cols) -> None:
        census = self.replicas_by_role()
        record = {
            "schema": FLEET_SCALE_SCHEMA,
            "action": action,
            "reason": reason,
            "replicas": sum(census.values()),
            "replicas_by_role": census,
            "replica_hours": round(self.router.replica_hours, 6),
            "t": round(now, 6),
            **cols,
        }
        self.events.append(record)
        if self.telemetry is not None and getattr(self.telemetry, "enabled",
                                                  False):
            self.telemetry.emit(record)

    # ------------------------------------------------------------------ report
    def stats(self) -> dict:
        return {
            "bounds": [self.min_replicas, self.max_replicas],
            "replicas": len(self._live()),
            "replicas_by_role": self.replicas_by_role(),
            "replica_hours": round(self.router.replica_hours, 6),
            "scale_events": len(self.events),
            "actions": {
                action: sum(1 for e in self.events if e["action"] == action)
                for action in ("scale_up", "scale_down", "rebalance")
            },
            "firing": sorted(n for n, f in self._firing.items() if f),
            "service_rate_per_lane": self._mu,
        }

    def __repr__(self) -> str:
        return (f"Autoscaler(replicas={len(self._live())}, "
                f"bounds=[{self.min_replicas},{self.max_replicas}], "
                f"events={len(self.events)})")
