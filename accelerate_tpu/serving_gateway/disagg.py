"""Disaggregated prefill/decode serving: role-aware routing over a split fleet.

Every replica in the PR-10 fleet pays BOTH phases on the same lanes: a decode
lane is held while the host loop chunk-prefills other admissions — exactly the
STALL share ``trace-report``'s critical-path breakdown was built to expose
(ROADMAP item 1). This module splits the two phases onto separately-provisioned
replicas (the TPU serving comparison in PAPERS.md and the multi-slice DCN
scaling work both argue the compute-bound prefill and HBM-bound decode phases
want different provisioning):

- **Replica roles** — each engine is ``prefill`` / ``decode`` / ``mixed``
  (``ContinuousBatcher(role=...)``, threaded through ``GatewayConfig.
  replica_roles`` and the restart ``engine_factory``). Prefill replicas
  chunk-prefill admitted requests on TRANSIENT lanes (freed the same step) and
  export each request's KV as a page-list :class:`~..serving.KVHandoff`;
  decode replicas never prefill — they adopt handoffs read-only (COW at the
  write boundary, the prefix-cache adoption semantics generalized across
  engines) and run decode-only lanes at high occupancy.
- **Cross-engine page handoff** — the page payload crosses engines through
  ``ops.collectives.kv_page_transfer`` (``jax.device_put`` onto the decode
  replica's placement, byte/latency-accounted, one ``serving.handoff/v1``
  record per handoff). Handoff v1 is a same-process device copy between two
  engines' pools; the DCN-shaped path between real slices is the same call.
- **Role-aware routing** — :class:`DisaggRouter` (a ``FleetRouter`` subclass:
  same policy queue, same submit/SLO contract) dispatches admissions to the
  healthiest prefill-capable replica, collects completed prefills into a
  handoff queue, and adopts them onto the healthiest decode-capable replica.
  Admission cost is priced by the DECODE side's adoption demand (context +
  budget) while the prefill side validates context-only servability — pricing
  both phases at full prompt+budget would double-count KV and reject servable
  requests (the ``kv_budget`` fix).
- **Failover, still lossless** — a dead prefill replica's in-flight and
  pending-handoff requests re-prefill on a peer via the PR-9 replay path; a
  dead decode replica's requests RE-ADOPT from the still-refcounted source
  pages (the handoff record keeps them alive until the request is terminal)
  or fall back to replay when the source is gone too — streams byte-identical
  either way, at zero preemption-retry-budget spend.

Proof: ``serve-bench --disagg P:D`` (``commands/serve_bench.run_disagg_bench``)
→ ``BENCH_DISAGG.json`` — decode-replica STALL share and TTFT p95 vs a
same-chip mixed fleet at ≥2× offered load, disagg streams byte-identical to the
mixed baseline, zero silently-lost requests under the chaos variant
(docs/disaggregated_serving.md).
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Callable, List, Optional, Sequence

from ..ops.collectives import TransferStats, kv_page_transfer
from ..resilience.faults import EngineCrashed
from ..utils.dataclasses import GatewayConfig
from .fleet import DRAINING, RESTARTING, RETIRED, FleetRouter, Replica
from .gateway import (
    CANCELLED,
    EXPIRED,
    FAILED,
    RUNNING,
    GatewayRequest,
)

__all__ = ["DisaggRouter", "parse_roles"]

#: Roles that can take an ADMISSION (run prefill) / adopt a handoff (decode).
PREFILL_CAPABLE = ("prefill", "mixed")
DECODE_CAPABLE = ("decode", "mixed")


def parse_roles(spec) -> List[str]:
    """Normalize a role spec: a sequence of role names, or the
    ``GatewayConfig.replica_roles`` comma string (``"prefill,decode,decode"``)."""
    if isinstance(spec, str):
        roles = [r.strip() for r in spec.split(",")]
    else:
        roles = [str(r) for r in spec]
    bad = [r for r in roles if r not in ("prefill", "decode", "mixed")]
    if bad or not roles:
        raise ValueError(
            f"replica roles {spec!r}: expected prefill/decode/mixed, one per "
            "replica"
        )
    return roles


@dataclasses.dataclass
class _PendingHandoff:
    """One exported-but-not-terminal handoff: the gateway request it serves,
    the engine-side record (which OWNS refcounted pages on the source pool),
    and the source identity the re-adoption / release guards check — a
    restarted or rebuilt source invalidates the record (its pool is gone), and
    releasing against a DIFFERENT BlockManager would corrupt refcounts."""

    greq: GatewayRequest
    handoff: object          # serving.KVHandoff
    src_rid: int
    src_engine: object
    src_mgr: object          # the source BlockManager at export time
    exported_at: float = 0.0  # router clock at export/requeue (the handoff
    #                           span's t0 — adoption-queue wait is handoff
    #                           time, not prefill-replica stall)
    readoptions: int = 0     # decode-replica deaths this handoff survived


class DisaggRouter(FleetRouter):
    """Role-aware fleet router: prefill replicas feed decode replicas through
    KV page handoffs (see module docstring).

    ``roles`` (or ``config.replica_roles``) names each replica's role, matching
    the engines' own ``role`` attributes; the fleet needs at least one
    prefill-capable and one decode-capable replica. ``engine_factory`` may take
    ``(rid)`` or ``(rid, role)`` — restarts rebuild the replica with its
    original role either way. ``config.preempt`` is rejected: preemption
    dispatches into an arbitrary lane, and disagg admissions flow through the
    handoff pipeline instead."""

    def __init__(self, engines: Sequence, config: Optional[GatewayConfig] = None,
                 telemetry=None, clock: Optional[Callable[[], float]] = None,
                 tracer=None, engine_factory: Optional[Callable] = None,
                 supervisor=None, roles: Optional[Sequence] = None):
        if config is None:
            config = GatewayConfig(enabled=True)
        if roles is None:
            if config.replica_roles is None:
                raise ValueError(
                    "DisaggRouter needs replica roles: pass roles=[...] or set "
                    "GatewayConfig.replica_roles"
                )
            roles = config.replica_roles
        self.roles = parse_roles(roles)
        if len(self.roles) != len(list(engines)):
            raise ValueError(
                f"{len(self.roles)} roles for {len(list(engines))} engines"
            )
        if not any(r in PREFILL_CAPABLE for r in self.roles):
            raise ValueError("disagg fleet needs a prefill-capable replica")
        if not any(r in DECODE_CAPABLE for r in self.roles):
            raise ValueError("disagg fleet needs a decode-capable replica")
        if config.preempt:
            raise ValueError(
                "preempt=True is a lane-level mechanism; disagg admissions "
                "flow through the handoff pipeline — disable it"
            )
        for i, eng in enumerate(engines):
            if getattr(eng, "role", "mixed") != self.roles[i]:
                raise ValueError(
                    f"replica {i}: engine role {getattr(eng, 'role', None)!r} "
                    f"!= declared role {self.roles[i]!r} — build engines with "
                    "ContinuousBatcher(role=...) matching replica_roles"
                )
            # Handoffs are pages: once any prefill replica exports, every
            # decode-capable replica must be paged — reject at construction,
            # not one adopt_fault per request at serve time. (The fleet
            # geometry check also enforces one page_size, but a clear message
            # beats a geometry-tuple mismatch.)
            if (any(r == "prefill" for r in self.roles)
                    and self.roles[i] in DECODE_CAPABLE
                    and not getattr(eng, "paged", False)):
                raise ValueError(
                    f"replica {i} ({self.roles[i]}) is dense (page_size=0) in "
                    "a fleet with prefill replicas: handoff adoption needs the "
                    "paged KV cache on every decode-capable replica"
                )
        if engine_factory is not None:
            # Thread the role through: a (rid, role) factory gets it handed,
            # a (rid) factory is trusted to consult the same role table. Only
            # a factory with exactly two REQUIRED positional parameters is
            # wrapped — `lambda rid, cfg=...:` or `def f(rid, *, log=None)`
            # must keep their single-arg call (handing the role string into a
            # defaulted second parameter would build a corrupt replacement
            # engine mid-failover).
            import inspect

            try:
                required = [
                    p for p in
                    inspect.signature(engine_factory).parameters.values()
                    if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
                    and p.default is p.empty
                ]
                takes_role = len(required) >= 2
            except (TypeError, ValueError):
                takes_role = False
            if takes_role:
                user_factory, role_table = engine_factory, self.roles
                engine_factory = lambda rid: user_factory(rid, role_table[rid])  # noqa: E731
        super().__init__(engines, config, telemetry=telemetry, clock=clock,
                         tracer=tracer, engine_factory=engine_factory,
                         supervisor=supervisor)
        self.counters.update({"handoffs": 0, "readopted": 0,
                              "handoff_defers": 0})
        #: Handoffs awaiting decode-side adoption, admission order (FIFO — the
        #: policy already ordered them at dispatch).
        self._handoffs: deque = deque()
        #: gateway uid → live _PendingHandoff (released at the terminal state;
        #: the re-adoption index for decode-replica deaths).
        self._live_handoffs: dict = {}
        #: Byte/latency accounting across every kv_page_transfer.
        self.transfer_stats = TransferStats()

    # ------------------------------------------------------------- introspection
    @property
    def running_count(self) -> int:
        """In-flight requests INCLUDING handoff limbo (exported, not yet
        adopted) — ``run()`` must not drain while a handoff still owes tokens."""
        return (sum(len(rep.running) for rep in self._replicas)
                + len(self._handoffs))

    def _phase_reps(self, want) -> List[Replica]:
        return [rep for rep in self._replicas
                if getattr(rep.engine, "role", "mixed") in want]

    # ------------------------------------------------------------------ pricing
    def _admission_cost(self, prompt_len: int, max_new: int) -> int:
        """The disagg admission-cost fix: price by the DECODE side's adoption
        demand (adopted context pages + budget (+ the transient boundary-page
        import)), and validate the prefill side can hold the context — NOT
        prompt+budget on both phases, which double-counts KV and rejects
        servable requests (``kv_budget``)."""
        prefill_ref = next(
            (rep.engine for rep in self._phase_reps(PREFILL_CAPABLE)), None)
        decode_ref = next(
            (rep.engine for rep in self._phase_reps(DECODE_CAPABLE)), None)
        cost = 0
        if prefill_ref is not None:
            cost = int(prefill_ref.kv_demand(prompt_len, max_new))
        if decode_ref is not None:
            cost = max(cost, int(decode_ref.kv_demand(prompt_len, max_new)))
        return cost

    # ------------------------------------------------------------------ routing
    def _pick_replica(self, now: float, reps=None) -> Optional[Replica]:
        """Admissions go to prefill-capable replicas only (probe-first, then
        healthiest-least-loaded — the ONE base heuristic over the role
        subset)."""
        return super()._pick_replica(
            now, self._phase_reps(PREFILL_CAPABLE) if reps is None else reps)

    def _pick_decode_replica(self, now: float) -> Optional[Replica]:
        return super()._pick_replica(now, self._phase_reps(DECODE_CAPABLE))

    def _admission_gate(self, greq: GatewayRequest,
                        now: float) -> Optional[str]:
        """The fleet can serve a request only while BOTH phases have a
        non-retired replica — a fleet whose decode side is permanently gone
        must refuse, not prefill into a queue nothing will ever drain."""
        if all(rep.state == RETIRED
               for rep in self._phase_reps(PREFILL_CAPABLE)):
            return "fleet_down"
        if all(rep.state == RETIRED
               for rep in self._phase_reps(DECODE_CAPABLE)):
            return "fleet_down"
        return None

    # ------------------------------------------------------------------ stepping
    def step(self) -> List[GatewayRequest]:
        """One disagg cycle: the base fleet cycle (deadlines, lifecycle,
        role-filtered admission → prefill replicas, every engine stepped —
        prefill replicas EXPORT during theirs), then the handoff pass: expire
        limbo deadline violators, collect fresh exports, and adopt pending
        handoffs onto decode-capable replicas."""
        events = super().step()
        now = self._clock()
        extra = self._disagg_pass(now)
        if extra:
            events = sorted(events + extra, key=lambda r: r.uid)
        return events

    def _disagg_pass(self, now: float) -> List[GatewayRequest]:
        events: List[GatewayRequest] = []
        # Limbo deadline expiry: a handoff nobody adopted in time is a normal
        # deadline miss, not a stranded request.
        for ph in list(self._handoffs):
            greq = ph.greq
            if greq.deadline_at is not None and now > greq.deadline_at:
                self._handoffs.remove(ph)
                greq.tokens = list(ph.handoff.tokens)
                self.counters["expired"] += 1
                self._finalize(greq, EXPIRED, "deadline_handoff", now)
                events.append(greq)
        self._collect_handoffs(now)
        events.extend(self._pump_handoffs(now))
        return events

    def _collect_handoffs(self, now: float) -> None:
        """Drain every prefill replica's export queue into the router's
        handoff queue: the request leaves the replica's running set (its lane
        is already free) and enters handoff limbo."""
        for rep in self._replicas:
            if getattr(rep.engine, "role", "mixed") != "prefill":
                continue
            if rep.state in (RESTARTING, RETIRED):
                continue
            for h in rep.engine.take_handoffs():
                greq = rep.running.pop(h.uid, None)
                if greq is None or greq.terminal:
                    # engine-direct submission, or finalized out-of-band
                    # (cancel/expiry raced the export): nothing owes tokens.
                    rep.engine.release_handoff(h)
                    continue
                ph = _PendingHandoff(greq, h, rep.rid, rep.engine,
                                     rep.engine.block_mgr, exported_at=now)
                greq._rid = None
                greq._engine_req = None
                self._handoffs.append(ph)
                self._live_handoffs[greq.uid] = ph

    def _handoff_alive(self, ph: _PendingHandoff) -> bool:
        """May we still read/release ``ph``'s source pages? A crashed, replaced
        (restart factory) or rebuilt (fault-recovery fresh pool) source engine
        invalidates the record — its pages and content are gone."""
        rep = self._replicas[ph.src_rid]
        return (rep.engine is ph.src_engine
                and not getattr(ph.src_engine, "crashed", False)
                and ph.src_engine.block_mgr is ph.src_mgr
                and rep.state not in (RESTARTING, RETIRED))

    def _pump_handoffs(self, now: float) -> List[GatewayRequest]:
        """Adopt pending handoffs onto decode-capable replicas, FIFO. Head-of-
        line blocking is deliberate: a deferred adoption (pool pressure) holds
        the queue exactly like the engine's own paged admission defers —
        later arrivals never jump a request waiting for pages."""
        events: List[GatewayRequest] = []
        if self._handoffs and all(
            rep.state == RETIRED for rep in self._phase_reps(DECODE_CAPABLE)
        ):
            # Nothing will ever adopt: fail the limbo machine-readably (the
            # all-retired analog of the base fleet_down backlog flush).
            events.extend(self._flush_handoffs_fleet_down(now))
            return events
        while self._handoffs:
            ph = self._handoffs[0]
            greq = ph.greq
            if greq.terminal:
                self._handoffs.popleft()
                continue
            if not self._handoff_alive(ph):
                # Source died/rebuilt before adoption: the PR-9 fallback —
                # full re-prefill on a peer, stream reset, zero losses.
                self._handoffs.popleft()
                self._drop_handoff_record(greq.uid)
                self._replay_requeue(greq, now, "handoff_src_dead")
                continue
            rep = self._pick_decode_replica(now)
            if rep is None:
                break
            if not rep.engine.can_adopt_handoff(ph.handoff):
                # Pool pressure on the chosen replica: defer WITHOUT paying
                # (or telemetering) the page-block transfer — a repeated
                # export-then-throw-away would inflate the handoff byte
                # accounting one copy per deferred step.
                self.counters["handoff_defers"] += 1
                break
            probe = False
            if rep.breaker.enabled:
                gate = rep.breaker.gate(greq.uid, now)
                assert gate is None, (rep, gate)
                probe = rep.breaker.probe_uid == greq.uid
            block = ph.src_engine.export_page_block(ph.handoff)
            block, nbytes, _dur = kv_page_transfer(
                block, src_replica=ph.src_rid, dst_replica=rep.rid,
                uid=greq.uid, pages=len(ph.handoff.pages),
                stats=self.transfer_stats, telemetry=self.telemetry,
            )
            try:
                ereq = rep.engine.adopt_handoff(
                    ph.handoff, block, on_token=self._stream_cb(greq),
                    replay_tokens=ph.readoptions > 0,
                )
            except EngineCrashed as e:
                if probe:
                    rep.breaker.probe_uid = None
                self._replica_died(rep, f"crash:{e.site}", now)
                continue  # ph stays at the head; next pick skips the dead rep
            except Exception as e:  # injected/real adoption fault: attributable
                if probe:
                    rep.breaker.probe_uid = None
                self._handoffs.popleft()
                greq.tokens = list(ph.handoff.tokens)
                kind = getattr(e, "kind", type(e).__name__)
                self.counters["failed"] += 1
                self._finalize(greq, FAILED, f"adopt_fault:{kind}", now)
                events.append(greq)
                continue
            if ereq is None:
                # Pool pressure / lane race on the chosen replica: defer —
                # retried next step, nothing consumed.
                if probe:
                    rep.breaker.probe_uid = None
                break
            self._handoffs.popleft()
            greq._rid = rep.rid
            greq._engine_req = ereq
            rep.running[ereq.uid] = greq
            self.counters["handoffs"] += 1
            tr = self.tracer
            if tr is not None and greq._trace is not None:
                # Span opens at EXPORT (adoption-queue wait is handoff time,
                # not prefill-replica lane stall — the prefill lane freed at
                # export) and closes when the decode lane is live.
                tr.span(greq._trace, "handoff", ph.exported_at, self._clock(),
                        src_replica=ph.src_rid, dst_replica=rep.rid,
                        pages=len(ph.handoff.pages), nbytes=nbytes)
                tr.bind_engine(greq._trace, ereq.uid)
            self._emit_route(greq.uid, rep,
                             "probe" if probe else "handoff", now)
        return events

    # ------------------------------------------------------------------ failover
    def _migrate(self, rep: Replica, cause: str, now: float,
                 engine_alive: bool) -> List[GatewayRequest]:
        """Role-aware failover: a request whose handoff record is still alive
        on a DIFFERENT replica RE-ADOPTS from the still-refcounted source
        pages (decode-replica death: prefill work is never repeated); anything
        else falls back to the PR-9 replay path (full re-prefill). Streams are
        byte-identical either way — greedy decode is deterministic and sampled
        lanes keep their emission-indexed key schedule."""
        migrated = []
        for greq in list(rep.running.values()):
            if engine_alive:
                rep.engine.cancel(greq._engine_req.uid)
            ph = self._live_handoffs.get(greq.uid)
            if (ph is not None and ph.src_rid != rep.rid
                    and self._handoff_alive(ph)):
                self._readopt_requeue(greq, ph, now, cause)
            else:
                if ph is not None:
                    self._drop_handoff_record(greq.uid)
                self._replay_requeue(greq, now, cause)
            self.counters["migrated"] += 1
            self._emit_route(greq.uid, rep, "migrate", now)
            migrated.append(greq)
        rep.running.clear()
        return migrated

    def _readopt_requeue(self, greq: GatewayRequest, ph: _PendingHandoff,
                         now: float, cause: str) -> None:
        """Reset one request for idempotent RE-ADOPTION: the stream resets
        (``on_retry``), the handoff re-enters the adoption queue, and the next
        decode replica replays the handoff's tokens then regenerates the rest
        — byte-identical, at zero preemption-retry-budget spend, without
        paying prefill again."""
        greq.replays += 1
        self.counters["replayed"] += 1
        self.counters["readopted"] += 1
        greq.status = RUNNING  # mid-service: in the adoption queue, not the policy queue
        greq.tokens = []
        greq._engine_req = None
        greq._rid = None
        greq.t_first_token = greq.t_last_token = None
        greq.n_streamed = 0
        if greq.on_retry is not None:
            greq.on_retry()
        if self.tracer is not None and greq._trace is not None:
            greq._trace.attempt = greq.retries_used + greq.replays
            self.tracer.event(greq._trace, "retry", t=now,
                              attempt=greq._trace.attempt, cause=cause)
        ph.readoptions += 1
        ph.exported_at = now  # the re-adoption span times the re-wait alone
        self._handoffs.append(ph)

    def _replica_died(self, rep: Replica, reason: str, now: float) -> None:
        super()._replica_died(rep, reason, now)
        # Handoffs pending adoption whose SOURCE died with this replica: the
        # pages are gone — re-prefill on a peer (zero silent losses).
        survivors: deque = deque()
        for ph in self._handoffs:
            if ph.src_rid == rep.rid and not self._handoff_alive(ph):
                if not ph.greq.terminal:
                    self._drop_handoff_record(ph.greq.uid)
                    self._replay_requeue(ph.greq, now,
                                         f"handoff_src_dead:{reason}")
            else:
                survivors.append(ph)
        self._handoffs = survivors

    def _flush_handoffs_fleet_down(self, now: float) -> List[GatewayRequest]:
        """Fail every limbo handoff machine-readably (`fleet_down`) — the ONE
        flush shared by the all-retired pump path and the last-replica retire
        (destination buffers differ at the call sites, the semantics must
        not)."""
        failed: List[GatewayRequest] = []
        while self._handoffs:
            ph = self._handoffs.popleft()
            if ph.greq.terminal:
                continue
            ph.greq.tokens = list(ph.handoff.tokens)
            self.counters["failed"] += 1
            self._finalize(ph.greq, FAILED, "fleet_down", now)
            failed.append(ph.greq)
        return failed

    def _retire(self, rep: Replica, now: float) -> None:
        super()._retire(rep, now)
        if all(r.state == RETIRED for r in self._replicas):
            self._pending_events.extend(self._flush_handoffs_fleet_down(now))

    def spawn_replica(self, role: Optional[str] = None) -> Replica:
        """Role-aware scale-up: grow the role table FIRST (the wrapped
        ``(rid, role)`` factory reads it by reference), then build the replica
        through the base actuator. The role-ratio controller uses this paired
        with :meth:`decommission` to shift prefill:decode without changing
        fleet size."""
        if role is None:
            raise ValueError(
                "DisaggRouter.spawn_replica needs a role "
                "(prefill/decode/mixed) — a disagg fleet grows BY role"
            )
        role = parse_roles([role])[0]
        self.roles.append(role)
        try:
            rep = super().spawn_replica()
        except Exception:
            self.roles.pop()
            raise
        eng = rep.engine
        problem = None
        if getattr(eng, "role", "mixed") != role:
            problem = (f"engine role {getattr(eng, 'role', None)!r} != "
                       f"requested role {role!r} — the factory must consult "
                       "the router's role table")
        elif (role in DECODE_CAPABLE
                and any(r == "prefill" for r in self.roles)
                and not getattr(eng, "paged", False)):
            problem = (f"spawned {role} replica is dense (page_size=0) in a "
                       "fleet with prefill replicas: handoff adoption needs "
                       "the paged KV cache")
        if problem is not None:
            # Unwind the registration — a misbuilt replica must not route.
            self._replicas.pop()
            self.roles.pop()
            self.counters["replica_spawned"] -= 1
            raise ValueError(f"replica {rep.rid}: {problem}")
        return rep

    def _restart(self, rep: Replica, now: float) -> None:
        """A draining PREFILL replica waits for its exported handoffs to reach
        terminal states before the engine is torn down (their pages live in
        its pool); past the drain deadline it restarts anyway and the
        outstanding handoffs fall back to re-prefill on first touch."""
        if (rep.state == DRAINING
                and getattr(rep.engine, "role", "mixed") == "prefill"
                and (rep.drain_deadline is None or now <= rep.drain_deadline)
                and any(ph.src_rid == rep.rid and not ph.greq.terminal
                        for ph in self._live_handoffs.values())):
            return
        super()._restart(rep, now)

    # ------------------------------------------------------------------- control
    def cancel(self, uid: int) -> bool:
        greq = self._all.get(uid)
        if (greq is not None and not greq.terminal and greq.status == RUNNING
                and greq._rid is None and uid in self._live_handoffs):
            # Handoff limbo: withdrawn before any decode replica adopted.
            ph = self._live_handoffs[uid]
            self._handoffs = deque(
                p for p in self._handoffs if p.greq is not greq)
            greq.tokens = list(ph.handoff.tokens)
            self.counters["cancelled"] += 1
            self._finalize(greq, CANCELLED, "cancelled_handoff", self._clock())
            return True
        return super().cancel(uid)

    # ---------------------------------------------------------------- lifecycle
    def _drop_handoff_record(self, uid: int) -> None:
        """Forget a handoff whose source pool is GONE — nothing to release."""
        self._live_handoffs.pop(uid, None)

    def _finalize(self, greq: GatewayRequest, status: str,
                  reason: Optional[str], now: float) -> None:
        ph = self._live_handoffs.pop(greq.uid, None)
        if ph is not None and self._handoff_alive(ph):
            # The terminal state releases the source-side page references —
            # the pool the prefill replica lent this request returns to it.
            ph.src_engine.release_handoff(ph.handoff)
        super()._finalize(greq, status, reason, now)

    # ------------------------------------------------------------------ reporting
    def stats(self) -> dict:
        out = super().stats()
        out["roles"] = list(self.roles)
        out["handoffs_pending"] = len(self._handoffs)
        out["handoffs_live"] = len(self._live_handoffs)
        out["handoff_transfer"] = self.transfer_stats.summary()
        return out

    def __repr__(self) -> str:
        states = ",".join(
            f"{r.rid}:{self.roles[r.rid][0]}:{r.state}" for r in self._replicas
        )
        return (f"DisaggRouter(policy={self._policy.name!r}, "
                f"replicas=[{states}], queued={len(self._policy)}, "
                f"running={self.running_count}, "
                f"handoffs_pending={len(self._handoffs)})")
