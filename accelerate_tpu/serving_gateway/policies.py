"""Pluggable queue disciplines behind one ``SchedulerPolicy`` interface.

The gateway holds exactly one policy; every queued :class:`~.gateway.GatewayRequest`
lives inside it between ``submit()`` and admission. A policy never touches the
engine or the clock — it is a pure priority structure over items exposing
``uid`` / ``priority`` / ``deadline_at`` / ``tenant`` / ``cost`` / ``t_submit``,
which keeps each discipline independently testable with plain objects.

Catalog (``make_policy``):

- ``fifo`` — arrival order; the seed-equivalent default (a gateway with the fifo
  policy and no bounds schedules exactly like the bare engine's deque).
- ``priority`` — strict priority with **aging**: a request's effective priority is
  ``priority + waited/aging_s``, so any request eventually outranks a sustained
  stream of fresher high-priority arrivals (starvation-freedom, tested).
- ``edf`` — earliest deadline first; deadline-less requests rank after every
  deadline-bearing one, FIFO among themselves.
- ``wfq`` — start-time weighted fair queueing across tenants: each item is tagged
  with a virtual finish time ``start + cost/weight``; tenants receive service in
  proportion to their weight regardless of arrival burstiness.

``urgency(item, now)`` is the policy's own importance measure (higher = more
urgent). The gateway's shed-lowest-priority-first overload mode compares the
newcomer's urgency against ``shed_candidate()``'s — each discipline defines what
"lowest" means for itself (fifo: the newest arrival; edf: the slackest deadline).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Iterable, Optional

__all__ = [
    "SchedulerPolicy",
    "FifoPolicy",
    "PriorityPolicy",
    "EdfPolicy",
    "WfqPolicy",
    "POLICIES",
    "make_policy",
]


class SchedulerPolicy:
    """One queue discipline. Items are opaque beyond the scheduling attributes
    (see module docstring); insertion uids are unique and monotonically increasing,
    which every tie-break leans on for determinism."""

    name = "base"

    def __init__(self):
        self._items: "OrderedDict[int, object]" = OrderedDict()

    # -------------------------------------------------------------- structure
    def push(self, item) -> None:
        self._items[item.uid] = item

    def remove(self, uid: int):
        """Withdraw by uid BEFORE service (cancellation/shed/expiry); returns the
        item or None. Disciplines with virtual-clock state treat withdrawal as
        never-happened (WFQ refunds the charge) — removal for SERVICE goes
        through :meth:`take`."""
        return self._items.pop(uid, None)

    def take(self, uid: int, now: float):
        """Remove a specific uid FOR SERVICE (targeted admission, e.g. a
        preemptor): like ``pop()`` but by uid, so virtual-clock disciplines
        charge the service instead of refunding it."""
        return self.remove(uid)

    def items(self) -> Iterable:
        """Queued items in insertion order (deadline scans, stats)."""
        return list(self._items.values())

    def __len__(self) -> int:
        return len(self._items)

    # -------------------------------------------------------------- discipline
    def urgency(self, item, now: float) -> float:
        """Importance under this discipline, higher = served sooner. The default
        (FIFO) ranks older arrivals higher."""
        return -item.uid

    def pop(self, now: float):
        """Remove and return the most urgent item (None when empty).
        Ties break toward the lower uid — oldest first, deterministic."""
        if not self._items:
            return None
        best = max(self._items.values(), key=lambda i: (self.urgency(i, now), -i.uid))
        return self._items.pop(best.uid)

    def shed_candidate(self, now: float):
        """The item overload sheds first: the LEAST urgent, ties toward the
        newest arrival (never returns items the discipline would pop next).
        Read-only — the gateway decides whether to actually remove it."""
        if not self._items:
            return None
        return min(self._items.values(), key=lambda i: (self.urgency(i, now), -i.uid))


class FifoPolicy(SchedulerPolicy):
    """Arrival order — the bare engine's deque semantics, made explicit."""

    name = "fifo"


class PriorityPolicy(SchedulerPolicy):
    """Strict priority with linear aging (starvation-free).

    ``effective(item) = item.priority + waited/aging_s``: with ``aging_s=10`` a
    priority-0 request outranks a fresh priority-2 one after 20 s in queue. Pop
    scans the queue (O(n)) — correct under aging, whose effective keys change with
    time and so cannot live in a static heap; gateway queues are thousands of
    entries, not millions."""

    name = "priority"

    def __init__(self, aging_s: float = 10.0):
        super().__init__()
        if aging_s <= 0:
            raise ValueError(f"aging_s={aging_s} must be > 0")
        self.aging_s = aging_s

    def urgency(self, item, now: float) -> float:
        return item.priority + max(0.0, now - item.t_submit) / self.aging_s


class EdfPolicy(SchedulerPolicy):
    """Earliest deadline first. No deadline = infinitely slack: such requests
    rank after every deadline-bearing one and FIFO among themselves (the uid
    tie-break in ``pop``/``shed_candidate``)."""

    name = "edf"

    def urgency(self, item, now: float) -> float:
        if item.deadline_at is None:
            return float("-inf")
        return -item.deadline_at


class WfqPolicy(SchedulerPolicy):
    """Start-time weighted fair queueing (SFQ) across tenants.

    On push an item gets ``start = max(v, tenant_last_finish)`` and
    ``finish = start + cost/weight``; pop serves the minimum finish tag and
    advances the virtual clock ``v`` to the served item's start tag. Tenants
    receive service proportional to weight: a weight-3 tenant's items accrue
    virtual time 3x slower, so bursts from a weight-1 tenant cannot crowd it out.
    Tags are assigned at push and never revised — WFQ is about ordering among
    tenants, not wall-clock aging."""

    name = "wfq"

    def __init__(self, tenant_weights: Optional[Dict[str, float]] = None):
        super().__init__()
        self.tenant_weights = dict(tenant_weights or {})
        for tenant, weight in self.tenant_weights.items():
            if weight <= 0:
                raise ValueError(f"tenant_weights[{tenant!r}]={weight} must be > 0")
        self._v = 0.0                    # virtual clock: start tag of last served item
        self._tenant_finish: Dict[str, float] = {}
        self._tags: Dict[int, tuple] = {}  # uid → (start, finish)

    def push(self, item) -> None:
        weight = self.tenant_weights.get(item.tenant, 1.0)
        start = max(self._v, self._tenant_finish.get(item.tenant, 0.0))
        finish = start + float(item.cost) / weight
        self._tenant_finish[item.tenant] = finish
        self._tags[item.uid] = (start, finish)
        super().push(item)

    def remove(self, uid: int):
        tag = self._tags.pop(uid, None)
        item = super().remove(uid)
        if item is not None and tag is not None:
            start, finish = tag
            if self._tenant_finish.get(item.tenant) == finish:
                # Withdrawn before service (shed/cancel/expiry): refund the virtual
                # service charged at push when it was the tenant's latest item —
                # otherwise a shed-heavy tenant's future items start ever further
                # behind _v and overload inverts its fair share. (Mid-chain
                # removals keep their charge: later tags already embed it.)
                self._tenant_finish[item.tenant] = start
        return item

    def take(self, uid: int, now: float):
        """Serve a specific uid: keep the tenant's service charge and advance the
        virtual clock exactly as ``pop()`` would — a preempting tenant must pay
        for the lane it takes, or routine preemptors would outrun their weight."""
        tag = self._tags.pop(uid, None)
        item = SchedulerPolicy.remove(self, uid)
        if item is not None and tag is not None:
            self._v = max(self._v, tag[0])
        return item

    def urgency(self, item, now: float) -> float:
        tag = self._tags.get(item.uid)
        if tag is None:
            # Not pushed yet (the gateway compares a prospective newcomer against
            # the shed candidate): the tag it WOULD receive, without registering.
            weight = self.tenant_weights.get(item.tenant, 1.0)
            start = max(self._v, self._tenant_finish.get(item.tenant, 0.0))
            tag = (start, start + float(item.cost) / weight)
        return -tag[1]  # smaller finish tag = more urgent

    def pop(self, now: float):
        item = super().pop(now)
        if item is not None:
            start, _ = self._tags.pop(item.uid)
            self._v = max(self._v, start)
        return item


#: name → constructor; ``GatewayConfig.policy`` validates against the same names
#: (``utils.dataclasses._GATEWAY_POLICIES``; paired by a test).
POLICIES = {
    "fifo": FifoPolicy,
    "priority": PriorityPolicy,
    "edf": EdfPolicy,
    "wfq": WfqPolicy,
}


def make_policy(config) -> SchedulerPolicy:
    """Instantiate the policy a :class:`~..utils.dataclasses.GatewayConfig` names,
    threading the discipline-specific knobs (``aging_s``, ``tenant_weights``)."""
    name = config.policy
    if name == "priority":
        return PriorityPolicy(aging_s=config.aging_s)
    if name == "wfq":
        return WfqPolicy(tenant_weights=config.tenant_weights)
    try:
        return POLICIES[name]()
    except KeyError:
        raise ValueError(
            f"unknown scheduler policy {name!r}; known: {sorted(POLICIES)}"
        ) from None
