"""Fleet-level serving resilience: a router over N engine replicas.

``ServingGateway`` made ONE ``ContinuousBatcher`` a production front door, and
PR 9 made that engine survive its own faults — but one wedged or killed engine
was still a total outage. :class:`FleetRouter` is the missing tier (ROADMAP
item 3(b)): the same policy/admission machinery (it IS a ``ServingGateway``
subclass — one queue, one policy, the same submit contract and SLO records),
dispatching into a FLEET of engine replicas with:

- **Health-driven routing** — a per-replica health score computed from the
  telemetry the stack already emits (recent step-failure rate incl. watchdog
  timeouts, lane occupancy, engine-internal queue depth, paged-KV pool
  occupancy); admission dispatches to the healthiest least-loaded routable
  replica, and every decision is a ``fleet.route/v1`` record. Per-replica
  health goes out as ``replica.health/v1`` each router step.
- **Per-replica circuit breakers** — the single-engine gateway's breaker
  (one shared :class:`~.gateway.CircuitBreaker` implementation), instantiated
  per replica: OPEN isolates one replica from routing while the rest keep
  serving; after the cooldown the replica earns routing back through a
  half-open probe. A submission is never refused while any replica could
  serve it (the per-replica-isolation acceptance contract).
- **Lossless failover** — a replica death (injected ``crash`` fault →
  :class:`~..resilience.faults.EngineCrashed`, or an operator
  :meth:`FleetRouter.kill`) or a tripped breaker migrates its in-flight
  requests to the queue via the PR-9 replay path (``on_retry`` stream reset,
  byte-identical transcripts, zero preemption-retry-budget spend); the next
  step re-admits them on a healthy replica.
- **Drain-on-restart / rolling restart** — :meth:`drain` stops routing new
  admissions to a replica, lets in-flight requests finish (or migrates them
  past the drain deadline), restarts the engine through the per-gang
  :class:`~..elastic.FleetSupervisor` budgets, and re-admits the fresh replica
  through a half-open probe warm-up. :meth:`rolling_restart` walks the whole
  fleet one replica at a time so capacity never drops by more than one.

Proof: ``serve-bench --fleet N --chaos`` (``commands/serve_bench.
run_fleet_chaos_bench``) replays one workload trace against the fleet while a
seeded plan kills replicas, and stamps ``BENCH_FLEET.json`` — zero
``silently_lost``, migrated streams byte-identical to the undisturbed fleet,
availability above the single-replica run at the same fault rate, and the
failover p95 TTFT penalty (docs/resilience.md).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from ..elastic import FleetSupervisor
from ..resilience.faults import EngineCrashed
from ..telemetry.schemas import (
    FLEET_ROUTE_SCHEMA,
    RECOVERY_SCHEMA,
    REPLICA_HEALTH_SCHEMA,
)
from ..utils.dataclasses import GatewayConfig
from .gateway import (
    CANCELLED,
    DONE,
    EVICTED,
    EXPIRED,
    FAILED,
    QUEUED,
    RUNNING,
    CircuitBreaker,
    GatewayRequest,
    ServingGateway,
)

__all__ = [
    "FleetRouter",
    "Replica",
    "ACTIVE",
    "DRAINING",
    "RESTARTING",
    "RETIRED",
]

# ------------------------------------------------------------- replica states
ACTIVE = "active"          # routable (subject to its breaker)
DRAINING = "draining"      # no new admissions; in-flight finishing (→ restart)
RESTARTING = "restarting"  # dead/stopped; waiting out supervisor backoff
RETIRED = "retired"        # restart budget exhausted: permanently out


class Replica:
    """One engine replica's routing state: the engine, its circuit breaker,
    the requests it is serving (engine uid → gateway request — engine uids are
    only unique per engine, so the map is per replica), and the failure-recency
    window the health score reads."""

    def __init__(self, rid: int, engine, breaker: CircuitBreaker):
        self.rid = rid
        self.engine = engine
        self.breaker = breaker
        self.state = ACTIVE
        self.running: Dict[int, GatewayRequest] = {}
        self.failures_seen = getattr(engine, "step_failures", 0)
        self.fail_times: List[float] = []  # recency window for the health score
        self.drain_deadline: Optional[float] = None
        self.restarts = 0
        #: Autoscaler decommission flag: when the drain completes, RETIRE the
        #: replica (charging no supervisor restart budget) instead of
        #: restarting it — scale-down is a planned exit, not a failure.
        self.retire_on_drain = False

    @property
    def gang_id(self) -> str:
        return f"replica{self.rid}"

    def free_lanes(self) -> int:
        eng = self.engine
        return (eng.max_slots
                - sum(r is not None for r in eng.slot_req)
                - len(eng.queue))

    def __repr__(self) -> str:
        return (f"Replica({self.rid}, state={self.state!r}, "
                f"running={len(self.running)}, breaker={self.breaker.state!r})")


#: Seconds of failure history the health score weighs (independent of the
#: breaker window so health-driven routing works with the breaker disabled).
HEALTH_WINDOW_S = 60.0


class FleetRouter(ServingGateway):
    """Health-routed, failover-capable gateway over N ``ContinuousBatcher``
    replicas (see module docstring).

    ``engines`` must be homogeneous (same slot/length/page geometry — the
    admission cost model prices one layout). ``engine_factory(rid)`` builds a
    fresh replacement engine for restarts; without one, a dead replica simply
    retires. ``supervisor`` (a :class:`~..elastic.FleetSupervisor`) owns the
    per-replica restart budgets/backoff; a default one is built from the
    gateway config on the router's own clock."""

    def __init__(self, engines: Sequence, config: Optional[GatewayConfig] = None,
                 telemetry=None, clock: Optional[Callable[[], float]] = None,
                 tracer=None, engine_factory: Optional[Callable[[int], object]] = None,
                 supervisor: Optional[FleetSupervisor] = None):
        engines = list(engines)
        if not engines:
            raise ValueError("FleetRouter needs at least one engine replica")
        geo = [(e.max_slots, e.max_len, e.prompt_bucket, e.page_size)
               for e in engines]
        if len(set(geo)) > 1:
            raise ValueError(
                f"fleet replicas must share one engine geometry "
                f"(max_slots/max_len/prompt_bucket/page_size), got {geo}: the "
                "admission cost model prices ONE layout"
            )
        if config is not None and config.degrade:
            raise ValueError(
                "degrade=True is a single-engine breaker rung ladder; the fleet "
                "degrades by ISOLATING replicas instead — disable it"
            )
        super().__init__(engines[0], config, telemetry=telemetry, clock=clock,
                         tracer=tracer)
        self.engine_factory = engine_factory
        cfg = self.config
        self.supervisor = supervisor if supervisor is not None else FleetSupervisor(
            max_restarts=cfg.replica_restarts,
            restart_backoff=cfg.replica_restart_backoff,
            telemetry=telemetry, clock=self._clock,
        )
        self._replicas: List[Replica] = []
        for rid, eng in enumerate(engines):
            if tracer is not None and getattr(eng, "tracer", None) is None:
                eng.tracer = tracer
            self._replicas.append(Replica(rid, eng, CircuitBreaker(
                cfg.breaker_threshold, cfg.breaker_window_s,
                cfg.breaker_cooldown_s,
            )))
        self.counters.update({
            "migrated": 0, "replica_kills": 0, "replica_restarts": 0,
            "replica_retired": 0, "replica_spawned": 0,
        })
        self._steps = 0
        #: Cumulative replica-hours (ACTIVE + DRAINING replicas integrated
        #: over router-clock time) — the cost axis of the autoscale bench's
        #: attainment-per-replica-hour economics.
        self.replica_hours = 0.0
        self._last_step_t: Optional[float] = None
        #: Attached :class:`~.autoscaler.Autoscaler` (polled at the end of
        #: every step, AFTER health emission, so decisions read this step's
        #: signals and land deterministically on the router clock).
        self._autoscaler = None
        #: Replica ids still awaiting their turn in a rolling restart.
        self._rolling: List[int] = []
        self._rolling_deadline_s: Optional[float] = None
        #: Requests finalized OUTSIDE a step's event collection (the all-
        #: retired backlog flush, possibly triggered by an out-of-band
        #: ``kill()``) — drained into the next ``step()``'s return so the
        #: documented every-terminal-is-returned contract holds.
        self._pending_events: List[GatewayRequest] = []

    # ------------------------------------------------------------- introspection
    @property
    def replicas(self) -> List[Replica]:
        return list(self._replicas)

    @property
    def running_count(self) -> int:
        return sum(len(rep.running) for rep in self._replicas)

    def replica_health(self, rid: int) -> float:
        return self._health(self._replicas[rid], self._clock())

    # ---------------------------------------------------------------- admission
    def _admission_gate(self, greq: GatewayRequest, now: float) -> Optional[str]:
        """Fleet front door: refuse ONLY when no replica could ever serve the
        request — every replica permanently retired. A replica with an open
        breaker, mid-drain or mid-restart keeps the request QUEUED (deadlines
        still protect the caller); rejecting there would refuse work a healthy
        replica could pick up the very next step (the per-replica-isolation
        acceptance contract)."""
        if all(rep.state == RETIRED for rep in self._replicas):
            return "fleet_down"
        return None

    def _free_lanes(self) -> int:
        """Lanes the fleet can fill this step (routable replicas only) — feeds
        the preemption trigger exactly like the single-engine count."""
        now = self._clock()
        return sum(rep.free_lanes() for rep in self._replicas
                   if self._routable(rep, now))

    def _routable(self, rep: Replica, now: float) -> bool:
        """May ``rep`` receive a NEW admission right now? (Read-only: the
        probe assignment happens at dispatch, through ``breaker.gate``.)"""
        if rep.state != ACTIVE:
            return False
        br = rep.breaker
        if br.enabled and br.state != "closed":
            if br.state == "open":
                return now - br._opened_at >= br.cooldown_s  # will half-open
            return br.probe_uid is None  # half-open: one outstanding probe
        return True

    def _health(self, rep: Replica, now: float) -> float:
        """Health score in [0, 1] from signals the stack already tracks:
        recent step failures (quarantines, watchdog timeouts — everything the
        engine's fault boundary counts), lane occupancy, engine-internal queue
        depth (paged pool-pressure deferrals park requests there), and paged
        page-pool occupancy. Dead/retired replicas score 0; a replica whose
        breaker is not closed is capped low so routing prefers proven-healthy
        peers even when the sick one has free lanes."""
        if rep.state in (RESTARTING, RETIRED):
            return 0.0
        eng = rep.engine
        rep.fail_times = [t for t in rep.fail_times
                          if now - t <= HEALTH_WINDOW_S]
        fail_scale = max(1, rep.breaker.threshold or 3)
        score = 1.0
        score -= 0.5 * min(1.0, len(rep.fail_times) / fail_scale)
        active = sum(r is not None for r in eng.slot_req)
        score -= 0.2 * (active / eng.max_slots)
        score -= 0.1 * min(1.0, len(eng.queue) / eng.max_slots)
        if eng.paged:
            ms = eng.block_mgr
            score -= 0.2 * (ms.pages_in_use / ms.num_pages)
        if rep.breaker.enabled and rep.breaker.state != "closed":
            score = min(score, 0.25)
        return max(0.0, round(score, 4))

    def _pick_replica(self, now: float,
                      reps: Optional[List[Replica]] = None) -> Optional[Replica]:
        """Routing decision for the next admission: any half-open replica with
        no outstanding probe gets it FIRST (one probe resolves its state — a
        restarted replica earns full routing back, a still-sick one re-opens
        after a single request); otherwise the healthiest routable replica
        with free lanes, ties to most free lanes then lowest rid. ``reps``
        restricts the candidate pool (the disagg router routes each phase over
        its role subset through this ONE heuristic)."""
        if reps is None:
            reps = self._replicas
        probes = [rep for rep in reps
                  if rep.state == ACTIVE and rep.breaker.enabled
                  and rep.breaker.state != "closed"
                  and self._routable(rep, now) and rep.free_lanes() > 0]
        if probes:
            return probes[0]
        candidates = [rep for rep in reps
                      if self._routable(rep, now) and rep.free_lanes() > 0]
        if not candidates:
            return None
        return max(candidates,
                   key=lambda r: (self._health(r, now), r.free_lanes(), -r.rid))

    def _dispatch(self, greq: GatewayRequest, rep: Replica, now: float) -> None:
        """Admit ``greq`` into ``rep``'s engine (the fleet spelling of the base
        ``_admit``), recording the routing decision as ``fleet.route/v1``."""
        probe = False
        if rep.breaker.enabled:
            gate = rep.breaker.gate(greq.uid, now)
            # _routable said yes, so the only mutation here is assigning the
            # half-open probe; a refusal would be a bookkeeping bug.
            assert gate is None, (rep, gate)
            probe = rep.breaker.probe_uid == greq.uid
        greq.status = RUNNING
        greq.t_admit = now
        greq._rid = rep.rid
        self.counters["admitted"] += 1
        ereq = rep.engine.submit(
            greq.prompt, gen=greq.gen,
            rng=greq.rng if greq.gen.temperature > 0.0 else None,
            on_token=self._stream_cb(greq),
        )
        greq._engine_req = ereq
        rep.running[ereq.uid] = greq
        tr = self.tracer
        if tr is not None:
            tr.span(greq._trace, "queue", greq.t_enqueued, now,
                    attempt=greq.retries_used + greq.replays,
                    outcome="admitted")
            tr.bind_engine(greq._trace, ereq.uid)
        self._emit_route(greq.uid, rep, "probe" if probe else "dispatch", now)

    # ------------------------------------------------------------------ stepping
    def step(self) -> List[GatewayRequest]:
        """One fleet cycle: expire deadline violators, advance replica
        lifecycle (drain completion, restart backoff expiry, rolling restart),
        preempt, admit queued requests to routable replicas in policy order,
        step every live replica engine (a crash fails over instead of
        propagating), observe per-replica failures into the breakers, and emit
        the per-replica ``replica.health/v1`` records."""
        now = self._clock()
        self._steps += 1
        if self._last_step_t is not None and now > self._last_step_t:
            live = sum(1 for rep in self._replicas
                       if rep.state in (ACTIVE, DRAINING))
            self.replica_hours += (now - self._last_step_t) / 3600.0 * live
        self._last_step_t = now
        # Terminals finalized between steps (out-of-band kill → backlog flush)
        # are reported by THIS step — never silently dropped.
        events: List[GatewayRequest] = self._pending_events
        self._pending_events = []

        # 1) queued deadline expiry — never occupies a lane.
        for item in self._policy.items():
            if item.deadline_at is not None and now > item.deadline_at:
                self._policy.remove(item.uid)
                self._queued_cost -= item.cost
                self.counters["expired"] += 1
                self._finalize(item, EXPIRED, "deadline_queued", now)
                events.append(item)

        # 2) running deadline eviction, per replica (lane frees for this same
        #    step's admission pass; engine.cancel finds recovery-parked copies).
        for rep in self._replicas:
            for greq in list(rep.running.values()):
                if greq.deadline_at is not None and now > greq.deadline_at:
                    rep.engine.cancel(greq._engine_req.uid)
                    rep.running.pop(greq._engine_req.uid, None)
                    greq.tokens = list(greq._engine_req.tokens)
                    self.counters["expired"] += 1
                    self._finalize(greq, EXPIRED, "deadline_running", now)
                    events.append(greq)

        # 3) replica lifecycle: drains that completed/overran, restarts whose
        #    backoff elapsed, the next rung of a rolling restart.
        self._advance_replicas(now, events)

        # 4) priority preemption (opt-in), fleet-wide.
        if self.config.preempt:
            events.extend(self._preempt(now))

        # 5) admit in policy order while some replica can take the work.
        while len(self._policy):
            rep = self._pick_replica(now)
            if rep is None:
                break
            item = self._policy.pop(now)
            self._queued_cost -= item.cost
            self._dispatch(item, rep, now)

        # 6) advance every live replica engine; map completions; a crash is
        #    the failover signal, not an exception the caller sees.
        for rep in self._replicas:
            if rep.state in (RESTARTING, RETIRED):
                continue
            try:
                finished = rep.engine.step()
            except EngineCrashed as e:
                self._replica_died(rep, f"crash:{e.site}", now)
                continue
            t_done = self._clock()
            for ereq in finished:
                greq = rep.running.pop(ereq.uid, None)
                if greq is None:
                    continue
                greq.tokens = list(ereq.tokens)
                greq.recoveries = getattr(ereq, "recoveries", 0)
                failed_reason = getattr(ereq, "failed", None)
                if failed_reason is not None:
                    self.counters["failed"] += 1
                    self._finalize(greq, FAILED, failed_reason, t_done)
                else:
                    self.counters["done"] += 1
                    self._finalize(greq, DONE, None, t_done)
                events.append(greq)
            self._observe_replica(rep, now)

        # 7) replica lifecycle again, with this step's completions applied: a
        #    drain whose last in-flight request just finished restarts NOW —
        #    otherwise a drain that completes on the workload's final step
        #    would strand the replica DRAINING until some future step.
        self._advance_replicas(self._clock(), events)
        # Terminals finalized DURING this step outside the event collection
        # (a mid-step retire flushing the backlog) belong to this step too.
        events.extend(self._pending_events)
        self._pending_events = []
        self._emit_health(now)
        if self._autoscaler is not None:
            self._autoscaler.poll(self._clock())
        return sorted(events, key=lambda r: r.uid)

    def run(self, report_slo: bool = False):
        """Base drain loop, plus: keep stepping while out-of-band terminals
        (an all-retired backlog flush after ``kill()``) wait in the pending
        buffer — they must be RETURNED, not just finalized."""
        out: List[GatewayRequest] = []
        while self.queue_depth or self.running_count or self._pending_events:
            out.extend(self.step())
        if report_slo:
            return out, self.emit_slo_record()
        return out

    def _observe_replica(self, rep: Replica, now: float) -> None:
        """Read the replica's step-failure delta into its breaker and health
        window; a breaker trip isolates the replica AND migrates its in-flight
        requests (a replica misbehaving enough to trip the breaker should not
        keep holding requests healthy peers could finish)."""
        failures = getattr(rep.engine, "step_failures", 0)
        delta = failures - rep.failures_seen
        rep.failures_seen = failures
        if delta > 0:
            rep.fail_times.extend([now] * delta)
        if rep.breaker.record_failures(delta, now):
            rep.breaker.open(now)
            self._emit_fleet_recovery("circuit_open", rep, now)
            self._migrate(rep, f"breaker_open:replica{rep.rid}", now,
                          engine_alive=True)

    # ------------------------------------------------------------------ failover
    def _migrate(self, rep: Replica, cause: str, now: float,
                 engine_alive: bool) -> List[GatewayRequest]:
        """Move every in-flight request off ``rep`` back into the queue via the
        replay path (byte-identical transcripts, zero retry-budget spend). With
        the engine still alive its lanes are cancelled first; a crashed engine
        is simply abandoned."""
        migrated = []
        for greq in list(rep.running.values()):
            if engine_alive:
                rep.engine.cancel(greq._engine_req.uid)
            self._replay_requeue(greq, now, cause)
            self.counters["migrated"] += 1
            self._emit_route(greq.uid, rep, "migrate", now)
            migrated.append(greq)
        rep.running.clear()
        return migrated

    def _replica_died(self, rep: Replica, reason: str, now: float) -> None:
        """A replica's engine is gone (crash fault or operator kill): migrate
        its requests, then hand the gang to the supervisor — restart when the
        per-gang budget and backoff allow, retire when the budget is spent."""
        self.counters["replica_kills"] += 1
        self._migrate(rep, reason, now, engine_alive=False)
        allowed = self.supervisor.record_failure(rep.gang_id, reason=reason)
        if allowed and self.engine_factory is not None:
            rep.state = RESTARTING
        else:
            self._retire(rep, now)
        self._emit_fleet_recovery("replica_died", rep, now, reason=reason)

    def _retire(self, rep: Replica, now: float) -> None:
        rep.state = RETIRED
        self.counters["replica_retired"] += 1
        self._emit_fleet_recovery("replica_retired", rep, now)
        if all(r.state == RETIRED for r in self._replicas):
            # Nothing left to serve with: fail the backlog machine-readably
            # rather than stranding it queued forever (a silent loss). The
            # finalized requests ride the pending-event buffer into the next
            # step()'s return — run()'s every-terminal contract holds.
            for item in self._policy.items():
                self._policy.remove(item.uid)
                self._queued_cost -= item.cost
                self.counters["failed"] += 1
                self._finalize(item, FAILED, "fleet_down", now)
                self._pending_events.append(item)

    def kill(self, rid: int, reason: str = "killed") -> None:
        """Operator/test hook: treat replica ``rid`` as dead right now (the
        out-of-band spelling of an injected ``crash`` fault)."""
        rep = self._replicas[rid]
        if rep.state in (RESTARTING, RETIRED):
            return
        rep.engine.crashed = True
        self._replica_died(rep, reason, self._clock())

    # ------------------------------------------------------------ scale up / down
    def spawn_replica(self, role: Optional[str] = None) -> Replica:
        """Scale-up actuator: append a fresh replica built by
        ``engine_factory`` (same geometry as the fleet — validated), with its
        own breaker started HALF-OPEN so the newcomer earns full routing
        through one probe, exactly like a restarted replica. ``role`` is
        rejected here; the disagg router's override grows its role table."""
        if self.engine_factory is None:
            raise ValueError(
                "spawn_replica needs an engine_factory — a fleet that cannot "
                "build engines cannot grow"
            )
        if role is not None:
            raise ValueError(
                "role-aware spawning is a DisaggRouter capability; a flat "
                "fleet has no roles"
            )
        rid = len(self._replicas)
        engine = self.engine_factory(rid)
        ref = self._replicas[0].engine
        geo = (engine.max_slots, engine.max_len, engine.prompt_bucket,
               engine.page_size)
        ref_geo = (ref.max_slots, ref.max_len, ref.prompt_bucket, ref.page_size)
        if geo != ref_geo:
            raise ValueError(
                f"spawned replica geometry {geo} != fleet geometry {ref_geo}: "
                "the admission cost model prices ONE layout"
            )
        if self.tracer is not None and getattr(engine, "tracer", None) is None:
            engine.tracer = self.tracer
        cfg = self.config
        rep = Replica(rid, engine, CircuitBreaker(
            cfg.breaker_threshold, cfg.breaker_window_s, cfg.breaker_cooldown_s,
        ))
        self._replicas.append(rep)
        self.counters["replica_spawned"] += 1
        if rep.breaker.enabled:
            rep.breaker.force_half_open()  # one probe earns full routing
        self._emit_fleet_recovery("replica_spawn", rep, self._clock())
        return rep

    def decommission(self, rid: int, deadline_s: Optional[float] = None) -> Replica:
        """Scale-down actuator: drain replica ``rid`` (in-flight requests
        finish, or migrate byte-identically past the deadline) and RETIRE it
        when the drain completes instead of restarting — a planned exit that
        charges no supervisor restart budget."""
        rep = self.drain(rid, deadline_s)
        rep.retire_on_drain = True
        return rep

    # ------------------------------------------------------------ drain / restart
    def drain(self, rid: int, deadline_s: Optional[float] = None) -> Replica:
        """Stop routing new admissions to replica ``rid``; in-flight requests
        keep running until they finish or the drain deadline passes (then they
        migrate), after which the replica restarts and re-admits through a
        half-open probe warm-up. The rolling-restart primitive."""
        rep = self._replicas[rid]
        if rep.state != ACTIVE:
            raise ValueError(f"replica {rid} is {rep.state}, not active")
        if deadline_s is None:
            deadline_s = self.config.drain_deadline_s
        rep.state = DRAINING
        rep.drain_deadline = (
            None if deadline_s is None else self._clock() + float(deadline_s)
        )
        self._emit_fleet_recovery("drain", rep, self._clock())
        return rep

    def rolling_restart(self, deadline_s: Optional[float] = None) -> None:
        """Restart every replica, one at a time: drain the first; each next
        replica drains only once the previous one is back (ACTIVE with a
        closed/disabled breaker), so fleet capacity never drops by more than
        one replica."""
        self._rolling = [rep.rid for rep in self._replicas
                         if rep.state == ACTIVE]
        self._rolling_deadline_s = deadline_s
        if self._rolling:
            self.drain(self._rolling.pop(0), deadline_s)

    def _advance_replicas(self, now: float, events: List[GatewayRequest]) -> None:
        for rep in self._replicas:
            if rep.state == DRAINING:
                overdue = (rep.drain_deadline is not None
                           and now > rep.drain_deadline)
                if overdue and rep.running:
                    self._migrate(rep, f"drain_deadline:replica{rep.rid}", now,
                                  engine_alive=True)
                if not rep.running:
                    self._restart(rep, now)
            elif rep.state == RESTARTING:
                if (self.engine_factory is not None
                        and self.supervisor.may_restart(rep.gang_id)):
                    self._restart(rep, now)
        # Rolling restart: start the next drain once no replica is mid-cycle —
        # the drained one is back to ACTIVE and fully routable. RETIRED
        # replicas are out of the fleet for good: they neither block the gate
        # (a mid-cycle retirement must not stall the remaining restarts
        # forever) nor take a turn (drain() would refuse them).
        if self._rolling and all(
            rep.state == RETIRED
            or (rep.state == ACTIVE and (not rep.breaker.enabled
                                         or rep.breaker.state == "closed"))
            for rep in self._replicas
        ):
            while self._rolling:
                rid = self._rolling.pop(0)
                if self._replicas[rid].state == ACTIVE:
                    self.drain(rid, self._rolling_deadline_s)
                    break

    def _restart(self, rep: Replica, now: float) -> None:
        """Bring a drained/dead replica back: fresh engine from the factory
        (or the drained engine itself when no factory is configured — a drain
        cycle without replacement still re-proves health), then the half-open
        probe warm-up: the replica serves ONE probe request before regaining
        full routing."""
        if rep.retire_on_drain:
            # Autoscaler decommission: the drain completing means the replica
            # leaves the fleet for good — no supervisor budget charge (this
            # is not a failure), no restart. Routed through _restart so the
            # disagg override's live-handoff drain guard protects scale-down
            # exactly like a rolling restart.
            rep.drain_deadline = None
            self._retire(rep, now)
            return
        if self.engine_factory is not None:
            rep.engine = self.engine_factory(rep.rid)
            if self.tracer is not None and getattr(rep.engine, "tracer", None) is None:
                rep.engine.tracer = self.tracer
            if rep.rid == 0:
                # Base-class machinery (kv_demand cost model) reads self.engine.
                self.engine = rep.engine
        rep.failures_seen = getattr(rep.engine, "step_failures", 0)
        rep.fail_times = []
        rep.drain_deadline = None
        rep.restarts += 1
        rep.state = ACTIVE
        self.counters["replica_restarts"] += 1
        if rep.breaker.enabled:
            rep.breaker.force_half_open()  # one probe earns full routing back
        self._emit_fleet_recovery("replica_restart", rep, now)

    def _probe_verdict(self, greq: GatewayRequest, status: str,
                       now: float) -> None:
        """Per-replica probe fate (overrides the single-breaker hook): DONE
        closes that replica's breaker (full routing restored), FAILED re-opens
        it for another cooldown; any other terminal (cancel/expiry) releases
        the probe slot so the next admission re-probes."""
        for rep in self._replicas:
            br = rep.breaker
            if br.probe_uid is None or br.probe_uid != greq.uid:
                continue
            if status == DONE:
                br.close(now)
                self._emit_fleet_recovery("circuit_close", rep, now)
            elif status == FAILED:
                br.open(now)
                self._emit_fleet_recovery("circuit_open", rep, now)
            else:
                br.probe_uid = None
            return

    # ------------------------------------------------------------------- control
    def cancel(self, uid: int) -> bool:
        greq = self._all.get(uid)
        if greq is None or greq.terminal:
            return False
        now = self._clock()
        if greq.status == QUEUED:
            self._policy.remove(greq.uid)
            self._queued_cost -= greq.cost
            self.counters["cancelled"] += 1
            self._finalize(greq, CANCELLED, "cancelled_queued", now)
            return True
        rep = self._replicas[greq._rid]
        rep.engine.cancel(greq._engine_req.uid)
        rep.running.pop(greq._engine_req.uid, None)
        greq.tokens = list(greq._engine_req.tokens)
        self.counters["cancelled"] += 1
        self._finalize(greq, CANCELLED, "cancelled_running", now)
        return True

    def _preempt(self, now: float) -> List[GatewayRequest]:
        """Fleet-wide preemption: the globally least-urgent running request
        yields its lane to a strictly higher-priority queued one, which is
        admitted into that same replica directly — the base-class semantics,
        with the victim lookup spanning replicas. Victims are taken ONLY from
        replicas whose breaker is closed (or disabled): a half-open replica's
        lane may hold its probe — cancelling it and dispatching the preemptor
        there would corrupt the probe bookkeeping, and a sick replica is the
        wrong home for the most urgent request anyway."""
        events: List[GatewayRequest] = []
        while len(self._policy):
            running = [(rep, greq) for rep in self._replicas
                       if rep.state == ACTIVE
                       and (not rep.breaker.enabled
                            or rep.breaker.state == "closed")
                       for greq in rep.running.values()]
            if not running or self._free_lanes() > 0:
                break
            top = max(self._policy.items(), key=lambda i: (i.priority, -i.uid))
            rep, victim = min(running,
                              key=lambda rg: (rg[1].priority, -rg[1].uid))
            if victim.priority >= top.priority:
                break
            rep.engine.cancel(victim._engine_req.uid)
            rep.running.pop(victim._engine_req.uid, None)
            if self.tracer is not None:
                self.tracer.event(victim._trace, "preempt", t=now,
                                  preempted_by=top.uid,
                                  tokens_lost=len(victim._engine_req.tokens))
            self._policy.take(top.uid, now)
            self._queued_cost -= top.cost
            self._dispatch(top, rep, now)
            evicted = self._preempt_victim_requeue(victim, now)
            if evicted is not None:
                events.append(evicted)
        return events

    def reattach_engine(self, engine=None, reason: str = "engine_restart"):
        raise NotImplementedError(
            "the fleet router owns replica recovery itself — use kill()/drain()/"
            "rolling_restart(); single-engine replay is ServingGateway's"
        )

    # ---------------------------------------------------------------- telemetry
    def _emit_route(self, uid: int, rep: Replica, reason: str,
                    now: float) -> None:
        tel = self.telemetry
        if tel is None or not tel.enabled:
            return
        tel.emit({
            "schema": FLEET_ROUTE_SCHEMA,
            "uid": uid,
            "replica": rep.rid,
            "reason": reason,
            "health": self._health(rep, now),
            "free_lanes": rep.free_lanes(),
            "step": self._steps,
            "t": now,
        })

    def _emit_health(self, now: float) -> None:
        tel = self.telemetry
        if tel is None or not tel.enabled:
            return
        for rep in self._replicas:
            eng = rep.engine
            record = {
                "schema": REPLICA_HEALTH_SCHEMA,
                "replica": rep.rid,
                "state": rep.state,
                "role": getattr(eng, "role", "mixed"),
                "health": self._health(rep, now),
                "breaker_state": rep.breaker.state,
                "active_slots": sum(r is not None for r in eng.slot_req),
                "queued": len(eng.queue),
                "step_failures": getattr(eng, "step_failures", 0),
                "watchdog_timeouts": (
                    eng._watchdog.timeouts
                    if getattr(eng, "_watchdog", None) is not None else 0
                ),
                "restarts": rep.restarts,
                "step": self._steps,
                "t": now,
            }
            if eng.paged:
                record["page_occupancy"] = round(
                    eng.block_mgr.pages_in_use / eng.block_mgr.num_pages, 4
                )
            tel.emit(record)

    def _emit_fleet_recovery(self, action: str, rep: Replica, now: float,
                             **cols) -> None:
        tel = self.telemetry
        if tel is None or not tel.enabled:
            return
        tel.emit({
            "schema": RECOVERY_SCHEMA, "action": action, "t": now,
            "replica": rep.rid, "replica_state": rep.state,
            "breaker_state": rep.breaker.state, **cols,
        })

    # ------------------------------------------------------------------ reporting
    def stats(self) -> dict:
        """Fleet + per-replica observability snapshot (replaces the base's
        single nested engine block with one block per replica)."""
        now = self._clock()
        return {
            "policy": self._policy.name,
            "queued": len(self._policy),
            "queued_cost_tokens": self._queued_cost,
            "running": self.running_count,
            **dict(self.counters),
            "replicas": [
                {
                    "replica": rep.rid,
                    "state": rep.state,
                    "health": self._health(rep, now),
                    "breaker_state": rep.breaker.state,
                    "breaker_openings": rep.breaker.openings,
                    "breaker_closings": rep.breaker.closings,
                    "running": len(rep.running),
                    "restarts": rep.restarts,
                    "engine": rep.engine.stats(),
                }
                for rep in self._replicas
            ],
            "supervisor": self.supervisor.stats(),
            "slo": self.slo_summary(),
            # The live metrics plane (GatewayConfig.metrics, inherited from
            # the base constructor): the per-replica health/route records this
            # router emits every step land back here as labeled gauges — the
            # fleet-wide signal surface the autoscaler polls.
            **({"metrics": self.metrics.stats()} if self.metrics is not None
               else {}),
        }

    def __repr__(self) -> str:
        states = ",".join(f"{r.rid}:{r.state}" for r in self._replicas)
        return (f"FleetRouter(policy={self._policy.name!r}, replicas=[{states}], "
                f"queued={len(self._policy)}, running={self.running_count})")
