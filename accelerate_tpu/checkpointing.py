"""Checkpoint / resume (L7).

TPU-native analog of reference ``checkpointing.py`` (/root/reference/src/accelerate/
checkpointing.py): ``save_accelerator_state`` (:57), ``load_accelerator_state`` (:175),
custom-object hooks (:303,313); plus the ``Accelerator.save_state``/``load_state`` directory
contract (reference ``accelerator.py:3106,3272``) with automatic naming + rotation
(``ProjectConfiguration``, pruning at reference ``accelerator.py:3149-3163``).

Format divergences from the reference (torch pickles):
- The sharded ``TrainState`` (params / optimizer state / counters) is saved with **orbax /
  tensorstore** — every host writes only its own shards (the SHARDED_STATE_DICT analog,
  reference ``utils/fsdp_utils.py:96-107``), and restore re-shards to the current mesh.
- Host-side bits keep the reference's file naming: ``random_states_{rank}.pkl`` (python/numpy/
  torch RNG), ``custom_checkpoint_{i}.pkl``, ``scheduler.json``/``sampler.json`` metadata.
- ``model.safetensors`` can additionally be exported for interchange (``safe_serialization``).
"""

from __future__ import annotations

import json
import pickle
import random
import shutil
from pathlib import Path
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .logging import get_logger
from .utils.constants import (
    CUSTOM_OBJECT_NAME,
    MODEL_NAME,
    RNG_STATE_NAME,
    SAFE_WEIGHTS_NAME,
    SAMPLER_STATE_NAME,
    SCHEDULER_STATE_NAME,
    SHARDED_STATE_DIR,
)
from .utils.imports import is_safetensors_available, is_torch_available

logger = get_logger(__name__)

__all__ = [
    "save_accelerator_state",
    "load_accelerator_state",
    "save_custom_state",
    "load_custom_state",
    "wait_for_async_save",
    "verify_checkpoint",
    "CheckpointCorruptError",
    "MANIFEST_NAME",
    "COMMIT_MARKER",
    "PIPELINE_META",
    "save_pipeline_checkpoint",
    "load_pipeline_checkpoint",
    "select_pipeline_checkpoint",
    "rotate_pipeline_checkpoints",
]

# ------------------------------------------------------------ verified checkpoints
#: Per-file sha256 manifest written after every file of a snapshot lands.
MANIFEST_NAME = "manifest.sha256.json"
#: Atomic validity marker written LAST (tmp + rename): its presence is the
#: committed bit — a crash mid-save leaves no marker, and the loader treats
#: the directory as garbage instead of restoring a torn snapshot.
COMMIT_MARKER = "COMMITTED"
#: Quarantine subdirectory invalid checkpoints are moved into on load fallback
#: (outside the ``checkpoint_*`` glob, so rotation/iteration never sees them).
QUARANTINE_DIR = "quarantined"

#: Epoch-level metadata of a COORDINATED multi-stage (MPMD pipeline) snapshot:
#: written FIRST, before any stage saves, naming how many ``stage_<i>/``
#: subdirectories a complete snapshot must carry. Its presence switches
#: :func:`verify_checkpoint` to pipeline semantics — the epoch is committed
#: only when EVERY declared stage's own marker landed and verifies; a
#: partial-commit epoch (one stage crashed mid-save) is invalid AS A UNIT.
PIPELINE_META = "pipeline.json"


class CheckpointCorruptError(RuntimeError):
    """An explicitly-named checkpoint failed integrity verification."""

    def __init__(self, path, problems):
        super().__init__(
            f"checkpoint {path} failed verification: {'; '.join(problems)}"
        )
        self.path = str(path)
        self.problems = list(problems)


def _sha256_file(path: Path) -> str:
    import hashlib

    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _manifest_files(path: Path):
    """Every snapshot file, checkpoint-relative, manifest/marker excluded."""
    skip = {MANIFEST_NAME, COMMIT_MARKER}
    return sorted(
        p.relative_to(path).as_posix()
        for p in path.rglob("*")
        if p.is_file() and p.name not in skip
    )


def _write_commit_marker(path: Path) -> None:
    """Hash every file, write the manifest, then the marker — atomically
    (tmp + rename), and strictly LAST: a crash at any earlier point leaves an
    uncommitted directory the loader skips."""
    manifest = {rel: _sha256_file(path / rel) for rel in _manifest_files(path)}
    (path / MANIFEST_NAME).write_text(json.dumps(manifest, indent=1, sort_keys=True))
    import os

    tmp = path / (COMMIT_MARKER + ".tmp")
    tmp.write_text(json.dumps({"files": len(manifest)}))
    os.replace(tmp, path / COMMIT_MARKER)


def verify_checkpoint(path) -> list:
    """Integrity problems of one checkpoint directory (empty = valid):
    missing commit marker (crash mid-save), missing manifest, files that
    disappeared, grew extra, or whose sha256 no longer matches.

    A directory carrying :data:`PIPELINE_META` is a COORDINATED multi-stage
    snapshot: every declared ``stage_<i>/`` subdirectory is verified with its
    own manifest+marker, problems prefixed with the stage. One stage missing
    its marker (a stage process killed mid-save) makes the WHOLE epoch
    invalid — a pipeline restore mixing epochs across stages would silently
    train a Frankenstein state."""
    path = Path(path)
    meta_file = path / PIPELINE_META
    if meta_file.exists():
        try:
            meta = json.loads(meta_file.read_text())
            n_stages = int(meta["n_stages"])
        except (json.JSONDecodeError, OSError, KeyError, TypeError, ValueError) as e:
            return [f"unreadable {PIPELINE_META}: {e}"]
        problems = []
        for i in range(n_stages):
            sdir = path / f"stage_{i}"
            if not sdir.is_dir():
                problems.append(f"stage_{i}: missing (partial pipeline save)")
                continue
            problems.extend(f"stage_{i}: {p}" for p in verify_checkpoint(sdir))
        return problems
    problems = []
    if not (path / COMMIT_MARKER).exists():
        return ["uncommitted (no COMMITTED marker — crash mid-save?)"]
    if not (path / MANIFEST_NAME).exists():
        return ["committed but manifest missing"]
    try:
        manifest = json.loads((path / MANIFEST_NAME).read_text())
    except (json.JSONDecodeError, OSError) as e:
        return [f"unreadable manifest: {e}"]
    present = set(_manifest_files(path))
    for rel, digest in manifest.items():
        if rel not in present:
            problems.append(f"missing file {rel}")
            continue
        try:
            ok = _sha256_file(path / rel) == digest
        except OSError as e:
            # Another rank may be quarantining this very directory under our
            # feet (multi-process load fallback) — a vanished file is an
            # invalidity verdict, not a crash.
            problems.append(f"unreadable file {rel}: {e}")
            continue
        if not ok:
            problems.append(f"sha256 mismatch: {rel}")
    for rel in sorted(present - set(manifest)):
        problems.append(f"unmanifested file {rel}")
    return problems


def _list_checkpoints(base: Path) -> list:
    """``checkpoint_*`` directories under ``base`` in numeric order — the ONE
    listing behind latest-selection, rotation and the verified-load fallback,
    so the three can never disagree on what the checkpoint set is."""
    return sorted(
        base.glob("checkpoint_*"), key=lambda p: int(p.name.split("_")[-1])
    )


def _checkpoint_committed(path: Path) -> bool:
    """Cheap committed-bit check (marker presence, no hashing) that rotation
    shares with the pipeline helpers. A :data:`PIPELINE_META` epoch is
    committed only when EVERY declared stage's marker landed — a
    partial-commit epoch must neither count toward ``total_limit`` nor shield
    older complete snapshots from rotation."""
    meta_file = path / PIPELINE_META
    if meta_file.exists():
        try:
            n_stages = int(json.loads(meta_file.read_text())["n_stages"])
        except (json.JSONDecodeError, OSError, KeyError, TypeError, ValueError):
            return False
        return all(
            (path / f"stage_{i}" / COMMIT_MARKER).exists()
            for i in range(n_stages)
        )
    return (path / COMMIT_MARKER).exists()


def _checkpoint_dir(accelerator, output_dir: Optional[str], for_save: bool) -> Path:
    project = accelerator.project_configuration
    if output_dir is None:
        if project.project_dir is None:
            raise ValueError("No output_dir given and no project_dir configured.")
        base = Path(project.project_dir) / "checkpoints"
        if for_save:
            target = base / f"checkpoint_{project.iteration}"
        else:
            # Load the latest checkpoint (reference load_state default behavior :3290).
            existing = _list_checkpoints(base)
            if not existing:
                raise FileNotFoundError(f"No checkpoints found under {base}")
            target = existing[-1]
        return target
    return Path(output_dir)


def _rotate_checkpoints(accelerator, base: Path) -> None:
    """Prune old snapshots to ``total_limit``, counting only COMMITTED
    checkpoints and never deleting the newest valid one.

    Uncommitted/corrupt directories (a crashed save's leftovers) neither count
    toward the limit nor shield older valid snapshots from rotation — and the
    newest committed checkpoint survives unconditionally: if the save about to
    happen crashes mid-write, it is the only state the loader can fall back
    to (regression-tested with an injected mid-save crash)."""
    limit = accelerator.project_configuration.total_limit
    if limit is None:
        return
    existing = _list_checkpoints(base.parent)
    committed = [p for p in existing if _checkpoint_committed(p)]
    # Keep limit-1 committed snapshots (the incoming save is the limit-th),
    # but never fewer than one: the newest valid checkpoint is sacred.
    while len(committed) > max(max(limit, 1) - 1, 1):
        victim = committed.pop(0)
        logger.info(f"Deleting old checkpoint {victim} (total_limit={limit})")
        shutil.rmtree(victim, ignore_errors=True)


# Persistent async checkpointer (orbax keeps a background thread pool; one per process).
# Created lazily on the first async save; ``wait_for_async_save`` joins any in-flight write.
_ASYNC_CKPTR = None

# An async save defers its manifest + COMMITTED marker until the background
# write joins: (path, write_marker, corrupt) — the marker lands in
# wait_for_async_save, which every save AND load calls first, so no reader can
# see the snapshot as committed before its bytes are durable. ``corrupt``
# carries a deferred ckpt.save corruption injection (it must land AFTER the
# manifest is hashed, or the manifest would faithfully describe corrupt bytes
# and verification could never catch them).
_PENDING_COMMIT = None


def _corrupt_one_file(path: Path) -> None:
    """Injected silent corruption: flip one byte of the first manifested file
    — the bit-rot the marker alone cannot catch and manifest verification
    must."""
    files = _manifest_files(path)
    if not files:
        return
    victim = path / files[0]
    data = bytearray(victim.read_bytes())
    if data:
        data[len(data) // 2] ^= 0xFF
        victim.write_bytes(bytes(data))


def _async_checkpointer():
    global _ASYNC_CKPTR
    if _ASYNC_CKPTR is None:
        import orbax.checkpoint as ocp

        _ASYNC_CKPTR = ocp.StandardCheckpointer()
    return _ASYNC_CKPTR


def wait_for_async_save() -> None:
    """Block until any in-flight async checkpoint write has committed to disk
    (and stamp the deferred integrity manifest + COMMITTED marker — an async
    snapshot is only *valid* once its background write joined)."""
    global _PENDING_COMMIT
    if _ASYNC_CKPTR is not None:
        _ASYNC_CKPTR.wait_until_finished()
    if _PENDING_COMMIT is not None:
        path, write_marker, corrupt = _PENDING_COMMIT
        _PENDING_COMMIT = None
        if write_marker:
            _write_commit_marker(Path(path))
        if corrupt:
            _corrupt_one_file(Path(path))


def save_accelerator_state(
    accelerator,
    output_dir: Optional[str] = None,
    train_state=None,
    safe_serialization: bool = False,
    async_save: bool = False,
) -> str:
    """Write a full resumable snapshot. Returns the checkpoint path.

    ``async_save=True`` (sharded format only): the device→host copy happens synchronously
    (so donated train steps may immediately reuse the buffers) but the disk write runs in
    orbax's background threads — training resumes while the snapshot commits. The next
    save (or :func:`wait_for_async_save` / ``Accelerator.end_training``) joins the write.
    The reference has no async path (single-file torch pickles, SURVEY.md §5).
    """
    # Unconditionally join any in-flight async write FIRST: rotation below may delete the
    # very directory that write targets, and a sync save to the same path would rmtree it
    # mid-write — both would corrupt the snapshot.
    wait_for_async_save()
    project = accelerator.project_configuration
    automatic = output_dir is None and project.automatic_checkpoint_naming
    if automatic:
        # Rotation must be single-writer: every rank pruning concurrently races the
        # directory listing against the other ranks' in-progress saves and over-deletes
        # (observed: total_limit=2 leaving ONE checkpoint under 2 processes).
        # Barrier BEFORE the prune: every rank has then entered save_state and joined its
        # own async writer (wait_for_async_save above), so no straggler is still writing
        # shards into a directory the main rank is about to rmtree. Barrier after keeps
        # ranks from writing the new snapshot into a directory mid-prune.
        accelerator.wait_for_everyone()
        if accelerator.is_main_process:
            _rotate_checkpoints(accelerator, Path(project.project_dir) / "checkpoints" / "x")
        accelerator.wait_for_everyone()
    path = _checkpoint_dir(accelerator, output_dir, for_save=True)
    path.mkdir(parents=True, exist_ok=True)
    # A re-used directory (overwriting a crashed save, or an explicit path
    # saved twice) must lose its committed bit FIRST: the marker only ever
    # describes bytes that are fully on disk.
    marker = path / COMMIT_MARKER
    if marker.exists():
        marker.unlink()
    pending_async = False

    for hook in accelerator._save_model_hooks:
        hook(accelerator._models, train_state, str(path))

    # 1. Train state: SHARDED (orbax/tensorstore, every host writes its shards) or FULL
    # (all-gather + consolidated single-file state on rank 0 — reference FSDP
    # FULL_STATE_DICT, utils/fsdp_utils.py:66-107), chosen by the fsdp plugin's
    # ``state_dict_type``.
    if train_state is not None:
        full = (
            getattr(accelerator.state, "fsdp_plugin", None) is not None
            and accelerator.state.fsdp_plugin.state_dict_type == "FULL_STATE_DICT"
        )
        full_file = path / f"{MODEL_NAME}_full.pkl"
        sharded_dir = (path / SHARDED_STATE_DIR).absolute()
        if full:
            if async_save:
                logger.warning(
                    "async_save is only supported for the sharded format; "
                    "FULL_STATE_DICT saves synchronously", main_process_only=True,
                )
            from .parallel.fsdp import gather_full_params

            # The allgather is a collective — EVERY process must run it; only rank 0 writes
            # (FULL checkpoints therefore assume a filesystem readable by all ranks at load
            # time, the same contract as the reference's FULL_STATE_DICT).
            host_state = gather_full_params(train_state)
            if accelerator.is_main_process:
                if sharded_dir.exists():  # don't leave a stale other-format snapshot behind
                    shutil.rmtree(sharded_dir)
                with open(full_file, "wb") as f:
                    pickle.dump(host_state, f)
            accelerator.wait_for_everyone()
        else:
            import orbax.checkpoint as ocp

            if sharded_dir.exists():
                shutil.rmtree(sharded_dir)
            if full_file.exists() and accelerator.is_main_process:
                full_file.unlink()  # same: a stale FULL file would shadow this save on load
            if async_save:
                # Snapshot BEFORE handing off: orbax's background threads read the
                # buffers after save() returns, but the caller immediately resumes
                # (donating) training — on the CPU backend the donated buffers are
                # then overwritten IN PLACE and the background write would persist
                # post-step values (observed: async roundtrip restoring a state
                # 3 steps newer than the save point). jnp.copy allocates fresh
                # device buffers with the same shardings (multi-host safe); the
                # transient 2x state memory lives only until the write commits.
                snapshot = jax.tree_util.tree_map(
                    lambda l: jnp.copy(l) if isinstance(l, jax.Array) else l,
                    train_state,
                )
                _async_checkpointer().save(sharded_dir, snapshot)
                pending_async = True
            else:
                with ocp.StandardCheckpointer() as ckptr:
                    ckptr.save(sharded_dir, train_state)
        # 1b. Optional interchange export: consolidated safetensors of the params.
        if safe_serialization and accelerator.is_main_process:
            _export_safetensors(train_state.params, path / SAFE_WEIGHTS_NAME)

    # 2. Host-side objects (main process writes shared files; every process its RNG).
    meta: dict[str, Any] = {
        "step": accelerator.step,
        "iteration": project.iteration,
        "optimizers": [opt.state_dict() for opt in accelerator._optimizers],
    }
    schedulers = []
    for sched in accelerator._schedulers:
        try:
            schedulers.append(sched.state_dict())
        except Exception:
            schedulers.append(None)
    meta["schedulers"] = schedulers
    samplers = []
    for dl in accelerator._dataloaders:
        if getattr(dl, "stateful", False) and hasattr(dl, "state_dict"):
            # Stateful mode: epoch AND mid-epoch position (torchdata StatefulDataLoader
            # analog, reference checkpointing.py:135-139).
            samplers.append(dl.state_dict())
        else:
            samplers.append({"iteration": getattr(dl, "iteration", 0)})
    meta["dataloaders"] = samplers
    if accelerator.is_main_process:
        (path / SCHEDULER_STATE_NAME).write_text(json.dumps(meta, indent=2))
        (path / SAMPLER_STATE_NAME).write_text(json.dumps(samplers))

    # Custom objects are host-replicated: one copy suffices on a shared filesystem;
    # ProjectConfiguration.save_on_each_node asks each node's local-main process to
    # write its own copy (node-local disks, reference checkpointing.py:303). The
    # per-process gate lives inside save_custom_state (utils.other.save idiom).
    save_each = getattr(
        getattr(accelerator, "project_configuration", None), "save_on_each_node", False
    )
    for i, obj in enumerate(accelerator._custom_objects):
        save_custom_state(obj, str(path), i, save_on_each_node=save_each)

    # 3. Per-process host RNG states (reference checkpointing.py:148-171).
    states: dict[str, Any] = {
        "random_state": random.getstate(),
        "numpy_random_seed": np.random.get_state(),
    }
    if is_torch_available():
        import torch

        states["torch_manual_seed"] = torch.get_rng_state()
    with open(path / f"{RNG_STATE_NAME}_{accelerator.process_index}.pkl", "wb") as f:
        pickle.dump(states, f)

    # ---- verified-checkpoint commit (docs/resilience.md): every file hashed
    # into a sha256 manifest, then the atomic COMMITTED marker written LAST —
    # a crash anywhere above leaves an uncommitted directory the loader skips.
    plan = getattr(accelerator, "fault_plan", None)
    spec = plan.draw("ckpt.save") if plan is not None else None
    if spec is not None and spec.kind == "crash":
        from .resilience.faults import InjectedFault

        # Injected mid-save crash: the data files are on disk, the marker is
        # NOT — exactly the torn state a preemption during save leaves behind.
        raise InjectedFault("ckpt.save", "crash")
    if accelerator.num_processes > 1:
        # Every rank's files (RNG pickles, shards) must exist before the main
        # process hashes the directory.
        accelerator.wait_for_everyone()
    corrupt = spec is not None and spec.kind == "corrupt"
    if pending_async:
        # The corruption injection rides the deferred commit: flipping a byte
        # NOW would be hashed into the manifest at the join and read as valid.
        global _PENDING_COMMIT
        _PENDING_COMMIT = (str(path), accelerator.is_main_process, corrupt)
    else:
        if accelerator.is_main_process:
            _write_commit_marker(path)
        if corrupt:
            # Injected silent corruption AFTER the commit: a bit flip the
            # marker alone cannot catch — manifest verification at load must.
            _corrupt_one_file(path)
    if automatic:
        project.iteration += 1
    logger.info(f"Saved accelerator state to {path}")
    return str(path)


def _quarantine_checkpoint(accelerator, cand: Path, base: Path, problems) -> None:
    """Move an invalid checkpoint out of the ``checkpoint_*`` namespace (so
    rotation and latest-selection never see it again), count it, and telemeter
    the fault — corruption must be observable, not silently skipped."""
    logger.warning(
        f"checkpoint {cand} failed verification ({'; '.join(problems)}) — "
        f"quarantining and falling back to the previous valid snapshot"
    )
    if accelerator.is_main_process:
        qdir = base / QUARANTINE_DIR
        qdir.mkdir(parents=True, exist_ok=True)
        dest = qdir / cand.name
        if dest.exists():
            shutil.rmtree(dest, ignore_errors=True)
        shutil.move(str(cand), str(dest))
    accelerator.checkpoints_quarantined = (
        getattr(accelerator, "checkpoints_quarantined", 0) + 1
    )
    tel = getattr(accelerator, "telemetry", None)
    if tel is not None and getattr(tel, "enabled", False):
        from .telemetry.schemas import FAULT_SCHEMA, RECOVERY_SCHEMA

        tel.emit({
            "schema": FAULT_SCHEMA, "site": "ckpt.load", "kind": "corrupt",
            "checkpoint": cand.name, "problems": list(problems),
        })
        tel.emit({
            "schema": RECOVERY_SCHEMA, "action": "checkpoint_fallback",
            "quarantined": cand.name,
            "quarantined_total": accelerator.checkpoints_quarantined,
        })


def _select_valid_checkpoint(accelerator) -> Path:
    """Newest checkpoint that passes integrity verification; invalid ones
    (uncommitted mid-save crashes, corrupt files) are quarantined and the
    search falls back to the next-newest — the automatic-naming load contract
    (docs/resilience.md)."""
    project = accelerator.project_configuration
    if project.project_dir is None:
        raise ValueError("No output_dir given and no project_dir configured.")
    base = Path(project.project_dir) / "checkpoints"
    existing = _list_checkpoints(base)
    if not existing:
        raise FileNotFoundError(f"No checkpoints found under {base}")
    for cand in reversed(existing):
        problems = verify_checkpoint(cand)
        if not problems:
            return cand
        _quarantine_checkpoint(accelerator, cand, base, problems)
    raise FileNotFoundError(
        f"No VALID checkpoint under {base}: all {len(existing)} candidates "
        f"failed verification (quarantined under {base / QUARANTINE_DIR})"
    )


def load_accelerator_state(
    accelerator,
    input_dir: Optional[str] = None,
    train_state=None,
    load_optimizer_states: bool = True,
):
    """Restore a snapshot. Returns the restored TrainState (or None if none was given).

    With ``input_dir=None`` (automatic naming) the NEWEST checkpoint that
    passes integrity verification wins — uncommitted or corrupt ones are
    quarantined (moved under ``checkpoints/quarantined/``), counted on
    ``accelerator.checkpoints_quarantined`` and telemetered. An explicit
    ``input_dir`` that carries a commit marker is verified and raises
    :class:`CheckpointCorruptError` on mismatch (an explicit path is caller
    intent — falling back silently would restore the wrong state); marker-less
    directories (external/interop snapshots) load as before."""
    wait_for_async_save()  # never read a directory whose write hasn't committed
    if input_dir is None and accelerator.project_configuration.project_dir is not None:
        path = _select_valid_checkpoint(accelerator)
    else:
        path = _checkpoint_dir(accelerator, input_dir, for_save=False)
    if not path.exists():
        raise FileNotFoundError(f"Checkpoint {path} does not exist")
    if input_dir is not None and (
        (path / COMMIT_MARKER).exists() or (path / MANIFEST_NAME).exists()
    ):
        problems = verify_checkpoint(path)
        if problems:
            raise CheckpointCorruptError(path, problems)

    for hook in accelerator._load_model_hooks:
        hook(accelerator._models, train_state, str(path))

    restored = None
    if train_state is not None:
        # Format dispatch follows the plugin when one is configured (identical on every rank
        # — a per-host file probe would diverge across ranks without a shared filesystem);
        # the file probe is only the single-process/no-plugin fallback.
        full_file = path / f"{MODEL_NAME}_full.pkl"
        plugin = getattr(accelerator.state, "fsdp_plugin", None)
        if plugin is not None:
            use_full = plugin.state_dict_type == "FULL_STATE_DICT"
        else:
            use_full = full_file.exists()
        if use_full:
            # FULL_STATE_DICT: re-place the consolidated host pytree onto the current mesh
            # with the live state's shardings (works across mesh-shape changes).
            with open(full_file, "rb") as f:
                host_state = pickle.load(f)
            restored = jax.tree_util.tree_map(
                lambda live, loaded: jax.device_put(loaded, live.sharding)
                if isinstance(live, jax.Array)
                else loaded,
                train_state,
                host_state,
            )
        else:
            import orbax.checkpoint as ocp

            with ocp.StandardCheckpointer() as ckptr:
                abstract = jax.tree_util.tree_map(_abstractify, train_state)
                restored = ckptr.restore((path / SHARDED_STATE_DIR).absolute(), abstract)

    meta_file = path / SCHEDULER_STATE_NAME
    if meta_file.exists():
        meta = json.loads(meta_file.read_text())
        accelerator.step = meta.get("step", 0)
        if load_optimizer_states:
            for opt, sd in zip(accelerator._optimizers, meta.get("optimizers", [])):
                opt.load_state_dict(sd)
        for sched, sd in zip(accelerator._schedulers, meta.get("schedulers", [])):
            if sd is not None:
                try:
                    sched.load_state_dict(sd)
                except Exception:
                    logger.warning("Could not restore a scheduler state", main_process_only=True)
        for dl, sd in zip(accelerator._dataloaders, meta.get("dataloaders", [])):
            if getattr(dl, "stateful", False) and hasattr(dl, "load_state_dict"):
                dl.load_state_dict(sd)
            elif hasattr(dl, "set_epoch"):
                dl.set_epoch(sd.get("iteration", 0))

    for i, obj in enumerate(accelerator._custom_objects):
        load_custom_state(obj, str(path), i)

    rng_file = path / f"{RNG_STATE_NAME}_{accelerator.process_index}.pkl"
    if rng_file.exists():
        with open(rng_file, "rb") as f:
            states = pickle.load(f)
        random.setstate(states["random_state"])
        np.random.set_state(states["numpy_random_seed"])
        if is_torch_available() and "torch_manual_seed" in states:
            import torch

            torch.set_rng_state(states["torch_manual_seed"])

    logger.info(f"Loaded accelerator state from {path}")
    return restored


def _abstractify(leaf):
    if isinstance(leaf, jax.Array):
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=leaf.sharding)
    return leaf


def _export_safetensors(params, file_path: Path) -> None:
    """Consolidated (unsharded) safetensors export, shared flattening convention
    (``utils/serialization.py``)."""
    if not is_safetensors_available():
        logger.warning("safetensors unavailable; skipping interchange export")
        return
    from .parallel.fsdp import gather_full_params
    from .utils.serialization import save_pytree_safetensors

    save_pytree_safetensors(gather_full_params(params), file_path)


def save_custom_state(obj, path: str, index: int = 0, save_on_each_node: bool = False) -> None:
    """Pickle ``obj.state_dict()`` (reference ``checkpointing.py:303``).

    Writes once globally (main process), or once per node (local-main process) when
    ``save_on_each_node`` — the ``utils.other.save`` gate: concurrent same-path writers
    on a multi-process host would corrupt the pickle.
    """
    from .state import PartialState

    state = PartialState()
    should_write = state.is_local_main_process if save_on_each_node else state.is_main_process
    if not should_write:
        return
    load_location = Path(path) / f"{CUSTOM_OBJECT_NAME}_{index}.pkl"
    with open(load_location, "wb") as f:
        pickle.dump(obj.state_dict(), f)


def load_custom_state(obj, path: str, index: int = 0) -> None:
    """Load into ``obj.load_state_dict`` (reference ``checkpointing.py:313``)."""
    load_location = Path(path) / f"{CUSTOM_OBJECT_NAME}_{index}.pkl"
    if load_location.exists():
        with open(load_location, "rb") as f:
            obj.load_state_dict(pickle.load(f))


# ------------------------------------------- coordinated pipeline (MPMD) checkpoints
# MPMD multi-slice training (parallel/mpmd.py) has no single writer: each stage
# is an independent process saving its OWN state, and a consistent restore must
# take every stage from the SAME epoch. The coordination contract:
#
#   checkpoint_<step>/pipeline.json        written FIRST ({"n_stages": N, "step": s})
#   checkpoint_<step>/stage_<i>/           one verified snapshot per stage
#       stage_state.pkl                    host pytree (params/opt_state/step)
#       manifest.sha256.json + COMMITTED   the PR-9 verified-checkpoint machinery
#
# The epoch is committed IFF every declared stage's marker landed and verifies;
# a stage killed mid-save leaves a partial epoch that is quarantined AS A UNIT
# (never stage-by-stage — mixing epochs across stages would restore a pipeline
# state no run ever produced).

STAGE_STATE_NAME = "stage_state.pkl"


def save_pipeline_checkpoint(base, step: int, stage_states, faults=None) -> str:
    """Write one coordinated pipeline snapshot at ``base/checkpoint_<step>``.

    ``stage_states`` is the per-stage list of HOST pytrees (numpy leaves —
    callers snapshot via ``utils.host_snapshot`` / ``StageProcess.state()``).
    ``faults`` is an optional per-stage list of :class:`FaultPlan`-likes; each
    stage draws the ``ckpt.save`` site exactly as ``save_accelerator_state``
    does — a ``crash`` spec raises after the stage's data landed but BEFORE its
    marker (the torn mid-save state), a ``corrupt`` spec flips a byte after the
    marker (caught by manifest verification at load). Returns the epoch path.
    """
    base = Path(base)
    n_stages = len(stage_states)
    path = base / f"checkpoint_{int(step)}"
    path.mkdir(parents=True, exist_ok=True)
    # Meta FIRST: from this point the directory declares how many stages a
    # complete snapshot needs, so a crash after any subset of stage saves is
    # detectable as partial (verify_checkpoint's pipeline branch).
    (path / PIPELINE_META).write_text(
        json.dumps({"n_stages": n_stages, "step": int(step)})
    )
    for i, state in enumerate(stage_states):
        plan = faults[i] if faults is not None else None
        _save_stage_snapshot(path, i, state, plan)
    return str(path)


def _save_stage_snapshot(epoch_path: Path, stage_id: int, host_state,
                         plan=None) -> None:
    """One stage's verified snapshot under ``epoch_path/stage_<i>/`` (data →
    manifest → atomic marker, the save_accelerator_state ordering)."""
    sdir = epoch_path / f"stage_{stage_id}"
    sdir.mkdir(parents=True, exist_ok=True)
    marker = sdir / COMMIT_MARKER
    if marker.exists():  # re-used dir: lose the stale committed bit first
        marker.unlink()
    with open(sdir / STAGE_STATE_NAME, "wb") as f:
        pickle.dump(host_state, f)
    spec = plan.draw("ckpt.save") if plan is not None else None
    if spec is not None and spec.kind == "crash":
        from .resilience.faults import InjectedFault

        # Injected mid-save stage death: data on disk, marker NOT — the whole
        # epoch is now partial and must never be selected by the fallback.
        raise InjectedFault("ckpt.save", "crash")
    _write_commit_marker(sdir)
    if spec is not None and spec.kind == "corrupt":
        _corrupt_one_file(sdir)


def load_pipeline_checkpoint(path, verify: bool = True):
    """Restore one coordinated snapshot → ``(step, [host_state, ...])``.

    Verifies first and raises :class:`CheckpointCorruptError` on any problem —
    an explicit epoch path is caller intent, exactly like
    ``load_accelerator_state(input_dir=...)``; the silent-fallback path is
    :func:`select_pipeline_checkpoint`. ``verify=False`` skips the hash pass
    for callers that JUST verified the path (the selection fallback hands an
    already-verified epoch straight to the load — hashing every stage twice
    back to back buys nothing)."""
    path = Path(path)
    if verify:
        problems = verify_checkpoint(path)
        if problems:
            raise CheckpointCorruptError(path, problems)
    meta = json.loads((path / PIPELINE_META).read_text())
    states = []
    for i in range(int(meta["n_stages"])):
        with open(path / f"stage_{i}" / STAGE_STATE_NAME, "rb") as f:
            states.append(pickle.load(f))
    return int(meta["step"]), states


def select_pipeline_checkpoint(base, quarantine: bool = True,
                               telemetry=None):
    """Newest epoch under ``base`` whose EVERY stage verifies, or ``None``.

    Invalid epochs — partial commits (a stage killed mid-save), corrupt files —
    are quarantined AS A UNIT under ``base/quarantined/`` (never one stage at a
    time: the surviving stages of a torn epoch are exactly as unusable as the
    missing one) and telemetered like the accelerator fallback path, then the
    search falls back to the next-newest epoch on ALL stages."""
    base = Path(base)
    for cand in reversed(_list_checkpoints(base)):
        problems = verify_checkpoint(cand)
        if not problems:
            return cand
        logger.warning(
            f"pipeline checkpoint {cand} failed verification "
            f"({'; '.join(problems)}) — "
            + ("quarantining the whole epoch and " if quarantine else "")
            + "falling back to the previous consistent snapshot"
        )
        if telemetry is not None and getattr(telemetry, "enabled", False):
            from .telemetry.schemas import FAULT_SCHEMA, RECOVERY_SCHEMA

            telemetry.emit({
                "schema": FAULT_SCHEMA, "site": "ckpt.load", "kind": "corrupt",
                "checkpoint": cand.name, "problems": list(problems),
            })
            telemetry.emit({
                "schema": RECOVERY_SCHEMA, "action": "checkpoint_fallback",
                "quarantined": cand.name,
            })
        if quarantine:
            qdir = base / QUARANTINE_DIR
            qdir.mkdir(parents=True, exist_ok=True)
            dest = qdir / cand.name
            if dest.exists():
                shutil.rmtree(dest, ignore_errors=True)
            shutil.move(str(cand), str(dest))
    return None


def rotate_pipeline_checkpoints(base, total_limit) -> None:
    """Prune old pipeline epochs to ``total_limit``, with the
    ``_rotate_checkpoints`` guarantees generalized to coordinated snapshots:
    only FULLY-committed epochs (every stage's marker landed) count toward the
    limit, and the newest fully-committed epoch is never deleted — it is the
    only state a post-crash replay can fall back to."""
    if total_limit is None:
        return
    committed = [
        p for p in _list_checkpoints(Path(base)) if _checkpoint_committed(p)
    ]
    while len(committed) > max(int(total_limit), 1):
        victim = committed.pop(0)
        logger.info(
            f"Deleting old pipeline checkpoint {victim} "
            f"(total_limit={total_limit})"
        )
        shutil.rmtree(victim, ignore_errors=True)
