"""Pipelined model inference — the ``prepare_pippy`` analog (L6).

Reference ``inference.py`` (/root/reference/src/accelerate/inference.py): ``prepare_pippy``
(:124) wraps a torch model so its forward runs as a GPipe schedule over
``torch.distributed.pipelining`` with auto split points (:164) and microbatched forward
(:99). Here the same capability is a function factory over the mesh's ``pp`` axis: stage
splitting is a reshape of the scan-stacked layer params (no tracing/split-point search —
the layer dim IS the split axis), the schedule is the differentiable collective-permute
pipeline from ``parallel/pp.py``, and the returned callable is one jitted XLA program.

Unlike the reference (inference-only), the same pipeline trains — see
``models.llama.loss_fn_pp``. This module is the inference-facing wrapper.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from .parallel.mesh import mesh_context
from .utils.constants import PIPELINE_AXIS

__all__ = ["prepare_pippy", "pipeline_forward_fn"]


def pipeline_forward_fn(
    stage_fn: Callable,
    mesh,
    num_microbatches: Optional[int] = None,
):
    """Generic pipelined forward over shape-stable stages (``make_pipeline_fn`` re-export)."""
    from .parallel.pp import make_pipeline_fn

    return make_pipeline_fn(mesh, stage_fn, num_microbatches=num_microbatches)


def prepare_pippy(
    params: dict,
    cfg,
    mesh=None,
    num_microbatches: Optional[int] = None,
    split_points: str = "auto",
):
    """Model params → (stage-sharded params, jitted pipelined logits fn).

    - ``cfg`` selects the family by type — llama, gpt, bert, or t5 (the reference's
      pippy examples cover the same four: ``examples/inference/pippy/{llama,gpt2,
      bert,t5}.py``; its ``prepare_pippy`` is likewise model-generic,
      ``inference.py:124``). Decoder families return ``forward(tokens)``; bert
      returns ``forward(input_ids, attention_mask=None, token_type_ids=None)``
      (classification logits); t5 returns ``forward(input_ids, decoder_input_ids)``
      (seq2seq LM logits).
    - ``params``: family params with per-layer list OR scan-stacked layers; they
      are stage-stacked ``[n_stages, L/n, ...]`` and placed with
      ``partition_specs(cfg, pp=True)`` (stage dim over the mesh ``pp`` axis).
    - ``split_points="auto"``: layers divide evenly over stages (the reference's
      auto-balancing, ``inference.py:164-168``, degenerates to this when blocks are uniform
      — a transformer's are).
    - Returns ``(pp_params, forward)`` with ``forward(tokens [B, S]) -> logits [B, S, V]``
      (fp32), ``B`` divisible by the microbatch count.
    """
    import dataclasses

    from jax.sharding import NamedSharding
    from .models import bert as bert_mod, gpt, llama, t5 as t5_mod
    from .parallel.pp import split_params_into_stages, stack_stage_params

    if isinstance(cfg, gpt.GPTConfig):
        family = gpt
    elif isinstance(cfg, llama.LlamaConfig):
        family = llama
    elif isinstance(cfg, bert_mod.BertConfig):
        family = bert_mod
    elif isinstance(cfg, t5_mod.T5Config):
        family = t5_mod
    else:
        raise TypeError(
            f"prepare_pippy supports llama/gpt/bert/t5 family configs, "
            f"got {type(cfg).__name__}"
        )

    if mesh is None:
        from .state import AcceleratorState

        mesh = AcceleratorState().mesh
    n_stages = mesh.shape[PIPELINE_AXIS]
    if split_points != "auto":
        raise ValueError("only split_points='auto' (even layer split) is supported")

    if family in (bert_mod, t5_mod):
        # Encoder / enc-dec families: stack_pp_params handles their layouts (bert's
        # homogeneous block list; t5's rel-bias lift + per-stack stages).
        pp_params = family.stack_pp_params(params, cfg, n_stages)
        specs = family.partition_specs(cfg, pp=True)
        pp_params = jax.tree_util.tree_map(
            lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
            pp_params, specs,
        )
        if family is bert_mod:
            def fwd(input_ids, attention_mask=None, token_type_ids=None):
                return bert_mod.forward_pp(
                    pp_params, input_ids, cfg, mesh,
                    num_microbatches=num_microbatches,
                    attention_mask=attention_mask, token_type_ids=token_type_ids,
                )
        else:
            def fwd(input_ids, decoder_input_ids):
                return t5_mod.forward_pp(
                    pp_params, input_ids, decoder_input_ids, cfg, mesh,
                    num_microbatches=num_microbatches,
                )
        jitted_fwd = jax.jit(fwd)

        def with_mesh_multi(*args, **kwargs):
            with mesh_context(mesh):
                return jitted_fwd(
                    *(jnp.asarray(a, jnp.int32) if a is not None else None for a in args),
                    **{k: (jnp.asarray(v, jnp.int32) if v is not None else None)
                       for k, v in kwargs.items()},
                )

        return pp_params, with_mesh_multi

    if not cfg.scan_layers:
        cfg = dataclasses.replace(cfg, scan_layers=True)
    layers = params["layers"]
    if isinstance(layers, (list, tuple)):
        layers = stack_stage_params(list(layers))  # [L, ...]
    pp_params = dict(params)
    pp_params["layers"] = (
        layers if _leading(layers) == n_stages and _second_dim_known(layers, cfg, n_stages)
        else split_params_into_stages(layers, n_stages)
    )
    specs = family.partition_specs(cfg, pp=True)
    pp_params = jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)), pp_params, specs
    )

    def forward(tokens: jax.Array) -> jax.Array:
        x = family.forward_pp(
            pp_params, tokens, cfg, mesh, num_microbatches=num_microbatches
        )
        # head_logits is part of the family contract (applies softcap / head bias),
        # so the pipelined logits match the family's single-device forward exactly.
        return family.head_logits(x, pp_params, cfg)

    jitted = jax.jit(forward)

    def with_mesh(tokens):
        with mesh_context(mesh):
            return jitted(jnp.asarray(tokens, jnp.int32))

    return pp_params, with_mesh


def _leading(tree) -> int:
    return jax.tree_util.tree_leaves(tree)[0].shape[0]


def _second_dim_known(tree, cfg, n_stages: int) -> bool:
    return jax.tree_util.tree_leaves(tree)[0].shape[1] == cfg.n_layers // n_stages
