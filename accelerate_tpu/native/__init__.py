"""Native (C++) components, loaded via ctypes with pure-Python fallbacks.

The reference delegates its native-performance concerns to external engines (NCCL,
DeepSpeed, bitsandbytes, ...); here the device-side equivalents are XLA/Pallas programs,
and the HOST-side hot loops that remain (data-path work like sequence packing) live in
this package as small C-ABI libraries built on demand with g++ (``ops/packing.py``).
"""
