"""Native (C++) components, loaded via ctypes with pure-Python fallbacks.

The reference delegates its native-performance concerns to external engines (NCCL,
DeepSpeed, bitsandbytes, ...); here the device-side equivalents are XLA/Pallas programs,
and the HOST-side hot loops that remain (data-path work like sequence packing and corpus
batch assembly) live in this package as small C-ABI libraries built on demand with g++
(``ops/packing.py``, ``lm_dataset.py`` via :func:`load_native`).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Callable, Optional, Sequence


def load_native(
    src: str,
    so: str,
    configure: Callable[[ctypes.CDLL], None],
    extra_flags: Sequence[str] = (),
) -> Optional[ctypes.CDLL]:
    """Build ``src`` → ``so`` (if stale) and CDLL it; None when the toolchain fails.

    Build goes to a per-process temp name then renames atomically: concurrent processes
    (multi-process launches, dataloader workers) would otherwise race g++ on the same
    output path and CDLL a half-written file. ``configure`` sets restype/argtypes.
    Callers hold their own once-lock and cache the handle / build-failed flag.
    """
    try:
        if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
            tmp = f"{so}.{os.getpid()}.tmp"
            try:
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", src, "-o", tmp, *extra_flags],
                    check=True, capture_output=True, timeout=120,
                )
                os.replace(tmp, so)
            finally:
                if os.path.exists(tmp):  # failed/partial build: don't litter the package
                    os.unlink(tmp)
        lib = ctypes.CDLL(so)
        configure(lib)
        return lib
    except Exception:
        return None
