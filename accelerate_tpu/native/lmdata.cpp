// Indexed LM dataset hot loops (C ABI; loaded via ctypes from lm_dataset.py).
//
// The Megatron-indexed-dataset analog for this framework: a pretraining corpus is one
// flat memmapped token array; a training sample is a [seq_len+1] window at a shuffled
// offset. The shuffle and the batch gather are pure host work on the dataloader thread —
// implemented natively (deterministic RNG, multithreaded gather) with a behavior-identical
// pure-Python fallback (tests assert C++ == Python).
//
// Build: g++ -O3 -shared -fPIC lmdata.cpp -o liblmdata.so -pthread   (lm_dataset.py does
// this on demand and caches the .so next to this file).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// splitmix64: tiny, seedable, platform-stable. Python fallback mirrors it exactly.
inline uint64_t splitmix64(uint64_t& state) {
  uint64_t z = (state += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

}  // namespace

extern "C" {

// Deterministic Fisher-Yates over idx[0..n) seeded by `seed` (epoch folded in by caller).
void lm_shuffle(int64_t* idx, int64_t n, uint64_t seed) {
  uint64_t state = seed;
  for (int64_t i = n - 1; i > 0; --i) {
    const uint64_t j = splitmix64(state) % static_cast<uint64_t>(i + 1);
    const int64_t tmp = idx[i];
    idx[i] = idx[j];
    idx[j] = tmp;
  }
}

// Gather `batch` windows of `width` tokens each: out[b] = tokens[starts[b] .. +width).
// Multithreaded memcpy; caller guarantees starts[b] + width <= n_tokens.
// Returns 0, or -1 on a bounds violation (nothing partially written in that case).
int64_t lm_gather(const int32_t* tokens, int64_t n_tokens, const int64_t* starts,
                  int64_t batch, int64_t width, int32_t* out) {
  for (int64_t b = 0; b < batch; ++b) {
    if (starts[b] < 0 || starts[b] + width > n_tokens) return -1;
  }
  const int64_t bytes = width * static_cast<int64_t>(sizeof(int32_t));
  // Thread only when the copy is big enough to amortize spawn/join (~10s of us): for
  // small batches or narrow windows the single-thread memcpy loop wins outright.
  constexpr int64_t kMinBytesForThreads = 1 << 20;  // 1 MiB total
  const unsigned hw = std::thread::hardware_concurrency();
  const int64_t n_threads =
      (batch >= 8 && hw > 1 && batch * bytes >= kMinBytesForThreads)
          ? std::min<int64_t>(batch, hw)
          : 1;
  if (n_threads == 1) {
    for (int64_t b = 0; b < batch; ++b) {
      std::memcpy(out + b * width, tokens + starts[b], bytes);
    }
    return 0;
  }
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  for (int64_t t = 0; t < n_threads; ++t) {
    workers.emplace_back([=]() {
      for (int64_t b = t; b < batch; b += n_threads) {
        std::memcpy(out + b * width, tokens + starts[b], bytes);
      }
    });
  }
  for (auto& w : workers) w.join();
  return 0;
}

}  // extern "C"
