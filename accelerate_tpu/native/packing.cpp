// Sequence-packing hot loop (C ABI; loaded via ctypes from ops/packing.py).
//
// TPU programs need static shapes: variable-length sequences must be packed into
// fixed-length rows ("sample packing"). The bin assignment + scatter is pure host work in
// the data path — for web-scale corpora it runs per batch on the dataloader thread, so it
// is implemented here natively with a pure-Python fallback kept behavior-identical
// (tests assert C++ == Python on random corpora).
//
// Build: g++ -O3 -shared -fPIC packing.cpp -o libpacking.so   (ops/packing.py does this
// on demand and caches the .so next to this file).

#include <cstdint>
#include <vector>

extern "C" {

// First-fit packing of n_seq sequences into rows of `capacity` tokens.
//
// tokens:   concatenated int32 token ids for all sequences
// offsets:  n_seq+1 prefix offsets into `tokens` (sequence i = [offsets[i], offsets[i+1]))
// out_*:    preallocated [max_bins * capacity] int32, zero-filled by the caller
//           (tokens: pad 0; segments: 0 = padding, first real segment = 1; positions: 0)
// Returns the number of bins used, or -1 if max_bins was insufficient or a sequence
// exceeds capacity.
long long pack_sequences_ffit(const int32_t* tokens, const int64_t* offsets, int64_t n_seq,
                              int64_t capacity, int32_t* out_tokens, int32_t* out_segments,
                              int32_t* out_positions, int64_t max_bins) {
  std::vector<int64_t> used;     // tokens consumed per bin
  std::vector<int32_t> n_segs;   // segments placed per bin
  used.reserve(256);
  n_segs.reserve(256);
  for (int64_t i = 0; i < n_seq; ++i) {
    const int64_t len = offsets[i + 1] - offsets[i];
    if (len > capacity || len < 0) return -1;
    if (len == 0) continue;
    // First-fit: the earliest bin with room. O(n_seq * n_bins) worst case; bins fill and
    // stop matching quickly for natural length distributions.
    int64_t bin = -1;
    for (int64_t b = 0; b < (int64_t)used.size(); ++b) {
      if (used[b] + len <= capacity) { bin = b; break; }
    }
    if (bin < 0) {
      if ((int64_t)used.size() >= max_bins) return -1;
      used.push_back(0);
      n_segs.push_back(0);
      bin = (int64_t)used.size() - 1;
    }
    const int64_t start = bin * capacity + used[bin];
    const int32_t seg = ++n_segs[bin];
    const int32_t* src = tokens + offsets[i];
    for (int64_t t = 0; t < len; ++t) {
      out_tokens[start + t] = src[t];
      out_segments[start + t] = seg;
      out_positions[start + t] = (int32_t)t;
    }
    used[bin] += len;
  }
  return (long long)used.size();
}

}  // extern "C"
