"""In-jit collectives — the compiled-path counterpart of ``utils/operations.py``.

These are thin, named wrappers over XLA collective HLOs (``psum``/``all_gather``/``ppermute``/
``all_to_all``), the TPU-native replacement for the reference's NCCL calls (SURVEY.md §2.7).
They are meaningful only inside ``shard_map``/``pmap``-style traced code where mesh axis names
are bound. Defaults target the batch axes ``("dp", "fsdp")`` so a plain ``grad_psum`` matches
DDP's gradient all-reduce (reference ``optimizer.py:148-154`` / torch DDP reducer).

**Inter-stage (DCN) transfers** — :func:`stage_transfer` — are the one
HOST-level op here: MPMD multi-slice training (``parallel/mpmd.py``) runs each
pipeline stage as an independent program on its own mesh, so activations and
cotangents cross stage boundaries outside any jit, over the data-center
network rather than ICI (arxiv 2204.06514's multi-slice DCN regime). The op
is first-class on purpose: every transfer is byte- and latency-accounted
(:class:`TransferStats`, ``mpmd.transfer/v1`` telemetry records), and
graftaudit's collective inventory audits the per-program transfer payload the
same way it audits in-jit collective bytes.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.constants import BATCH_AXES
from ..utils.jax_compat import axis_size as _axis_size

__all__ = [
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "all_gather",
    "reduce_scatter",
    "ppermute",
    "all_to_all",
    "axis_index",
    "axis_size",
    "grad_psum",
    "grad_pmean",
    "TransferStats",
    "tree_bytes",
    "stage_transfer",
    "kv_page_transfer",
]

AxisNames = Any  # str | tuple[str, ...]


def _axes(axis_name: Optional[AxisNames]) -> AxisNames:
    return BATCH_AXES if axis_name is None else axis_name


def psum(x, axis_name: Optional[AxisNames] = None):
    return jax.tree_util.tree_map(lambda t: lax.psum(t, _axes(axis_name)), x)


def maybe_shard(x, spec, require_axis: Optional[str] = None):
    """Apply a sharding constraint only when a mesh context is active (``jax.set_mesh``) —
    and, if ``require_axis`` is given, only when that axis exists in the mesh. Lets the same
    model code run in plain single-device baselines."""
    from ..utils.jax_compat import current_abstract_mesh

    mesh = current_abstract_mesh()
    if mesh is None or mesh.empty:
        return x
    if require_axis is not None and require_axis not in mesh.shape:
        return x
    return jax.lax.with_sharding_constraint(x, spec)


def pmean(x, axis_name: Optional[AxisNames] = None):
    return jax.tree_util.tree_map(lambda t: lax.pmean(t, _axes(axis_name)), x)


def pmax(x, axis_name: Optional[AxisNames] = None):
    return jax.tree_util.tree_map(lambda t: lax.pmax(t, _axes(axis_name)), x)


def pmin(x, axis_name: Optional[AxisNames] = None):
    return jax.tree_util.tree_map(lambda t: lax.pmin(t, _axes(axis_name)), x)


def all_gather(x, axis_name: Optional[AxisNames] = None, axis: int = 0, tiled: bool = True):
    return jax.tree_util.tree_map(
        lambda t: lax.all_gather(t, _axes(axis_name), axis=axis, tiled=tiled), x
    )


def reduce_scatter(x, axis_name: Optional[AxisNames] = None, scatter_dimension: int = 0):
    return jax.tree_util.tree_map(
        lambda t: lax.psum_scatter(t, _axes(axis_name), scatter_dimension=scatter_dimension, tiled=True),
        x,
    )


def ppermute(x, perm: Sequence[tuple[int, int]], axis_name: Optional[AxisNames] = None):
    return jax.tree_util.tree_map(lambda t: lax.ppermute(t, _axes(axis_name), perm), x)


def all_to_all(x, axis_name: Optional[AxisNames] = None, split_axis: int = 0, concat_axis: int = 0):
    return jax.tree_util.tree_map(
        lambda t: lax.all_to_all(t, _axes(axis_name), split_axis, concat_axis, tiled=True), x
    )


def axis_index(axis_name: Optional[AxisNames] = None):
    return lax.axis_index(_axes(axis_name))


def axis_size(axis_name: Optional[AxisNames] = None):
    return _axis_size(_axes(axis_name))


def grad_psum(grads, axis_name: Optional[AxisNames] = None, reduce_dtype=None):
    """Gradient all-reduce with optional compressed-dtype reduction.

    Casting to ``reduce_dtype`` (e.g. bf16) before the psum is the TPU analog of the
    reference's DDP fp16/bf16 compression comm hooks (``dataclasses.py:128-222``): it halves
    ICI bytes and upcasts back afterwards.
    """

    def _reduce(g):
        orig = g.dtype
        if reduce_dtype is not None and g.dtype != reduce_dtype:
            g = g.astype(reduce_dtype)
        g = lax.psum(g, _axes(axis_name))
        return g.astype(orig)

    return jax.tree_util.tree_map(_reduce, grads)


def grad_pmean(grads, axis_name: Optional[AxisNames] = None, reduce_dtype=None):
    def _reduce(g):
        orig = g.dtype
        if reduce_dtype is not None and g.dtype != reduce_dtype:
            g = g.astype(reduce_dtype)
        g = lax.pmean(g, _axes(axis_name))
        return g.astype(orig)

    return jax.tree_util.tree_map(_reduce, grads)


# --------------------------------------------------------- inter-stage (DCN) transfers
@dataclasses.dataclass
class TransferStats:
    """Running byte/latency accounting for one transfer edge (or one stage's
    whole transfer history — the caller picks the granularity). ``record`` is
    what :func:`stage_transfer` calls; ``summary()`` is the stats()-shaped
    dict bench rows stamp."""

    count: int = 0
    bytes: int = 0
    seconds: float = 0.0

    def record(self, nbytes: int, dur_s: float) -> None:
        self.count += 1
        self.bytes += int(nbytes)
        self.seconds += float(dur_s)

    def summary(self) -> dict:
        return {
            "transfers": self.count,
            "transfer_bytes": self.bytes,
            "transfer_s": round(self.seconds, 6),
        }


def tree_bytes(tree) -> int:
    """Total payload bytes of a pytree of arrays (the DCN wire cost of
    transferring it, compression aside)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None:
            import numpy as np

            nbytes = np.asarray(leaf).nbytes
        total += int(nbytes)
    return total


def stage_transfer(
    x,
    *,
    src_stage: int,
    dst_stage: int,
    direction: str = "fwd",
    sharding=None,
    step: Optional[int] = None,
    microbatch: Optional[int] = None,
    stats: Optional[TransferStats] = None,
    telemetry=None,
):
    """Ship one inter-stage payload (activation or cotangent) across the MPMD
    stage boundary — the DCN-shaped transfer between two independent stage
    programs (``parallel/mpmd.py``).

    This is deliberately a HOST-level first-class op, not an in-jit collective:
    the two stages are separate programs on separate meshes (separate slices on
    real hardware), so the payload leaves one program, crosses DCN, and enters
    the other — ``jax.device_put`` onto ``sharding`` (the destination stage's
    placement; ``None`` keeps the default device, the single-host simulation).
    The copy is synchronously waited on so the recorded latency is the
    transfer, not dispatch overhead.

    ``direction`` is ``"fwd"`` (activation, stage i → i+1) or ``"bwd"``
    (cotangent, stage i+1 → i). Every call records into ``stats`` (a
    :class:`TransferStats`) and — when ``telemetry`` is enabled — emits one
    ``accelerate_tpu.telemetry.mpmd.transfer/v1`` record, so chaos-train and
    trace tooling can account every byte that crossed a stage boundary.
    """
    if direction not in ("fwd", "bwd"):
        raise ValueError(f"direction={direction!r} must be 'fwd' or 'bwd'")
    nbytes = tree_bytes(x)
    t0 = time.perf_counter()
    out = jax.device_put(x) if sharding is None else jax.device_put(x, sharding)
    jax.block_until_ready(out)
    dur = time.perf_counter() - t0
    if stats is not None:
        stats.record(nbytes, dur)
    if telemetry is not None and getattr(telemetry, "enabled", False):
        from ..telemetry.schemas import MPMD_TRANSFER_SCHEMA

        telemetry.emit({
            "schema": MPMD_TRANSFER_SCHEMA,
            "src_stage": int(src_stage),
            "dst_stage": int(dst_stage),
            "direction": direction,
            "nbytes": nbytes,
            "dur_s": round(dur, 6),
            "step": step,
            "microbatch": microbatch,
        })
    return out


def kv_page_transfer(
    block,
    *,
    src_replica: int,
    dst_replica: int,
    uid=None,
    pages: Optional[int] = None,
    sharding=None,
    stats: Optional[TransferStats] = None,
    telemetry=None,
):
    """Ship one KV page block across the prefill→decode replica boundary —
    the serving counterpart of :func:`stage_transfer` (docs/
    disaggregated_serving.md). ``block`` is the page pytree a prefill-role
    engine gathered (``ContinuousBatcher.export_page_block``); the copy is
    ``jax.device_put`` onto ``sharding`` (the decode replica's placement;
    ``None`` keeps the default device — the same-process v1), synchronously
    waited so the recorded latency is the transfer itself. The DCN-shaped path
    between real slices is the SAME call with a cross-mesh sharding.

    Every call records into ``stats`` and — when ``telemetry`` is enabled —
    emits one ``accelerate_tpu.telemetry.serving.handoff/v1`` record (src/dst
    replica, request uid, page count, bytes, latency), so trace tooling and
    serve-bench account every byte a handoff moved. Returns
    ``(block, nbytes, dur_s)``.

    Note the block is table-width (one compiled gather/scatter per geometry,
    whatever the handoff size): ``nbytes`` is the honest WIRE cost including
    that padding; ``pages`` says how many entries carry real context.
    """
    nbytes = tree_bytes(block)
    t0 = time.perf_counter()
    out = jax.device_put(block) if sharding is None else jax.device_put(block, sharding)
    jax.block_until_ready(out)
    dur = time.perf_counter() - t0
    if stats is not None:
        stats.record(nbytes, dur)
    if telemetry is not None and getattr(telemetry, "enabled", False):
        from ..telemetry.schemas import SERVING_HANDOFF_SCHEMA

        telemetry.emit({
            "schema": SERVING_HANDOFF_SCHEMA,
            "src_replica": int(src_replica),
            "dst_replica": int(dst_replica),
            "uid": uid,
            "pages": pages,
            "nbytes": nbytes,
            "dur_s": round(dur, 6),
        })
    return out, nbytes, dur
