"""In-jit collectives — the compiled-path counterpart of ``utils/operations.py``.

These are thin, named wrappers over XLA collective HLOs (``psum``/``all_gather``/``ppermute``/
``all_to_all``), the TPU-native replacement for the reference's NCCL calls (SURVEY.md §2.7).
They are meaningful only inside ``shard_map``/``pmap``-style traced code where mesh axis names
are bound. Defaults target the batch axes ``("dp", "fsdp")`` so a plain ``grad_psum`` matches
DDP's gradient all-reduce (reference ``optimizer.py:148-154`` / torch DDP reducer).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.constants import BATCH_AXES
from ..utils.jax_compat import axis_size as _axis_size

__all__ = [
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "all_gather",
    "reduce_scatter",
    "ppermute",
    "all_to_all",
    "axis_index",
    "axis_size",
    "grad_psum",
    "grad_pmean",
]

AxisNames = Any  # str | tuple[str, ...]


def _axes(axis_name: Optional[AxisNames]) -> AxisNames:
    return BATCH_AXES if axis_name is None else axis_name


def psum(x, axis_name: Optional[AxisNames] = None):
    return jax.tree_util.tree_map(lambda t: lax.psum(t, _axes(axis_name)), x)


def maybe_shard(x, spec, require_axis: Optional[str] = None):
    """Apply a sharding constraint only when a mesh context is active (``jax.set_mesh``) —
    and, if ``require_axis`` is given, only when that axis exists in the mesh. Lets the same
    model code run in plain single-device baselines."""
    from ..utils.jax_compat import current_abstract_mesh

    mesh = current_abstract_mesh()
    if mesh is None or mesh.empty:
        return x
    if require_axis is not None and require_axis not in mesh.shape:
        return x
    return jax.lax.with_sharding_constraint(x, spec)


def pmean(x, axis_name: Optional[AxisNames] = None):
    return jax.tree_util.tree_map(lambda t: lax.pmean(t, _axes(axis_name)), x)


def pmax(x, axis_name: Optional[AxisNames] = None):
    return jax.tree_util.tree_map(lambda t: lax.pmax(t, _axes(axis_name)), x)


def pmin(x, axis_name: Optional[AxisNames] = None):
    return jax.tree_util.tree_map(lambda t: lax.pmin(t, _axes(axis_name)), x)


def all_gather(x, axis_name: Optional[AxisNames] = None, axis: int = 0, tiled: bool = True):
    return jax.tree_util.tree_map(
        lambda t: lax.all_gather(t, _axes(axis_name), axis=axis, tiled=tiled), x
    )


def reduce_scatter(x, axis_name: Optional[AxisNames] = None, scatter_dimension: int = 0):
    return jax.tree_util.tree_map(
        lambda t: lax.psum_scatter(t, _axes(axis_name), scatter_dimension=scatter_dimension, tiled=True),
        x,
    )


def ppermute(x, perm: Sequence[tuple[int, int]], axis_name: Optional[AxisNames] = None):
    return jax.tree_util.tree_map(lambda t: lax.ppermute(t, _axes(axis_name), perm), x)


def all_to_all(x, axis_name: Optional[AxisNames] = None, split_axis: int = 0, concat_axis: int = 0):
    return jax.tree_util.tree_map(
        lambda t: lax.all_to_all(t, _axes(axis_name), split_axis, concat_axis, tiled=True), x
    )


def axis_index(axis_name: Optional[AxisNames] = None):
    return lax.axis_index(_axes(axis_name))


def axis_size(axis_name: Optional[AxisNames] = None):
    return _axis_size(_axes(axis_name))


def grad_psum(grads, axis_name: Optional[AxisNames] = None, reduce_dtype=None):
    """Gradient all-reduce with optional compressed-dtype reduction.

    Casting to ``reduce_dtype`` (e.g. bf16) before the psum is the TPU analog of the
    reference's DDP fp16/bf16 compression comm hooks (``dataclasses.py:128-222``): it halves
    ICI bytes and upcasts back afterwards.
    """

    def _reduce(g):
        orig = g.dtype
        if reduce_dtype is not None and g.dtype != reduce_dtype:
            g = g.astype(reduce_dtype)
        g = lax.psum(g, _axes(axis_name))
        return g.astype(orig)

    return jax.tree_util.tree_map(_reduce, grads)


def grad_pmean(grads, axis_name: Optional[AxisNames] = None, reduce_dtype=None):
    def _reduce(g):
        orig = g.dtype
        if reduce_dtype is not None and g.dtype != reduce_dtype:
            g = g.astype(reduce_dtype)
        g = lax.pmean(g, _axes(axis_name))
        return g.astype(orig)

    return jax.tree_util.tree_map(_reduce, grads)
