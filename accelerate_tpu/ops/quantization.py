"""Weight-only int8 / int4(+nf4) quantization — the TPU-native bitsandbytes replacement.

Reference delegation points this file replaces (``utils/bnb.py``: ``load_and_quantize_model``
:44, layer swap :277-374; config ``dataclasses.py:2450``; guard rails ``accelerator.py:
1479-1516``): bnb swaps ``nn.Linear`` for CUDA ``Linear8bitLt``/``Linear4bit`` modules. Here a
weight is a pytree leaf, so quantization is a *leaf transform*: ``quantize_weight`` produces a
:class:`QuantizedWeight` (itself a pytree node carrying packed codes + per-block scales) and
matmuls go through :func:`quant_matmul`, whose Pallas kernel dequantizes **inside the tile
loop** — HBM reads stay int8/int4, dequant happens in VMEM right before the MXU, which is the
entire memory-bandwidth win of weight-only quantization on TPU.

Schemes (bnb parity):
- ``int8``: per-output-channel absmax (bnb's vectorwise Linear8bitLt analog).
- ``int4``: blockwise absmax linear codes, two nibbles packed per uint8 (bnb FP4 analog).
- ``nf4``: blockwise absmax with the NormalFloat-4 codebook (QLoRA's data type; same 16-entry
  table as bnb's nf4).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "BnbQuantizationConfig",
    "QuantizedWeight",
    "quantize_weight",
    "dequantize_weight",
    "quant_matmul",
    "load_and_quantize_model",
    "dequantize_model",
    "NF4_CODEBOOK",
]

# NormalFloat-4: quantiles of N(0,1) normalized to [-1, 1] (QLoRA paper, bnb's nf4 table).
NF4_CODEBOOK = jnp.asarray(
    [
        -1.0, -0.6961928009986877, -0.5250730514526367, -0.39491748809814453,
        -0.28444138169288635, -0.18477343022823334, -0.09105003625154495, 0.0,
        0.07958029955625534, 0.16093020141124725, 0.24611230194568634, 0.33791524171829224,
        0.44070982933044434, 0.5626170039176941, 0.7229568362236023, 1.0,
    ],
    dtype=jnp.float32,
)


@dataclasses.dataclass
class BnbQuantizationConfig:
    """Quantization knobs (reference ``dataclasses.py:2450`` BnbQuantizationConfig)."""

    load_in_8bit: bool = False
    load_in_4bit: bool = False
    bnb_4bit_quant_type: str = "int4"  # int4 | nf4
    block_size: int = 64               # int4/nf4 scaling-block length
    torch_dtype: Any = jnp.bfloat16  # graftlint: disable=dead-knob(HF BnB config parity; dequant compute dtype follows the param tree)
    skip_modules: Optional[list[str]] = None
    keep_in_fp32_modules: Optional[list[str]] = None
    min_weight_size: int = 4096        # leaves smaller than this stay unquantized

    def __post_init__(self):
        if self.load_in_8bit and self.load_in_4bit:
            raise ValueError("load_in_8bit and load_in_4bit can't be both True")
        if not (self.load_in_8bit or self.load_in_4bit):
            raise ValueError("load_in_8bit and load_in_4bit can't be both False")
        if self.bnb_4bit_quant_type not in ("int4", "nf4"):
            raise ValueError(f"unsupported 4-bit quant type {self.bnb_4bit_quant_type!r}")

    @property
    def scheme(self) -> str:
        return "int8" if self.load_in_8bit else self.bnb_4bit_quant_type


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QuantizedWeight:
    """Packed codes + scales; a pytree node, so it checkpoints/shards like any leaf pair.

    int8: ``data`` int8 [in, out], ``scales`` fp32 [out] (per-output-channel absmax).
    int4/nf4: ``data`` uint8 [in*out/2] (two nibbles per byte, row-major), ``scales`` fp32
    [n_blocks] (per-block absmax); ``shape``/``scheme``/``block_size`` are static metadata.
    """

    data: jax.Array
    scales: jax.Array
    shape: tuple = dataclasses.field(metadata={"static": True})
    scheme: str = dataclasses.field(metadata={"static": True})
    block_size: int = dataclasses.field(metadata={"static": True})

    @property
    def dtype(self):  # quacks like an array for size accounting
        return self.data.dtype

    @property
    def nbytes(self) -> int:
        return int(self.data.size * self.data.dtype.itemsize + self.scales.size * 4)


def quantize_weight(w: jax.Array, scheme: str = "int8", block_size: int = 64) -> QuantizedWeight:
    """Quantize one 2-D weight. ``scheme``: int8 | int4 | nf4."""
    w = jnp.asarray(w)
    if w.ndim != 2:
        raise ValueError(f"weight-only quantization expects 2-D weights, got {w.shape}")
    shape = tuple(w.shape)
    wf = w.astype(jnp.float32)
    if scheme == "int8":
        absmax = jnp.max(jnp.abs(wf), axis=0)  # per output channel
        scales = jnp.maximum(absmax, 1e-8) / 127.0
        q = jnp.clip(jnp.round(wf / scales), -127, 127).astype(jnp.int8)
        return QuantizedWeight(q, scales, shape, "int8", block_size)

    flat = wf.reshape(-1)
    pad = (-flat.size) % block_size
    flat = jnp.pad(flat, (0, pad))
    blocks = flat.reshape(-1, block_size)
    absmax = jnp.maximum(jnp.max(jnp.abs(blocks), axis=1, keepdims=True), 1e-8)
    normed = blocks / absmax  # [-1, 1]
    if scheme == "int4":
        codes = jnp.clip(jnp.round(normed * 7.0) + 8, 0, 15).astype(jnp.uint8)
    elif scheme == "nf4":
        codes = jnp.argmin(jnp.abs(normed[..., None] - NF4_CODEBOOK), axis=-1).astype(jnp.uint8)
    else:
        raise ValueError(f"unknown scheme {scheme!r}")
    flat_codes = codes.reshape(-1)
    packed = (flat_codes[0::2] | (flat_codes[1::2] << 4)).astype(jnp.uint8)
    return QuantizedWeight(packed, absmax[:, 0], shape, scheme, block_size)


def _unpack_codes(qw: QuantizedWeight) -> jax.Array:
    lo = (qw.data & 0x0F).astype(jnp.uint8)
    hi = (qw.data >> 4).astype(jnp.uint8)
    return jnp.stack([lo, hi], axis=1).reshape(-1)


def dequantize_weight(qw: QuantizedWeight, dtype=jnp.float32) -> jax.Array:
    if qw.scheme == "int8":
        return (qw.data.astype(jnp.float32) * qw.scales).astype(dtype).reshape(qw.shape)
    codes = _unpack_codes(qw)
    if qw.scheme == "int4":
        values = (codes.astype(jnp.float32) - 8.0) / 7.0
    else:  # nf4
        values = NF4_CODEBOOK[codes]
    blocks = values.reshape(-1, qw.block_size) * qw.scales[:, None]
    n = int(np.prod(qw.shape))
    return blocks.reshape(-1)[:n].reshape(qw.shape).astype(dtype)


# -------------------------------------------------------------------------- pallas matmul
def _int8_matmul_kernel(x_ref, w_ref, s_ref, o_ref):
    """Tile matmul dequantizing int8 w in VMEM: HBM traffic stays int8."""
    from jax.experimental import pallas as pl

    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    o_ref[...] += jax.lax.dot(x, w, preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _scale():
        o_ref[...] *= s_ref[...].astype(jnp.float32)


def _quant_matmul_pallas_int8(x, qw: QuantizedWeight, block_m=128, block_k=128, block_n=128):
    from jax.experimental import pallas as pl

    K, N = qw.shape
    B = int(np.prod(x.shape[:-1]))
    x2 = x.reshape(B, K).astype(jnp.float32)
    interpret = jax.default_backend() not in ("tpu", "axon")

    bm, bk, bn = min(block_m, B), min(block_k, K), min(block_n, N)
    pad_m, pad_k, pad_n = (-B) % bm, (-K) % bk, (-N) % bn
    xp = jnp.pad(x2, ((0, pad_m), (0, pad_k)))
    wp = jnp.pad(qw.data, ((0, pad_k), (0, pad_n)))
    sp = jnp.pad(qw.scales, (0, pad_n))

    grid = (xp.shape[0] // bm, wp.shape[1] // bn, xp.shape[1] // bk)
    out = pl.pallas_call(
        _int8_matmul_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((xp.shape[0], wp.shape[1]), jnp.float32),
        interpret=interpret,
    )(xp, wp, sp[None, :])
    return out[:B, :N].reshape(*x.shape[:-1], N)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _int8_matmul_diffable(x, data, scales, shape_0: int, shape_1: int):
    qw = QuantizedWeight(data, scales, (shape_0, shape_1), "int8", 0)
    return _quant_matmul_pallas_int8(x, qw)


def _int8_mm_fwd(x, data, scales, shape_0, shape_1):
    qw = QuantizedWeight(data, scales, (shape_0, shape_1), "int8", 0)
    return _quant_matmul_pallas_int8(x, qw), (x, data, scales)


def _int8_mm_bwd(shape_0, shape_1, residuals, g):
    x, data, scales = residuals
    w = (data.astype(jnp.float32) * scales).astype(x.dtype)  # dequant for the backward
    dx = jnp.einsum("...n,kn->...k", g.astype(x.dtype), w)
    # Quantized weights are frozen (weight-only inference/fine-tune); int data gets a
    # symbolic-zero cotangent, scales a real zero.
    d_data = np.zeros(data.shape, jax.dtypes.float0)
    d_scales = jnp.zeros_like(scales)
    return dx, d_data, d_scales


_int8_matmul_diffable.defvjp(_int8_mm_fwd, _int8_mm_bwd)


def quant_matmul(x: jax.Array, qw: QuantizedWeight, out_dtype=None, use_pallas: bool = True):
    """``x @ dequant(qw)`` with the dequant fused into the kernel (int8 Pallas path).

    Differentiable w.r.t. ``x`` (custom VJP over the kernel — the quantized weight is frozen,
    which is the weight-only fine-tuning contract). int4/nf4 fall back to XLA dequant-then-dot
    — XLA fuses the unpack+scale into the matmul prologue, so codes still stream from HBM
    packed.
    """
    out_dtype = out_dtype or x.dtype
    if qw.scheme == "int8" and use_pallas and x.ndim >= 2:
        y = _int8_matmul_diffable(x, qw.data, qw.scales, qw.shape[0], qw.shape[1])
        return y.astype(out_dtype)
    w = dequantize_weight(qw, dtype=x.dtype)
    return (x @ w).astype(out_dtype)


# ------------------------------------------------------------------------ model transform
def load_and_quantize_model(
    params: Any,
    quantization_config: BnbQuantizationConfig,
) -> Any:
    """Quantize every eligible 2-D weight leaf of a params pytree.

    Reference analog: ``load_and_quantize_model`` (``bnb.py:44``) + ``replace_with_bnb_layers``
    (:277) — module swap becomes a leaf transform. Eligibility mirrors bnb's rules: 2-D, at
    least ``min_weight_size`` elements, key path not in ``skip_modules`` /
    ``keep_in_fp32_modules``.
    """
    from ..utils.modeling import named_parameters
    from ..utils.serialization import unflatten_to_nested_dict

    cfg = quantization_config
    skip = set(cfg.skip_modules or []) | set(cfg.keep_in_fp32_modules or [])
    flat = named_parameters(params)
    out = {}
    for name, leaf in flat.items():
        eligible = (
            hasattr(leaf, "ndim")
            and leaf.ndim == 2
            and leaf.size >= cfg.min_weight_size
            and not any(name == s or name.startswith(s + "/") or name.endswith("/" + s) for s in skip)
        )
        out[name] = quantize_weight(leaf, cfg.scheme, cfg.block_size) if eligible else leaf
    nested = unflatten_to_nested_dict(out)
    from ..big_modeling import _listify_int_dicts

    return _listify_int_dicts(nested)


def dequantize_model(params: Any, dtype=jnp.float32) -> Any:
    """Inverse transform: QuantizedWeight leaves → dense arrays."""
    return jax.tree_util.tree_map(
        lambda leaf: dequantize_weight(leaf, dtype) if isinstance(leaf, QuantizedWeight) else leaf,
        params,
        is_leaf=lambda leaf: isinstance(leaf, QuantizedWeight),
    )
