"""Paged-attention decode kernel for TPU in Pallas (+ a pure-jnp gather reference).

Dense continuous-batching decode reads a ``[B, max_len, K, hd]`` cache row per lane even
when the lane holds a 40-token chat turn. With the paged KV layout
(``models.common.paged_kv_planes`` / ``paged_kv.BlockManager``) K/V lives in a shared pool
``[num_pages, page_size, K, hd]`` and each lane maps logical pages to physical pages
through an int32 **block table** — this module is the attention read through that
indirection.

``paged_attention`` is the Pallas kernel: grid ``(batch, kv_head, logical_page)``, the
block table rides as a **scalar-prefetch** operand so each grid step's BlockSpec index map
resolves ``table[b, i]`` to the physical pool page whose ``[page_size, hd]`` tile the
pipeline DMAs next (double-buffered by the pipeline machinery itself — the classic
manual-DMA formulation buys batched page fetches on top, at ~4× the kernel complexity;
this formulation keeps the whole indirection in the index map). Online-softmax state
(running max / sum, lane-replicated like ``flash_attention``) accumulates in VMEM scratch
across the sequential page dimension. Queries are the decode shapes: ``T == 1`` (the
engine's one-token step) or ``T == spec_k+1`` (the batched speculative verify) — all
``T×G`` query rows of a lane ride one tile, with per-row causal masking against the
lane's scalar-prefetched start position. int8 pools (``kv_quant``) dequantize in-kernel
from per-slot scale pages, so the fp32 cache never exists in HBM *or* VMEM.

``paged_attention_reference`` is the same contract in pure jnp (gather through the table,
mask, softmax) — the kernel's test oracle and the CPU fallback for direct users. The
serving engine's own CPU fallback instead gathers into the family's ``_attention_cached``
(``models.common.paged_attention_dispatch``) so paged decode stays BITWISE the dense
engine on the tier-1 host; this reference exists so ops-level kernel tests need no model.

Sentinel table entries (== num_pages, unallocated logical pages) are clamped into range
for the fetch and masked out of the softmax by the valid/causal mask — the kernel never
reads through an uninitialized indirection. Runs in interpreter mode on CPU (tests) and
compiled on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.jax_compat import tpu_compiler_params as _tpu_compiler_params
from ._common import interpret_default as _interpret_default

__all__ = ["paged_attention", "paged_attention_reference", "gather_pages"]

_NEG_INF = -1e30
_LANES = 128  # native VPU lane count: softmax state is replicated across lanes


def _lane_tile(x, cols):
    """Broadcast lane-replicated state [rows, _LANES] across [rows, cols] (tile+slice,
    never a 1-lane relayout) — same trick as ``flash_attention``."""
    if cols == _LANES:
        return x
    reps = -(-cols // _LANES)
    return jnp.tile(x, (1, reps))[:, :cols]


def gather_pages(pool: dict, name: str, tables: jax.Array, length: int, dtype):
    """Dense ``[B, length, K, hd]`` view of pool plane ``name`` through block tables
    ``[B, MP]`` — sentinel entries clamp to a real page (callers mask those slots).
    int8 planes dequantize against their scale pages (the convert+scale fuses into the
    consuming einsum, so the fp32 copy never lands in HBM)."""
    P, ps = pool[name].shape[0], pool[name].shape[1]
    ids = jnp.minimum(tables, P - 1)
    pages = jnp.take(pool[name], ids, axis=0)                  # [B, MP, ps, K, hd]
    B, MP = ids.shape
    x = pages.reshape(B, MP * ps, *pages.shape[3:])[:, :length]
    if f"{name}_scale" in pool:
        scales = jnp.take(pool[f"{name}_scale"], ids, axis=0)
        scales = scales.reshape(B, MP * ps, *scales.shape[3:])[:, :length]
        return x.astype(dtype) * scales.astype(dtype)
    return x.astype(dtype)


def paged_attention_reference(q, pool, tables, positions, valid, *, page_size,
                              sm_scale, window: int = 0, softcap: float = 0.0):
    """Pure-jnp oracle: q [B,T,H,hd] against the paged pool via gather — identical
    math to the dense cached-attention path (GQA contraction against the unrepeated
    cache, fp32 softmax). ``positions`` [B] is each lane's first query position;
    ``valid`` [B,C] marks live, non-pad cache slots."""
    B, T, H, hd = q.shape
    C = valid.shape[1]
    ck = gather_pages(pool, "k", tables, C, q.dtype)
    cv = gather_pages(pool, "v", tables, C, q.dtype)
    K = ck.shape[2]
    G = H // K
    qg = q.reshape(B, T, K, G, hd)
    scores = jnp.einsum("btkgd,bckd->bkgtc", qg, ck) * sm_scale
    if softcap:
        scores = softcap * jnp.tanh(scores / softcap)
    q_pos = positions[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]   # [B,T]
    slots = jnp.arange(C)[None, None, :]
    causal = slots <= q_pos[:, :, None]                                    # [B,T,C]
    if window:
        causal = causal & (slots > q_pos[:, :, None] - window)
    mask = (causal & valid[:, None, :])[:, None, None, :, :]               # [B,1,1,T,C]
    scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bkgtc,bckd->btkgd", probs, cv).reshape(B, T, H, hd)


def _kernel(tab_ref, pos_ref, *refs, page_size, max_pages, T, G, num_pages,
            sm_scale, window, softcap, quantized):
    if quantized:
        (q_ref, k_ref, ks_ref, v_ref, vs_ref, valid_ref,
         o_ref, acc_ref, m_ref, l_ref) = refs
    else:
        ks_ref = vs_ref = None
        q_ref, k_ref, v_ref, valid_ref, o_ref, acc_ref, m_ref, l_ref = refs
    b = pl.program_id(0)
    i = pl.program_id(2)
    R = T * G
    hd = q_ref.shape[-1]

    @pl.when(i == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0, :, 0].reshape(R, hd)                      # [T*G, hd]
    k = k_ref[0, :, 0]                                     # [ps, hd]
    v = v_ref[0, :, 0]
    if quantized:
        k = k.astype(jnp.float32) * ks_ref[0, :, 0]
        v = v.astype(jnp.float32) * vs_ref[0, :, 0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale                                           # [R, ps] fp32
    if softcap:
        s = softcap * jnp.tanh(s / softcap)

    # Mask: key slot j (global position i*ps + j) is visible to query row r
    # (query index t = r // G) iff j <= pos[b] + t, inside the window, and marked
    # valid — sentinel-table garbage pages land here too and mask out entirely.
    # This bound is also the speculative rewind contract: rejected drafts leave
    # stale K/V at slots above pos[b] (once per round under the fused super-step,
    # which rewinds and rewrites in-scan), and those slots are exactly the ones
    # this mask makes unreachable until a later round's writes replace them.
    key_pos = i * page_size + jax.lax.broadcasted_iota(jnp.int32, (R, page_size), 1)
    q_pos = pos_ref[b] + jax.lax.broadcasted_iota(jnp.int32, (R, page_size), 0) // G
    mask = (key_pos <= q_pos) & (valid_ref[...] > 0)
    if window:
        mask = mask & (key_pos > q_pos - window)
    s = jnp.where(mask, s, _NEG_INF)

    m_prev = m_ref[:]                                      # [R, LANES] replicated
    m_curr = jnp.max(s, axis=1)[:, None]
    m_next = jnp.maximum(m_prev, m_curr)
    p = jnp.exp(s - _lane_tile(m_next, page_size))
    # Fully-masked rows have every s == _NEG_INF == m_next, making exp() == 1; the
    # row sum must still be 0 so finalize emits zeros for never-written lanes.
    p = jnp.where(mask, p, 0.0)
    alpha = jnp.exp(m_prev - m_next)
    l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1)[:, None]
    acc_ref[:] = acc_ref[:] * _lane_tile(alpha, hd) + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[:] = m_next

    @pl.when(i == max_pages - 1)
    def _finalize():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, :, 0] = (
            acc_ref[:] / _lane_tile(l_safe, hd)
        ).reshape(T, G, hd).astype(o_ref.dtype)


def paged_attention(q, pool, tables, positions, valid, *, page_size, sm_scale,
                    window: int = 0, softcap: float = 0.0, interpret=None):
    """Paged-attention decode: q [B,T,H,hd] against pool pages through block tables.

    - ``pool``: ``{"k","v": [P, page_size, K, hd]}`` (+ ``k_scale``/``v_scale``
      [P, page_size, K, 1] fp32 when int8-quantized).
    - ``tables`` [B, MP] int32 physical page per logical page (sentinel == P for
      unallocated entries — clamped for the fetch, masked from the softmax).
    - ``positions`` [B] int32: the lane's first query position (query t sits at
      ``positions[b] + t``); ``valid`` [B, C] bool marks live cache slots.

    Returns [B, T, H, hd] in q's dtype. T is 1 for plain decode, spec_k+1 for the
    speculative verify; every (lane, kv-head) processes its pages sequentially with
    online-softmax scratch, so output matches the dense one-shot softmax to fp32
    accumulation order."""
    B, T, H, hd = q.shape
    P, ps, K = pool["k"].shape[0], pool["k"].shape[1], pool["k"].shape[2]
    if ps != page_size:
        raise ValueError(f"pool page_size {ps} != page_size argument {page_size}")
    if H % K:
        raise ValueError(f"H={H} must be a multiple of KV heads K={K}")
    G = H // K
    MP = tables.shape[1]
    C = valid.shape[1]
    quantized = "k_scale" in pool
    if interpret is None:
        interpret = _interpret_default()

    # Valid mask padded to the table-covered extent (logical slots past max_len can
    # never be written; they mask out like any other dead slot).
    valid_i32 = jnp.pad(valid.astype(jnp.int32), ((0, 0), (0, MP * ps - C)))
    q5 = q.reshape(B, T, K, G, hd)

    def _q_idx(b, h, i, tabs, pos):
        return (b, 0, h, 0, 0)

    def _kv_idx(b, h, i, tabs, pos):
        return (jnp.minimum(tabs[b * MP + i], P - 1), 0, h, 0)

    def _valid_idx(b, h, i, tabs, pos):
        return (b, i)

    in_specs = [pl.BlockSpec((1, T, 1, G, hd), _q_idx),
                pl.BlockSpec((1, ps, 1, hd), _kv_idx)]
    args = [q5, pool["k"]]
    if quantized:
        in_specs.append(pl.BlockSpec((1, ps, 1, 1), _kv_idx))
        args.append(pool["k_scale"])
    in_specs.append(pl.BlockSpec((1, ps, 1, hd), _kv_idx))
    args.append(pool["v"])
    if quantized:
        in_specs.append(pl.BlockSpec((1, ps, 1, 1), _kv_idx))
        args.append(pool["v_scale"])
    in_specs.append(pl.BlockSpec((1, ps), _valid_idx))
    args.append(valid_i32)

    kernel = functools.partial(
        _kernel, page_size=ps, max_pages=MP, T=T, G=G, num_pages=P,
        sm_scale=sm_scale, window=window, softcap=softcap, quantized=quantized,
    )
    R = T * G
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, K, MP),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, T, 1, G, hd), _q_idx),
        scratch_shapes=[
            pltpu.VMEM((R, hd), jnp.float32),
            pltpu.VMEM((R, _LANES), jnp.float32),
            pltpu.VMEM((R, _LANES), jnp.float32),
        ],
    )
    # Decode is HBM-bound: bytes = every pool page each lane's table covers (+q/out);
    # flops = the two dots over the covered extent.
    kv_itemsize = pool["k"].dtype.itemsize
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, T, K, G, hd), q.dtype),
        compiler_params=_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(4 * B * K * R * MP * ps * hd),
            bytes_accessed=int(
                B * K * MP * ps * hd * kv_itemsize * 2 + 2 * q.size * q.dtype.itemsize
            ),
            transcendentals=int(B * K * R * MP * ps),
        ),
        interpret=interpret,
    )(tables.reshape(-1).astype(jnp.int32), positions.astype(jnp.int32), *args)
    return out.reshape(B, T, H, hd)
