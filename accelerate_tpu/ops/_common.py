"""Shared helpers for the Pallas kernel modules (flash/fused_optim/fused_xent)."""

from __future__ import annotations

import jax


def interpret_default() -> bool:
    """Run kernels in interpreter mode unless a real TPU backend is active."""
    return jax.default_backend() not in ("tpu", "axon")
