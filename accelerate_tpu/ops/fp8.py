"""FP8 training ops — the TPU-native replacement for TransformerEngine / torchao / MS-AMP.

Reference delegation points this file replaces with first-class XLA:
- ``utils/transformer_engine.py`` (convert_model Linear→te.Linear, fp8 recipes
  ``dataclasses.py:314-388``) — module swap onto CUDA kernels.
- ``utils/ao.py`` ``convert_model_to_fp8_ao``; ``_prepare_msamp`` (``accelerator.py:2164``).

TPU-native design: XLA has native fp8 dtypes (``float8_e4m3fn`` forward / ``float8_e5m2``
gradient — the "HYBRID" recipe) and ``lax.dot_general`` on fp8 inputs lowers to the hardware
scaled-matmul where the generation supports it (emulated in bf16 otherwise, still halving HBM
traffic for weights/activations that are stored quantized). There is no module swap: models
call :func:`fp8_dot` (a ``custom_vjp``) in place of ``@``.

Two scaling modes, mirroring TE's recipes:
- **current scaling** (default, stateless): per-tensor scale from the tensor's own amax.
- **delayed scaling** (:class:`DelayedScalingState`): scales derived from a rolling amax
  history (window ``amax_history_len``, reduction ``amax_compute_algo``), updated once per
  step — the state threads through the train step as a pytree, replacing TE's module buffers.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = [
    "FP8_MAX",
    "Format",
    "compute_scale",
    "quantize",
    "dequantize",
    "fp8_dot",
    "fp8_linear",
    "DelayedScalingState",
    "delayed_scales",
    "autoscale_ctx",
]

# Maximum representable magnitude per fp8 format.
FP8_MAX = {
    jnp.float8_e4m3fn: 448.0,
    jnp.float8_e5m2: 57344.0,
}


class Format:
    """Recipe formats (reference ``dataclasses.py:314`` fp8_format choices)."""

    E4M3 = "E4M3"      # e4m3 everywhere
    HYBRID = "HYBRID"  # e4m3 forward, e5m2 backward (the TE default)


# Process-wide recipe defaults, set by Accelerator(mixed_precision="fp8",
# kwargs_handlers=[FP8RecipeKwargs(...)]) — consulted whenever a call site doesn't pass
# explicit format/margin (the functional analog of TE's fp8_autocast recipe context).
_DEFAULT_RECIPE = {"fp8_format": Format.HYBRID, "margin": 0}


def set_default_recipe(fp8_format: Optional[str] = None, margin: Optional[int] = None) -> None:
    if fp8_format is not None:
        _DEFAULT_RECIPE["fp8_format"] = fp8_format.upper()
    if margin is not None:
        _DEFAULT_RECIPE["margin"] = int(margin)


def _resolve(fp8_format, margin):
    return (
        _DEFAULT_RECIPE["fp8_format"] if fp8_format is None else fp8_format,
        _DEFAULT_RECIPE["margin"] if margin is None else margin,
    )


def _fmt_dtypes(fp8_format: str):
    if fp8_format == Format.E4M3:
        return jnp.float8_e4m3fn, jnp.float8_e4m3fn
    if fp8_format == Format.HYBRID:
        return jnp.float8_e4m3fn, jnp.float8_e5m2
    raise ValueError(f"unknown fp8 format {fp8_format!r}")


def compute_scale(amax: jax.Array, fp8_dtype, margin: int = 0) -> jax.Array:
    """TE-style scale: largest power of two with ``amax * scale <= fp8_max / 2**margin``."""
    fp8_max = FP8_MAX[fp8_dtype]
    amax = jnp.maximum(amax.astype(jnp.float32), 1e-12)
    exp = jnp.floor(jnp.log2(fp8_max / amax)) - margin
    return jnp.exp2(exp)


def quantize(x: jax.Array, scale: jax.Array, fp8_dtype) -> jax.Array:
    """Scale then saturate-cast to fp8. ``scale`` multiplies x into the representable range."""
    fp8_max = FP8_MAX[fp8_dtype]
    scaled = jnp.clip(x.astype(jnp.float32) * scale, -fp8_max, fp8_max)
    return scaled.astype(fp8_dtype)


def dequantize(x: jax.Array, scale: jax.Array, dtype=jnp.float32) -> jax.Array:
    return (x.astype(jnp.float32) / scale).astype(dtype)


def _scaled_dot(x_q, w_q, x_scale, w_scale, out_dtype):
    """fp8 × fp8 dot with fp32 accumulation, rescaled back to real magnitude.

    ``preferred_element_type=float32`` lets XLA pick the native fp8 MXU path when the TPU
    generation has one; elsewhere it widens — numerics are identical either way.
    """
    y = jax.lax.dot_general(
        x_q, w_q,
        dimension_numbers=(((x_q.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return (y / (x_scale * w_scale)).astype(out_dtype)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fp8_dot_impl(x, w, scales, fp8_format: str, margin: int):
    """``scales``: fp32 [3] array (x, w, grad) — NaN entries mean "current scaling"."""
    y, _ = _fp8_dot_fwd(x, w, scales, fp8_format, margin)
    return y


def _pick_scale(provided, tensor, fp8_dtype, margin):
    current = compute_scale(jnp.max(jnp.abs(tensor)), fp8_dtype, margin)
    return jnp.where(jnp.isnan(provided), current, provided)


def _fp8_dot_fwd(x, w, scales, fp8_format, margin):
    fwd_dtype, _ = _fmt_dtypes(fp8_format)
    x_scale = _pick_scale(scales[0], x, fwd_dtype, margin)
    w_scale = _pick_scale(scales[1], w, fwd_dtype, margin)
    x_q = quantize(x, x_scale, fwd_dtype)
    w_q = quantize(w, w_scale, fwd_dtype)
    y = _scaled_dot(x_q, w_q, x_scale, w_scale, x.dtype)
    # Zero-size carriers keep the primal dtypes through the residual pytree (dtype objects
    # themselves are not valid pytree leaves under jit).
    x_tag = jnp.zeros((0,), x.dtype)
    w_tag = jnp.zeros((0,), w.dtype)
    return y, (x_q, w_q, x_scale, w_scale, scales[2], x_tag, w_tag)


def _fp8_dot_bwd(fp8_format, margin, residuals, g):
    _, bwd_dtype = _fmt_dtypes(fp8_format)
    x_q, w_q, x_scale, w_scale, g_scale_in, x_tag, w_tag = residuals
    x_dtype, w_dtype = x_tag.dtype, w_tag.dtype
    g_scale = _pick_scale(g_scale_in, g, bwd_dtype, margin)
    g_q = quantize(g, g_scale, bwd_dtype)
    # dx = g @ w.T : contract g's last dim with w's output dim.
    dx = jax.lax.dot_general(
        g_q, w_q,
        dimension_numbers=(((g_q.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) / (g_scale * w_scale)
    # dw = x.T @ g : contract every batch dim.
    batch_dims = tuple(range(x_q.ndim - 1))
    dw = jax.lax.dot_general(
        x_q, g_q,
        dimension_numbers=((batch_dims, batch_dims), ((), ())),
        preferred_element_type=jnp.float32,
    ) / (x_scale * g_scale)
    # Cotangent dtypes must match the primal dtypes (bf16 activations under mixed precision).
    return dx.astype(x_dtype), dw.astype(w_dtype), jnp.zeros((3,), jnp.float32)


_fp8_dot_impl.defvjp(_fp8_dot_fwd, _fp8_dot_bwd)

import contextlib

# Active delayed-scaling context: {"scales": fp32[3] tracer, "amax": fp32[2] tracer or None}.
# Set by autoscale_ctx during train-step tracing; consulted by fp8_dot when no explicit
# scales are passed (the functional analog of TE's fp8_autocast context).
_AUTOSCALE: dict = {"scales": None, "amax": None}


@contextlib.contextmanager
def autoscale_ctx(scales: jax.Array):
    """Route ``scales`` to every :func:`fp8_dot` in the block and collect observed forward
    amaxes (elementwise max across call sites) — used by
    ``Accelerator.build_train_step`` to wire :class:`DelayedScalingState` automatically.

    Read ``ctx["amax"]`` INSIDE the block (it holds trace-local values; nothing is retained
    after exit — retaining it would leak tracers out of the enclosing jit trace).
    """
    prev = dict(_AUTOSCALE)
    _AUTOSCALE["scales"] = scales
    _AUTOSCALE["amax"] = jnp.zeros((2,), jnp.float32)
    try:
        yield _AUTOSCALE
    finally:
        _AUTOSCALE["scales"] = prev["scales"]
        _AUTOSCALE["amax"] = prev["amax"]


def fp8_dot(
    x: jax.Array,
    w: jax.Array,
    fp8_format: Optional[str] = None,
    margin: Optional[int] = None,
    scales: Optional[jax.Array] = None,
):
    """``x @ w`` with fp8-quantized operands (forward e4m3; backward per ``fp8_format``).

    ``fp8_format``/``margin`` default to the process recipe (:func:`set_default_recipe`).
    ``scales``: optional fp32 ``[3]`` array ``(x_scale, w_scale, grad_scale)`` from
    :func:`delayed_scales`; None selects the active :func:`autoscale_ctx`'s scales if one is
    set, else current scaling (each tensor's own amax, stateless).
    """
    fp8_format, margin = _resolve(fp8_format, margin)
    if scales is None and _AUTOSCALE["scales"] is not None:
        scales = _AUTOSCALE["scales"]
        _AUTOSCALE["amax"] = jnp.maximum(
            _AUTOSCALE["amax"],
            jnp.stack([
                jnp.max(jnp.abs(x)).astype(jnp.float32),
                jnp.max(jnp.abs(w)).astype(jnp.float32),
            ]),
        )
    if scales is None:
        scales = jnp.full((3,), jnp.nan, jnp.float32)
    return _fp8_dot_impl(x, w, scales, fp8_format, margin)


def fp8_linear(x, w, b=None, fp8_format: Optional[str] = None, margin: Optional[int] = None, scales=None):
    """Linear layer on :func:`fp8_dot` (the ``te.Linear`` swap target)."""
    y = fp8_dot(x, w, fp8_format, margin, scales)
    if b is not None:
        y = y + b.astype(y.dtype)
    return y


# ------------------------------------------------------------------------ delayed scaling
@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DelayedScalingState:
    """Rolling amax history per quantized tensor role (x / w / grad).

    The functional replacement for TE's per-module fp8 buffers: carried in the user's train
    state, updated once per step with the step's observed amaxes.
    ``history``: [3, amax_history_len] fp32 (rows: x, w, grad).
    """

    history: jax.Array
    step: jax.Array

    @classmethod
    def init(cls, amax_history_len: int = 16) -> "DelayedScalingState":
        return cls(
            history=jnp.zeros((3, amax_history_len), jnp.float32),
            step=jnp.zeros((), jnp.int32),
        )

    def update(self, x_amax, w_amax, g_amax) -> "DelayedScalingState":
        idx = self.step % self.history.shape[1]
        new = self.history.at[:, idx].set(jnp.stack([x_amax, w_amax, g_amax]).astype(jnp.float32))
        return DelayedScalingState(history=new, step=self.step + 1)


def delayed_scales(
    state: DelayedScalingState,
    fp8_format: str = Format.HYBRID,
    margin: int = 0,
    amax_compute_algo: str = "max",
):
    """fp32 [3] scales (x, w, grad) from the history (``amax_compute_algo``: max|most_recent).

    Suitable to pass straight to :func:`fp8_dot`'s ``scales``. Positions whose history is still
    all-zero come out NaN, which :func:`fp8_dot` treats as "fall back to current scaling" — the
    warm-up behavior TE gets from its ``interval`` bootstrapping.
    """
    fwd_dtype, bwd_dtype = _fmt_dtypes(fp8_format)
    if amax_compute_algo == "max":
        amaxes = jnp.max(state.history, axis=1)
    elif amax_compute_algo == "most_recent":
        idx = (state.step - 1) % state.history.shape[1]
        amaxes = state.history[:, idx]
    else:
        raise ValueError(f"unknown amax_compute_algo {amax_compute_algo!r}")
    scales = jnp.stack([
        compute_scale(amaxes[0], fwd_dtype, margin),
        compute_scale(amaxes[1], fwd_dtype, margin),
        compute_scale(amaxes[2], bwd_dtype, margin),
    ])
    return jnp.where(amaxes > 0, scales, jnp.nan)
