"""Mixture-of-Experts layer with expert parallelism over the mesh "ep" axis.

Reference delegation points this replaces (SURVEY.md §2.2 EP row): the reference only
*recognizes* DeepSpeed MoE modules (``transformer_moe_cls_names`` ``dataclasses.py:1105``) and
defers all routing/dispatch to DeepSpeed's CUDA all-to-all. Here MoE is first-class and
TPU-idiomatic: routing builds dense one-hot dispatch/combine tensors (the GSPMD MoE pattern —
einsums the MXU loves, no ragged scatter), expert weights carry an explicit PartitionSpec on
the "ep" axis, and a ``with_sharding_constraint`` on the dispatched activations makes XLA
insert the token all-to-all over ICI — the NCCL a2a analog is a compiler-inserted collective,
not a library call.

Components: top-k softmax router with capacity dropping, Switch/Mixtral-style load-balancing
auxiliary loss, batched expert FFN (SwiGLU, matching the dense MLP).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..utils.constants import EXPERT_AXIS

__all__ = ["router_topk", "load_balancing_loss", "moe_mlp", "moe_mlp_dense", "expert_partition_specs"]


def router_topk(
    x: jax.Array, w_router: jax.Array, top_k: int
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k softmax routing.

    x [T, D], w_router [D, E] → (logits [T, E], gates [T, k] renormalized, idx [T, k]).
    Router math in fp32 regardless of compute dtype (routing is precision-sensitive).
    """
    logits = x.astype(jnp.float32) @ w_router.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    gates, idx = jax.lax.top_k(probs, top_k)
    gates = gates / jnp.maximum(jnp.sum(gates, axis=-1, keepdims=True), 1e-9)
    return logits, gates, idx


def load_balancing_loss(
    logits: jax.Array, idx: jax.Array, num_experts: int,
    token_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Switch-Transformer auxiliary loss: E · Σ_e f_e · p_e.

    f_e = fraction of tokens whose top-1 lands on expert e; p_e = mean router probability of
    e. Minimized (=1) at uniform balance. ``token_mask`` [T] bool (sample packing: False on
    pad slots) restricts both means to REAL tokens — pads would otherwise bias the balance
    statistic toward whatever experts they happen to route to.
    """
    probs = jax.nn.softmax(logits, axis=-1)
    top1 = idx[..., 0]
    oh = jax.nn.one_hot(top1, num_experts, dtype=jnp.float32)
    if token_mask is not None:
        m = token_mask.astype(jnp.float32)[:, None]
        denom = jnp.maximum(m.sum(), 1.0)
        f = jnp.sum(oh * m, axis=0) / denom
        p = jnp.sum(probs * m, axis=0) / denom
    else:
        f = jnp.mean(oh, axis=0)
        p = jnp.mean(probs, axis=0)
    return num_experts * jnp.sum(f * p)


def _capacity(tokens: int, num_experts: int, top_k: int, capacity_factor: float) -> int:
    cap = int(tokens * top_k * capacity_factor / num_experts)
    return max(cap, 1)


def moe_mlp(
    x: jax.Array,
    experts: dict,
    w_router: jax.Array,
    top_k: int = 2,
    capacity_factor: float = 1.25,
    compute_dtype=jnp.bfloat16,
    shard: bool = True,
    token_mask: Optional[jax.Array] = None,
) -> tuple[jax.Array, jax.Array]:
    """MoE SwiGLU FFN. x [B, S, D]; experts {w_gate/w_up [E, D, F], w_down [E, F, D]}.

    Returns (y [B, S, D], aux_loss scalar). Tokens beyond an expert's capacity are dropped
    (contribute zero through that expert) — the standard fixed-shape TPU formulation; with
    ``capacity_factor ≥ top_k·E/…`` nothing drops.

    ``token_mask`` [B, S] bool (sample packing: False on pad slots): pad tokens neither
    claim expert-capacity slots (they would crowd out REAL tokens and increase dropping)
    nor enter the load-balancing statistic; their output rows are zero.
    """
    B, S, D = x.shape
    T = B * S
    E = experts["w_gate"].shape[0]
    C = _capacity(T, E, top_k, capacity_factor)

    flat = x.reshape(T, D)
    logits, gates, idx = router_topk(flat, w_router, top_k)
    live = None if token_mask is None else token_mask.reshape(T).astype(bool)
    aux = load_balancing_loss(logits, idx, E, token_mask=live)

    # Position of each (token, choice) in its expert's buffer, via cumulative count over the
    # flattened (k-major) assignment order; entries beyond capacity are dropped.
    onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)           # [T, k, E]
    if live is not None:
        # Pads claim no slots: zeroing their assignment BEFORE the cumsum removes them
        # from capacity competition entirely (and from dispatch/combine below).
        onehot = onehot * live[:, None, None].astype(jnp.int32)
    flat_oh = onehot.transpose(1, 0, 2).reshape(T * top_k, E)  # k-major: top-1s claim slots first
    pos_flat = jnp.cumsum(flat_oh, axis=0) - flat_oh           # [T*k, E]
    pos = pos_flat.reshape(top_k, T, E).transpose(1, 0, 2)     # [T, k, E]
    pos_tk = jnp.sum(pos * onehot, axis=-1)                    # [T, k] slot within chosen expert
    keep = pos_tk < C

    # Dense dispatch/combine tensors (GSPMD MoE): dispatch [T, E, C] bool, combine [T, E, C].
    slot_oh = jax.nn.one_hot(jnp.where(keep, pos_tk, C), C + 1, dtype=compute_dtype)[..., :C]
    dispatch = jnp.einsum("tke,tkc->tec", onehot.astype(compute_dtype), slot_oh)
    combine = jnp.einsum("tk,tke,tkc->tec", gates.astype(compute_dtype),
                         onehot.astype(compute_dtype), slot_oh)

    xin = jnp.einsum("td,tec->ecd", flat.astype(compute_dtype), dispatch)  # [E, C, D]
    if shard:
        xin = _maybe_shard(xin, P(EXPERT_AXIS, None, None))

    # Batched expert SwiGLU — expert dim sharded on "ep": XLA turns the dispatch einsum above
    # into the token all-to-all, and each device computes only its local experts.
    gate = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xin, experts["w_gate"].astype(compute_dtype)))
    up = jnp.einsum("ecd,edf->ecf", xin, experts["w_up"].astype(compute_dtype))
    out = jnp.einsum("ecf,efd->ecd", gate * up, experts["w_down"].astype(compute_dtype))
    if shard:
        out = _maybe_shard(out, P(EXPERT_AXIS, None, None))

    y = jnp.einsum("ecd,tec->td", out, combine)  # combine: weighted return all-to-all
    return y.reshape(B, S, D).astype(x.dtype), aux


def moe_mlp_dense(
    x: jax.Array,
    experts: dict,
    w_router: jax.Array,
    top_k: int = 2,
    compute_dtype=jnp.bfloat16,
) -> jax.Array:
    """Drop-free MoE FFN: every expert computed on every token, combined by top-k gates.

    Exact inference semantics — no capacity dropping (the training formulation's fixed-shape
    load-management artifact, ``moe_mlp``).  Cost is E× the FFN over the given tokens, which
    is the right trade only when T is tiny: single-token decode steps, where the FFN is
    HBM-bandwidth-bound anyway and a ragged per-expert gather would defeat jit.
    """
    B, S, D = x.shape
    T = B * S
    E = experts["w_gate"].shape[0]
    flat = x.reshape(T, D).astype(compute_dtype)
    _, gates, idx = router_topk(x.reshape(T, D), w_router, top_k)
    # [T, E] combine weights: renormalized gate mass on each chosen expert, 0 elsewhere.
    weights = jnp.sum(
        gates[..., None] * jax.nn.one_hot(idx, E, dtype=jnp.float32), axis=1
    ).astype(compute_dtype)
    gate = jax.nn.silu(jnp.einsum("td,edf->etf", flat, experts["w_gate"].astype(compute_dtype)))
    up = jnp.einsum("td,edf->etf", flat, experts["w_up"].astype(compute_dtype))
    out = jnp.einsum("etf,efd->etd", gate * up, experts["w_down"].astype(compute_dtype))
    y = jnp.einsum("etd,te->td", out, weights)
    return y.reshape(B, S, D).astype(x.dtype)


def expert_partition_specs() -> dict:
    """PartitionSpecs for the expert weight dict: expert dim on "ep", ffn dim on "tp"."""
    from ..utils.constants import TENSOR_AXIS

    return {
        "w_gate": P(EXPERT_AXIS, None, TENSOR_AXIS),
        "w_up": P(EXPERT_AXIS, None, TENSOR_AXIS),
        "w_down": P(EXPERT_AXIS, TENSOR_AXIS, None),
        "w_router": P(),
    }


def _maybe_shard(x: jax.Array, spec: P) -> jax.Array:
    from .collectives import maybe_shard

    return maybe_shard(x, spec, require_axis=EXPERT_AXIS)
