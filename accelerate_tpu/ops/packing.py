"""Sequence packing — variable-length samples into fixed-shape rows (sample packing).

XLA compiles one program per shape, so TPU data pipelines must deliver STATIC shapes; the
naive answer (pad every sequence to ``max_seq``) wastes compute proportional to the padding
fraction — often 2-3× on instruction-tuning mixtures. Packing concatenates multiple
sequences per row with segment ids, recovering that compute. The reference has no packing
facility (its data layer only shards/dispatches torch batches); this is a TPU-first
capability, paired with segment-aware attention masking in every model family: llama/gpt
consume ``segment_ids``/``positions`` directly (``pack_sequences``), and t5 consumes the
paired ``enc_segment_ids``/``dec_segment_ids`` layout (``pack_seq2seq``).

The bin-assignment + scatter hot loop runs natively (``native/packing.cpp``, first-fit,
loaded via ctypes; built on demand with g++) with a behavior-identical pure-Python
fallback — tests assert C++ == Python on random corpora.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional, Sequence

import numpy as np

__all__ = ["pack_sequences", "pack_seq2seq", "packed_batch_iterator", "native_available"]

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_SRC = os.path.join(_NATIVE_DIR, "packing.cpp")
_SO = os.path.join(_NATIVE_DIR, "libpacking.so")
_lock = threading.Lock()
_lib = None
_build_failed = False


def _configure(lib: ctypes.CDLL) -> None:
    lib.pack_sequences_ffit.restype = ctypes.c_longlong
    lib.pack_sequences_ffit.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
    ]


def _load_native():
    """Build (once) and load the native packer; None when no toolchain is available."""
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        from ..native import load_native

        _lib = load_native(_SRC, _SO, _configure)
        if _lib is None:
            _build_failed = True
        return _lib


def native_available() -> bool:
    return _load_native() is not None


# NOTE: the first-fit scan appears three times by design — _pack_python (single capacity,
# must mirror native/packing.cpp bit for bit), pack_seq2seq (dual enc/dec capacity), and
# packed_batch_iterator (online, emits mid-stream). They carry different bin state; a
# predicate-parameterized shared helper was tried and read worse than three plain loops.
# When changing the fit policy or segment numbering, change ALL THREE (tests assert
# native==python and per-variant invariants).
def _pack_python(flat, offsets, capacity, max_bins):
    """Reference implementation: must match native/packing.cpp bit for bit."""
    used: list[int] = []
    n_segs: list[int] = []
    assignments = []  # (bin, start, seg, seq_index, length)
    for i in range(len(offsets) - 1):
        length = int(offsets[i + 1] - offsets[i])
        if length > capacity or length < 0:
            return None
        if length == 0:
            continue
        bin_id = next((b for b in range(len(used)) if used[b] + length <= capacity), -1)
        if bin_id < 0:
            if len(used) >= max_bins:
                return None
            used.append(0)
            n_segs.append(0)
            bin_id = len(used) - 1
        n_segs[bin_id] += 1
        assignments.append((bin_id, used[bin_id], n_segs[bin_id], i, length))
        used[bin_id] += length
    n_bins = len(used)
    tokens = np.zeros((n_bins, capacity), np.int32)
    segments = np.zeros((n_bins, capacity), np.int32)
    positions = np.zeros((n_bins, capacity), np.int32)
    for bin_id, start, seg, i, length in assignments:
        tokens[bin_id, start:start + length] = flat[offsets[i]:offsets[i] + length]
        segments[bin_id, start:start + length] = seg
        positions[bin_id, start:start + length] = np.arange(length, dtype=np.int32)
    return tokens, segments, positions


def pack_sequences(
    sequences: Sequence[np.ndarray],
    seq_len: int,
    max_bins: Optional[int] = None,
    use_native: Optional[bool] = None,
) -> dict:
    """Pack variable-length int sequences into fixed [n_bins, seq_len] rows (first-fit).

    Returns ``{"tokens", "segment_ids", "positions"}`` int32 arrays. ``segment_ids`` is 0 on
    padding and 1..k per packed sequence within a row; ``positions`` restart at 0 per
    segment (feed them to the model so RoPE/causality are per-sequence). Raises
    ``ValueError`` if any sequence exceeds ``seq_len``.
    """
    seqs = [np.asarray(s, np.int32).ravel() for s in sequences]
    flat = np.concatenate(seqs) if seqs else np.zeros((0,), np.int32)
    offsets = np.zeros(len(seqs) + 1, np.int64)
    np.cumsum([len(s) for s in seqs], out=offsets[1:])
    if max_bins is None:
        # First-fit leaves at most one bin ≤ half full, so bins ≤ 2·total/capacity + 1;
        # len(seqs) also bounds it (one bin per sequence worst case).
        total = int(offsets[-1])
        max_bins = max(1, min(len(seqs), 2 * -(-total // max(seq_len, 1)) + 1))
    lib = _load_native() if use_native in (None, True) else None
    if use_native is True and lib is None:
        raise RuntimeError("native packer requested but unavailable (no g++?)")
    if lib is not None:
        out_t = np.zeros((max_bins, seq_len), np.int32)
        out_s = np.zeros((max_bins, seq_len), np.int32)
        out_p = np.zeros((max_bins, seq_len), np.int32)
        flat_c = np.ascontiguousarray(flat)
        n_bins = lib.pack_sequences_ffit(
            flat_c.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(seqs), seq_len,
            out_t.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out_s.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out_p.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            max_bins,
        )
        if n_bins < 0:
            raise ValueError(
                f"packing failed: a sequence exceeds seq_len={seq_len} or max_bins="
                f"{max_bins} is too small"
            )
        # Copy: slicing a view would pin the whole [max_bins, seq_len] allocation.
        result = (
            out_t[:n_bins].copy(), out_s[:n_bins].copy(), out_p[:n_bins].copy()
        )
    else:
        packed = _pack_python(flat, offsets, seq_len, max_bins)
        if packed is None:
            raise ValueError(
                f"packing failed: a sequence exceeds seq_len={seq_len} or max_bins="
                f"{max_bins} is too small"
            )
        result = packed
    tokens, segments, positions = result
    return {"tokens": tokens, "segment_ids": segments, "positions": positions}


def pack_seq2seq(
    inputs: Sequence[np.ndarray],
    targets: Sequence[np.ndarray],
    enc_len: int,
    dec_len: int,
    max_bins: Optional[int] = None,
) -> dict:
    """Pack (encoder input, decoder target) PAIRS into aligned fixed-shape rows (first-fit).

    Pair ``i`` goes to a row only when BOTH its sides fit; the pair receives the SAME
    segment number on the encoder and decoder side of that row, which is what lets
    cross-attention match decoder segment k to encoder segment k (``models/t5`` packed
    path). Returns ``{"input_ids", "enc_segment_ids", "labels", "dec_segment_ids"}``
    int32 arrays of widths ``enc_len`` / ``dec_len``; padding slots are 0 with segment 0
    (``labels`` padding is -100, the ignored-label convention).
    """
    if len(inputs) != len(targets):
        raise ValueError(f"{len(inputs)} inputs vs {len(targets)} targets")
    ins = [np.asarray(s, np.int32).ravel() for s in inputs]
    tgts = [np.asarray(s, np.int32).ravel() for s in targets]
    if max_bins is None:
        max_bins = max(1, len(ins))
    enc_used: list[int] = []
    dec_used: list[int] = []
    n_segs: list[int] = []
    assignments = []
    for i, (src, tgt) in enumerate(zip(ins, tgts)):
        if len(src) > enc_len or len(tgt) > dec_len:
            raise ValueError(
                f"pair {i} exceeds capacity (input {len(src)}>{enc_len} or "
                f"target {len(tgt)}>{dec_len})"
            )
        if len(src) == 0 and len(tgt) == 0:
            continue
        if len(src) == 0 or len(tgt) == 0:
            # Dropping only one side would silently discard the other's tokens — surface
            # the malformed pair instead (oversize pairs raise too).
            raise ValueError(f"pair {i} has an empty side (input {len(src)}, target {len(tgt)})")
        bin_id = next(
            (
                b
                for b in range(len(enc_used))
                if enc_used[b] + len(src) <= enc_len and dec_used[b] + len(tgt) <= dec_len
            ),
            -1,
        )
        if bin_id < 0:
            if len(enc_used) >= max_bins:
                raise ValueError(f"max_bins={max_bins} too small")
            enc_used.append(0)
            dec_used.append(0)
            n_segs.append(0)
            bin_id = len(enc_used) - 1
        n_segs[bin_id] += 1
        assignments.append((bin_id, enc_used[bin_id], dec_used[bin_id], n_segs[bin_id], i))
        enc_used[bin_id] += len(src)
        dec_used[bin_id] += len(tgt)
    n_bins = len(enc_used)
    input_ids = np.zeros((n_bins, enc_len), np.int32)
    enc_seg = np.zeros((n_bins, enc_len), np.int32)
    labels = np.full((n_bins, dec_len), -100, np.int32)
    dec_seg = np.zeros((n_bins, dec_len), np.int32)
    for bin_id, e0, d0, seg, i in assignments:
        src, tgt = ins[i], tgts[i]
        input_ids[bin_id, e0:e0 + len(src)] = src
        enc_seg[bin_id, e0:e0 + len(src)] = seg
        labels[bin_id, d0:d0 + len(tgt)] = tgt
        dec_seg[bin_id, d0:d0 + len(tgt)] = seg
    return {
        "input_ids": input_ids,
        "enc_segment_ids": enc_seg,
        "labels": labels,
        "dec_segment_ids": dec_seg,
    }


def packed_batch_iterator(
    documents,
    seq_len: int,
    rows_per_batch: int,
    drop_last: bool = False,
):
    """Stream variable-length docs into fixed-shape packed batches (online first-fit).

    Maintains up to ``rows_per_batch`` open rows; each incoming document goes to the first
    open row it fits (first-fit). When a document fits no open row and all rows are open,
    the batch is emitted and a fresh one starts — so every yielded batch is exactly
    ``[rows_per_batch, seq_len]`` (the final partial batch pads with empty rows unless
    ``drop_last``). This is the data-layer integration of ``pack_sequences``: wrap the
    per-process document stream AFTER sharding (each process packs its own shard) and feed
    the yielded dicts straight to a packed-aware ``loss_fn``.
    """
    def emit(bins):
        tokens = np.zeros((rows_per_batch, seq_len), np.int32)
        segments = np.zeros((rows_per_batch, seq_len), np.int32)
        positions = np.zeros((rows_per_batch, seq_len), np.int32)
        for r, docs in enumerate(bins):
            at = 0
            for s, doc in enumerate(docs, start=1):
                n = len(doc)
                tokens[r, at:at + n] = doc
                segments[r, at:at + n] = s
                positions[r, at:at + n] = np.arange(n, dtype=np.int32)
                at += n
        return {"tokens": tokens, "segment_ids": segments, "positions": positions}

    bins: list[list[np.ndarray]] = []
    used: list[int] = []
    for doc in documents:
        doc = np.asarray(doc, np.int32).ravel()
        if len(doc) > seq_len:
            raise ValueError(f"document of {len(doc)} tokens exceeds seq_len={seq_len}")
        if len(doc) == 0:
            continue
        row = next((b for b in range(len(bins)) if used[b] + len(doc) <= seq_len), -1)
        if row < 0:
            if len(bins) < rows_per_batch:
                bins.append([])
                used.append(0)
                row = len(bins) - 1
            else:
                yield emit(bins)
                bins, used = [[]], [0]
                row = 0
        bins[row].append(doc)
        used[row] += len(doc)
    if bins and not drop_last:
        yield emit(bins)
