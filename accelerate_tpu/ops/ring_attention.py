"""Ring attention — exact causal attention over a sequence-sharded mesh axis.

The reference has NO long-context implementation (SURVEY.md §5: only a Megatron passthrough
flag); this module is the first-class TPU-native answer. Each device holds a local sequence
chunk of q/k/v; kv chunks rotate around the ``sp`` ring via ``ppermute`` (riding ICI
neighbor links) while every device computes flash-attention partials of its local q against
the visiting kv chunk, merged with numerically-stable online-softmax weights. Communication
overlaps compute and HBM never sees an S_global×S_global score matrix — context length scales
linearly with ring size (Ring Attention, Liu et al. 2023).

Backward: (k, v, dk, dv) rotate together; each device adds its local-q contribution to the
visiting kv block's gradients; after a full revolution the gradients are home. dq accumulates
locally. Both passes reuse the Pallas flash kernels (``ops/flash_attention._fwd/_bwd_*``)
with global position offsets for cross-device causal masking.

Use inside ``shard_map`` (manual axes must include the ring axis), e.g. via
``parallel.sequence.sequence_parallel_attention``.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .flash_attention import _bwd_dq, _bwd_dkv, _fwd, _interpret_default
from ..utils.jax_compat import axis_size as _axis_size

__all__ = ["ring_attention"]


def _merge(o_run, lse_run, o_b, lse_b):
    """Online-softmax merge of two normalized partial outputs ([B,H,S,hd], [B,H,S])."""
    m = jnp.maximum(lse_run, lse_b)
    w_run = jnp.exp(lse_run - m)
    w_b = jnp.exp(lse_b - m)
    denom = w_run + w_b
    denom_safe = jnp.where(denom == 0.0, 1.0, denom)
    o = (o_run * w_run[..., None] + o_b.astype(jnp.float32) * w_b[..., None]) / denom_safe[..., None]
    return o, m + jnp.log(denom_safe)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11))
def _ring_bhsd(q, k, v, seg_f32, axis_name, causal, sm_scale, block_sizes, interpret,
               window, softcap, has_segments):
    o, _ = _ring_fwd_impl(q, k, v, seg_f32, axis_name, causal, sm_scale, block_sizes,
                          interpret, window, softcap, has_segments)
    return o


def _ring_fwd_impl(q, k, v, seg_f32, axis_name, causal, sm_scale, block_sizes, interpret,
                   window=0, softcap=0.0, has_segments=False):
    block_q, block_k = block_sizes
    B, H, S_local, hd = q.shape
    idx = lax.axis_index(axis_name)
    n = _axis_size(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    q_off = idx * S_local
    # Packing: q keeps its LOCAL segment-id slice; the kv-side slice rotates around the
    # ring WITH its k/v block so every visiting block carries matching segment ids.
    q_seg = seg_f32.astype(jnp.int32) if has_segments else None

    def body(carry, t):
        k_cur, v_cur, kv_seg_cur, o_run, lse_run = carry
        kv_idx = (idx - t) % n
        # The kernels take GLOBAL offsets, so sliding-window masking (and its tile
        # skipping) is correct across ring steps without any extra logic here.
        o_b, lse_b = _fwd(
            q, k_cur, v_cur, causal, sm_scale, block_q, block_k, interpret,
            q_offset=q_off, kv_offset=kv_idx * S_local, window=window, softcap=softcap,
            segments=(q_seg, kv_seg_cur) if has_segments else None,
        )
        o_run, lse_run = _merge(o_run, lse_run, o_b, lse_b)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        kv_seg_next = (
            lax.ppermute(kv_seg_cur, axis_name, perm) if has_segments else kv_seg_cur
        )
        return (k_next, v_next, kv_seg_next, o_run, lse_run), None

    o0 = jnp.zeros((B, H, S_local, hd), jnp.float32)
    lse0 = jnp.full((B, H, S_local), -1e30, jnp.float32)
    kv_seg0 = q_seg if has_segments else jnp.zeros((), jnp.int32)
    (k_home, v_home, _seg_home, o, lse), _ = lax.scan(
        body, (k, v, kv_seg0, o0, lse0), jnp.arange(n)
    )
    return o.astype(q.dtype), lse


def _ring_fwd(q, k, v, seg_f32, axis_name, causal, sm_scale, block_sizes, interpret,
              window, softcap, has_segments):
    o, lse = _ring_fwd_impl(q, k, v, seg_f32, axis_name, causal, sm_scale, block_sizes,
                            interpret, window, softcap, has_segments)
    return o, (q, k, v, seg_f32, o, lse)


def _ring_bwd(axis_name, causal, sm_scale, block_sizes, interpret, window, softcap,
              has_segments, residuals, do):
    block_q, block_k = block_sizes
    q, k, v, seg_f32, o, lse = residuals
    B, H, S_local, hd = q.shape
    idx = lax.axis_index(axis_name)
    n = _axis_size(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    q_off = idx * S_local
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    q_seg = seg_f32.astype(jnp.int32) if has_segments else None

    def body(carry, t):
        k_cur, v_cur, kv_seg_cur, dk_cur, dv_cur, dq_run = carry
        kv_idx = (idx - t) % n
        kv_off = kv_idx * S_local
        segs = (q_seg, kv_seg_cur) if has_segments else None
        dq_b = _bwd_dq(
            q, k_cur, v_cur, do, lse, delta, causal, sm_scale, block_q, block_k, interpret,
            q_offset=q_off, kv_offset=kv_off, window=window, softcap=softcap,
            segments=segs,
        )
        dk_b, dv_b = _bwd_dkv(
            q, k_cur, v_cur, do, lse, delta, causal, sm_scale, block_q, block_k, interpret,
            q_offset=q_off, kv_offset=kv_off, window=window, softcap=softcap,
            segments=segs,
        )
        dq_run = dq_run + dq_b
        dk_cur = dk_cur + dk_b
        dv_cur = dv_cur + dv_b
        # Rotate kv (and its segment ids) AND its gradient accumulators together: after
        # n steps they're home.
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        kv_seg_next = (
            lax.ppermute(kv_seg_cur, axis_name, perm) if has_segments else kv_seg_cur
        )
        dk_next = lax.ppermute(dk_cur, axis_name, perm)
        dv_next = lax.ppermute(dv_cur, axis_name, perm)
        return (k_next, v_next, kv_seg_next, dk_next, dv_next, dq_run), None

    zeros_kv = jnp.zeros(k.shape, jnp.float32)  # [B, K, S_local, hd] — K kv heads, unrepeated
    kv_seg0 = q_seg if has_segments else jnp.zeros((), jnp.int32)
    (k_home, v_home, _seg_home, dk, dv, dq), _ = lax.scan(
        body,
        (k, v, kv_seg0, zeros_kv, zeros_kv, jnp.zeros((B, H, S_local, hd), jnp.float32)),
        jnp.arange(n),
    )
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            jnp.zeros_like(seg_f32))


_ring_bhsd.defvjp(_ring_fwd, _ring_bwd)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: Optional[bool] = None,
    window: int = 0,
    softcap: float = 0.0,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Exact ring attention for use inside shard_map; user layout q [B, S_loc, H, hd].

    k/v [B, S_loc, K, hd] with K dividing H — GQA is native in the flash kernels, so the
    ring rotates the UNREPEATED [B, K, S_loc, hd] k/v (and dk/dv): for 16q/8kv that halves
    the per-step ppermute bytes on the ICI ring. Returns [B, S_loc, H, hd].

    ``segment_ids``: this shard's LOCAL [B, S_loc] slice of the packed segment ids
    (``ops/packing.py`` layout: 0 = pad). The kv-side slice rotates around the ring with
    its k/v block, so same-segment masking stays exact across shard boundaries — packing
    and long-context sequence parallelism compose.
    """
    B, S_local, H, hd = q.shape
    K = k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(hd)
    if interpret is None:
        interpret = _interpret_default()
    if H % K:
        raise ValueError(f"q heads ({H}) must be a multiple of kv heads ({K})")
    if segment_ids is not None and segment_ids.shape != (B, S_local):
        raise ValueError(
            f"segment_ids must be the local [B, S_local] slice {(B, S_local)}, "
            f"got {segment_ids.shape}"
        )
    qT = q.transpose(0, 2, 1, 3)
    kT = k.transpose(0, 2, 1, 3)
    vT = v.transpose(0, 2, 1, 3)
    from .flash_attention import _DEFAULT_BLOCK_K, _DEFAULT_BLOCK_Q, _fit_block

    bq = _fit_block(block_q or _DEFAULT_BLOCK_Q, S_local)
    bk = _fit_block(block_k or _DEFAULT_BLOCK_K, S_local)
    has_segments = segment_ids is not None
    seg_f32 = (
        jnp.asarray(segment_ids, jnp.float32) if has_segments
        else jnp.zeros((1, 1), jnp.float32)
    )
    o = _ring_bhsd(qT, kT, vT, seg_f32, axis_name, causal, sm_scale, (bq, bk), interpret,
                   int(window), float(softcap), has_segments)
    return o.transpose(0, 2, 1, 3)
