"""Fused AdamW — single-pass Pallas optimizer kernel (the TPU-native FusedAdam).

Reference delegation points this replaces: the reference ecosystem leans on fused CUDA
optimizers for the apply step — DeepSpeed's FusedAdam/cpu-Adam behind
``utils/dataclasses.py:1019-1448`` (DeepSpeedPlugin) and apex ``FusedAdam`` in Megatron mode
(``utils/megatron_lm.py``).  On TPU the optimizer apply is pure HBM bandwidth: the ideal
schedule reads each of p/m/v/g exactly once and writes p/m/v exactly once (7 passes over
param bytes with fp32 moments).  ``optax.adamw`` expresses the update as a chain of
whole-tree transforms; XLA usually fuses them, but the fusion is at the compiler's mercy —
measured on the v5e chip this repo benches on, the full train step loses ~790 ms/step to the
apply phase at 0.9B params (benchmarks/decompose.py, step_attrib.py).  This kernel makes the
single pass explicit: one Pallas grid over each leaf computes m', v', bias corrections,
decoupled weight decay, and the parameter update in VMEM, streaming HBM at full rate.

Integration: :class:`FusedAdamW` quacks like an ``optax.GradientTransformation`` (``init`` /
``update``) so every existing code path works, and additionally exposes
``fused_apply(grads, state, params) -> (new_params, new_state)`` which
``Accelerator.build_train_step`` uses when present — fusing what optax's API forces apart
(``update`` then ``apply_updates`` = one extra full read+write of the update tree).

Layout: a leaf is processed by the kernel when its trailing dimension work-reshapes to
lanes of 128 (any leaf with ``size % 1024 == 0`` — all matmul weights; stacked scan leaves
included).  Small/odd leaves (norm gains, biases) fall back to the identical jnp math —
negligible traffic.  ``mu_dtype=bfloat16`` stores the first moment in bf16 (t5x-style),
cutting standing optimizer HBM by 25%.

Low-precision optimizer STATE (the MS-AMP analog — the reference's third fp8 backend
keeps fp8 master weights / optimizer state, ``/root/reference/src/accelerate/accelerator.py:2164``,
``dataclasses.py:1235-1242``): ``mu_dtype``/``nu_dtype`` may be ``float8_e4m3fn`` /
``float8_e5m2``.  fp8 moments are stored with a per-tensor fp32 scale living beside them
in :class:`ScaledAdamState` (the ``DelayedScalingState`` pattern from ``ops/fp8.py``,
but with CURRENT scaling — the true amax of the freshly computed moment, available for
free since the moment is in registers when quantizing).  fp8-stated leaves take the
plain-XLA path rather than the Pallas kernel: the per-leaf math is a single fused
map+amax-reduce XLA program (one read of p/m/v/g, one write of p/m/v + a scalar), and
GSPMD partitions it under any sharding — including FSDP/TP layouts — without shard_map.
At 0.9B params, fp8 mu + fp8 nu cut standing optimizer HBM from ~7.1 GB (fp32) to
~1.8 GB and the apply's moment traffic by 4x, directly attacking the bandwidth-bound
apply the decompose isolated (~790 ms/step).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import interpret_default as _interpret_default
from ..utils.jax_compat import shard_map as _shard_map, tpu_compiler_params as _tpu_compiler_params

__all__ = ["FusedAdamW", "fused_adamw", "ScaledAdamState"]


class ScaledAdamState(NamedTuple):
    """AdamW state whose moments may be stored in fp8 with per-tensor fp32 scales
    living beside them (the MS-AMP low-precision-optimizer-state analog; reference
    ``accelerator.py:2164``). ``mu_scale``/``nu_scale`` mirror the param tree with one
    fp32 scalar per leaf, or are ``None`` when that moment is full/bf16 precision.
    Same leading fields as ``optax.ScaleByAdamState`` so ``state[0].mu``-style
    introspection and checkpointing (a plain pytree) work unchanged."""

    count: Any
    mu: Any
    nu: Any
    mu_scale: Any = None
    nu_scale: Any = None


_F8_MAX = {
    jnp.dtype(jnp.float8_e4m3fn): 448.0,
    jnp.dtype(jnp.float8_e5m2): 57344.0,
}


def _is_f8(dt) -> bool:
    return dt is not None and jnp.dtype(dt) in _F8_MAX


def _quant_f8(x32: jax.Array, dt) -> tuple[jax.Array, jax.Array]:
    """Per-tensor CURRENT scaling: scale = amax/emax of the value being stored (the
    value is already in registers — no extra HBM pass, unlike delayed scaling which
    exists to avoid exactly that pass for activations)."""
    emax = _F8_MAX[jnp.dtype(dt)]
    amax = jnp.max(jnp.abs(x32))
    scale = (jnp.maximum(amax, 1e-30) / emax).astype(jnp.float32)
    return (x32 / scale).astype(dt), scale


def _dequant_f8(x: jax.Array, scale: jax.Array) -> jax.Array:
    return x.astype(jnp.float32) * scale

_LANES = 1024  # 8 sublanes x 128 lanes: the fp32 VMEM tile; every kernel row is one tile


def _adamw_kernel(
    sc_ref, p_ref, m_ref, v_ref, g_ref, po_ref, mo_ref, vo_ref, *, b1, b2, eps, wd
):
    """One block: m' = b1*m + (1-b1)*g; v' = b2*v + (1-b2)*g^2;
    p' = p - lr*(mhat/(sqrt(vhat)+eps) + wd*p)  (decoupled AdamW decay).

    ``sc_ref`` (SMEM, [4]) carries the traced scalars: [grad_scale (clip), lr,
    (1-b1^t), (1-b2^t)] — hyperparameters that vary per step stay out of the
    compiled kernel constant pool.

    Expression order mirrors ``optax.adamw`` exactly (incl. division by the bias
    correction), making fp32-moment trajectories bit-identical.  With
    ``mu_dtype=bfloat16`` the TPU VPU keeps the ``b1 * m`` product in fp32 where optax
    rounds it to bf16 first — one rounding tighter, so trajectories agree only to bf16
    ulp (see tests/test_fused_optim.py tolerances).
    """
    gscale = sc_ref[0]
    lr = sc_ref[1]
    bc1 = sc_ref[2]
    bc2 = sc_ref[3]
    g = g_ref[:].astype(jnp.float32) * gscale
    p = p_ref[:].astype(jnp.float32)
    m_new = (1.0 - b1) * g + b1 * m_ref[:]   # promotion order = optax update_moment
    v_new = (1.0 - b2) * (g * g) + b2 * v_ref[:]
    mhat = m_new / bc1
    vhat = v_new / bc2
    update = mhat / (jnp.sqrt(vhat) + eps) + wd * p
    po_ref[:] = (p - lr * update).astype(po_ref.dtype)
    mo_ref[:] = m_new.astype(mo_ref.dtype)
    vo_ref[:] = v_new.astype(vo_ref.dtype)


_VMEM_BUDGET = 12 * 2**20  # bytes a block's refs may claim; v5e VMEM is ~16 MB total


def _leaf_fused(p, m, v, g, scalars, *, b1, b2, eps, wd, block_rows, interpret):
    """Run the kernel over one leaf reshaped to [rows, 1024].

    Rows that don't divide by a near-``block_rows`` factor are PADDED up to a multiple
    (the update math is elementwise, so padded rows compute garbage that is sliced off) —
    the old largest-divisor rule degraded to block_rows=1 for prime row counts, turning
    one launch into thousands of [1, 1024] grid steps.

    ``block_rows`` is additionally capped by a VMEM budget: the grid streams 7 refs
    (p/m/v/g in, p/m/v out) and Pallas double-buffers each, so an all-fp32 512-row
    block claims 2 x 512 x 1024 x 28 B ~= 29 MB — past the v5e's ~16 MB VMEM. That is
    what 500'd the 2026-08-01 window's ``opt_fused_adamw`` rows at bench shapes while
    the small-leaf probe (rows=128, 7.3 MB) compiled fine: the remote compile helper
    reports any Mosaic failure as a bare 'subprocess exit code 1'. The cap is
    dtype-aware, so bf16 moments earn proportionally taller blocks."""
    shape, dtype = p.shape, p.dtype
    rows = p.size // _LANES
    bytes_per_row = _LANES * (
        2 * p.dtype.itemsize + 2 * m.dtype.itemsize + 2 * v.dtype.itemsize
        + g.dtype.itemsize
    )
    vmem_rows = max(8, _VMEM_BUDGET // (2 * bytes_per_row) // 8 * 8)
    cap = min(block_rows, rows, vmem_rows)
    br = cap
    pad = 0
    while rows % br:  # largest divisor <= cap keeps the grid exact (no masking)
        br -= 1
    if br < cap // 4:
        # No decent divisor (prime-ish rows): pad to a cap multiple instead.
        br = cap
        pad = (-rows) % br
    grid = ((rows + pad) // br,)

    def _prep(a):
        a2 = a.reshape(rows, _LANES)
        if pad:
            a2 = jnp.pad(a2, ((0, pad), (0, 0)))
        return a2

    p2, m2, v2, g2 = _prep(p), _prep(m), _prep(v), _prep(g)
    rows += pad
    kernel = functools.partial(_adamw_kernel, b1=b1, b2=b2, eps=eps, wd=wd)
    spec = pl.BlockSpec((br, _LANES), lambda i: (i, 0))
    po, mo, vo = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            spec, spec, spec, spec,
        ],
        out_specs=[spec, spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows, _LANES), dtype),
            jax.ShapeDtypeStruct((rows, _LANES), m.dtype),
            jax.ShapeDtypeStruct((rows, _LANES), v.dtype),
        ],
        compiler_params=_tpu_compiler_params(
            dimension_semantics=(pltpu.PARALLEL,),
        ),
        interpret=interpret,
    )(scalars, p2, m2, v2, g2)
    if pad:
        po, mo, vo = po[:-pad], mo[:-pad], vo[:-pad]
    return po.reshape(shape), mo.reshape(shape), vo.reshape(shape)


def _leaf_xla(p, m, v, g, scalars, *, b1, b2, eps, wd):
    """Identical math for leaves the kernel layout doesn't cover (small/odd shapes)."""
    gscale, lr, bc1, bc2 = scalars[0], scalars[1], scalars[2], scalars[3]
    g = g.astype(jnp.float32) * gscale
    p32 = p.astype(jnp.float32)
    m_new = (1.0 - b1) * g + b1 * m     # promotion order = optax update_moment
    v_new = (1.0 - b2) * (g * g) + b2 * v
    update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps) + wd * p32
    p_new = (p32 - lr * update).astype(p.dtype)
    return p_new, m_new.astype(m.dtype), v_new.astype(v.dtype)


def _leaf_xla_scaled(p, m, v, g, scalars, m_scale, v_scale, *, b1, b2, eps, wd):
    """AdamW update for a leaf whose moments are stored scaled-fp8.

    One fused XLA map+amax-reduce over the leaf (GSPMD-partitionable under any layout,
    so fp8-stated leaves never need shard_map): dequantize the incoming moments with
    last step's per-tensor scale, do the fp32 update, requantize with the fresh amax.
    Returns ``(p', m', v', m_scale', v_scale')`` — scale entries are None for a moment
    that isn't fp8."""
    gscale, lr, bc1, bc2 = scalars[0], scalars[1], scalars[2], scalars[3]
    g = g.astype(jnp.float32) * gscale
    p32 = p.astype(jnp.float32)
    m32 = _dequant_f8(m, m_scale) if m_scale is not None else m
    v32 = _dequant_f8(v, v_scale) if v_scale is not None else v
    m_new = (1.0 - b1) * g + b1 * m32
    v_new = (1.0 - b2) * (g * g) + b2 * v32
    update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps) + wd * p32
    p_new = (p32 - lr * update).astype(p.dtype)
    if m_scale is not None:
        m_out, m_scale_out = _quant_f8(m_new, m.dtype)
    else:
        m_out, m_scale_out = m_new.astype(m.dtype), None
    if v_scale is not None:
        v_out, v_scale_out = _quant_f8(v_new, v.dtype)
    else:
        v_out, v_scale_out = v_new.astype(v.dtype), None
    return p_new, m_out, v_out, m_scale_out, v_scale_out


@dataclasses.dataclass
class FusedAdamW:
    """Drop-in AdamW with a fused Pallas apply.

    Quacks like ``optax.GradientTransformation`` (``init``/``update``) so
    ``Accelerator.prepare`` / checkpointing / schedulers work unchanged, while
    ``build_train_step`` detects ``fused_apply`` and uses the single-pass kernel.
    ``learning_rate`` may be a float or an optax schedule (called on the step count).
    """

    learning_rate: Union[float, Callable[[Any], Any]] = 1e-3
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 1e-4
    mu_dtype: Optional[Any] = None
    nu_dtype: Optional[Any] = None
    block_rows: int = 512
    interpret: Optional[bool] = None
    # ``False`` routes every leaf of ``fused_apply`` through the identical-math XLA
    # update (``_leaf_xla``) while keeping the single-call donation/shard_map framing —
    # an A/B lever for transports whose compile service rejects the Pallas program
    # (2026-08-01 window: remote-compile HTTP 500 on the kernel, flash compiled fine).
    use_kernel: Optional[bool] = None

    # -------------------------------------------------------------- optax-compatible API
    def init(self, params):
        mu_dtype = self.mu_dtype or None
        nu_dtype = self.nu_dtype or None

        # zeros_LIKE, not zeros: each moment leaf must inherit its param's sharding —
        # create_train_state relies on that invariant, and at 0.9B params an unsharded
        # fp32 mu+nu is ~7 GB landing on one device.
        mu = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=mu_dtype or p.dtype), params
        )
        nu = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=nu_dtype or p.dtype), params
        )
        count = jnp.zeros((), jnp.int32)
        if not (_is_f8(mu_dtype) or _is_f8(nu_dtype)):
            return optax.ScaleByAdamState(count=count, mu=mu, nu=nu)
        ones = lambda: jax.tree_util.tree_map(  # noqa: E731
            lambda _: jnp.ones((), jnp.float32), params
        )
        return ScaledAdamState(
            count=count, mu=mu, nu=nu,
            mu_scale=ones() if _is_f8(mu_dtype) else None,
            nu_scale=ones() if _is_f8(nu_dtype) else None,
        )

    def _scalars(self, count, grad_scale):
        count_f = (count + 1).astype(jnp.float32)
        lr = self.learning_rate(count) if callable(self.learning_rate) else self.learning_rate
        return jnp.stack([
            jnp.asarray(grad_scale, jnp.float32),
            jnp.asarray(lr, jnp.float32),
            1.0 - jnp.asarray(self.b1, jnp.float32) ** count_f,
            1.0 - jnp.asarray(self.b2, jnp.float32) ** count_f,
        ])

    def update(self, grads, state, params=None):
        """optax-protocol path (returns an update tree) in PURE XLA — no Pallas.

        This is the route ``build_train_step`` takes for layouts the kernel cannot
        partition (ZeRO-1/2, where opt state and params have different shardings), so it
        must stay an ordinary partitionable XLA program: same math via ``_leaf_xla`` on
        every leaf, GSPMD free to shard it however the state is laid out.
        """
        if params is None:
            raise ValueError("FusedAdamW.update requires params (AdamW decays weights).")
        scalars = self._scalars(state.count, 1.0)
        kw = dict(b1=self.b1, b2=self.b2, eps=self.eps, wd=self.weight_decay)

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_ms, flat_vs = self._flat_scales(state, treedef, len(flat_p))

        def one(p, m, v, g, ms, vs):
            if ms is not None or vs is not None:
                return _leaf_xla_scaled(p, m, v, g, scalars, ms, vs, **kw)
            return (*_leaf_xla(p, m, v, g, scalars, **kw), None, None)

        out = [
            one(p, m, v, g, ms, vs)
            for p, m, v, g, ms, vs in zip(
                flat_p,
                treedef.flatten_up_to(state.mu),
                treedef.flatten_up_to(state.nu),
                treedef.flatten_up_to(grads),
                flat_ms, flat_vs,
            )
        ]
        updates = treedef.unflatten(
            [
                (n.astype(jnp.float32) - p.astype(jnp.float32)).astype(p.dtype)
                for (n, *_), p in zip(out, flat_p)
            ]
        )
        return updates, self._rebuild_state(state, treedef, out)

    def _flat_scales(self, state, treedef, n):
        """Per-leaf (mu_scale, nu_scale) lists — all-None for plain ScaleByAdamState."""
        mu_scale = getattr(state, "mu_scale", None)
        nu_scale = getattr(state, "nu_scale", None)
        flat_ms = treedef.flatten_up_to(mu_scale) if mu_scale is not None else [None] * n
        flat_vs = treedef.flatten_up_to(nu_scale) if nu_scale is not None else [None] * n
        return flat_ms, flat_vs

    def _rebuild_state(self, state, treedef, out):
        """Reassemble the state from per-leaf (p', m', v', m_scale', v_scale') rows,
        preserving the incoming state's type (plain vs scaled)."""
        mu = treedef.unflatten([o[1] for o in out])
        nu = treedef.unflatten([o[2] for o in out])
        if getattr(state, "mu_scale", None) is None and getattr(
            state, "nu_scale", None
        ) is None and not isinstance(state, ScaledAdamState):
            return optax.ScaleByAdamState(count=state.count + 1, mu=mu, nu=nu)
        return ScaledAdamState(
            count=state.count + 1, mu=mu, nu=nu,
            mu_scale=(
                treedef.unflatten([o[3] for o in out])
                if getattr(state, "mu_scale", None) is not None
                else None
            ),
            nu_scale=(
                treedef.unflatten([o[4] for o in out])
                if getattr(state, "nu_scale", None) is not None
                else None
            ),
        )

    # ------------------------------------------------------------------ fused fast path
    def fused_apply(self, grads, state, params, grad_scale=1.0, specs=None, mesh=None):
        """Single-pass apply: ``(new_params, new_state)``.

        ``grad_scale`` folds an already-computed global-norm clip factor into the same
        pass (``build_train_step`` passes it instead of pre-scaling the grad tree, saving
        one full read+write of the gradients).

        ``specs``/``mesh``: per-leaf ``PartitionSpec`` tree for cross-device-sharded
        states (FSDP/ZeRO-3, TP — where p/m/v/g share one layout, the default produced by
        ``create_train_state``). Sharded leaves run the kernel under ``shard_map``: each
        device updates exactly its own shard, no gather, no replication — the fused apply
        IS the ZeRO-3 optimizer step. Leaves whose spec is None/empty run unmapped.
        """
        interpret = self.interpret if self.interpret is not None else _interpret_default()
        scalars = self._scalars(state.count, grad_scale)
        kw = dict(b1=self.b1, b2=self.b2, eps=self.eps, wd=self.weight_decay)

        def local(sc, p, m, v, g):
            # Kernel-vs-fallback decided on the LOCAL (per-shard) shape.
            if self.use_kernel is not False and p.size % _LANES == 0 and p.size > 0:
                return _leaf_fused(
                    p, m, v, g, sc,
                    block_rows=self.block_rows, interpret=interpret, **kw,
                )
            return _leaf_xla(p, m, v, g, sc, **kw)

        def _evenly_divisible(shape, spec) -> bool:
            for dim, axes in zip(shape, spec):
                if axes is None:
                    continue
                axes = axes if isinstance(axes, tuple) else (axes,)
                n = 1
                for a in axes:
                    n *= mesh.shape[a]
                if dim % n:
                    return False
            return True

        def one(p, m, v, g, spec=None, ms=None, vs=None):
            if ms is not None or vs is not None:
                # fp8-stated leaf: one fused XLA map+amax-reduce — GSPMD partitions it
                # under any spec (the amax collective included), so no shard_map and no
                # Pallas here by design (see module docstring).
                return _leaf_xla_scaled(p, m, v, g, scalars, ms, vs, **kw)
            if isinstance(spec, str):  # "opaque": un-expressible layout — plain XLA only
                return (*_leaf_xla(p, m, v, g, scalars, **kw), None, None)
            if spec is not None and mesh is not None and any(a for a in spec):
                if not _evenly_divisible(p.shape, spec):
                    # shard_map needs even shards; GSPMD pads NamedShardings (legal), so
                    # uneven leaves take the identical partitionable XLA math instead.
                    return (*_leaf_xla(p, m, v, g, scalars, **kw), None, None)
                from jax.sharding import PartitionSpec

                mapped = _shard_map(
                    local,
                    mesh=mesh,
                    in_specs=(PartitionSpec(), spec, spec, spec, spec),
                    out_specs=(spec, spec, spec),
                    check_vma=False,  # pallas_call outputs carry no vma info
                )
                return (*mapped(scalars, p, m, v, g), None, None)
            return (*local(scalars, p, m, v, g), None, None)

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        flat_g = treedef.flatten_up_to(grads)
        flat_s = (
            treedef.flatten_up_to(specs) if specs is not None else [None] * len(flat_p)
        )
        flat_ms, flat_vs = self._flat_scales(state, treedef, len(flat_p))
        out = [
            one(p, m, v, g, s, ms, vs)
            for p, m, v, g, s, ms, vs in zip(
                flat_p, flat_m, flat_v, flat_g, flat_s, flat_ms, flat_vs
            )
        ]
        new_params = treedef.unflatten([o[0] for o in out])
        return new_params, self._rebuild_state(state, treedef, out)


def fused_adamw(
    learning_rate: Union[float, Callable] = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 1e-4,
    mu_dtype=None,
    nu_dtype=None,
    use_kernel: Optional[bool] = None,
) -> FusedAdamW:
    """``optax.adamw``-shaped constructor for the fused kernel optimizer.

    ``mu_dtype``/``nu_dtype`` accept ``jnp.bfloat16`` (plain low-precision moment) or
    ``jnp.float8_e4m3fn``/``float8_e5m2`` (scaled-fp8 moment with a per-tensor scale in
    :class:`ScaledAdamState` — the MS-AMP low-precision-optimizer-state analog).
    ``use_kernel=False`` keeps the fused_apply structure but runs the identical-math
    XLA update on every leaf (no Pallas program — see FusedAdamW.use_kernel)."""
    return FusedAdamW(
        learning_rate=learning_rate, b1=b1, b2=b2, eps=eps,
        weight_decay=weight_decay, mu_dtype=mu_dtype, nu_dtype=nu_dtype,
        use_kernel=use_kernel,
    )
