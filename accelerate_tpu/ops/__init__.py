"""Compute & collective ops: in-jit collectives, Pallas kernels, fp8 and quantized matmuls."""

from .fp8 import DelayedScalingState, delayed_scales, fp8_dot, fp8_linear
from .fused_optim import FusedAdamW, fused_adamw
from .fused_xent import fused_cross_entropy, fused_cross_entropy_tp
from .quantization import (
    BnbQuantizationConfig,
    QuantizedWeight,
    dequantize_model,
    dequantize_weight,
    load_and_quantize_model,
    quant_matmul,
    quantize_weight,
)
from .collectives import (
    all_gather,
    all_to_all,
    axis_index,
    axis_size,
    grad_pmean,
    grad_psum,
    pmax,
    pmean,
    pmin,
    ppermute,
    psum,
    reduce_scatter,
)
