"""Compute & collective ops: in-jit collectives and Pallas kernels."""

from .collectives import (
    all_gather,
    all_to_all,
    axis_index,
    axis_size,
    grad_pmean,
    grad_psum,
    pmax,
    pmean,
    pmin,
    ppermute,
    psum,
    reduce_scatter,
)
