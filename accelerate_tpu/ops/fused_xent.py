"""Fused cross-entropy — logits never touch HBM (Pallas, custom VJP).

The reference computes ``lm_head`` logits then ``torch.nn.CrossEntropyLoss`` — at
V=32k, S=2048, B=4 that is a ~1 GB fp32 tensor materialized twice per step (forward
and backward). ``models/llama._chunked_ce`` already bounds this by chunking over the
sequence, but each [B, chunk, V] block still round-trips HBM. This kernel goes the rest
of the way (the CCE / Liger-kernel idea, TPU-style): the score tile ``x_tile @ w_tile``
lives only in VMEM, reduced on the fly into an online logsumexp (exactly the
FlashAttention recurrence with the kv axis replaced by the vocab axis), and the
backward recomputes score tiles while accumulating ``dx``/``dw`` in VMEM scratch —
HBM traffic is just the inputs, outputs, and one fp32 [T] logsumexp residual.

API: ``fused_cross_entropy(x, w, targets)`` → per-token nll ``[T]`` (fp32). Mask and
mean OUTSIDE — autodiff threads the cotangent ``g = mask/denom`` into the kernels.
Optional ``softcap`` matches Gemma-2's final-logit capping (exact 1−tanh² backward).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import interpret_default as _interpret_default
from ..utils.jax_compat import axis_size as _axis_size, tpu_compiler_params as _tpu_compiler_params

__all__ = ["fused_cross_entropy", "fused_cross_entropy_tp"]

_NEG_INF = -1e30


def _raw_scores(x_ref, w_ref):
    return jax.lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _scores(x_ref, w_ref, softcap):
    s = _raw_scores(x_ref, w_ref)
    if softcap:
        s = softcap * jnp.tanh(s / softcap)
    return s


def _col_mask(j, block_v, vocab, bt):
    cols = j * block_v + jax.lax.broadcasted_iota(jnp.int32, (bt, block_v), 1)
    return cols, cols < vocab


def _online_tile(j, t_ref, x_ref, w_ref, m_ref, l_ref, tgt_ref, *, block_v, vocab, softcap):
    """Shared forward tile: fold one [bt, bv] score tile into the online (m, l, tgt)."""

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        tgt_ref[:] = jnp.zeros_like(tgt_ref)

    s = _scores(x_ref, w_ref, softcap)                    # [bt, bv] fp32
    bt = s.shape[0]
    cols, valid = _col_mask(j, block_v, vocab, bt)
    s = jnp.where(valid, s, _NEG_INF)

    m_prev = m_ref[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    l_ref[:] = l_ref[:] * jnp.exp(m_prev - m_new) + jnp.sum(
        jnp.where(valid, jnp.exp(s - m_new), 0.0), axis=1, keepdims=True
    )
    m_ref[:] = m_new
    # The target column lands in exactly one vocab tile; accumulate its (capped) score.
    # `valid` matters for the tp variant: a target id outside this shard's vocab slice
    # must not match a padded column (whose masked score is -inf).
    match = jnp.logical_and(cols == t_ref[:], valid)      # t_ref [bt, 1] broadcasts
    tgt_ref[:] = tgt_ref[:] + jnp.sum(jnp.where(match, s, 0.0), axis=1, keepdims=True)


def _fwd_kernel(t_ref, x_ref, w_ref, nll_ref, lse_ref, m_ref, l_ref, tgt_ref,
                *, block_v, vocab, softcap):
    j = pl.program_id(1)
    nv = pl.num_programs(1)
    _online_tile(j, t_ref, x_ref, w_ref, m_ref, l_ref, tgt_ref,
                 block_v=block_v, vocab=vocab, softcap=softcap)

    @pl.when(j == nv - 1)
    def _finalize():
        lse = m_ref[:] + jnp.log(l_ref[:])
        lse_ref[:] = lse
        nll_ref[:] = lse - tgt_ref[:]


def _fwd_partial_kernel(t_ref, x_ref, w_ref, m_out, l_out, tgt_out, m_ref, l_ref, tgt_ref,
                        *, block_v, vocab, softcap):
    """Partial-statistics variant for vocab-sharded heads: emits the raw online
    (max, sumexp-at-max, target-score) so the caller can merge across shards."""
    j = pl.program_id(1)
    nv = pl.num_programs(1)
    _online_tile(j, t_ref, x_ref, w_ref, m_ref, l_ref, tgt_ref,
                 block_v=block_v, vocab=vocab, softcap=softcap)

    @pl.when(j == nv - 1)
    def _finalize():
        m_out[:] = m_ref[:]
        l_out[:] = l_ref[:]
        tgt_out[:] = tgt_ref[:]


def _bwd_common(s_raw, lse, g, cols, t_ref, vocab, softcap):
    """dlogits for one tile: ``(softmax − onehot) · g``, with the softcap chain rule."""
    if softcap:
        capped = softcap * jnp.tanh(s_raw / softcap)
        chain = 1.0 - (capped / softcap) ** 2             # d(cap·tanh(s/cap))/ds
    else:
        capped, chain = s_raw, None
    valid = cols < vocab
    p = jnp.where(valid, jnp.exp(capped - lse), 0.0)
    onehot = jnp.logical_and(cols == t_ref[:], valid).astype(jnp.float32)
    d = (p - onehot) * g
    if chain is not None:
        d = d * chain
    return d


def _bwd_dx_kernel(t_ref, x_ref, w_ref, lse_ref, g_ref, dx_ref, acc_ref,
                   *, block_v, vocab, softcap):
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    s = _raw_scores(x_ref, w_ref)
    bt = s.shape[0]
    cols, _ = _col_mask(j, block_v, vocab, bt)
    d = _bwd_common(s, lse_ref[:], g_ref[:], cols, t_ref, vocab, softcap)
    acc_ref[:] = acc_ref[:] + jax.lax.dot_general(
        d.astype(w_ref.dtype), w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == nv - 1)
    def _finalize():
        dx_ref[:] = acc_ref[:].astype(dx_ref.dtype)


def _bwd_dw_kernel(t_ref, x_ref, w_ref, lse_ref, g_ref, dw_ref, acc_ref,
                   *, block_v, vocab, softcap):
    # grid (nv, nt): token tiles iterate INNER so dw accumulates in VMEM scratch.
    j = pl.program_id(0)
    i = pl.program_id(1)
    nt = pl.num_programs(1)

    @pl.when(i == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    s = _raw_scores(x_ref, w_ref)
    bt = s.shape[0]
    cols, _ = _col_mask(j, block_v, vocab, bt)
    d = _bwd_common(s, lse_ref[:], g_ref[:], cols, t_ref, vocab, softcap)
    acc_ref[:] = acc_ref[:] + jax.lax.dot_general(
        x_ref[:], d.astype(x_ref.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(i == nt - 1)
    def _finalize():
        dw_ref[:] = acc_ref[:].astype(dw_ref.dtype)


def fused_cross_entropy(
    x: jax.Array,
    w: jax.Array,
    targets: jax.Array,
    softcap: float = 0.0,
    block_t: int = 256,
    block_v: int = 256,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Per-token ``-log p(target)`` for ``logits = x @ w`` without materializing logits.

    x [T, D] (any float dtype; dots run in it), w [D, V], targets [T] int32 → nll [T]
    fp32. Pad/ignored positions: mask the RESULT (a −1 target never matches any column,
    its nll is just lse — finite, safe to mask).
    """
    if interpret is None:
        interpret = _interpret_default()
    T, D = x.shape
    V = w.shape[1]
    Tp = pl.cdiv(T, block_t) * block_t
    Vp = pl.cdiv(V, block_v) * block_v
    # Padding happens OUTSIDE the custom_vjp: jnp.pad is differentiable, so autodiff
    # slices the padded cotangents back down and the kernels only see exact grids.
    if Tp != T:
        x = jnp.pad(x, ((0, Tp - T), (0, 0)))
        targets = jnp.pad(jnp.asarray(targets, jnp.int32), (0, Tp - T),
                          constant_values=-1)
    if Vp != V:
        w = jnp.pad(w, ((0, 0), (0, Vp - V)))
    t2 = jnp.asarray(targets, jnp.int32).reshape(Tp, 1)
    nll = _fce(x, w, t2, V, softcap, block_t, block_v, interpret)
    return nll[:T]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _fce(x, w, t2, vocab, softcap, block_t, block_v, interpret):
    nll, _ = _fce_fwd(x, w, t2, vocab, softcap, block_t, block_v, interpret)
    return nll


def _launch_fwd(kernel_fn, n_outputs, x, w, t2, *, vocab, softcap, block_t, block_v,
                interpret):
    """Shared forward launch (same grid/specs/scratch for both fwd kernel variants —
    they differ only in the kernel fn and how many [Tp, 1] statistics they emit)."""
    Tp, D = x.shape
    Vp = w.shape[1]
    nt, nv = Tp // block_t, Vp // block_v
    stat_spec = pl.BlockSpec((block_t, 1), lambda i, j: (i, 0))
    return pl.pallas_call(
        functools.partial(kernel_fn, block_v=block_v, vocab=vocab, softcap=softcap),
        grid=(nt, nv),
        in_specs=[
            stat_spec,
            pl.BlockSpec((block_t, D), lambda i, j: (i, 0)),
            pl.BlockSpec((D, block_v), lambda i, j: (0, j)),
        ],
        out_specs=[stat_spec] * n_outputs,
        out_shape=[jax.ShapeDtypeStruct((Tp, 1), jnp.float32)] * n_outputs,
        scratch_shapes=[pltpu.VMEM((block_t, 1), jnp.float32)] * 3,
        compiler_params=_tpu_compiler_params(
            dimension_semantics=(pltpu.PARALLEL, pltpu.ARBITRARY),
        ),
        interpret=interpret,
    )(t2, x, w)


def _fce_fwd(x, w, t2, vocab, softcap, block_t, block_v, interpret):
    nll, lse = _launch_fwd(
        _fwd_kernel, 2, x, w, t2, vocab=vocab, softcap=softcap,
        block_t=block_t, block_v=block_v, interpret=interpret,
    )
    return nll[:, 0], (x, w, t2, lse)


def _fce_bwd(vocab, softcap, block_t, block_v, interpret, res, g):
    x, w, t2, lse = res                # padded shapes throughout
    Tp, D = x.shape
    Vp = w.shape[1]
    nt, nv = Tp // block_t, Vp // block_v
    g2 = jnp.asarray(g, jnp.float32).reshape(Tp, 1)

    common = dict(block_v=block_v, vocab=vocab, softcap=softcap)
    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, **common),
        grid=(nt, nv),
        in_specs=[
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_t, D), lambda i, j: (i, 0)),
            pl.BlockSpec((D, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_t, D), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((Tp, D), x.dtype),
        scratch_shapes=[pltpu.VMEM((block_t, D), jnp.float32)],
        compiler_params=_tpu_compiler_params(
            dimension_semantics=(pltpu.PARALLEL, pltpu.ARBITRARY),
        ),
        interpret=interpret,
    )(t2, x, w, lse, g2)

    dw = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, **common),
        grid=(nv, nt),
        in_specs=[
            pl.BlockSpec((block_t, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((block_t, D), lambda j, i: (i, 0)),
            pl.BlockSpec((D, block_v), lambda j, i: (0, j)),
            pl.BlockSpec((block_t, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((block_t, 1), lambda j, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((D, block_v), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((D, Vp), w.dtype),
        scratch_shapes=[pltpu.VMEM((D, block_v), jnp.float32)],
        compiler_params=_tpu_compiler_params(
            dimension_semantics=(pltpu.PARALLEL, pltpu.ARBITRARY),
        ),
        interpret=interpret,
    )(t2, x, w, lse, g2)

    return dx, dw, None


_fce.defvjp(_fce_fwd, _fce_bwd)


# ------------------------------------------------------------ vocab-sharded (tp) variant
def fused_cross_entropy_tp(
    x: jax.Array,
    w_shard: jax.Array,
    targets: jax.Array,
    axis_name,
    softcap: float = 0.0,
    block_t: int = 256,
    block_v: int = 256,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused CE for a TENSOR-PARALLEL (vocab-sharded) head — call INSIDE shard_map,
    which MUST be built with ``check_vma=False`` (pallas outputs carry no vma info, and
    the backward compensates for that mode's split-cotangent adjoint convention — under
    ``check_vma=True`` gradients would come back scaled by the axis size).

    Each shard holds ``w_shard`` [D, V/ntp] (vocab-major order along ``axis_name``) and
    the full ``targets`` (global ids). Shards compute local online statistics with the
    kernel, then merge across ``axis_name``: ``lse = pmax/psum`` logsumexp merge, target
    score via psum (exactly one shard owns each target id). The backward runs the local
    dx/dw kernels against the GLOBAL lse — dw stays shard-local, dx partials are summed
    by shard_map's transpose (x enters replicated over ``axis_name``).
    """
    if interpret is None:
        interpret = _interpret_default()
    T, D = x.shape
    Vl = w_shard.shape[1]
    idx = jax.lax.axis_index(axis_name)
    t_local = jnp.asarray(targets, jnp.int32) - idx * Vl  # non-owners go out of range
    Tp = pl.cdiv(T, block_t) * block_t
    Vp = pl.cdiv(Vl, block_v) * block_v
    if Tp != T:
        x = jnp.pad(x, ((0, Tp - T), (0, 0)))
        t_local = jnp.pad(t_local, (0, Tp - T), constant_values=-1)
    if Vp != Vl:
        w_shard = jnp.pad(w_shard, ((0, 0), (0, Vp - Vl)))
    t2 = t_local.reshape(Tp, 1)
    nll = _fce_tp(x, w_shard, t2, Vl, softcap, block_t, block_v, interpret, axis_name)
    return nll[:T]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _fce_tp(x, w, t2, vocab, softcap, block_t, block_v, interpret, axis_name):
    nll, _ = _fce_tp_fwd(x, w, t2, vocab, softcap, block_t, block_v, interpret, axis_name)
    return nll


def _fce_tp_fwd(x, w, t2, vocab, softcap, block_t, block_v, interpret, axis_name):
    m, l, tgt = _launch_fwd(
        _fwd_partial_kernel, 3, x, w, t2, vocab=vocab, softcap=softcap,
        block_t=block_t, block_v=block_v, interpret=interpret,
    )

    # Cross-shard logsumexp merge (the ring-attention recurrence over the tp axis).
    m_g = jax.lax.pmax(m, axis_name)
    l_g = jax.lax.psum(l * jnp.exp(m - m_g), axis_name)
    lse = m_g + jnp.log(l_g)
    tgt_g = jax.lax.psum(tgt, axis_name)  # exactly one shard owns each target id
    nll = (lse - tgt_g)[:, 0]
    return nll, (x, w, t2, lse)


def _fce_tp_bwd(vocab, softcap, block_t, block_v, interpret, axis_name, res, g):
    # The local backward is IDENTICAL to the single-shard one once lse is global:
    # each shard differentiates only its vocab slice; shard_map's transpose psums the
    # x-cotangents (x is replicated over axis_name), dw stays local.
    #
    # check_vma=False adjoint convention: a replicated (out_specs P()) output's
    # cotangent arrives SPLIT across the axis (g/n per shard — the psum adjoint).
    # Scale it back so dx = psum(partials·g) and the shard-local dw see the true g.
    # tests/test_fused_xent.py::test_tp_variant_matches_dense pins this convention.
    g = g * _axis_size(axis_name)
    dx, dw, _ = _fce_bwd(vocab, softcap, block_t, block_v, interpret, res, g)
    return dx, dw, None


_fce_tp.defvjp(_fce_tp_fwd, _fce_tp_bwd)
