"""FlashAttention-2 for TPU in Pallas (forward + backward custom VJP).

The reference outsources attention entirely to torch/CUDA libraries; on TPU this kernel is the
framework's hot-path attention (SURVEY.md §7: "Pallas flash/splash attention"). Standard
online-softmax tiling: the (S×T) score matrix never materializes in HBM — per-block partial
maxima/sums ride in VMEM scratch across the kv-grid dimension (FlashAttention-2 schedule).

Layout: q [B, H, S, hd], k/v [B, K, T, hd] with K dividing H (the public wrapper handles the
user-facing [B, S, H, hd] layout). GQA is native: the kernels' BlockSpec index maps send q
head h to kv head h // (H//K), and the dk/dv kernel accumulates each kv head's gradient over
its whole query group in VMEM — repeated K/V never exist in HBM. Sequence lengths are padded
to block multiples; padded keys are masked via global column indices, padded query rows
sliced off by the wrapper.

**Position offsets**: the kernels take traced ``q_offset``/``kv_offset`` scalars (SMEM) giving
the global position of the local block — this is what lets ``ops/ring_attention.py`` reuse
these exact kernels per ring step with correct cross-device causal masking. The raw ``_fwd`` /
``_bwd_dq`` / ``_bwd_dkv`` entry points (returning/consuming lse and delta) are the building
blocks for the ring; ``flash_attention`` is the single-device public API.

TPU-specific structure (the r2 on-chip decompose showed the first version of this kernel
running at ~1/5 the throughput of plain XLA attention; these three choices close it):

- **Lane-replicated softmax state.** The running max ``m`` and sum ``l`` live in VMEM as
  [block_q, 128] with every lane carrying the same value, so the per-step rescale math runs
  on full native (8,128) VPU registers and broadcasting into the [block_q, block_k] score
  tile is a cheap ``jnp.tile`` of a native register instead of a 1-lane → 128-lane relayout.
  The backward kernels read lse/delta lane-replicated the same way.
- **Mask-free interior tiles.** For causal attention only the tiles the diagonal actually
  crosses need the iota row/col mask; tiles entirely below the diagonal (the majority at
  long S) skip mask construction, the select, and the zero-fill entirely — splash-attention
  style tile classing, decided per grid step from the SMEM offsets.
- **Grid semantics + cost estimate.** (batch, head, q-block) grid dimensions are declared
  PARALLEL (only the kv dimension carries scratch state and stays ARBITRARY), and each
  ``pallas_call`` carries a ``pl.CostEstimate`` so XLA's scheduler sees the real arithmetic
  intensity. ``ACCEL_FLASH_DIMSEM=0`` disables the semantics for A/B measurement.

Runs in interpreter mode on CPU (tests) and compiled on TPU. Block sizes default to 256×512
(see ``_DEFAULT_BLOCK_Q/K``); hd should be a multiple of 128 for peak efficiency (llama3:
hd=128). Sweep overrides: ACCEL_FLASH_BLOCK_Q / ACCEL_FLASH_BLOCK_K.
"""

from __future__ import annotations

import functools
import math
from ..utils.jax_compat import tpu_compiler_params as _tpu_compiler_params
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import interpret_default as _interpret_default

__all__ = ["flash_attention"]

_NEG_INF = -1e30
_LANES = 128  # native VPU lane count: softmax state is replicated across lanes


# Default tile sizes. The grid iterates sequentially on the TensorCore, so per-step fixed
# overhead (semaphores, block DMA setup) is paid nq*nk times per (batch, head): 128x128 tiles
# at S=2048 mean 256 steps/head of mostly overhead. 512x512 is the r2 ON-CHIP sweep best
# (v5e, llama-0.9B b4 seq2048: blocks512 0.1937 MFU vs blocks128 0.135, blocks256x1024
# 0.161 — PERF_NOTES.md); the working set (q/k/v 3x512KB bf16 + fp32 acc/s ~1.3MB) stays
# well under VMEM. Baked in as the default because the round driver resets the sweep
# output the auto-adoption would otherwise replay the tuning from.
# Env overrides allow per-chip tuning without code changes (used by bench sweeps).
def _env_block(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else default
    except ValueError:
        import warnings

        warnings.warn(f"{name}={raw!r} is not an int; using default {default}")
        return default


_DEFAULT_BLOCK_Q = _env_block("ACCEL_FLASH_BLOCK_Q", 512)
_DEFAULT_BLOCK_K = _env_block("ACCEL_FLASH_BLOCK_K", 512)


def _dim_semantics(n_parallel: int, n_arbitrary: int):
    """Mosaic grid-dimension semantics: the leading (batch/head/row-block) dims carry no
    scratch state and may be reordered/pipelined freely (PARALLEL); the trailing dims
    accumulate into VMEM scratch across iterations and must stay sequential (ARBITRARY).
    Default ON (the official jax flash kernel ships this unconditionally);
    ACCEL_FLASH_DIMSEM=0 turns it off for A/B rows in the bench sweep."""
    if os.environ.get("ACCEL_FLASH_DIMSEM", "1") == "0":
        return None
    return _tpu_compiler_params(
        dimension_semantics=("parallel",) * n_parallel + ("arbitrary",) * n_arbitrary
    )


def _cost(flops: float, bytes_accessed: float, transcendentals: float):
    return pl.CostEstimate(
        flops=int(flops), bytes_accessed=int(bytes_accessed),
        transcendentals=int(transcendentals),
    )


def _scalar(x) -> jax.Array:
    return jnp.asarray(x, dtype=jnp.int32).reshape(1, 1)


def _smem_scalar_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _lane_tile(x, cols):
    """Broadcast lane-replicated state [rows, _LANES] across a tile [rows, cols] —
    full-register tile then slice, never a 1-lane relayout. Handles any cols (ceil-tile
    + slice for non-multiples of 128, e.g. head_dim 192)."""
    if cols == _LANES:
        return x
    if cols < _LANES:
        return x[:, :cols]
    tiled = jnp.tile(x, (1, pl.cdiv(cols, _LANES)))
    return tiled if tiled.shape[1] == cols else tiled[:, :cols]


def _tile_mask(*, causal, window, has_segments, kv_pad, block_q, block_k,
               q_global, k_global, k_local, kv_len, q_seg_ref=None, kv_seg_ref=None):
    """Build the [block_q, block_k] validity mask for a tile whose top-left element sits at
    global (q_global, k_global) and local kv column ``k_local`` (padding is local).
    Returns None when no constraint applies (interior tile)."""
    mask = None

    def _and(m, c):
        return c if m is None else jnp.logical_and(m, c)

    if kv_pad:
        col_local = k_local + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = _and(mask, col_local < kv_len)
    if causal or window:
        row = q_global + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        col = k_global + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        if causal:
            mask = _and(mask, col <= row)
        if window:
            mask = _and(mask, col > row - window)
    if has_segments:
        sq = q_seg_ref[0][:, None]
        sk = kv_seg_ref[0][None, :]
        mask = _and(mask, jnp.logical_and(sq == sk, sk != 0))
    return mask


# ------------------------------------------------------------------------------ forward
def _fwd_kernel(
    q_off_ref, kv_off_ref, *refs,
    sm_scale, causal, block_q, block_k, kv_len, kv_pad, has_segments, window, softcap,
):
    if has_segments:
        (q_seg_ref, kv_seg_ref, q_ref, k_ref, v_ref,
         o_ref, lse_ref, acc_ref, m_ref, l_ref) = refs
    else:
        q_seg_ref = kv_seg_ref = None
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    i = pl.program_id(2)  # q block
    j = pl.program_id(3)  # kv block
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = i * block_q
    k_start = j * block_k
    q_off = q_off_ref[0, 0]
    kv_off = kv_off_ref[0, 0]
    q_global = q_off + q_start        # global position of this tile's first row
    k_global = kv_off + k_start       # global position of this tile's first col
    # Causal: skip kv tiles strictly above the diagonal band (in global positions).
    needed = jnp.logical_or(
        jnp.asarray(not causal), k_global <= q_global + block_q - 1
    )
    if window:
        # Sliding window: also skip kv tiles entirely BELOW the band (col <= row - window
        # for every pair in the tile) — long-context Mistral-style attention never touches
        # those tiles at all.
        needed = jnp.logical_and(needed, k_global + block_k - 1 > q_global - window)

    # Tile classing: interior tiles (diagonal doesn't cross, window band doesn't clip,
    # no kv padding, no segment ids) take the mask-free fast path.
    interior = jnp.asarray(not (has_segments or kv_pad))
    if causal:
        interior = jnp.logical_and(interior, k_global + block_k - 1 <= q_global)
    if window:
        interior = jnp.logical_and(interior, k_global > q_global + block_q - 1 - window)

    def _accumulate(s, mask):
        """Online-softmax update; all state lane-replicated [block_q, _LANES]."""
        if mask is not None:
            s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_ref[:]                                   # [bq, LANES]
        m_curr = jnp.max(s, axis=1)[:, None]                # [bq, 1]
        m_next = jnp.maximum(m_prev, m_curr)                # [bq, LANES]
        p = jnp.exp(s - _lane_tile(m_next, block_k))        # [bq, bk] fp32
        if mask is not None:
            # On a FULLY-masked row (packed-padding slots) every s equals _NEG_INF and so
            # does m_next, making exp(s - m_next) = 1 — the row sum l must still be 0 so
            # the finalize step emits zeros / -inf lse.
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_next)                    # [bq, LANES]
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1)[:, None]
        v = v_ref[0, 0]
        acc_ref[:] = acc_ref[:] * _lane_tile(alpha, acc_ref.shape[1]) + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_next

    def _scores():
        # Dots run in the INPUT dtype with fp32 accumulation (preferred_element_type):
        # bf16 inputs hit the MXU at full bf16 rate (an upfront fp32 cast would halve it);
        # fp32 inputs keep full-precision parity with the XLA reference path.
        q = q_ref[0, 0]                      # [block_q, hd]
        k = k_ref[0, 0]                      # [block_k, hd]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [block_q, block_k] fp32
        if softcap:  # Gemma-style capping: s = cap*tanh(s/cap)
            s = softcap * jnp.tanh(s / softcap)
        return s

    @pl.when(jnp.logical_and(needed, interior))
    def _compute_fast():
        _accumulate(_scores(), None)

    @pl.when(jnp.logical_and(needed, jnp.logical_not(interior)))
    def _compute_masked():
        mask = _tile_mask(
            causal=causal, window=window, has_segments=has_segments, kv_pad=kv_pad,
            block_q=block_q, block_k=block_k, q_global=q_global, k_global=k_global,
            k_local=k_start, kv_len=kv_len, q_seg_ref=q_seg_ref, kv_seg_ref=kv_seg_ref,
        )
        _accumulate(_scores(), mask)

    @pl.when(j == nk - 1)
    def _finalize():
        l = l_ref[:]                                        # [bq, LANES] replicated
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / _lane_tile(l_safe, acc_ref.shape[1])).astype(o_ref.dtype)
        # lse = -inf where no key attended (fully-masked row) so ring merging ignores it.
        lse = jnp.where(l == 0.0, _NEG_INF, m_ref[:] + jnp.log(l_safe))
        lse_ref[0, 0] = lse                                  # [bq, LANES] replicated


def _seg_blocks(segments, Sp, Tp):
    """Pad + split packed segment ids into (q_seg [B,Sp], kv_seg [B,Tp]) int32 (pad = 0).

    ``segments`` is either one [B,S] array (self-attention: both sides share it) or a
    ``(q_seg [B,S], kv_seg [B,T])`` pair — the ring/allgather SP case, where the kv block
    comes from another sequence shard and carries its own segment ids."""
    if isinstance(segments, (tuple, list)):
        q_raw, kv_raw = segments
    else:
        q_raw = kv_raw = segments
    q_raw = jnp.asarray(q_raw, jnp.int32)
    kv_raw = jnp.asarray(kv_raw, jnp.int32)
    q_seg = jnp.pad(q_raw, ((0, 0), (0, Sp - q_raw.shape[1])))
    kv_seg = jnp.pad(kv_raw, ((0, 0), (0, Tp - kv_raw.shape[1])))
    return q_seg, kv_seg


def _fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret, q_offset=0, kv_offset=0,
         segments=None, window=0, softcap=0.0):
    """Raw forward: q [B,H,S,hd], k/v [B,K,T,hd] (K divides H — GQA resolved IN the BlockSpec
    index maps, never via a materialized head repeat) → (o [B,H,S,hd], lse [B,H,S] fp32).
    Differentiation-free."""
    B, H, S, hd = q.shape
    K = k.shape[1]
    reps = H // K
    T = k.shape[2]
    nq = pl.cdiv(S, block_q)
    nk = pl.cdiv(T, block_k)
    Sp, Tp = nq * block_q, nk * block_k
    q = _pad_seq(q, Sp)
    k = _pad_seq(k, Tp)
    v = _pad_seq(v, Tp)
    has_segments = segments is not None

    kernel = functools.partial(
        _fwd_kernel,
        sm_scale=sm_scale, causal=causal, block_q=block_q, block_k=block_k, kv_len=T,
        kv_pad=(Tp != T), has_segments=has_segments, window=window, softcap=softcap,
    )
    seg_specs, seg_args = [], []
    if has_segments:
        q_seg, kv_seg = _seg_blocks(segments, Sp, Tp)
        seg_specs = [
            pl.BlockSpec((1, block_q), lambda b, h, i, j: (b, i)),
            pl.BlockSpec((1, block_k), lambda b, h, i, j: (b, j)),
        ]
        seg_args = [q_seg, kv_seg]
    # fwd cost: qk^T + pv dots (causal ≈ half the tiles), exp over the score tiles.
    dot_flops = 4 * B * H * Sp * Tp * hd * (0.5 if causal else 1.0)
    o, lse = pl.pallas_call(
        kernel,
        grid=(B, H, nq, nk),
        in_specs=[
            _smem_scalar_spec(),
            _smem_scalar_spec(),
            *seg_specs,
            pl.BlockSpec((1, 1, block_q, hd), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, hd), lambda b, h, i, j: (b, h // reps, j, 0)),
            pl.BlockSpec((1, 1, block_k, hd), lambda b, h, i, j: (b, h // reps, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, hd), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, _LANES), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sp, hd), q.dtype),
            jax.ShapeDtypeStruct((B, H, Sp, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, hd), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=_dim_semantics(3, 1),
        cost_estimate=_cost(
            dot_flops,
            q.size * q.dtype.itemsize + (k.size + v.size) * k.dtype.itemsize * reps
            + B * H * Sp * hd * q.dtype.itemsize,
            B * H * Sp * Tp * (0.5 if causal else 1.0),
        ),
        interpret=interpret,
    )(_scalar(q_offset), _scalar(kv_offset), *seg_args, q, k, v)
    return o[:, :, :S], lse[:, :, :S, 0]


# ------------------------------------------------------------------------------ backward
def _bwd_dq_kernel(
    q_off_ref, kv_off_ref, *refs,
    sm_scale, causal, block_q, block_k, kv_len, kv_pad, has_segments, window, softcap,
):
    if has_segments:
        (q_seg_ref, kv_seg_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_acc) = refs
    else:
        q_seg_ref = kv_seg_ref = None
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc = refs
    i = pl.program_id(2)
    j = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = i * block_q
    k_start = j * block_k
    q_off = q_off_ref[0, 0]
    kv_off = kv_off_ref[0, 0]
    q_global = q_off + q_start
    k_global = kv_off + k_start
    needed = jnp.logical_or(
        jnp.asarray(not causal), k_global <= q_global + block_q - 1
    )
    if window:
        needed = jnp.logical_and(needed, k_global + block_k - 1 > q_global - window)
    interior = jnp.asarray(not (has_segments or kv_pad))
    if causal:
        interior = jnp.logical_and(interior, k_global + block_k - 1 <= q_global)
    if window:
        interior = jnp.logical_and(interior, k_global > q_global + block_q - 1 - window)

    def _compute(mask):
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]                    # [block_q, LANES] lane-replicated
        delta = delta_ref[0, 0]                # [block_q, LANES] lane-replicated
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if softcap:  # recompute the capped scores AND the cap's local slope
            t = jnp.tanh(s / softcap)
            s = softcap * t
        p = jnp.exp(s - _lane_tile(lse, block_k))
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - _lane_tile(delta, block_k)) * sm_scale
        if softcap:  # chain rule through s = cap*tanh(s_raw/cap): d/ds_raw = 1 - t^2
            ds = ds * (1.0 - t * t)
        ds = ds.astype(k.dtype)
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(jnp.logical_and(needed, interior))
    def _compute_fast():
        _compute(None)

    @pl.when(jnp.logical_and(needed, jnp.logical_not(interior)))
    def _compute_masked():
        _compute(_tile_mask(
            causal=causal, window=window, has_segments=has_segments, kv_pad=kv_pad,
            block_q=block_q, block_k=block_k, q_global=q_global, k_global=k_global,
            k_local=k_start, kv_len=kv_len, q_seg_ref=q_seg_ref, kv_seg_ref=kv_seg_ref,
        ))

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_off_ref, kv_off_ref, *refs,
    sm_scale, causal, block_q, block_k, kv_len, kv_pad, q_len, q_pad, nq,
    has_segments, window, softcap,
):
    if has_segments:
        (q_seg_ref, kv_seg_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        q_seg_ref = kv_seg_ref = None
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    j = pl.program_id(2)  # kv block (outer)
    # Inner dim walks (GQA group rep, q block) pairs: g = r*nq + i. dk/dv for one kv head
    # accumulate over every q head in its group, entirely in VMEM scratch.
    g = pl.program_id(3)
    ni = pl.num_programs(3)
    i = jax.lax.rem(g, nq)

    @pl.when(g == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = i * block_q
    k_start = j * block_k
    q_off = q_off_ref[0, 0]
    kv_off = kv_off_ref[0, 0]
    q_global = q_off + q_start
    k_global = kv_off + k_start
    needed = jnp.logical_or(
        jnp.asarray(not causal), q_global + block_q - 1 >= k_global
    )
    if window:
        needed = jnp.logical_and(needed, k_global + block_k - 1 > q_global - window)
    # Padded q rows (q_pad) matter here: ds/p for padded rows must be zero before they
    # accumulate into dk/dv, so those tiles are never "interior".
    interior = jnp.asarray(not (has_segments or kv_pad or q_pad))
    if causal:
        interior = jnp.logical_and(interior, k_global + block_k - 1 <= q_global)
    if window:
        interior = jnp.logical_and(interior, k_global > q_global + block_q - 1 - window)

    def _compute(mask):
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]                    # [block_q, LANES]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if softcap:
            t = jnp.tanh(s / softcap)
            s = softcap * t
        p = jnp.exp(s - _lane_tile(lse, block_k))
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - _lane_tile(delta, block_k)) * sm_scale
        if softcap:  # chain rule through s = cap*tanh(s_raw/cap)
            ds = ds * (1.0 - t * t)
        ds = ds.astype(q.dtype)
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    def _mask_with_qpad():
        mask = _tile_mask(
            causal=causal, window=window, has_segments=has_segments, kv_pad=kv_pad,
            block_q=block_q, block_k=block_k, q_global=q_global, k_global=k_global,
            k_local=k_start, kv_len=kv_len, q_seg_ref=q_seg_ref, kv_seg_ref=kv_seg_ref,
        )
        if q_pad:
            row_local = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            qmask = row_local < q_len
            mask = qmask if mask is None else jnp.logical_and(mask, qmask)
        return mask

    @pl.when(jnp.logical_and(needed, interior))
    def _compute_fast():
        _compute(None)

    @pl.when(jnp.logical_and(needed, jnp.logical_not(interior)))
    def _compute_masked():
        _compute(_mask_with_qpad())

    @pl.when(g == ni - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _rep_lanes(x, Sp):
    """[B,H,S] fp32 → [B,H,Sp,_LANES] lane-replicated (for in-kernel full-register math)."""
    x = _pad_seq(x[..., None], Sp)
    return jnp.broadcast_to(x, (*x.shape[:3], _LANES))


def _bwd_dq(q, k, v, do, lse, delta, causal, sm_scale, block_q, block_k, interpret,
            q_offset=0, kv_offset=0, segments=None, window=0, softcap=0.0):
    """dq for local q against one kv block (ring building block). GQA (K < H kv heads)
    resolved via the k/v index maps, matching ``_fwd``."""
    B, H, S, hd = q.shape
    K = k.shape[1]
    reps = H // K
    T = k.shape[2]
    nq = pl.cdiv(S, block_q)
    nk = pl.cdiv(T, block_k)
    Sp, Tp = nq * block_q, nk * block_k
    qp, dop = _pad_seq(q, Sp), _pad_seq(do, Sp)
    kp, vp = _pad_seq(k, Tp), _pad_seq(v, Tp)
    lsep = _rep_lanes(lse, Sp)
    deltap = _rep_lanes(delta, Sp)
    has_segments = segments is not None
    seg_specs, seg_args = [], []
    if has_segments:
        q_seg, kv_seg = _seg_blocks(segments, Sp, Tp)
        seg_specs = [
            pl.BlockSpec((1, block_q), lambda b, h, i, j: (b, i)),
            pl.BlockSpec((1, block_k), lambda b, h, i, j: (b, j)),
        ]
        seg_args = [q_seg, kv_seg]
    kernel = functools.partial(
        _bwd_dq_kernel,
        sm_scale=sm_scale, causal=causal, block_q=block_q, block_k=block_k, kv_len=T,
        kv_pad=(Tp != T), has_segments=has_segments, window=window, softcap=softcap,
    )
    dot_flops = 8 * B * H * Sp * Tp * hd * (0.5 if causal else 1.0)
    dq = pl.pallas_call(
        kernel,
        grid=(B, H, nq, nk),
        in_specs=[
            _smem_scalar_spec(),
            _smem_scalar_spec(),
            *seg_specs,
            pl.BlockSpec((1, 1, block_q, hd), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, hd), lambda b, h, i, j: (b, h // reps, j, 0)),
            pl.BlockSpec((1, 1, block_k, hd), lambda b, h, i, j: (b, h // reps, j, 0)),
            pl.BlockSpec((1, 1, block_q, hd), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, _LANES), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, _LANES), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, hd), lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, Sp, hd), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_q, hd), jnp.float32)],
        compiler_params=_dim_semantics(3, 1),
        cost_estimate=_cost(
            dot_flops,
            (qp.size + dop.size) * q.dtype.itemsize
            + (kp.size + vp.size) * k.dtype.itemsize * reps
            + B * H * Sp * hd * 4,
            B * H * Sp * Tp * (0.5 if causal else 1.0),
        ),
        interpret=interpret,
    )(_scalar(q_offset), _scalar(kv_offset), *seg_args, qp, kp, vp, dop, lsep, deltap)
    return dq[:, :, :S]


def _bwd_dkv(q, k, v, do, lse, delta, causal, sm_scale, block_q, block_k, interpret,
             q_offset=0, kv_offset=0, segments=None, window=0, softcap=0.0):
    """(dk, dv) [B,K,T,hd] for one kv block against local q (ring building block).

    GQA: the inner grid dim runs ``reps * nq`` steps — every (q head in the kv head's
    group, q block) pair — so each kv head's gradient accumulates over its whole group in
    VMEM scratch, without materializing per-q-head dk/dv."""
    B, H, S, hd = q.shape
    K = k.shape[1]
    reps = H // K
    T = k.shape[2]
    nq = pl.cdiv(S, block_q)
    nk = pl.cdiv(T, block_k)
    Sp, Tp = nq * block_q, nk * block_k
    qp, dop = _pad_seq(q, Sp), _pad_seq(do, Sp)
    kp, vp = _pad_seq(k, Tp), _pad_seq(v, Tp)
    lsep = _rep_lanes(lse, Sp)
    deltap = _rep_lanes(delta, Sp)
    has_segments = segments is not None
    seg_specs, seg_args = [], []
    if has_segments:
        q_seg, kv_seg = _seg_blocks(segments, Sp, Tp)
        # Grid order here is (b, kh, j, g): kv block outer, (group rep, q block) inner.
        seg_specs = [
            pl.BlockSpec((1, block_q), lambda b, kh, j, g: (b, g % nq)),
            pl.BlockSpec((1, block_k), lambda b, kh, j, g: (b, j)),
        ]
        seg_args = [q_seg, kv_seg]
    kernel = functools.partial(
        _bwd_dkv_kernel,
        sm_scale=sm_scale, causal=causal, block_q=block_q, block_k=block_k,
        kv_len=T, kv_pad=(Tp != T), q_len=S, q_pad=(Sp != S), nq=nq,
        has_segments=has_segments, window=window, softcap=softcap,
    )
    dot_flops = 10 * B * H * Sp * Tp * hd * (0.5 if causal else 1.0)
    dk, dv = pl.pallas_call(
        kernel,
        grid=(B, K, nk, reps * nq),
        in_specs=[
            _smem_scalar_spec(),
            _smem_scalar_spec(),
            *seg_specs,
            pl.BlockSpec((1, 1, block_q, hd), lambda b, kh, j, g: (b, kh * reps + g // nq, g % nq, 0)),
            pl.BlockSpec((1, 1, block_k, hd), lambda b, kh, j, g: (b, kh, j, 0)),
            pl.BlockSpec((1, 1, block_k, hd), lambda b, kh, j, g: (b, kh, j, 0)),
            pl.BlockSpec((1, 1, block_q, hd), lambda b, kh, j, g: (b, kh * reps + g // nq, g % nq, 0)),
            pl.BlockSpec((1, 1, block_q, _LANES), lambda b, kh, j, g: (b, kh * reps + g // nq, g % nq, 0)),
            pl.BlockSpec((1, 1, block_q, _LANES), lambda b, kh, j, g: (b, kh * reps + g // nq, g % nq, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, hd), lambda b, kh, j, g: (b, kh, j, 0)),
            pl.BlockSpec((1, 1, block_k, hd), lambda b, kh, j, g: (b, kh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, K, Tp, hd), jnp.float32),
            jax.ShapeDtypeStruct((B, K, Tp, hd), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, hd), jnp.float32),
            pltpu.VMEM((block_k, hd), jnp.float32),
        ],
        compiler_params=_dim_semantics(3, 1),
        cost_estimate=_cost(
            dot_flops,
            (qp.size + dop.size) * q.dtype.itemsize
            + (kp.size + vp.size) * k.dtype.itemsize
            + 2 * B * K * Tp * hd * 4,
            B * H * Sp * Tp * (0.5 if causal else 1.0),
        ),
        interpret=interpret,
    )(_scalar(q_offset), _scalar(kv_offset), *seg_args, qp, kp, vp, dop, lsep, deltap)
    return dk[:, :, :T], dv[:, :, :T]


def _pad_seq(x, target):
    S = x.shape[2]
    if S == target:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, target - S), (0, 0)))


def _fit_block(block: int, seq: int) -> int:
    if seq >= block:
        return block
    return max(16, 1 << (seq - 1).bit_length())


# ----------------------------------------------------------------------------- public API
# Offsets travel as float32 scalars so the custom_vjp has well-defined (zero) cotangents for
# them; kernels receive them as int32. This is what lets shard_map callers (ring/allgather SP)
# pass traced global positions.
def _seg_pair_f32(segments):
    """Normalize ``segments`` (None | [B,S] array | (q_seg, kv_seg) pair) to the fixed
    (q, kv) float32 pair the custom_vjp carries, plus the has_segments flag."""
    if segments is None:
        return (jnp.zeros((1, 1), jnp.float32),) * 2, False
    if not isinstance(segments, (tuple, list)):
        segments = (segments, segments)
    return tuple(jnp.asarray(s, jnp.float32) for s in segments), True


def _seg_pair_i32(seg_f32, has_segments):
    """``seg_f32`` travels through the custom_vjp as a (q_seg, kv_seg) float32 pair
    (identical arrays in the self-attention case) so the cotangent structure is fixed;
    kernels receive int32."""
    if not has_segments:
        return None
    return tuple(s.astype(jnp.int32) for s in seg_f32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11, 12, 13))
def _flash_bhsd(q, k, v, q_off, kv_off, seg_f32, causal, sm_scale, block_q, block_k,
                interpret, has_segments, window, softcap):
    segs = _seg_pair_i32(seg_f32, has_segments)
    o, _ = _fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret,
                q_offset=q_off.astype(jnp.int32), kv_offset=kv_off.astype(jnp.int32),
                segments=segs, window=window, softcap=softcap)
    return o


def _flash_bhsd_fwd(q, k, v, q_off, kv_off, seg_f32, causal, sm_scale, block_q, block_k,
                    interpret, has_segments, window, softcap):
    segs = _seg_pair_i32(seg_f32, has_segments)
    o, lse = _fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret,
                  q_offset=q_off.astype(jnp.int32), kv_offset=kv_off.astype(jnp.int32),
                  segments=segs, window=window, softcap=softcap)
    return o, (q, k, v, q_off, kv_off, seg_f32, o, lse)


def _flash_bhsd_bwd(causal, sm_scale, block_q, block_k, interpret, has_segments, window,
                    softcap, residuals, do):
    q, k, v, q_off, kv_off, seg_f32, o, lse = residuals
    qo = q_off.astype(jnp.int32)
    ko = kv_off.astype(jnp.int32)
    segs = _seg_pair_i32(seg_f32, has_segments)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)  # [B,H,S]
    dq = _bwd_dq(q, k, v, do, lse, delta, causal, sm_scale, block_q, block_k, interpret,
                 q_offset=qo, kv_offset=ko, segments=segs, window=window, softcap=softcap)
    dk, dv = _bwd_dkv(q, k, v, do, lse, delta, causal, sm_scale, block_q, block_k, interpret,
                      q_offset=qo, kv_offset=ko, segments=segs, window=window,
                      softcap=softcap)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32),
            jax.tree_util.tree_map(jnp.zeros_like, seg_f32))


_flash_bhsd.defvjp(_flash_bhsd_fwd, _flash_bhsd_bwd)


def _flash_bhsd_offset(q, k, v, q_offset=0, kv_offset=0, causal=True, sm_scale=None,
                       block_q=None, block_k=None, interpret=None, window=0, softcap=0.0,
                       segments=None):
    """Offset-aware flash attention over user layout [B, S, H, hd] (shard_map helper).

    ``segments``: None, a shared [B,S] array, or a ``(q_seg [B,S], kv_seg [B,T])`` pair —
    the pair form is how the SP modes keep packing exact when kv spans other shards."""
    B, S, H, hd = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(hd)
    if interpret is None:
        interpret = _interpret_default()
    qT = q.transpose(0, 2, 1, 3)
    kT = k.transpose(0, 2, 1, 3)
    vT = v.transpose(0, 2, 1, 3)
    bq = _fit_block(block_q or _DEFAULT_BLOCK_Q, S)
    bk = _fit_block(block_k or _DEFAULT_BLOCK_K, k.shape[1])
    seg_f32, has_segments = _seg_pair_f32(segments)
    o = _flash_bhsd(qT, kT, vT,
                    jnp.asarray(q_offset, jnp.float32), jnp.asarray(kv_offset, jnp.float32),
                    seg_f32,
                    causal, sm_scale, bq, bk, interpret, has_segments, int(window),
                    float(softcap))
    return o.transpose(0, 2, 1, 3)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    segment_ids: Optional[jax.Array] = None,
    window: int = 0,
    softcap: float = 0.0,
) -> jax.Array:
    """Flash attention over user layout q [B, S, H, hd], k/v [B, T, K, hd] (GQA: K ≤ H).

    Returns [B, S, H, hd] in q's dtype. Differentiable (custom VJP with flash backward).

    ``segment_ids`` [B, S] (sample packing, ``ops/packing.py``: 0 = pad, 1..k = packed
    sequences) restricts attention to same-segment pairs IN-KERNEL — packed training keeps
    the flash memory/compute profile instead of falling back to masked XLA attention.
    Requires self-attention shapes (T == S).

    ``window`` > 0 adds Mistral-style sliding-window masking (position i attends
    (i-window, i]): kv tiles entirely outside the band are SKIPPED, not just masked, so
    long-context compute scales with S·window instead of S².

    ``softcap`` > 0 applies Gemma-style score capping cap·tanh(s/cap) in-kernel, with the
    exact chain rule (1 − tanh²) in both backward kernels — Gemma-2 trains on the flash
    path instead of falling back to masked XLA attention.
    """
    B, S, H, hd = q.shape
    K = k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(hd)
    if interpret is None:
        interpret = _interpret_default()
    if segment_ids is not None and k.shape[1] != S:
        raise ValueError("segment_ids requires self-attention shapes (kv length == q length)")
    if H % K:
        raise ValueError(f"q heads ({H}) must be a multiple of kv heads ({K})")
    # GQA needs no head repeat: the kernels map q head h → kv head h // (H//K) in their
    # BlockSpec index maps, so the repeated K/V never exist in HBM.
    # [B, S, H, hd] → [B, H, S, hd]
    qT = q.transpose(0, 2, 1, 3)
    kT = k.transpose(0, 2, 1, 3)
    vT = v.transpose(0, 2, 1, 3)
    block_q = _fit_block(block_q or _DEFAULT_BLOCK_Q, S)
    block_k = _fit_block(block_k or _DEFAULT_BLOCK_K, k.shape[1])
    zero = jnp.zeros((), jnp.float32)
    seg_f32, has_segments = _seg_pair_f32(segment_ids)
    o = _flash_bhsd(qT, kT, vT, zero, zero, seg_f32, causal, sm_scale, block_q, block_k,
                    interpret, has_segments, int(window), float(softcap))
    return o.transpose(0, 2, 1, 3)
