"""The Accelerator facade (L3) — one object that prepares everything for the mesh.

TPU-native analog of reference ``accelerator.py`` (/root/reference/src/accelerate/accelerator.py,
3769 LoC): ``__init__`` (:266), ``prepare`` (:1283), ``backward`` (:2357), ``accumulate``
(:1116), ``clip_grad_norm_`` (:2485), ``gather_for_metrics`` (:2601), ``autocast`` (:3587).

**The central design inversion** (SURVEY.md §7): the reference mutates user objects — wraps the
model in DDP, patches ``forward``, wraps the optimizer so ``step()`` no-ops during
accumulation. Under jit that object-graph choreography cannot exist; instead the Accelerator
owns a **functional train step compiled once over the mesh**:

    accelerator = Accelerator(mixed_precision="bf16", gradient_accumulation_steps=4)
    params, optimizer, dataloader = accelerator.prepare(params, optax.adamw(1e-4), dataloader)
    state = accelerator.create_train_state(params, optimizer)
    step = accelerator.build_train_step(loss_fn)     # jitted; GSPMD handles DP/FSDP/TP comms
    for batch in dataloader:
        state, metrics = step(state, batch)          # grad-accum & clipping inside

Gradient synchronization is *not* an explicit collective: batches are sharded over the
``(dp, fsdp)`` mesh axes while params are replicated (DDP) or fsdp-sharded (ZeRO-3), so XLA
derives the all-reduce / reduce-scatter from the shardings — the entire DDP reducer +
DeepSpeed engine + FSDP wrapper surface of the reference collapses into ``jax.device_put``
placements plus one ``jax.jit``.
"""

from __future__ import annotations

import contextlib
import functools
import inspect
import os
from dataclasses import dataclass, replace as dataclass_replace
from typing import Any, Callable, Optional, Union

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .data_loader import DataLoaderDispatcher, DataLoaderShard, prepare_data_loader, skip_first_batches
from .logging import get_logger
from .optimizer import AcceleratedOptimizer
from .parallel.fsdp import shard_params
from .parallel.mesh import MeshConfig, mesh_context, replicated as _mesh_replicated
from .scheduler import AcceleratedScheduler
from .state import AcceleratorState, GradientState
from .utils.constants import BATCH_AXES
from .utils.dataclasses import (
    DataLoaderConfiguration,
    DistributedType,
    FullyShardedDataParallelPlugin,
    GradientAccumulationPlugin,
    MixedPrecisionPolicy,
    ProjectConfiguration,
)
from .utils.operations import (
    convert_to_fp32,
    gather,
    gather_object,
    pad_across_processes,
    recursively_apply,
    reduce,
    send_to_device,
    slice_tensors,
)

logger = get_logger(__name__)

__all__ = ["Accelerator", "TrainState", "cast_floating"]


def cast_floating(tree: Any, dtype) -> Any:
    """Cast floating leaves of a pytree to ``dtype`` (ints/bools untouched)."""

    def _cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(_cast, tree)


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    """The sharded training carry: everything a train step reads and writes.

    The functional replacement for the reference's (model, optimizer, scaler) object trio.
    ``grad_accum`` holds the running gradient sum between sync steps (the ``no_sync``
    mechanism, reference ``accelerator.py:1001``); ``step`` counts *optimizer* steps only.
    """

    params: Any
    opt_state: Any
    step: jax.Array
    grad_accum: Any = None
    rng: Any = None
    micro: jax.Array = None  # micro-steps since last apply (unique RNG per micro-batch)
    fp8_state: Any = None    # DelayedScalingState when the fp8 recipe uses delayed scaling

    def replace(self, **kwargs) -> "TrainState":
        import dataclasses

        return dataclasses.replace(self, **kwargs)


def _poison_float_leaves(batch):
    """Fault-injection helper: NaN out every float leaf of a batch (integer
    token ids pass through — NaN has no integer spelling)."""
    def poison(leaf):
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating):
            return np.full_like(arr, np.nan)
        return leaf

    return jax.tree_util.tree_map(poison, batch)


class _TrainStep:
    """Callable produced by ``Accelerator.build_train_step``.

    Two compiled variants — accumulate-only and accumulate+apply — dispatched host-side from
    the gradient-accumulation counter. This keeps each variant free of data-dependent control
    flow (XLA-friendly) while preserving the reference's ``sync_gradients`` semantics exactly.
    """

    def __init__(self, accelerator: "Accelerator", micro_fn, apply_fn, optimizer=None,
                 skip_nonfinite_steps: int = 0):
        self.accelerator = accelerator
        self.micro_fn = micro_fn
        self.apply_fn = apply_fn
        self.optimizer = optimizer
        self.micro_count = 0
        # Non-finite guard (docs/resilience.md): 0 = off (no host sync, byte-
        # identical to the unguarded step). K > 0 = the compiled step gates its
        # own update on all-finite loss+grads (params/opt state pass through
        # unchanged on a bad apply; a bad micro's contribution is zeroed) and
        # the host fetches ONE boolean per call. K consecutive non-finite
        # calls — micro OR apply — raise NonFiniteStepError.
        self.skip_nonfinite_steps = skip_nonfinite_steps
        self.nonfinite_total = 0
        self.nonfinite_consecutive = 0

    def __call__(self, state: TrainState, batch) -> tuple[TrainState, Any]:
        acc = self.accelerator
        # Fault injection (disabled = one attribute read): a "nonfinite" fault
        # poisons the batch's float leaves with NaN — the REAL guard path, not
        # a simulated exception — and an "error" fault raises at the boundary.
        plan = getattr(acc, "fault_plan", None)
        if plan is not None:
            spec = plan.draw("train.step")
            if spec is not None:
                if spec.kind == "nonfinite":
                    batch = _poison_float_leaves(batch)
                elif spec.kind == "crash":
                    # Whole-gang death: raises PAST the step boundary the way
                    # EngineCrashed does for serving — nothing in-process may
                    # catch it; the gang-of-gangs supervisor converts it into a
                    # budgeted gang restart + checkpoint replay.
                    from .resilience.faults import StageCrashed

                    raise StageCrashed("train.step",
                                       gang_id=plan.scope or "gang0")
                else:
                    raise plan.fault_for(spec, "train.step")
        # Telemetry bracket: when off this is two attribute reads — no syncs, no
        # allocation. When on, the record fences on the 1-element loss (telemetry.fence
        # never fetches the full result) so step time includes the device work.
        tel = acc.telemetry
        tel_on = tel is not None and tel.enabled
        if tel_on:
            tel._step_begin()
        try:
            state, metrics = self._dispatch(acc, tel if tel_on else None, state, batch)
        except BaseException:
            if tel_on:
                tel._step_abort()  # a failed step must not leak the compile label
            raise
        if self.skip_nonfinite_steps:
            self._check_nonfinite(acc, metrics)
        return state, metrics

    def _check_nonfinite(self, acc, metrics) -> None:
        """One boolean fetch per guarded step: count skipped (non-finite)
        updates, telemeter them, abort after the consecutive budget."""
        nf = bool(np.asarray(metrics.get("nonfinite", False)))
        if not nf:
            self.nonfinite_consecutive = 0
            return
        self.nonfinite_total += 1
        self.nonfinite_consecutive += 1
        tel = acc.telemetry
        if tel is not None and tel.enabled:
            from .telemetry import FAULT_SCHEMA

            tel.emit({
                "schema": FAULT_SCHEMA, "site": "train.step",
                "kind": "nonfinite", "step": acc.step,
                "consecutive": self.nonfinite_consecutive,
                "total": self.nonfinite_total,
            })
        if self.nonfinite_consecutive >= self.skip_nonfinite_steps:
            from .resilience.faults import NonFiniteStepError

            raise NonFiniteStepError(self.nonfinite_consecutive, self.nonfinite_total)

    def _dispatch(self, acc, tel, state: TrainState, batch) -> tuple[TrainState, Any]:
        gs = acc.gradient_state
        if acc._in_accumulate_ctx:
            do_sync = gs.sync_gradients  # accumulate() ctx already decided
        else:
            at_end = gs.sync_with_dataloader and gs.end_of_dataloader
            do_sync = ((self.micro_count + 1) % acc.gradient_accumulation_steps == 0) or at_end
            gs._set_sync_gradients(do_sync)
        offload = acc._opt_device_shardings is not None
        # Mesh context lets model code use bare PartitionSpecs in sharding constraints.
        with mesh_context(acc.mesh):
            state = acc._offload_fetch(state, opt=do_sync)
            if do_sync:
                state, metrics = self.apply_fn(state, batch)
                self.micro_count = 0
            else:
                # Micro steps never touch the optimizer state: detach it so the host-resident
                # moments neither transit PCIe nor occupy HBM during the activation-heavy
                # fwd/bwd (and the jit never sees host-memory-kind inputs).
                host_opt = state.opt_state if offload else None
                if offload:
                    state = state.replace(opt_state=None)
                state, metrics = self.micro_fn(state, batch)
                if offload:
                    state = state.replace(opt_state=host_opt)
                self.micro_count += 1
            state = acc._offload_stash(state, opt=do_sync)
        acc.step += 1
        if self.optimizer is not None:
            self.optimizer.step()
        if tel is not None:
            tel._step_end(fence_on=metrics, batch=batch)
        return state, metrics

    def warm(self, state: TrainState, batch) -> list:
        """Prime the AOT compile cache for this step's programs without executing
        (``compile_cache.warmup``). Mirrors ``_dispatch``'s argument shaping — the
        cpu_offload opt-state detach included — so the fingerprints match live
        steps. Returns the manifest entries (empty when the cache is disabled)."""
        acc = self.accelerator
        if not hasattr(self.apply_fn, "warm"):
            return []
        offload = acc._opt_device_shardings is not None
        entries = []
        with mesh_context(acc.mesh):
            apply_state = acc._offload_fetch(state, opt=True)
            entries.append(self.apply_fn.warm(apply_state, batch))
            if acc.gradient_accumulation_steps > 1:
                micro_state = acc._offload_fetch(state, opt=False)
                if offload:
                    micro_state = micro_state.replace(opt_state=None)
                entries.append(self.micro_fn.warm(micro_state, batch))
        return entries


class _FusedTrainStep:
    """M train steps per dispatch via ``lax.scan`` (``build_train_step(fused_steps=M)``).

    One compiled program advances M micro-steps (optimizer applies every
    ``gradient_accumulation_steps``-th) — amortizing host dispatch over M steps, which on TPU
    removes the host-side bottleneck the reference's per-batch Python loop suffers from.
    Call with a list of M batches or a pytree stacked on a leading M dim; metrics come back
    stacked [M, ...].
    """

    def __init__(self, accelerator: "Accelerator", fused_fn, fused_steps: int, optimizer=None):
        self.accelerator = accelerator
        self.fused_fn = fused_fn
        self.fused_steps = fused_steps
        self.optimizer = optimizer

    def _stack(self, batches):
        if isinstance(batches, (list, tuple)):
            if len(batches) != self.fused_steps:
                raise ValueError(f"expected {self.fused_steps} batches, got {len(batches)}")
            import numpy as _np

            stacked = jax.tree_util.tree_map(
                lambda *leaves: _np.stack([_np.asarray(l) for l in leaves]), *batches
            )
        else:
            stacked = batches
            for leaf in jax.tree_util.tree_leaves(stacked):
                if np.ndim(leaf) < 1 or np.shape(leaf)[0] != self.fused_steps:
                    raise ValueError(
                        f"pre-stacked batch leaves must have leading dim {self.fused_steps}, "
                        f"got shape {np.shape(leaf)}"
                    )
        sharding = NamedSharding(self.accelerator.mesh, PartitionSpec(None, BATCH_AXES))

        def _put(leaf):
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                return leaf
            if np.ndim(leaf) < 2:
                # Scalars / per-step vectors can't take the (step, batch) sharding.
                return jax.device_put(
                    leaf, NamedSharding(self.accelerator.mesh, PartitionSpec())
                )
            return jax.device_put(leaf, sharding)

        return jax.tree_util.tree_map(_put, stacked)

    def __call__(self, state: TrainState, batches) -> tuple[TrainState, Any]:
        acc = self.accelerator
        tel = acc.telemetry
        tel_on = tel is not None and tel.enabled
        if tel_on:
            tel._step_begin()
        try:
            stacked = self._stack(batches)
            with mesh_context(acc.mesh):
                state = acc._offload_fetch(state, opt=True)
                state, metrics = self.fused_fn(state, stacked)
                state = acc._offload_stash(state, opt=True)
        except BaseException:
            if tel_on:
                tel._step_abort()  # a failed step must not leak the compile label
            raise
        acc.step += self.fused_steps
        applies = self.fused_steps // acc.gradient_accumulation_steps
        if self.optimizer is not None:
            self.optimizer._step_count += applies
        acc.gradient_state._set_sync_gradients(
            self.fused_steps % acc.gradient_accumulation_steps == 0
        )
        if tel_on:
            # One record per dispatch window of M steps; batch shapes sit behind the
            # stacked [M, B, ...] leading dim.
            tel._step_end(
                fence_on=metrics, batch=stacked, n_steps=self.fused_steps, drop_leading=1
            )
        return state, metrics

    def warm(self, state: TrainState, batches) -> list:
        """Prime the AOT compile cache for the fused program without executing
        (``compile_cache.warmup``); batches take the same list/stacked forms as
        ``__call__``. Returns the manifest entries (empty when cache disabled)."""
        acc = self.accelerator
        if not hasattr(self.fused_fn, "warm"):
            return []
        stacked = self._stack(batches)
        with mesh_context(acc.mesh):
            fetched = acc._offload_fetch(state, opt=True)
            return [self.fused_fn.warm(fetched, stacked)]


class Accelerator:
    """One facade for device placement, parallelism, precision, accumulation and IO."""

    def __init__(
        self,
        device_placement: bool = True,
        split_batches: bool = False,
        mixed_precision: Optional[str] = None,
        gradient_accumulation_steps: Optional[int] = None,
        cpu: bool = False,
        dataloader_config: Optional[DataLoaderConfiguration] = None,
        mesh_config: Optional[MeshConfig] = None,
        fsdp_plugin: Optional[FullyShardedDataParallelPlugin] = None,
        tp_plugin=None,
        pp_plugin=None,
        sp_plugin=None,
        ep_plugin=None,
        megatron_lm_plugin=None,
        rng_types: Optional[list[str]] = None,
        log_with=None,
        project_dir: Optional[str] = None,
        project_config: Optional[ProjectConfiguration] = None,
        gradient_accumulation_plugin: Optional[GradientAccumulationPlugin] = None,
        step_scheduler_with_optimizer: bool = True,
        kwargs_handlers: Optional[list] = None,
        dynamo_plugin=None,
        telemetry_config=None,
        compile_cache_config=None,
        gateway_config=None,
        fault_config=None,
    ):
        self.project_configuration = project_config or ProjectConfiguration(project_dir=project_dir)
        if project_dir is not None and self.project_configuration.project_dir is None:
            self.project_configuration.set_directories(project_dir)

        # Plugins may also arrive via the env wire protocol (launcher sets ACCELERATE_*).
        if fsdp_plugin is None and os.environ.get("ACCELERATE_USE_FSDP", "false").lower() == "true":
            fsdp_plugin = FullyShardedDataParallelPlugin()

        # A MegatronLMPlugin is a bundle: expand it into the individual plugins it implies
        # (reference _prepare_megatron_lm, accelerator.py:2011; our mesh subsumes the engine).
        self._megatron_grad_clip = None
        if megatron_lm_plugin is not None:
            from .utils.dataclasses import (
                PipelineParallelPlugin,
                SequenceParallelPlugin,
                TensorParallelPlugin,
            )

            if tp_plugin is None and megatron_lm_plugin.tp_degree > 1:
                tp_plugin = TensorParallelPlugin(tp_size=megatron_lm_plugin.tp_degree)
            if pp_plugin is None and megatron_lm_plugin.pp_degree > 1:
                pp_plugin = PipelineParallelPlugin(
                    pp_size=megatron_lm_plugin.pp_degree,
                    num_microbatches=megatron_lm_plugin.num_micro_batches,
                    schedule=megatron_lm_plugin.pp_schedule,
                    virtual_stages=megatron_lm_plugin.virtual_pipeline_stages,
                )
            if sp_plugin is None and megatron_lm_plugin.sp_degree > 1:
                sp_plugin = SequenceParallelPlugin(sp_size=megatron_lm_plugin.sp_degree)
            if fsdp_plugin is None and megatron_lm_plugin.use_distributed_optimizer:
                fsdp_plugin = FullyShardedDataParallelPlugin(zero_stage=1)
            if (
                megatron_lm_plugin.pp_degree == 1
                and megatron_lm_plugin.num_micro_batches
                and gradient_accumulation_steps is None
                and gradient_accumulation_plugin is None
            ):
                # Megatron micro-batching implies gradient accumulation independent of
                # pipeline depth; without a pipe the microbatches become accum steps.
                gradient_accumulation_steps = megatron_lm_plugin.num_micro_batches
            self._megatron_grad_clip = megatron_lm_plugin.gradient_clipping

        # Kwargs handler dispatch (reference accelerator.py:425-450).
        self.fp8_recipe = None
        self.autocast_handler = None
        self.profile_handler = None
        self.scaler_handler = None
        distributed_init_kwargs = None
        ddp_kwargs = None
        for handler in kwargs_handlers or []:
            from .utils.dataclasses import (
                AutocastKwargs,
                DistributedDataParallelKwargs,
                DistributedInitKwargs,
                FP8RecipeKwargs,
                GradScalerKwargs,
                ProfileKwargs,
            )

            if isinstance(handler, FP8RecipeKwargs):
                self.fp8_recipe = handler
            elif isinstance(handler, AutocastKwargs):
                self.autocast_handler = handler
            elif isinstance(handler, ProfileKwargs):
                self.profile_handler = handler
            elif isinstance(handler, GradScalerKwargs):
                self.scaler_handler = handler  # API parity; moot under bf16/fp8 on TPU
            elif isinstance(handler, DistributedInitKwargs):
                distributed_init_kwargs = handler
            elif isinstance(handler, DistributedDataParallelKwargs):
                ddp_kwargs = handler  # comm_hook → reduce_dtype, applied post-state-init
            else:
                raise ValueError(f"Unsupported kwargs handler: {handler!r}")
        if mixed_precision == "fp8" and self.fp8_recipe is None:
            from .utils.dataclasses import FP8RecipeKwargs

            self.fp8_recipe = FP8RecipeKwargs()
        if self.fp8_recipe is not None:
            # Install the recipe as the process default consulted by ops.fp8.fp8_dot.
            # Delayed scaling is wired automatically: create_train_state seeds a
            # DelayedScalingState into TrainState.fp8_state and build_train_step threads it
            # through every fp8_dot via ops.fp8.autoscale_ctx.
            from .ops.fp8 import set_default_recipe

            set_default_recipe(self.fp8_recipe.fp8_format, self.fp8_recipe.margin)

        self.state = AcceleratorState(
            **({"distributed_init_kwargs": distributed_init_kwargs} if distributed_init_kwargs else {}),
            mixed_precision=mixed_precision,
            cpu=cpu,
            mesh_config=mesh_config,
            fsdp_plugin=fsdp_plugin,
            tp_plugin=tp_plugin,
            pp_plugin=pp_plugin,
            sp_plugin=sp_plugin,
            ep_plugin=ep_plugin,
            megatron_lm_plugin=megatron_lm_plugin,
            telemetry_config=telemetry_config,
            compile_cache_config=compile_cache_config,
            gateway_config=gateway_config,
            fault_config=fault_config,
        )

        # Step-level telemetry (off by default; ACCELERATE_TELEMETRY=1 or an enabled
        # TelemetryConfig turns it on). The disabled object costs two attribute reads
        # per train step — no listeners, no files, no host syncs.
        from .telemetry import Telemetry

        self.telemetry = Telemetry(self.state.telemetry_config)
        if self.telemetry.enabled:
            self.telemetry.sinks.append(self._telemetry_tracker_sink)

        # Persistent AOT executable cache (off by default; ACCELERATE_COMPILE_CACHE=1
        # or an enabled CompileCacheConfig turns it on). Disabled, wrap() is the
        # identity and every step dispatches through plain jax.jit as before.
        from .compile_cache import AotCache

        self.compile_cache = AotCache(self.state.compile_cache_config)

        # Deterministic fault injection (off by default; ACCELERATE_FAULTS or an
        # enabled FaultConfig turns it on). Disabled, the plan is None and every
        # instrumented site pays one attribute read (docs/resilience.md).
        self.fault_plan = self.state.fault_config.build_plan()

        if ddp_kwargs is not None and ddp_kwargs.reduce_dtype is not None:
            # DDP comm_hook analog: compress cross-device gradient reductions.
            # build_train_step only honors it when it EQUALS the compute dtype (the
            # compressed reduce is exact there); per this handler's own
            # accepted-but-ignored-is-worse-than-an-error policy, any other combination
            # raises instead of silently running uncompressed.
            import dataclasses as _dc

            compute_dtype = self.state.mixed_precision_policy.compute_dtype
            if ddp_kwargs.reduce_dtype != compute_dtype:
                raise ValueError(
                    f"DistributedDataParallelKwargs comm_hook compression dtype "
                    f"{ddp_kwargs.reduce_dtype.__name__} does not match the mixed-"
                    f"precision compute dtype {compute_dtype.__name__}: the hook would "
                    "be accepted but never applied. Use the comm_hook matching "
                    "mixed_precision (bf16 ↔ 'bf16'), or drop the handler."
                )
            self.state.mixed_precision_policy = _dc.replace(
                self.state.mixed_precision_policy, reduce_dtype=ddp_kwargs.reduce_dtype
            )
            # Distinguishes an EXPLICIT comm_hook from the bf16/fp16 policy's default
            # reduce_dtype: only the former hard-errors when a build_train_step option
            # later disables compression (the default silently not compressing under
            # cast_params=False is expected behavior, not a dropped user request).
            self._explicit_comm_hook = True

        if gradient_accumulation_plugin is None:
            # Priority: explicit Python arg (any int, including 1) > env wire protocol > 1.
            if gradient_accumulation_steps is None:
                env_steps = int(os.environ.get("ACCELERATE_GRADIENT_ACCUMULATION_STEPS", "-1"))
                gradient_accumulation_steps = env_steps if env_steps > 0 else 1
            gradient_accumulation_plugin = GradientAccumulationPlugin(
                num_steps=gradient_accumulation_steps
            )
        self.gradient_state = GradientState(gradient_accumulation_plugin)

        self.device_placement = device_placement
        self.split_batches = split_batches
        self.dataloader_config = dataloader_config or DataLoaderConfiguration(
            split_batches=split_batches
        )
        self.rng_types = rng_types or ["generator"]
        self.step_scheduler_with_optimizer = step_scheduler_with_optimizer
        if log_with is None and os.environ.get("ACCELERATE_LOG_WITH"):
            log_with = os.environ["ACCELERATE_LOG_WITH"]
        self.log_with = log_with
        self.trackers: list = []

        self.step = 0
        # Param-layout record for the fused-optimizer fast path. None = unknown (no
        # create_train_state yet — user-managed TrainStates stay on the safe optax path
        # when sharding machinery is configured); set to ground truth by create_train_state.
        self._params_cross_sharded: Optional[bool] = None
        self._param_spec_tree = None
        # ZeRO-1/2 spec trees, filled by create_train_state when the fsdp plugin requests
        # optimizer/gradient sharding with replicated params (zero_stage 1/2).
        self._zero_opt_specs = None
        self._zero_grad_specs = None
        self._zero_param_specs = None
        # cpu_offload sharding trees (host/device variants), filled by create_train_state.
        self._opt_host_shardings = None
        self._opt_device_shardings = None
        self._accum_host_shardings = None
        self._accum_device_shardings = None
        self._in_accumulate_ctx = False
        self._accumulate_count = 0
        self._max_grad_norm: Optional[float] = (
            float(self._megatron_grad_clip) if self._megatron_grad_clip is not None else None
        )
        self._max_grad_value: Optional[float] = None
        self._models: list = []
        self._optimizers: list[AcceleratedOptimizer] = []
        self._schedulers: list = []
        self._dataloaders: list = []
        self._custom_objects: list = []
        self._save_model_hooks: list[Callable] = []
        self._load_model_hooks: list[Callable] = []

        self.flag_tensor = None

    # ------------------------------------------------------------------------ properties
    @property
    def mesh(self) -> Mesh:
        return self.state.mesh

    @property
    def distributed_type(self) -> DistributedType:
        return self.state.distributed_type

    @property
    def num_processes(self) -> int:
        return self.state.num_processes

    @property
    def process_index(self) -> int:
        return self.state.process_index

    @property
    def local_process_index(self) -> int:
        return self.state.local_process_index

    @property
    def device(self):
        return self.state.device

    @property
    def is_main_process(self) -> bool:
        return self.state.is_main_process

    @property
    def is_local_main_process(self) -> bool:
        return self.state.is_local_main_process

    @property
    def is_last_process(self) -> bool:
        return self.state.is_last_process

    @property
    def mixed_precision(self) -> str:
        return self.state.mixed_precision

    @property
    def mixed_precision_policy(self) -> MixedPrecisionPolicy:
        return self.state.mixed_precision_policy

    @property
    def use_distributed(self) -> bool:
        return self.state.use_distributed

    @property
    def num_microbatches(self) -> int:
        """Pipeline microbatch count: plugin value > launcher env > n_stages (min full pipe)."""
        from .utils.constants import PIPELINE_AXIS

        plugin = self.state.pp_plugin
        if plugin is not None and plugin.num_microbatches is not None:
            return plugin.num_microbatches
        env_mb = os.environ.get("ACCELERATE_PP_MICROBATCHES")
        if env_mb:
            return int(env_mb)
        return self.mesh.shape[PIPELINE_AXIS]

    @property
    def pp_schedule(self) -> str:
        """Pipeline schedule from the plugin ("gpipe" | "1f1b") — pass to the model's
        ``loss_fn_pp(..., schedule=accelerator.pp_schedule)`` so
        ``PipelineParallelPlugin(schedule=...)`` actually takes effect; env override
        ACCELERATE_PP_SCHEDULE mirrors the launcher protocol."""
        env_s = os.environ.get("ACCELERATE_PP_SCHEDULE")
        if env_s:
            if env_s not in ("gpipe", "1f1b"):
                raise ValueError(
                    f"ACCELERATE_PP_SCHEDULE={env_s!r}: expected 'gpipe' or '1f1b'"
                )
            return env_s
        plugin = self.state.pp_plugin
        return plugin.schedule if plugin is not None else "gpipe"

    @property
    def virtual_stages(self) -> int:
        """Interleaved virtual-pipeline chunks per device from the plugin (the Megatron
        ``virtual_pipeline`` analog) — pass to the model's
        ``loss_fn_pp(..., virtual_stages=accelerator.virtual_stages)``; env override
        ACCELERATE_PP_VIRTUAL_STAGES mirrors the launcher protocol."""
        env_v = os.environ.get("ACCELERATE_PP_VIRTUAL_STAGES")
        if env_v:
            v = int(env_v)
            if v < 1:
                # Mirror PipelineParallelPlugin.__post_init__ — an invalid env value
                # must fail here, not as an opaque modulo-by-zero at split time.
                raise ValueError(f"ACCELERATE_PP_VIRTUAL_STAGES={env_v!r} must be >= 1")
            return v
        plugin = self.state.pp_plugin
        return plugin.virtual_stages if plugin is not None else 1

    @property
    def gradient_accumulation_steps(self) -> int:
        return self.gradient_state.num_steps

    @gradient_accumulation_steps.setter
    def gradient_accumulation_steps(self, value: int):
        self.gradient_state.plugin_kwargs.update({"num_steps": value})

    @property
    def sync_gradients(self) -> bool:
        return self.gradient_state.sync_gradients

    @property
    def project_dir(self):
        return self.project_configuration.project_dir

    # ------------------------------------------------------------------- process control
    def wait_for_everyone(self):
        self.state.wait_for_everyone()

    def print(self, *args, **kwargs):
        self.state.print(*args, **kwargs)

    def split_between_processes(self, inputs, apply_padding: bool = False):
        return self.state.split_between_processes(inputs, apply_padding=apply_padding)

    def main_process_first(self):
        """Main host runs the body first, then the rest (reference ``accelerator.py:957``)."""
        return self.state.main_process_first()

    def local_main_process_first(self):
        """Per-node variant of :meth:`main_process_first` (reference ``accelerator.py:979``)."""
        return self.state.local_main_process_first()

    def on_main_process(self, function):
        return self.state.on_main_process(function)

    def on_local_main_process(self, function):
        return self.state.on_local_main_process(function)

    def on_process(self, function=None, process_index=None):
        return self.state.on_process(function, process_index=process_index)

    def on_last_process(self, function):
        return self.state.on_last_process(function)

    # ------------------------------------------------------------------------- prepare
    def prepare(self, *args, device_placement: Optional[list[bool]] = None):
        """Prepare each object for the mesh, preserving order (reference ``:1283``).

        Dispatch by duck type: dataloaders are sharded; optax transformations become
        ``AcceleratedOptimizer``; param pytrees are sharded per the FSDP plugin; stateful
        schedulers become ``AcceleratedScheduler``; flax modules pass through (their params
        are what need preparing).
        """
        if device_placement is None:
            device_placement = [None] * len(args)
        result = tuple(
            self._prepare_one(obj, device_placement=dp) for obj, dp in zip(args, device_placement)
        )
        return result if len(result) > 1 else result[0]

    def _prepare_one(self, obj, device_placement=None):
        if _is_dataloader_like(obj):
            return self.prepare_data_loader(obj)
        # Before the optax duck-type check: AcceleratedOptimizer itself has init/update,
        # so the order decides whether re-prepare is idempotent or double-wraps.
        if isinstance(obj, AcceleratedOptimizer):
            if obj not in self._optimizers:
                self._optimizers.append(obj)
            return obj
        if _is_optax_transformation(obj):
            return self.prepare_optimizer(obj, device_placement=device_placement)
        if _is_stateful_scheduler(obj):
            return self.prepare_scheduler(obj)
        if _is_flax_module(obj):
            self._models.append(obj)
            return obj
        if _is_torch_module(obj):
            raise NotImplementedError(
                "A live torch nn.Module cannot run under the mesh/jit runtime; migrate its "
                "STATE instead: accelerate_tpu.interop.torch_module_to_pytree(module) for "
                "generic state dicts, or models.hf_interop for exact llama/gpt2 conversion "
                "— then pass the pytree with a JAX forward."
            )
        if _is_params_pytree(obj):
            return self.prepare_params(obj)
        return obj

    def prepare_params(self, params, partition_specs=None):
        """Shard a param pytree over the mesh (the ``prepare_model`` analog, reference :1421).

        Casts to the policy's param dtype (fp32 master weights) and applies the combined
        sharding: model TP specs (``partition_specs``, e.g. ``models.llama.partition_specs``)
        first, ZeRO-3/FSDP on the remaining free axes, replicated otherwise (DDP layout).
        """
        policy = self.mixed_precision_policy
        params = cast_floating(params, policy.param_dtype)
        if partition_specs is not None:
            from .parallel.tp import apply_tensor_parallel

            return apply_tensor_parallel(
                params, self.mesh, specs=partition_specs, fsdp_plugin=self.state.fsdp_plugin
            )
        return shard_params(params, self.mesh, self.state.fsdp_plugin)

    prepare_model = prepare_params  # reference-name alias for pytree models

    def prepare_data_loader(self, data_loader, device_placement=None, slice_fn_for_dispatch=None):
        if isinstance(data_loader, (DataLoaderShard, DataLoaderDispatcher)):
            self._dataloaders.append(data_loader)
            return data_loader
        cfg = self.dataloader_config
        device = self.mesh if (device_placement if device_placement is not None else self.device_placement) else None
        prepared = prepare_data_loader(
            data_loader,
            device=device,
            split_batches=cfg.split_batches,
            put_on_device=device is not None,
            rng_types=self.rng_types,
            dispatch_batches=cfg.dispatch_batches,
            even_batches=cfg.even_batches,
            use_seedable_sampler=cfg.use_seedable_sampler,
            data_seed=cfg.data_seed,
            non_blocking=cfg.non_blocking,
            use_stateful_dataloader=cfg.use_stateful_dataloader,
            prefetch_depth=cfg.prefetch_depth,
        )
        self._dataloaders.append(prepared)
        return prepared

    def prepare_optimizer(self, optimizer, device_placement=None) -> AcceleratedOptimizer:
        if isinstance(optimizer, AcceleratedOptimizer):  # idempotent re-prepare
            if optimizer not in self._optimizers:
                self._optimizers.append(optimizer)
            return optimizer
        optimizer = self._apply_fp8_opt_level(optimizer)
        if device_placement is None:
            device_placement = True  # None = unspecified; an explicit False must stick
        wrapped = AcceleratedOptimizer(optimizer, device_placement=device_placement)
        self._optimizers.append(wrapped)
        return wrapped

    def _apply_fp8_opt_level(self, optimizer):
        """MS-AMP ``opt_level="O2"`` analog (reference ``accelerator.py:2164``): store the
        AdamW moments as scaled-fp8. Takes effect on a ``FusedAdamW`` whose moment dtypes
        were left unset; measured on-chip this is a ~10% end-to-end MFU win at 0.9B params
        (the apply is bandwidth-bound — see PERF_NOTES.md round-4 window 3)."""
        recipe = self.fp8_recipe
        if recipe is None or getattr(recipe, "opt_level", "O1") != "O2":
            return optimizer
        from .ops.fused_optim import FusedAdamW

        if isinstance(optimizer, FusedAdamW):
            if optimizer.mu_dtype is None and optimizer.nu_dtype is None:
                return dataclass_replace(
                    optimizer,
                    mu_dtype=jnp.float8_e4m3fn,
                    nu_dtype=jnp.float8_e4m3fn,
                )
            return optimizer  # explicit user dtypes win over the recipe
        logger.warning(
            "FP8RecipeKwargs(opt_level='O2') requires the fused optimizer "
            "(accelerate_tpu.ops.fused_optim.fused_adamw) to carry low-precision "
            "moments; %s keeps fp32 optimizer state.",
            type(optimizer).__name__,
        )
        return optimizer

    def prepare_scheduler(self, scheduler) -> AcceleratedScheduler:
        wrapped = AcceleratedScheduler(
            scheduler,
            optimizers=self._optimizers,
            step_with_optimizer=self.step_scheduler_with_optimizer,
            split_batches=self.dataloader_config.split_batches,
        )
        self._schedulers.append(wrapped)
        return wrapped

    # -------------------------------------------------------------------- train state/step
    def _offload_fetch(self, state: TrainState, opt: bool) -> TrainState:
        """cpu_offload: stream host-resident optimizer/accum state into device HBM for one
        step dispatch. Transfers happen OUTSIDE jit (XLA CPU cannot annotate host placement
        on jit outputs); between steps the state lives in pinned host RAM, so HBM holds the
        optimizer moments only during the (activation-free) apply phase."""
        if self._opt_device_shardings is None:
            return state
        updates = {}
        if opt:
            # Single device_put over the whole tree: the runtime batches/overlaps the
            # transfers instead of serializing one PCIe copy per leaf.
            updates["opt_state"] = jax.device_put(state.opt_state, self._opt_device_shardings)
        if state.grad_accum is not None and self._accum_device_shardings is not None:
            updates["grad_accum"] = jax.device_put(
                state.grad_accum, self._accum_device_shardings
            )
        return state.replace(**updates) if updates else state

    def _offload_stash(self, state: TrainState, opt: bool) -> TrainState:
        if self._opt_device_shardings is None:
            return state
        updates = {}
        if opt:
            updates["opt_state"] = jax.device_put(state.opt_state, self._opt_host_shardings)
        if state.grad_accum is not None and self._accum_host_shardings is not None:
            updates["grad_accum"] = jax.device_put(
                state.grad_accum, self._accum_host_shardings
            )
        return state.replace(**updates) if updates else state

    def create_train_state(
        self,
        params,
        optimizer: Union[AcceleratedOptimizer, Any],
        rng: Optional[jax.Array] = None,
        partition_specs=None,
    ) -> TrainState:
        """Build the sharded training carry.

        Params are prepared (cast + sharded); optimizer state is initialized *from the sharded
        params*, so each opt-state leaf inherits its param's sharding. ZeRO stages 1/2
        (``zero_stage`` on the fsdp plugin, reference DeepSpeed partitioned optimizer
        ``utils/dataclasses.py:1019-1448``) additionally shard the optimizer state (stage 1)
        and the gradient-accumulation buffers (stage 2) over the fsdp axis while params stay
        replicated — the train step then reduce-scatters grads and all-gathers updates.
        """
        if not isinstance(optimizer, AcceleratedOptimizer):
            optimizer = self.prepare_optimizer(optimizer)
        params = self.prepare_params(params, partition_specs=partition_specs)
        opt_state = optimizer.init(params)
        # Scalar opt-state leaves (optax step counts) come out of init on ONE device
        # while the compiled step returns them mesh-replicated — without this commit
        # the second step call would silently retrace (found by the ISSUE-3
        # compiles-exactly-once regression guard: every train loop paid the compile
        # twice). Array-valued leaves already inherit their param's sharding.
        _replicated_scalar = _mesh_replicated(self.mesh)
        opt_state = jax.tree_util.tree_map(
            lambda l: jax.device_put(l, _replicated_scalar)
            if isinstance(l, jax.Array)
            and l.ndim == 0
            and not isinstance(l.sharding, NamedSharding)
            else l,
            opt_state,
        )

        from .utils.constants import FSDP_AXIS

        plugin = self.state.fsdp_plugin
        # Ground-truth record of the params' cross-device layout (TP plans, FSDP/ZeRO-3,
        # user partition_specs all included): the fused-optimizer fast path runs sharded
        # leaves under shard_map with exactly these specs (opt-state moments share the
        # param layout in this default path).
        self._params_cross_sharded = any(
            isinstance(l, jax.Array) and not l.sharding.is_fully_replicated
            for l in jax.tree_util.tree_leaves(params)
        )
        self._param_spec_tree = jax.tree_util.tree_map(
            # "opaque" = a layout we can't express as a PartitionSpec; the fused optimizer
            # routes such leaves through plain (partitionable) XLA math, never the kernel.
            lambda l: (
                l.sharding.spec
                if isinstance(l.sharding, NamedSharding)
                else (PartitionSpec() if l.sharding.is_fully_replicated else "opaque")
            )
            if isinstance(l, jax.Array)
            else PartitionSpec(),
            params,
        )
        self._zero_opt_specs = None
        self._zero_grad_specs = None
        if (
            plugin is not None
            and plugin.shards_optimizer
            and not plugin.shards_params
            and self.mesh.shape[FSDP_AXIS] > 1
        ):
            from .parallel.fsdp import get_zero_specs, shard_tree

            self._zero_opt_specs = get_zero_specs(opt_state, self.mesh, plugin)
            opt_state = shard_tree(opt_state, self.mesh, self._zero_opt_specs)
            # Pin the param layout in the apply step: without this, GSPMD propagates the
            # sharded updates into the output params, silently turning stage 1/2 into 3.
            self._zero_param_specs = jax.tree_util.tree_map(
                lambda leaf: leaf.sharding.spec
                if isinstance(leaf, jax.Array) and isinstance(leaf.sharding, NamedSharding)
                else PartitionSpec(),
                params,
            )
            if plugin.shards_grads:
                self._zero_grad_specs = get_zero_specs(params, self.mesh, plugin)

        accum = None
        if self.gradient_accumulation_steps > 1:
            accum = jax.tree_util.tree_map(jnp.zeros_like, params)
            if self._zero_grad_specs is not None:
                from .parallel.fsdp import shard_tree

                accum = shard_tree(accum, self.mesh, self._zero_grad_specs)

        if plugin is not None and plugin.cpu_offload:
            # ZeRO-Offload layout (reference DeepSpeed offload fields, dataclasses.py:1078):
            # optimizer state and accumulation buffers live in pinned host RAM; the apply
            # step streams them through device HBM (SURVEY.md §7 equivalence table).
            def _kinds(tree):
                def _spec(leaf):
                    sh = getattr(leaf, "sharding", None)
                    return sh.spec if isinstance(sh, NamedSharding) else PartitionSpec()

                dev = jax.tree_util.tree_map(
                    lambda l: NamedSharding(self.mesh, _spec(l), memory_kind="device"), tree
                )
                host = jax.tree_util.tree_map(
                    lambda l: NamedSharding(self.mesh, _spec(l), memory_kind="pinned_host"),
                    tree,
                )
                return host, dev

            self._opt_host_shardings, self._opt_device_shardings = _kinds(opt_state)
            opt_state = jax.device_put(opt_state, self._opt_host_shardings)
            if accum is not None:
                self._accum_host_shardings, self._accum_device_shardings = _kinds(accum)
                accum = jax.device_put(accum, self._accum_host_shardings)

        fp8_state = None
        if self.fp8_recipe is not None and self.fp8_recipe.use_delayed_scaling:
            from .ops.fp8 import DelayedScalingState

            fp8_state = DelayedScalingState.init(self.fp8_recipe.amax_history_len)

        optimizer._opt_state_ref = opt_state
        # Scalars are committed mesh-replicated, not left on one device: a checkpoint
        # restore templates its shardings on these leaves (`_abstractify`), and a
        # single-device `step` restored into a >1-device mesh context is an error at the
        # next jitted call (caught by tests/test_elastic.py preemption-resume parity).
        replicated = _mesh_replicated(self.mesh)

        def _counter():
            # Distinct buffers: two leaves sharing one donated buffer would alias.
            return jax.device_put(jnp.zeros((), dtype=jnp.int32), replicated)

        def _replicate(tree):
            # rng keys / fp8 amax histories get the same treatment as the counters.
            return jax.tree_util.tree_map(
                lambda leaf: jax.device_put(leaf, replicated)
                if isinstance(leaf, (jax.Array, np.ndarray))
                else leaf,
                tree,
            )

        return TrainState(
            params=params,
            opt_state=opt_state,
            step=_counter(),
            grad_accum=accum,
            rng=_replicate(rng),
            micro=_counter(),
            fp8_state=_replicate(fp8_state),
        )

    def build_train_step(
        self,
        loss_fn: Callable,
        optimizer: Optional[Union[AcceleratedOptimizer, Any]] = None,
        max_grad_norm: Optional[float] = None,
        max_grad_value: Optional[float] = None,
        has_aux: bool = False,
        donate: bool = True,
        fused_steps: int = 1,
        cast_params: bool = True,
        skip_nonfinite_steps: int = 0,
    ) -> _TrainStep:
        """Compile the training step (the reference hot loop, SURVEY.md §3.4, as one XLA program).

        ``loss_fn(params, batch)`` or ``loss_fn(params, batch, rng)`` returns a scalar loss
        (or ``(loss, aux)`` with ``has_aux=True``). Mixed precision: params are cast to the
        compute dtype *inside* the step so gradients/master weights stay fp32 (the
        autocast + GradScaler-free equivalent of reference ``:1462-1473``).

        ``cast_params=False`` skips that whole-tree cast — pass it when the model casts each
        weight at its point of use (``models.llama`` does, via ``cfg.dtype``): the upfront cast
        materializes a full low-precision copy of the parameters in HBM (and, with scanned
        layers, matching zero-init buffers in the scan backward), which on a 16 GB chip is the
        difference between fitting a ~1B-param adamw step and OOM.

        ``skip_nonfinite_steps=K`` (0 = off, the byte-identical default) arms
        the non-finite guard: an APPLY step whose loss or gradients contain
        NaN/inf skips its update inside the compiled program (params and
        optimizer state pass through unchanged); a non-finite MICRO step's
        contribution is zeroed before it can poison the accumulation window.
        The host counts every guarded call that observed non-finite compute —
        micro or apply; consecutive non-finite COMPUTE is the divergence
        signal, wherever the accumulation boundary falls — and ``K``
        consecutive raise :class:`~.resilience.faults.NonFiniteStepError`
        instead of silently training on divergence (docs/resilience.md). The
        guard costs one boolean device fetch per step.
        """
        if skip_nonfinite_steps < 0:
            raise ValueError(
                f"skip_nonfinite_steps={skip_nonfinite_steps} must be >= 0 (0 = off)"
            )
        if skip_nonfinite_steps and fused_steps > 1:
            raise ValueError(
                "skip_nonfinite_steps needs the per-step host check; with "
                "fused_steps>1 the applies run inside one XLA program where the "
                "host cannot abort between them — use fused_steps=1"
            )
        if optimizer is None:
            if not self._optimizers:
                raise ValueError("No optimizer prepared; pass one to build_train_step.")
            optimizer = self._optimizers[-1]
        if not isinstance(optimizer, AcceleratedOptimizer):
            optimizer = self.prepare_optimizer(optimizer)
        tx = optimizer.optimizer
        policy = self.mixed_precision_policy
        if max_grad_norm is None:
            max_grad_norm = self._max_grad_norm
        if max_grad_value is None:
            max_grad_value = self._max_grad_value
        accum_steps = self.gradient_accumulation_steps
        wants_rng = _loss_fn_wants_rng(loss_fn)
        # Low-precision cross-device gradient reduction (DDP comm-hook analog): honored
        # when the declared reduce_dtype equals the compute dtype — the grad w.r.t. the
        # cast tree is bit-identical to the grad w.r.t. master params pre-upcast, so the
        # only change is where GSPMD places the all-reduce.
        compress_reduce = (
            cast_params
            and policy.reduce_dtype is not None
            and policy.reduce_dtype == policy.compute_dtype
            and policy.compute_dtype != jnp.float32
        )
        if not cast_params and getattr(self, "_explicit_comm_hook", False):
            # The comm_hook passed __init__'s dtype check, but compression rides the
            # whole-tree pre-cast — with cast_params=False it cannot apply. Same
            # accepted-but-ignored policy as the constructor: raise, don't silently
            # reduce uncompressed. (The bf16/fp16 policy's DEFAULT reduce_dtype is not
            # a user request and does not trigger this.)
            raise ValueError(
                "a gradient-compression comm_hook is configured (reduce_dtype="
                f"{policy.reduce_dtype.__name__}) but build_train_step(cast_params="
                "False) disables the parameter pre-cast it rides on — drop the "
                "comm_hook or keep cast_params=True"
            )
        self._reduce_compressed = compress_reduce  # introspection/testing

        def compute(state: TrainState, batch):
            step_rng = None
            if state.rng is not None:
                # Unique key per micro-batch: step alone would repeat dropout masks across
                # an accumulation window.
                micro = state.micro if state.micro is not None else 0
                step_rng = jax.random.fold_in(state.rng, state.step * accum_steps + micro)

            def wrapped(params):
                cparams = cast_floating(params, policy.compute_dtype) if cast_params else params
                out = loss_fn(cparams, batch, step_rng) if wants_rng else loss_fn(cparams, batch)
                loss, aux = out if has_aux else (out, None)
                return jnp.asarray(loss, dtype=jnp.float32), aux

            if state.fp8_state is not None:
                # Delayed-scaling fp8: thread the rolling-history scales into every fp8_dot.
                # Forward x/w amaxes are observed exactly (global-per-role granularity vs
                # TE's per-module buffers); the GRAD role stays on current scaling — the
                # output cotangent g is quantized inside the custom_vjp, so no faithfully
                # observed g-amax exists at this level, and any proxy (e.g. the dw amax,
                # ~10^3× larger) would underflow small cotangents to zero in e5m2.
                from .ops.fp8 import autoscale_ctx, delayed_scales

                recipe = self.fp8_recipe
                scales = delayed_scales(
                    state.fp8_state, recipe.fp8_format, recipe.margin,
                    recipe.amax_compute_algo,
                ).at[2].set(jnp.nan)  # NaN → fp8_dot falls back to current scaling for g

                def wrapped_fp8(params):
                    # The ctx must open INSIDE the differentiated function: its collected
                    # amaxes are inner-trace values and must leave as aux outputs, not by
                    # escaping through the context dict (tracer leak).
                    with autoscale_ctx(scales) as ctx:
                        loss, aux = wrapped(params)
                        return loss, (aux, ctx["amax"])

                (loss, (aux, fwd_amax)), grads = jax.value_and_grad(
                    wrapped_fp8, has_aux=True
                )(state.params)
                new_fp8 = state.fp8_state.update(
                    fwd_amax[0], fwd_amax[1], jnp.zeros((), jnp.float32)
                )
            elif compress_reduce:
                # reduce_dtype consumer (the DDP bf16 comm-hook analog): differentiate
                # w.r.t. the CAST (compute-dtype) tree and upcast to the master dtype
                # afterwards. Mathematically identical — the backward of the cast IS that
                # upcast — but GSPMD now attaches the cross-device gradient all-reduce to
                # the low-precision tensors, halving the reduction bytes on ICI/DCN.
                cparams = cast_floating(state.params, policy.compute_dtype)

                def inner(cp):
                    out = loss_fn(cp, batch, step_rng) if wants_rng else loss_fn(cp, batch)
                    loss, aux = out if has_aux else (out, None)
                    return jnp.asarray(loss, dtype=jnp.float32), aux

                (loss, aux), gradsc = jax.value_and_grad(inner, has_aux=True)(cparams)
                grads = jax.tree_util.tree_map(
                    lambda g, p: g.astype(p.dtype) if jnp.issubdtype(p.dtype, jnp.floating)
                    else g,
                    gradsc,
                    state.params,
                )
                new_fp8 = None
            else:
                (loss, aux), grads = jax.value_and_grad(wrapped, has_aux=True)(state.params)
                new_fp8 = None
            if self._zero_grad_specs is not None:
                # ZeRO-2: constrain grads onto the fsdp axis — GSPMD lowers the data-axis
                # all-reduce into a reduce-scatter and keeps grads partitioned.
                from .ops.collectives import maybe_shard

                grads = jax.tree_util.tree_map(
                    lambda g, s: maybe_shard(g, s), grads, self._zero_grad_specs
                )
            return loss, aux, grads, new_fp8

        nonfinite_guard = skip_nonfinite_steps > 0

        def _all_finite(loss, grads):
            # One fused reduction over loss + every float grad leaf; int leaves
            # (none today) cannot be non-finite and are skipped.
            finite = jnp.isfinite(loss)
            for leaf in jax.tree_util.tree_leaves(grads):
                if jnp.issubdtype(leaf.dtype, jnp.inexact):
                    finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(leaf)))
            return finite

        def micro_step(state: TrainState, batch):
            loss, aux, grads, new_fp8 = compute(state, batch)
            if nonfinite_guard:
                # A non-finite micro contribution would poison the whole
                # accumulation window: zero it out and flag the step.
                finite = _all_finite(loss, grads)
                grads = jax.tree_util.tree_map(
                    lambda g: jnp.where(finite, g, jnp.zeros_like(g)), grads
                )
            if state.grad_accum is None:
                # First no_sync() use with accumulation disabled: adopt grads as the buffer
                # (structure change → one retrace, then stable).
                accum = grads
            else:
                accum = jax.tree_util.tree_map(jnp.add, state.grad_accum, grads)
            metrics = {"loss": loss}
            if nonfinite_guard:
                metrics["nonfinite"] = jnp.logical_not(finite)
            if has_aux:
                metrics["aux"] = aux
            micro = (state.micro if state.micro is not None else 0) + 1
            return (
                state.replace(
                    grad_accum=accum, micro=jnp.asarray(micro, jnp.int32), fp8_state=new_fp8
                ),
                metrics,
            )

        def apply_step(state: TrainState, batch):
            loss, aux, grads, new_fp8 = compute(state, batch)
            if state.grad_accum is not None:
                grads = jax.tree_util.tree_map(jnp.add, state.grad_accum, grads)
            if accum_steps > 1:
                grads = jax.tree_util.tree_map(lambda g: g / accum_steps, grads)
            finite = _all_finite(loss, grads) if nonfinite_guard else None
            metrics = {"loss": loss}
            # Fused single-pass optimizers (ops/fused_optim.FusedAdamW) take the clip
            # factor as a scalar and fold it into their one HBM pass over the grads —
            # pre-scaling the tree here would cost an extra full read+write.
            # Sharded states: a pallas_call cannot partition under GSPMD, so sharded
            # leaves run the kernel under shard_map with the recorded param specs (valid
            # when moments share the param layout — the create_train_state default, i.e.
            # FSDP/ZeRO-3/TP). ZeRO-1/2 (opt layout differs from params) falls back to
            # tx.update, which FusedAdamW also provides.
            fused_opt = getattr(tx, "fused_apply", None)
            fused_specs = None
            if fused_opt is not None:
                plugin = self.state.fsdp_plugin
                if self._zero_opt_specs is not None or self._zero_param_specs is not None:
                    fused_opt = None
                elif self._params_cross_sharded:
                    fused_specs = self._param_spec_tree
                    if fused_specs is None:
                        fused_opt = None
                elif self._params_cross_sharded is None:
                    # User-managed TrainState (no create_train_state record): the layout
                    # is unknown, so on ANY multi-device mesh assume leaves may be
                    # cross-device sharded (manual NamedShardings, TP without the plugin,
                    # ...) and fall back to tx.update — an unmapped pallas_call would
                    # force GSPMD to gather the full param+moment trees onto one device.
                    if self.mesh is not None and self.mesh.size > 1:
                        fused_opt = None
            grad_scale = None
            if max_grad_value is not None:
                # Elementwise clamp BEFORE the norm clip (a torch user calls
                # clip_grad_value_ then clip_grad_norm_ in that order between backward
                # and step; the norm below is the norm of the clamped tree). Unlike the
                # norm clip this cannot fold into the fused apply's scalar grad_scale —
                # it materializes a clipped tree either way.
                v = jnp.asarray(max_grad_value, jnp.float32)
                grads = jax.tree_util.tree_map(
                    lambda g: jnp.clip(g, -v.astype(g.dtype), v.astype(g.dtype)), grads
                )
            if max_grad_norm is not None:
                gnorm = _global_norm(grads)
                scale = jnp.minimum(1.0, max_grad_norm / (gnorm + 1e-6))
                metrics["grad_norm"] = jnp.asarray(gnorm, jnp.float32)
                if fused_opt is None:
                    grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
                else:
                    grad_scale = scale
            import optax

            if fused_opt is not None:
                new_params, new_opt_state = fused_opt(
                    grads, state.opt_state, state.params,
                    grad_scale=1.0 if grad_scale is None else grad_scale,
                    specs=fused_specs,
                    mesh=self.mesh if fused_specs is not None else None,
                )
                updates = None
            else:
                updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
            if self._zero_opt_specs is not None:
                # ZeRO-1/2: keep optimizer state partitioned over the fsdp axis across steps
                # (params replicated; GSPMD all-gathers the sharded updates below).
                from .ops.collectives import maybe_shard

                new_opt_state = jax.tree_util.tree_map(
                    lambda o, s: maybe_shard(o, s), new_opt_state, self._zero_opt_specs
                )
            if updates is not None:
                new_params = optax.apply_updates(state.params, updates)
            if self._zero_param_specs is not None:
                from .ops.collectives import maybe_shard

                new_params = jax.tree_util.tree_map(
                    lambda p, s: maybe_shard(p, s), new_params, self._zero_param_specs
                )
            new_accum = state.grad_accum
            if new_accum is not None:
                new_accum = jax.tree_util.tree_map(jnp.zeros_like, new_accum)
                if self._zero_grad_specs is not None:
                    from .ops.collectives import maybe_shard

                    new_accum = jax.tree_util.tree_map(
                        lambda a, s: maybe_shard(a, s), new_accum, self._zero_grad_specs
                    )
            if has_aux:
                metrics["aux"] = aux
            step_inc = 1
            if nonfinite_guard:
                # Skip-don't-apply: a non-finite update passes the old params/
                # opt state (and fp8 scales) through unchanged inside the SAME
                # compiled program — no second "skip" executable, no retrace.
                # The window's accumulated garbage is dropped with the reset
                # below; the host counts the skip off metrics["nonfinite"].
                def _keep(new, old):
                    return jax.tree_util.tree_map(
                        lambda n, o: jnp.where(finite, n, o), new, old
                    )

                new_params = _keep(new_params, state.params)
                new_opt_state = _keep(new_opt_state, state.opt_state)
                if state.fp8_state is not None:
                    new_fp8 = _keep(new_fp8, state.fp8_state)
                step_inc = jnp.where(finite, 1, 0)
                metrics["nonfinite"] = jnp.logical_not(finite)
            return (
                state.replace(
                    params=new_params,
                    opt_state=new_opt_state,
                    step=state.step + step_inc,
                    grad_accum=new_accum,
                    # Reset derived from the input, not a fresh constant: XLA cannot
                    # alias a constant output into the donated buffer, so zeros(())
                    # here left state.micro's donation dead (graftaudit dead-donation).
                    # int32 counter — multiply-by-zero is exact.
                    micro=state.micro * 0 if state.micro is not None else None,
                    fp8_state=new_fp8,
                ),
                metrics,
            )

        donate_args = (0,) if donate else ()
        if fused_steps > 1:
            if fused_steps % accum_steps != 0:
                raise ValueError(
                    f"fused_steps ({fused_steps}) must be a multiple of "
                    f"gradient_accumulation_steps ({accum_steps})"
                )
            if self._schedulers:
                raise ValueError(
                    "fused_steps>1 compiles the optimizer applies into one XLA program, so a "
                    "host-stepped scheduler cannot fire between them. Encode the schedule in "
                    "the optimizer instead (e.g. optax.warmup_cosine_decay_schedule passed to "
                    "adamw) — it is traced per-step from the optimizer state's count."
                )

            def micro_step_padded(st, batch):
                # lax.cond branches need identical metric structures; pad the micro branch
                # with the keys only apply_step produces.
                new_st, metrics = micro_step(st, batch)
                if max_grad_norm is not None:
                    metrics["grad_norm"] = jnp.zeros((), jnp.float32)
                return new_st, metrics

            def fused(state: TrainState, batches):
                def body(st, batch):
                    if accum_steps == 1:
                        new_st, metrics = apply_step(st, batch)
                    else:
                        micro = st.micro if st.micro is not None else jnp.zeros((), jnp.int32)
                        is_sync = (micro + 1) % accum_steps == 0
                        new_st, metrics = jax.lax.cond(
                            is_sync, apply_step, micro_step_padded, st, batch
                        )
                    return new_st, metrics

                return jax.lax.scan(body, state, batches)

            jit_fused = self.compile_cache.wrap(
                jax.jit(fused, donate_argnums=donate_args), "train_step.fused"
            )
            return _FusedTrainStep(self, jit_fused, fused_steps, optimizer=optimizer)

        jit_micro = self.compile_cache.wrap(
            jax.jit(micro_step, donate_argnums=donate_args), "train_step.micro"
        )
        jit_apply = self.compile_cache.wrap(
            jax.jit(apply_step, donate_argnums=donate_args), "train_step.apply"
        )
        return _TrainStep(self, jit_micro, jit_apply, optimizer=optimizer,
                          skip_nonfinite_steps=skip_nonfinite_steps)

    def build_eval_step(self, eval_fn: Callable, donate: bool = False) -> Callable:
        """Jit an eval function ``eval_fn(params, batch) -> outputs`` with compute-dtype cast."""
        policy = self.mixed_precision_policy

        def wrapped(params, batch):
            cparams = cast_floating(params, policy.compute_dtype)
            out = eval_fn(cparams, batch)
            if policy.output_dtype == jnp.float32:
                out = cast_floating(out, jnp.float32)
            return out

        jitted = self.compile_cache.wrap(jax.jit(wrapped), "eval_step")
        mesh = self.mesh

        @functools.wraps(wrapped)
        def with_mesh(params, batch):
            with mesh_context(mesh):
                return jitted(params, batch)

        def warm(params, batch):
            # Warmup-manifest hook: prime the AOT cache for this signature without
            # executing the eval (no-op live entry when the cache is disabled).
            if not hasattr(jitted, "warm"):
                return {"label": "eval_step", "key": None, "status": "live", "seconds": 0.0}
            with mesh_context(mesh):
                return jitted.warm(params, batch)

        with_mesh.warm = warm
        return with_mesh

    # -------------------------------------------------------- accumulation / sync contexts
    @contextlib.contextmanager
    def accumulate(self, *models):
        """Gradient-accumulation context (reference ``:1116``).

        Counts entries; ``sync_gradients`` is True every ``gradient_accumulation_steps``-th
        entry or at end-of-dataloader (``sync_with_dataloader``). The jitted step built by
        ``build_train_step`` reads the flag host-side to pick the accumulate vs apply program.
        """
        self._accumulate_count += 1
        at_end = self.gradient_state.sync_with_dataloader and self.gradient_state.end_of_dataloader
        do_sync = (
            (self._accumulate_count % self.gradient_accumulation_steps == 0)
            or at_end
            or self.gradient_state.sync_each_batch
        )
        self.gradient_state._set_sync_gradients(do_sync)
        self._in_accumulate_ctx = True
        try:
            yield
        finally:
            self._in_accumulate_ctx = False

    @contextlib.contextmanager
    def no_sync(self, model=None):
        """Force-skip gradient sync (reference ``:1001``). Under GSPMD this only toggles the
        host flag — the compiled accumulate-variant performs no cross-device grad traffic."""
        prev = self.gradient_state.sync_gradients
        self.gradient_state._set_sync_gradients(False)
        self._in_accumulate_ctx = True
        try:
            yield
        finally:
            self.gradient_state._set_sync_gradients(prev)
            self._in_accumulate_ctx = False

    @contextlib.contextmanager
    def autocast(self, autocast_handler=None):
        """API-parity context (reference ``:3587``): under JAX the compute-dtype cast happens
        inside the compiled step; this context exists so reference-style code runs unchanged."""
        yield

    @contextlib.contextmanager
    def profile(self, profile_handler=None):
        """Profile the enclosed block with ``jax.profiler`` (reference ``:3614``).

        Two modes, decided by ``ProfileKwargs.schedule_option``:

        - **Scheduled** (``schedule_option`` set): yields a
          ``telemetry.ScheduledProfiler`` — call its ``step()`` once per train step
          and traces cover exactly the wait/warmup/active/repeat windows (one
          ``cycle<N>`` trace directory per repeat), the torch
          ``torch.profiler.schedule`` semantics. ``on_trace_ready(path)`` fires per
          window.
        - **Whole-block** (no schedule): the block is captured with one
          ``jax.profiler`` trace (TensorBoard/perfetto-compatible, includes XLA HLO +
          TPU device timelines); ``on_trace_ready(trace_dir)`` fires on exit.

        ``profile_memory`` writes a pprof device-memory profile beside each trace in
        both modes.
        """
        from .utils.dataclasses import ProfileKwargs

        handler = profile_handler or getattr(self, "profile_handler", None) or ProfileKwargs()
        if handler.schedule_option is not None:
            from .telemetry import ScheduledProfiler

            profiler = ScheduledProfiler.from_profile_kwargs(handler)
            if not self.is_main_process:
                # Same contract as the whole-block branch below: the user callback
                # fires once per window, not once per process.
                profiler.on_trace_ready = None
            try:
                yield profiler
            finally:
                profiler.close()
            return
        trace_dir = handler.output_trace_dir
        if trace_dir is None:
            import tempfile

            trace_dir = tempfile.mkdtemp(prefix="accelerate_tpu_trace_")
        os.makedirs(trace_dir, exist_ok=True)
        jax.profiler.start_trace(trace_dir)
        try:
            yield handler
        finally:
            jax.profiler.stop_trace()
            if handler.profile_memory:
                try:
                    jax.profiler.save_device_memory_profile(
                        os.path.join(trace_dir, "device_memory.prof")
                    )
                except Exception:  # backends without a memory profile: trace stands
                    pass
            if handler.on_trace_ready is not None and self.is_main_process:
                handler.on_trace_ready(trace_dir)

    def build_serving_gateway(self, engine, clock=None, tracer=None,
                              engine_factory=None):
        """Front a ``ContinuousBatcher`` with the SLO-aware request gateway
        (``serving_gateway.ServingGateway``), resolved from the state-resident
        ``GatewayConfig`` (``Accelerator(gateway_config=...)`` or
        ``ACCELERATE_GATEWAY`` env) and sharing this accelerator's telemetry
        pipeline. With the config disabled (the default) the engine is returned
        unchanged — callers drive one object either way (both expose
        ``submit``/``step``/``run``/``stats``).

        ``engine`` may also be a LIST of engine replicas: the result is then a
        ``serving_gateway.fleet.FleetRouter`` — the same submit/step/run
        contract over the whole fleet, with health-driven routing, per-replica
        circuit breakers and lossless failover (docs/resilience.md).
        ``engine_factory(rid)`` (fleet only) builds replacement engines for
        replica restarts.

        ``tracer`` threads a request-scoped ``telemetry.tracing.Tracer``
        through gateway AND engine (the gateway hands it to an engine that has
        none), so per-request spans cover the whole lifecycle
        (docs/telemetry.md)."""
        config = self.state.gateway_config
        is_fleet = isinstance(engine, (list, tuple))
        if not config.enabled:
            if is_fleet:
                raise ValueError(
                    "a fleet of engines needs the gateway enabled: there is no "
                    "bare-engine equivalent of a multi-replica router (set "
                    "GatewayConfig(enabled=True) or ACCELERATE_GATEWAY=1)"
                )
            return engine
        kwargs = {} if clock is None else {"clock": clock}
        if is_fleet:
            if config.replica_roles is not None:
                # Role-split fleet (docs/disaggregated_serving.md): prefill
                # replicas export KV page handoffs, decode replicas adopt them.
                from .serving_gateway import DisaggRouter

                return DisaggRouter(list(engine), config,
                                    telemetry=self.telemetry, tracer=tracer,
                                    engine_factory=engine_factory, **kwargs)
            from .serving_gateway import FleetRouter

            return FleetRouter(list(engine), config, telemetry=self.telemetry,
                               tracer=tracer, engine_factory=engine_factory,
                               **kwargs)
        from .serving_gateway import ServingGateway

        return ServingGateway(engine, config, telemetry=self.telemetry,
                              tracer=tracer, **kwargs)

    @contextlib.contextmanager
    def join_uneven_inputs(self, joinables, even_batches: Optional[bool] = None):
        """Reference ``:1197``: with mesh-global batches, uneven inputs are already handled by
        the dataloader's even_batches padding; honor an override for this block."""
        cfg = self.dataloader_config
        prev = cfg.even_batches
        if even_batches is not None:
            cfg.even_batches = even_batches
        try:
            yield
        finally:
            cfg.even_batches = prev

    # ----------------------------------------------------------------- gradient utilities
    def backward(self, loss, **kwargs):
        raise RuntimeError(
            "JAX has no backward tape: gradients are computed inside the compiled train step. "
            "Use `step = accelerator.build_train_step(loss_fn)` and call "
            "`state, metrics = step(state, batch)` — or `accelerator.value_and_grad(loss_fn)` "
            "for manual loops."
        )

    def value_and_grad(self, loss_fn: Callable, has_aux: bool = False) -> Callable:
        """Mixed-precision-aware ``jax.value_and_grad`` for manual training loops."""
        policy = self.mixed_precision_policy

        def wrapped(params, *args, **kwargs):
            def inner(p):
                return loss_fn(cast_floating(p, policy.compute_dtype), *args, **kwargs)

            return jax.value_and_grad(inner, has_aux=has_aux)(params)

        return wrapped

    def clip_grad_norm_(self, max_grad_norm: float):
        """Record the global-norm clip applied inside subsequently-built train steps
        (reference ``:2485``; returns None — the realized norm is in step metrics)."""
        self._max_grad_norm = float(max_grad_norm)

    def clip_grad_value_(self, clip_value: float):
        """Record an elementwise gradient clamp to ``[-clip_value, clip_value]`` applied
        inside subsequently-built train steps (reference ``accelerator.py:2542``
        ``clip_grad_value_`` → ``torch.nn.utils.clip_grad_value_``; here the clamp is
        traced into the step, before any ``clip_grad_norm_`` norm scaling — the order a
        torch user would call the pair in)."""
        self._max_grad_value = float(clip_value)

    # ---------------------------------------------------------------------- metrics / ops
    def set_trigger(self):
        """Arm the cross-process breakpoint flag (reference ``accelerator.py:2569``):
        any process may set it; ``check_trigger`` fires on ALL processes."""
        self.flag_tensor = 1

    def check_trigger(self) -> bool:
        """True on every process if any process called ``set_trigger`` since the last check
        (reference ``:2583``) — the synchronized early-stopping primitive."""
        local = np.asarray([self.flag_tensor or 0], dtype=np.float32)
        fired = float(np.asarray(reduce(local, reduction="sum")).reshape(-1)[0]) > 0
        self.flag_tensor = None
        return fired

    def gather(self, tensor):
        return gather(tensor)

    def gather_for_metrics(self, input_data, use_gather_object: bool = False):
        """Gather + drop the duplicate tail samples of the final batch (reference ``:2601``).

        The dataloader's even_batches padding duplicates samples in the last global batch;
        ``GradientState.remainder`` (set by the prepared dataloader) says how many are real.
        """
        try:
            recursively_apply(lambda x: x, input_data, error_on_other_type=True)
            all_tensors = True
        except TypeError:
            all_tensors = False

        if use_gather_object or not all_tensors:
            data = gather_object(input_data)
        else:
            data = gather(input_data)

        if self.gradient_state.end_of_dataloader:
            remainder = self.gradient_state.remainder
            if remainder > 0:

                def _trim(tensor):
                    return tensor[:remainder]

                try:
                    if use_gather_object or not all_tensors:
                        return data[:remainder]
                    return recursively_apply(_trim, data)
                except (TypeError, IndexError):
                    # Unsliceable payload (objects without __getitem__ → TypeError, 0-d
                    # scalar tensors → IndexError): fall back to untrimmed, matching the
                    # reference's behavior of only trimming indexable containers. Real
                    # errors propagate.
                    logger.warning(
                        "gather_for_metrics could not trim the duplicate tail of the last "
                        "batch; returning untrimmed data"
                    )
                    return data
        return data

    def reduce(self, tensor, reduction: str = "sum", scale: float = 1.0):
        return reduce(tensor, reduction=reduction, scale=scale)

    def pad_across_processes(self, tensor, dim: int = 0, pad_index: int = 0, pad_first: bool = False):
        return pad_across_processes(tensor, dim=dim, pad_index=pad_index, pad_first=pad_first)

    # ----------------------------------------------------------------------- model utils
    def unwrap_model(self, model, keep_fp32_wrapper: bool = True):
        return model

    def get_state_dict(self, model, unwrap: bool = True):
        """Full (unsharded) host state dict of a param pytree (reference ``:3500``)."""
        from .parallel.fsdp import gather_full_params

        return gather_full_params(model)

    def free_memory(self, *objects):
        """Release references + device buffers (reference ``:3545``)."""
        self._models.clear()
        self._optimizers.clear()
        self._schedulers.clear()
        self._dataloaders.clear()
        self.step = 0
        jax.clear_caches()
        return objects

    def clear(self, *objects):
        return self.free_memory(*objects)

    # ------------------------------------------------------------------- checkpoint hooks
    def register_for_checkpointing(self, *objects):
        """Register custom stateful objects for save_state/load_state (reference ``:3067``)."""
        invalid = [o for o in objects if not (hasattr(o, "state_dict") and hasattr(o, "load_state_dict"))]
        if invalid:
            raise ValueError(
                f"Objects {invalid} lack state_dict/load_state_dict and cannot be registered."
            )
        self._custom_objects.extend(objects)

    def register_save_state_pre_hook(self, hook: Callable):
        self._save_model_hooks.append(hook)
        return _RemovableHandle(self._save_model_hooks, hook)

    def register_load_state_pre_hook(self, hook: Callable):
        self._load_model_hooks.append(hook)
        return _RemovableHandle(self._load_model_hooks, hook)

    def save_state(self, output_dir: Optional[str] = None, train_state: Optional[TrainState] = None, **save_kwargs):
        from .checkpointing import save_accelerator_state

        return save_accelerator_state(self, output_dir, train_state=train_state, **save_kwargs)

    def load_state(self, input_dir: Optional[str] = None, train_state: Optional[TrainState] = None, **load_kwargs):
        from .checkpointing import load_accelerator_state

        return load_accelerator_state(self, input_dir, train_state=train_state, **load_kwargs)

    def skip_first_batches(self, dataloader, num_batches: int = 0):
        return skip_first_batches(dataloader, num_batches=num_batches)

    # ------------------------------------------------------------------------ trackers
    def init_trackers(self, project_name: str, config: Optional[dict] = None, init_kwargs: dict = None):
        from .tracking import filter_trackers

        self.trackers = filter_trackers(self.log_with, self.logging_dir, project_name, config, init_kwargs or {})

    @property
    def logging_dir(self):
        return self.project_configuration.logging_dir

    def _telemetry_tracker_sink(self, record: dict) -> None:
        """Fan a telemetry record out to every configured tracker (JSONL gets the raw
        record; scalar backends get it flattened — see tracking.log_telemetry_record)."""
        if self.is_main_process and self.trackers:
            from .tracking import log_telemetry_record

            log_telemetry_record(self.trackers, record, step=record.get("step"))

    def log(self, values: dict, step: Optional[int] = None, log_kwargs: dict = None):
        if self.telemetry.enabled and self.telemetry.config.merge_into_log:
            # Auto-merge the latest step's telemetry columns (prefixed telemetry/, so
            # user keys can never collide; explicit values always win regardless).
            merged = self.telemetry.log_columns()
            if merged:
                values = {**merged, **values}
        if self.is_main_process:
            for tracker in self.trackers:
                tracker.log(values, step=step, **(log_kwargs or {}).get(tracker.name, {}))

    def log_images(self, values: dict, step: Optional[int] = None, log_kwargs: dict = None):
        """Fan ``{name: image array}`` out to every tracker that supports images
        (reference ``tracking.py:251``; unsupported backends warn and skip)."""
        if self.is_main_process:
            for tracker in self.trackers:
                tracker.log_images(
                    values, step=step, **(log_kwargs or {}).get(tracker.name, {})
                )

    def log_table(
        self,
        table_name: str,
        columns: Optional[list] = None,
        data: Optional[list] = None,
        dataframe=None,
        step: Optional[int] = None,
        log_kwargs: dict = None,
    ):
        """Fan a table (``columns`` + ``data`` rows, or a pandas ``dataframe``) out to
        every tracker that supports tables (reference ``tracking.py:360``)."""
        if self.is_main_process:
            for tracker in self.trackers:
                tracker.log_table(
                    table_name, columns=columns, data=data, dataframe=dataframe,
                    step=step, **(log_kwargs or {}).get(tracker.name, {})
                )

    def log_artifact(self, file_path: str, name: Optional[str] = None):
        """Upload a file to every tracker with an artifact store (MLflow/ClearML/WandB
        analog of the reference's artifact logging)."""
        if self.is_main_process:
            for tracker in self.trackers:
                tracker.log_artifact(file_path, name=name)

    def get_tracker(self, name: str, unwrap: bool = False):
        for tracker in self.trackers:
            if tracker.name == name:
                return tracker.tracker if unwrap else tracker
        raise ValueError(f"Tracker {name} not initialized")

    def wait_for_checkpoint(self):
        """Join any in-flight ``save_state(async_save=True)`` disk write."""
        from .checkpointing import wait_for_async_save

        wait_for_async_save()

    def end_training(self):
        self.wait_for_checkpoint()
        self.telemetry.close()
        for tracker in self.trackers:
            tracker.finish()
        self.wait_for_everyone()

    def __repr__(self):
        return (
            f"Accelerator(distributed_type={self.distributed_type}, "
            f"mixed_precision={self.mixed_precision!r}, "
            f"grad_accum={self.gradient_accumulation_steps}, "
            f"mesh={dict(zip(self.mesh.axis_names, self.mesh.devices.shape))})"
        )


class _RemovableHandle:
    def __init__(self, container: list, item):
        self.container = container
        self.item = item

    def remove(self):
        if self.item in self.container:
            self.container.remove(self.item)


# ------------------------------------------------------------------------- type sniffing
def _is_dataloader_like(obj) -> bool:
    if isinstance(obj, (DataLoaderShard, DataLoaderDispatcher)):
        return True
    if type(obj).__module__.startswith("torch.utils.data"):
        return True
    return hasattr(obj, "__iter__") and (hasattr(obj, "batch_sampler") or hasattr(obj, "dataset"))


def _is_optax_transformation(obj) -> bool:
    return (
        hasattr(obj, "init")
        and hasattr(obj, "update")
        and not hasattr(obj, "apply")
        and not isinstance(obj, type)
        and not _is_params_pytree(obj)
    )


def _is_stateful_scheduler(obj) -> bool:
    return hasattr(obj, "step") and hasattr(obj, "state_dict") and not hasattr(obj, "update")


def _is_flax_module(obj) -> bool:
    mod = type(obj).__module__
    return mod.startswith("flax") and hasattr(obj, "apply")


def _is_torch_module(obj) -> bool:
    mod = type(obj).__module__
    return mod.startswith("torch") and hasattr(obj, "forward")


def _is_params_pytree(obj) -> bool:
    if not isinstance(obj, dict) or not obj:
        return False
    leaves = jax.tree_util.tree_leaves(obj)
    return len(leaves) > 0 and all(isinstance(l, (jax.Array, np.ndarray)) for l in leaves)


def _loss_fn_wants_rng(loss_fn) -> bool:
    try:
        sig = inspect.signature(loss_fn)
    except (TypeError, ValueError):
        return False
    params = [
        p
        for p in sig.parameters.values()
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
    ]
    return len(params) >= 3 or "rng" in sig.parameters


def _global_norm(tree) -> jax.Array:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves))
