"""Distributed data pipeline (L2).

TPU-native analog of reference ``data_loader.py`` (/root/reference/src/accelerate/data_loader.py):
``SeedableRandomSampler`` (:72), ``BatchSamplerShard`` (:109), ``IterableDatasetShard`` (:265),
``DataLoaderShard`` (:499, ``__iter__`` :557), ``DataLoaderDispatcher`` (:696),
``prepare_data_loader`` (:988), ``SkipDataLoader`` (:1309), ``skip_first_batches`` (:1349).

Key TPU divergence: sharding happens at **host-process** granularity (one JAX process per TPU
VM host drives several chips), and per-host batches are assembled into a single *global*
``jax.Array`` sharded over the mesh batch axes via ``jax.make_array_from_process_local_data``.
Inside jit nothing ever sees a "per-rank batch" — the mesh does the splitting. The index math
(which rows each host loads) is identical to the reference's rank-sharding math, so the
reference's exhaustive sampler tests translate 1:1 (tests/test_data_loader.py).

Datasets are duck-typed: map-style (``__getitem__`` + ``__len__``) or iterable. torch
DataLoaders are accepted by ``prepare_data_loader`` and re-sharded (their dataset/collate_fn
are reused; torch tensors are converted to numpy on the way out).
"""

from __future__ import annotations

import math
from collections import deque
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence, Union

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .logging import get_logger
from .state import GradientState, PartialState
from .utils.constants import BATCH_AXES
from .utils.operations import (
    broadcast,
    broadcast_object_list,
    concatenate,
    find_batch_size,
    get_data_structure,
    initialize_tensors,
    is_tensor,
    recursively_apply,
    send_to_device,
    slice_tensors,
)
from .utils.random import synchronize_rng_states

logger = get_logger(__name__)

__all__ = [
    "SeedableRandomSampler",
    "BatchSamplerShard",
    "IterableDatasetShard",
    "DataLoader",
    "DataLoaderShard",
    "DataLoaderDispatcher",
    "SkipBatchSampler",
    "SkipDataLoader",
    "prepare_data_loader",
    "skip_first_batches",
    "default_collate",
    "assemble_global_batch",
]


# ------------------------------------------------------------------------------- samplers
class SeedableRandomSampler:
    """Deterministic, epoch-reseeded random permutation sampler.

    Reference ``data_loader.py:72``: identical permutations on every process for a given
    (seed, epoch), so shards never overlap. Uses numpy's Philox-based generator rather than a
    torch generator.
    """

    def __init__(self, data_source, seed: Optional[int] = None, epoch: int = 0):
        self.data_source = data_source
        self.seed = seed if seed is not None else 0
        self.epoch = epoch

    def __len__(self) -> int:
        return len(self.data_source)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __iter__(self) -> Iterator[int]:
        rng = np.random.default_rng(self.seed + self.epoch)
        yield from rng.permutation(len(self.data_source)).tolist()


class SequentialSampler:
    def __init__(self, data_source):
        self.data_source = data_source

    def __len__(self) -> int:
        return len(self.data_source)

    def __iter__(self) -> Iterator[int]:
        yield from range(len(self.data_source))


class BatchSampler:
    """Groups a sampler's indices into batches (torch BatchSampler semantics)."""

    def __init__(self, sampler, batch_size: int, drop_last: bool = False):
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __len__(self) -> int:
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else math.ceil(n / self.batch_size)

    def __iter__(self) -> Iterator[list[int]]:
        batch: list[int] = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.sampler, "set_epoch"):
            self.sampler.set_epoch(epoch)


class BatchSamplerShard:
    """Shard a batch sampler across processes (reference ``data_loader.py:109``).

    Two modes, matching the reference exactly:

    - ``split_batches=False`` (default): the inner sampler yields batches of the *per-process*
      size; process ``p`` receives batches ``p, p+n, p+2n, …``. With ``even_batches=True`` the
      tail is completed by cycling samples from the beginning of the epoch, so every process
      yields the same number of identically-sized batches (a hard requirement under jit: shapes
      must be static).
    - ``split_batches=True``: the inner sampler yields *global* batches whose size must be a
      multiple of ``num_processes``; each process takes its contiguous slice of every batch.
    """

    def __init__(
        self,
        batch_sampler,
        num_processes: int = 1,
        process_index: int = 0,
        split_batches: bool = False,
        even_batches: bool = True,
    ):
        if split_batches and getattr(batch_sampler, "batch_size", None) is not None:
            if batch_sampler.batch_size % num_processes != 0:
                raise ValueError(
                    f"batch_size {batch_sampler.batch_size} must be divisible by "
                    f"num_processes {num_processes} when split_batches=True"
                )
        self.batch_sampler = batch_sampler
        self.num_processes = num_processes
        self.process_index = process_index
        self.split_batches = split_batches
        self.even_batches = even_batches
        self.batch_size = getattr(batch_sampler, "batch_size", None)
        self.drop_last = getattr(batch_sampler, "drop_last", False)

    @property
    def total_length(self) -> int:
        return len(self.batch_sampler)

    def __len__(self) -> int:
        if self.split_batches:
            return len(self.batch_sampler)
        length = len(self.batch_sampler) // self.num_processes
        if len(self.batch_sampler) % self.num_processes != 0 and not self.drop_last:
            if self.even_batches:
                length += 1
            else:
                length += 1 if self.process_index < len(self.batch_sampler) % self.num_processes else 0
        return length

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.batch_sampler, "set_epoch"):
            self.batch_sampler.set_epoch(epoch)

    def __iter__(self) -> Iterator[list[int]]:
        return self._iter_split() if self.split_batches else self._iter_no_split()

    def _iter_split(self):
        initial_batch = None
        for batch in self.batch_sampler:
            if initial_batch is None:
                initial_batch = list(batch)
            chunk = len(batch) // self.num_processes
            if chunk * self.num_processes != len(batch):
                # Uneven final global batch.
                if self.drop_last:
                    continue
                if self.even_batches:
                    batch = list(batch) + initial_batch[: self.batch_size - len(batch)]
                    chunk = len(batch) // self.num_processes
                else:
                    start = self.process_index * chunk
                    end = min(len(batch), (self.process_index + 1) * chunk)
                    if start < len(batch):
                        yield batch[start:end]
                    continue
            yield batch[self.process_index * chunk : (self.process_index + 1) * chunk]

    def _iter_no_split(self):
        batch_size = self.batch_size
        initial_data: list[int] = []  # first samples, banked for tail completion
        cached: list[list[int]] = []
        for batch in self.batch_sampler:
            if not self.drop_last and batch_size is not None:
                if len(initial_data) < self.num_processes * batch_size:
                    initial_data += list(batch)
            cached.append(list(batch))
            if len(cached) == self.num_processes:
                is_full = all(batch_size is None or len(b) == batch_size for b in cached)
                if is_full:
                    yield cached[self.process_index]
                    cached = []
                # A short batch can only be the dataset tail — fall through to tail handling.
        if not cached or self.drop_last:
            return
        # Tail: an incomplete group of batches and/or a short final batch.
        if not self.even_batches:
            if self.process_index < len(cached):
                yield cached[self.process_index]
            return
        # even_batches: flatten the tail and cycle banked samples until every process
        # gets a full-size batch (shapes must be static under jit).
        flat = [i for b in cached for i in b]
        per = batch_size if batch_size is not None else max(len(b) for b in cached)
        target = per * self.num_processes
        while len(flat) < target and initial_data:
            flat += initial_data[: target - len(flat)]
        yield flat[self.process_index * per : (self.process_index + 1) * per]


class IterableDatasetShard:
    """Shard an iterable dataset across processes (reference ``data_loader.py:265``).

    Buffers ``batch_size * num_processes`` examples (split_batches=False) or ``batch_size``
    (True) and yields this process's slice. The tail is completed by cycling from the first
    buffered batch when ``even_batches`` (via ``drop_last=False``).
    """

    def __init__(
        self,
        dataset: Iterable,
        batch_size: int = 1,
        drop_last: bool = False,
        num_processes: int = 1,
        process_index: int = 0,
        split_batches: bool = False,
    ):
        if split_batches and batch_size % num_processes != 0:
            raise ValueError(
                f"batch_size {batch_size} must be divisible by num_processes "
                f"{num_processes} when split_batches=True"
            )
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.num_processes = num_processes
        self.process_index = process_index
        self.split_batches = split_batches

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def __len__(self) -> int:
        n = len(self.dataset)
        real_batch = self.batch_size if self.split_batches else self.batch_size * self.num_processes
        if self.drop_last:
            return (n // real_batch) * real_batch // self.num_processes
        return math.ceil(n / real_batch) * real_batch // self.num_processes

    def __iter__(self):
        real_batch_size = (
            self.batch_size if self.split_batches else self.batch_size * self.num_processes
        )
        process_batch_size = real_batch_size // self.num_processes
        process_slice = range(
            self.process_index * process_batch_size, (self.process_index + 1) * process_batch_size
        )
        first_batch = None
        current_batch: list[Any] = []
        for element in self.dataset:
            current_batch.append(element)
            if len(current_batch) == real_batch_size:
                for i in process_slice:
                    yield current_batch[i]
                if first_batch is None:
                    first_batch = current_batch.copy()
                current_batch = []
        if not self.drop_last and len(current_batch) > 0:
            if first_batch is None:
                first_batch = current_batch.copy()
            while len(current_batch) < real_batch_size:
                current_batch += first_batch[: real_batch_size - len(current_batch)]
            for i in process_slice:
                yield current_batch[i]


# ----------------------------------------------------------------------------- collation
def default_collate(examples: Sequence[Any]):
    """Stack a list of examples into a batch pytree (np.stack per leaf)."""
    first = examples[0]
    if isinstance(first, dict):
        return {k: default_collate([ex[k] for ex in examples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate([ex[i] for ex in examples]) for i in range(len(first)))
    arrs = [np.asarray(_torch_to_np(ex)) for ex in examples]
    return np.stack(arrs)


def _torch_to_np(x):
    if type(x).__module__.startswith("torch"):
        return x.detach().cpu().numpy()
    return x


def _batch_to_numpy(batch):
    return recursively_apply(
        lambda t: np.asarray(_torch_to_np(t)),
        batch,
        test_type=lambda o: is_tensor(o) or type(o).__module__.startswith("torch"),
    )


# ---------------------------------------------------------------------------- dataloaders
class DataLoader:
    """Minimal torch-free DataLoader over a map-style dataset.

    Accepts a ``batch_sampler`` (or builds one from batch_size/shuffle/drop_last) and a
    ``collate_fn``. This is the in-framework stand-in for ``torch.utils.data.DataLoader``; the
    prepared wrappers below accept either.
    """

    def __init__(
        self,
        dataset,
        batch_size: Optional[int] = 1,
        shuffle: bool = False,
        sampler=None,
        batch_sampler=None,
        drop_last: bool = False,
        collate_fn: Optional[Callable] = None,
        generator_seed: Optional[int] = None,
    ):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", None)
            self.drop_last = getattr(batch_sampler, "drop_last", False)
        else:
            if sampler is None:
                if shuffle:
                    sampler = SeedableRandomSampler(dataset, seed=generator_seed or 0)
                else:
                    sampler = SequentialSampler(dataset)
            self.sampler = sampler
            self.batch_size = batch_size
            self.drop_last = drop_last
            self.batch_sampler = BatchSampler(sampler, batch_size, drop_last)

    def __len__(self) -> int:
        return len(self.batch_sampler)

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.batch_sampler, "set_epoch"):
            self.batch_sampler.set_epoch(epoch)

    def __iter__(self):
        for batch_indices in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in batch_indices])


class _PreparedDataLoader:
    """Shared plumbing: GradientState registration + device placement + RNG sync."""

    def __init__(
        self,
        device=None,
        rng_types: Optional[list[str]] = None,
        synchronized_generator=None,
        non_blocking: bool = False,
    ):
        self.device = device
        self.rng_types = rng_types
        self.synchronized_generator = synchronized_generator
        self.non_blocking = non_blocking
        self.gradient_state = GradientState()
        self.end_of_dataloader = False
        self.remainder = -1

    def _place(self, batch):
        batch = _batch_to_numpy(batch)
        if self.device is None:
            return batch
        if isinstance(self.device, (Mesh, NamedSharding)):
            return _make_global_batch(batch, self.device)
        return send_to_device(batch, self.device, non_blocking=self.non_blocking)

    def begin(self):
        self.end_of_dataloader = False
        self.remainder = -1
        self.gradient_state._add_dataloader(self)

    def end(self):
        self.gradient_state._remove_dataloader(self)


def _make_global_batch(batch, device):
    """Assemble per-host numpy batch into a global mesh-sharded jax.Array.

    Single-host: plain sharded device_put. Multi-host: each host contributes its local rows
    via ``make_array_from_process_local_data`` (the MpDeviceLoaderWrapper analog,
    reference ``data_loader.py:646`` — but producing ONE global array, not per-core splits).
    """
    if isinstance(device, Mesh):
        sharding = NamedSharding(device, PartitionSpec(BATCH_AXES))
    else:
        sharding = device

    def _put(t):
        t = np.asarray(t)
        if t.ndim == 0:
            return jax.device_put(t, NamedSharding(sharding.mesh, PartitionSpec()))
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(sharding, t)
        try:
            return jax.device_put(t, sharding)
        except (ValueError, TypeError):
            return jax.device_put(t, NamedSharding(sharding.mesh, PartitionSpec()))

    return recursively_apply(_put, batch)


def assemble_global_batch(batch, device):
    """Public alias of the per-host -> global-array assembly used by the prepared
    dataloaders: single-host sharded ``device_put``; multi-host
    ``make_array_from_process_local_data`` (each host contributes its local rows).
    For custom data paths (e.g. ``lm_dataset.TokenDataset.iter_batches``)."""
    return _make_global_batch(batch, device)


class DataLoaderShard(_PreparedDataLoader):
    """Per-process sharded dataloader (reference ``data_loader.py:499``).

    Iterates the underlying (already index-sharded) dataloader with a device prefetch
    of ``prefetch_depth`` batches (default 1), so ``end_of_dataloader`` is known
    *before* the final batch is yielded (the reference's trick at :557-587) —
    GradientState consumers (optimizer skip logic, ``gather_for_metrics``) depend on
    it. ``jax.device_put`` is asynchronous, so each prefetched batch's H2D transfer
    overlaps the consumer's compute; deeper prefetch trades device memory for more
    overlap when per-batch host work (tokenize/collate) is bursty. At most
    ``prefetch_depth`` batches are in flight (placed but not yet yielded).
    """

    def __init__(
        self,
        dataloader,
        device=None,
        rng_types=None,
        synchronized_generator=None,
        skip_batches: int = 0,
        _non_blocking: bool = False,
        stateful: bool = False,
        prefetch_depth: int = 1,
        **kwargs,
    ):
        super().__init__(
            device=device,
            rng_types=rng_types,
            synchronized_generator=synchronized_generator,
            non_blocking=_non_blocking,
        )
        self.dataloader = dataloader
        self.skip_batches = skip_batches
        if prefetch_depth < 1:
            raise ValueError(f"prefetch_depth={prefetch_depth} must be >= 1")
        self.prefetch_depth = prefetch_depth
        self.iteration = 0
        # Stateful-resume bookkeeping (the torchdata StatefulDataLoader analog, reference
        # checkpointing.py:135-139): ``batches_yielded`` tracks position within the CURRENT
        # epoch; ``_resume_batches`` is the ONE-SHOT skip armed exclusively by
        # load_state_dict (a live counter must never be misread as a resume — peeking a
        # batch or breaking early would otherwise silently skip data next epoch). Enabled
        # by prepare_data_loader(use_stateful_dataloader=True).
        self.stateful = stateful
        self.batches_yielded = 0
        self._resume_batches = 0

    @property
    def dataset(self):
        return getattr(self.dataloader, "dataset", None)

    @property
    def batch_sampler(self):
        return getattr(self.dataloader, "batch_sampler", None)

    def __len__(self) -> int:
        return len(self.dataloader) - self.skip_batches - self._resume_batches

    @property
    def total_batch_size(self) -> int:
        sampler = self.batch_sampler
        if isinstance(sampler, BatchSamplerShard):
            bs = sampler.batch_size or 0
            return bs * (1 if sampler.split_batches else sampler.num_processes)
        return (getattr(self.dataloader, "batch_size", None) or 0) * PartialState().num_processes

    @property
    def total_dataset_length(self) -> int:
        return len(self.dataset) if self.dataset is not None and hasattr(self.dataset, "__len__") else -1

    def set_epoch(self, epoch: int) -> None:
        self.iteration = epoch
        if hasattr(self.dataloader, "set_epoch"):
            self.dataloader.set_epoch(epoch)
        elif hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def __iter__(self):
        if self.rng_types is not None:
            # "generator" sync only applies when a host-side generator actually drives data
            # order; SeedableRandomSampler-based order is (seed, epoch)-deterministic and
            # cannot desync, so no generator exists to synchronize.
            rng_types = [
                r for r in self.rng_types
                if r != "generator" or self.synchronized_generator is not None
            ]
            synchronize_rng_states(rng_types, self.synchronized_generator)
        self.begin()
        try:
            skip = self.skip_batches
            if self._resume_batches and not self.skip_batches:
                # Mid-epoch resume armed by load_state_dict; consumed exactly once.
                skip = self._resume_batches
                self._resume_batches = 0
            self.batches_yielded = 0
            dataloader_iter = iter(self.dataloader)
            depth = self.prefetch_depth
            # Device placement at FETCH time, up to ``depth`` batches ahead of the
            # yield: jax.device_put is asynchronous, so prefetched batches' H2D
            # transfers overlap the consumer's current step even when the consumer
            # blocks on metrics between steps (the MpDeviceLoaderWrapper
            # background-transfer analog, reference data_loader.py:646). The
            # ≥1-batch lookahead also detects the end before the final batch is
            # yielded (end_of_dataloader contract).
            buffered: deque = deque()  # (index, placed batch), yielded from the left
            batch_index = 0  # index of the next batch to FETCH from the inner loader
            exhausted = False
            any_fetched = False
            while True:
                # Top up so the head batch has ``depth`` placed successors in flight.
                while not exhausted and len(buffered) < depth + 1:
                    try:
                        fetched = next(dataloader_iter)
                    except StopIteration:
                        exhausted = True
                        break
                    any_fetched = True
                    if batch_index >= skip:
                        buffered.append((batch_index, self._place(fetched)))
                    batch_index += 1
                if not buffered:
                    if any_fetched and not self.end_of_dataloader:
                        # Every batch was skipped: the epoch still ended (parity with
                        # the historical one-batch-lookahead loop).
                        self.end_of_dataloader = True
                        self.remainder = self._final_remainder()
                    break
                index, batch = buffered.popleft()
                if exhausted and not buffered:
                    self.end_of_dataloader = True
                    self.remainder = self._final_remainder()
                # Count BEFORE the yield: the generator suspends there, so a state_dict
                # taken between batches must already include the batch just handed out.
                self.batches_yielded = index + 1
                yield batch
            if not any_fetched:
                return
            self.iteration += 1
            self.batches_yielded = 0
        finally:
            self.end()

    def state_dict(self) -> dict:
        """Resumable position: epoch + batches consumed within it (stateful mode)."""
        return {"iteration": self.iteration, "batches_yielded": self.batches_yielded}

    def load_state_dict(self, state: dict) -> None:
        if self.skip_batches:
            raise ValueError(
                "load_state_dict on a skip_first_batches-wrapped loader is ambiguous "
                "(two competing resume offsets); restore state on the base loader OR use "
                "skip_first_batches, not both."
            )
        self.iteration = int(state.get("iteration", 0))
        self.batches_yielded = int(state.get("batches_yielded", 0))
        self._resume_batches = self.batches_yielded
        self.set_epoch(self.iteration)

    def _final_remainder(self) -> int:
        length = self.total_dataset_length
        total_bs = self.total_batch_size
        if length >= 0 and total_bs:
            rem = length % total_bs
            return rem if rem != 0 else -1
        return -1


class DataLoaderDispatcher(_PreparedDataLoader):
    """Main-process-reads, broadcast-and-slice dataloader (reference ``data_loader.py:696``).

    Process 0 iterates the *full* dataloader (global batches); each batch's structure is
    broadcast (pickle) then its tensors broadcast and every process slices its shard. Used for
    IterableDatasets without deterministic per-process sharding and ``dispatch_batches=True``.
    """

    def __init__(
        self,
        dataloader,
        device=None,
        split_batches: bool = False,
        skip_batches: int = 0,
        _non_blocking: bool = False,
        **kwargs,
    ):
        super().__init__(device=device, non_blocking=_non_blocking)
        self.dataloader = dataloader
        self.split_batches = split_batches
        self.skip_batches = skip_batches
        self.state = PartialState()
        self.iteration = 0

    @property
    def dataset(self):
        return getattr(self.dataloader, "dataset", None)

    def set_epoch(self, epoch: int) -> None:
        self.iteration = epoch
        if hasattr(self.dataloader, "set_epoch"):
            self.dataloader.set_epoch(epoch)
        elif hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def _fetch_global_batch(self, iterator):
        """Main process fetches; returns (batch_info, stop). Reference ``_fetch_batches`` :778."""
        if self.state.is_main_process:
            if self.split_batches:
                try:
                    batch = _batch_to_numpy(next(iterator))
                except StopIteration:
                    batch = None
            else:
                # Fetch one by one so a partial tail (StopIteration mid-round) is kept,
                # matching the reference's pad-the-last-batch behavior (:871-898).
                batches = []
                for _ in range(self.state.num_processes):
                    try:
                        batches.append(_batch_to_numpy(next(iterator)))
                    except StopIteration:
                        break
                batch = concatenate(batches, dim=0) if batches else None
            batch_info = [get_data_structure(batch) if batch is not None else None, batch is None]
        else:
            batch, batch_info = None, [None, False]
        broadcast_object_list(batch_info)
        if batch_info[1]:
            return None, True
        if not self.state.is_main_process:
            batch = initialize_tensors(batch_info[0])
        batch = broadcast(batch, from_process=0)
        return batch, False

    def __iter__(self):
        self.begin()
        try:
            iterator = iter(self.dataloader) if self.state.is_main_process else iter(())
            batch_index = 0
            current_batch, stop = self._fetch_global_batch(iterator)
            while not stop:
                next_batch, stop = self._fetch_global_batch(iterator)
                if stop:
                    self.end_of_dataloader = True
                    bs = find_batch_size(current_batch)
                    if bs is not None and bs % self.state.num_processes != 0:
                        self.remainder = bs
                if batch_index >= self.skip_batches:
                    yield self._yield_batch(current_batch)
                if stop:
                    break
                current_batch = next_batch
                batch_index += 1
            self.iteration += 1
        finally:
            self.end()

    def _yield_batch(self, global_batch):
        bs = find_batch_size(global_batch)
        n = self.state.num_processes
        if bs is not None and bs % n != 0:
            # Pad with the first rows (reference loops the first batch :871-898).
            pad = n - bs % n

            def _pad(t):
                return np.concatenate([t, t[:pad]], axis=0) if np.ndim(t) > 0 else t

            global_batch = recursively_apply(_pad, global_batch)
            bs += pad
        if self.device is not None and isinstance(self.device, (Mesh, NamedSharding)):
            if jax.process_count() > 1 and bs is not None:
                per = bs // n
                local = slice_tensors(
                    global_batch, slice(self.state.process_index * per, (self.state.process_index + 1) * per)
                )
                return _make_global_batch(local, self.device)
            return _make_global_batch(global_batch, self.device)
        if bs is not None and n > 1:
            per = bs // n
            local = slice_tensors(
                global_batch, slice(self.state.process_index * per, (self.state.process_index + 1) * per)
            )
            return send_to_device(local, self.device) if self.device is not None else local
        return send_to_device(global_batch, self.device) if self.device is not None else global_batch

    def __len__(self) -> int:
        whole_length = len(self.dataloader)
        if self.split_batches:
            return whole_length - self.skip_batches
        return math.ceil(whole_length / self.state.num_processes) - self.skip_batches

    @property
    def total_batch_size(self) -> int:
        bs = getattr(self.dataloader, "batch_size", None) or 0
        return bs * (1 if self.split_batches else self.state.num_processes)

    @property
    def total_dataset_length(self) -> int:
        ds = self.dataset
        return len(ds) if ds is not None and hasattr(ds, "__len__") else -1


# ------------------------------------------------------------------------------ skipping
class SkipBatchSampler:
    """Yields batches of an inner batch sampler from ``skip_batches`` on
    (reference ``data_loader.py:1281``)."""

    def __init__(self, batch_sampler, skip_batches: int = 0):
        self.batch_sampler = batch_sampler
        self.skip_batches = skip_batches
        self.batch_size = getattr(batch_sampler, "batch_size", None)
        self.drop_last = getattr(batch_sampler, "drop_last", False)

    def __iter__(self):
        for index, samples in enumerate(self.batch_sampler):
            if index >= self.skip_batches:
                yield samples

    def set_epoch(self, epoch):
        if hasattr(self.batch_sampler, "set_epoch"):
            self.batch_sampler.set_epoch(epoch)

    @property
    def total_length(self):
        return len(self.batch_sampler)

    def __len__(self):
        return len(self.batch_sampler) - self.skip_batches


class SkipDataLoader(DataLoaderShard):
    """Dataloader that skips the first batches (reference ``data_loader.py:1309``)."""


def skip_first_batches(dataloader, num_batches: int = 0):
    """Return a dataloader resuming mid-epoch (reference ``data_loader.py:1349``).

    For prepared shard/dispatcher loaders, re-wraps with ``skip_batches`` so GradientState
    bookkeeping stays intact; for raw loaders, wraps in ``SkipDataLoader``.
    """
    if isinstance(dataloader, DataLoaderDispatcher):
        return DataLoaderDispatcher(
            dataloader.dataloader,
            device=dataloader.device,
            split_batches=dataloader.split_batches,
            skip_batches=num_batches,
            _non_blocking=dataloader.non_blocking,
        )
    if isinstance(dataloader, DataLoaderShard):
        return DataLoaderShard(
            dataloader.dataloader,
            device=dataloader.device,
            rng_types=dataloader.rng_types,
            synchronized_generator=dataloader.synchronized_generator,
            skip_batches=num_batches,
            _non_blocking=dataloader.non_blocking,
            stateful=dataloader.stateful,
            prefetch_depth=dataloader.prefetch_depth,
        )
    return SkipDataLoader(dataloader, skip_batches=num_batches)


# ------------------------------------------------------------------------------- prepare
def _is_torch_dataloader(obj) -> bool:
    return type(obj).__module__.startswith("torch.utils.data")


def _extract_torch_parts(dataloader):
    """Pull (dataset, batch_sampler, collate_fn, generator_seed) out of a torch DataLoader."""
    import torch.utils.data as tud

    dataset = dataloader.dataset
    collate = dataloader.collate_fn
    batch_sampler = dataloader.batch_sampler
    sampler = getattr(dataloader, "sampler", None)
    shuffle = isinstance(sampler, tud.RandomSampler)
    return dataset, batch_sampler, collate, sampler, shuffle


def prepare_data_loader(
    dataloader,
    device=None,
    num_processes: Optional[int] = None,
    process_index: Optional[int] = None,
    split_batches: bool = False,
    put_on_device: bool = True,
    rng_types: Optional[list[str]] = None,
    dispatch_batches: Optional[bool] = None,
    even_batches: bool = True,
    slice_fn_for_dispatch=None,
    use_seedable_sampler: bool = True,
    data_seed: Optional[int] = None,
    non_blocking: bool = False,
    use_stateful_dataloader: bool = False,
    prefetch_depth: int = 1,
) -> Union[DataLoaderShard, DataLoaderDispatcher]:
    """Shard any dataloader across host processes (reference ``data_loader.py:988``).

    ``device`` may be a ``jax.Device``, ``Mesh`` or ``NamedSharding``; with a mesh, batches are
    assembled into global mesh-sharded ``jax.Array``s (the jit-ready representation).
    """
    state = PartialState()
    if num_processes is None:
        num_processes = state.num_processes
    if process_index is None:
        process_index = state.process_index
    if dispatch_batches is None:
        dispatch_batches = False
    if dispatch_batches and use_stateful_dataloader:
        # A silent epoch-granularity degrade would replay trained batches after preemption.
        raise ValueError(
            "use_stateful_dataloader (mid-epoch resume) is not implemented for "
            "dispatch_batches=True loaders; use shard mode or checkpoint at epoch "
            "boundaries."
        )
    if use_stateful_dataloader and not use_seedable_sampler:
        # Resume-by-count is only sound when the data ORDER is (seed, epoch)-deterministic:
        # with torch's own generator-driven shuffle, a fresh process reshuffles and the
        # skipped count lands on different samples (some trained twice, some never).
        raise ValueError(
            "use_stateful_dataloader requires use_seedable_sampler=True: mid-epoch resume "
            "skips by batch count, which is only correct under a deterministic "
            "(seed, epoch) data order."
        )

    # torch DataLoader → re-wrap into the framework DataLoader with the same pieces.
    synchronized_generator = None
    if _is_torch_dataloader(dataloader):
        dataset, batch_sampler, collate, sampler, shuffle = _extract_torch_parts(dataloader)
        if hasattr(dataset, "__getitem__") and hasattr(dataset, "__len__"):
            if shuffle and use_seedable_sampler:
                sampler = SeedableRandomSampler(dataset, seed=data_seed or 0)
            elif shuffle:
                # Honor the user's request for torch's own (nondeterministic) shuffle
                # order: keep the original torch RandomSampler as the index stream and
                # synchronize its generator across hosts (reference behavior).
                synchronized_generator = getattr(sampler, "generator", None)
            else:
                sampler = SequentialSampler(dataset)
            inner = DataLoader(
                dataset,
                batch_size=dataloader.batch_size,
                sampler=sampler,
                drop_last=dataloader.drop_last,
                collate_fn=collate,
            )
            dataloader = inner
        else:
            # Iterable torch dataset: wrap for dispatch or iterable-shard below.
            pass

    if dispatch_batches:
        if prefetch_depth > 1:
            # Accepted-but-ignored is worse than a warning: the dispatcher's
            # broadcast protocol is lock-step one batch at a time.
            logger.warning(
                "prefetch_depth=%d is not supported by dispatch_batches=True loaders "
                "(main-process broadcast is one batch at a time); running with the "
                "built-in one-batch lookahead",
                prefetch_depth,
            )
        return DataLoaderDispatcher(
            dataloader,
            device=device if put_on_device else None,
            split_batches=split_batches,
            _non_blocking=non_blocking,
        )

    dataset = getattr(dataloader, "dataset", dataloader)
    is_map_style = hasattr(dataset, "__getitem__") and hasattr(dataset, "__len__")

    if num_processes == 1:
        return DataLoaderShard(
            dataloader,
            device=device if put_on_device else None,
            rng_types=rng_types,
            synchronized_generator=synchronized_generator,
            _non_blocking=non_blocking,
            stateful=use_stateful_dataloader,
            prefetch_depth=prefetch_depth,
        )

    if is_map_style and hasattr(dataloader, "batch_sampler"):
        sharded_sampler = BatchSamplerShard(
            dataloader.batch_sampler,
            num_processes=num_processes,
            process_index=process_index,
            split_batches=split_batches,
            even_batches=even_batches,
        )
        inner = DataLoader(
            dataset,
            batch_sampler=sharded_sampler,
            collate_fn=getattr(dataloader, "collate_fn", None) or default_collate,
        )
        return DataLoaderShard(
            inner,
            device=device if put_on_device else None,
            rng_types=rng_types,
            synchronized_generator=synchronized_generator,
            _non_blocking=non_blocking,
            stateful=use_stateful_dataloader,
            prefetch_depth=prefetch_depth,
        )

    # Iterable dataset path.
    shard = IterableDatasetShard(
        dataset,
        batch_size=getattr(dataloader, "batch_size", 1) or 1,
        drop_last=getattr(dataloader, "drop_last", False),
        num_processes=num_processes,
        process_index=process_index,
        split_batches=split_batches,
    )
    inner = _IterableLoader(shard, getattr(dataloader, "collate_fn", None) or default_collate,
                            _per_process_batch_size(dataloader, split_batches, num_processes))
    return DataLoaderShard(
        inner,
        device=device if put_on_device else None,
        rng_types=rng_types,
        _non_blocking=non_blocking,
        stateful=use_stateful_dataloader,
        prefetch_depth=prefetch_depth,
    )


def _per_process_batch_size(dataloader, split_batches, num_processes):
    bs = getattr(dataloader, "batch_size", 1) or 1
    return bs // num_processes if split_batches else bs


class _IterableLoader:
    """Batches an IterableDatasetShard's element stream."""

    def __init__(self, shard: IterableDatasetShard, collate_fn, batch_size: int):
        self.dataset = shard
        self.collate_fn = collate_fn
        self.batch_size = batch_size
        self.drop_last = shard.drop_last

    def set_epoch(self, epoch):
        self.dataset.set_epoch(epoch)

    def __len__(self):
        return math.ceil(len(self.dataset) / self.batch_size)

    def __iter__(self):
        batch = []
        for element in self.dataset:
            batch.append(element)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)
