"""Llama-family decoder LM — the flagship model (BASELINE.md north star: Llama-3-8B FSDP
fine-tune at ≥0.4 MFU on v5e-256).

The reference framework ships no models (it prepares arbitrary ``transformers`` modules); this
framework ships first-class model families because the TPU-native path needs models whose
**sharding is part of their definition**. Every param leaf here has a matching
``PartitionSpec`` in ``partition_specs()`` implementing the Megatron tensor-parallel layout
(column-parallel up-projections, row-parallel down-projections — the torch-TP plan analog,
reference ``dataclasses.py:1863`` / ``accelerator.py:1545-1554``), composable with fsdp-axis
sharding (``parallel/fsdp.py``) and sequence-axis activation sharding.

Pure-functional: ``init_params(cfg, key) -> pytree``; ``forward(params, tokens, cfg)``.
Attention dispatches to the Pallas flash kernel on TPU (``ops/flash_attention.py``) and a pure
XLA reference path elsewhere (``attn_impl``).
"""

from __future__ import annotations

import dataclasses
import math
from collections import OrderedDict
from functools import partial
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..utils.constants import BATCH_AXES, SEQUENCE_AXIS, TENSOR_AXIS
from .common import kv_planes as _kv_planes
from .common import paged_attention_dispatch as _paged_attention
from .common import paged_kv_planes as _paged_kv_planes
from .common import quant_kv as _quant_kv
from .common import read_kv as _read_cache
from .common import write_kv as _write_cache
from .common import write_kv_paged as _write_cache_paged

__all__ = [
    "LlamaConfig",
    "init_params",
    "forward",
    "forward_hidden",
    "forward_pp",
    "head_logits",
    "forward_streamed",
    "loss_fn",
    "loss_fn_pp",
    "score",
    "perplexity",
    "packed_target_mask",
    "segment_mask",
    "segment_positions",
    "partition_specs",
    "CONFIGS",
    "init_cache",
    "init_paged_cache",
    "forward_cached",
    "forward_slots",
    "forward_slots_paged",
    "generate",
    "generate_speculative",
    "generate_streamed",
]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    max_seq: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    tie_embeddings: bool = False
    attn_impl: str = "auto"  # "auto" | "flash" | "xla"
    remat: bool = True       # jax.checkpoint each block (activation checkpointing)
    # Remat policy: "full" recomputes everything (min memory), "dots" saves matmul outputs
    # and recomputes only cheap elementwise ops (jax.checkpoint_policies — trades HBM for
    # ~25-30% less recompute FLOPs), "offload" offloads block inputs to host memory.
    remat_policy: str = "full"
    # jax.checkpoint's prevent_cse. None = auto: False under scan_layers (the scan boundary
    # already isolates the block, and prevent_cse's anti-CSE barriers pessimize XLA's
    # scheduling inside it — the standard setting for scanned transformer stacks), True
    # for the unrolled python-loop stack where CSE could defeat rematerialization.
    remat_prevent_cse: Optional[bool] = None
    scan_layers: bool = False  # lax.scan over stacked layer params (fast compile)
    # lax.scan unroll for the layer stack: >1 gives XLA a bigger basic block to overlap
    # DMA with compute across layer boundaries, costing compile time and program size.
    scan_unroll: int = 1
    use_fp8: bool = False    # fp8-quantized projections (ops/fp8.py, the TE-swap analog)
    fp8_format: Optional[str] = None  # None → the process recipe (FP8RecipeKwargs) decides
    # Mixture-of-Experts (Mixtral-style): 0 = dense MLP. Experts shard over the mesh "ep" axis.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # Cross-entropy chunking (memory): compute logits+logsumexp per sequence chunk of this
    # many tokens under remat instead of materializing fp32 [B,S,V] logits. 0 = auto
    # (chunk only when S*V is large enough to matter), -1 = never chunk.
    loss_chunk: int = 0
    # "auto": loss_chunk logic above. "fused": ops/fused_xent Pallas kernel — the score
    # tiles never leave VMEM (no [tokens, V] logits in HBM at all, fwd or bwd);
    # single-device (multi-device meshes fall back to auto). "fused_dp": the multi-chip
    # variant — shard_map over the batch axes with a replicated head (for dp/fsdp-batch
    # layouts; needs an active mesh context).
    loss_impl: str = "auto"
    # int8 KV cache (inference): store cached k/v as int8 with a per-(token, kv-head)
    # scale — half the cache bytes of bf16, so decode (an HBM gather over the cache)
    # reads half the bytes and a serving engine fits 2× the slots. Dequantization fuses
    # into the attention einsums; no repeated or fp16 copy ever materializes.
    kv_quant: bool = False
    # Sliding-window attention (Mistral-style): position i attends only (i-window, i].
    # 0 = full causal. The flash kernels SKIP kv tiles outside the band, so long-context
    # compute scales with S·window instead of S². Not composable with the sp attention
    # modes (ring/ulysses/allgather) — those raise.
    sliding_window: int = 0
    # Apply the sliding window to every Nth layer only (Gemma-2 alternates banded and
    # full-attention layers: window_every=2 → even layers banded, odd layers full).
    # >1 requires scan_layers=False (the layers are no longer a uniform scan body).
    window_every: int = 1
    # ---- Gemma-family architectural knobs (all default to llama behavior) ----
    head_dim_override: Optional[int] = None  # per-head dim when != d_model // n_heads
    mlp_act: str = "silu"       # "silu" (SwiGLU) | "gelu" (GeGLU, tanh approximation)
    post_norm: bool = False     # extra RMSNorm on each sublayer OUTPUT before the residual
    norm_plus_one: bool = False  # RMSNorm weight stored zero-centered: out = x̂·(1 + w)
    embed_scale: bool = False   # multiply token embeddings by sqrt(d_model)
    attn_scale: Optional[float] = None  # softmax scale override (query_pre_attn_scalar)
    attn_softcap: float = 0.0   # tanh-cap attention scores (in-kernel on the flash path)
    final_softcap: float = 0.0  # tanh-cap output logits
    # Qwen2-style biases on the q/k/v projections (o/MLP stay bias-free).
    qkv_bias: bool = False
    # RoPE frequency scaling for context extension. "llama3" = the Llama-3.1 scheme
    # (per-band scaling: high-frequency bands kept, low-frequency bands divided by
    # ``rope_scaling_factor``, smooth ramp between) — required to load 3.1+ checkpoints.
    rope_scaling: Optional[str] = None
    rope_scaling_factor: float = 8.0
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    rope_original_max: int = 8192
    # ---- LoRA (the reference's peft-integration analog, TPU-native) ----
    # rank>0 adds frozen-base low-rank adapters on ``lora_targets``: the forward computes
    # x@W + (x@A)@B·(alpha/rank) — the base weight is never materialized in adapted form,
    # so memory stays base + O(rank) and the optimizer (``models.lora.lora_optimizer``)
    # holds state only for adapter leaves. Dense projections only (moe experts excluded).
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_targets: tuple = ("wq", "wk", "wv", "wo")

    @property
    def head_dim(self) -> int:
        return self.head_dim_override or self.d_model // self.n_heads

    @property
    def q_per_kv(self) -> int:
        return self.n_heads // self.n_kv_heads


CONFIGS = {
    "llama3-8b": LlamaConfig(
        vocab_size=128256, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8, d_ff=14336
    ),
    "llama3.1-8b": LlamaConfig(
        vocab_size=128256, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        d_ff=14336, max_seq=131072, rope_scaling="llama3",
    ),
    "llama3-70b": LlamaConfig(
        vocab_size=128256, d_model=8192, n_layers=80, n_heads=64, n_kv_heads=8, d_ff=28672
    ),
    "llama2-7b": LlamaConfig(
        vocab_size=32000, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=32, d_ff=11008,
        rope_theta=10000.0, max_seq=4096,
    ),
    "tiny": LlamaConfig(
        vocab_size=256, d_model=128, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=256,
        max_seq=128, remat=False,
    ),
    "debug": LlamaConfig(
        vocab_size=512, d_model=256, n_layers=4, n_heads=8, n_kv_heads=4, d_ff=512,
        max_seq=512, remat=False,
    ),
    "mistral-7b": LlamaConfig(
        vocab_size=32000, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8, d_ff=14336,
        rope_theta=10000.0, max_seq=32768, sliding_window=4096,
    ),
    "gemma2-9b": LlamaConfig(
        vocab_size=256000, d_model=3584, n_layers=42, n_heads=16, n_kv_heads=8,
        d_ff=14336, head_dim_override=256, rope_theta=10000.0, max_seq=8192,
        tie_embeddings=True, mlp_act="gelu", post_norm=True, norm_plus_one=True,
        embed_scale=True, attn_scale=224.0**-0.5, attn_softcap=50.0, final_softcap=30.0,
        sliding_window=4096, window_every=2, norm_eps=1e-6,
    ),
    "qwen2-7b": LlamaConfig(
        vocab_size=152064, d_model=3584, n_layers=28, n_heads=28, n_kv_heads=4,
        d_ff=18944, rope_theta=1e6, max_seq=32768, qkv_bias=True, norm_eps=1e-6,
    ),
    "mixtral-8x7b": LlamaConfig(
        vocab_size=32000, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8, d_ff=14336,
        rope_theta=1e6, max_seq=32768, moe_experts=8, moe_top_k=2,
    ),
    "moe-tiny": LlamaConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=128,
        max_seq=128, remat=False, moe_experts=4, moe_top_k=2,
    ),
}


# --------------------------------------------------------------------------------- params
def _layer_params(cfg: LlamaConfig, key) -> dict:
    # fold_in (not split) so the base-weight stream is bit-identical with lora off/on.
    lora_key = jax.random.fold_in(key, 0x10A4)
    k = jax.random.split(key, 8)
    D, H, K, hd, F = cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.d_ff
    s_in = 1.0 / math.sqrt(D)
    s_ff = 1.0 / math.sqrt(F)
    norm_init = jnp.zeros if cfg.norm_plus_one else jnp.ones  # zero-centered Gemma weights
    params = {
        "ln_attn": norm_init((D,), jnp.float32),
        "wq": jax.random.normal(k[0], (D, H * hd), jnp.float32) * s_in,
        "wk": jax.random.normal(k[1], (D, K * hd), jnp.float32) * s_in,
        "wv": jax.random.normal(k[2], (D, K * hd), jnp.float32) * s_in,
        "wo": jax.random.normal(k[3], (H * hd, D), jnp.float32) * s_in,
        "ln_mlp": norm_init((D,), jnp.float32),
    }
    if cfg.post_norm:
        params["ln_attn_post"] = norm_init((D,), jnp.float32)
        params["ln_mlp_post"] = norm_init((D,), jnp.float32)
    if cfg.qkv_bias:
        params["bq"] = jnp.zeros((H * hd,), jnp.float32)
        params["bk"] = jnp.zeros((K * hd,), jnp.float32)
        params["bv"] = jnp.zeros((K * hd,), jnp.float32)
    if cfg.moe_experts > 0:
        E = cfg.moe_experts
        params["moe"] = {
            "w_router": jax.random.normal(k[7], (D, E), jnp.float32) * s_in,
            "w_gate": jax.random.normal(k[4], (E, D, F), jnp.float32) * s_in,
            "w_up": jax.random.normal(k[5], (E, D, F), jnp.float32) * s_in,
            "w_down": jax.random.normal(k[6], (E, F, D), jnp.float32) * s_ff,
        }
    else:
        params.update({
            "w_gate": jax.random.normal(k[4], (D, F), jnp.float32) * s_in,
            "w_up": jax.random.normal(k[5], (D, F), jnp.float32) * s_in,
            "w_down": jax.random.normal(k[6], (F, D), jnp.float32) * s_ff,
        })
    if cfg.lora_rank > 0:
        r = cfg.lora_rank
        for i, name in enumerate(_lora_target_names(cfg)):
            d_in, d_out = params[name].shape
            # Standard LoRA init: A ~ N(0, 1/d_in), B = 0 → the adapted forward starts
            # exactly equal to the base model.
            params[f"{name}_lora_a"] = (
                jax.random.normal(jax.random.fold_in(lora_key, i), (d_in, r), jnp.float32)
                / math.sqrt(d_in)
            )
            params[f"{name}_lora_b"] = jnp.zeros((r, d_out), jnp.float32)
    return params


def _lora_target_names(cfg: LlamaConfig) -> tuple:
    """The subset of ``cfg.lora_targets`` that exists as dense projections."""
    dense = {"wq", "wk", "wv", "wo"} | (set() if cfg.moe_experts > 0 else {"w_gate", "w_up", "w_down"})
    unknown = set(cfg.lora_targets) - {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"}
    if unknown:
        raise ValueError(f"lora_targets {sorted(unknown)} are not dense projection names")
    return tuple(t for t in cfg.lora_targets if t in dense)


def init_params(cfg: LlamaConfig, key: Optional[jax.Array] = None) -> dict:
    if key is None:
        key = jax.random.PRNGKey(0)  # graftlint: disable=rng-key-reuse(deterministic default init; callers pass a key for real entropy)
    keys = jax.random.split(key, cfg.n_layers + 2)
    scale = 1.0 / math.sqrt(cfg.d_model)
    params = {
        "embed": jax.random.normal(keys[0], (cfg.vocab_size, cfg.d_model), jnp.float32) * scale,
        "layers": [_layer_params(cfg, keys[i + 1]) for i in range(cfg.n_layers)],
        "ln_f": (jnp.zeros if cfg.norm_plus_one else jnp.ones)((cfg.d_model,), jnp.float32),
    }
    if cfg.scan_layers:
        params["layers"] = jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls), *params["layers"]
        )
    if not cfg.tie_embeddings:
        params["lm_head"] = (
            jax.random.normal(keys[-1], (cfg.d_model, cfg.vocab_size), jnp.float32) * scale
        )
    return params


def partition_specs(cfg: LlamaConfig, pp: bool = False, virtual_stages: int = 1) -> dict:
    """Megatron-layout PartitionSpecs, same structure as the params pytree.

    Column-parallel: wq/wk/wv/w_gate/w_up split their output dim over ``tp``.
    Row-parallel: wo/w_down split their input dim over ``tp`` (GSPMD inserts the psum).
    Embedding/lm_head shard the vocab dim (logits stay tp-sharded until the loss psum).

    ``pp=True``: layer params are stage-stacked ``[n_stages, L/n_stages, ...]``
    (``parallel.pp.split_params_into_stages``) with the stage dim sharded over ``pp`` — each
    pipeline stage holds only its own blocks. Embed/ln_f/head stay outside the pipeline
    (replicated over pp; the reference pins them to first/last rank instead —
    ``inference.py:164-168`` — but under GSPMD replicating the cheap ends costs less than the
    extra transfer ticks).
    """
    layer = {
        "ln_attn": P(),
        "wq": P(None, TENSOR_AXIS),
        "wk": P(None, TENSOR_AXIS),
        "wv": P(None, TENSOR_AXIS),
        "wo": P(TENSOR_AXIS, None),
        "ln_mlp": P(),
    }
    if cfg.post_norm:
        layer["ln_attn_post"] = P()
        layer["ln_mlp_post"] = P()
    if cfg.qkv_bias:
        layer["bq"] = P(TENSOR_AXIS)
        layer["bk"] = P(TENSOR_AXIS)
        layer["bv"] = P(TENSOR_AXIS)
    if cfg.moe_experts > 0:
        from ..ops.moe import expert_partition_specs

        layer["moe"] = expert_partition_specs()
    else:
        layer.update({
            "w_gate": P(None, TENSOR_AXIS),
            "w_up": P(None, TENSOR_AXIS),
            "w_down": P(TENSOR_AXIS, None),
        })
    if cfg.lora_rank > 0:
        for name in _lora_target_names(cfg):
            base = layer[name]
            # A inherits the base's INPUT-dim placement, B its OUTPUT-dim placement, so the
            # low-rank path reads the same activation shardings as the base matmul (and the
            # rank dim — tiny — stays unsharded).
            layer[f"{name}_lora_a"] = P(base[0], None)
            layer[f"{name}_lora_b"] = P(None, base[1])
    if pp:
        if not cfg.scan_layers:
            raise ValueError("pipeline parallelism requires cfg.scan_layers=True")
        from ..utils.constants import PIPELINE_AXIS

        # virtual_stages > 1 → interleaved layout [v, n_stages, L/(n·v), ...]: the pp
        # axis on dim 1 so device s hosts the STRIDED virtual stages (see
        # split_params_into_stages).
        from ..parallel.pp import stage_spec_prefix

        layer = jax.tree_util.tree_map(
            lambda spec: P(*stage_spec_prefix(virtual_stages), *spec),
            layer,
            is_leaf=lambda s: isinstance(s, P),
        )
        layers: Any = layer
    elif cfg.scan_layers:
        # Leading stacked-layer dim on every leaf spec (handles the nested moe subtree).
        layer = jax.tree_util.tree_map(
            lambda spec: P(None, *spec), layer, is_leaf=lambda s: isinstance(s, P)
        )
        layers = layer
    else:
        layers = [dict(layer) for _ in range(cfg.n_layers)]
    from ..utils.constants import FSDP_AXIS

    # Vocab dim sharded over (tp, fsdp) together: Megatron vocab-parallel embedding composed
    # with ZeRO-3 memory sharding on the SAME dim. Sharding d_model instead (what fsdp
    # auto-composition would pick) makes the token-lookup gather unshardable — XLA's SPMD
    # partitioner falls back to "involuntary full rematerialization" (replicate + repartition)
    # on every embedding lookup under a dp×fsdp×tp×sp mesh.
    # Under pp, fold the pipeline axis into the same vocab sharding: embed/head sit
    # OUTSIDE the pipeline (every stage runs them), and replicating the untied head costs
    # ~1 GB/device at 8B scale — vocab-sharding over pp makes them cost HBM like one
    # shard, with GSPMD inserting the gather/psum at the lookup / logits matmul.
    vocab_axes = (TENSOR_AXIS, FSDP_AXIS, PIPELINE_AXIS) if pp else (TENSOR_AXIS, FSDP_AXIS)
    specs = {
        "embed": P(vocab_axes, None),
        "layers": layers,
        "ln_f": P(),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, vocab_axes)
    return specs


# -------------------------------------------------------------------------------- forward
def _maybe_shard(x: jax.Array, spec: P) -> jax.Array:
    from ..ops.collectives import maybe_shard

    return maybe_shard(x, spec)


def _rms_norm(x: jax.Array, gamma: jax.Array, eps: float, plus_one: bool = False) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    normed = x.astype(jnp.float32) * jax.lax.rsqrt(var + eps)
    g = gamma.astype(jnp.float32)
    if plus_one:  # Gemma convention: weights stored zero-centered
        g = g + 1.0
    return (normed * g).astype(x.dtype)


def _rope_freqs(cfg: LlamaConfig, hd: int) -> jax.Array:
    """Per-band inverse wavelengths, with optional Llama-3.1 context-extension scaling."""
    freqs = 1.0 / (cfg.rope_theta ** (jnp.arange(0, hd, 2, dtype=jnp.float32) / hd))
    if cfg.rope_scaling is None:
        return freqs
    if cfg.rope_scaling != "llama3":
        raise ValueError(f"rope_scaling={cfg.rope_scaling!r}: expected None or 'llama3'")
    factor = cfg.rope_scaling_factor
    low_wl = cfg.rope_original_max / cfg.rope_low_freq_factor
    high_wl = cfg.rope_original_max / cfg.rope_high_freq_factor
    wavelen = 2.0 * math.pi / freqs
    smooth = (cfg.rope_original_max / wavelen - cfg.rope_low_freq_factor) / (
        cfg.rope_high_freq_factor - cfg.rope_low_freq_factor
    )
    scaled = jnp.where(
        wavelen > low_wl,
        freqs / factor,  # long-wavelength (low-freq) bands: fully scaled
        jnp.where(
            wavelen < high_wl,
            freqs,  # short-wavelength bands: untouched
            (1.0 - smooth) * freqs / factor + smooth * freqs,  # smooth ramp between
        ),
    )
    return scaled


def _rope(x: jax.Array, positions: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """Rotary embedding: x [B, S, H, hd], positions [B, S]."""
    hd = x.shape[-1]
    freqs = _rope_freqs(cfg, hd)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, hd/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _sm_scale(cfg: LlamaConfig) -> float:
    """Softmax scale: 1/sqrt(head_dim) unless the config overrides it (Gemma-2's
    query_pre_attn_scalar)."""
    return cfg.attn_scale if cfg.attn_scale is not None else 1.0 / math.sqrt(cfg.head_dim)


def _softcap(scores: jax.Array, cap: float) -> jax.Array:
    """Gemma-style logit capping: cap·tanh(x/cap) (identity when cap == 0)."""
    return cap * jnp.tanh(scores / cap) if cap else scores


def _attention_xla(q, k, v, mask, cfg: LlamaConfig):
    """Reference attention path: q [B,S,H,hd], kv [B,S,K,hd] → [B,S,H,hd].

    GQA stays grouped: q reshapes to [B,S,K,G,hd] and both einsums contract against the
    UNREPEATED kv — the repeated K/V tensors never materialize."""
    B, S, H, hd = q.shape
    K = k.shape[2]
    G = H // K
    qg = q.reshape(B, S, K, G, hd)
    scores = jnp.einsum("bskgd,btkd->bkgst", qg, k) * _sm_scale(cfg)
    scores = _softcap(scores, cfg.attn_softcap)
    scores = jnp.where(mask[:, None, None, :, :], scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bkgst,btkd->bskgd", probs, v).reshape(B, S, H, hd)


def _attention(q, k, v, mask, cfg: LlamaConfig, segment_ids=None):
    """Family attention via the shared dispatcher (``common.attention_dispatch``):
    sliding windows, Gemma score capping, packing, and the sp modes all flow through;
    the XLA fallback keeps llama's grouped-GQA einsum."""
    from .common import attention_dispatch

    return attention_dispatch(
        q, k, v, mask, impl=cfg.attn_impl, sm_scale=_sm_scale(cfg),
        window=cfg.sliding_window, softcap=cfg.attn_softcap, segment_ids=segment_ids,
        xla_attention=lambda q, k, v, m: _attention_xla(q, k, v, m, cfg),
    )


def _proj(h, w, cfg: LlamaConfig):
    """Projection matmul: plain bf16, fp8-quantized (cfg.use_fp8, the TE-swap analog), or a
    fused dequant-matmul when the weight leaf is int8/int4-quantized (the bnb-swap analog)."""
    from ..ops.quantization import QuantizedWeight, quant_matmul

    if isinstance(w, QuantizedWeight):
        return quant_matmul(h, w, out_dtype=cfg.dtype)
    if cfg.use_fp8:
        from ..ops.fp8 import fp8_dot

        return fp8_dot(h, w, cfg.fp8_format)
    return h @ w.astype(cfg.dtype)


def _proj_l(h, layer, name, cfg: LlamaConfig):
    """``_proj`` + the layer's LoRA delta when adapters exist for ``name``.

    The delta is computed low-rank — ``(h @ A) @ B`` — never as a materialized ``W + AB``,
    so adapted training costs base-weights + O(rank) memory (``models/lora.py``).
    """
    out = _proj(h, layer[name], cfg)
    if cfg.lora_rank > 0 and f"{name}_lora_a" in layer:
        a = layer[f"{name}_lora_a"].astype(cfg.dtype)
        b = layer[f"{name}_lora_b"].astype(cfg.dtype)
        out = out + ((h @ a) @ b) * (cfg.lora_alpha / cfg.lora_rank)
    return out


def _mlp_gate_act(h: jax.Array, cfg: LlamaConfig) -> jax.Array:
    if cfg.mlp_act == "silu":
        return jax.nn.silu(h)
    if cfg.mlp_act == "gelu":  # GeGLU (tanh approximation — Gemma convention)
        return jax.nn.gelu(h, approximate=True)
    raise ValueError(f"mlp_act={cfg.mlp_act!r}: expected 'silu' or 'gelu'")


def _qkv_proj(h, layer, cfg: LlamaConfig):
    """q/k/v projections (+ Qwen2-style biases when ``cfg.qkv_bias``)."""
    q = _proj_l(h, layer, "wq", cfg)
    k = _proj_l(h, layer, "wk", cfg)
    v = _proj_l(h, layer, "wv", cfg)
    if cfg.qkv_bias:
        q = q + layer["bq"].astype(q.dtype)
        k = k + layer["bk"].astype(k.dtype)
        v = v + layer["bv"].astype(v.dtype)
    return q, k, v


def _block(x, layer, positions, mask, cfg: LlamaConfig, segment_ids=None):
    """One transformer block → (x, moe_aux_loss) (aux is 0.0 for dense MLPs)."""
    B, S, D = x.shape
    p1 = cfg.norm_plus_one
    h = _rms_norm(x, layer["ln_attn"], cfg.norm_eps, p1)
    q, k, v = _qkv_proj(h, layer, cfg)
    q = q.reshape(B, S, cfg.n_heads, cfg.head_dim)
    k = k.reshape(B, S, cfg.n_kv_heads, cfg.head_dim)
    v = v.reshape(B, S, cfg.n_kv_heads, cfg.head_dim)
    q = _rope(q, positions, cfg)
    k = _rope(k, positions, cfg)
    attn = _attention(q, k, v, mask, cfg, segment_ids).reshape(
        B, S, cfg.n_heads * cfg.head_dim
    )
    attn_out = _proj_l(attn, layer, "wo", cfg)
    if cfg.post_norm:  # Gemma-2: normalize the sublayer OUTPUT before the residual add
        attn_out = _rms_norm(attn_out, layer["ln_attn_post"], cfg.norm_eps, p1)
    x = x + attn_out
    h = _rms_norm(x, layer["ln_mlp"], cfg.norm_eps, p1)
    if cfg.moe_experts > 0:
        from ..ops.moe import moe_mlp

        y, aux = moe_mlp(
            h, layer["moe"], layer["moe"]["w_router"],
            top_k=cfg.moe_top_k, capacity_factor=cfg.moe_capacity_factor,
            compute_dtype=cfg.dtype,
            # Packing: pad slots neither claim expert capacity nor bias the aux stat.
            token_mask=None if segment_ids is None else (segment_ids != 0),
        )
        return x + y, aux
    gate = _mlp_gate_act(_proj_l(h, layer, "w_gate", cfg), cfg)
    up = _proj_l(h, layer, "w_up", cfg)
    mlp_out = _proj_l(gate * up, layer, "w_down", cfg)
    if cfg.post_norm:
        mlp_out = _rms_norm(mlp_out, layer["ln_mlp_post"], cfg.norm_eps, p1)
    x = x + mlp_out
    return x, jnp.zeros((), jnp.float32)


def _maybe_remat_block(cfg: LlamaConfig):
    """The block fn under the config's activation-checkpointing policy (validated)."""
    from .common import remat_wrap

    return remat_wrap(
        _block, remat=cfg.remat, policy=cfg.remat_policy,
        prevent_cse=cfg.remat_prevent_cse, scan_layers=cfg.scan_layers,
        static_argnums=(4,),
    )


def packed_target_mask(segment_ids: jax.Array) -> jax.Array:
    """Float mask [B, S-1] of valid next-token targets in packed rows: position t's target
    (slot t+1) counts only when it continues the SAME segment and is not padding."""
    seg = segment_ids
    return ((seg[:, 1:] == seg[:, :-1]) & (seg[:, 1:] != 0)).astype(jnp.float32)


def segment_positions(segment_ids: jax.Array) -> jax.Array:
    """Per-segment 0-based positions [B, S] from contiguous ``segment_ids`` (packed rows):
    position = index - index_of_segment_start."""
    B, S = segment_ids.shape
    idx = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    change = jnp.concatenate(
        [jnp.ones((B, 1), bool), segment_ids[:, 1:] != segment_ids[:, :-1]], axis=1
    )
    starts = jax.lax.associative_scan(jnp.maximum, jnp.where(change, idx, 0), axis=1)
    return jnp.where(segment_ids != 0, idx - starts, 0)


def segment_mask(segment_ids: jax.Array) -> jax.Array:
    """Packed-row attention mask [B, S, S]: causal AND same-segment AND not padding.

    ``segment_ids`` [B, S] as produced by ``ops.packing.pack_sequences`` (0 = pad,
    1..k = packed sequences). Sequences in one row cannot attend to each other.
    """
    S = segment_ids.shape[1]
    causal = jnp.tril(jnp.ones((S, S), dtype=jnp.bool_))[None]
    same = segment_ids[:, :, None] == segment_ids[:, None, :]
    live = (segment_ids != 0)[:, None, :]
    return causal & same & live


def forward_hidden(
    params: dict,
    tokens: jax.Array,
    cfg: LlamaConfig,
    positions: Optional[jax.Array] = None,
    shard_activations: bool = True,
    segment_ids: Optional[jax.Array] = None,
) -> tuple[jax.Array, jax.Array]:
    """Backbone: tokens [B, S] → (final hidden states [B, S, D] after ln_f, MoE aux loss).

    Activation sharding constraints pin the batch dim to ``(dp, fsdp)`` and the sequence dim
    to ``sp`` so GSPMD propagates a consistent layout through every block (naive sequence
    parallelism; ring attention in ``ops/ring_attention.py`` upgrades the attention part).

    ``segment_ids`` (sample packing, ``ops/packing.py``): attention is restricted to the
    block-diagonal per-segment causal mask — in-kernel on the flash path, via the explicit
    mask on the XLA path — and positions default to per-segment RoPE restarts (derived from
    the segment ids when not given). The sequence-parallel modes take no mask and fall back.
    """
    B, S = tokens.shape
    dtype = cfg.dtype
    if positions is None:
        positions = (
            # Continuous arange positions would silently run RoPE across segment boundaries.
            segment_positions(segment_ids)
            if segment_ids is not None
            else jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
        )
    x = params["embed"].astype(dtype)[tokens]
    if cfg.embed_scale:
        x = x * jnp.asarray(math.sqrt(cfg.d_model), dtype)
    if shard_activations:
        x = _maybe_shard(x, P(BATCH_AXES, SEQUENCE_AXIS, None))
    if segment_ids is not None:
        # Packing composes with every attention impl: flash takes segment ids IN-KERNEL,
        # xla takes the block-diagonal mask, and the sp modes shard the ids over the sp
        # axis (ring rotates the kv-side slice with its kv block).
        mask = segment_mask(segment_ids)
    else:
        mask = jnp.tril(jnp.ones((S, S), dtype=jnp.bool_))[None, :, :]
    full_mask = mask
    if cfg.sliding_window:
        # Band-limit the XLA-path mask to (i-window, i]; the flash kernels apply the same
        # band in-kernel (and skip out-of-band tiles entirely).
        idx = jnp.arange(S, dtype=jnp.int32)
        mask = mask & (idx[None, :] > idx[:, None] - cfg.sliding_window)[None]

    block = _maybe_remat_block(cfg)

    aux_total = jnp.zeros((), jnp.float32)
    alternating = bool(cfg.sliding_window) and cfg.window_every > 1
    if cfg.scan_layers and alternating:
        # Gemma-2 style alternation under scan: group ``window_every`` consecutive layers
        # into one scan body (layer j of a group is banded iff j == 0 — global index
        # g·per + j keeps j's parity). Compile time stays O(group), not O(L).
        per = cfg.window_every
        if cfg.n_layers % per:
            raise ValueError(
                f"window_every={per} must divide n_layers={cfg.n_layers} under scan_layers"
            )
        full_cfg = dataclasses.replace(cfg, sliding_window=0)
        grouped = jax.tree_util.tree_map(
            lambda a: a.reshape(cfg.n_layers // per, per, *a.shape[1:]), params["layers"]
        )

        def scan_body(carry, group):
            out = carry
            aux_g = jnp.zeros((), jnp.float32)
            for j in range(per):
                layer_j = jax.tree_util.tree_map(lambda a, j=j: a[j], group)
                banded = j == 0
                out, aux_j = block(
                    out, layer_j, positions,
                    mask if banded else full_mask,
                    cfg if banded else full_cfg,
                    segment_ids,
                )
                if shard_activations:
                    out = _maybe_shard(out, P(BATCH_AXES, SEQUENCE_AXIS, None))
                aux_g = aux_g + aux_j
            return out, aux_g

        x, auxes = jax.lax.scan(scan_body, x, grouped, unroll=cfg.scan_unroll)
        aux_total = jnp.sum(auxes)
    elif cfg.scan_layers:
        def scan_body(carry, layer):
            out, aux = block(carry, layer, positions, mask, cfg, segment_ids)
            if shard_activations:
                out = _maybe_shard(out, P(BATCH_AXES, SEQUENCE_AXIS, None))
            return out, aux

        x, auxes = jax.lax.scan(scan_body, x, params["layers"], unroll=cfg.scan_unroll)
        aux_total = jnp.sum(auxes)
    else:
        full_cfg = dataclasses.replace(cfg, sliding_window=0)
        for i, layer in enumerate(params["layers"]):
            banded = cfg.sliding_window and i % cfg.window_every == 0
            x, aux = block(
                x, layer, positions,
                mask if banded else full_mask,
                cfg if banded else full_cfg,
                segment_ids,
            )
            aux_total = aux_total + aux
            if shard_activations:
                x = _maybe_shard(x, P(BATCH_AXES, SEQUENCE_AXIS, None))
    x = _rms_norm(x, params["ln_f"], cfg.norm_eps, cfg.norm_plus_one)
    return x, aux_total


def forward(
    params: dict,
    tokens: jax.Array,
    cfg: LlamaConfig,
    positions: Optional[jax.Array] = None,
    shard_activations: bool = True,
    return_aux: bool = False,
):
    """Causal LM: tokens [B, S] → logits [B, S, V] (fp32); with ``return_aux``, also the summed
    MoE load-balancing loss."""
    x, aux_total = forward_hidden(params, tokens, cfg, positions, shard_activations)
    logits = head_logits(x, params, cfg)
    if return_aux:
        return logits, aux_total
    return logits


def head_logits(x, params: dict, cfg: LlamaConfig) -> jax.Array:
    """Final-hidden → fp32 logits, incl. the Gemma final softcap — part of the model
    family's pipeline contract (``inference.prepare_pippy`` calls it per family)."""
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = (x @ head.astype(cfg.dtype)).astype(jnp.float32)
    return _softcap(logits, cfg.final_softcap)


def _loss_chunk_size(cfg: LlamaConfig, S: int) -> int:
    """Resolve the chunked-CE chunk length for this config (see
    ``common.resolve_loss_chunk`` — the shared single copy of the auto rule)."""
    from .common import resolve_loss_chunk

    return resolve_loss_chunk(cfg.loss_chunk, S, cfg.vocab_size)


def _chunked_ce(x, head, targets, mask, chunk: int, dtype, final_softcap: float = 0.0):
    """Memory-efficient chunked CE (moved to ``common.chunked_ce``; kept as the
    family-local name for callers like ``benchmarks/decompose.py``)."""
    from .common import chunked_ce

    return chunked_ce(x, head, targets, mask, chunk, dtype, final_softcap=final_softcap)


def _ce_from_hidden(x, params, targets, mask, cfg: LlamaConfig) -> jax.Array:
    """Cross-entropy from post-ln_f hidden states (chunked when ``cfg.loss_chunk`` says so)."""
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    denom = jnp.maximum(mask.sum(), 1.0)
    return _ce_sum_impl(x, head, targets, mask, cfg) / denom


def _ce_sum_impl(x, head, targets, mask, cfg: LlamaConfig) -> jax.Array:
    """SUM-style CE dispatcher for this family — delegates to the cross-family
    ``common.ce_sum_dispatch`` (the ONE place every loss_impl routes through), used by
    both the normalized single/GPipe path (``_ce_from_hidden``) and the 1F1B head
    (``_head_ce_sum``, where sums across microbatch groups must add up exactly)."""
    from .common import ce_sum_dispatch

    return ce_sum_dispatch(
        x, head, targets, mask, loss_impl=cfg.loss_impl, dtype=cfg.dtype,
        chunk=_loss_chunk_size(cfg, x.shape[1]), softcap=cfg.final_softcap,
    )


def loss_fn(
    params: dict,
    batch: dict,
    cfg: LlamaConfig,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Next-token cross-entropy over batch {'tokens': [B, S+1]} with optional 'mask'.

    Large-vocab models use the chunked-CE path (``cfg.loss_chunk``): the reference's torch
    loop materializes full fp32 logits, which alone OOMs a 16 GB chip at B8/S2048/V32k.
    """
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    B, S = inputs.shape
    if "segment_ids" in batch:
        # Packed rows (ops/packing.py): a position's next-token target is valid only
        # when the next slot continues the SAME segment (never across a boundary or
        # into padding), and attention/positions are per-segment.
        seg = batch["segment_ids"]
        mask = packed_target_mask(seg)
        if "mask" in batch:
            mask = mask * batch["mask"][:, 1:].astype(jnp.float32)
        positions = (
            batch["positions"][:, :-1]
            if "positions" in batch
            # Without explicit positions, derive them — continuous arange positions would
            # silently run RoPE across segment boundaries.
            else segment_positions(seg[:, :-1])
        )
        x, aux = forward_hidden(
            params, inputs, cfg, positions=positions, segment_ids=seg[:, :-1]
        )
    else:
        mask = (
            batch["mask"][:, 1:].astype(jnp.float32)
            if "mask" in batch
            else jnp.ones((B, S), jnp.float32)
        )
        x, aux = forward_hidden(params, inputs, cfg)
    ce = _ce_from_hidden(x, params, targets, mask, cfg)
    if cfg.moe_experts > 0:
        return ce + cfg.moe_aux_weight * aux
    return ce


def score(
    params: dict,
    tokens: jax.Array,
    cfg: LlamaConfig,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Per-token log-probabilities log p(token[t+1] | tokens[:t+1]) → [B, S-1] fp32.

    The evaluation companion to ``loss_fn`` (which returns their masked mean negated):
    use for perplexity, answer scoring, or re-ranking. ``mask`` [B, S] marks real tokens
    (False on pads); masked target positions score 0.0.
    """
    tokens = jnp.asarray(tokens, jnp.int32)
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits = forward(params, inputs, cfg, shard_activations=False)  # final_softcap applied
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1).squeeze(-1)
    if mask is not None:
        ll = ll * mask[:, 1:].astype(ll.dtype)
    return ll


def perplexity(
    params: dict,
    tokens: jax.Array,
    cfg: LlamaConfig,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """exp(mean negative log-likelihood over real target positions) — scalar fp32."""
    ll = score(params, tokens, cfg, mask)
    if mask is not None:
        denom = jnp.maximum(mask[:, 1:].sum(), 1)
    else:
        denom = ll.size
    return jnp.exp(-ll.sum() / denom)


# --------------------------------------------------------------- pipeline-parallel training
def _pp_microbatches(mesh, num_microbatches) -> int:
    """Resolve M (None → n_stages, make_pipeline_fn's default) — the ONE copy of the
    default both forward_pp's aux normalization and loss_fn_pp's 1f1b aux_weight use,
    so GPipe and 1F1B cannot drift to differently-scaled MoE aux objectives."""
    from ..utils.constants import PIPELINE_AXIS as _PP

    return num_microbatches if num_microbatches is not None else mesh.shape[_PP]


def _pp_stage_fn(
    cfg: LlamaConfig, S: int, with_aux: bool, packed: bool = False,
    sp_manual: bool = False,
):
    """One pipeline stage body, shared by the GPipe (forward_pp) and 1F1B (loss_fn_pp)
    schedules so their numerics cannot drift: scan this stage's blocks over one
    microbatch [B_m, S, D], positions/causal mask rebuilt locally (identical rows).
    ``with_aux`` returns the stage's summed MoE aux alongside the activation.

    ``packed`` (sample packing): the stage takes a third ``side`` argument — the
    pipeline's per-microbatch constants ``{"positions", "segment_ids"}`` [B_m, S]
    (``parallel.pp``'s side-input contract: indexed by microbatch id inside the
    schedule, never ppermuted, non-differentiable) — and restricts attention to the
    block-diagonal per-segment causal mask exactly like ``forward_hidden``."""
    block = _maybe_remat_block(cfg)

    def body_scan(x, stage_layers, pos, mask, seg):
        def body(carry, layer):
            out, aux = block(carry, layer, pos, mask, cfg, seg)
            return out, aux

        out, auxes = jax.lax.scan(body, x, stage_layers)
        if with_aux:
            return out, jnp.sum(auxes)
        return out

    if packed and sp_manual:
        # packing × sp × pp: activations AND the side constants arrive sequence-sliced
        # ([B_m, S/sp, D] and [B_m, S/sp] — loss_fn_pp passes the matching side_spec).
        # Positions are the pre-computed per-segment RoPE restarts (global array,
        # sliced); attention dispatches to the flat ring/ulysses collectives inside
        # _attention with the LOCAL segment slice (ring rotates the kv-side ids).
        def stage_fn(stage_layers, x, side):
            return body_scan(
                x, stage_layers, side["positions"], None, side["segment_ids"]
            )

        return stage_fn

    if packed:
        def stage_fn(stage_layers, x, side):
            seg = side["segment_ids"]
            return body_scan(x, stage_layers, side["positions"], segment_mask(seg), seg)

        return stage_fn

    if sp_manual:
        # sp×pp: the pipeline's shard_map is manual over sp too, so x arrives
        # SEQUENCE-SLICED [B_m, S/sp, D]. RoPE needs the slice's global positions;
        # attention dispatches to the flat ring/ulysses collectives inside _attention
        # (no mask — the sp kernels handle causality with global offsets in-kernel).
        def stage_fn(stage_layers, x):
            S_loc = x.shape[1]
            offs = jax.lax.axis_index(SEQUENCE_AXIS) * S_loc
            pos = jnp.broadcast_to(
                offs + jnp.arange(S_loc, dtype=jnp.int32), (x.shape[0], S_loc)
            )
            return body_scan(x, stage_layers, pos, None, None)

        return stage_fn

    def stage_fn(stage_layers, x):
        pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (x.shape[0], S))
        mask = jnp.tril(jnp.ones((S, S), dtype=jnp.bool_))[None, :, :]
        return body_scan(x, stage_layers, pos, mask, None)

    return stage_fn


def forward_pp(
    params: dict,
    tokens: jax.Array,
    cfg: LlamaConfig,
    mesh,
    num_microbatches: Optional[int] = None,
    shard_activations: bool = True,
    return_aux: bool = False,
    segment_ids: Optional[jax.Array] = None,
    positions: Optional[jax.Array] = None,
):
    """Causal LM forward with the transformer blocks run as a GPipe pipeline over ``pp``.

    ``params["layers"]`` must be stage-stacked ``[n_stages, L/n, ...]`` (scan_layers params
    through ``parallel.pp.split_params_into_stages``; specs from ``partition_specs(cfg,
    pp=True)``). Embed and head run outside the pipeline on every device (vocab-dim sharded
    over pp×tp by ``partition_specs(pp=True)`` so they cost HBM like one shard, not one
    replica). The whole schedule is one differentiable scan, so the same function trains —
    unlike the reference, whose pipelining is inference-only (``inference.py:82-121``).

    MoE configs run through the pipeline too (the reference's engine runs MoE models,
    ``/root/reference/src/accelerate/utils/dataclasses.py:1105``): the expert dispatch
    lives inside the stage body with ``ep``/``tp`` left to GSPMD (the pp shard_map is
    manual over ``pp`` only), and per-(stage, microbatch) load-balancing aux losses are
    masked to real ticks and summed across the pipeline. Routing/capacity are
    per-microbatch, so MoE aux/dropping match a non-pipelined run only in the no-drop
    regime (capacity_factor high enough) — same caveat as any GPipe MoE.
    Returns hidden states [B, S, D]; MoE aux is returned when ``return_aux``.
    """
    from ..parallel.pp import make_pipeline_fn

    B, S = tokens.shape
    dtype = cfg.dtype
    is_moe = cfg.moe_experts > 0
    packed = segment_ids is not None
    stage_fn = _pp_stage_fn(cfg, S, with_aux=is_moe, packed=packed)
    side = None
    if packed:
        if positions is None:
            positions = segment_positions(segment_ids)
        side = {"positions": positions, "segment_ids": segment_ids}

    x = params["embed"].astype(dtype)[tokens]
    if shard_activations:
        x = _maybe_shard(x, P(BATCH_AXES, None, None))
    pipe = make_pipeline_fn(
        mesh, stage_fn, num_microbatches=num_microbatches, with_aux=is_moe
    )
    if is_moe:
        x, aux = pipe(params["layers"], x, side=side)
        # load_balancing_loss is a batch-size-invariant MEAN statistic (~1 at balance):
        # the pipeline sums one value per (stage, microbatch), so divide by M to keep
        # moe_aux_weight meaning the same thing as the non-pipelined path — otherwise
        # retuning num_microbatches (a throughput knob) would silently rescale the
        # training objective.
        aux = aux / _pp_microbatches(mesh, num_microbatches)
    else:
        x, aux = pipe(params["layers"], x, side=side), jnp.zeros((), jnp.float32)
    x = _rms_norm(x, params["ln_f"], cfg.norm_eps, cfg.norm_plus_one)
    if return_aux:
        return x, aux
    return x


def _head_ce_sum(hp: dict, y: jax.Array, ex: dict, cfg: LlamaConfig) -> jax.Array:
    """SUM-style ln_f + CE head over one microbatch (the 1F1B last-stage loss):
    ``hp = {"ln_f", "head" [D, V]}``, ``ex = {"targets", "mask"}``. Sums across
    microbatches add up to the full-batch numerator; normalization stays outside.
    Delegates to ``_ce_sum_impl`` so the CE math (including the fused kernel variants)
    cannot drift from the GPipe/sequential paths."""
    x = _rms_norm(y, hp["ln_f"], cfg.norm_eps, cfg.norm_plus_one)
    return _ce_sum_impl(x, hp["head"], ex["targets"], ex["mask"], cfg)


def loss_fn_pp(
    params: dict,
    batch: dict,
    cfg: LlamaConfig,
    mesh,
    num_microbatches: Optional[int] = None,
    rng: Optional[jax.Array] = None,
    schedule: str = "gpipe",
    virtual_stages: int = 1,
) -> jax.Array:
    """Pipeline-parallel next-token cross-entropy (same contract as ``loss_fn``,
    including sample packing: ``segment_ids`` ride the pipeline as per-microbatch side
    constants — ``parallel.pp``'s side-input contract — restricting attention to the
    block-diagonal per-segment mask with per-segment RoPE restarts, both schedules).

    ``virtual_stages=v > 1`` (interleaved virtual pipeline, 1f1b only): layers in the
    ``split_params_into_stages(..., virtual_stages=v)`` layout with specs from
    ``partition_specs(pp=True, virtual_stages=v)`` — the bubble amortizes ≈ v×.

    ``schedule="1f1b"`` routes through ``parallel.pp.make_pipeline_loss_fn``: the custom
    VJP's hand-scheduled one-forward-one-backward keeps in-flight activations bounded by
    the stage count instead of ``num_microbatches``. ln_f + the CE head run OUTSIDE the
    pipeline on the full batch (ordinary GSPMD — every ``loss_impl`` incl. the fused
    kernels works); MoE stages carry their load-balancing aux through the replay with
    the same /num_microbatches normalization as GPipe."""
    if schedule not in ("gpipe", "1f1b"):
        # Mirrors PipelineParallelPlugin's validation: an unrecognized schedule (e.g. a
        # typo'd ACCELERATE_PP_SCHEDULE) must not silently run GPipe.
        raise ValueError(f"schedule={schedule!r}: expected 'gpipe' or '1f1b'")
    # sp×pp (VERDICT r3 #10): family-shared routing (see common.resolve_sp_pipeline for
    # the full rationale + the ulysses→ppermute substitution under 1f1b). MoE composes
    # too: each sp member routes/dispatches its OWN sequence slice (per-slice capacity —
    # exact parity in the no-drop regime, the standard MoE-under-resharding caveat) and
    # the aux statistic is psum-meaned over sp.
    from .common import resolve_sp_pipeline

    sp_pipeline, cfg = resolve_sp_pipeline(cfg, mesh, schedule, virtual_stages)
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    B, S = inputs.shape
    if "segment_ids" in batch:
        # Packed rows — same target-mask / per-segment-position semantics as loss_fn.
        seg = batch["segment_ids"]
        mask = packed_target_mask(seg)
        if "mask" in batch:
            mask = mask * batch["mask"][:, 1:].astype(jnp.float32)
        positions = (
            batch["positions"][:, :-1]
            if "positions" in batch
            else segment_positions(seg[:, :-1])
        )
        seg_in = seg[:, :-1]
        side = {"positions": positions, "segment_ids": seg_in}
    else:
        mask = (
            batch["mask"][:, 1:].astype(jnp.float32)
            if "mask" in batch
            else jnp.ones((B, S), jnp.float32)
        )
        seg_in = None
        side = None
    if virtual_stages > 1 and schedule != "1f1b":
        # (packing, sp-in-pp, and MoE all compose with virtual stages — only the
        # schedule restriction remains.)
        raise NotImplementedError(
            "virtual_stages > 1 requires schedule='1f1b' (parallel/pp.py)"
        )
    if schedule == "1f1b" or sp_pipeline:
        from ..parallel.pp import make_pipeline_loss_fn

        dtype = cfg.dtype
        is_moe = cfg.moe_experts > 0
        M = _pp_microbatches(mesh, num_microbatches)
        stage_fn = _pp_stage_fn(
            cfg, S, with_aux=is_moe, packed=side is not None, sp_manual=sp_pipeline
        )
        head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
        hp = {"ln_f": params["ln_f"], "head": head}

        def head_loss(h, y, ex):
            # MEAN-normalized inside (the head runs on the FULL batch, so the denom is
            # exact here) — the aux term must NOT be divided by the token count.
            return _head_ce_sum(h, y, ex, cfg=cfg) / jnp.maximum(ex["mask"].sum(), 1.0)

        pipe_loss = make_pipeline_loss_fn(
            mesh, stage_fn, head_loss,
            num_microbatches=num_microbatches, schedule=schedule,
            with_aux=is_moe,
            # Same normalization as the GPipe path: aux is a mean statistic summed over
            # (stage, microbatch) pairs → divide by M so moe_aux_weight keeps its
            # non-pipelined meaning.
            aux_weight=(cfg.moe_aux_weight / M) if is_moe else 0.0,
            # sp×pp: activations ride sequence-sliced through a pipeline that is manual
            # over sp too (microbatch layout [M, B_m, S, D] → sp on dim 2). Packed
            # batches slice their side constants the same way (side_spec): each sp
            # member's stage sees its own [B_m, S/sp] positions/segment ids, and the
            # ring rotates the kv-side segment slice with its kv block.
            act_spec=P(None, None, SEQUENCE_AXIS, None) if sp_pipeline else None,
            extra_manual_axes=(SEQUENCE_AXIS,) if sp_pipeline else (),
            virtual_stages=virtual_stages,
            side_spec=(
                {"positions": P(None, None, SEQUENCE_AXIS),
                 "segment_ids": P(None, None, SEQUENCE_AXIS)}
                if (sp_pipeline and side is not None) else None
            ),
        )
        x = params["embed"].astype(dtype)[inputs]
        return pipe_loss(
            params["layers"], hp, x, {"targets": targets, "mask": mask}, side=side
        )
    x, aux = forward_pp(
        params, inputs, cfg, mesh, num_microbatches=num_microbatches, return_aux=True,
        segment_ids=seg_in, positions=side["positions"] if side else None,
    )
    ce = _ce_from_hidden(x, params, targets, mask, cfg)
    if cfg.moe_experts > 0:
        return ce + cfg.moe_aux_weight * aux
    return ce


@partial(jax.jit, static_argnames=("cfg",))
def _block_jit(x, layer, positions, mask, cfg):
    """Module-level jit: stable identity → one compilation per (config, shapes) across
    repeated forward_streamed calls."""
    return _block(x, layer, positions, mask, cfg)


def forward_streamed(
    dispatched,
    tokens: jax.Array,
    cfg: LlamaConfig,
    positions: Optional[jax.Array] = None,
    prefetch: int = 2,
) -> jax.Array:
    """Big-model inference forward: block weights streamed from host RAM / disk.

    The L6 path (``big_modeling.dispatch_model`` + ``stream_blocks``): runs a model whose
    params exceed HBM by fetching one transformer block at a time onto the main device, with a
    background thread prefetching the next block while the current one computes. Equivalent in
    role to the reference's ``AlignDevicesHook`` forward (``hooks.py:329``), functional instead
    of module-patching. Requires ``cfg.scan_layers=False`` (blocks addressed as ``layers/<i>``).
    """
    from ..big_modeling import consume_block, stream_blocks

    if cfg.scan_layers:
        raise ValueError("forward_streamed requires per-layer (non-scanned) params.")
    B, S = tokens.shape
    dtype = cfg.dtype
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    mask = jnp.tril(jnp.ones((S, S), dtype=jnp.bool_))[None, :, :]

    embed = dispatched.fetch("embed")
    x = embed[tokens].astype(dtype)  # gather then cast (host-driven loop; see generate_streamed)
    prefixes = [f"layers/{i}" for i in range(cfg.n_layers)]
    for name, layer in stream_blocks(dispatched, prefixes, prefetch=prefetch):
        x, _ = _block_jit(x, layer, positions, mask, cfg=cfg)
        consume_block(x, layer, dispatched, name)  # fence + free (big_modeling.consume_block)
    ln_f = dispatched.fetch("ln_f")
    x = _rms_norm(x, ln_f, cfg.norm_eps)
    head = embed if cfg.tie_embeddings else dispatched.fetch("lm_head")
    eq = "bsd,vd->bsv" if cfg.tie_embeddings else "bsd,dv->bsv"
    return jnp.einsum(eq, x, head.astype(dtype)).astype(jnp.float32)


# ----------------------------------------------------------------------- cached generation
def init_cache(
    cfg: LlamaConfig, batch_size: int, max_len: int, dtype=None,
    quantized: Optional[bool] = None,
) -> dict:
    """Allocate an empty KV cache for ``batch_size`` sequences of up to ``max_len`` tokens.

    Layout: ``{"layers": [{"k": [B,C,K,hd], "v": ...}, ...], "valid": [B,C] bool,
    "index": int32}`` — ``valid`` marks filled, non-pad slots (False on left-pads), ``index``
    is the next write slot.  With ``cfg.scan_layers`` the per-layer dicts are stacked on a
    leading layer dim, matching the stacked param layout.  The reference's decode baselines
    come from transformers' cache via hook dispatch (``benchmarks/big_model_inference``);
    here the cache is an explicit pytree so the whole decode loop jits.

    ``quantized`` (default ``cfg.kv_quant``): int8 k/v plus per-(token, kv-head) fp32
    scales — half the cache HBM of bf16. ``_block_cached`` quantizes on write and fuses
    dequantization into the attention reads.
    """
    quantized = cfg.kv_quant if quantized is None else quantized
    dtype = dtype or cfg.dtype
    one = lambda: _kv_planes(  # noqa: E731
        batch_size, max_len, cfg.n_kv_heads, cfg.head_dim, dtype, quantized
    )
    if cfg.scan_layers:
        layers = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x, (cfg.n_layers, *x.shape)), one()
        )
    else:
        layers = [one() for _ in range(cfg.n_layers)]
    return {
        "layers": layers,
        "valid": jnp.zeros((batch_size, max_len), jnp.bool_),
        "index": jnp.zeros((), jnp.int32),
    }


def init_paged_cache(
    cfg: LlamaConfig, batch_size: int, max_len: int, num_pages: int, page_size: int,
    dtype=None, quantized: Optional[bool] = None,
) -> dict:
    """Allocate an empty PAGED KV cache: a shared pool of ``num_pages`` fixed-size
    pages instead of a dense ``[B, max_len]`` row per lane.

    Layout: ``{"layers": [{"k": [P,ps,K,hd], "v": ...}, ...], "valid": [B,max_len]
    bool}`` — per-layer pool planes (stacked on a leading layer dim under
    ``cfg.scan_layers``), plus the per-lane valid mask, which stays DENSE by logical
    position (bools are ~1/2(head_dim·heads·bytes·layers)00th of the K/V bytes; the
    pool is where the memory goes). Which lane owns which page lives OUTSIDE the
    pytree in the host-side ``paged_kv.BlockManager`` block table, uploaded per step —
    so page allocation/release never rebuilds device state. ``quantized`` (default
    ``cfg.kv_quant``): int8 pages with per-slot fp32 scale pages — half the pool HBM.
    """
    quantized = cfg.kv_quant if quantized is None else quantized
    dtype = dtype or cfg.dtype
    one = lambda: _paged_kv_planes(  # noqa: E731
        num_pages, page_size, cfg.n_kv_heads, cfg.head_dim, dtype, quantized
    )
    if cfg.scan_layers:
        layers = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x, (cfg.n_layers, *x.shape)), one()
        )
    else:
        layers = [one() for _ in range(cfg.n_layers)]
    return {
        "layers": layers,
        "valid": jnp.zeros((batch_size, max_len), jnp.bool_),
    }


def _attention_cached(q, ck, cv, q_positions, valid, cfg: LlamaConfig):
    """q [B,T,H,hd] against the full cache ck/cv [B,C,K,hd]; ``valid`` [B,C] marks live keys.

    Causality: key slot j may be seen by the query at absolute slot p iff ``j <= p``.
    Single-token decode (T=1) is a pure HBM-bandwidth gather — the XLA path is the right
    kernel; flash only pays off for the (uncached) training/prefill shapes.
    """
    B, T, H, hd = q.shape
    C = ck.shape[1]
    K = ck.shape[2]
    G = H // K
    # Grouped-query decode: contract against the UNREPEATED cache. Decode (T=1) is an
    # HBM-bandwidth gather over the cache, so never repeating it reads H/K× fewer bytes.
    qg = q.reshape(B, T, K, G, hd)
    scores = jnp.einsum("btkgd,bckd->bkgtc", qg, ck) * _sm_scale(cfg)
    scores = _softcap(scores, cfg.attn_softcap)
    slots = jnp.arange(C)[None, None, :]
    causal = slots <= q_positions[:, :, None]  # [B,T,C]
    if cfg.sliding_window:
        causal = causal & (slots > q_positions[:, :, None] - cfg.sliding_window)
    mask = (causal & valid[:, None, :])[:, None, None, :, :]  # [B,1,1,T,C]
    scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bkgtc,bckd->btkgd", probs, cv).reshape(B, T, H, hd)


def _block_cached(x, layer, kv, index, positions, valid, cfg: LlamaConfig,
                  moe_dense: Optional[bool] = None, paged=None):
    """One block with KV-cache read/write → (x, new_kv).

    ``index`` is the write slot: a SCALAR advances every row together (generate's
    prefill/decode), a VECTOR [B] gives each row its own slot (the continuous-batching
    engine, ``serving.py`` — T == 1 decode, or T == k for the batched speculative
    verify, where row b writes slots ``index[b] .. index[b]+T-1``).

    ``moe_dense`` forces the drop-free dense MoE routing regardless of T (default:
    dense iff T == 1). The speculative verify passes True — every verified position
    must route exactly like the T == 1 decode it replaces, or acceptance would compare
    against capacity-pooled logits and break decode parity.

    ``paged`` — ``(tables, pages, offs, start_positions, page_size)`` switches the KV
    side to the paged pool layout (``kv`` then holds [P, page_size, K, hd] pool planes;
    ``index`` is unused): writes scatter through the precomputed physical (page, slot)
    grid, reads go through ``common.paged_attention_dispatch`` (Pallas kernel on TPU,
    gather into THIS function's own ``_attention_cached`` on CPU — bitwise the dense
    path there).
    """
    B, T, D = x.shape
    if moe_dense is None:
        moe_dense = T == 1
    p1 = cfg.norm_plus_one
    h = _rms_norm(x, layer["ln_attn"], cfg.norm_eps, p1)
    q, k, v = _qkv_proj(h, layer, cfg)
    q = q.reshape(B, T, cfg.n_heads, cfg.head_dim)
    k = k.reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
    v = v.reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
    q = _rope(q, positions, cfg)
    k = _rope(k, positions, cfg)
    if paged is not None:
        tables, pages, offs, start_pos, page_size = paged
        new_kv = {**_write_cache_paged(kv, "k", k, pages, offs),
                  **_write_cache_paged(kv, "v", v, pages, offs)}
        attn = _paged_attention(
            q, new_kv, tables, start_pos, valid, page_size=page_size,
            sm_scale=_sm_scale(cfg), window=cfg.sliding_window,
            softcap=cfg.attn_softcap, dtype=cfg.dtype,
            dense_attention=lambda ck, cv: _attention_cached(
                q, ck, cv, positions, valid, cfg
            ),
        )
    else:
        new_kv = {**_write_cache(kv, "k", k, index), **_write_cache(kv, "v", v, index)}
        attn = _attention_cached(
            q, _read_cache(new_kv, "k", cfg.dtype), _read_cache(new_kv, "v", cfg.dtype),
            positions, valid, cfg,
        )
    attn_out = _proj_l(attn.reshape(B, T, cfg.n_heads * cfg.head_dim), layer, "wo", cfg)
    if cfg.post_norm:
        attn_out = _rms_norm(attn_out, layer["ln_attn_post"], cfg.norm_eps, p1)
    x = x + attn_out
    h = _rms_norm(x, layer["ln_mlp"], cfg.norm_eps, p1)
    if cfg.moe_experts > 0:
        from ..ops.moe import moe_mlp, moe_mlp_dense

        if moe_dense:
            # Decode: drop-free dense routing — capacity pooling over a single-token step
            # would drop tokens whenever a step's rows collide on an expert (training's
            # fixed-shape load-management artifact, wrong for inference).
            y = moe_mlp_dense(
                h, layer["moe"], layer["moe"]["w_router"],
                top_k=cfg.moe_top_k, compute_dtype=cfg.dtype,
            )
        else:
            # Prefill: identical pooled formulation (and token set) as the training forward.
            y, _ = moe_mlp(
                h, layer["moe"], layer["moe"]["w_router"],
                top_k=cfg.moe_top_k, capacity_factor=cfg.moe_capacity_factor,
                compute_dtype=cfg.dtype,
            )
        return x + y, new_kv
    gate = _mlp_gate_act(_proj_l(h, layer, "w_gate", cfg), cfg)
    up = _proj_l(h, layer, "w_up", cfg)
    mlp_out = _proj_l(gate * up, layer, "w_down", cfg)
    if cfg.post_norm:
        mlp_out = _rms_norm(mlp_out, layer["ln_mlp_post"], cfg.norm_eps, cfg.norm_plus_one)
    x = x + mlp_out
    return x, new_kv


def _cache_advance(cache: dict, tokens: jax.Array, token_mask: Optional[jax.Array]):
    """Shared cache bookkeeping for the in-memory and streamed cached-forward paths:
    (write index, absolute rope positions [B,T], updated valid mask [B,C])."""
    B, T = tokens.shape
    index = cache["index"]
    positions = index + jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    if token_mask is None:
        token_mask = jnp.ones((B, T), jnp.bool_)
    valid = jax.lax.dynamic_update_slice(cache["valid"], token_mask, (0, index))
    return index, positions, valid


def forward_cached(
    params: dict,
    tokens: jax.Array,
    cache: dict,
    cfg: LlamaConfig,
    token_mask: Optional[jax.Array] = None,
    last_only: bool = False,
) -> tuple[jax.Array, dict]:
    """Write ``tokens`` [B,T] into the cache at its current index and return
    (logits fp32, updated cache) — logits [B,T,V], or [B,1,V] with ``last_only`` (prefill
    wants only the final position; skipping the [B,T,V] vocab matmul saves S0× head compute
    and HBM).

    Prefill passes the left-padded prompt with ``token_mask`` False on pads; decode passes a
    single token per row (T=1, mask omitted).  Rope positions are the absolute cache slots —
    rotary attention only depends on position *differences*, so left-pad offsets cancel.
    """
    B, T = tokens.shape
    dtype = cfg.dtype
    index, positions, valid = _cache_advance(cache, tokens, token_mask)

    x = params["embed"].astype(dtype)[tokens]
    if cfg.embed_scale:
        x = x * jnp.asarray(math.sqrt(cfg.d_model), dtype)
    alternating = bool(cfg.sliding_window) and cfg.window_every > 1
    if cfg.scan_layers and alternating:
        # Same grouped scan as forward_hidden: layer j of each group is banded iff j == 0.
        per = cfg.window_every
        if cfg.n_layers % per:
            raise ValueError(
                f"window_every={per} must divide n_layers={cfg.n_layers} under scan_layers"
            )
        full_cfg = dataclasses.replace(cfg, sliding_window=0)
        regroup = lambda a: a.reshape(cfg.n_layers // per, per, *a.shape[1:])  # noqa: E731
        grouped = jax.tree_util.tree_map(
            regroup, (params["layers"], cache["layers"])
        )

        def scan_body(carry, group):
            layers_g, kv_g = group
            out = carry
            new_kvs = []
            for j in range(per):
                layer_j = jax.tree_util.tree_map(lambda a, j=j: a[j], layers_g)
                kv_j = jax.tree_util.tree_map(lambda a, j=j: a[j], kv_g)
                out, new_kv = _block_cached(
                    out, layer_j, kv_j, index, positions, valid,
                    cfg if j == 0 else full_cfg,
                )
                new_kvs.append(new_kv)
            stacked = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *new_kvs)
            return out, stacked

        x, new_grouped = jax.lax.scan(scan_body, x, grouped)
        new_layers = jax.tree_util.tree_map(
            lambda a: a.reshape(cfg.n_layers, *a.shape[2:]), new_grouped
        )
    elif cfg.scan_layers:
        def scan_body(carry, layer_and_kv):
            layer, kv = layer_and_kv
            out, new_kv = _block_cached(carry, layer, kv, index, positions, valid, cfg)
            return out, new_kv

        x, new_layers = jax.lax.scan(scan_body, x, (params["layers"], cache["layers"]))
    else:
        full_cfg = dataclasses.replace(cfg, sliding_window=0)
        new_layers = []
        for i, (layer, kv) in enumerate(zip(params["layers"], cache["layers"])):
            banded = cfg.sliding_window and i % cfg.window_every == 0
            x, new_kv = _block_cached(
                x, layer, kv, index, positions, valid, cfg if banded else full_cfg
            )
            new_layers.append(new_kv)
    x = _rms_norm(x, params["ln_f"], cfg.norm_eps, cfg.norm_plus_one)
    if last_only:
        x = x[:, -1:, :]
    logits = head_logits(x, params, cfg)
    new_cache = {"layers": new_layers, "valid": valid, "index": index + T}
    return logits, new_cache


def forward_slots(
    params: dict,
    tokens: jax.Array,
    cache: dict,
    positions: jax.Array,
    cfg: LlamaConfig,
    tables: Optional[jax.Array] = None,
    page_size: int = 0,
) -> tuple[jax.Array, dict]:
    """Per-slot cached forward: ``tokens`` [B,T] written at each row's own cache slots
    ``positions[b] .. positions[b]+T-1`` → (logits fp32 [B,T,V], new cache).

    The continuous-batching counterpart of :func:`forward_cached` (whose single scalar
    ``index`` advances all rows together): every lane carries its own write position, so
    one compiled program serves a batch of requests at arbitrary, different sequence
    lengths. T == 1 is the engine's decode step; T == k+1 is the batched speculative
    VERIFY — one fused target forward scoring a pending token plus k draft proposals
    per lane, each position's logits exactly the distribution the T == 1 decode would
    have produced there (same rope positions, same causal/valid masking, dense MoE
    routing — decode-parity is what makes speculative acceptance lossless). Slots past
    a lane's rewound position may hold garbage K/V from rejected drafts; the causal
    mask (``slot <= q_position``) makes them unreachable until overwritten.

    ``tables``/``page_size`` switch the KV side to the PAGED layout (``cache`` from
    :func:`init_paged_cache`): writes scatter through each lane's block-table row into
    shared pool pages (sentinel/out-of-range positions drop), reads go through the
    paged-attention dispatch. ONE forward implementation for both layouts — the
    alternating-sliding-window grouping, per-layer banding and MoE routing literally
    cannot drift between them (the dense/paged token-parity contract,
    tests/test_serving_paged.py).
    """
    from .common import paged_write_coords

    B, T = tokens.shape
    rows = jnp.arange(B)
    pos_grid = positions[:, None] + jnp.arange(T, dtype=positions.dtype)[None, :]  # [B,T]
    if T == 1:
        valid = cache["valid"].at[rows, positions].set(True)
    else:
        valid = cache["valid"].at[rows[:, None], pos_grid].set(True)
    paged = None
    if tables is not None:
        num_pages = jax.tree_util.tree_leaves(cache["layers"])[0].shape[
            1 if cfg.scan_layers else 0
        ]
        pages, offs = paged_write_coords(
            tables, pos_grid, page_size, cache["valid"].shape[1], num_pages
        )
        paged = (tables, pages, offs, positions, page_size)
    x = params["embed"][tokens].astype(cfg.dtype)
    if cfg.embed_scale:
        x = x * jnp.asarray(math.sqrt(cfg.d_model), cfg.dtype)
    alternating = bool(cfg.sliding_window) and cfg.window_every > 1
    if cfg.scan_layers and alternating:
        # Mirror forward_cached's grouped scan: layer j of each window_every-group is
        # banded iff j == 0 (without this, decode would band-limit the full-attention
        # layers and diverge from generate()).
        per = cfg.window_every
        full_cfg = dataclasses.replace(cfg, sliding_window=0)
        regroup = lambda a: a.reshape(cfg.n_layers // per, per, *a.shape[1:])  # noqa: E731
        grouped = jax.tree_util.tree_map(regroup, (params["layers"], cache["layers"]))

        def body(carry, group):
            layers_g, kv_g = group
            out = carry
            new_kvs = []
            for j in range(per):
                layer_j = jax.tree_util.tree_map(lambda a, j=j: a[j], layers_g)
                kv_j = jax.tree_util.tree_map(lambda a, j=j: a[j], kv_g)
                out, new_kv = _block_cached(
                    out, layer_j, kv_j, positions, pos_grid, valid,
                    cfg if j == 0 else full_cfg, moe_dense=True, paged=paged,
                )
                new_kvs.append(new_kv)
            return out, jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *new_kvs)

        x, new_grouped = jax.lax.scan(body, x, grouped)
        new_layers = jax.tree_util.tree_map(
            lambda a: a.reshape(cfg.n_layers, *a.shape[2:]), new_grouped
        )
    elif cfg.scan_layers:
        def body(carry, layer_and_kv):
            layer, kv = layer_and_kv
            # vector index → per-row write slots (_block_cached handles both)
            out, new_kv = _block_cached(
                carry, layer, kv, positions, pos_grid, valid, cfg, moe_dense=True,
                paged=paged,
            )
            return out, new_kv

        x, new_layers = jax.lax.scan(body, x, (params["layers"], cache["layers"]))
    else:
        # Mirror forward_cached's per-layer banded/full alternation (cfg.window_every).
        full_cfg = dataclasses.replace(cfg, sliding_window=0)
        new_layers = []
        for i, (layer, kv) in enumerate(zip(params["layers"], cache["layers"])):
            banded = cfg.sliding_window and i % cfg.window_every == 0
            x, new_kv = _block_cached(
                x, layer, kv, positions, pos_grid, valid,
                cfg if banded else full_cfg, moe_dense=True, paged=paged,
            )
            new_layers.append(new_kv)
    x = _rms_norm(x, params["ln_f"], cfg.norm_eps, cfg.norm_plus_one)
    logits = head_logits(x, params, cfg)
    if paged is not None:
        return logits, {"layers": new_layers, "valid": valid}
    return logits, {"layers": new_layers, "valid": valid, "index": cache["index"]}


def forward_slots_paged(
    params: dict,
    tokens: jax.Array,
    cache: dict,
    tables: jax.Array,
    positions: jax.Array,
    cfg: LlamaConfig,
    page_size: int,
) -> tuple[jax.Array, dict]:
    """:func:`forward_slots` over the PAGED cache (``init_paged_cache``) — a thin
    delegate: the serving engine's stable entry point for the paged layout.
    ``tables`` [B, MP] int32 maps each lane's logical pages to physical pool pages
    (SENTINEL == num_pages marks unallocated entries; writes through them, and any
    position at/past max_len, DROP). The forward itself is the ONE shared
    implementation in :func:`forward_slots`, so the two layouts cannot drift."""
    return forward_slots(
        params, tokens, cache, positions, cfg, tables=tables, page_size=page_size
    )


def forward_slots_multi(
    params: dict,
    cache: dict,
    tokens: jax.Array,
    positions: jax.Array,
    active: jax.Array,
    budgets: jax.Array,
    eos_ids: jax.Array,
    select_token,
    xs,
    n_steps: int,
    cfg: LlamaConfig,
    tables: Optional[jax.Array] = None,
    page_size: int = 0,
) -> tuple[dict, jax.Array, jax.Array]:
    """N :func:`forward_slots` decode steps (T == 1) as ONE ``lax.scan`` — the
    scan-friendly super-step the serving engine's ``decode_steps=N`` path
    dispatches. Each scan step is literally a T == 1 ``forward_slots`` call (same
    rope positions, same valid/causal masking, same paged routing), so per-step
    logits are bitwise the host-loop's; see
    :func:`~.common.multi_step_decode` for the freeze/emission contract.
    Returns ``(cache, tok_buf [n_steps, B], counts [B])``."""
    from .common import multi_step_decode

    max_len = cache["valid"].shape[1]

    def forward_one(c, tok, write_pos):
        logits, c = forward_slots(
            params, tok[:, None], c, write_pos, cfg, tables=tables,
            page_size=page_size,
        )
        return logits[:, -1, :], c

    return multi_step_decode(
        forward_one, cache, tokens, positions, active, budgets, eos_ids,
        select_token, xs, n_steps, max_len,
    )


def forward_slots_spec_multi(
    params: dict,
    cache: dict,
    tokens: jax.Array,
    positions: jax.Array,
    active: jax.Array,
    budgets: jax.Array,
    eos_ids: jax.Array,
    propose,
    select_ref,
    key_tab: jax.Array,
    history: jax.Array,
    hist_lens: jax.Array,
    n_steps: int,
    spec_k: int,
    cfg: LlamaConfig,
    tables: Optional[jax.Array] = None,
    page_size: int = 0,
):
    """N speculative draft→verify→accept rounds as ONE ``lax.scan`` — the fused
    super-step the serving engine's ``spec_k > 0, decode_steps=N`` path
    dispatches (``serving.spec_multi[_paged]``). Each scan round's verify is
    literally a T == spec_k+1 :func:`forward_slots` call (the PR-6
    ``_spec_verify_step`` body: same rope positions, same valid/causal masking,
    same paged routing), so per-round logits are bitwise the host loop's; see
    :func:`~.common.spec_multi_step_decode` for the accept/key-cursor/freeze
    contract. Returns ``(cache, tok_buf [n_steps, B, spec_k+1], emits
    [n_steps, B], counts [B], proposed [B], accepted [B])``."""
    from .common import spec_multi_step_decode

    max_len = cache["valid"].shape[1]

    def forward_verify(c, seq, write_pos):
        return forward_slots(
            params, seq, c, write_pos, cfg, tables=tables, page_size=page_size
        )

    return spec_multi_step_decode(
        forward_verify, propose, select_ref, cache, tokens, positions, active,
        budgets, eos_ids, key_tab, history, hist_lens, n_steps, spec_k, max_len,
    )


def _make_gen_fns(cfg: LlamaConfig, max_len: int):
    """Stable-identity (prefill, decode) pair for ``generation.generate_loop`` (jit-static)."""

    def prefill_fn(params, prompt, prompt_mask):
        cache = init_cache(cfg, prompt.shape[0], max_len)
        logits, cache = forward_cached(
            params, prompt, cache, cfg, token_mask=prompt_mask, last_only=True
        )
        return logits[:, -1, :], cache

    def decode_fn(params, cache, token):
        logits, cache = forward_cached(params, token[:, None], cache, cfg)
        return logits[:, -1, :], cache

    return prefill_fn, decode_fn


# Bounded cache of (prefill, decode) closure pairs: stable identities keep generate_loop's
# jit cache warm, the bound keeps a long-running server from pinning one executable pair per
# distinct prompt length forever (max_len is bucketed below for the same reason).
_GEN_FNS: OrderedDict = OrderedDict()
_GEN_FNS_MAX = 16


def generate(
    params: dict,
    prompt: jax.Array,
    cfg: LlamaConfig,
    gen=None,
    rng: Optional[jax.Array] = None,
    prompt_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Autoregressive generation: one compiled prefill + decode-scan program.

    ``prompt`` [B,S0] int32 (left-padded; pass ``prompt_mask`` False on pads).  Returns
    [B, max_new_tokens].  The reference-side analog is ``model.generate()`` over a dispatched
    model (``/root/reference/benchmarks/big_model_inference/README.md:25``).
    """
    from ..generation import GenerationConfig, generate_loop

    gen = gen or GenerationConfig()
    prompt = jnp.asarray(prompt, jnp.int32)
    if prompt_mask is None:
        prompt_mask = jnp.ones(prompt.shape, jnp.bool_)
    # Bucket the cache length so nearby prompt lengths share one compiled program (the
    # valid-mask/index machinery makes an over-long cache semantically identical).
    max_len = prompt.shape[1] + gen.max_new_tokens
    max_len = -(-max_len // 64) * 64
    key = (cfg, max_len)
    if key not in _GEN_FNS:
        _GEN_FNS[key] = _make_gen_fns(cfg, max_len)
        while len(_GEN_FNS) > _GEN_FNS_MAX:
            _GEN_FNS.popitem(last=False)
    _GEN_FNS.move_to_end(key)
    prefill_fn, decode_fn = _GEN_FNS[key]
    return generate_loop(prefill_fn, decode_fn, params, prompt, prompt_mask, gen, rng)


def generate_streamed(
    dispatched,
    prompt: jax.Array,
    cfg: LlamaConfig,
    gen=None,
    rng: Optional[jax.Array] = None,
    prompt_mask: Optional[jax.Array] = None,
    prefetch: int = 2,
    pass_times: Optional[list] = None,
) -> jax.Array:
    """Generation for models bigger than HBM: every forward streams blocks from host/disk.

    The reference's offloaded ``generate`` re-loads each layer per *token* through
    ``AlignDevicesHook.pre_forward`` (hooks.py:329) — its OPT-30B disk number is 33.9 s/token
    (BASELINE.md).  This path does the same amount of traffic but overlaps each block's H2D
    copy with the previous block's compute (``stream_blocks`` double-buffering).  Use
    ``generate`` whenever the params fit — streamed decode is HBM-bandwidth-bound by design.
    """
    from ..big_modeling import consume_block, stream_blocks
    from ..generation import GenerationConfig, streamed_generate_loop

    if cfg.scan_layers:
        raise ValueError("generate_streamed requires per-layer (non-scanned) params.")
    gen = gen or GenerationConfig()
    B, S0 = jnp.asarray(prompt).shape
    max_len = S0 + gen.max_new_tokens
    prefixes = [f"layers/{i}" for i in range(cfg.n_layers)]
    # Hoist always-resident leaves out of the loop: only transformer BLOCKS stream per
    # pass; re-fetching the embedding from host/disk per token would dominate the traffic.
    embed = dispatched.fetch("embed")
    ln_f = dispatched.fetch("ln_f")
    head = embed if cfg.tie_embeddings else dispatched.fetch("lm_head")

    def one_pass(tokens, cache, token_mask):
        if cache is None:
            cache = init_cache(cfg, B, max_len)
        index, positions, valid = _cache_advance(cache, tokens, token_mask)
        # Gather THEN cast: this loop is host-driven (un-jitted between blocks), so
        # embed.astype(...)[tokens] would eagerly convert the full [V, D] matrix per pass.
        x = embed[tokens].astype(cfg.dtype)
        new_layers = []
        for i, layer in stream_blocks(dispatched, prefixes, prefetch=prefetch):
            idx = int(i.split("/")[1])
            x, new_kv = _block_cached_jit(
                x, layer, cache["layers"][idx], index, positions, valid, cfg=cfg
            )
            # Fence + free this block's buffers NOW (relay clients retain host
            # mirrors of lazily-GC'd device buffers — see big_modeling.consume_block).
            consume_block(x, layer, dispatched, i)
            new_layers.append(new_kv)
        x = _rms_norm(x, ln_f, cfg.norm_eps)
        logits = _streamed_head_jit(x[:, -1, :], head, transpose=cfg.tie_embeddings)
        return logits, {"layers": new_layers, "valid": valid, "index": index + tokens.shape[1]}

    return streamed_generate_loop(one_pass, prompt, prompt_mask, gen, rng,
                                  pass_times=pass_times)


@partial(jax.jit, static_argnames=("transpose",))
def _streamed_head_jit(x_last, head, transpose: bool):
    """Final-position vocab projection for streamed decode, fused under one jit so the
    head-matrix cast/transpose never materializes eagerly ([V,D] when tied, [D,V] when not)."""
    eq = "bd,vd->bv" if transpose else "bd,dv->bv"
    return jnp.einsum(eq, x_last, head.astype(x_last.dtype)).astype(jnp.float32)


@partial(jax.jit, static_argnames=("cfg",))
def _block_cached_jit(x, layer, kv, index, positions, valid, cfg):
    """Module-level jit identity: one compile per shape across streamed decode steps."""
    return _block_cached(x, layer, kv, index, positions, valid, cfg)


def num_params(cfg: LlamaConfig) -> int:
    """Analytic parameter count (used by MFU computation in bench)."""
    D, F, V, H, K, hd = cfg.d_model, cfg.d_ff, cfg.vocab_size, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    mlp = 3 * D * F if cfg.moe_experts == 0 else cfg.moe_experts * 3 * D * F + D * cfg.moe_experts
    per_layer = D * H * hd + 2 * D * K * hd + H * hd * D + mlp + 2 * D
    total = V * D + cfg.n_layers * per_layer + D
    if not cfg.tie_embeddings:
        total += D * V
    return total


# -------------------------------------------------------------------- speculative decoding
def _cached_family(cfg):
    """Family module for a config — ``common.cached_decode_family`` (llama or gpt,
    which share the cached-decode contract; gpt reuses llama's ``_cache_advance``).
    Lets the speculative decoder drive either family, including cross-family
    draft/target pairs (e.g. a gpt target with a small llama draft) as long as the
    vocabularies match. Raises TypeError for families without a decode contract."""
    from .common import cached_decode_family

    return cached_decode_family(cfg)


def _cache_rewind(cache: dict, to_index) -> dict:
    """Roll a cache back to ``to_index`` written tokens: later slots become invalid (their
    k/v are garbage from rejected drafts and are masked; the next writes overwrite them)."""
    C = cache["valid"].shape[1]
    keep = jnp.arange(C)[None, :] < to_index
    return {
        "layers": cache["layers"],
        "valid": cache["valid"] & keep,
        "index": jnp.asarray(to_index, jnp.int32),
    }


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(2,))
def _spec_forward_jit(params, tokens, cache, cfg):
    """forward_cached + per-position argmax (used for both the T=K verify and T=1 steps).
    The input cache is donated — callers always replace their reference with the output."""
    logits, cache = _cached_family(cfg).forward_cached(params, tokens, cache, cfg)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache


@partial(jax.jit, static_argnames=("t_cfg", "d_cfg", "k"), donate_argnums=(2, 3))
def _spec_round_greedy_jit(t_params, d_params, t_cache, d_cache, pending, *, t_cfg, d_cfg, k):
    """ONE fused greedy speculative round: k-1 draft steps (``lax.scan``), the T=k
    target verify, prefix acceptance, both cache rewinds, and the full-acceptance
    draft catch-up — a single compiled program per round.

    The unfused loop costs ~k+3 host->device dispatches per round, each a round-trip
    (ruinous through a network-attached device, and measurable even host-attached:
    the CPU smoke of ``benchmarks/big_model_inference/speculative_tpu.py`` put
    per-round host overhead at ~50x the tiny-model step cost). Fused, the Python
    loop makes ONE dispatch and ONE result read per round. Control flow lives
    on-device: acceptance length ``n`` = leading-match count via ``cumprod``; the
    draft catch-up runs under ``lax.cond``. Token-for-token identical to the
    unfused greedy path (same argmax/accept math; parity-tested).

    Returns ``(emitted[k], count, t_cache, d_cache)``: ``emitted[:count]`` =
    accepted drafts + the target's correction (the new pending token is
    ``emitted[count-1]``, sliced on-device by the caller's next round)."""
    fam_t, fam_d = _cached_family(t_cfg), _cached_family(d_cfg)
    base_t = t_cache["index"]            # emitted length - 1 (pending unwritten)
    base_d = d_cache["index"]

    def draft_step(carry, _):
        tok, cache = carry
        logits, cache = fam_d.forward_cached(d_params, tok[None, None], cache, d_cfg)
        nxt = jnp.argmax(logits[0, -1]).astype(jnp.int32)
        return (nxt, cache), nxt

    pending = jnp.asarray(pending, jnp.int32)
    (_, d_cache), drafts = jax.lax.scan(draft_step, (pending, d_cache), None, length=k - 1)

    seq = jnp.concatenate([pending[None], drafts])[None]          # [1, k]
    logits, t_cache = fam_t.forward_cached(t_params, seq, t_cache, t_cfg)
    ys = jnp.argmax(logits[0], axis=-1).astype(jnp.int32)         # [k]
    matches = (drafts == ys[: k - 1]).astype(jnp.int32)
    n = jnp.sum(jnp.cumprod(matches))                             # leading agreements
    correction = ys[n]
    emitted = jnp.where(
        jnp.arange(k) < n, jnp.concatenate([drafts, jnp.zeros((1,), jnp.int32)]), 0
    )
    emitted = emitted.at[n].set(correction)
    t_cache = _cache_rewind(t_cache, base_t + 1 + n)

    def full_acceptance(cache):
        # The draft never processed its own last proposal (it wrote pending +
        # drafts[:-1]); one catch-up step so the next round's cache has no hole.
        cache = _cache_rewind(cache, base_d + n)
        _, cache = fam_d.forward_cached(d_params, drafts[-1][None, None], cache, d_cfg)
        return cache

    d_cache = jax.lax.cond(
        n == k - 1, full_acceptance, lambda c: _cache_rewind(c, base_d + 1 + n), d_cache
    )
    # Pack emitted+count into one vector: the caller reads the round result in a
    # single device->host transfer; ``correction`` feeds the next round's pending
    # as a device scalar (never synced).
    packed = jnp.concatenate([emitted, (n + 1)[None]])
    return packed, correction, t_cache, d_cache


@partial(jax.jit, static_argnames=("cfg", "top_k", "apply_top_p"), donate_argnums=(2,))
def _spec_probs_jit(params, tokens, cache, cfg, temperature, top_p, top_k, apply_top_p):
    """forward_cached + the SAME temperature/top-k/top-p filtering ``generate`` samples
    from, as per-position probability rows [B, T, V] — speculative sampling's accept test
    compares draft and target over these exact distributions. Only the shape-affecting
    knobs (top_k, apply_top_p) are static; temperature/top_p trace as scalars so varying
    sampling-irrelevant GenerationConfig fields never recompiles the model."""
    from ..generation import filtered_logits

    logits, cache = _cached_family(cfg).forward_cached(params, tokens, cache, cfg)
    fl = filtered_logits(logits, temperature, top_p, top_k, apply_top_p)
    return jax.nn.softmax(fl, axis=-1), cache


def generate_speculative(
    target_params: dict,
    target_cfg,   # LlamaConfig | GPTConfig (see _cached_family)
    draft_params: dict,
    draft_cfg,    # LlamaConfig | GPTConfig
    prompt: jax.Array,
    max_new_tokens: int = 32,
    k: int = 4,
    eos_token_id: Optional[int] = None,
    prompt_mask: Optional[jax.Array] = None,
    return_stats: bool = False,
    gen=None,
    rng: Optional[jax.Array] = None,
):
    """Speculative decoding: ONE target dispatch per round verifies the pending token
    plus ``k-1`` draft proposals and emits 1..k tokens (accepted prefix + the target's
    correction). Greedy by default — output PROVABLY identical to the target's plain
    greedy decode (tested token-for-token). With a ``GenerationConfig`` whose
    ``temperature > 0`` (plus ``rng``), it runs LOSSLESS SPECULATIVE SAMPLING (Leviathan
    et al. 2022): each proposal is accepted with min(1, p/q) and rejections re-draw from
    the residual norm(max(p − q, 0)), so the output distribution is exactly the target's
    own temperature/top-k/top-p sampling distribution (``generation.speculative_accept``;
    distribution asserted in tests). The draft only changes how many target forwards it
    takes. The reference has no speculative path. Single sequence (B=1): speculation is a
    latency tool for individual streams; batch throughput is ``serving.ContinuousBatcher``.

    Family-generic over the shared cached-decode contract (``_cached_family``): target
    and draft may each be llama or gpt configs — including cross-family pairs (e.g. a
    gpt-family target speculated by a small llama draft, as the tests do) — as long as
    the vocabularies match.

    Round invariant: both caches hold the emitted sequence EXCEPT the newest token
    (``pending``), which rides as the first input of the next round's forwards — so the
    correction never costs its own target dispatch. Verified drafts' k/v already sit in
    the caches; acceptance is a cache REWIND plus bookkeeping.

    ``return_stats=True`` also returns ``{"rounds", "target_dispatches", "tokens"}``
    (dispatches = rounds + 1 prefill) for tokens-per-dispatch accounting.
    """
    from ..generation import sample_logits, speculative_accept

    if target_cfg.vocab_size != draft_cfg.vocab_size:
        raise ValueError("draft and target must share a vocabulary")
    if k < 2:
        raise ValueError("k must be >= 2 (k-1 draft proposals per round)")
    sampled = gen is not None and gen.temperature > 0.0
    if sampled and rng is None:
        raise ValueError("speculative sampling (gen.temperature > 0) needs an rng key")
    _key_n = [0]

    def next_key():
        _key_n[0] += 1
        return jax.random.fold_in(rng, _key_n[0])
    prompt = jnp.asarray(prompt, jnp.int32)
    if prompt.ndim == 1:
        prompt = prompt[None]
    if prompt.shape[0] != 1:
        raise ValueError("generate_speculative is single-sequence (B=1)")
    if prompt_mask is None:
        prompt_mask = jnp.ones(prompt.shape, jnp.bool_)
    else:
        prompt_mask = jnp.asarray(prompt_mask, jnp.bool_)
        if prompt_mask.ndim == 1:  # mirror the prompt's auto batch dim
            prompt_mask = prompt_mask[None]
    S0 = prompt.shape[1]
    # Bucketed like generate(): nearby prompt/k/max_new combinations share one compiled
    # program per token shape (the valid-mask machinery makes an over-long cache identical).
    max_len = -(-(S0 + max_new_tokens + k + 1) // 64) * 64

    fam_t = _cached_family(target_cfg)
    fam_d = _cached_family(draft_cfg)
    t_cache = fam_t.init_cache(target_cfg, 1, max_len)
    d_cache = fam_d.init_cache(draft_cfg, 1, max_len)
    t_logits, t_cache = fam_t.forward_cached(
        target_params, prompt, t_cache, target_cfg, token_mask=prompt_mask, last_only=True
    )
    _, d_cache = fam_d.forward_cached(
        draft_params, prompt, d_cache, draft_cfg, token_mask=prompt_mask, last_only=True
    )
    # ``pending``: emitted but not yet written to either cache.
    if sampled:
        pending = int(np.asarray(sample_logits(t_logits[:, -1, :], gen, next_key()))[0])
    else:
        pending = int(np.asarray(jnp.argmax(t_logits[0, -1])))
    out: list[int] = [pending]
    rounds = 0

    def finish():
        toks = jnp.asarray([out[:max_new_tokens]], jnp.int32)
        if return_stats:
            return toks, {
                "rounds": rounds, "target_dispatches": rounds + 1, "tokens": min(len(out), max_new_tokens),
            }
        return toks

    if eos_token_id is not None and pending == eos_token_id:
        return finish()

    pending_dev = jnp.asarray(pending, jnp.int32)  # greedy path: device-resident pending
    while len(out) < max_new_tokens:
        rounds += 1
        if not sampled:
            # Greedy: the WHOLE round is one fused program (_spec_round_greedy_jit —
            # draft scan + T=k verify + acceptance + rewinds + catch-up); the loop
            # makes one dispatch and one packed result read per round.
            packed, pending_dev, t_cache, d_cache = _spec_round_greedy_jit(
                target_params, draft_params, t_cache, d_cache, pending_dev,
                t_cfg=target_cfg, d_cfg=draft_cfg, k=k,
            )
            # graftlint: disable=host-sync-in-hot-path(one fused round = ONE result read; the host must see the accepted tokens)
            arr = np.asarray(packed)  # [k+1]: emitted slots + count
            for tok in arr[: int(arr[k])].tolist():  # graftlint: disable=host-sync-in-hot-path(arr is host-side numpy already; no device fetch here)
                out.append(int(tok))
                if len(out) >= max_new_tokens or (
                    eos_token_id is not None and tok == eos_token_id
                ):
                    return finish()
            continue
        # ---- lossless speculative sampling: host-side sequential accept (each accept
        # consumes an rng key and can end the round, so this path keeps the unfused
        # per-step dispatches; fusing it needs the accept chain as a lax.scan over
        # carried keys — future work, the greedy path above shows the shape).
        # 1. draft k-1 proposals; the draft's first input is the pending token itself.
        drafts: list[int] = []
        q_rows = []  # the draft's filtered distribution per proposal
        tok = pending
        for _ in range(k - 1):
            qp, d_cache = _spec_probs_jit(
                draft_params, jnp.asarray([[tok]], jnp.int32), d_cache,
                cfg=draft_cfg, temperature=gen.temperature, top_p=gen.top_p,
                top_k=gen.top_k, apply_top_p=gen.top_p < 1.0,
            )
            q_rows.append(qp[0, -1])
            # graftlint: disable=host-sync-in-hot-path(sampled accept chain is host-side by design; see the future-work note above)
            tok = int(np.asarray(jax.random.categorical(
                next_key(), jnp.log(jnp.maximum(qp[0, -1], 1e-30))
            )))
            drafts.append(tok)
        base_t = int(np.asarray(t_cache["index"]))      # emitted length - 1 (pending unwritten)  # graftlint: disable=host-sync-in-hot-path(rewind bookkeeping; 4-byte reads once per round)
        base_d = int(np.asarray(d_cache["index"])) - (k - 1)  # draft wrote pending + drafts[:-1]  # graftlint: disable=host-sync-in-hot-path(rewind bookkeeping; 4-byte reads once per round)
        # 2. ONE target dispatch (T=k): verify pending + ALL proposals. Position i of the
        # output is the target's prediction after input i — it checks drafts[i] for
        # i < k-1, and position k-1 (after the last proposal) backs the bonus token on
        # full acceptance.
        pp, t_cache = _spec_probs_jit(
            target_params, jnp.asarray([[pending, *drafts]], jnp.int32), t_cache,
            cfg=target_cfg, temperature=gen.temperature, top_p=gen.top_p,
            top_k=gen.top_k, apply_top_p=gen.top_p < 1.0,
        )
        # 3. stochastic prefix acceptance: accept proposal n w.p. min(1, p/q);
        # first rejection re-draws from the residual and ends the round.
        n = 0
        correction = None
        while n < k - 1:
            acc, token = speculative_accept(
                pp[0, n], q_rows[n], drafts[n], next_key()
            )
            if not bool(np.asarray(acc)):  # graftlint: disable=host-sync-in-hot-path(accept verdict must reach the host to end the round)
                correction = int(np.asarray(token))  # graftlint: disable=host-sync-in-hot-path(rejected-draft correction token crosses to host once)
                break
            n += 1
        if correction is None:  # full acceptance: bonus token from the target's own row
            # graftlint: disable=host-sync-in-hot-path(bonus-token draw; one 4-byte read per fully-accepted round)
            correction = int(np.asarray(jax.random.categorical(
                next_key(), jnp.log(jnp.maximum(pp[0, k - 1], 1e-30))
            )))
        emitted = drafts[:n] + [correction]  # correction becomes the new pending token
        # 4. rewind to written-emitted length: target wrote pending+accepted (base_t+1+n);
        # draft wrote the same prefix (its extra proposal writes are invalidated).
        t_cache = _cache_rewind(t_cache, base_t + 1 + n)
        if n == k - 1:
            # Full acceptance: the draft never processed its own last proposal (it wrote
            # pending + drafts[:-1]); catch it up with one cheap draft step so the next
            # round's cache has no invalid hole. Its output is discarded.
            d_cache = _cache_rewind(d_cache, base_d + n)
            _, d_cache = _spec_forward_jit(
                draft_params, jnp.asarray([[drafts[-1]]], jnp.int32), d_cache, cfg=draft_cfg
            )
        else:
            d_cache = _cache_rewind(d_cache, base_d + 1 + n)
        pending = emitted[-1]
        for tok in emitted:
            out.append(tok)
            if len(out) >= max_new_tokens or (
                eos_token_id is not None and tok == eos_token_id
            ):
                return finish()
    return finish()
