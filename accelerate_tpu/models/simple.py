"""A compact pure-JAX transformer LM used by bench.py and the driver entry points.

This is NOT the flagship model family (see ``models/llama.py`` / ``models/bert.py``) — it is a
dependency-free decoder stack with the canonical TPU-friendly shapes (d_model multiple of 128,
bf16 matmuls on the MXU) used for smoke benchmarks and multi-chip dry runs.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

__all__ = ["TransformerConfig", "init_params", "forward", "loss_fn"]


class TransformerConfig:
    def __init__(
        self,
        vocab_size: int = 32000,
        d_model: int = 512,
        n_heads: int = 8,
        n_layers: int = 4,
        d_ff: int = 2048,
        max_seq: int = 512,
        dtype=jnp.bfloat16,
    ):
        self.vocab_size = vocab_size
        self.d_model = d_model
        self.n_heads = n_heads
        self.n_layers = n_layers
        self.d_ff = d_ff
        self.max_seq = max_seq
        self.dtype = dtype
        self.head_dim = d_model // n_heads


def init_params(cfg: TransformerConfig, seed: int = 0) -> dict:
    key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, cfg.n_layers * 6 + 3)
    scale = 1.0 / math.sqrt(cfg.d_model)
    params: dict[str, Any] = {
        "embed": jax.random.normal(keys[0], (cfg.vocab_size, cfg.d_model), jnp.float32) * scale,
        "pos": jax.random.normal(keys[1], (cfg.max_seq, cfg.d_model), jnp.float32) * scale,
        "ln_f": jnp.ones((cfg.d_model,), jnp.float32),
        "layers": [],
    }
    k = 2
    for _ in range(cfg.n_layers):
        params["layers"].append(
            {
                "ln1": jnp.ones((cfg.d_model,), jnp.float32),
                "wqkv": jax.random.normal(keys[k], (cfg.d_model, 3 * cfg.d_model), jnp.float32) * scale,
                "wo": jax.random.normal(keys[k + 1], (cfg.d_model, cfg.d_model), jnp.float32) * scale,
                "ln2": jnp.ones((cfg.d_model,), jnp.float32),
                "w1": jax.random.normal(keys[k + 2], (cfg.d_model, cfg.d_ff), jnp.float32) * scale,
                "w2": jax.random.normal(keys[k + 3], (cfg.d_ff, cfg.d_model), jnp.float32) * scale,
            }
        )
        k += 4
    return params


def _rms_norm(x, g):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * g.astype(x.dtype)


def forward(params: dict, tokens: jax.Array, cfg: TransformerConfig) -> jax.Array:
    """Causal LM forward: tokens [B, S] int32 → logits [B, S, V]."""
    B, S = tokens.shape
    dtype = cfg.dtype
    x = params["embed"].astype(dtype)[tokens] + params["pos"].astype(dtype)[:S]
    mask = jnp.tril(jnp.ones((S, S), dtype=jnp.bool_))
    for layer in params["layers"]:
        h = _rms_norm(x, layer["ln1"])
        qkv = h @ layer["wqkv"].astype(dtype)
        q, k_, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, S, cfg.n_heads, cfg.head_dim).transpose(0, 2, 1, 3)
        k_ = k_.reshape(B, S, cfg.n_heads, cfg.head_dim).transpose(0, 2, 1, 3)
        v = v.reshape(B, S, cfg.n_heads, cfg.head_dim).transpose(0, 2, 1, 3)
        scores = (q @ k_.transpose(0, 1, 3, 2)) / math.sqrt(cfg.head_dim)
        scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dtype)
        attn = (probs @ v).transpose(0, 2, 1, 3).reshape(B, S, cfg.d_model)
        x = x + attn @ layer["wo"].astype(dtype)
        h = _rms_norm(x, layer["ln2"])
        x = x + jax.nn.gelu(h @ layer["w1"].astype(dtype)) @ layer["w2"].astype(dtype)
    x = _rms_norm(x, params["ln_f"])
    return (x @ params["embed"].astype(dtype).T).astype(jnp.float32)


def loss_fn(params: dict, batch: dict, cfg: TransformerConfig) -> jax.Array:
    """Next-token cross-entropy on batch {'tokens': [B, S]}."""
    tokens = batch["tokens"]
    logits = forward(params, tokens[:, :-1], cfg)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1).squeeze(-1)
    return -jnp.mean(ll)
