"""GPT family (GPT-2 / GPT-J / GPT-NeoX shaped) — the reference's inference-baseline models.

Every published reference baseline is a GPT-family model (GPT-J-6B, GPT-NeoX-20B —
``/root/reference/benchmarks/big_model_inference/README.md:25-37``), so the framework ships
the family natively: same functional contract as ``llama.py`` (init_params / forward /
loss_fn / partition_specs / cached generate), with the GPT architectural differences:

- LayerNorm with bias (not RMSNorm); biased projections.
- GELU MLP (not SwiGLU) — 2 matmuls per MLP instead of 3.
- Positions: learned embeddings (``pos="learned"``, GPT-2) or rotary (GPT-J/NeoX).
- Optional parallel residual (``parallel_residual``, GPT-J/NeoX): attention and MLP both
  read the same layernorm and add into the residual together — one fewer serial dependency,
  which on TPU lets XLA overlap the two matmul chains.

Sharding: Megatron column/row layout identical to llama's, composable with fsdp/ZeRO.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..utils.constants import BATCH_AXES, FSDP_AXIS, SEQUENCE_AXIS, TENSOR_AXIS
from ..utils.jax_compat import current_abstract_mesh

__all__ = [
    "GPTConfig",
    "CONFIGS",
    "init_params",
    "forward",
    "loss_fn",
    "score",
    "perplexity",
    "partition_specs",
    "forward_pp",
    "loss_fn_pp",
    "generate_speculative",
    "head_logits",
    "init_cache",
    "init_paged_cache",
    "forward_cached",
    "forward_slots",
    "forward_slots_paged",
    "generate",
    "generate_streamed",
    "num_params",
]


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq: int = 2048
    pos: str = "learned"          # "learned" (gpt2) | "rotary" (gpt-j/neox)
    rope_theta: float = 10000.0
    rotary_dim: Optional[int] = None  # partial rotary: rope the first N dims only
                                      # (gpt-j rotary_dim, neox rotary_pct); None = full
    rope_style: str = "half"      # "half" (neox rotate-half) | "interleaved" (gpt-j)
    parallel_residual: bool = False  # gpt-j/neox style
    activation: str = "gelu_new"  # "gelu_new" (gpt2/gpt-j tanh) | "gelu" (neox exact) | "relu" (OPT)
    lm_head_bias: bool = False    # gpt-j's lm_head carries a bias
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # "auto": flash on TPU, xla elsewhere. "ring"/"ulysses"/"allgather": sequence-
    # parallel attention over an sp mesh axis (same dispatcher as llama; packing
    # composes). sp modes train under pp too — loss_fn_pp goes manual over sp exactly
    # like llama's sp_pipeline (forward_pp's hidden-state path is the one sp×pp hole).
    attn_impl: str = "auto"
    remat: bool = True
    remat_policy: str = "full"            # "full" | "dots" | "offload" (see models/common.py)
    remat_prevent_cse: Optional[bool] = None  # None = auto (False under scan_layers)
    scan_layers: bool = False
    scan_unroll: int = 1                  # lax.scan unroll for the layer stack
    tie_embeddings: bool = True   # gpt2 ties lm_head to wte
    kv_quant: bool = False        # int8 KV cache (see models/common.py kv helpers)
    # "auto": dense/chunked CE. "fused": ops/fused_xent Pallas kernel (single-device);
    # "fused_dp"/"fused_tp": the batch-sharded / vocab-sharded multi-chip kernels (same
    # contract as llama, via common.ce_sum_dispatch). A biased lm_head (gpt-j) always
    # falls back to the dense/chunked path — the kernels have no bias term.
    loss_impl: str = "auto"
    loss_chunk: int = 0           # chunked-CE length: 0 auto, -1 off (common.resolve_loss_chunk)


CONFIGS = {
    "gpt2": GPTConfig(),
    "gpt2-xl": GPTConfig(d_model=1600, n_layers=48, n_heads=25, d_ff=6400),
    "gptj-6b": GPTConfig(
        vocab_size=50400, d_model=4096, n_layers=28, n_heads=16, d_ff=16384,
        pos="rotary", rotary_dim=64, rope_style="interleaved",
        parallel_residual=True, tie_embeddings=False, lm_head_bias=True,
    ),
    "gpt-neox-20b": GPTConfig(
        vocab_size=50432, d_model=6144, n_layers=44, n_heads=64, d_ff=24576,
        pos="rotary", rotary_dim=24, rope_style="half", activation="gelu",
        parallel_residual=True, tie_embeddings=False,
    ),
    # OPT-30B shape (the reference's biggest offload baseline, README.md:36-37): OPT is a
    # plain GPT decoder with learned positions, sequential residual, ReLU-family MLP —
    # architecturally GPT-2-shaped at 30B scale.
    "opt-30b": GPTConfig(
        vocab_size=50272, d_model=7168, n_layers=48, n_heads=56, d_ff=28672,
        pos="learned", activation="relu", tie_embeddings=True, max_seq=2048,
    ),
    "tiny": GPTConfig(
        vocab_size=256, d_model=128, n_layers=2, n_heads=4, d_ff=256, max_seq=128,
        remat=False,
    ),
}


def _layer_params(cfg: GPTConfig, key) -> dict:
    k = jax.random.split(key, 4)
    D, F = cfg.d_model, cfg.d_ff
    s = 1.0 / math.sqrt(D)
    return {
        "ln_attn": {"scale": jnp.ones((D,), jnp.float32), "bias": jnp.zeros((D,), jnp.float32)},
        "wqkv": jax.random.normal(k[0], (D, 3 * D), jnp.float32) * s,
        "b_qkv": jnp.zeros((3 * D,), jnp.float32),
        "wo": jax.random.normal(k[1], (D, D), jnp.float32) * s,
        "b_o": jnp.zeros((D,), jnp.float32),
        "ln_mlp": {"scale": jnp.ones((D,), jnp.float32), "bias": jnp.zeros((D,), jnp.float32)},
        "w_up": jax.random.normal(k[2], (D, F), jnp.float32) * s,
        "b_up": jnp.zeros((F,), jnp.float32),
        "w_down": jax.random.normal(k[3], (F, D), jnp.float32) / math.sqrt(F),
        "b_down": jnp.zeros((D,), jnp.float32),
    }


def init_params(cfg: GPTConfig, key: Optional[jax.Array] = None) -> dict:
    if key is None:
        key = jax.random.PRNGKey(0)  # graftlint: disable=rng-key-reuse(deterministic default init; callers pass a key for real entropy)
    keys = jax.random.split(key, cfg.n_layers + 3)
    scale = 1.0 / math.sqrt(cfg.d_model)
    params: dict = {
        "wte": jax.random.normal(keys[0], (cfg.vocab_size, cfg.d_model), jnp.float32) * scale,
        "layers": [_layer_params(cfg, keys[i + 2]) for i in range(cfg.n_layers)],
        "ln_f": {
            "scale": jnp.ones((cfg.d_model,), jnp.float32),
            "bias": jnp.zeros((cfg.d_model,), jnp.float32),
        },
    }
    if cfg.pos == "learned":
        params["wpe"] = (
            jax.random.normal(keys[1], (cfg.max_seq, cfg.d_model), jnp.float32) * scale * 0.1
        )
    if cfg.scan_layers:
        params["layers"] = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *params["layers"])
    if not cfg.tie_embeddings:
        params["lm_head"] = (
            jax.random.normal(keys[-1], (cfg.d_model, cfg.vocab_size), jnp.float32) * scale
        )
        if cfg.lm_head_bias:
            params["b_lm_head"] = jnp.zeros((cfg.vocab_size,), jnp.float32)
    return params


def partition_specs(cfg: GPTConfig, pp: bool = False, virtual_stages: int = 1) -> dict:
    """Megatron layout: qkv/up column-parallel, o/down row-parallel, vocab over (tp, fsdp).

    ``pp=True``: layer specs gain the stage-stacked leading dims sharded over ``pp``
    (``parallel.pp.split_params_into_stages`` layout) and embed/head fold the pipeline
    axis into the vocab sharding — same design as ``llama.partition_specs(pp=True)``.
    ``virtual_stages=v > 1``: the interleaved [v, n, L/(n·v), ...] layout (pp on dim 1)."""
    ln = {"scale": P(), "bias": P()}
    layer = {
        "ln_attn": dict(ln),
        "wqkv": P(None, TENSOR_AXIS),
        "b_qkv": P(TENSOR_AXIS),
        "wo": P(TENSOR_AXIS, None),
        "b_o": P(),
        "ln_mlp": dict(ln),
        "w_up": P(None, TENSOR_AXIS),
        "b_up": P(TENSOR_AXIS),
        "w_down": P(TENSOR_AXIS, None),
        "b_down": P(),
    }
    from ..utils.constants import PIPELINE_AXIS

    if pp:
        if not cfg.scan_layers:
            raise ValueError("pipeline parallelism requires cfg.scan_layers=True")
        from ..parallel.pp import stage_spec_prefix

        layer = jax.tree_util.tree_map(
            lambda spec: P(*stage_spec_prefix(virtual_stages), *spec),
            layer,
            is_leaf=lambda s: isinstance(s, P),
        )
        layers: Any = layer
    elif cfg.scan_layers:
        layer = jax.tree_util.tree_map(
            lambda spec: P(None, *spec), layer, is_leaf=lambda s: isinstance(s, P)
        )
        layers = layer
    else:
        layers = [dict(layer) for _ in range(cfg.n_layers)]
    vocab_axes = (TENSOR_AXIS, FSDP_AXIS, PIPELINE_AXIS) if pp else (TENSOR_AXIS, FSDP_AXIS)
    specs = {
        "wte": P(vocab_axes, None),
        "layers": layers,
        "ln_f": dict(ln),
    }
    if cfg.pos == "learned":
        specs["wpe"] = P(None, None)
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, vocab_axes)
        if cfg.lm_head_bias:
            specs["b_lm_head"] = P(vocab_axes)
    return specs


def _layer_norm(x, ln, eps):
    xf = x.astype(jnp.float32)
    mean = xf.mean(axis=-1, keepdims=True)
    var = xf.var(axis=-1, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (out * ln["scale"] + ln["bias"]).astype(x.dtype)


def _rope(x, positions, theta, style="half", rotary_dim=None):
    """Rotary embedding, both lineages: "half" rotates [x1|x2] halves (GPT-NeoX
    rotate_half), "interleaved" rotates (even, odd) pairs (GPT-J rotate_every_two).
    ``rotary_dim`` < head_dim ropes only the leading dims (gpt-j 64/256, neox pct)."""
    hd = x.shape[-1]
    rd = rotary_dim or hd
    x_pass = None
    if rd < hd:
        x, x_pass = x[..., :rd], x[..., rd:]
    freqs = 1.0 / (theta ** (jnp.arange(0, rd, 2, dtype=jnp.float32) / rd))
    angles = positions[..., None].astype(jnp.float32) * freqs
    cos, sin = jnp.cos(angles)[:, :, None, :], jnp.sin(angles)[:, :, None, :]
    xf = x.astype(jnp.float32)
    if style == "interleaved":
        x1, x2 = xf[..., 0::2], xf[..., 1::2]
        rot = jnp.stack([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
        out = rot.reshape(*x.shape)
    else:
        x1, x2 = jnp.split(xf, 2, axis=-1)
        out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    out = out.astype(x.dtype)
    return out if x_pass is None else jnp.concatenate([out, x_pass], axis=-1)


def _qkv(h, layer, positions, cfg: GPTConfig):
    B, T, D = h.shape
    hd = cfg.d_model // cfg.n_heads
    qkv = h @ layer["wqkv"].astype(h.dtype) + layer["b_qkv"].astype(h.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, cfg.n_heads, hd)
    k = k.reshape(B, T, cfg.n_heads, hd)
    v = v.reshape(B, T, cfg.n_heads, hd)
    if cfg.pos == "rotary":
        q = _rope(q, positions, cfg.rope_theta, cfg.rope_style, cfg.rotary_dim)
        k = _rope(k, positions, cfg.rope_theta, cfg.rope_style, cfg.rotary_dim)
    return q, k, v


def _attn_out(probs_v, layer, cfg: GPTConfig, B, T):
    out = probs_v.reshape(B, T, cfg.d_model)
    return out @ layer["wo"].astype(out.dtype) + layer["b_o"].astype(out.dtype)


def _attention_xla(q, k, v, mask):
    """gpt's reference attention path (H == K, no GQA): q/k/v [B,S,H,hd]."""
    hd = q.shape[-1]
    scores = jnp.einsum("bshd,bthd->bhst", q, k) / math.sqrt(hd)
    scores = jnp.where(mask[:, None, :, :], scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bthd->bshd", probs, v)


def _attention(q, k, v, mask, cfg: "GPTConfig", segment_ids=None):
    """Family attention via the shared dispatcher (``common.attention_dispatch``):
    flash on TPU (segment ids in-kernel for packed rows), the sp modes over an sp
    mesh, xla fallback elsewhere."""
    from .common import attention_dispatch

    return attention_dispatch(
        q, k, v, mask, impl=cfg.attn_impl, sm_scale=1.0 / math.sqrt(q.shape[-1]),
        segment_ids=segment_ids, xla_attention=_attention_xla,
    )


def _mlp(h, layer, dtype, activation="gelu_new"):
    up = h @ layer["w_up"].astype(dtype) + layer["b_up"].astype(dtype)
    if activation == "relu":
        act = jax.nn.relu(up)  # OPT's MLP nonlinearity
    else:
        act = jax.nn.gelu(up, approximate=(activation == "gelu_new"))
    return act @ layer["w_down"].astype(dtype) + layer["b_down"].astype(dtype)


def _block(x, layer, positions, mask, cfg: GPTConfig, segment_ids=None):
    B, T, D = x.shape
    h = _layer_norm(x, layer["ln_attn"], cfg.norm_eps)
    q, k, v = _qkv(h, layer, positions, cfg)
    attn = _attn_out(_attention(q, k, v, mask, cfg, segment_ids), layer, cfg, B, T)
    if cfg.parallel_residual:
        # GPT-J/NeoX: MLP reads the SAME pre-norm stream; both branches add at once.
        h2 = _layer_norm(x, layer["ln_mlp"], cfg.norm_eps)
        return x + attn + _mlp(h2, layer, x.dtype, cfg.activation)
    x = x + attn
    h2 = _layer_norm(x, layer["ln_mlp"], cfg.norm_eps)
    return x + _mlp(h2, layer, x.dtype, cfg.activation)


def _embed(params, tokens, positions, cfg: GPTConfig):
    x = params["wte"].astype(cfg.dtype)[tokens]
    if cfg.pos == "learned":
        x = x + params["wpe"].astype(cfg.dtype)[positions]
    return x


def forward(
    params: dict,
    tokens: jax.Array,
    cfg: GPTConfig,
    positions: Optional[jax.Array] = None,
    shard_activations: bool = True,
    segment_ids: Optional[jax.Array] = None,
    return_hidden: bool = False,
) -> jax.Array:
    """Causal LM: tokens [B, S] → logits [B, S, V] fp32 (post-ln_f hidden states when
    ``return_hidden`` — the fused-CE path applies the head inside its kernel).

    ``segment_ids`` (sample packing, ``ops/packing.py``): attention restricts to the
    per-segment causal block diagonal and positions default to per-segment restarts —
    learned position embeddings then index 0.. within each packed sequence, rotary
    variants restart their phase, matching unpacked behavior exactly.
    """
    from .llama import _maybe_shard, segment_mask, segment_positions

    B, S = tokens.shape
    if positions is None:
        positions = (
            segment_positions(segment_ids)
            if segment_ids is not None
            else jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
        )
    x = _embed(params, tokens, positions, cfg)
    if shard_activations:
        x = _maybe_shard(x, P(BATCH_AXES, SEQUENCE_AXIS, None))
    mask = (
        segment_mask(segment_ids)
        if segment_ids is not None
        else jnp.tril(jnp.ones((S, S), dtype=jnp.bool_))[None, :, :]
    )
    from .common import remat_wrap

    block = remat_wrap(
        _block, remat=cfg.remat, policy=cfg.remat_policy,
        prevent_cse=cfg.remat_prevent_cse, scan_layers=cfg.scan_layers, static_argnums=(4,),
    )
    if cfg.scan_layers:
        def body(carry, layer):
            out = block(carry, layer, positions, mask, cfg, segment_ids)
            if shard_activations:
                out = _maybe_shard(out, P(BATCH_AXES, SEQUENCE_AXIS, None))
            return out, None

        x, _ = jax.lax.scan(body, x, params["layers"], unroll=cfg.scan_unroll)
    else:
        for layer in params["layers"]:
            x = block(x, layer, positions, mask, cfg, segment_ids)
    x = _layer_norm(x, params["ln_f"], cfg.norm_eps)
    if return_hidden:
        return x
    return head_logits(x, params, cfg)


def _head_weight(params: dict, cfg: GPTConfig) -> jax.Array:
    return params["wte"].T if cfg.tie_embeddings else params["lm_head"]


def head_logits(x, params: dict, cfg: GPTConfig) -> jax.Array:
    """Final-hidden → fp32 logits incl. the optional lm_head bias — family pipeline
    contract (see ``llama.head_logits``)."""
    logits = (x @ _head_weight(params, cfg).astype(cfg.dtype)).astype(jnp.float32)
    if cfg.lm_head_bias and "b_lm_head" in params:
        logits = logits + params["b_lm_head"].astype(jnp.float32)
    return logits


def loss_fn(params: dict, batch: dict, cfg: GPTConfig, rng=None) -> jax.Array:
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    user_mask = batch["mask"][:, 1:].astype(jnp.float32) if "mask" in batch else None
    if "segment_ids" in batch:
        # Packed rows: targets valid only when the next slot continues the SAME segment.
        from .llama import packed_target_mask

        seg = batch["segment_ids"]
        m = packed_target_mask(seg)
        if user_mask is not None:
            m = m * user_mask
        positions = batch["positions"][:, :-1] if "positions" in batch else None
        seg_in = seg[:, :-1]
    else:
        m = user_mask
        positions = None
        seg_in = None
    from .common import ce_sum_dispatch, resolve_loss_chunk

    x = forward(
        params, inputs, cfg, positions=positions, segment_ids=seg_in,
        return_hidden=True,
    )
    mask2d = m if m is not None else jnp.ones(targets.shape, jnp.float32)
    bias = params.get("b_lm_head") if cfg.lm_head_bias else None
    total = ce_sum_dispatch(
        x, _head_weight(params, cfg), targets, mask2d,
        loss_impl=cfg.loss_impl, dtype=cfg.dtype,
        chunk=resolve_loss_chunk(cfg.loss_chunk, targets.shape[1], cfg.vocab_size),
        bias=bias,
    )
    return total / jnp.maximum(mask2d.sum(), 1.0)


# --------------------------------------------------------------- pipeline-parallel training
def _pp_stage_fn(cfg: GPTConfig, S: int, packed: bool = False, sp_manual: bool = False):
    """One pipeline stage body (gpt analog of ``llama._pp_stage_fn``): scan this stage's
    blocks over one microbatch [B_m, S, D]; positions/causal mask rebuilt locally.
    ``packed``: 3-arg form taking the pipeline's ``{"positions", "segment_ids"}`` side
    constants (sample packing — block-diagonal per-segment attention). ``sp_manual``
    (sp×pp): the pipeline's shard_map is manual over sp too, activations arrive
    sequence-sliced [B_m, S/sp, D]; attention dispatches to the flat ring/ulysses
    collectives inside ``_attention`` (rotary variants rebuild the slice's GLOBAL
    positions; gpt2's learned positions were already added at the embed, outside the
    pipeline, on the full sequence)."""
    from .common import remat_wrap

    block = remat_wrap(
        _block, remat=cfg.remat, policy=cfg.remat_policy,
        prevent_cse=cfg.remat_prevent_cse, scan_layers=True, static_argnums=(4,),
    )

    def body_scan(x, stage_layers, pos, mask, seg=None):
        def body(carry, layer):
            return block(carry, layer, pos, mask, cfg, seg), None

        out, _ = jax.lax.scan(body, x, stage_layers)
        return out

    if packed and sp_manual:
        # packing × sp × pp: activations AND the side constants arrive sequence-sliced
        # ([B_m, S/sp, D] and [B_m, S/sp] — loss_fn_pp passes the matching side_spec).
        # No mask — the sp kernels take the LOCAL segment slice (ring rotates the
        # kv-side ids with its kv block); positions are the pre-computed per-segment
        # restarts (global array, sliced).
        def stage_fn(stage_layers, x, side):
            return body_scan(
                x, stage_layers, side["positions"], None, side["segment_ids"]
            )

        return stage_fn

    if packed:
        from .llama import segment_mask

        def stage_fn(stage_layers, x, side):
            seg = side["segment_ids"]
            return body_scan(x, stage_layers, side["positions"], segment_mask(seg), seg)

        return stage_fn

    if sp_manual:
        # sp×pp: x arrives SEQUENCE-SLICED; rotary needs the slice's global positions,
        # and the sp kernels handle causality with global offsets in-kernel (no mask).
        def stage_fn(stage_layers, x):
            S_loc = x.shape[1]
            offs = jax.lax.axis_index(SEQUENCE_AXIS) * S_loc
            pos = jnp.broadcast_to(
                offs + jnp.arange(S_loc, dtype=jnp.int32), (x.shape[0], S_loc)
            )
            return body_scan(x, stage_layers, pos, None)

        return stage_fn

    def stage_fn(stage_layers, x):
        pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (x.shape[0], S))
        mask = jnp.tril(jnp.ones((S, S), dtype=jnp.bool_))[None, :, :]
        return body_scan(x, stage_layers, pos, mask)

    return stage_fn


def _guard_sp_under_pp(cfg: "GPTConfig", mesh) -> None:
    """``forward_pp``'s GPipe hidden-state path does not go manual over sp: an sp
    attention mode inside its shard_map would nest ``make_sp_attention``'s own
    shard_map, which fails to lower on the backward. Training composes sp×pp through
    ``loss_fn_pp`` (which routes through the manual-over-sp ``make_pipeline_loss_fn``
    exactly like llama); fail loudly here with the supported alternatives."""
    from .common import sp_active

    if cfg.attn_impl in ("ring", "ulysses", "ulysses_ppermute", "allgather") and (
        sp_active(mesh) or sp_active(current_abstract_mesh())
    ):
        raise NotImplementedError(
            "gpt forward_pp does not go manual over sp. For sp x pp training use "
            "loss_fn_pp (any schedule); for this forward, drop the pp axis or use "
            "attn_impl='auto'."
        )


def forward_pp(
    params: dict,
    tokens: jax.Array,
    cfg: GPTConfig,
    mesh,
    num_microbatches: Optional[int] = None,
    shard_activations: bool = True,
    segment_ids: Optional[jax.Array] = None,
    positions: Optional[jax.Array] = None,
) -> jax.Array:
    """Causal LM hidden states with the transformer blocks as a GPipe pipeline over
    ``pp`` (reference Megatron engine runs GPT with pp; its own pipelining is
    inference-only). ``params["layers"]`` stage-stacked [n_stages, L/n, ...]; embed and
    ln_f/head outside the pipe, vocab-sharded over (tp, fsdp, pp) by
    ``partition_specs(pp=True)``. Dense attention path (no packing)."""
    _guard_sp_under_pp(cfg, mesh)
    from .llama import _maybe_shard
    from ..parallel.pp import make_pipeline_fn

    B, S = tokens.shape
    packed = segment_ids is not None
    if positions is None:
        if packed:
            from .llama import segment_positions

            # Continuous arange positions would run learned/rotary positions across
            # packed segment boundaries.
            positions = segment_positions(segment_ids)
        else:
            positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    side = {"positions": positions, "segment_ids": segment_ids} if packed else None
    x = _embed(params, tokens, positions, cfg)
    if shard_activations:
        x = _maybe_shard(x, P(BATCH_AXES, None, None))
    pipe = make_pipeline_fn(
        mesh, _pp_stage_fn(cfg, S, packed=packed), num_microbatches=num_microbatches
    )
    x = pipe(params["layers"], x, side=side)
    return _layer_norm(x, params["ln_f"], cfg.norm_eps)


def _ce_sum_gpt(x, head, bias, targets, mask, cfg: GPTConfig) -> jax.Array:
    """SUM-style CE from post-ln_f hidden states, honoring the optional lm_head bias —
    the ONE copy of the gpt head math shared by loss_fn, loss_fn_pp (both schedules) and
    the 1F1B head so the paths cannot drift. Routes through ``common.ce_sum_dispatch``,
    so every ``loss_impl`` (incl. the fused_dp/fused_tp multi-chip kernels) works; a
    non-None bias falls back to the dense/chunked path (the kernels lack a bias term)."""
    from .common import ce_sum_dispatch, resolve_loss_chunk

    return ce_sum_dispatch(
        x, head, targets, mask, loss_impl=cfg.loss_impl, dtype=cfg.dtype,
        chunk=resolve_loss_chunk(cfg.loss_chunk, x.shape[1], cfg.vocab_size),
        bias=bias,
    )


def _head_ce_sum_gpt(hp: dict, y: jax.Array, ex: dict, cfg: GPTConfig) -> jax.Array:
    """SUM-style ln_f + head CE over one microbatch group (1F1B last-stage loss)."""
    x = _layer_norm(y, hp["ln_f"], cfg.norm_eps)
    return _ce_sum_gpt(x, hp["head"], hp.get("b_lm_head"), ex["targets"], ex["mask"], cfg)


def loss_fn_pp(
    params: dict,
    batch: dict,
    cfg: GPTConfig,
    mesh,
    num_microbatches: Optional[int] = None,
    rng=None,
    schedule: str = "gpipe",
    virtual_stages: int = 1,
) -> jax.Array:
    """Pipeline-parallel next-token CE for the gpt family (same contract as
    ``llama.loss_fn_pp``, including ``virtual_stages`` — the interleaved virtual
    pipeline, 1f1b only). Every ``loss_impl`` works — ln_f + the CE head run OUTSIDE
    the pipeline (1F1B) or after it (GPipe) on the full batch, ordinary GSPMD, so the
    fused kernel variants dispatch exactly as on the non-pipelined path. Sample packing
    (``segment_ids``) rides the pipeline as per-microbatch side constants, exactly like
    ``llama.loss_fn_pp``. sp attention modes (ring/ulysses/allgather over an active sp
    mesh) train inside the pipeline exactly like llama's sp_pipeline: the pipeline's
    shard_map goes manual over sp, activations ride sequence-sliced, and the stage
    body issues the collectives flat (no shard_map nesting)."""
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"schedule={schedule!r}: expected 'gpipe' or '1f1b'")
    if virtual_stages > 1 and schedule != "1f1b":
        raise NotImplementedError(
            "virtual_stages > 1 requires schedule='1f1b' (parallel/pp.py)"
        )
    from .common import resolve_sp_pipeline

    sp_pipeline, cfg = resolve_sp_pipeline(cfg, mesh, schedule, virtual_stages)
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    B, S = inputs.shape
    if "segment_ids" in batch:
        from .llama import packed_target_mask, segment_positions

        seg = batch["segment_ids"]
        mask = packed_target_mask(seg)
        if "mask" in batch:
            mask = mask * batch["mask"][:, 1:].astype(jnp.float32)
        positions = (
            batch["positions"][:, :-1]
            if "positions" in batch
            else segment_positions(seg[:, :-1])
        )
        side = {"positions": positions, "segment_ids": seg[:, :-1]}
    else:
        mask = (
            batch["mask"][:, 1:].astype(jnp.float32)
            if "mask" in batch
            else jnp.ones((B, S), jnp.float32)
        )
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
        side = None
    denom = jnp.maximum(mask.sum(), 1.0)
    if schedule == "1f1b" or sp_pipeline:
        from ..parallel.pp import make_pipeline_loss_fn

        hp = {"ln_f": params["ln_f"], "head": _head_weight(params, cfg)}
        if cfg.lm_head_bias and "b_lm_head" in params:
            hp["b_lm_head"] = params["b_lm_head"]
        pipe_loss = make_pipeline_loss_fn(
            mesh, _pp_stage_fn(cfg, S, packed=side is not None, sp_manual=sp_pipeline),
            lambda h, y, ex: _head_ce_sum_gpt(h, y, ex, cfg),
            num_microbatches=num_microbatches, schedule=schedule,
            virtual_stages=virtual_stages,
            # sp×pp: microbatch layout [M, B_m, S, D] → sequence on dim 2; packed side
            # constants slice the same way (same contract as llama.loss_fn_pp).
            act_spec=P(None, None, SEQUENCE_AXIS, None) if sp_pipeline else None,
            extra_manual_axes=(SEQUENCE_AXIS,) if sp_pipeline else (),
            side_spec=(
                {"positions": P(None, None, SEQUENCE_AXIS),
                 "segment_ids": P(None, None, SEQUENCE_AXIS)}
                if (sp_pipeline and side is not None) else None
            ),
        )
        x = _embed(params, inputs, positions, cfg)
        total = pipe_loss(
            params["layers"], hp, x, {"targets": targets, "mask": mask}, side=side
        )
        return total / denom
    x = forward_pp(
        params, inputs, cfg, mesh, num_microbatches=num_microbatches,
        segment_ids=side["segment_ids"] if side else None,
        positions=positions if side else None,
    )
    bias = params.get("b_lm_head") if cfg.lm_head_bias else None
    return _ce_sum_gpt(x, _head_weight(params, cfg), bias, targets, mask, cfg) / denom


def score(params: dict, tokens, cfg: GPTConfig, mask=None) -> jax.Array:
    """Per-token log-probabilities log p(token[t+1] | tokens[:t+1]) → [B, S-1] fp32
    (same contract as ``llama.score``; masked target positions score 0.0)."""
    tokens = jnp.asarray(tokens, jnp.int32)
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits = forward(params, inputs, cfg, shard_activations=False)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1).squeeze(-1)
    if mask is not None:
        ll = ll * mask[:, 1:].astype(ll.dtype)
    return ll


def perplexity(params: dict, tokens, cfg: GPTConfig, mask=None) -> jax.Array:
    """exp(mean negative log-likelihood over real target positions) — scalar fp32."""
    ll = score(params, tokens, cfg, mask)
    denom = jnp.maximum(mask[:, 1:].sum(), 1) if mask is not None else ll.size
    return jnp.exp(-ll.sum() / denom)


# ----------------------------------------------------------------------- cached generation
def init_cache(
    cfg: GPTConfig, batch_size: int, max_len: int, dtype=None,
    quantized: Optional[bool] = None,
) -> dict:
    from .common import kv_planes

    quantized = cfg.kv_quant if quantized is None else quantized
    dtype = dtype or cfg.dtype
    hd = cfg.d_model // cfg.n_heads
    one = lambda: kv_planes(batch_size, max_len, cfg.n_heads, hd, dtype, quantized)  # noqa: E731
    layers = (
        jax.tree_util.tree_map(lambda x: jnp.broadcast_to(x, (cfg.n_layers, *x.shape)), one())
        if cfg.scan_layers
        else [one() for _ in range(cfg.n_layers)]
    )
    return {
        "layers": layers,
        "valid": jnp.zeros((batch_size, max_len), jnp.bool_),
        "index": jnp.zeros((), jnp.int32),
    }


def init_paged_cache(
    cfg: GPTConfig, batch_size: int, max_len: int, num_pages: int, page_size: int,
    dtype=None, quantized: Optional[bool] = None,
) -> dict:
    """Paged pool cache, llama-identical contract (``llama.init_paged_cache``):
    ``{"layers": [{k,v: [P,ps,H,hd]}, ...], "valid": [B,max_len]}`` — page ownership
    lives in the host-side ``paged_kv.BlockManager``."""
    from .common import paged_kv_planes

    quantized = cfg.kv_quant if quantized is None else quantized
    dtype = dtype or cfg.dtype
    hd = cfg.d_model // cfg.n_heads
    one = lambda: paged_kv_planes(  # noqa: E731
        num_pages, page_size, cfg.n_heads, hd, dtype, quantized
    )
    layers = (
        jax.tree_util.tree_map(lambda x: jnp.broadcast_to(x, (cfg.n_layers, *x.shape)), one())
        if cfg.scan_layers
        else [one() for _ in range(cfg.n_layers)]
    )
    return {
        "layers": layers,
        "valid": jnp.zeros((batch_size, max_len), jnp.bool_),
    }


def _attention_cached(q, new_k, new_v, positions, valid, cfg: GPTConfig):
    """Attention probabilities [B,H,T,C] for q [B,T,H,hd] against the full dense
    cache view [B,C,H,hd] (``valid`` [B,C] marks live keys) — the one copy of gpt's
    cached-attention masking/softmax, shared by the dense write path and the paged
    gather fallback (bitwise parity between them)."""
    C = new_k.shape[1]
    hd = q.shape[-1]
    scores = jnp.einsum("bthd,bchd->bhtc", q, new_k) / math.sqrt(hd)
    causal = jnp.arange(C)[None, None, :] <= positions[:, :, None]
    m = (causal & valid[:, None, :])[:, None, :, :]
    return jax.nn.softmax(
        jnp.where(m, scores, jnp.finfo(scores.dtype).min).astype(jnp.float32), axis=-1
    ).astype(q.dtype)


def _block_cached(x, layer, kv, index, positions, valid, cfg: GPTConfig, paged=None):
    from .common import paged_attention_dispatch, read_kv, write_kv, write_kv_paged

    B, T, D = x.shape
    h = _layer_norm(x, layer["ln_attn"], cfg.norm_eps)
    q, k, v = _qkv(h, layer, positions, cfg)
    hd = q.shape[-1]
    if paged is not None:
        # Paged pool layout (llama._block_cached's paged contract): scatter writes
        # through the precomputed physical (page, slot) grid, read via the paged
        # dispatch (Pallas kernel on TPU, gather into this family's own
        # _attention_cached on CPU).
        tables, pages, offs, start_pos, page_size = paged
        new_kv = {**write_kv_paged(kv, "k", k, pages, offs),
                  **write_kv_paged(kv, "v", v, pages, offs)}
        probs_v = paged_attention_dispatch(
            q, new_kv, tables, start_pos, valid, page_size=page_size,
            sm_scale=1.0 / math.sqrt(hd), dtype=cfg.dtype,
            dense_attention=lambda ck, cv: jnp.einsum(
                "bhtc,bchd->bthd",
                _attention_cached(q, ck, cv, positions, valid, cfg), cv,
            ),
        )
        attn = _attn_out(probs_v, layer, cfg, B, T)
    else:
        new_kv = {**write_kv(kv, "k", k, index), **write_kv(kv, "v", v, index)}
        new_k = read_kv(new_kv, "k", cfg.dtype)
        new_v = read_kv(new_kv, "v", cfg.dtype)
        probs = _attention_cached(q, new_k, new_v, positions, valid, cfg)
        attn = _attn_out(jnp.einsum("bhtc,bchd->bthd", probs, new_v), layer, cfg, B, T)
    if cfg.parallel_residual:
        h2 = _layer_norm(x, layer["ln_mlp"], cfg.norm_eps)
        out = x + attn + _mlp(h2, layer, x.dtype, cfg.activation)
    else:
        x = x + attn
        h2 = _layer_norm(x, layer["ln_mlp"], cfg.norm_eps)
        out = x + _mlp(h2, layer, x.dtype, cfg.activation)
    return out, new_kv


def forward_cached(
    params: dict,
    tokens: jax.Array,
    cache: dict,
    cfg: GPTConfig,
    token_mask: Optional[jax.Array] = None,
    last_only: bool = False,
) -> tuple[jax.Array, dict]:
    from .llama import _cache_advance

    B, T = tokens.shape
    index, positions, valid = _cache_advance(cache, tokens, token_mask)
    x = _embed(params, tokens, positions, cfg)
    if cfg.scan_layers:
        def body(carry, layer_and_kv):
            layer, kv = layer_and_kv
            out, new_kv = _block_cached(carry, layer, kv, index, positions, valid, cfg)
            return out, new_kv

        x, new_layers = jax.lax.scan(body, x, (params["layers"], cache["layers"]))
    else:
        new_layers = []
        for layer, kv in zip(params["layers"], cache["layers"]):
            x, new_kv = _block_cached(x, layer, kv, index, positions, valid, cfg)
            new_layers.append(new_kv)
    x = _layer_norm(x, params["ln_f"], cfg.norm_eps)
    if last_only:
        x = x[:, -1:, :]
    head = params["wte"].T if cfg.tie_embeddings else params["lm_head"]
    logits = (x @ head.astype(cfg.dtype)).astype(jnp.float32)
    if cfg.lm_head_bias and "b_lm_head" in params:
        logits = logits + params["b_lm_head"].astype(jnp.float32)
    return logits, {"layers": new_layers, "valid": valid, "index": index + T}


def forward_slots(
    params: dict,
    tokens: jax.Array,
    cache: dict,
    positions: jax.Array,
    cfg: GPTConfig,
    tables: Optional[jax.Array] = None,
    page_size: int = 0,
) -> tuple[jax.Array, dict]:
    """Per-slot cached forward, llama-identical contract (``llama.forward_slots``):
    ``tokens`` [B,T] written at each row's own slots ``positions[b] ..
    positions[b]+T-1`` → (logits fp32 [B,T,V], new cache). T == 1 is continuous-batching
    decode; T == k+1 is the batched speculative verify. Lets a gpt-family draft model
    ride the serving engine's speculative decoder (cross-family draft/target pairs share
    this contract through ``common.cached_decode_family``). ``tables``/``page_size``
    switch the KV side to the paged pool layout — one forward for both layouts."""
    from .common import paged_write_coords

    B, T = tokens.shape
    rows = jnp.arange(B)
    pos_grid = positions[:, None] + jnp.arange(T, dtype=positions.dtype)[None, :]
    if T == 1:
        valid = cache["valid"].at[rows, positions].set(True)
    else:
        valid = cache["valid"].at[rows[:, None], pos_grid].set(True)
    paged = None
    if tables is not None:
        num_pages = jax.tree_util.tree_leaves(cache["layers"])[0].shape[
            1 if cfg.scan_layers else 0
        ]
        pages, offs = paged_write_coords(
            tables, pos_grid, page_size, cache["valid"].shape[1], num_pages
        )
        paged = (tables, pages, offs, positions, page_size)
    x = _embed(params, tokens, pos_grid, cfg)
    if cfg.scan_layers:
        def body(carry, layer_and_kv):
            layer, kv = layer_and_kv
            out, new_kv = _block_cached(
                carry, layer, kv, positions, pos_grid, valid, cfg, paged=paged
            )
            return out, new_kv

        x, new_layers = jax.lax.scan(body, x, (params["layers"], cache["layers"]))
    else:
        new_layers = []
        for layer, kv in zip(params["layers"], cache["layers"]):
            x, new_kv = _block_cached(
                x, layer, kv, positions, pos_grid, valid, cfg, paged=paged
            )
            new_layers.append(new_kv)
    x = _layer_norm(x, params["ln_f"], cfg.norm_eps)
    head = params["wte"].T if cfg.tie_embeddings else params["lm_head"]
    logits = (x @ head.astype(cfg.dtype)).astype(jnp.float32)
    if cfg.lm_head_bias and "b_lm_head" in params:
        logits = logits + params["b_lm_head"].astype(jnp.float32)
    if paged is not None:
        return logits, {"layers": new_layers, "valid": valid}
    return logits, {"layers": new_layers, "valid": valid, "index": cache["index"]}


def forward_slots_paged(
    params: dict,
    tokens: jax.Array,
    cache: dict,
    tables: jax.Array,
    positions: jax.Array,
    cfg: GPTConfig,
    page_size: int,
) -> tuple[jax.Array, dict]:
    """:func:`forward_slots` over the paged pool cache, llama-identical contract
    (``llama.forward_slots_paged``) — a thin delegate into the ONE shared forward,
    so the dense and paged layouts cannot drift. Keeps a gpt-family draft/target
    viable on a paged serving engine."""
    return forward_slots(
        params, tokens, cache, positions, cfg, tables=tables, page_size=page_size
    )


def forward_slots_multi(
    params: dict,
    cache: dict,
    tokens: jax.Array,
    positions: jax.Array,
    active: jax.Array,
    budgets: jax.Array,
    eos_ids: jax.Array,
    select_token,
    xs,
    n_steps: int,
    cfg: GPTConfig,
    tables: Optional[jax.Array] = None,
    page_size: int = 0,
) -> tuple[dict, jax.Array, jax.Array]:
    """N T == 1 :func:`forward_slots` decode steps as ONE ``lax.scan``,
    llama-identical contract (``llama.forward_slots_multi``) — the serving
    engine's ``decode_steps=N`` super-step for a gpt-family model. See
    :func:`~.common.multi_step_decode` for the freeze/emission contract.
    Returns ``(cache, tok_buf [n_steps, B], counts [B])``."""
    from .common import multi_step_decode

    max_len = cache["valid"].shape[1]

    def forward_one(c, tok, write_pos):
        logits, c = forward_slots(
            params, tok[:, None], c, write_pos, cfg, tables=tables,
            page_size=page_size,
        )
        return logits[:, -1, :], c

    return multi_step_decode(
        forward_one, cache, tokens, positions, active, budgets, eos_ids,
        select_token, xs, n_steps, max_len,
    )


def forward_slots_spec_multi(
    params: dict,
    cache: dict,
    tokens: jax.Array,
    positions: jax.Array,
    active: jax.Array,
    budgets: jax.Array,
    eos_ids: jax.Array,
    propose,
    select_ref,
    key_tab: jax.Array,
    history: jax.Array,
    hist_lens: jax.Array,
    n_steps: int,
    spec_k: int,
    cfg: GPTConfig,
    tables: Optional[jax.Array] = None,
    page_size: int = 0,
):
    """N speculative draft→verify→accept rounds as ONE ``lax.scan``,
    llama-identical contract (``llama.forward_slots_spec_multi``) — each round's
    verify is a T == spec_k+1 :func:`forward_slots` call. See
    :func:`~.common.spec_multi_step_decode` for the accept/key-cursor/freeze
    contract. Returns ``(cache, tok_buf [n_steps, B, spec_k+1], emits
    [n_steps, B], counts [B], proposed [B], accepted [B])``."""
    from .common import spec_multi_step_decode

    max_len = cache["valid"].shape[1]

    def forward_verify(c, seq, write_pos):
        return forward_slots(
            params, seq, c, write_pos, cfg, tables=tables, page_size=page_size
        )

    return spec_multi_step_decode(
        forward_verify, propose, select_ref, cache, tokens, positions, active,
        budgets, eos_ids, key_tab, history, hist_lens, n_steps, spec_k, max_len,
    )


def _make_gen_fns(cfg: GPTConfig, max_len: int):
    def prefill_fn(p, pr, pm):
        cache = init_cache(cfg, pr.shape[0], max_len)
        logits, cache = forward_cached(p, pr, cache, cfg, token_mask=pm, last_only=True)
        return logits[:, -1, :], cache

    def decode_fn(p, cache, token):
        logits, cache = forward_cached(p, token[:, None], cache, cfg)
        return logits[:, -1, :], cache

    return prefill_fn, decode_fn


# Stable (prefill, decode) closure identities per (cfg, bucketed max_len): generate_loop
# jit-caches by function identity, so fresh closures per call would recompile every time
# (same bounded-LRU pattern as llama._GEN_FNS).
from collections import OrderedDict  # noqa: E402

_GEN_FNS: OrderedDict = OrderedDict()
_GEN_FNS_MAX = 16


def generate(
    params: dict,
    prompt: jax.Array,
    cfg: GPTConfig,
    gen=None,
    rng: Optional[jax.Array] = None,
    prompt_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Autoregressive generation (one compiled prefill + decode scan), llama-identical API."""
    from ..generation import GenerationConfig, generate_loop

    gen = gen or GenerationConfig()
    prompt = jnp.asarray(prompt, jnp.int32)
    if prompt_mask is None:
        prompt_mask = jnp.ones(prompt.shape, jnp.bool_)
    max_len = -(-(prompt.shape[1] + gen.max_new_tokens) // 64) * 64
    key = (cfg, max_len)
    if key not in _GEN_FNS:
        _GEN_FNS[key] = _make_gen_fns(cfg, max_len)
        while len(_GEN_FNS) > _GEN_FNS_MAX:
            _GEN_FNS.popitem(last=False)
    _GEN_FNS.move_to_end(key)
    prefill_fn, decode_fn = _GEN_FNS[key]
    return generate_loop(prefill_fn, decode_fn, params, prompt, prompt_mask, gen, rng)


def generate_speculative(target_params, target_cfg, draft_params, draft_cfg, prompt,
                         **kwargs):
    """Speculative decoding for gpt-family targets/drafts — delegates to the
    family-generic implementation (``llama.generate_speculative``; both families
    share the cached-decode contract). Cross-family pairs work too."""
    from .llama import generate_speculative as _generic

    return _generic(target_params, target_cfg, draft_params, draft_cfg, prompt, **kwargs)


def generate_streamed(
    dispatched,
    prompt: jax.Array,
    cfg: GPTConfig,
    gen=None,
    rng: Optional[jax.Array] = None,
    prompt_mask: Optional[jax.Array] = None,
    prefetch: int = 2,
    pass_times: Optional[list] = None,
) -> jax.Array:
    """Generation for GPT models bigger than HBM (gpt-neox-20b bf16 = 40 GB, opt-30b = 60 GB):
    block weights stream from host RAM / disk with double-buffered prefetch.

    Same contract as ``llama.generate_streamed``; this is the TPU-native counterpart of the
    reference's offloaded ``generate`` over ``AlignDevicesHook`` (``hooks.py:329``) that
    produced the OPT-30B / GPT-NeoX-20B offload baselines
    (``benchmarks/big_model_inference/README.md:33-37``).
    """
    from .llama import _cache_advance, _streamed_head_jit
    from ..big_modeling import consume_block, stream_blocks
    from ..generation import GenerationConfig, streamed_generate_loop

    if cfg.scan_layers:
        raise ValueError("generate_streamed requires per-layer (non-scanned) params.")
    gen = gen or GenerationConfig()
    B, S0 = jnp.asarray(prompt).shape
    max_len = S0 + gen.max_new_tokens
    prefixes = [f"layers/{i}" for i in range(cfg.n_layers)]
    # Hoist the always-resident leaves out of the loop: only transformer BLOCKS stream
    # per pass; re-fetching wte from disk would cost ~690 MB of I/O per token at opt-30b.
    wte = dispatched.fetch("wte")
    wpe = dispatched.fetch("wpe") if cfg.pos == "learned" else None
    ln_f = dispatched.fetch("ln_f")
    head = wte if cfg.tie_embeddings else dispatched.fetch("lm_head")
    head_bias = (
        dispatched.fetch("b_lm_head")
        if cfg.lm_head_bias and not cfg.tie_embeddings and "b_lm_head" in dispatched.weights
        else None
    )

    def one_pass(tokens, cache, token_mask):
        if cache is None:
            cache = init_cache(cfg, B, max_len)
        index, positions, valid = _cache_advance(cache, tokens, token_mask)
        # Gather THEN cast — the loop is host-driven, so casting the whole [V, D] matrix
        # per pass would dominate.
        x = wte[tokens].astype(cfg.dtype)
        if wpe is not None:
            x = x + wpe[positions].astype(cfg.dtype)
        new_layers = []
        for i, layer in stream_blocks(dispatched, prefixes, prefetch=prefetch):
            idx = int(i.split("/")[1])
            x, new_kv = _block_cached_jit(
                x, layer, cache["layers"][idx], index, positions, valid, cfg=cfg
            )
            # Fence + free this block's buffers NOW (relay clients retain host
            # mirrors of lazily-GC'd device buffers — big_modeling.consume_block).
            consume_block(x, layer, dispatched, i)
            new_layers.append(new_kv)
        x = _layer_norm(x, ln_f, cfg.norm_eps)
        logits = _streamed_head_jit(x[:, -1, :], head, transpose=cfg.tie_embeddings)
        if head_bias is not None:
            logits = logits + jnp.asarray(head_bias, jnp.float32)
        return logits, {"layers": new_layers, "valid": valid, "index": index + tokens.shape[1]}

    return streamed_generate_loop(one_pass, prompt, prompt_mask, gen, rng,
                                  pass_times=pass_times)


@partial(jax.jit, static_argnames=("cfg",))
def _block_cached_jit(x, layer, kv, index, positions, valid, cfg):
    """Module-level jit identity: one compile per shape across streamed decode steps."""
    return _block_cached(x, layer, kv, index, positions, valid, cfg)


def num_params(cfg: GPTConfig) -> int:
    """Analytic parameter count — never materializes the model (gpt-neox-20b is 80 GB fp32)."""
    D, F, V, L = cfg.d_model, cfg.d_ff, cfg.vocab_size, cfg.n_layers
    per_layer = (
        D * 3 * D + 3 * D      # wqkv + bias
        + D * D + D            # wo + bias
        + 2 * D * F + F + D    # w_up/w_down + biases
        + 4 * D                # two layernorms (scale + bias)
    )
    total = V * D + L * per_layer + 2 * D  # wte + layers + ln_f
    if cfg.pos == "learned":
        total += cfg.max_seq * D
    if not cfg.tie_embeddings:
        total += D * V
        if cfg.lm_head_bias:
            total += V
    return total
