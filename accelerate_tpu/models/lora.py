"""LoRA fine-tuning helpers — the TPU-native analog of the reference's peft integration.

The reference trains peft-wrapped ``nn.Module``s through Accelerate (``is_peft_model``,
``extract_model_from_parallel`` unwrap support — reference ``utils/other.py:62``,
``accelerator.py``). Here adaptation is a property of the params pytree instead of a model
wrapper: ``LlamaConfig(lora_rank=r)`` makes ``init_params`` add ``{name}_lora_a/b`` leaves
next to each targeted projection and the forward adds the low-rank delta
``(x @ A) @ B · alpha/rank`` (``llama._proj_l``) — the adapted weight is never materialized.

The pieces here make partial trainability work through the standard facade:

- ``add_adapters(params, cfg)`` — attach freshly initialized adapters to an EXISTING
  params tree (an HF-loaded checkpoint via ``models.hf_interop`` — the primary workflow).
- ``lora_mask(params)`` — bool pytree, True on adapter leaves.
- ``lora_optimizer(tx)`` — ``optax.multi_transform`` wrapper routing base leaves to
  ``set_to_zero``: optimizer state exists ONLY for adapter leaves (the memory point of
  LoRA: the frozen base carries no Adam moments).
- ``merge_lora(params, cfg)`` — fold adapters into the base weights for export/serving;
  returns (plain params, cfg with lora off).
- ``only_lora(params)`` / ``load_lora(params, adapters)`` — adapter-only checkpoint
  round-trip (the peft ``save_pretrained``/``load_adapter`` analog).

Works with scanned ([L, ...]-stacked) and unrolled layer layouts.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

__all__ = [
    "add_adapters",
    "lora_mask",
    "lora_optimizer",
    "merge_lora",
    "merge_lora_trees",
    "only_lora",
    "load_lora",
]

_LORA_MARKERS = ("_lora_a", "_lora_b")


def _is_lora_path(path) -> bool:
    for entry in path:
        key = getattr(entry, "key", None)
        if isinstance(key, str) and key.endswith(_LORA_MARKERS):
            return True
    return False


def add_adapters(params: dict, cfg, key: Any = None) -> dict:
    """Attach freshly initialized adapters to an existing params tree.

    The primary LoRA workflow loads a PRETRAINED base (``models.hf_interop`` — which knows
    nothing about adapters) and then adapts it: this returns a new tree with
    ``{name}_lora_a`` (A ~ N(0, 1/d_in)) and ``{name}_lora_b`` (zeros) next to each target
    of ``cfg.lora_targets``, for both unrolled (list) and scan-stacked layer layouts.
    Forward behavior is exactly the base model until training moves B off zero.
    """
    import math

    from .llama import _lora_target_names

    if cfg.lora_rank <= 0:
        raise ValueError("add_adapters requires cfg.lora_rank > 0")
    if key is None:
        key = jax.random.PRNGKey(0)  # graftlint: disable=rng-key-reuse(deterministic default init; callers pass a key for real entropy)
    names = _lora_target_names(cfg)
    r = cfg.lora_rank

    def _one(layer: dict, layer_key) -> dict:
        out = dict(layer)
        for i, name in enumerate(names):
            if f"{name}_lora_a" in layer:
                raise ValueError(f"params already carry adapters for {name!r}")
            shape = layer[name].shape  # [d_in, d_out] or scan-stacked [L, d_in, d_out]
            d_in = shape[-2]
            a_shape = (*shape[:-1], r)
            b_shape = (*shape[:-2], r, shape[-1])
            out[f"{name}_lora_a"] = (
                jax.random.normal(jax.random.fold_in(layer_key, i), a_shape, jnp.float32)
                / math.sqrt(d_in)
            )
            out[f"{name}_lora_b"] = jnp.zeros(b_shape, jnp.float32)
        return out

    adapted = dict(params)
    layers = params["layers"]
    if isinstance(layers, list):
        adapted["layers"] = [
            _one(layer, jax.random.fold_in(key, i)) for i, layer in enumerate(layers)
        ]
    else:
        adapted["layers"] = _one(layers, key)
    return adapted


def lora_mask(params: Any) -> Any:
    """Bool pytree (same structure as ``params``): True exactly on adapter leaves."""
    return jax.tree_util.tree_map_with_path(lambda path, _: _is_lora_path(path), params)


def lora_optimizer(tx):
    """Wrap an optax transformation to update ONLY adapter leaves.

    ``optax.multi_transform`` routes adapter leaves to ``tx`` and base leaves to
    ``set_to_zero`` (``optax.masked`` alone would pass the base's raw gradients through as
    updates). Optimizer state exists solely for adapter leaves, so the frozen base carries
    no Adam moments — the LoRA memory win. Pass the result to
    ``Accelerator.create_train_state`` as usual.
    """
    import optax

    def labels(params):
        return jax.tree_util.tree_map_with_path(
            lambda path, _: "adapter" if _is_lora_path(path) else "frozen", params
        )

    return optax.multi_transform({"adapter": tx, "frozen": optax.set_to_zero()}, labels)


def only_lora(params: Any) -> dict:
    """Flat ``{path: leaf}`` dict of just the adapter leaves (tiny — checkpoint this to
    save adapters separately from the frozen base, peft-style)."""
    flat = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        if _is_lora_path(path):
            flat[jax.tree_util.keystr(path)] = leaf
    return flat


def load_lora(params: Any, adapters: dict) -> Any:
    """Inverse of :func:`only_lora`: replace adapter leaves with checkpointed values.

    ``adapters`` is the ``{keystr(path): leaf}`` dict ``only_lora`` produced; every entry
    must match an adapter leaf in ``params`` (missing or extra keys raise — a silent
    partial load would quietly serve the wrong model).
    """
    remaining = dict(adapters)

    def _sub(path, leaf):
        key = jax.tree_util.keystr(path)
        if _is_lora_path(path):
            if key not in remaining:
                raise KeyError(f"adapter checkpoint is missing {key}")
            new = remaining.pop(key)
            if new.shape != leaf.shape:
                raise ValueError(f"{key}: checkpoint shape {new.shape} != params {leaf.shape}")
            return new
        return leaf

    out = jax.tree_util.tree_map_with_path(_sub, params)
    if remaining:
        raise KeyError(f"adapter checkpoint has extra entries: {sorted(remaining)[:3]}")
    return out


def merge_lora_trees(layer: dict, cfg) -> dict:
    """Fold one layer dict's adapters into its base weights; drops the adapter leaves."""
    scale = cfg.lora_alpha / cfg.lora_rank
    merged = {}
    for name, leaf in layer.items():
        if name.endswith(_LORA_MARKERS):
            continue
        a = layer.get(f"{name}_lora_a")
        if a is not None:
            b = layer[f"{name}_lora_b"]
            # Works for both [d_in, d_out] and scan-stacked [L, d_in, d_out] leaves.
            delta = jnp.einsum("...ir,...ro->...io", a, b) * scale
            leaf = (leaf + delta.astype(leaf.dtype)).astype(leaf.dtype)
        merged[name] = leaf
    return merged


def merge_lora(params: dict, cfg):
    """Fold every layer's adapters into the base weights for export/serving.

    Returns ``(plain_params, plain_cfg)`` where ``plain_cfg`` has ``lora_rank=0`` — the
    merged model is a regular base-architecture checkpoint (usable by ``generate``, the
    serving engine, ``save_pretrained``-style export, quantization, ...).
    """
    if cfg.lora_rank <= 0:
        return params, cfg
    out = dict(params)
    layers = params["layers"]
    if isinstance(layers, list):
        out["layers"] = [merge_lora_trees(layer, cfg) for layer in layers]
    else:
        out["layers"] = merge_lora_trees(layers, cfg)
    plain_cfg = dataclasses.replace(cfg, lora_rank=0)
    return out, plain_cfg
