"""BERT-family encoder for sequence classification — the ``nlp_example.py`` workhorse.

The reference framework trains ``bert-base-cased`` on GLUE/MRPC in its flagship example
(reference ``examples/nlp_example.py``) through ``transformers``; this framework ships the
encoder natively so the same example runs TPU-first (sharding in the model definition, jitted
step). Architecture: standard BERT-base — learned position/type embeddings, post-LN
transformer blocks, GELU MLP, tanh pooler, classification head.

Weights are compatible in shape with HF ``bert-base-*`` checkpoints (vocab 30522, d=768,
L=12, H=12, ff=3072), loadable via ``utils/modeling.load_checkpoint_in_model`` after key-path
mapping. ``partition_specs`` gives the Megatron TP layout; batch/sequence activation sharding
matches llama's.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..utils.constants import BATCH_AXES, TENSOR_AXIS

__all__ = [
    "BertConfig", "init_params", "forward", "loss_fn", "partition_specs", "CONFIGS",
    "stack_pp_params", "forward_pp", "loss_fn_pp",
]


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq: int = 512
    type_vocab_size: int = 2
    num_labels: int = 2
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


CONFIGS = {
    "bert-base": BertConfig(),
    "bert-large": BertConfig(d_model=1024, n_layers=24, n_heads=16, d_ff=4096),
    "tiny": BertConfig(
        vocab_size=512, d_model=64, n_layers=2, n_heads=4, d_ff=128, max_seq=128
    ),
}


def _layer_params(cfg: BertConfig, key) -> dict:
    k = jax.random.split(key, 6)
    D, F = cfg.d_model, cfg.d_ff
    s = 0.02
    return {
        "wq": jax.random.normal(k[0], (D, D), jnp.float32) * s,
        "bq": jnp.zeros((D,), jnp.float32),
        "wk": jax.random.normal(k[1], (D, D), jnp.float32) * s,
        "bk": jnp.zeros((D,), jnp.float32),
        "wv": jax.random.normal(k[2], (D, D), jnp.float32) * s,
        "bv": jnp.zeros((D,), jnp.float32),
        "wo": jax.random.normal(k[3], (D, D), jnp.float32) * s,
        "bo": jnp.zeros((D,), jnp.float32),
        "ln1": {"gamma": jnp.ones((D,), jnp.float32), "beta": jnp.zeros((D,), jnp.float32)},
        "w_in": jax.random.normal(k[4], (D, F), jnp.float32) * s,
        "b_in": jnp.zeros((F,), jnp.float32),
        "w_out": jax.random.normal(k[5], (F, D), jnp.float32) * s,
        "b_out": jnp.zeros((D,), jnp.float32),
        "ln2": {"gamma": jnp.ones((D,), jnp.float32), "beta": jnp.zeros((D,), jnp.float32)},
    }


def init_params(cfg: BertConfig, key: Optional[jax.Array] = None) -> dict:
    if key is None:
        key = jax.random.PRNGKey(0)  # graftlint: disable=rng-key-reuse(deterministic default init; callers pass a key for real entropy)
    keys = jax.random.split(key, cfg.n_layers + 4)
    s = 0.02
    D = cfg.d_model
    return {
        "embed": {
            "tokens": jax.random.normal(keys[0], (cfg.vocab_size, D), jnp.float32) * s,
            "positions": jax.random.normal(keys[1], (cfg.max_seq, D), jnp.float32) * s,
            "types": jax.random.normal(keys[2], (cfg.type_vocab_size, D), jnp.float32) * s,
            "ln": {"gamma": jnp.ones((D,), jnp.float32), "beta": jnp.zeros((D,), jnp.float32)},
        },
        "layers": [_layer_params(cfg, keys[i + 3]) for i in range(cfg.n_layers)],
        "pooler": {
            "w": jax.random.normal(keys[-1], (D, D), jnp.float32) * s,
            "b": jnp.zeros((D,), jnp.float32),
        },
        "classifier": {
            "w": jnp.zeros((D, cfg.num_labels), jnp.float32),
            "b": jnp.zeros((cfg.num_labels,), jnp.float32),
        },
    }


def partition_specs(cfg: BertConfig, pp: bool = False, virtual_stages: int = 1) -> dict:
    """Megatron TP layout: QKV/in column-parallel, O/out row-parallel.

    ``pp=True``: specs for the :func:`stack_pp_params` layout — blocks stage-stacked
    ``[n_stages, L/n, ...]`` with the stage dim over ``pp``; embed/pooler/classifier
    stay outside the pipeline (replicated over pp — they are tiny next to the stack).
    ``virtual_stages=v > 1``: the interleaved [v, n, L/(n·v), ...] layout (pp dim 1)."""
    col, row = P(None, TENSOR_AXIS), P(TENSOR_AXIS, None)
    ln = {"gamma": P(), "beta": P()}
    layer = {
        "wq": col, "bq": P(TENSOR_AXIS), "wk": col, "bk": P(TENSOR_AXIS),
        "wv": col, "bv": P(TENSOR_AXIS), "wo": row, "bo": P(),
        "ln1": dict(ln),
        "w_in": col, "b_in": P(TENSOR_AXIS), "w_out": row, "b_out": P(),
        "ln2": dict(ln),
    }
    if pp:
        from ..parallel.pp import stage_spec_prefix

        layers = jax.tree_util.tree_map(
            lambda s: P(*stage_spec_prefix(virtual_stages), *s), layer,
            is_leaf=lambda s: isinstance(s, P),
        )
    else:
        layers = [dict(layer) for _ in range(cfg.n_layers)]
    return {
        "embed": {"tokens": P(TENSOR_AXIS, None), "positions": P(), "types": P(), "ln": dict(ln)},
        "layers": layers,
        "pooler": {"w": P(), "b": P()},
        "classifier": {"w": P(), "b": P()},
    }


def _layer_norm(x, ln, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    normed = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (normed * ln["gamma"] + ln["beta"]).astype(x.dtype)


def _maybe_shard(x):
    from ..ops.collectives import maybe_shard

    return maybe_shard(x, P(BATCH_AXES, None, None))


def _block(x, layer, attn_mask, cfg: BertConfig):
    B, S, D = x.shape
    dtype = cfg.dtype
    q = (x @ layer["wq"].astype(dtype) + layer["bq"].astype(dtype)).reshape(B, S, cfg.n_heads, cfg.head_dim)
    k = (x @ layer["wk"].astype(dtype) + layer["bk"].astype(dtype)).reshape(B, S, cfg.n_heads, cfg.head_dim)
    v = (x @ layer["wv"].astype(dtype) + layer["bv"].astype(dtype)).reshape(B, S, cfg.n_heads, cfg.head_dim)
    scores = jnp.einsum("bshd,bthd->bhst", q, k) / math.sqrt(cfg.head_dim)
    scores = jnp.where(attn_mask[:, None, None, :], scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dtype)
    attn = jnp.einsum("bhst,bthd->bshd", probs, v).reshape(B, S, D)
    x = _layer_norm(x + attn @ layer["wo"].astype(dtype) + layer["bo"].astype(dtype), layer["ln1"], cfg.layer_norm_eps)
    h = jax.nn.gelu(x @ layer["w_in"].astype(dtype) + layer["b_in"].astype(dtype), approximate=False)
    x = _layer_norm(x + h @ layer["w_out"].astype(dtype) + layer["b_out"].astype(dtype), layer["ln2"], cfg.layer_norm_eps)
    return x


def forward(
    params: dict,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array] = None,
    token_type_ids: Optional[jax.Array] = None,
    cfg: BertConfig = CONFIGS["bert-base"],
) -> jax.Array:
    """[B, S] ids → [B, num_labels] classification logits (fp32)."""
    x, attention_mask = _embed(params, input_ids, attention_mask, token_type_ids, cfg)
    x = _maybe_shard(x)

    block = _block
    if cfg.remat:
        block = jax.checkpoint(_block, static_argnums=(3,))
    for layer in params["layers"]:
        x = block(x, layer, attention_mask, cfg)
        x = _maybe_shard(x)
    return _head_logits(params, x, cfg)


def loss_fn(params: dict, batch: dict, cfg: BertConfig) -> jax.Array:
    """Cross-entropy over batch {input_ids, attention_mask?, token_type_ids?, labels}."""
    logits = forward(
        params,
        batch["input_ids"],
        batch.get("attention_mask"),
        batch.get("token_type_ids"),
        cfg,
    )
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, batch["labels"][:, None], axis=-1).squeeze(-1)
    return -jnp.mean(ll)


# --------------------------------------------------------------- pipeline-parallel training
def stack_pp_params(
    params: dict, cfg: BertConfig, n_stages: int, virtual_stages: int = 1
) -> dict:
    """Canonical params → pipeline layout: the (homogeneous) block list stacks to
    ``[n_stages, L/n, ...]`` (``[v, n, L/(n·v), ...]`` with ``virtual_stages``);
    embed/pooler/classifier pass through unchanged (they run outside the pipeline).
    Specs: ``partition_specs(cfg, pp=True)``. Reference bar: the Megatron engine
    drives Bert through pp (``megatron_lm.py:446``)."""
    if cfg.n_layers % (n_stages * virtual_stages):
        raise ValueError(
            f"n_layers={cfg.n_layers} must be divisible by n_stages={n_stages} x "
            f"virtual_stages={virtual_stages}"
        )
    from ..parallel.pp import split_params_into_stages

    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params["layers"])
    return {**{k: v for k, v in params.items() if k != "layers"},
            "layers": split_params_into_stages(
                stacked, n_stages, virtual_stages=virtual_stages
            )}


def _pp_stage_fn(cfg: BertConfig):
    """One pipeline stage: scan this stage's blocks over a microbatch; the attention
    mask rides as a per-microbatch side constant (``parallel.pp`` side contract —
    boolean, correctly non-differentiable)."""
    block = _block
    if cfg.remat:
        block = jax.checkpoint(_block, static_argnums=(3,))

    def stage_fn(stage_layers, x, side):
        def body(carry, layer):
            return block(carry, layer, side["attention_mask"], cfg), None

        out, _ = jax.lax.scan(body, x, stage_layers)
        return out

    return stage_fn


def _embed(params: dict, input_ids, attention_mask, token_type_ids, cfg: BertConfig):
    B, S = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones((B, S), jnp.bool_)
    else:
        attention_mask = attention_mask.astype(jnp.bool_)
    if token_type_ids is None:
        token_type_ids = jnp.zeros((B, S), jnp.int32)
    emb = params["embed"]
    x = (
        emb["tokens"][input_ids]
        + emb["positions"][jnp.arange(S)][None, :, :]
        + emb["types"][token_type_ids]
    ).astype(cfg.dtype)
    return _layer_norm(x, emb["ln"], cfg.layer_norm_eps), attention_mask


def _head_logits(hp: dict, x, cfg: BertConfig):
    dtype = cfg.dtype
    pooled = jnp.tanh(x[:, 0, :] @ hp["pooler"]["w"].astype(dtype) + hp["pooler"]["b"].astype(dtype))
    logits = pooled @ hp["classifier"]["w"].astype(dtype) + hp["classifier"]["b"].astype(dtype)
    return logits.astype(jnp.float32)


def forward_pp(
    params: dict,
    input_ids: jax.Array,
    cfg: BertConfig,
    mesh,
    num_microbatches: Optional[int] = None,
    attention_mask: Optional[jax.Array] = None,
    token_type_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Classification logits with the encoder blocks as a GPipe pipeline over ``pp``
    (params in :func:`stack_pp_params` layout)."""
    from ..parallel.pp import make_pipeline_fn

    x, attention_mask = _embed(params, input_ids, attention_mask, token_type_ids, cfg)
    x = _maybe_shard(x)
    pipe = make_pipeline_fn(mesh, _pp_stage_fn(cfg), num_microbatches=num_microbatches)
    x = pipe(params["layers"], x, side={"attention_mask": attention_mask})
    return _head_logits(params, x, cfg)


def loss_fn_pp(
    params: dict,
    batch: dict,
    cfg: BertConfig,
    mesh,
    num_microbatches: Optional[int] = None,
    rng=None,
    schedule: str = "gpipe",
    virtual_stages: int = 1,
) -> jax.Array:
    """Pipeline-parallel classification CE (same batch contract as ``loss_fn``; params
    in :func:`stack_pp_params` layout; both schedules — the pooler/classifier head runs
    OUTSIDE the pipeline on the full batch; ``virtual_stages`` with 1f1b = the
    interleaved pipeline, the attention mask riding as an int side constant)."""
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"schedule={schedule!r}: expected 'gpipe' or '1f1b'")
    if virtual_stages > 1 and schedule != "1f1b":
        raise NotImplementedError(
            "virtual_stages > 1 requires schedule='1f1b' (parallel/pp.py)"
        )
    labels = batch["labels"]
    if schedule == "1f1b":
        from ..parallel.pp import make_pipeline_loss_fn

        x, attention_mask = _embed(
            params, batch["input_ids"], batch.get("attention_mask"),
            batch.get("token_type_ids"), cfg,
        )
        hp = {"pooler": params["pooler"], "classifier": params["classifier"]}

        def head_loss(h, y, ex):
            logits = _head_logits(h, y, cfg)
            logp = jax.nn.log_softmax(logits, axis=-1)
            ll = jnp.take_along_axis(logp, ex["labels"][:, None], axis=-1).squeeze(-1)
            return -jnp.mean(ll)

        pipe_loss = make_pipeline_loss_fn(
            mesh, _pp_stage_fn(cfg), head_loss,
            num_microbatches=num_microbatches, schedule="1f1b",
            virtual_stages=virtual_stages,
        )
        x = _maybe_shard(x)
        return pipe_loss(
            params["layers"], hp, x, {"labels": labels},
            side={"attention_mask": attention_mask},
        )
    logits_x = forward_pp(
        params, batch["input_ids"], cfg, mesh, num_microbatches=num_microbatches,
        attention_mask=batch.get("attention_mask"),
        token_type_ids=batch.get("token_type_ids"),
    )
    logp = jax.nn.log_softmax(logits_x, axis=-1)
    ll = jnp.take_along_axis(logp, labels[:, None], axis=-1).squeeze(-1)
    return -jnp.mean(ll)
