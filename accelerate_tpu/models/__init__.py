"""Model families shipped with the framework (flagship: llama; plus gpt, bert, resnet, simple)."""

from . import bert, gpt, llama, lora, resnet, simple, t5
