"""Model families shipped with the framework (flagship: llama; plus gpt, bert, resnet, simple)."""

from . import bert, gpt, llama, resnet, simple, t5
