"""Model families shipped with the framework (flagship: llama; plus bert, resnet, simple)."""

from . import bert, llama, resnet, simple
