"""Model families shipped with the framework (flagship: llama; plus bert, gpt2, simple)."""

from . import bert, llama, simple
