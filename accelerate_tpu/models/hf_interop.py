"""HuggingFace checkpoint interop — load reference-ecosystem weights into the mesh runtime.

A user switching from the reference keeps their checkpoints: ``transformers`` state dicts
(LlamaForCausalLM, GPT2LMHeadModel) convert to this framework's param pytrees and back.
Torch linear layers store ``[out, in]`` (transposed here to our ``x @ w`` convention);
GPT-2's ``Conv1D`` already stores ``[in, out]`` and passes through.

Reference analog: the reference leans on ``transformers`` directly (its models ARE torch
modules); here the conversion is an explicit, tested mapping. Combine with
``utils/serialization.load_flat_safetensors`` / ``utils/modeling.load_checkpoint_in_model``
to stream sharded checkpoint files.
"""

from __future__ import annotations

import re
from typing import Any, Mapping

import numpy as np
import jax.numpy as jnp

__all__ = [
    "llama_config_from_hf",
    "llama_from_hf",
    "mistral_config_from_hf",
    "mistral_from_hf",
    "qwen2_config_from_hf",
    "qwen2_from_hf",
    "gemma2_config_from_hf",
    "gemma2_from_hf",
    "gpt2_config_from_hf",
    "gpt2_from_hf",
    "gptj_config_from_hf",
    "gptj_from_hf",
    "gpt_neox_config_from_hf",
    "gpt_neox_from_hf",
    "t5_config_from_hf",
    "t5_from_hf",
    "bert_config_from_hf",
    "bert_from_hf",
]


def _getter(hf_config: Any):
    """Uniform accessor over a transformers config object or a plain dict."""
    if isinstance(hf_config, Mapping):
        return lambda k, d=None: hf_config.get(k, d)
    return lambda k, d=None: getattr(hf_config, k, d)


def _np(t) -> np.ndarray:
    """torch tensor / np array → np array (without importing torch)."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t)


def llama_config_from_hf(hf_config: Any, **overrides):
    """LlamaConfig from a transformers LlamaConfig (object or dict)."""
    from .llama import LlamaConfig

    get = _getter(hf_config)
    kwargs = dict(
        vocab_size=get("vocab_size"),
        d_model=get("hidden_size"),
        n_layers=get("num_hidden_layers"),
        n_heads=get("num_attention_heads"),
        n_kv_heads=get("num_key_value_heads") or get("num_attention_heads"),
        d_ff=get("intermediate_size"),
        max_seq=get("max_position_embeddings", 4096),
        rope_theta=float(get("rope_theta", 10000.0)),
        norm_eps=float(get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(get("tie_word_embeddings", False)),
    )
    scaling = get("rope_scaling")
    if scaling:
        sget = scaling.get if isinstance(scaling, Mapping) else (
            lambda k, d=None: getattr(scaling, k, d)
        )
        rope_type = sget("rope_type", sget("type", None))
        if rope_type in (None, "default"):
            pass  # explicit no-op entry (transformers' "default" rope) — plain RoPE
        elif rope_type != "llama3":
            raise ValueError(
                f"unsupported rope_scaling type {rope_type!r} (only 'llama3'/'default')"
            )
        else:
            kwargs.update(
                rope_scaling="llama3",
                rope_scaling_factor=float(sget("factor", 8.0)),
                rope_low_freq_factor=float(sget("low_freq_factor", 1.0)),
                rope_high_freq_factor=float(sget("high_freq_factor", 4.0)),
                rope_original_max=int(sget("original_max_position_embeddings", 8192)),
            )
    kwargs.update(overrides)
    return LlamaConfig(**kwargs)


def llama_from_hf(state_dict: Mapping[str, Any], cfg) -> dict:
    """transformers LlamaForCausalLM state dict → ``models.llama`` params pytree."""
    sd = {k: v for k, v in state_dict.items()}

    def take(name):
        return _np(sd[name])

    params: dict = {
        "embed": take("model.embed_tokens.weight"),
        "ln_f": take("model.norm.weight"),
        "layers": [],
    }
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        params["layers"].append({
            "ln_attn": take(p + "input_layernorm.weight"),
            "wq": take(p + "self_attn.q_proj.weight").T,
            "wk": take(p + "self_attn.k_proj.weight").T,
            "wv": take(p + "self_attn.v_proj.weight").T,
            "wo": take(p + "self_attn.o_proj.weight").T,
            "ln_mlp": take(p + "post_attention_layernorm.weight"),
            "w_gate": take(p + "mlp.gate_proj.weight").T,
            "w_up": take(p + "mlp.up_proj.weight").T,
            "w_down": take(p + "mlp.down_proj.weight").T,
        })
    if not cfg.tie_embeddings:
        head = sd.get("lm_head.weight")
        params["lm_head"] = (
            _np(head).T if head is not None else params["embed"].T.copy()
        )
    if cfg.scan_layers:
        params["layers"] = _stack_layers(params["layers"])
    return _to_jnp(params)


def mistral_config_from_hf(hf_config: Any, **overrides):
    """LlamaConfig from a transformers MistralConfig — Mistral is the llama architecture
    with sliding-window attention on EVERY layer (``window_every=1``); weights convert
    via :func:`mistral_from_hf` (same tensor layout as llama)."""
    get = _getter(hf_config)
    window = int(get("sliding_window") or 0)
    return llama_config_from_hf(
        hf_config,
        **{
            "sliding_window": window,
            "window_every": 1,
            # Mistral-Nemo sets an explicit head_dim != d_model // n_heads.
            "head_dim_override": get("head_dim"),
            **overrides,
        },
    )


mistral_from_hf = llama_from_hf  # identical state-dict layout


def qwen2_config_from_hf(hf_config: Any, **overrides):
    """LlamaConfig (qkv_bias set) from a transformers Qwen2Config — Qwen2 is the llama
    architecture plus biases on the q/k/v projections."""
    cfg = llama_config_from_hf(hf_config, qkv_bias=True, **overrides)
    return cfg


def qwen2_from_hf(state_dict: Mapping[str, Any], cfg) -> dict:
    """transformers Qwen2ForCausalLM state dict → ``models.llama`` params pytree
    (llama layout + per-layer bq/bk/bv)."""
    params = llama_from_hf(state_dict, cfg)
    layers = params["layers"]
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}.self_attn."
        bias = {
            "bq": _np(state_dict[p + "q_proj.bias"]),
            "bk": _np(state_dict[p + "k_proj.bias"]),
            "bv": _np(state_dict[p + "v_proj.bias"]),
        }
        if cfg.scan_layers:
            raise NotImplementedError("convert with scan_layers=False, then restack")
        layers[i].update(_to_jnp(bias))
    return params


def gemma2_config_from_hf(hf_config: Any, **overrides):
    """LlamaConfig (Gemma-2 knobs set) from a transformers Gemma2Config (object or dict).

    Gemma-2 is the llama family plus: zero-centered (1+w) RMSNorms, post-sublayer norms,
    GeGLU, sqrt(d) embedding scaling, query_pre_attn_scalar softmax scale, attention and
    final logit soft-capping, head_dim != d/H, and alternating banded/full layers (HF
    ``Gemma2DecoderLayer.is_sliding = not layer_idx % 2`` == ``window_every=2``).
    """
    from .llama import LlamaConfig

    get = _getter(hf_config)
    kwargs = dict(
        vocab_size=get("vocab_size"),
        d_model=get("hidden_size"),
        n_layers=get("num_hidden_layers"),
        n_heads=get("num_attention_heads"),
        n_kv_heads=get("num_key_value_heads") or get("num_attention_heads"),
        d_ff=get("intermediate_size"),
        head_dim_override=get("head_dim"),
        max_seq=get("max_position_embeddings", 8192),
        rope_theta=float(get("rope_theta", 10000.0)),
        norm_eps=float(get("rms_norm_eps", 1e-6)),
        tie_embeddings=bool(get("tie_word_embeddings", True)),
        mlp_act="gelu",
        post_norm=True,
        norm_plus_one=True,
        embed_scale=True,
        attn_scale=float(get("query_pre_attn_scalar")) ** -0.5,
        attn_softcap=float(get("attn_logit_softcapping") or 0.0),
        final_softcap=float(get("final_logit_softcapping") or 0.0),
        sliding_window=int(get("sliding_window") or 0),
        window_every=2,
    )
    kwargs.update(overrides)
    return LlamaConfig(**kwargs)


def gemma2_from_hf(state_dict: Mapping[str, Any], cfg) -> dict:
    """transformers Gemma2ForCausalLM state dict → ``models.llama`` params pytree.

    Same projection layout as llama (torch ``[out, in]`` → transposed); the four
    per-layer norms map input→ln_attn, post_attention→ln_attn_post,
    pre_feedforward→ln_mlp, post_feedforward→ln_mlp_post (all zero-centered — consumed
    with the (1+w) convention, ``cfg.norm_plus_one``).
    """
    sd = {k: v for k, v in state_dict.items()}

    def take(name):
        return _np(sd[name])

    params: dict = {
        "embed": take("model.embed_tokens.weight"),
        "ln_f": take("model.norm.weight"),
        "layers": [],
    }
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        params["layers"].append({
            "ln_attn": take(p + "input_layernorm.weight"),
            "wq": take(p + "self_attn.q_proj.weight").T,
            "wk": take(p + "self_attn.k_proj.weight").T,
            "wv": take(p + "self_attn.v_proj.weight").T,
            "wo": take(p + "self_attn.o_proj.weight").T,
            "ln_attn_post": take(p + "post_attention_layernorm.weight"),
            "ln_mlp": take(p + "pre_feedforward_layernorm.weight"),
            "ln_mlp_post": take(p + "post_feedforward_layernorm.weight"),
            "w_gate": take(p + "mlp.gate_proj.weight").T,
            "w_up": take(p + "mlp.up_proj.weight").T,
            "w_down": take(p + "mlp.down_proj.weight").T,
        })
    if not cfg.tie_embeddings:
        head = sd.get("lm_head.weight")
        params["lm_head"] = (
            _np(head).T if head is not None else params["embed"].T.copy()
        )
    if cfg.scan_layers:
        params["layers"] = _stack_layers(params["layers"])
    return _to_jnp(params)


def gpt2_config_from_hf(hf_config: Any, **overrides):
    """GPTConfig from a transformers GPT2Config (object or dict)."""
    from .gpt import GPTConfig

    get = _getter(hf_config)
    kwargs = dict(
        vocab_size=get("vocab_size"),
        d_model=get("n_embd"),
        n_layers=get("n_layer"),
        n_heads=get("n_head"),
        d_ff=get("n_inner") or 4 * get("n_embd"),
        max_seq=get("n_positions", 1024),
        pos="learned",
        norm_eps=float(get("layer_norm_epsilon", 1e-5)),
        tie_embeddings=True,
    )
    kwargs.update(overrides)
    return GPTConfig(**kwargs)


def gpt2_from_hf(state_dict: Mapping[str, Any], cfg) -> dict:
    """transformers GPT2LMHeadModel state dict → ``models.gpt`` params pytree.

    GPT-2's Conv1D stores ``[in, out]`` — no transpose needed, unlike torch Linear.
    """
    sd = {re.sub(r"^transformer\.", "", k): v for k, v in state_dict.items()}

    def take(name):
        return _np(sd[name])

    params: dict = {
        "wte": take("wte.weight"),
        "wpe": take("wpe.weight"),
        "ln_f": {"scale": take("ln_f.weight"), "bias": take("ln_f.bias")},
        "layers": [],
    }
    for i in range(cfg.n_layers):
        p = f"h.{i}."
        params["layers"].append({
            "ln_attn": {"scale": take(p + "ln_1.weight"), "bias": take(p + "ln_1.bias")},
            "wqkv": take(p + "attn.c_attn.weight"),
            "b_qkv": take(p + "attn.c_attn.bias"),
            "wo": take(p + "attn.c_proj.weight"),
            "b_o": take(p + "attn.c_proj.bias"),
            "ln_mlp": {"scale": take(p + "ln_2.weight"), "bias": take(p + "ln_2.bias")},
            "w_up": take(p + "mlp.c_fc.weight"),
            "b_up": take(p + "mlp.c_fc.bias"),
            "w_down": take(p + "mlp.c_proj.weight"),
            "b_down": take(p + "mlp.c_proj.bias"),
        })
    if not cfg.tie_embeddings:
        head = sd.get("lm_head.weight")
        params["lm_head"] = _np(head).T if head is not None else params["wte"].T.copy()
    if cfg.scan_layers:
        params["layers"] = _stack_layers(params["layers"])
    return _to_jnp(params)


def gptj_config_from_hf(hf_config: Any, **overrides):
    """GPTConfig from a transformers GPTJConfig: interleaved partial rotary (rotary_dim),
    parallel residual off a single LN, biased lm_head (the reference's GPT-J-6B baseline,
    ``/root/reference/benchmarks/big_model_inference/README.md:25-37``)."""
    from .gpt import GPTConfig

    get = _getter(hf_config)
    kwargs = dict(
        vocab_size=get("vocab_size"),
        d_model=get("n_embd"),
        n_layers=get("n_layer"),
        n_heads=get("n_head"),
        d_ff=get("n_inner") or 4 * get("n_embd"),
        max_seq=get("n_positions", 2048),
        pos="rotary",
        rotary_dim=get("rotary_dim") or None,
        rope_style="interleaved",
        parallel_residual=True,
        norm_eps=float(get("layer_norm_epsilon", 1e-5)),
        tie_embeddings=False,
        lm_head_bias=True,
    )
    kwargs.update(overrides)
    return GPTConfig(**kwargs)


def gptj_from_hf(state_dict: Mapping[str, Any], cfg) -> dict:
    """transformers GPTJForCausalLM state dict → ``models.gpt`` params pytree.

    GPT-J has a SINGLE pre-norm (``ln_1``) feeding both branches of the parallel
    residual; our layout carries two LN slots, so ``ln_1`` maps to both (identical
    math). torch Linear stores [out, in] → transposed; missing biases become zeros.
    """
    sd = {re.sub(r"^transformer\.", "", k): v for k, v in state_dict.items()}

    def take(name):
        return _np(sd[name])

    D = cfg.d_model
    params: dict = {
        "wte": take("wte.weight"),
        "ln_f": {"scale": take("ln_f.weight"), "bias": take("ln_f.bias")},
        "layers": [],
    }
    for i in range(cfg.n_layers):
        p = f"h.{i}."
        ln = {"scale": take(p + "ln_1.weight"), "bias": take(p + "ln_1.bias")}
        wqkv = np.concatenate(
            [take(p + f"attn.{n}_proj.weight").T for n in ("q", "k", "v")], axis=1
        )
        params["layers"].append({
            "ln_attn": dict(ln),
            "wqkv": wqkv,
            "b_qkv": np.zeros((3 * D,), np.float32),
            "wo": take(p + "attn.out_proj.weight").T,
            "b_o": np.zeros((D,), np.float32),
            "ln_mlp": dict(ln),  # same tensors: GPT-J's one LN feeds both branches
            "w_up": take(p + "mlp.fc_in.weight").T,
            "b_up": take(p + "mlp.fc_in.bias"),
            "w_down": take(p + "mlp.fc_out.weight").T,
            "b_down": take(p + "mlp.fc_out.bias"),
        })
    if not cfg.tie_embeddings:
        params["lm_head"] = take("lm_head.weight").T
        if cfg.lm_head_bias and "lm_head.bias" in sd:
            params["b_lm_head"] = take("lm_head.bias")
    if cfg.scan_layers:
        params["layers"] = _stack_layers(params["layers"])
    return _to_jnp(params)


def opt_config_from_hf(hf_config: Any, **overrides):
    """GPTConfig from a transformers OPTConfig — the reference's 30B inference
    baseline family (``/root/reference/benchmarks/big_model_inference/README.md:36``):
    pre-LN decoder, learned positions (offset baked out by the converter), separate
    biased q/k/v projections, ReLU MLP, tied head."""
    from .gpt import GPTConfig

    get = _getter(hf_config)
    if get("word_embed_proj_dim", get("hidden_size")) != get("hidden_size"):
        raise NotImplementedError(
            "OPT word_embed_proj (the 350m in/out projection) is not supported"
        )
    if not get("do_layer_norm_before", True):
        raise NotImplementedError("post-norm OPT (350m) is not supported")
    kwargs = dict(
        vocab_size=get("vocab_size"),
        d_model=get("hidden_size"),
        n_layers=get("num_hidden_layers"),
        n_heads=get("num_attention_heads"),
        d_ff=get("ffn_dim"),
        max_seq=get("max_position_embeddings", 2048),
        pos="learned",
        activation=get("activation_function", "relu"),
        tie_embeddings=True,
    )
    kwargs.update(overrides)
    return GPTConfig(**kwargs)


def opt_from_hf(state_dict: Mapping[str, Any], cfg) -> dict:
    """transformers OPTForCausalLM state dict → ``models.gpt`` params pytree.

    OPT's learned positional table carries a +2 row offset
    (``OPTLearnedPositionalEmbedding``: position i reads row i+2 for a pad-free
    sequence); the converter slices those two rows off so our 0-based ``positions``
    index the table directly. Separate q/k/v torch Linears concatenate role-major
    into the fused ``wqkv`` layout."""
    sd = {re.sub(r"^(model\.)?decoder\.", "", k): v for k, v in state_dict.items()}

    def take(name):
        return _np(sd[name])

    params: dict = {
        "wte": take("embed_tokens.weight"),
        "wpe": take("embed_positions.weight")[2:],
        "ln_f": {
            "scale": take("final_layer_norm.weight"),
            "bias": take("final_layer_norm.bias"),
        },
        "layers": [],
    }
    for i in range(cfg.n_layers):
        p = f"layers.{i}."
        wq = take(p + "self_attn.q_proj.weight").T
        wk = take(p + "self_attn.k_proj.weight").T
        wv = take(p + "self_attn.v_proj.weight").T
        params["layers"].append({
            "ln_attn": {
                "scale": take(p + "self_attn_layer_norm.weight"),
                "bias": take(p + "self_attn_layer_norm.bias"),
            },
            "wqkv": np.concatenate([wq, wk, wv], axis=1),
            "b_qkv": np.concatenate([
                take(p + "self_attn.q_proj.bias"),
                take(p + "self_attn.k_proj.bias"),
                take(p + "self_attn.v_proj.bias"),
            ]),
            "wo": take(p + "self_attn.out_proj.weight").T,
            "b_o": take(p + "self_attn.out_proj.bias"),
            "ln_mlp": {
                "scale": take(p + "final_layer_norm.weight"),
                "bias": take(p + "final_layer_norm.bias"),
            },
            "w_up": take(p + "fc1.weight").T,
            "b_up": take(p + "fc1.bias"),
            "w_down": take(p + "fc2.weight").T,
            "b_down": take(p + "fc2.bias"),
        })
    if cfg.scan_layers:
        params["layers"] = _stack_layers(params["layers"])
    return _to_jnp(params)


def _map_gelu(hidden_act: str) -> str:
    """HF activation name → GPTConfig.activation; raise on anything unmapped rather than
    silently computing wrong logits with a different activation."""
    table = {
        "gelu": "gelu",                  # exact erf gelu (NeoX default)
        "gelu_new": "gelu_new",          # tanh approximation (GPT-2/GPT-J)
        "gelu_pytorch_tanh": "gelu_new",
        "gelu_fast": "gelu_new",         # same tanh form, different constant folding
    }
    if hidden_act not in table:
        raise NotImplementedError(
            f"hidden_act={hidden_act!r}: models.gpt implements exact and tanh-approx GELU; "
            "converting would silently change the activation."
        )
    return table[hidden_act]


def gpt_neox_config_from_hf(hf_config: Any, **overrides):
    """GPTConfig from a transformers GPTNeoXConfig: rotate-half partial rotary
    (rotary_pct), two-LN parallel residual, exact GELU (the reference's GPT-NeoX-20B
    baseline)."""
    from .gpt import GPTConfig

    get = _getter(hf_config)
    hd = get("hidden_size") // get("num_attention_heads")
    if not bool(get("use_parallel_residual", True)):
        raise NotImplementedError(
            "use_parallel_residual=False NeoX variants are not mapped (the 20B baseline "
            "and all Pythia models use the parallel form)."
        )
    kwargs = dict(
        vocab_size=get("vocab_size"),
        d_model=get("hidden_size"),
        n_layers=get("num_hidden_layers"),
        n_heads=get("num_attention_heads"),
        d_ff=get("intermediate_size"),
        max_seq=get("max_position_embeddings", 2048),
        pos="rotary",
        rotary_dim=int(hd * float(get("rotary_pct", 1.0))) or None,
        rope_style="half",
        rope_theta=float(get("rotary_emb_base", 10000.0)),
        parallel_residual=True,
        activation=_map_gelu(str(get("hidden_act", "gelu"))),
        norm_eps=float(get("layer_norm_eps", 1e-5)),
        tie_embeddings=bool(get("tie_word_embeddings", False)),
    )
    kwargs.update(overrides)
    return GPTConfig(**kwargs)


def gpt_neox_from_hf(state_dict: Mapping[str, Any], cfg) -> dict:
    """transformers GPTNeoXForCausalLM state dict → ``models.gpt`` params pytree.

    NeoX's fused ``query_key_value`` is head-interleaved on the output axis
    ([head, (q|k|v), head_dim]); our fused layout is role-major ([q_allheads |
    k_allheads | v_allheads]) — the converter permutes accordingly.
    """
    sd = {re.sub(r"^gpt_neox\.", "", k): v for k, v in state_dict.items()}

    def take(name):
        return _np(sd[name])

    D, H = cfg.d_model, cfg.n_heads
    hd = D // H

    def _dehead(w_qkv_out_axis):
        # [..., 3D] with per-head (q,k,v) blocks → role-major [..., 3D]
        x = w_qkv_out_axis.reshape(*w_qkv_out_axis.shape[:-1], H, 3, hd)
        x = np.moveaxis(x, -2, -3)  # [..., 3, H, hd]
        return x.reshape(*w_qkv_out_axis.shape[:-1], 3 * D)

    params: dict = {
        "wte": take("embed_in.weight"),
        "ln_f": {
            "scale": take("final_layer_norm.weight"),
            "bias": take("final_layer_norm.bias"),
        },
        "layers": [],
    }
    for i in range(cfg.n_layers):
        p = f"layers.{i}."
        params["layers"].append({
            "ln_attn": {
                "scale": take(p + "input_layernorm.weight"),
                "bias": take(p + "input_layernorm.bias"),
            },
            "wqkv": _dehead(take(p + "attention.query_key_value.weight").T),
            "b_qkv": _dehead(take(p + "attention.query_key_value.bias")),
            "wo": take(p + "attention.dense.weight").T,
            "b_o": take(p + "attention.dense.bias"),
            "ln_mlp": {
                "scale": take(p + "post_attention_layernorm.weight"),
                "bias": take(p + "post_attention_layernorm.bias"),
            },
            "w_up": take(p + "mlp.dense_h_to_4h.weight").T,
            "b_up": take(p + "mlp.dense_h_to_4h.bias"),
            "w_down": take(p + "mlp.dense_4h_to_h.weight").T,
            "b_down": take(p + "mlp.dense_4h_to_h.bias"),
        })
    if not cfg.tie_embeddings:
        params["lm_head"] = take("embed_out.weight").T
    if cfg.scan_layers:
        params["layers"] = _stack_layers(params["layers"])
    return _to_jnp(params)


def _stack_layers(layers):
    import jax

    return jax.tree_util.tree_map(lambda *ls: np.stack(ls), *layers)


def _to_jnp(tree):
    import jax

    return jax.tree_util.tree_map(lambda x: jnp.asarray(x), tree)


def t5_config_from_hf(hf_config: Any, **overrides):
    """T5Config from a transformers T5Config (object or dict)."""
    from .t5 import T5Config

    get = _getter(hf_config)
    proj = str(get("feed_forward_proj", "relu"))
    if proj not in ("relu", "gated-gelu"):
        raise NotImplementedError(
            f"feed_forward_proj={proj!r}: models.t5 implements 'relu' and 'gated-gelu' "
            "(the T5 / v1.1-T0 lineages); converting would silently change the activation."
        )
    kwargs = dict(
        vocab_size=get("vocab_size"),
        d_model=get("d_model"),
        d_kv=get("d_kv"),
        d_ff=get("d_ff"),
        n_layers=get("num_layers"),
        n_decoder_layers=get("num_decoder_layers") or get("num_layers"),
        n_heads=get("num_heads"),
        rel_buckets=get("relative_attention_num_buckets", 32),
        rel_max_distance=get("relative_attention_max_distance", 128),
        gated_ff="gated" in str(proj),
        norm_eps=float(get("layer_norm_epsilon", 1e-6)),
        tie_embeddings=bool(get("tie_word_embeddings", True)),
        decoder_start_token_id=get("decoder_start_token_id", 0) or 0,
    )
    kwargs.update(overrides)
    return T5Config(**kwargs)


def t5_from_hf(state_dict: Mapping[str, Any], cfg) -> dict:
    """transformers T5ForConditionalGeneration state dict → ``models.t5`` params pytree."""
    sd = dict(state_dict)

    def take(name):
        return _np(sd[name])

    def attn(prefix, with_rel):
        p = {
            "q": take(prefix + "q.weight").T,
            "k": take(prefix + "k.weight").T,
            "v": take(prefix + "v.weight").T,
            "o": take(prefix + "o.weight").T,
        }
        if with_rel:
            p["rel_bias"] = take(prefix + "relative_attention_bias.weight")
        return p

    def ff(prefix):
        if cfg.gated_ff:
            return {
                "wi_0": take(prefix + "wi_0.weight").T,
                "wi_1": take(prefix + "wi_1.weight").T,
                "wo": take(prefix + "wo.weight").T,
            }
        return {"wi": take(prefix + "wi.weight").T, "wo": take(prefix + "wo.weight").T}

    params: dict = {
        "shared": take("shared.weight"),
        "encoder": {"blocks": [], "ln_f": take("encoder.final_layer_norm.weight")},
        "decoder": {"blocks": [], "ln_f": take("decoder.final_layer_norm.weight")},
    }
    for i in range(cfg.n_layers):
        b = f"encoder.block.{i}."
        params["encoder"]["blocks"].append({
            "ln_attn": take(b + "layer.0.layer_norm.weight"),
            "attn": attn(b + "layer.0.SelfAttention.", i == 0),
            "ln_ff": take(b + "layer.1.layer_norm.weight"),
            "ff": ff(b + "layer.1.DenseReluDense."),
        })
    for i in range(cfg.dec_layers):
        b = f"decoder.block.{i}."
        params["decoder"]["blocks"].append({
            "ln_attn": take(b + "layer.0.layer_norm.weight"),
            "attn": attn(b + "layer.0.SelfAttention.", i == 0),
            "ln_cross": take(b + "layer.1.layer_norm.weight"),
            "cross": attn(b + "layer.1.EncDecAttention.", False),
            "ln_ff": take(b + "layer.2.layer_norm.weight"),
            "ff": ff(b + "layer.2.DenseReluDense."),
        })
    if not cfg.tie_embeddings:
        head = sd.get("lm_head.weight")
        params["lm_head"] = _np(head).T if head is not None else params["shared"].T.copy()
    return _to_jnp(params)


def bert_config_from_hf(hf_config: Any, **overrides):
    """BertConfig from a transformers BertConfig (object or dict) — the reference's
    flagship ``nlp_example.py`` model family (bert-base on GLUE/MRPC)."""
    from .bert import BertConfig

    get = _getter(hf_config)
    act = str(get("hidden_act", "gelu"))
    if act != "gelu":
        # models.bert._block hardcodes exact GELU; converting a relu/gelu_new
        # checkpoint would silently compute wrong logits (same guard as _map_gelu).
        raise NotImplementedError(
            f"hidden_act={act!r}: models.bert implements exact GELU only; converting "
            "would silently change the activation."
        )
    kwargs = dict(
        vocab_size=get("vocab_size"),
        d_model=get("hidden_size"),
        n_layers=get("num_hidden_layers"),
        n_heads=get("num_attention_heads"),
        d_ff=get("intermediate_size"),
        max_seq=get("max_position_embeddings", 512),
        type_vocab_size=get("type_vocab_size", 2),
        num_labels=get("num_labels", 2),
        layer_norm_eps=float(get("layer_norm_eps", 1e-12)),
    )
    kwargs.update(overrides)
    return BertConfig(**kwargs)


def bert_from_hf(state_dict: Mapping[str, Any], cfg) -> dict:
    """transformers ``BertForSequenceClassification`` state dict → ``models.bert``
    params pytree (torch Linear stores [out, in] — transposed to [in, out])."""
    sd = {re.sub(r"^bert\.", "", k): v for k, v in state_dict.items()}

    def take(name):
        return _np(sd[name])

    def lin(prefix):
        return take(prefix + ".weight").T, take(prefix + ".bias")

    def ln(prefix):
        return {"gamma": take(prefix + ".weight"), "beta": take(prefix + ".bias")}

    params: dict = {
        "embed": {
            "tokens": take("embeddings.word_embeddings.weight"),
            "positions": take("embeddings.position_embeddings.weight"),
            "types": take("embeddings.token_type_embeddings.weight"),
            "ln": ln("embeddings.LayerNorm"),
        },
        "layers": [],
    }
    for i in range(cfg.n_layers):
        p = f"encoder.layer.{i}."
        wq, bq = lin(p + "attention.self.query")
        wk, bk = lin(p + "attention.self.key")
        wv, bv = lin(p + "attention.self.value")
        wo, bo = lin(p + "attention.output.dense")
        w_in, b_in = lin(p + "intermediate.dense")
        w_out, b_out = lin(p + "output.dense")
        params["layers"].append({
            "wq": wq, "bq": bq, "wk": wk, "bk": bk, "wv": wv, "bv": bv,
            "wo": wo, "bo": bo,
            "ln1": ln(p + "attention.output.LayerNorm"),
            "w_in": w_in, "b_in": b_in, "w_out": w_out, "b_out": b_out,
            "ln2": ln(p + "output.LayerNorm"),
        })
    pw, pb = lin("pooler.dense")
    params["pooler"] = {"w": pw, "b": pb}
    cw, cb = lin("classifier")  # classifier sits OUTSIDE the bert.* prefix in HF
    params["classifier"] = {"w": cw, "b": cb}
    return _to_jnp(params)
