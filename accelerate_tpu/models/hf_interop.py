"""HuggingFace checkpoint interop — load reference-ecosystem weights into the mesh runtime.

A user switching from the reference keeps their checkpoints: ``transformers`` state dicts
(LlamaForCausalLM, GPT2LMHeadModel) convert to this framework's param pytrees and back.
Torch linear layers store ``[out, in]`` (transposed here to our ``x @ w`` convention);
GPT-2's ``Conv1D`` already stores ``[in, out]`` and passes through.

Reference analog: the reference leans on ``transformers`` directly (its models ARE torch
modules); here the conversion is an explicit, tested mapping. Combine with
``utils/serialization.load_flat_safetensors`` / ``utils/modeling.load_checkpoint_in_model``
to stream sharded checkpoint files.
"""

from __future__ import annotations

import re
from typing import Any, Mapping

import numpy as np
import jax.numpy as jnp

__all__ = [
    "llama_config_from_hf",
    "llama_from_hf",
    "gpt2_config_from_hf",
    "gpt2_from_hf",
]


def _np(t) -> np.ndarray:
    """torch tensor / np array → np array (without importing torch)."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t)


def llama_config_from_hf(hf_config: Any, **overrides):
    """LlamaConfig from a transformers LlamaConfig (object or dict)."""
    from .llama import LlamaConfig

    get = (lambda k, d=None: hf_config.get(k, d)) if isinstance(hf_config, Mapping) else (
        lambda k, d=None: getattr(hf_config, k, d)
    )
    kwargs = dict(
        vocab_size=get("vocab_size"),
        d_model=get("hidden_size"),
        n_layers=get("num_hidden_layers"),
        n_heads=get("num_attention_heads"),
        n_kv_heads=get("num_key_value_heads") or get("num_attention_heads"),
        d_ff=get("intermediate_size"),
        max_seq=get("max_position_embeddings", 4096),
        rope_theta=float(get("rope_theta", 10000.0)),
        norm_eps=float(get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(get("tie_word_embeddings", False)),
    )
    kwargs.update(overrides)
    return LlamaConfig(**kwargs)


def llama_from_hf(state_dict: Mapping[str, Any], cfg) -> dict:
    """transformers LlamaForCausalLM state dict → ``models.llama`` params pytree."""
    sd = {k: v for k, v in state_dict.items()}

    def take(name):
        return _np(sd[name])

    params: dict = {
        "embed": take("model.embed_tokens.weight"),
        "ln_f": take("model.norm.weight"),
        "layers": [],
    }
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        params["layers"].append({
            "ln_attn": take(p + "input_layernorm.weight"),
            "wq": take(p + "self_attn.q_proj.weight").T,
            "wk": take(p + "self_attn.k_proj.weight").T,
            "wv": take(p + "self_attn.v_proj.weight").T,
            "wo": take(p + "self_attn.o_proj.weight").T,
            "ln_mlp": take(p + "post_attention_layernorm.weight"),
            "w_gate": take(p + "mlp.gate_proj.weight").T,
            "w_up": take(p + "mlp.up_proj.weight").T,
            "w_down": take(p + "mlp.down_proj.weight").T,
        })
    if not cfg.tie_embeddings:
        head = sd.get("lm_head.weight")
        params["lm_head"] = (
            _np(head).T if head is not None else params["embed"].T.copy()
        )
    if cfg.scan_layers:
        params["layers"] = _stack_layers(params["layers"])
    return _to_jnp(params)


def gpt2_config_from_hf(hf_config: Any, **overrides):
    """GPTConfig from a transformers GPT2Config (object or dict)."""
    from .gpt import GPTConfig

    get = (lambda k, d=None: hf_config.get(k, d)) if isinstance(hf_config, Mapping) else (
        lambda k, d=None: getattr(hf_config, k, d)
    )
    kwargs = dict(
        vocab_size=get("vocab_size"),
        d_model=get("n_embd"),
        n_layers=get("n_layer"),
        n_heads=get("n_head"),
        d_ff=get("n_inner") or 4 * get("n_embd"),
        max_seq=get("n_positions", 1024),
        pos="learned",
        norm_eps=float(get("layer_norm_epsilon", 1e-5)),
        tie_embeddings=True,
    )
    kwargs.update(overrides)
    return GPTConfig(**kwargs)


def gpt2_from_hf(state_dict: Mapping[str, Any], cfg) -> dict:
    """transformers GPT2LMHeadModel state dict → ``models.gpt`` params pytree.

    GPT-2's Conv1D stores ``[in, out]`` — no transpose needed, unlike torch Linear.
    """
    sd = {re.sub(r"^transformer\.", "", k): v for k, v in state_dict.items()}

    def take(name):
        return _np(sd[name])

    params: dict = {
        "wte": take("wte.weight"),
        "wpe": take("wpe.weight"),
        "ln_f": {"scale": take("ln_f.weight"), "bias": take("ln_f.bias")},
        "layers": [],
    }
    for i in range(cfg.n_layers):
        p = f"h.{i}."
        params["layers"].append({
            "ln_attn": {"scale": take(p + "ln_1.weight"), "bias": take(p + "ln_1.bias")},
            "wqkv": take(p + "attn.c_attn.weight"),
            "b_qkv": take(p + "attn.c_attn.bias"),
            "wo": take(p + "attn.c_proj.weight"),
            "b_o": take(p + "attn.c_proj.bias"),
            "ln_mlp": {"scale": take(p + "ln_2.weight"), "bias": take(p + "ln_2.bias")},
            "w_up": take(p + "mlp.c_fc.weight"),
            "b_up": take(p + "mlp.c_fc.bias"),
            "w_down": take(p + "mlp.c_proj.weight"),
            "b_down": take(p + "mlp.c_proj.bias"),
        })
    if not cfg.tie_embeddings:
        head = sd.get("lm_head.weight")
        params["lm_head"] = _np(head).T if head is not None else params["wte"].T.copy()
    if cfg.scan_layers:
        params["layers"] = _stack_layers(params["layers"])
    return _to_jnp(params)


def _stack_layers(layers):
    import jax

    return jax.tree_util.tree_map(lambda *ls: np.stack(ls), *layers)


def _to_jnp(tree):
    import jax

    return jax.tree_util.tree_map(lambda x: jnp.asarray(x), tree)
