"""Shared model-family machinery: remat policy resolution + KV-cache plane helpers.

Also home to the cross-family fused-CE dispatch (``fused_ce_allowed`` /
``fused_ce_single_shard``) used by the ``loss_impl="fused"`` branches of llama/gpt/t5.

One implementation of the remat knobs every family config exposes (``remat``,
``remat_policy``, ``remat_prevent_cse``), so llama/gpt/t5 cannot drift: the reference
gets the analogous single point from torch's ``checkpoint_wrapper`` applied in
``accelerator.py:1594-1608``; here the policy maps onto ``jax.checkpoint`` policies.

The KV helpers implement the optional int8 cache shared by the decoder families: caches
are plane dicts (``k``/``v`` [B,C,heads,hd], plus ``k_scale``/``v_scale`` [B,C,heads,1]
when quantized); ``write_kv`` quantizes at the write slot, ``read_kv`` dequantizes into
the attention einsum (XLA fuses the convert+scale, so a full-precision copy never
materializes in HBM).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = [
    "remat_wrap", "kv_planes", "write_kv", "read_kv", "quant_kv",
    "fused_ce_allowed", "fused_ce_single_shard",
]


def remat_wrap(
    fn: Callable,
    *,
    remat: bool,
    policy: str = "full",
    prevent_cse: Optional[bool] = None,
    scan_layers: bool = False,
    static_argnums: Sequence[int] = (),
) -> Callable:
    """``fn`` under the config's activation-checkpointing policy (validated).

    ``policy``: "full" recomputes everything (min memory); "dots" saves matmul outputs and
    recomputes only elementwise ops; "offload" parks the saved dots in pinned host memory.
    ``prevent_cse=None`` resolves automatically: False under ``scan_layers`` (the scan
    boundary already isolates the block, and checkpoint's anti-CSE barriers only pessimize
    XLA's scheduling inside it), True for an unrolled python-loop stack where CSE could
    silently defeat rematerialization.
    """
    if not remat:
        return fn
    if policy == "full":
        jax_policy = None
    elif policy == "dots":
        jax_policy = jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
    elif policy == "offload":
        jax_policy = jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host"
        )
    else:
        raise ValueError(f"remat_policy={policy!r}: expected 'full', 'dots' or 'offload'")
    if prevent_cse is None:
        prevent_cse = not scan_layers
    return jax.checkpoint(
        fn, static_argnums=tuple(static_argnums), policy=jax_policy, prevent_cse=prevent_cse
    )


# ------------------------------------------------------------------------ KV cache planes
def kv_planes(batch: int, max_len: int, heads: int, head_dim: int, dtype, quantized: bool):
    """One layer's empty cache planes: {k, v} (+ {k_scale, v_scale} when int8)."""
    shape = (batch, max_len, heads, head_dim)
    if quantized:
        scale = (batch, max_len, heads, 1)
        return {
            "k": jnp.zeros(shape, jnp.int8), "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(scale, jnp.float32),
            "v_scale": jnp.zeros(scale, jnp.float32),
        }
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def quant_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 quantization per (batch, token, head): x [B,T,K,hd] →
    (int8 values, fp32 scales [B,T,K,1]). Scale floor keeps all-zero rows exact."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / 127.0, 1e-8)
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def write_kv(kv: dict, name: str, val: jax.Array, index) -> dict:
    """Write ``val`` [B,T,...] into cache plane ``name`` at ``index`` (scalar slot for all
    rows, or per-row vector with T == 1), quantizing when the cache is int8."""
    out = {}
    if f"{name}_scale" in kv:
        q, scale = quant_kv(val)
        planes = ((name, q), (f"{name}_scale", scale))
    else:
        planes = ((name, val.astype(kv[name].dtype)),)
    for key, plane in planes:
        if jnp.ndim(index) == 0:
            out[key] = jax.lax.dynamic_update_slice(
                kv[key], plane.astype(kv[key].dtype), (0, index, 0, 0)
            )
        else:
            rows = jnp.arange(plane.shape[0])
            out[key] = kv[key].at[rows, index].set(plane[:, 0].astype(kv[key].dtype))
    return out


def read_kv(new_kv: dict, name: str, dtype) -> jax.Array:
    """Cache plane as compute dtype; int8 planes dequantize (the convert+scale fuses into
    the attention einsum, so the full-precision cache never materializes in HBM)."""
    if f"{name}_scale" in new_kv:
        return new_kv[name].astype(dtype) * new_kv[f"{name}_scale"].astype(dtype)
    return new_kv[name]


def fused_ce_allowed() -> bool:
    """True when the single-shard fused-CE kernel may run: one device, or interpret
    mode (CPU tests — lowers to partitionable XLA). On a real multi-device mesh the
    pallas_call would force GSPMD to gather the batch-sharded activations."""
    from ..ops._common import interpret_default

    return jax.device_count() == 1 or interpret_default()


def fused_ce_single_shard(x, head, targets, mask, softcap: float = 0.0):
    """Masked-mean fused cross-entropy over [B, S, D] hidden states, or None.

    Shared dispatch for the model families' ``loss_impl="fused"`` branches: returns None
    when :func:`fused_ce_allowed` says the kernel must not run. ``mask`` [B, S] float;
    ``head`` [D, V] already in compute dtype.
    """
    if not fused_ce_allowed():
        return None
    from ..ops.fused_xent import fused_cross_entropy

    B, S, D = x.shape
    nll = fused_cross_entropy(
        x.reshape(B * S, D), head, targets.reshape(B * S), softcap=softcap
    )
    mask1d = mask.reshape(B * S)
    return (nll * mask1d).sum() / jnp.maximum(mask1d.sum(), 1.0)
