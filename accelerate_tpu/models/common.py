"""Shared model-family machinery: activation-checkpointing (remat) policy resolution.

One implementation of the remat knobs every family config exposes (``remat``,
``remat_policy``, ``remat_prevent_cse``), so llama/gpt/t5 cannot drift: the reference
gets the analogous single point from torch's ``checkpoint_wrapper`` applied in
``accelerator.py:1594-1608``; here the policy maps onto ``jax.checkpoint`` policies.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax

__all__ = ["remat_wrap"]


def remat_wrap(
    fn: Callable,
    *,
    remat: bool,
    policy: str = "full",
    prevent_cse: Optional[bool] = None,
    scan_layers: bool = False,
    static_argnums: Sequence[int] = (),
) -> Callable:
    """``fn`` under the config's activation-checkpointing policy (validated).

    ``policy``: "full" recomputes everything (min memory); "dots" saves matmul outputs and
    recomputes only elementwise ops; "offload" parks the saved dots in pinned host memory.
    ``prevent_cse=None`` resolves automatically: False under ``scan_layers`` (the scan
    boundary already isolates the block, and checkpoint's anti-CSE barriers only pessimize
    XLA's scheduling inside it), True for an unrolled python-loop stack where CSE could
    silently defeat rematerialization.
    """
    if not remat:
        return fn
    if policy == "full":
        jax_policy = None
    elif policy == "dots":
        jax_policy = jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
    elif policy == "offload":
        jax_policy = jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host"
        )
    else:
        raise ValueError(f"remat_policy={policy!r}: expected 'full', 'dots' or 'offload'")
    if prevent_cse is None:
        prevent_cse = not scan_layers
    return jax.checkpoint(
        fn, static_argnums=tuple(static_argnums), policy=jax_policy, prevent_cse=prevent_cse
    )
