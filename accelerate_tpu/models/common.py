"""Shared model-family machinery: remat policy resolution + KV-cache plane helpers.

Also home to the cross-family fused-CE dispatch (``fused_ce_allowed`` /
``fused_ce_single_shard``) used by the ``loss_impl="fused"`` branches of llama/gpt/t5.

One implementation of the remat knobs every family config exposes (``remat``,
``remat_policy``, ``remat_prevent_cse``), so llama/gpt/t5 cannot drift: the reference
gets the analogous single point from torch's ``checkpoint_wrapper`` applied in
``accelerator.py:1594-1608``; here the policy maps onto ``jax.checkpoint`` policies.

The KV helpers implement the optional int8 cache shared by the decoder families: caches
are plane dicts (``k``/``v`` [B,C,heads,hd], plus ``k_scale``/``v_scale`` [B,C,heads,1]
when quantized); ``write_kv`` quantizes at the write slot, ``read_kv`` dequantizes into
the attention einsum (XLA fuses the convert+scale, so a full-precision copy never
materializes in HBM).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from ..utils.jax_compat import current_abstract_mesh, shard_map as _shard_map

__all__ = [
    "remat_wrap", "kv_planes", "write_kv", "read_kv", "quant_kv",
    "paged_kv_planes", "write_kv_paged", "read_kv_paged", "paged_write_coords",
    "paged_attention_dispatch", "multi_step_decode",
    "fused_ce_allowed", "fused_ce_single_shard",
    "resolve_loss_chunk", "chunked_ce", "ce_sum", "ce_sum_dispatch",
    "sp_active", "sp_manual", "resolve_sp_pipeline", "attention_dispatch",
]


def remat_wrap(
    fn: Callable,
    *,
    remat: bool,
    policy: str = "full",
    prevent_cse: Optional[bool] = None,
    scan_layers: bool = False,
    static_argnums: Sequence[int] = (),
) -> Callable:
    """``fn`` under the config's activation-checkpointing policy (validated).

    ``policy``: "full" recomputes everything (min memory); "dots" saves matmul outputs and
    recomputes only elementwise ops; "offload" parks the saved dots in pinned host memory.
    ``prevent_cse=None`` resolves automatically: False under ``scan_layers`` (the scan
    boundary already isolates the block, and checkpoint's anti-CSE barriers only pessimize
    XLA's scheduling inside it), True for an unrolled python-loop stack where CSE could
    silently defeat rematerialization.
    """
    if not remat:
        return fn
    if policy == "full":
        jax_policy = None
    elif policy == "dots":
        jax_policy = jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
    elif policy == "offload":
        jax_policy = jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host"
        )
    else:
        raise ValueError(f"remat_policy={policy!r}: expected 'full', 'dots' or 'offload'")
    if prevent_cse is None:
        prevent_cse = not scan_layers
    return jax.checkpoint(
        fn, static_argnums=tuple(static_argnums), policy=jax_policy, prevent_cse=prevent_cse
    )


# ------------------------------------------------------------------------ KV cache planes
def kv_planes(batch: int, max_len: int, heads: int, head_dim: int, dtype, quantized: bool):
    """One layer's empty cache planes: {k, v} (+ {k_scale, v_scale} when int8)."""
    shape = (batch, max_len, heads, head_dim)
    if quantized:
        scale = (batch, max_len, heads, 1)
        return {
            "k": jnp.zeros(shape, jnp.int8), "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(scale, jnp.float32),
            "v_scale": jnp.zeros(scale, jnp.float32),
        }
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def quant_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 quantization per (batch, token, head): x [B,T,K,hd] →
    (int8 values, fp32 scales [B,T,K,1]). Scale floor keeps all-zero rows exact."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / 127.0, 1e-8)
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def write_kv(kv: dict, name: str, val: jax.Array, index) -> dict:
    """Write ``val`` [B,T,...] into cache plane ``name`` at ``index`` (scalar slot for all
    rows, or per-row vector: row b's tokens land at slots ``index[b] .. index[b]+T-1`` —
    the continuous-batching decode (T == 1) and the batched speculative verify (T == k)
    share this path), quantizing when the cache is int8. Per-row writes past the cache
    end are dropped (jax scatter OOB semantics); the serving engine's budget capping
    guarantees no emitted token ever depends on a dropped slot."""
    out = {}
    if f"{name}_scale" in kv:
        q, scale = quant_kv(val)
        planes = ((name, q), (f"{name}_scale", scale))
    else:
        planes = ((name, val.astype(kv[name].dtype)),)
    for key, plane in planes:
        if jnp.ndim(index) == 0:
            out[key] = jax.lax.dynamic_update_slice(
                kv[key], plane.astype(kv[key].dtype), (0, index, 0, 0)
            )
        else:
            rows = jnp.arange(plane.shape[0])
            T = plane.shape[1]
            if T == 1:
                out[key] = kv[key].at[rows, index].set(plane[:, 0].astype(kv[key].dtype))
            else:
                slots = index[:, None] + jnp.arange(T, dtype=index.dtype)[None, :]
                out[key] = kv[key].at[rows[:, None], slots].set(
                    plane.astype(kv[key].dtype)
                )
    return out


def read_kv(new_kv: dict, name: str, dtype) -> jax.Array:
    """Cache plane as compute dtype; int8 planes dequantize (the convert+scale fuses into
    the attention einsum, so the full-precision cache never materializes in HBM)."""
    if f"{name}_scale" in new_kv:
        return new_kv[name].astype(dtype) * new_kv[f"{name}_scale"].astype(dtype)
    return new_kv[name]


# ---------------------------------------------------------------- paged KV cache planes
def paged_kv_planes(num_pages: int, page_size: int, heads: int, head_dim: int, dtype,
                    quantized: bool):
    """One layer's empty paged pool: {k, v} [P, page_size, K, hd] (+ fp32 scales
    [P, page_size, K, 1] when int8) — the shared-pool counterpart of
    :func:`kv_planes`, indexed by (physical page, slot) instead of (lane, position).
    ``paged_kv.BlockManager`` owns which lane references which page."""
    shape = (num_pages, page_size, heads, head_dim)
    if quantized:
        scale = (num_pages, page_size, heads, 1)
        return {
            "k": jnp.zeros(shape, jnp.int8), "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(scale, jnp.float32),
            "v_scale": jnp.zeros(scale, jnp.float32),
        }
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def write_kv_paged(kv: dict, name: str, val: jax.Array, pages: jax.Array,
                   offs: jax.Array) -> dict:
    """Write ``val`` [B,T,K,hd] into pool plane ``name`` at physical slots
    ``(pages[b,t], offs[b,t])``, quantizing when the pool is int8 (same per-slot
    quantization as the dense :func:`write_kv`, so paged and dense caches hold
    bit-identical values). Sentinel page ids (== num_pages) are out of bounds and
    the scatter DROPS them — stale/unallocated block-table entries and past-budget
    draft writes vanish instead of corrupting another lane's pages."""
    out = {}
    if f"{name}_scale" in kv:
        q, scale = quant_kv(val)
        planes = ((name, q), (f"{name}_scale", scale))
    else:
        planes = ((name, val.astype(kv[name].dtype)),)
    for key, plane in planes:
        out[key] = kv[key].at[pages, offs].set(plane.astype(kv[key].dtype))
    return out


def read_kv_paged(new_kv: dict, name: str, tables: jax.Array, length: int,
                  dtype) -> jax.Array:
    """Dense ``[B, length, K, hd]`` compute-dtype view of pool plane ``name``
    gathered through block tables [B, MP] — the jnp fallback read the CPU tier-1
    suite exercises (sentinel entries clamp to a real page; the caller's
    valid/causal mask hides those slots). int8 pools dequantize like
    :func:`read_kv`. ONE implementation shared with the kernel's test oracle
    (``ops.paged_attention.gather_pages``) — the CPU fallback and the reference
    the kernel is pinned against can never diverge."""
    from ..ops.paged_attention import gather_pages

    return gather_pages(new_kv, name, tables, length, dtype)


def paged_write_coords(tables: jax.Array, pos_grid: jax.Array, page_size: int,
                       max_len: int, num_pages: int):
    """Physical (page, slot) write coordinates for logical positions
    ``pos_grid`` [B,T] through block tables [B,MP] — the ONE copy of the
    logical→physical routing both decoder families' paged forwards share.
    Positions at/past ``max_len`` (idle-lane clamps, past-budget draft tails) and
    unallocated logical pages route to the SENTINEL page id (== ``num_pages``,
    out of bounds for the pool's page axis) so the scatter DROPS them — the
    paged spelling of the dense out-of-bounds-write contract."""
    logical = jnp.minimum(pos_grid // page_size, tables.shape[1] - 1)
    pages = jnp.where(
        pos_grid < max_len,
        jnp.take_along_axis(tables, logical, axis=1),
        jnp.int32(num_pages),
    )
    return pages, pos_grid % page_size


def multi_step_decode(forward_one: Callable, cache, tokens: jax.Array,
                      positions: jax.Array, active: jax.Array, budgets: jax.Array,
                      eos_ids: jax.Array, select_token: Callable, xs, n_steps: int,
                      max_len: int):
    """N cached decode steps as ONE ``lax.scan`` — the device-resident super-step
    both decoder families' ``forward_slots_multi`` wrappers share.

    Per scan step the carried ``tokens`` [B] (each lane's PENDING token — emitted
    by the previous step but not yet written, exactly the engine's host-loop
    invariant) are written+attended at ``positions``, one new token per live lane
    is selected by ``select_token(logits [B,V], x)`` (argmax for greedy; the
    sampled program folds per-lane emission-indexed keys in via ``xs``), and
    EOS/budget masking freezes finished lanes IN-SCAN: a frozen lane's write
    position is clamped to ``max_len`` so the dense scatter and the paged
    sentinel route both DROP the write (see :func:`write_kv` /
    :func:`paged_write_coords`) — which is also why the final emitted token of a
    finishing lane is never written, bitwise matching the N=1 loop where the
    engine frees the lane before the next dispatch.

    ``active`` bool[B] marks live lanes (idle lanes start frozen and never write
    — their host-side position stays put, unlike the N=1 path's harmless
    garbage write; both states are fully re-initialized at admit). ``budgets``
    int32[B] is each lane's REMAINING token budget (emission stops at exactly
    ``budgets`` tokens — the drain clamps again host-side, belt and braces).
    ``eos_ids`` int32[B] uses −1 for "no EOS".

    Returns ``(cache, tok_buf [N,B], counts [B])``: the token buffer is
    step-major (drain order), ``counts[b]`` is how many of lane b's rows are
    real emissions; the lane's final position is ``positions[b] + counts[b]``."""
    done0 = ~active
    count0 = jnp.zeros(tokens.shape, jnp.int32)

    def body(carry, x):
        cache, tok, pos, done, count = carry
        write_pos = jnp.where(done, jnp.int32(max_len), pos)
        logits, cache = forward_one(cache, tok, write_pos)
        nxt = select_token(logits, x)
        nxt = jnp.where(done, tok, nxt)
        emit = ~done
        count = count + emit.astype(jnp.int32)
        hit_eos = (eos_ids >= 0) & (nxt == eos_ids)
        done = done | (emit & (hit_eos | (count >= budgets)))
        pos = jnp.where(emit, pos + 1, pos)
        return (cache, nxt, pos, done, count), nxt

    (cache, _, _, _, counts), tok_buf = jax.lax.scan(
        body, (cache, tokens, positions, done0, count0), xs, length=n_steps
    )
    return cache, tok_buf, counts


def spec_multi_step_decode(forward_verify: Callable, propose: Callable,
                           select_ref: Callable, cache, tokens: jax.Array,
                           positions: jax.Array, active: jax.Array,
                           budgets: jax.Array, eos_ids: jax.Array,
                           key_tab: jax.Array, history: jax.Array,
                           hist_lens: jax.Array, n_steps: int, spec_k: int,
                           max_len: int):
    """N speculative rounds (draft → verify → accept) as ONE ``lax.scan`` — the
    device-resident speculative super-step both decoder families'
    ``forward_slots_spec_multi`` wrappers share. Composes :func:`multi_step_decode`'s
    lane-freezing carry with the serving engine's host spec round
    (``serving._spec_step``), eliminating the per-round host round-trip.

    Per scan step: ``propose(history, hist_lens) -> proposals [B, spec_k]``
    drafts on device from the carried token history (prompt + all emissions so
    far, packed from column 0 — the resident NgramDrafter is a pure gather);
    the carried pending ``tokens`` [B] and the proposals form the ``[B, spec_k+1]``
    verify sequence, written+attended at ``positions`` via
    ``forward_verify(cache, seq, write_pos) -> (logits [B, spec_k+1, V], cache)``;
    ``select_ref(logits, keys) -> ref [B, spec_k+1]`` picks the reference tokens
    (argmax for greedy lanes, the engine's replay sampler for sampled lanes);
    acceptance is :func:`generation.speculative_prefix_accept`.

    The bitwise-parity linchpin is the per-lane emission-key CURSOR: sampled
    draws consume keys indexed by EMISSION count, and acceptance makes that
    count lane-varying, so ``xs``-style key threading cannot work. Instead
    ``key_tab`` [B, K, 2] holds each lane's next K emission keys (K ≥
    n_steps·(spec_k+1) covers the worst case) and the carried ``count`` is the
    cursor: round keys are ``key_tab[b, count[b] + j]`` — exactly the keys the
    host loop's ``_step_keys_window(req, len(req.tokens), spec_k+1)`` would
    fetch at the same point, because ``len(req.tokens)`` grows by the SAME
    per-lane ``n_emit``.

    Lane freezing, the pending-token invariant, and the frozen-lane write-drop
    (position clamped to ``max_len`` → dense OOB scatter / paged sentinel both
    drop) carry over from :func:`multi_step_decode` verbatim. Rejected-draft
    writes above the accepted prefix leave garbage KV, masked by causality
    until the NEXT round's window (which starts exactly at the first garbage
    slot and spans ``spec_k+1 ≥`` the garbage run) overwrites it — the PR-6
    garbage-above-rewind contract, now applied per scan round.

    Accepted emissions are appended to the carried ``history`` in-scan (OOB
    columns drop), so round r+1 drafts from a context that includes round r's
    tokens — no host involvement at any point.

    Returns ``(cache, tok_buf [N, B, spec_k+1], emits [N, B], counts [B],
    proposed [B], accepted [B])``: per round, ``tok_buf[r, b, :emits[r, b]]``
    are lane b's real emissions (drain round-major, lane-minor to match the
    host loop's streaming order); ``counts`` is the per-lane emission total
    (final position is ``positions[b] + counts[b]``); ``proposed``/``accepted``
    are the telemetry accept-rate counters (spec_k per live lane per round /
    accepted-prefix lengths), summed on device in the carry."""
    from ..generation import speculative_prefix_accept

    B = tokens.shape[0]
    S = history.shape[1]
    k1 = spec_k + 1
    done0 = ~active
    zeros = jnp.zeros((B,), jnp.int32)

    def body(carry, _):
        cache, hist, lens, tok, pos, done, count, proposed, accepted = carry
        live = ~done
        props = propose(hist, lens)
        seq = jnp.concatenate([tok[:, None], props], axis=1)
        write_pos = jnp.where(done, jnp.int32(max_len), pos)
        logits, cache = forward_verify(cache, seq, write_pos)
        # Emission-key cursor: lane b's j-th key this round is its (count+j)-th
        # emission key. The clip only guards the table edge — a live lane never
        # reads past n_steps*(spec_k+1)-1, and the window itself already clamps
        # at the request's key-schedule end like the host loop's does.
        ki = jnp.clip(
            count[:, None] + jnp.arange(k1, dtype=jnp.int32)[None, :],
            0, key_tab.shape[1] - 1,
        )
        keys = jnp.take_along_axis(key_tab, ki[:, :, None], axis=1)
        ref = select_ref(logits, keys)
        n_emit, last, hit_eos, n_acc = speculative_prefix_accept(
            props, ref, live, budgets - count, eos_ids
        )
        # Append this round's emissions to the drafting history (columns past
        # n_emit route to S — out of bounds, the scatter drops them).
        wi = jnp.where(
            jnp.arange(k1, dtype=jnp.int32)[None, :] < n_emit[:, None],
            lens[:, None] + jnp.arange(k1, dtype=jnp.int32)[None, :],
            jnp.int32(S),
        )
        hist = hist.at[jnp.arange(B)[:, None], wi].set(ref)
        lens = lens + n_emit
        tok = jnp.where(n_emit > 0, last, tok)
        count = count + n_emit
        pos = pos + n_emit
        done = done | (live & (hit_eos | (count >= budgets)))
        proposed = proposed + jnp.where(live, jnp.int32(spec_k), 0)
        accepted = accepted + n_acc
        carry = (cache, hist, lens, tok, pos, done, count, proposed, accepted)
        return carry, (ref, n_emit)

    carry0 = (cache, history, hist_lens, tokens, positions, done0, zeros,
              zeros, zeros)
    (cache, _, _, _, _, _, counts, proposed, accepted), (tok_buf, emits) = (
        jax.lax.scan(body, carry0, None, length=n_steps)
    )
    return cache, tok_buf, emits, counts, proposed, accepted


def paged_attention_dispatch(q, pool, tables, positions, valid, *, page_size: int,
                             sm_scale: float, window: int = 0, softcap: float = 0.0,
                             dtype, dense_attention):
    """Family-shared paged-attention read: the Pallas kernel on TPU backends (or when
    forced), else gather-through-the-table into the family's own dense cached-attention
    math — which makes CPU paged decode BITWISE the dense engine (the tier-1 parity
    contract; the kernel path matches to fp32 accumulation order).

    ``ACCEL_PAGED_ATTN`` ∈ {auto, kernel, gather} picks the path (trace-time, like the
    backend probe in :func:`attention_dispatch`); ``dense_attention(ck, cv)`` is the
    family's fallback closure over its q/positions/valid/cfg."""
    import os

    impl = os.environ.get("ACCEL_PAGED_ATTN", "auto")
    if impl not in ("auto", "kernel", "gather"):
        raise ValueError(
            f"ACCEL_PAGED_ATTN={impl!r}: expected 'auto', 'kernel' or 'gather'"
        )
    if impl == "kernel" or (impl == "auto"
                            and jax.default_backend() in ("tpu", "axon")):
        try:
            from ..ops.paged_attention import paged_attention

            return paged_attention(
                q, pool, tables, positions, valid, page_size=page_size,
                sm_scale=sm_scale, window=window, softcap=softcap,
            )
        except Exception as exc:  # pragma: no cover - backend-dependent
            if impl == "kernel":
                raise
            # auto mode degrades to the gather path (a serving replica must not
            # crash on a kernel lowering regression) — but NEVER silently: the
            # fallback reads every table-covered page densely, so an unnoticed
            # degrade costs real HBM bandwidth on every decode step.
            import warnings

            warnings.warn(
                "paged-attention kernel failed; falling back to the gather path "
                f"(set ACCEL_PAGED_ATTN=kernel to make this fatal): "
                f"{type(exc).__name__}: {exc}"
            )
    ck = read_kv_paged(pool, "k", tables, valid.shape[1], dtype)
    cv = read_kv_paged(pool, "v", tables, valid.shape[1], dtype)
    return dense_attention(ck, cv)


def _softcap(scores: jax.Array, cap: float) -> jax.Array:
    """Gemma-style logit capping: cap·tanh(x/cap) (identity when cap == 0)."""
    return cap * jnp.tanh(scores / cap) if cap else scores


def cached_decode_family(cfg):
    """Resolve the family module owning a config's cached-decode contract
    (``init_cache`` / ``forward_cached`` over ``{layers, valid, index}``): llama or
    gpt. Raises for families without one (bert/t5) — the same loud failure
    ``inference.prepare_pippy`` gives unknown configs."""
    from . import gpt as _gpt
    from . import llama as _llama

    if isinstance(cfg, _gpt.GPTConfig):
        return _gpt
    if isinstance(cfg, _llama.LlamaConfig):
        return _llama
    raise TypeError(
        f"no cached-decode family for {type(cfg).__name__}: expected a LlamaConfig "
        "or GPTConfig (bert/t5 have no KV-cache decode contract)"
    )


# ------------------------------------------------------------- attention dispatch (shared)
def sp_active(mesh) -> bool:
    """Does this mesh (concrete or abstract; may be None) engage the sp axis? The ONE
    copy of the sequence-parallel activation predicate — shared by the family attention
    dispatchers (on the ambient mesh) and the pp sp-under-pp routing (on the mesh arg)."""
    from ..utils.constants import SEQUENCE_AXIS

    return mesh is not None and not mesh.empty and mesh.shape.get(SEQUENCE_AXIS, 1) > 1


def sp_manual(mesh) -> bool:
    """Is the sp axis already MANUAL in this context — i.e. are we inside a shard_map
    whose manual axes include sp (the pipeline's sp×pp composition)? Then the sp
    collectives (``lax.ppermute`` KV rotation / all_to_all) must be issued directly;
    wrapping another shard_map would nest, which fails to lower on the backward."""
    from ..utils.constants import SEQUENCE_AXIS

    try:
        types = dict(zip(mesh.axis_names, mesh.axis_types))
        return types.get(SEQUENCE_AXIS) == jax.sharding.AxisType.Manual
    except Exception:
        return False


def resolve_sp_pipeline(cfg, mesh, schedule: str, virtual_stages: int):
    """Family-shared sp×pp routing decision for ``loss_fn_pp`` → ``(sp_pipeline, cfg)``.

    ``sp_pipeline=True`` when ``cfg.attn_impl`` is an sp mode AND the sp axis is live —
    checked on the mesh ARGUMENT (the one the pipeline's shard_map will run under, which
    callers may pass without ``jax.set_mesh``) and on the ambient context. The pipeline
    then goes manual over sp: activations ride sequence-sliced, the stage body issues
    the ring/ulysses collectives flat (nesting ``make_sp_attention``'s own shard_map
    inside the pipeline's fails MLIR verification on the backward).

    Empirical lowering wall (r4, shared by every family): the ``all_to_all`` PRIMITIVE
    inside the hand-scheduled replay's per-tick ``jax.grad`` does not finish lowering
    (ring/allgather compile in seconds on the same config; ulysses hangs >9 min), so
    under 1f1b or virtual stages the returned cfg substitutes the ppermute-decomposed
    all-to-all (``sequence._a2a_ppermute``) — same math (equivalence-tested), ~2x the
    minimal ring bytes. Users who want the primitive's comm schedule can stay on gpipe
    or ring. ONE copy of both the predicate and the substitution, so the families
    cannot drift when the wall moves."""
    import dataclasses

    if cfg.attn_impl not in ("ring", "ulysses", "ulysses_ppermute", "allgather"):
        return False, cfg
    if not (sp_active(mesh) or sp_active(current_abstract_mesh())):
        return False, cfg
    if cfg.attn_impl == "ulysses" and (schedule == "1f1b" or virtual_stages > 1):
        cfg = dataclasses.replace(cfg, attn_impl="ulysses_ppermute")
    return True, cfg


def attention_dispatch(q, k, v, mask, *, impl: str, sm_scale: float, window: int = 0,
                       softcap: float = 0.0, segment_ids=None, xla_attention=None):
    """Family-shared causal self-attention dispatch (llama/gpt): ``impl`` in
    ``auto | flash | xla | ring | ulysses | allgather`` over q [B,S,H,hd],
    k/v [B,S,K,hd] (GQA: K ≤ H).

    - sp modes need an active mesh with sp > 1; inside a manual-sp shard_map (the
      pipeline's sp×pp composition) the collectives are issued flat, else the call is
      wrapped in ``make_sp_attention``'s own shard_map. Without sp, they fall back to
      local attention. Packed rows (``segment_ids``) compose with every impl.
    - ``xla_attention(q, k, v, mask)`` is the family's reference path (fallback)."""
    from ..utils.constants import SEQUENCE_AXIS

    if impl in ("ring", "ulysses", "ulysses_ppermute", "allgather"):
        mesh = current_abstract_mesh()
        if sp_active(mesh):
            if sp_manual(mesh):
                from ..parallel.sequence import sequence_parallel_attention

                return sequence_parallel_attention(
                    q, k, v, mode=impl, axis_name=SEQUENCE_AXIS, causal=True,
                    window=window, softcap=softcap, sm_scale=sm_scale,
                    segment_ids=segment_ids,
                )
            from ..parallel.sequence import make_sp_attention

            attn = make_sp_attention(
                mesh, mode=impl, axis_name=SEQUENCE_AXIS, causal=True,
                window=window, softcap=softcap, sm_scale=sm_scale,
            )
            return attn(q, k, v, segment_ids=segment_ids)
        impl = "auto"
    if impl == "auto":
        impl = "flash" if jax.default_backend() in ("tpu", "axon") else "xla"
    if impl == "flash":
        try:
            from ..ops.flash_attention import flash_attention

            # Packed rows stay on the flash path: the kernels take segment ids directly.
            return flash_attention(
                q, k, v, causal=True, segment_ids=segment_ids, window=window,
                sm_scale=sm_scale, softcap=softcap,
            )
        except Exception:  # pragma: no cover - kernel unavailable on this backend
            pass
    return xla_attention(q, k, v, mask)


def resolve_loss_chunk(loss_chunk: int, S: int, vocab_size: int) -> int:
    """Resolve the chunked-CE chunk length (0 tokens = don't chunk).

    An explicit ``loss_chunk`` is always honored (``chunked_ce`` pads S up to a chunk
    multiple, so divisibility never silently disables it). Auto mode (``loss_chunk=0``)
    chunks at 512 only when the fp32 logits would be large enough to matter (> 64 MB per
    example row); ``-1`` disables chunking outright.
    """
    if loss_chunk == -1:
        return 0
    if loss_chunk > 0:
        return min(loss_chunk, S)
    # auto: threshold on S*V; 2**24 elements = 64 MB of fp32 logits per example row.
    if S * vocab_size <= 2**24:
        return 0
    return min(512, S)


def chunked_ce(x, head, targets, mask, chunk: int, dtype, final_softcap: float = 0.0,
               bias=None):
    """Memory-efficient cross-entropy: per-chunk head matmul + logsumexp under remat.

    ``x`` [B,S,D] (post-final-norm hidden), ``head`` [D,V]; returns the sum of
    -log p(target) over unmasked positions. The fp32 [B,S,V] logits are never
    materialized — each scan step computes one [B,chunk,V] block and the backward pass
    recomputes it (``jax.checkpoint``), so peak memory drops from O(S·V) to O(chunk·V).
    S is padded up to a chunk multiple with masked positions, so any chunk works for any
    sequence length. ``bias`` [V] (gpt-j's lm_head bias) is added pre-softmax.
    """
    B, S, D = x.shape
    if S % chunk:
        pad = chunk - S % chunk
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
        S += pad
    n = S // chunk
    xs = x.reshape(B, n, chunk, D).swapaxes(0, 1)            # [n, B, c, D]
    ts = targets.reshape(B, n, chunk).swapaxes(0, 1)         # [n, B, c]
    ms = mask.reshape(B, n, chunk).swapaxes(0, 1)            # [n, B, c]

    @jax.checkpoint
    def chunk_loss(xc, tc, mc):
        logits = (xc @ head.astype(dtype)).astype(jnp.float32)   # [B, c, V]
        if bias is not None:
            logits = logits + bias.astype(jnp.float32)
        logits = _softcap(logits, final_softcap)
        lse = jax.nn.logsumexp(logits, axis=-1)                  # [B, c]
        tgt = jnp.take_along_axis(logits, tc[..., None], axis=-1).squeeze(-1)
        return -((tgt - lse) * mc).sum()

    def body(carry, xtm):
        xc, tc, mc = xtm
        return carry + chunk_loss(xc, tc, mc), None

    total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (xs, ts, ms))
    return total


def ce_sum(x, head, targets, mask, *, dtype, chunk: int = 0, softcap: float = 0.0,
           bias=None) -> jax.Array:
    """SUM-style chunked/dense CE core — the ONE copy of the softcap + log_softmax +
    target-gather math shared by the model families' normalized loss paths and the 1F1B
    last-stage heads (where sums across microbatch groups must add up exactly)."""
    if chunk > 0:
        return chunked_ce(x, head, targets, mask, chunk, dtype, final_softcap=softcap,
                          bias=bias)
    logits = (x @ head.astype(dtype)).astype(jnp.float32)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    logits = _softcap(logits, softcap)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1).squeeze(-1)
    return -(ll * mask).sum()


def ce_sum_dispatch(x, head, targets, mask, *, loss_impl: str, dtype,
                    chunk: int = 0, softcap: float = 0.0, bias=None) -> jax.Array:
    """SUM-style CE dispatcher — the ONE place every ``loss_impl`` routes through,
    shared across model families (llama/gpt) and across execution modes (single, GPipe,
    and the 1F1B last-stage head, where sums across microbatch groups must add up
    exactly).

    ``bias`` (gpt-j's lm_head bias): the fused kernels have no bias term, so a non-None
    bias always takes the chunked/dense path regardless of ``loss_impl`` — the same
    silent-fallback contract as ``gpt.loss_fn``'s single-device kernel gate.
    """
    S = x.shape[1]
    if loss_impl not in ("auto", "fused", "fused_dp", "fused_tp"):
        raise ValueError(
            f"loss_impl={loss_impl!r}: expected 'auto', 'fused', 'fused_dp', or "
            "'fused_tp' (a typo would otherwise silently run the chunked path)"
        )
    if bias is not None:
        loss_impl = "auto"
    if loss_impl == "fused_tp":
        # Megatron-layout fused CE: the head stays VOCAB-SHARDED over tp (never
        # gathered), each tp shard runs the Pallas kernel on its vocab slice, and the
        # logsumexp merges across tp in fp32 (ops/fused_xent.fused_cross_entropy_tp).
        # Tokens stay sharded over the batch axes. For batch-only layouts use
        # "fused_dp"; single device "fused".
        from jax.sharding import PartitionSpec as P

        from ..ops.fused_xent import fused_cross_entropy_tp
        from ..utils.constants import BATCH_AXES, TENSOR_AXIS as _TP

        mesh = current_abstract_mesh()
        if not getattr(mesh, "axis_names", ()):
            raise ValueError(
                "loss_impl='fused_tp' needs an active mesh context "
                "(Accelerator.build_train_step provides one; or wrap in jax.set_mesh)."
            )
        D = x.shape[-1]

        def _local(xl, tl, ml, hd):
            Bl = xl.shape[0]
            nll = fused_cross_entropy_tp(
                xl.reshape(Bl * S, D), hd, tl.reshape(Bl * S), axis_name=_TP,
                softcap=softcap,
            )
            return (nll * ml.reshape(Bl * S)).sum()[None]

        partials = _shard_map(
            _local,
            mesh=mesh,
            in_specs=(P(BATCH_AXES), P(BATCH_AXES), P(BATCH_AXES), P(None, _TP)),
            out_specs=P(BATCH_AXES),
            check_vma=False,  # pallas_call outputs carry no vma info (kernel contract)
        )(x, targets, mask, head.astype(dtype))
        return partials.sum()
    if loss_impl == "fused_dp":
        # Multi-chip fused CE: shard_map over the batch axes — each device runs the
        # kernel on ITS tokens against a replicated head (in_spec P() makes shard_map's
        # transpose psum the head gradient). For batch-sharded layouts (dp/fsdp); under
        # tp-sharded heads or sp-sharded sequences prefer the chunked path (this one
        # would all-gather the head / sequence into every shard).
        from jax.sharding import PartitionSpec as P

        from ..ops.fused_xent import fused_cross_entropy
        from ..utils.constants import BATCH_AXES

        mesh = current_abstract_mesh()
        if not getattr(mesh, "axis_names", ()):
            raise ValueError(
                "loss_impl='fused_dp' needs an active mesh context "
                "(Accelerator.build_train_step provides one; or wrap in jax.set_mesh)."
            )
        D = x.shape[-1]

        def _local(xl, tl, ml, hd):
            Bl = xl.shape[0]
            nll = fused_cross_entropy(
                xl.reshape(Bl * S, D), hd, tl.reshape(Bl * S), softcap=softcap,
            )
            return (nll * ml.reshape(Bl * S)).sum()[None]

        partials = _shard_map(
            _local,
            mesh=mesh,
            in_specs=(P(BATCH_AXES), P(BATCH_AXES), P(BATCH_AXES), P()),
            out_specs=P(BATCH_AXES),
            check_vma=False,  # pallas_call outputs carry no vma info
        )(x, targets, mask, head.astype(dtype))
        return partials.sum()
    if loss_impl == "fused":
        # Single-shard path: on a real multi-chip mesh fused_ce_single_shard returns
        # None — fall through to the chunked path (or use "fused_dp").
        loss = fused_ce_single_shard(x, head.astype(dtype), targets, mask,
                                     softcap=softcap)
        if loss is not None:
            # fused_ce_single_shard returns the masked MEAN; convert back to SUM so
            # every branch of this dispatcher has identical (sum) semantics.
            return loss * jnp.maximum(mask.sum(), 1.0)
    return ce_sum(x, head, targets, mask, dtype=dtype, chunk=chunk, softcap=softcap,
                  bias=bias)


def fused_ce_allowed() -> bool:
    """True when the single-shard fused-CE kernel may run: one device, or interpret
    mode (CPU tests — lowers to partitionable XLA). On a real multi-device mesh the
    pallas_call would force GSPMD to gather the batch-sharded activations."""
    from ..ops._common import interpret_default

    return jax.device_count() == 1 or interpret_default()


def fused_ce_single_shard(x, head, targets, mask, softcap: float = 0.0):
    """Masked-mean fused cross-entropy over [B, S, D] hidden states, or None.

    Shared dispatch for the model families' ``loss_impl="fused"`` branches: returns None
    when :func:`fused_ce_allowed` says the kernel must not run. ``mask`` [B, S] float;
    ``head`` [D, V] already in compute dtype.
    """
    if not fused_ce_allowed():
        return None
    from ..ops.fused_xent import fused_cross_entropy

    B, S, D = x.shape
    nll = fused_cross_entropy(
        x.reshape(B * S, D), head, targets.reshape(B * S), softcap=softcap
    )
    mask1d = mask.reshape(B * S)
    return (nll * mask1d).sum() / jnp.maximum(mask1d.sum(), 1.0)
