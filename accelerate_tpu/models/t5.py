"""T5 encoder-decoder family — the reference baseline table's T0pp (11B) architecture.

Reference baselines cover decoder-only (GPT-J/NeoX) AND encoder-decoder models (T0pp,
``/root/reference/benchmarks/big_model_inference/README.md:35``); this module supplies the
latter natively with the T5 conventions that differ from the other families:

- T5 LayerNorm: RMS, scale-only, NO mean subtraction and NO bias, computed in fp32.
- Relative position bias (bucketed, log-spaced): a [num_buckets, n_heads] table held by the
  FIRST block of the encoder and of the decoder, shared by all their blocks; no positional
  embeddings anywhere else.
- Attention scores are NOT scaled by 1/sqrt(head_dim) (absorbed into init).
- Feed-forward: gated-GELU (``wi_0``·gelu × ``wi_1`` → ``wo``, T5 v1.1/T0 lineage) or ReLU.
- Tied embeddings rescale decoder output by ``d_model**-0.5`` before the vocab projection.

``hf_interop.t5_from_hf`` maps transformers ``T5ForConditionalGeneration`` weights; parity
is asserted against transformers itself in ``tests/test_hf_interop.py``.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..utils.constants import BATCH_AXES, FSDP_AXIS, TENSOR_AXIS

__all__ = [
    "T5Config",
    "CONFIGS",
    "init_params",
    "encode",
    "decode",
    "forward",
    "loss_fn",
    "score",
    "perplexity",
    "partition_specs",
    "stack_pp_params",
    "forward_pp",
    "loss_fn_pp",
    "generate",
    "generate_streamed",
    "num_params",
]


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64            # per-head dim (NOT d_model // n_heads in general!)
    d_ff: int = 1024
    n_layers: int = 6         # encoder depth
    n_decoder_layers: Optional[int] = None  # None → n_layers
    n_heads: int = 8
    rel_buckets: int = 32
    rel_max_distance: int = 128
    gated_ff: bool = True     # gated-gelu (v1.1/T0); False → relu
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    tie_embeddings: bool = True
    # "auto": dense CE. "fused": ops/fused_xent kernel (single device; multi-device
    # meshes fall back to dense).
    loss_impl: str = "auto"
    remat: bool = False                       # jax.checkpoint each enc/dec block
    remat_policy: str = "full"                # "full" | "dots" | "offload" (models/common.py)
    remat_prevent_cse: Optional[bool] = None  # None = auto (True: python-loop stack)
    decoder_start_token_id: int = 0

    @property
    def dec_layers(self) -> int:
        return self.n_decoder_layers or self.n_layers


CONFIGS = {
    "t5-small-v1_1": T5Config(),
    "t5-base-v1_1": T5Config(d_model=768, d_ff=2048, n_layers=12, n_heads=12),
    # T0pp / t5-v1.1-xxl shape — the reference's 11B baseline model.
    "t0pp": T5Config(d_model=4096, d_kv=64, d_ff=10240, n_layers=24, n_heads=64),
    "tiny": T5Config(vocab_size=128, d_model=32, d_kv=8, d_ff=64, n_layers=2, n_heads=4),
}


def _attn_params(cfg: T5Config, key, with_rel_bias: bool) -> dict:
    k = jax.random.split(key, 5)
    D, inner = cfg.d_model, cfg.n_heads * cfg.d_kv
    p = {
        "q": jax.random.normal(k[0], (D, inner), jnp.float32) * (D * cfg.d_kv) ** -0.5,
        "k": jax.random.normal(k[1], (D, inner), jnp.float32) * D**-0.5,
        "v": jax.random.normal(k[2], (D, inner), jnp.float32) * D**-0.5,
        "o": jax.random.normal(k[3], (inner, D), jnp.float32) * inner**-0.5,
    }
    if with_rel_bias:
        p["rel_bias"] = jax.random.normal(
            k[4], (cfg.rel_buckets, cfg.n_heads), jnp.float32
        ) * 0.1
    return p


def _ff_params(cfg: T5Config, key) -> dict:
    k = jax.random.split(key, 3)
    D, F = cfg.d_model, cfg.d_ff
    p = {"wo": jax.random.normal(k[2], (F, D), jnp.float32) * F**-0.5}
    if cfg.gated_ff:
        p["wi_0"] = jax.random.normal(k[0], (D, F), jnp.float32) * D**-0.5
        p["wi_1"] = jax.random.normal(k[1], (D, F), jnp.float32) * D**-0.5
    else:
        p["wi"] = jax.random.normal(k[0], (D, F), jnp.float32) * D**-0.5
    return p


def init_params(cfg: T5Config, key: Optional[jax.Array] = None) -> dict:
    if key is None:
        key = jax.random.PRNGKey(0)  # graftlint: disable=rng-key-reuse(deterministic default init; callers pass a key for real entropy)
    n_enc, n_dec = cfg.n_layers, cfg.dec_layers
    keys = jax.random.split(key, 2 + 2 * n_enc + 3 * n_dec)
    ki = iter(range(len(keys)))
    params: dict = {
        "shared": jax.random.normal(keys[next(ki)], (cfg.vocab_size, cfg.d_model), jnp.float32),
        "encoder": {"blocks": [], "ln_f": jnp.ones((cfg.d_model,), jnp.float32)},
        "decoder": {"blocks": [], "ln_f": jnp.ones((cfg.d_model,), jnp.float32)},
    }
    for i in range(n_enc):
        params["encoder"]["blocks"].append({
            "ln_attn": jnp.ones((cfg.d_model,), jnp.float32),
            "attn": _attn_params(cfg, keys[next(ki)], with_rel_bias=(i == 0)),
            "ln_ff": jnp.ones((cfg.d_model,), jnp.float32),
            "ff": _ff_params(cfg, keys[next(ki)]),
        })
    for i in range(n_dec):
        params["decoder"]["blocks"].append({
            "ln_attn": jnp.ones((cfg.d_model,), jnp.float32),
            "attn": _attn_params(cfg, keys[next(ki)], with_rel_bias=(i == 0)),
            "ln_cross": jnp.ones((cfg.d_model,), jnp.float32),
            "cross": _attn_params(cfg, keys[next(ki)], with_rel_bias=False),
            "ln_ff": jnp.ones((cfg.d_model,), jnp.float32),
            "ff": _ff_params(cfg, keys[next(ki)]),
        })
    if not cfg.tie_embeddings:
        params["lm_head"] = jax.random.normal(
            keys[next(ki)], (cfg.d_model, cfg.vocab_size), jnp.float32
        ) * cfg.d_model**-0.5
    return params


def partition_specs(cfg: T5Config, pp: bool = False, virtual_stages: int = 1) -> dict:
    """Megatron layout: q/k/v/wi column-parallel, o/wo row-parallel, vocab over (tp,fsdp).

    ``pp=True``: specs for the :func:`stack_pp_params` layout — encoder/decoder block
    stacks ``[n_stages, L/n, ...]`` with the stage dim over ``pp`` (each stage holds only
    its blocks), rel-bias tables lifted out of block 0 and replicated, vocab folded over
    (tp, fsdp, pp) like the llama/gpt pipeline layouts."""
    def attn_spec(with_rel: bool) -> dict:
        s = {"q": P(None, TENSOR_AXIS), "k": P(None, TENSOR_AXIS),
             "v": P(None, TENSOR_AXIS), "o": P(TENSOR_AXIS, None)}
        if with_rel:
            s["rel_bias"] = P(None, TENSOR_AXIS)
        return s

    def ff_spec() -> dict:
        s = {"wo": P(TENSOR_AXIS, None)}
        if cfg.gated_ff:
            s.update({"wi_0": P(None, TENSOR_AXIS), "wi_1": P(None, TENSOR_AXIS)})
        else:
            s["wi"] = P(None, TENSOR_AXIS)
        return s

    if pp:
        from ..utils.constants import PIPELINE_AXIS

        from ..parallel.pp import stage_spec_prefix

        def stage_stack(spec_tree, v=1):
            # [n_stages, L/n, ...] (or interleaved [v, n, L/(n·v), ...] — pp on dim 1).
            return jax.tree_util.tree_map(
                lambda s: P(*stage_spec_prefix(v), *s), spec_tree,
                is_leaf=lambda s: isinstance(s, P),
            )

        vocab_axes = (TENSOR_AXIS, FSDP_AXIS, PIPELINE_AXIS)
        enc_blk = {"ln_attn": P(), "attn": attn_spec(False), "ln_ff": P(), "ff": ff_spec()}
        dec_blk = {"ln_attn": P(), "attn": attn_spec(False), "ln_cross": P(),
                   "cross": attn_spec(False), "ln_ff": P(), "ff": ff_spec()}
        specs = {
            "shared": P(vocab_axes, None),
            "enc_rel": P(None, TENSOR_AXIS),
            "dec_rel": P(None, TENSOR_AXIS),
            "encoder": {"stages": stage_stack(enc_blk), "ln_f": P()},
            "decoder": {"stages": stage_stack(dec_blk, virtual_stages), "ln_f": P()},
        }
        if not cfg.tie_embeddings:
            specs["lm_head"] = P(None, vocab_axes)
        return specs

    enc = [
        {"ln_attn": P(), "attn": attn_spec(i == 0), "ln_ff": P(), "ff": ff_spec()}
        for i in range(cfg.n_layers)
    ]
    dec = [
        {"ln_attn": P(), "attn": attn_spec(i == 0), "ln_cross": P(),
         "cross": attn_spec(False), "ln_ff": P(), "ff": ff_spec()}
        for i in range(cfg.dec_layers)
    ]
    specs = {
        "shared": P((TENSOR_AXIS, FSDP_AXIS), None),
        "encoder": {"blocks": enc, "ln_f": P()},
        "decoder": {"blocks": dec, "ln_f": P()},
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, (TENSOR_AXIS, FSDP_AXIS))
    return specs


def _segment_pair_mask(q_seg, k_seg):
    """[B,1,Q,K] bool: query/key in the SAME segment AND key not padding (segment 0)."""
    same = q_seg[:, :, None] == k_seg[:, None, :]
    live = (k_seg != 0)[:, None, :]
    return (same & live)[:, None]


def _t5_norm(x, scale, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + eps) * scale).astype(x.dtype)


def _relative_bucket(rel_pos, bidirectional: bool, num_buckets: int, max_distance: int):
    """HF T5's bucketing: half the buckets for sign (bidirectional), log-spaced far bins."""
    ret = jnp.zeros_like(rel_pos)
    n = rel_pos
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n > 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = -jnp.minimum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / math.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    large = jnp.minimum(large, num_buckets - 1)
    return ret + jnp.where(is_small, n, large)


def _rel_bias(table, q_len: int, k_len: int, bidirectional: bool, cfg: T5Config):
    """[1, heads, q_len, k_len] additive attention bias from the bucket table."""
    ctx = jnp.arange(q_len)[:, None]
    mem = jnp.arange(k_len)[None, :]
    buckets = _relative_bucket(
        mem - ctx, bidirectional, cfg.rel_buckets, cfg.rel_max_distance
    )
    bias = table[buckets]  # [q, k, heads]
    return jnp.transpose(bias, (2, 0, 1))[None].astype(jnp.float32)


def _attention(h_q, h_kv, p, cfg: T5Config, bias, mask):
    """T5 attention: UNscaled scores + additive (rel + mask) fp32 bias."""
    B, Q, D = h_q.shape
    K = h_kv.shape[1]
    dtype = h_q.dtype
    q = (h_q @ p["q"].astype(dtype)).reshape(B, Q, cfg.n_heads, cfg.d_kv)
    k = (h_kv @ p["k"].astype(dtype)).reshape(B, K, cfg.n_heads, cfg.d_kv)
    v = (h_kv @ p["v"].astype(dtype)).reshape(B, K, cfg.n_heads, cfg.d_kv)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    if bias is not None:
        scores = scores + bias
    if mask is not None:
        scores = jnp.where(mask, scores, -1e9)
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, Q, cfg.n_heads * cfg.d_kv)
    return out @ p["o"].astype(dtype)


def _ff(h, p, cfg: T5Config):
    dtype = h.dtype
    if cfg.gated_ff:
        inner = jax.nn.gelu(h @ p["wi_0"].astype(dtype), approximate=False) * (
            h @ p["wi_1"].astype(dtype)
        )
    else:
        inner = jax.nn.relu(h @ p["wi"].astype(dtype))
    return inner @ p["wo"].astype(dtype)


def _enc_block(x, blk, bias, mask, cfg: T5Config):
    """One encoder block (self-attention + FF, pre-norm residuals)."""
    h = _t5_norm(x, blk["ln_attn"], cfg.norm_eps)
    x = x + _attention(h, h, blk["attn"], cfg, bias, mask)
    h = _t5_norm(x, blk["ln_ff"], cfg.norm_eps)
    return x + _ff(h, blk["ff"], cfg)


def _dec_block(x, blk, enc_out, bias, causal, cmask, cfg: T5Config):
    """One decoder block (causal self-attention + cross-attention + FF)."""
    h = _t5_norm(x, blk["ln_attn"], cfg.norm_eps)
    x = x + _attention(h, h, blk["attn"], cfg, bias, causal)
    h = _t5_norm(x, blk["ln_cross"], cfg.norm_eps)
    x = x + _attention(h, enc_out, blk["cross"], cfg, None, cmask)
    h = _t5_norm(x, blk["ln_ff"], cfg.norm_eps)
    return x + _ff(h, blk["ff"], cfg)


def encode(params: dict, input_ids: jax.Array, cfg: T5Config,
           attention_mask: Optional[jax.Array] = None,
           segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """Encoder: input_ids [B, S] → hidden [B, S, D].

    ``segment_ids`` (seq2seq packing, ``ops/packing.pack_seq2seq``): bidirectional
    attention restricted to same-segment pairs; segment 0 is padding. T5's relative-
    position bias needs no change — within a contiguous segment, relative distances are
    shift-invariant, and cross-segment pairs are masked.
    """
    from .llama import _maybe_shard

    B, S = input_ids.shape
    x = params["shared"].astype(cfg.dtype)[input_ids]
    x = _maybe_shard(x, P(BATCH_AXES, None, None))
    rel_table = params["encoder"]["blocks"][0]["attn"]["rel_bias"]
    bias = _rel_bias(rel_table, S, S, bidirectional=True, cfg=cfg)
    mask = None
    if segment_ids is not None:
        mask = _segment_pair_mask(segment_ids, segment_ids)
        if attention_mask is not None:
            mask = mask & attention_mask[:, None, None, :].astype(bool)
    elif attention_mask is not None:
        mask = attention_mask[:, None, None, :].astype(bool)
    from .common import remat_wrap

    enc_block = remat_wrap(
        _enc_block, remat=cfg.remat, policy=cfg.remat_policy,
        prevent_cse=cfg.remat_prevent_cse, static_argnums=(4,),
    )
    for blk in params["encoder"]["blocks"]:
        x = enc_block(x, blk, bias, mask, cfg)
    return _t5_norm(x, params["encoder"]["ln_f"], cfg.norm_eps)


def decode(params: dict, decoder_input_ids: jax.Array, enc_out: jax.Array, cfg: T5Config,
           enc_mask: Optional[jax.Array] = None,
           dec_segment_ids: Optional[jax.Array] = None,
           enc_segment_ids: Optional[jax.Array] = None,
           return_hidden: bool = False) -> jax.Array:
    """Decoder: ids [B, T] + encoder hidden → logits [B, T, V] fp32 (or the post-ln_f
    [B, T, D] compute-dtype hidden states — tied-head scaling included — when
    ``return_hidden``; the fused-CE path applies the head inside its kernel).

    Packed rows (``dec_segment_ids``/``enc_segment_ids``): self-attention restricts to
    per-segment causal; cross-attention lets decoder segment k attend ONLY encoder
    segment k (pack_seq2seq assigns pairs the same number on both sides).
    """
    B, T = decoder_input_ids.shape
    x = params["shared"].astype(cfg.dtype)[decoder_input_ids]
    rel_table = params["decoder"]["blocks"][0]["attn"]["rel_bias"]
    bias = _rel_bias(rel_table, T, T, bidirectional=False, cfg=cfg)
    causal = jnp.tril(jnp.ones((T, T), bool))[None, None]
    if (dec_segment_ids is None) != (enc_segment_ids is None):
        # One side alone would leave cross-attention unmasked across packed segments —
        # silently wrong logits, the exact failure packing support exists to prevent.
        raise ValueError(
            "packed decode requires BOTH dec_segment_ids and enc_segment_ids"
        )
    cmask = None
    if dec_segment_ids is not None:
        causal = causal & _segment_pair_mask(dec_segment_ids, dec_segment_ids)
        cmask = _segment_pair_mask(dec_segment_ids, enc_segment_ids)
        if enc_mask is not None:
            cmask = cmask & enc_mask[:, None, None, :].astype(bool)
    elif enc_mask is not None:
        cmask = enc_mask[:, None, None, :].astype(bool)
    from .common import remat_wrap

    dec_block = remat_wrap(
        _dec_block, remat=cfg.remat, policy=cfg.remat_policy,
        prevent_cse=cfg.remat_prevent_cse, static_argnums=(6,),
    )
    for blk in params["decoder"]["blocks"]:
        x = dec_block(x, blk, enc_out, bias, causal, cmask, cfg)
    x = _t5_norm(x, params["decoder"]["ln_f"], cfg.norm_eps)
    if cfg.tie_embeddings:
        x = x * (cfg.d_model**-0.5)  # tied-head scaling lives on the hidden side
    if return_hidden:
        return x
    return (x @ _t5_head(params, cfg).astype(cfg.dtype)).astype(jnp.float32)


def _t5_head(params: dict, cfg: T5Config) -> jax.Array:
    return params["shared"].T if cfg.tie_embeddings else params["lm_head"]


def forward(params: dict, input_ids: jax.Array, decoder_input_ids: jax.Array,
            cfg: T5Config, attention_mask: Optional[jax.Array] = None) -> jax.Array:
    enc = encode(params, input_ids, cfg, attention_mask)
    return decode(params, decoder_input_ids, enc, cfg, attention_mask)


def loss_fn(params: dict, batch: dict, cfg: T5Config, rng=None) -> jax.Array:
    """Seq2seq cross-entropy over {'input_ids', 'labels'} (+optional 'attention_mask').

    Decoder inputs are the labels shifted right with ``decoder_start_token_id`` (the HF
    ``_shift_right`` convention); label positions equal to -100 are ignored.

    Packed batches (``ops/packing.pack_seq2seq``: +'enc_segment_ids'/'dec_segment_ids'):
    the shift-right restarts at every decoder segment boundary (each packed pair begins
    with the start token), attention restricts per segment on both sides, and
    cross-attention pairs decoder segment k with encoder segment k.
    """
    if "segment_ids" in batch:
        raise ValueError(
            "seq2seq packing uses pack_seq2seq ('enc_segment_ids'/'dec_segment_ids'), "
            "not the decoder-only 'segment_ids' layout"
        )
    if cfg.loss_impl not in ("auto", "fused"):
        raise ValueError(f"loss_impl={cfg.loss_impl!r}: expected 'auto' or 'fused'")
    from .common import fused_ce_allowed

    want_fused = cfg.loss_impl == "fused" and fused_ce_allowed()
    labels = batch["labels"]
    start = jnp.full((labels.shape[0], 1), cfg.decoder_start_token_id, labels.dtype)
    if "dec_segment_ids" in batch:
        dec_seg = batch["dec_segment_ids"]
        enc_seg = batch["enc_segment_ids"]
        prev = jnp.concatenate([start, jnp.maximum(labels[:, :-1], 0)], axis=1)
        is_start = jnp.concatenate(
            [jnp.ones((labels.shape[0], 1), bool), dec_seg[:, 1:] != dec_seg[:, :-1]],
            axis=1,
        )
        dec_in = jnp.where(is_start, jnp.asarray(cfg.decoder_start_token_id, labels.dtype), prev)
        enc_out = encode(
            params, batch["input_ids"], cfg, batch.get("attention_mask"), segment_ids=enc_seg
        )
        out = decode(
            params, dec_in, enc_out, cfg, batch.get("attention_mask"),
            dec_segment_ids=dec_seg, enc_segment_ids=enc_seg, return_hidden=want_fused,
        )
        mask = ((labels >= 0) & (dec_seg != 0)).astype(jnp.float32)
    else:
        dec_in = jnp.concatenate([start, jnp.maximum(labels[:, :-1], 0)], axis=1)
        enc_out = encode(params, batch["input_ids"], cfg, batch.get("attention_mask"))
        out = decode(
            params, dec_in, enc_out, cfg, batch.get("attention_mask"),
            return_hidden=want_fused,
        )
        mask = (labels >= 0).astype(jnp.float32)
    safe = jnp.maximum(labels, 0)
    if want_fused:
        # want_fused == fused_ce_allowed(), so the helper cannot return None here
        # (and `out` is hidden states, not logits — the dense tail must not run).
        from .common import fused_ce_single_shard

        return fused_ce_single_shard(
            out, _t5_head(params, cfg).astype(cfg.dtype), safe, mask
        )
    logp = jax.nn.log_softmax(out, axis=-1)
    ll = jnp.take_along_axis(logp, safe[..., None], axis=-1).squeeze(-1)
    return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


# --------------------------------------------------------------- pipeline-parallel training
def stack_pp_params(
    params: dict, cfg: T5Config, n_stages: int, virtual_stages: int = 1
) -> dict:
    """Canonical params → the pipeline layout (the enc-dec analog of llama's
    stage-stacked layers; reference Megatron pipelines T5 too,
    ``/root/reference/src/accelerate/utils/megatron_lm.py:720``).

    The rel-bias tables live in block 0 only, which makes the raw block lists
    structurally heterogeneous and unstackable — they are LIFTED to top-level
    ``enc_rel``/``dec_rel`` leaves (shared by all blocks anyway), and the now-homogeneous
    blocks stack to ``[n_stages, L/n, ...]`` under ``encoder.stages``/``decoder.stages``.
    Specs: ``partition_specs(cfg, pp=True)``.

    ``virtual_stages=v > 1`` (interleaved, 1f1b): the DECODER stacks to the
    interleaved ``[v, n, L/(n·v), ...]`` layout (its pipeline is the hand-scheduled
    half); the encoder keeps ``[n, L/n, ...]`` (it runs AD-GPipe either way).
    """
    if cfg.n_layers % n_stages or cfg.dec_layers % (n_stages * virtual_stages):
        raise ValueError(
            f"encoder depth ({cfg.n_layers}) must be divisible by n_stages={n_stages} "
            f"and decoder depth ({cfg.dec_layers}) by n_stages x "
            f"virtual_stages={virtual_stages}"
        )

    def strip_stack(blocks, v=1):
        first = dict(blocks[0])
        first["attn"] = {k: v2 for k, v2 in first["attn"].items() if k != "rel_bias"}
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), first, *blocks[1:])
        from ..parallel.pp import split_params_into_stages

        return split_params_into_stages(stacked, n_stages, virtual_stages=v)

    out = {
        "shared": params["shared"],
        "enc_rel": params["encoder"]["blocks"][0]["attn"]["rel_bias"],
        "dec_rel": params["decoder"]["blocks"][0]["attn"]["rel_bias"],
        "encoder": {"stages": strip_stack(params["encoder"]["blocks"]),
                    "ln_f": params["encoder"]["ln_f"]},
        "decoder": {"stages": strip_stack(params["decoder"]["blocks"], virtual_stages),
                    "ln_f": params["decoder"]["ln_f"]},
    }
    if not cfg.tie_embeddings:
        out["lm_head"] = params["lm_head"]
    return out


def _enc_stage_fn(cfg: T5Config):
    """Encoder pipeline stage: scan this stage's blocks over one microbatch. The shared
    rel bias rides as a per-stage param slice (``sp["bias"]``, same value every stage —
    broadcast at trace time, so AD sums the per-stage grads back into the one table);
    the optional attention mask is a per-microbatch side constant."""
    from .common import remat_wrap

    block = remat_wrap(
        _enc_block, remat=cfg.remat, policy=cfg.remat_policy,
        prevent_cse=cfg.remat_prevent_cse, scan_layers=True, static_argnums=(4,),
    )

    def stage_fn(sp, x, side):
        mask = None
        if "enc_seg" in side:
            # seq2seq packing: bidirectional attention restricted to same-segment pairs.
            mask = _segment_pair_mask(side["enc_seg"], side["enc_seg"])
        if "enc_mask" in side:
            am = side["enc_mask"][:, None, None, :].astype(bool)
            mask = am if mask is None else mask & am

        def body(carry, blk):
            # sp["bias"] is [1, H, S, S] here: pipeline_apply already stripped the
            # leading stage dim from every stage-param leaf.
            return block(carry, blk, sp["bias"], mask, cfg), None

        out, _ = jax.lax.scan(body, x, sp["blocks"])
        return out

    return stage_fn


def _dec_stage_fn(cfg: T5Config, T: int):
    """Decoder pipeline stage: causal self-attention + cross-attention against the
    frozen encoder output, which rides as a per-microbatch side constant — indexed by
    microbatch id, never ppermuted. Under the AD-derived GPipe schedule the side input
    IS differentiable, so encoder grads flow back through cross-attention."""
    from .common import remat_wrap

    block = remat_wrap(
        _dec_block, remat=cfg.remat, policy=cfg.remat_policy,
        prevent_cse=cfg.remat_prevent_cse, scan_layers=True, static_argnums=(6,),
    )

    def stage_fn(sp, x, side):
        causal = jnp.tril(jnp.ones((T, T), bool))[None, None]
        cmask = None
        if "dec_seg" in side:
            # seq2seq packing: per-segment causal self-attention; cross-attention pairs
            # decoder segment k with encoder segment k only (pack_seq2seq numbering).
            causal = causal & _segment_pair_mask(side["dec_seg"], side["dec_seg"])
            cmask = _segment_pair_mask(side["dec_seg"], side["enc_seg"])
        if "enc_mask" in side:
            am = side["enc_mask"][:, None, None, :].astype(bool)
            cmask = am if cmask is None else cmask & am

        def body(carry, blk):
            return block(carry, blk, side["enc_out"], sp["bias"], causal, cmask, cfg), None

        out, _ = jax.lax.scan(body, x, sp["blocks"])
        return out

    return stage_fn


def forward_pp(
    params: dict,
    input_ids: jax.Array,
    decoder_input_ids: jax.Array,
    cfg: T5Config,
    mesh,
    num_microbatches: Optional[int] = None,
    attention_mask: Optional[jax.Array] = None,
    return_hidden: bool = False,
    enc_segment_ids: Optional[jax.Array] = None,
    dec_segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Seq2seq forward with BOTH stacks pipelined over ``pp`` — the enc-dec pipeline
    shape the reference's Megatron engine drives for T5 (``megatron_lm.py:720``).

    Two chained GPipe pipelines over the same ``pp`` axis: encoder stages first
    (microbatches stream through all of them), then decoder stages, with the completed
    ``enc_out`` delivered to every decoder stage's cross-attention as a per-microbatch
    side constant (``parallel.pp`` side-input contract — indexed, never ppermuted).
    Params in :func:`stack_pp_params` layout; embed/ln_f/head outside the pipelines,
    vocab-sharded over (tp, fsdp, pp) by ``partition_specs(pp=True)``.
    """
    enc_out = _encode_pp(
        params, input_ids, cfg, mesh, num_microbatches, attention_mask, enc_segment_ids,
        dec_segment_ids,
    )
    xd, sp_d, side_d = _dec_pp_inputs(
        params, decoder_input_ids, cfg, mesh, enc_out, attention_mask,
        enc_segment_ids, dec_segment_ids,
    )
    from ..parallel.pp import make_pipeline_fn

    T = decoder_input_ids.shape[1]
    pipe_d = make_pipeline_fn(
        mesh, _dec_stage_fn(cfg, T), num_microbatches=num_microbatches
    )
    xd = pipe_d(sp_d, xd, side=side_d)
    xd = _t5_norm(xd, params["decoder"]["ln_f"], cfg.norm_eps)
    if cfg.tie_embeddings:
        xd = xd * (cfg.d_model**-0.5)
    if return_hidden:
        return xd
    return (xd @ _t5_head(params, cfg).astype(cfg.dtype)).astype(jnp.float32)


def _encode_pp(
    params, input_ids, cfg: T5Config, mesh, num_microbatches, attention_mask,
    enc_segment_ids, dec_segment_ids,
):
    """The encoder half of the t5 pipeline: GPipe over the encoder stages → post-ln_f
    encoder output (shared by the GPipe and 1F1B decoder paths)."""
    from ..parallel.pp import make_pipeline_fn
    from ..utils.constants import PIPELINE_AXIS
    from .llama import _maybe_shard

    if (dec_segment_ids is None) != (enc_segment_ids is None):
        raise ValueError("packed forward_pp requires BOTH enc_ and dec_segment_ids")
    n = mesh.shape[PIPELINE_AXIS]
    B, S = input_ids.shape
    x = params["shared"].astype(cfg.dtype)[input_ids]
    x = _maybe_shard(x, P(BATCH_AXES, None, None))
    bias_e = _rel_bias(params["enc_rel"], S, S, bidirectional=True, cfg=cfg)
    sp_e = {
        "blocks": params["encoder"]["stages"],
        # [n, 1, H, S, S]: one (identical) slice per stage; sliced back to [1,H,S,S] in
        # the stage body. Broadcast inside the traced fn → AD sums per-stage grads.
        "bias": jnp.broadcast_to(bias_e[None], (n, *bias_e.shape)),
    }
    side_e = {"enc_mask": attention_mask} if attention_mask is not None else {}
    if enc_segment_ids is not None:
        side_e["enc_seg"] = enc_segment_ids
    pipe_e = make_pipeline_fn(mesh, _enc_stage_fn(cfg), num_microbatches=num_microbatches)
    # side={} still routes through the side path (3-arg stage_fn), just with no leaves.
    enc_out = pipe_e(sp_e, x, side=side_e)
    return _t5_norm(enc_out, params["encoder"]["ln_f"], cfg.norm_eps)


def _dec_pp_inputs(
    params, decoder_input_ids, cfg: T5Config, mesh, enc_out, attention_mask,
    enc_segment_ids, dec_segment_ids, virtual_stages: int = 1,
):
    """Decoder-pipeline inputs shared by the GPipe and 1F1B paths: embedded decoder
    activations, decoder stage params (blocks + broadcast rel bias), and the side tree
    (enc_out + masks/segments — enc_out is the FLOAT side leaf whose cotangent both
    schedules propagate back into the encoder pipeline)."""
    from ..utils.constants import PIPELINE_AXIS
    from .llama import _maybe_shard

    n = mesh.shape[PIPELINE_AXIS]
    T = decoder_input_ids.shape[1]
    xd = params["shared"].astype(cfg.dtype)[decoder_input_ids]
    xd = _maybe_shard(xd, P(BATCH_AXES, None, None))
    bias_d = _rel_bias(params["dec_rel"], T, T, bidirectional=False, cfg=cfg)
    # One (identical) bias slice per stage — per (chunk, stage) in the interleaved
    # layout; AD sums the broadcast's per-slice grads back into the one table.
    bias_st = (
        jnp.broadcast_to(bias_d[None, None], (virtual_stages, n, *bias_d.shape))
        if virtual_stages > 1
        else jnp.broadcast_to(bias_d[None], (n, *bias_d.shape))
    )
    sp_d = {
        "blocks": params["decoder"]["stages"],
        "bias": bias_st,
    }
    side_d = {"enc_out": enc_out}
    if attention_mask is not None:
        side_d["enc_mask"] = attention_mask
    if dec_segment_ids is not None:
        side_d["dec_seg"] = dec_segment_ids
        side_d["enc_seg"] = enc_segment_ids
    return xd, sp_d, side_d


def loss_fn_pp(
    params: dict,
    batch: dict,
    cfg: T5Config,
    mesh,
    num_microbatches: Optional[int] = None,
    rng=None,
    schedule: str = "gpipe",
    virtual_stages: int = 1,
) -> jax.Array:
    """Pipeline-parallel seq2seq CE (params in :func:`stack_pp_params` layout; same
    batch contract as ``loss_fn``, INCLUDING seq2seq packing — enc/dec segment ids ride
    both pipelines as per-microbatch side constants). Every ``loss_impl`` works — the
    head runs after the pipelines via ``common.ce_sum_dispatch``.

    ``virtual_stages=v > 1`` (with 1f1b): the DECODER pipeline runs interleaved
    (params from ``stack_pp_params(..., virtual_stages=v)``) — enc_out's cotangent
    accumulates through the virtual-stage replay exactly as in the flat 1f1b.

    ``schedule="1f1b"`` hand-schedules the DECODER pipeline (the deeper, heavier half —
    self + cross attention per block) through ``make_pipeline_loss_fn``; the replay
    computes the TRUE ``enc_out`` cotangent (float side leaves accumulate across stages
    and microbatches), which jax AD then chains back through the encoder's GPipe
    pipeline. The encoder half stays AD-GPipe — its activations are the cheap half, and
    a fully hand-scheduled enc+dec interleave would buy little for the added table
    complexity."""
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"schedule={schedule!r}: expected 'gpipe' or '1f1b'")
    if virtual_stages > 1 and schedule != "1f1b":
        raise NotImplementedError(
            "virtual_stages > 1 requires schedule='1f1b' (parallel/pp.py)"
        )
    if "segment_ids" in batch:
        raise ValueError(
            "seq2seq packing uses pack_seq2seq ('enc_segment_ids'/'dec_segment_ids'), "
            "not the decoder-only 'segment_ids' layout"
        )
    from .common import ce_sum_dispatch, resolve_loss_chunk

    labels = batch["labels"]
    start = jnp.full((labels.shape[0], 1), cfg.decoder_start_token_id, labels.dtype)
    if "dec_segment_ids" in batch:
        # Same packed conventions as loss_fn: the shift-right restarts at every decoder
        # segment boundary, and targets count only inside real decoder segments.
        dec_seg = batch["dec_segment_ids"]
        enc_seg = batch["enc_segment_ids"]
        prev = jnp.concatenate([start, jnp.maximum(labels[:, :-1], 0)], axis=1)
        is_start = jnp.concatenate(
            [jnp.ones((labels.shape[0], 1), bool), dec_seg[:, 1:] != dec_seg[:, :-1]],
            axis=1,
        )
        dec_in = jnp.where(
            is_start, jnp.asarray(cfg.decoder_start_token_id, labels.dtype), prev
        )
        mask = ((labels >= 0) & (dec_seg != 0)).astype(jnp.float32)
    else:
        dec_seg = enc_seg = None
        dec_in = jnp.concatenate([start, jnp.maximum(labels[:, :-1], 0)], axis=1)
        mask = (labels >= 0).astype(jnp.float32)
    safe = jnp.maximum(labels, 0)
    if schedule == "1f1b":
        from ..parallel.pp import make_pipeline_loss_fn

        T = labels.shape[1]
        am = batch.get("attention_mask")
        enc_out = _encode_pp(
            params, batch["input_ids"], cfg, mesh, num_microbatches, am,
            enc_seg, dec_seg,
        )
        xd, sp_d, side_d = _dec_pp_inputs(
            params, dec_in, cfg, mesh, enc_out, am, enc_seg, dec_seg,
            virtual_stages=virtual_stages,
        )
        hp = {"ln_f": params["decoder"]["ln_f"], "head": _t5_head(params, cfg)}

        def head_loss(h, y, ex):
            xh = _t5_norm(y, h["ln_f"], cfg.norm_eps)
            if cfg.tie_embeddings:
                xh = xh * (cfg.d_model**-0.5)
            total = ce_sum_dispatch(
                xh, h["head"], ex["targets"], ex["mask"],
                loss_impl=cfg.loss_impl, dtype=cfg.dtype,
                chunk=resolve_loss_chunk(0, T, cfg.vocab_size),
            )
            return total / jnp.maximum(ex["mask"].sum(), 1.0)

        pipe_loss = make_pipeline_loss_fn(
            mesh, _dec_stage_fn(cfg, T), head_loss,
            num_microbatches=num_microbatches, schedule="1f1b",
            virtual_stages=virtual_stages,
        )
        return pipe_loss(
            sp_d, hp, xd, {"targets": safe, "mask": mask}, side=side_d
        )
    hidden = forward_pp(
        params, batch["input_ids"], dec_in, cfg, mesh,
        num_microbatches=num_microbatches,
        attention_mask=batch.get("attention_mask"), return_hidden=True,
        enc_segment_ids=enc_seg, dec_segment_ids=dec_seg,
    )
    total = ce_sum_dispatch(
        hidden, _t5_head(params, cfg), safe, mask,
        loss_impl=cfg.loss_impl, dtype=cfg.dtype,
        chunk=resolve_loss_chunk(0, labels.shape[1], cfg.vocab_size),
    )
    return total / jnp.maximum(mask.sum(), 1.0)


def score(params: dict, input_ids, labels, cfg: T5Config,
          attention_mask=None) -> jax.Array:
    """Per-target-token log-probabilities log p(label[t] | inputs, labels[:t]) → [B, T]
    fp32 (seq2seq; ignored -100 labels score 0.0). Same contract as ``llama.score``."""
    labels = jnp.asarray(labels, jnp.int32)
    start = jnp.full((labels.shape[0], 1), cfg.decoder_start_token_id, labels.dtype)
    dec_in = jnp.concatenate([start, jnp.maximum(labels[:, :-1], 0)], axis=1)
    logits = forward(params, input_ids, dec_in, cfg, attention_mask)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, jnp.maximum(labels, 0)[..., None], axis=-1).squeeze(-1)
    return ll * (labels >= 0).astype(ll.dtype)


def perplexity(params: dict, input_ids, labels, cfg: T5Config,
               attention_mask=None) -> jax.Array:
    """exp(mean negative log-likelihood over real label positions) — scalar fp32."""
    labels = jnp.asarray(labels, jnp.int32)
    ll = score(params, input_ids, labels, cfg, attention_mask)
    denom = jnp.maximum((labels >= 0).sum(), 1)
    return jnp.exp(-ll.sum() / denom)


def generate(params: dict, input_ids: jax.Array, cfg: T5Config,
             max_new_tokens: int = 32, attention_mask: Optional[jax.Array] = None,
             eos_token_id: int = 1) -> jax.Array:
    """Greedy seq2seq generation: encoder runs once, decoder re-runs on the growing prefix
    (O(T²) decode — adequate for eval loops; a cached incremental decoder is the llama/gpt
    families' pattern and can be grafted when T5 decode becomes a hot path)."""
    enc = encode(params, input_ids, cfg, attention_mask)
    B = input_ids.shape[0]
    dec = jnp.full((B, 1), cfg.decoder_start_token_id, jnp.int32)
    done = jnp.zeros((B,), bool)
    for _ in range(max_new_tokens):
        logits = decode(params, dec, enc, cfg, attention_mask)
        nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        nxt = jnp.where(done, eos_token_id, nxt)
        done = done | (nxt == eos_token_id)
        dec = jnp.concatenate([dec, nxt[:, None]], axis=1)
        if bool(jnp.all(done)):
            break
    return dec[:, 1:]


def generate_streamed(
    dispatched,
    input_ids: jax.Array,
    cfg: T5Config,
    max_new_tokens: int = 32,
    attention_mask: Optional[jax.Array] = None,
    eos_token_id: int = 1,
    prefetch: int = 2,
    pass_times: Optional[list] = None,
) -> jax.Array:
    """Greedy seq2seq generation with encoder/decoder blocks streamed from host/disk.

    Completes the big-model story for the reference's T0pp baseline (11B — 22 GB even in
    bf16, beyond a single v5e's HBM; the reference spreads it over two 24 GB GPUs,
    ``benchmarks/big_model_inference/README.md:35``). The encoder streams once; each decode
    step re-runs the decoder over a FIXED-width padded prefix buffer so the per-block jit
    compiles exactly twice (one encoder, one decoder shape) regardless of step count —
    causality makes the garbage tail positions unobservable to position t. Weight streaming,
    not the O(T²) prefix recompute, dominates at these scales.
    """
    from ..big_modeling import consume_block, stream_blocks
    from .llama import _streamed_head_jit

    import time as _time

    t_pass = _time.perf_counter()
    input_ids = jnp.asarray(input_ids, jnp.int32)
    B, S = input_ids.shape
    shared = dispatched.fetch("shared")
    # Gather then cast: this loop is host-driven, so .astype on the full [V, D] matrix
    # would eagerly convert ~0.5 GB per pass at T0pp scale.
    x = shared[input_ids].astype(cfg.dtype)
    mask = None
    if attention_mask is not None:
        mask = jnp.asarray(attention_mask)[:, None, None, :].astype(bool)
    bias = None
    for name, blk in stream_blocks(
        dispatched, [f"encoder/blocks/{i}" for i in range(cfg.n_layers)], prefetch=prefetch
    ):
        if bias is None:  # block 0 carries the shared relative-position table
            bias = _rel_bias(blk["attn"]["rel_bias"], S, S, bidirectional=True, cfg=cfg)
        x = _enc_block_jit(x, blk, bias, mask, cfg=cfg)
        # Fence + free (relay clients retain host mirrors of lazily-GC'd device
        # buffers — big_modeling.consume_block). bias survives: _rel_bias built a NEW
        # array from block 0's table before this point.
        consume_block(x, blk, dispatched, name)
    enc_out = _t5_norm(x, dispatched.fetch("encoder/ln_f"), cfg.norm_eps)
    if pass_times is not None:
        # Same contract as streamed_generate_loop: entry 0 is the prefill analog (the
        # streamed encoder), then one entry per decode step, each blocked on its tokens.
        jax.block_until_ready(enc_out)
        pass_times.append(_time.perf_counter() - t_pass)

    T = 1 + max_new_tokens
    dec = jnp.full((B, T), cfg.decoder_start_token_id, jnp.int32)
    causal = jnp.tril(jnp.ones((T, T), bool))[None, None]
    cmask = mask
    head = shared if cfg.tie_embeddings else dispatched.fetch("lm_head")
    dec_prefixes = [f"decoder/blocks/{i}" for i in range(cfg.dec_layers)]
    dec_ln_f = dispatched.fetch("decoder/ln_f")
    done = jnp.zeros((B,), bool)
    out = []
    dbias = None
    for t in range(max_new_tokens):
        t_pass = _time.perf_counter()
        y = shared[dec].astype(cfg.dtype)
        for name, blk in stream_blocks(dispatched, dec_prefixes, prefetch=prefetch):
            if dbias is None:
                dbias = _rel_bias(blk["attn"]["rel_bias"], T, T, bidirectional=False, cfg=cfg)
            y = _dec_block_jit(y, blk, enc_out, dbias, causal, cmask, cfg=cfg)
            consume_block(y, blk, dispatched, name)  # fence + free (see encoder loop note)
        y_t = _t5_norm(y[:, t, :], dec_ln_f, cfg.norm_eps)
        if cfg.tie_embeddings:
            y_t = y_t * (cfg.d_model**-0.5)
        logits = _streamed_head_jit(y_t, head, transpose=cfg.tie_embeddings)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(done, eos_token_id, nxt)
        done = done | (nxt == eos_token_id)
        if pass_times is not None:
            jax.block_until_ready(nxt)  # graftlint: disable=host-sync-in-hot-path(pass_times contract: per-pass wall time blocked on the step output)
            pass_times.append(_time.perf_counter() - t_pass)
        out.append(nxt)
        dec = dec.at[:, t + 1].set(nxt)
        if bool(jnp.all(done)):
            out.extend([jnp.full((B,), eos_token_id, jnp.int32)] * (max_new_tokens - len(out)))
            break
    return jnp.stack(out, axis=1)


@partial(jax.jit, static_argnames=("cfg",))
def _enc_block_jit(x, blk, bias, mask, cfg):
    return _enc_block(x, blk, bias, mask, cfg)


@partial(jax.jit, static_argnames=("cfg",))
def _dec_block_jit(x, blk, enc_out, bias, causal, cmask, cfg):
    return _dec_block(x, blk, enc_out, bias, causal, cmask, cfg)


def num_params(cfg: T5Config) -> int:
    D, F, V, H, kv = cfg.d_model, cfg.d_ff, cfg.vocab_size, cfg.n_heads, cfg.d_kv
    inner = H * kv
    attn = 3 * D * inner + inner * D
    ff = (2 * D * F if cfg.gated_ff else D * F) + F * D
    enc = cfg.n_layers * (attn + ff + 2 * D) + D + cfg.rel_buckets * H
    dec = cfg.dec_layers * (2 * attn + ff + 3 * D) + D + cfg.rel_buckets * H
    total = V * D + enc + dec
    if not cfg.tie_embeddings:
        total += D * V
    return total
