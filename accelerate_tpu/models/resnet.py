"""Vision model family: functional ResNet with GroupNorm (the ``cv_example`` backbone).

The reference's CV examples fine-tune a timm ``resnet50d`` (``/root/reference/examples/
cv_example.py``); the framework ships its own TPU-native ResNet because the mesh runtime
needs models whose sharding is part of their definition (same rationale as ``llama.py``).

TPU-first choices:
- **GroupNorm instead of BatchNorm**: batch statistics are cross-device state that would
  need ``psum``s in the forward and running-stat mutation outside the functional step;
  GroupNorm is stateless, batch-size-independent and jit-trivial — the standard swap for
  functional vision stacks.
- NHWC layout (XLA:TPU's native convolution layout, feeds the MXU without transposes).
- ``partition_specs`` shard conv filters over their output-channel dim (column-parallel
  analog) so TP/FSDP composition works exactly like the llama plans.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..utils.constants import TENSOR_AXIS

__all__ = [
    "ResNetConfig",
    "CONFIGS",
    "init_params",
    "forward",
    "loss_fn",
    "partition_specs",
    "num_params",
]


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 10
    widths: tuple = (64, 128, 256, 512)
    blocks_per_stage: tuple = (2, 2, 2, 2)  # resnet18-shaped
    in_channels: int = 3
    groups: int = 8           # GroupNorm groups
    dtype: Any = jnp.float32


CONFIGS = {
    "resnet18": ResNetConfig(),
    "resnet34": ResNetConfig(blocks_per_stage=(3, 4, 6, 3)),
    "tiny": ResNetConfig(widths=(8, 16), blocks_per_stage=(1, 1), groups=4),
}


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * math.sqrt(2.0 / fan_in)


def _block_params(cfg: ResNetConfig, key, cin: int, cout: int) -> dict:
    k = jax.random.split(key, 3)
    p = {
        "conv1": _conv_init(k[0], 3, 3, cin, cout),
        "gn1": {"scale": jnp.ones((cout,)), "bias": jnp.zeros((cout,))},
        "conv2": _conv_init(k[1], 3, 3, cout, cout),
        "gn2": {"scale": jnp.ones((cout,)), "bias": jnp.zeros((cout,))},
    }
    if cin != cout:
        p["proj"] = _conv_init(k[2], 1, 1, cin, cout)
    return p


def init_params(cfg: ResNetConfig, key: Optional[jax.Array] = None) -> dict:
    if key is None:
        key = jax.random.PRNGKey(0)  # graftlint: disable=rng-key-reuse(deterministic default init; callers pass a key for real entropy)
    n_blocks = sum(cfg.blocks_per_stage)
    keys = jax.random.split(key, n_blocks + 2)
    params: dict = {
        "stem": _conv_init(keys[0], 3, 3, cfg.in_channels, cfg.widths[0]),
        "stem_gn": {"scale": jnp.ones((cfg.widths[0],)), "bias": jnp.zeros((cfg.widths[0],))},
        "stages": [],
    }
    ki = 1
    cin = cfg.widths[0]
    for width, n in zip(cfg.widths, cfg.blocks_per_stage):
        stage = []
        for _ in range(n):
            stage.append(_block_params(cfg, keys[ki], cin, width))
            cin = width
            ki += 1
        params["stages"].append(stage)
    params["head"] = {
        "w": jax.random.normal(keys[-1], (cin, cfg.num_classes), jnp.float32) / math.sqrt(cin),
        "b": jnp.zeros((cfg.num_classes,)),
    }
    return params


def partition_specs(cfg: ResNetConfig) -> dict:
    """Conv filters column-parallel on output channels; head row/col like an MLP."""
    gn = {"scale": P(), "bias": P()}

    def block_spec(has_proj: bool) -> dict:
        s = {
            "conv1": P(None, None, None, TENSOR_AXIS),
            "gn1": dict(gn),
            "conv2": P(None, None, None, TENSOR_AXIS),
            "gn2": dict(gn),
        }
        if has_proj:
            s["proj"] = P(None, None, None, TENSOR_AXIS)
        return s

    stages = []
    cin = cfg.widths[0]
    for width, n in zip(cfg.widths, cfg.blocks_per_stage):
        stage = []
        for _ in range(n):
            stage.append(block_spec(cin != width))
            cin = width
        stages.append(stage)
    return {
        "stem": P(None, None, None, TENSOR_AXIS),
        "stem_gn": dict(gn),
        "stages": stages,
        "head": {"w": P(None, TENSOR_AXIS), "b": P(TENSOR_AXIS)},
    }


def _group_norm(x, gn, groups: int, eps: float = 1e-5):
    B, H, W, C = x.shape
    out_dtype = x.dtype  # stats in fp32; the output must return to the compute dtype
    g = min(groups, C)
    xg = x.reshape(B, H, W, g, C // g).astype(jnp.float32)
    mean = xg.mean(axis=(1, 2, 4), keepdims=True)
    var = xg.var(axis=(1, 2, 4), keepdims=True)
    xg = (xg - mean) * jax.lax.rsqrt(var + eps)
    x = xg.reshape(B, H, W, C)
    return (x * gn["scale"] + gn["bias"]).astype(out_dtype)


def _conv(x, w, stride: int = 1):
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _block(x, p, cfg: ResNetConfig, stride: int):
    h = _conv(x, p["conv1"], stride)
    h = jax.nn.relu(_group_norm(h, p["gn1"], cfg.groups))
    h = _conv(h, p["conv2"])
    h = _group_norm(h, p["gn2"], cfg.groups)
    if "proj" in p:
        x = _conv(x, p["proj"], stride)
    elif stride != 1:
        x = x[:, ::stride, ::stride, :]
    return jax.nn.relu(x + h)


def forward(params: dict, images: jax.Array, cfg: ResNetConfig) -> jax.Array:
    """images [B, H, W, C] (NHWC, float) → logits [B, num_classes] fp32."""
    x = images.astype(cfg.dtype)
    x = jax.nn.relu(_group_norm(_conv(x, params["stem"]), params["stem_gn"], cfg.groups))
    for si, stage in enumerate(params["stages"]):
        for bi, block in enumerate(stage):
            stride = 2 if (si > 0 and bi == 0) else 1
            x = _block(x, block, cfg, stride)
    x = x.mean(axis=(1, 2))  # global average pool
    head = params["head"]
    return (x @ head["w"].astype(x.dtype) + head["b"].astype(x.dtype)).astype(jnp.float32)


def loss_fn(params: dict, batch: dict, cfg: ResNetConfig) -> jax.Array:
    """Cross-entropy over batch {'image': [B,H,W,C], 'label': [B]}."""
    logits = forward(params, batch["image"], cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, batch["label"][:, None].astype(jnp.int32), axis=-1)
    return -jnp.mean(ll)


def num_params(cfg: ResNetConfig) -> int:
    return sum(int(np.prod(np.shape(l))) for l in jax.tree_util.tree_leaves(init_params(cfg)))
