"""Deterministic fault injection: a seed-driven plan of failures at named sites.

Chaos testing a serving stack with ``kill -9`` and hope is not reproducible;
this module makes failure a FIRST-CLASS, seeded input. A :class:`FaultPlan`
holds :class:`FaultSpec` clauses, each naming an injection **site** (a stable
string the instrumented code passes to :meth:`FaultPlan.draw` — the catalog
lives in docs/resilience.md), a fault **kind**, and a deterministic firing
rule (per-spec RNG stream keyed off the plan seed, an invocation window, a
fire budget, and optional request matching). The instrumented sites are:

=====================  ======================================================
site                   instrumented in
=====================  ======================================================
``serving.decode``     ``ContinuousBatcher`` decode/verify dispatch (kinds:
                       ``error``, ``hang``, ``nonfinite``; ``crash`` = whole-
                       engine death — raises :class:`EngineCrashed` PAST the
                       engine's recovery boundary, the fleet router's failover
                       signal)
``serving.prefill``    admission prefill (``error`` — always attributable to
                       the admitting request; ``crash`` as above)
``serving.kv_admit``   paged page-pool allocation (``error``)
``train.step``         ``_TrainStep`` and the MPMD ``StageProcess`` (kind
                       ``nonfinite`` poisons the batch's float leaves with NaN
                       — the REAL non-finite guard path, not a simulated
                       exception; ``crash`` = whole-gang death — raises
                       :class:`StageCrashed` PAST the step boundary, the
                       gang-of-gangs supervisor's restart signal, exactly as
                       ``EngineCrashed`` is the fleet router's)
``ckpt.save``          ``save_accelerator_state`` (``crash`` raises before the
                       commit marker lands; ``corrupt`` flips bytes in a saved
                       file after the marker — caught by manifest verification
                       at load)
=====================  ======================================================

**Zero overhead when disabled**: instrumented code holds ``faults=None`` and
the hot path pays one attribute read (the Telemetry contract). **Deterministic
by seed**: each spec draws from its own ``np.random`` stream, so whether spec
i fires at its site's n-th invocation depends only on ``(seed, i, n)`` — never
on other sites' interleaving.

Plans thread through the stack like the other cross-cutting configs: the
``ACCELERATE_FAULTS`` env var / ``FaultConfig`` ride ``AcceleratorState``
(``Accelerator.fault_plan``), and serving constructs take ``faults=`` directly
(``serve-bench --chaos`` builds one per replay).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

from ..telemetry.clocks import resolve_clock

__all__ = [
    "FaultError",
    "InjectedFault",
    "EngineCrashed",
    "StageCrashed",
    "StepTimeout",
    "NonFiniteStepError",
    "FaultSpec",
    "FaultPlan",
    "StepWatchdog",
    "parse_fault_spec",
]

#: Fault kinds a spec may inject. What each means is site-specific (see the
#: site catalog above); sites ignore kinds they don't implement.
FAULT_KINDS = ("error", "hang", "nonfinite", "crash", "corrupt")


class FaultError(RuntimeError):
    """Base of every failure the resilience layer raises or injects."""


class InjectedFault(FaultError):
    """An injected failure firing at an instrumented site.

    ``uid`` carries the poison request when the spec is *attributed* (the
    recovery path quarantines it directly); ``None`` forces the bisection
    fallback. ``pre_dispatch`` tells the boundary the device state was NOT
    touched (the fault raised before any donated dispatch), so recovery can
    skip the full rebuild."""

    def __init__(self, site: str, kind: str, uid: Optional[int] = None,
                 pre_dispatch: bool = True):
        super().__init__(f"injected fault at {site}: {kind}"
                         + (f" (uid={uid})" if uid is not None else ""))
        self.site = site
        self.kind = kind
        self.uid = uid
        self.pre_dispatch = pre_dispatch


class EngineCrashed(FaultError):
    """A whole-engine (replica) death — the in-process stand-in for a killed
    serving process.

    Unlike :class:`InjectedFault`, the engine's own recovery boundary must NOT
    catch this: there is no process left to quarantine a request in, so the
    crash propagates out of ``step()`` to whoever owns the replica (the fleet
    router, which migrates the in-flight requests to another replica and hands
    the corpse to the supervisor for restart). Injected via fault kind
    ``crash`` at the serving sites (``serving.decode`` / ``serving.prefill``)."""

    def __init__(self, site: str, uid: Optional[int] = None):
        super().__init__(f"engine crashed at {site}")
        self.site = site
        self.kind = "crash"
        self.uid = uid


class StageCrashed(FaultError):
    """A whole-training-gang (MPMD stage) death — the training analog of
    :class:`EngineCrashed`.

    The step boundary must NOT catch this: there is no process left to skip a
    step in, so the crash propagates past ``train.step`` to whoever owns the
    gang (the gang-of-gangs orchestrator, ``elastic.GangOfGangs``, which holds
    the peer stages at a barrier, hands the corpse to the ``FleetSupervisor``
    for a budgeted restart, and replays the pipeline from the last verified
    checkpoint). ``gang_id`` is machine-readable — it names WHICH gang's
    restart budget the failure charges. Injected via fault kind ``crash`` at
    the ``train.step`` site."""

    def __init__(self, site: str, gang_id: str = "gang0",
                 uid: Optional[int] = None):
        super().__init__(f"stage gang {gang_id} crashed at {site}")
        self.site = site
        self.kind = "crash"
        self.gang_id = str(gang_id)
        self.uid = uid


class StepTimeout(FaultError):
    """A dispatch exceeded its :class:`StepWatchdog` wall-clock budget."""

    def __init__(self, site: str, elapsed_s: float, budget_s: float):
        super().__init__(
            f"{site}: dispatch took {elapsed_s:.3f}s (budget {budget_s:.3f}s)"
        )
        self.site = site
        self.uid = None
        self.elapsed_s = elapsed_s
        self.budget_s = budget_s


class NonFiniteStepError(FaultError):
    """Training aborted: ``skip_nonfinite_steps`` consecutive-skip budget hit."""

    def __init__(self, consecutive: int, total: int):
        super().__init__(
            f"{consecutive} consecutive non-finite training steps "
            f"({total} total skipped) — loss/grads are diverging, aborting"
        )
        self.consecutive = consecutive
        self.total = total


@dataclasses.dataclass
class FaultSpec:
    """One injection clause: fire ``kind`` at ``site`` with probability
    ``prob`` per invocation, inside the invocation window ``[start, stop)``,
    at most ``max_fires`` times.

    ``match_uid`` restricts firing to invocations whose context includes that
    request uid (a data-poison stand-in); ``attributed=False`` withholds the
    uid from the raised fault, forcing the recovery path's bisection fallback.
    ``hang_s`` is the injected dispatch stall for kind ``hang``."""

    site: str
    kind: str = "error"
    prob: float = 1.0
    start: int = 0
    stop: Optional[int] = None
    max_fires: Optional[int] = None
    match_uid: Optional[int] = None
    attributed: bool = True
    hang_s: float = 0.05

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"kind={self.kind!r} must be one of {list(FAULT_KINDS)}"
            )
        if not 0.0 <= self.prob <= 1.0:
            raise ValueError(f"prob={self.prob} must be in [0, 1]")
        if self.start < 0:
            raise ValueError(f"start={self.start} must be >= 0")
        if self.stop is not None and self.stop <= self.start:
            raise ValueError(f"stop={self.stop} must be > start={self.start}")
        if self.max_fires is not None and self.max_fires < 1:
            raise ValueError(f"max_fires={self.max_fires} must be >= 1")
        if self.hang_s < 0:
            raise ValueError(f"hang_s={self.hang_s} must be >= 0")


class FaultPlan:
    """A seeded set of :class:`FaultSpec` clauses plus the firing bookkeeping.

    ``draw(site, uids=...)`` is the ONE call instrumented code makes: it
    advances the site's invocation counter and returns the first spec that
    fires (or None). Every fire is recorded in :attr:`fired` (site, kind, uid,
    invocation) so tests and the chaos bench can assert exactly which faults
    landed. Determinism: spec ``i`` owns the RNG stream ``(seed, i)`` and
    consumes one uniform per invocation of its site — whether it fires at the
    site's n-th invocation is independent of every other site and spec.

    ``scope`` keys the streams ``(seed, scope, i)`` instead — the stage-scoped
    spelling for gang-of-gangs training: every MPMD stage process holds its OWN
    plan built from the SAME seed and clause string but scoped by its
    ``gang_id``, so which stage crashes at which step is a pure function of
    ``(seed, gang_id)`` and never of how the stages interleave."""

    def __init__(self, specs: Sequence[FaultSpec], seed: int = 0,
                 scope: Optional[str] = None):
        import zlib

        import numpy as np

        self.specs = list(specs)
        self.seed = int(seed)
        self.scope = scope
        scope_key = ([] if scope is None
                     else [zlib.crc32(str(scope).encode("utf-8"))])
        self._rngs = [np.random.default_rng([self.seed, *scope_key, i])
                      for i in range(len(self.specs))]
        self._site_counts: dict = {}
        self._fires_left = [
            s.max_fires if s.max_fires is not None else -1 for s in self.specs
        ]
        self.fired: List[dict] = []

    @classmethod
    def from_spec(cls, spec: str, seed: int = 0,
                  scope: Optional[str] = None) -> "FaultPlan":
        """Build a plan from the compact ``ACCELERATE_FAULTS`` string form
        (:func:`parse_fault_spec`). ``scope`` stage-scopes the RNG streams
        (one plan per gang from one clause string)."""
        specs, parsed_seed = parse_fault_spec(spec)
        return cls(specs, seed=parsed_seed if parsed_seed is not None else seed,
                   scope=scope)

    def draw(self, site: str, uids: Optional[Sequence[int]] = None,
             uid: Optional[int] = None) -> Optional[FaultSpec]:
        """One invocation of ``site``: returns the first spec that fires.

        ``uids`` (the active request set) / ``uid`` (a single admitting
        request) let ``match_uid`` specs model data poison — they fire only
        when their target participates. The matched spec's raised fault
        carries the uid only when the spec is ``attributed``."""
        n = self._site_counts.get(site, 0)
        self._site_counts[site] = n + 1
        hit = None
        for i, spec in enumerate(self.specs):
            if spec.site != site:
                continue
            # Every site-matching spec consumes its uniform at every
            # invocation (fired or not) — the stream position depends only on
            # the site's invocation count, never on which specs fired.
            u = float(self._rngs[i].random())
            if hit is not None or self._fires_left[i] == 0:
                continue
            if n < spec.start or (spec.stop is not None and n >= spec.stop):
                continue
            if spec.match_uid is not None:
                present = (uid == spec.match_uid) or (
                    uids is not None and spec.match_uid in uids
                )
                if not present:
                    continue
            if u < spec.prob:
                hit = (i, spec)
        if hit is None:
            return None
        i, spec = hit
        if self._fires_left[i] > 0:
            self._fires_left[i] -= 1
        target = spec.match_uid if spec.match_uid is not None else uid
        self.fired.append({
            "site": site, "kind": spec.kind, "invocation": n,
            "uid": target if spec.attributed else None,
        })
        return spec

    def fault_for(self, spec: FaultSpec, site: str,
                  uid: Optional[int] = None) -> InjectedFault:
        """The exception a fired spec injects (uid withheld when the spec is
        unattributed — the bisection-fallback test hook)."""
        target = spec.match_uid if spec.match_uid is not None else uid
        return InjectedFault(
            site, spec.kind, uid=target if spec.attributed else None
        )

    def stats(self) -> dict:
        return {
            "seed": self.seed,
            "scope": self.scope,
            "specs": len(self.specs),
            "fired": len(self.fired),
            "by_site": {
                site: sum(1 for f in self.fired if f["site"] == site)
                for site in sorted({f["site"] for f in self.fired})
            },
            "invocations": dict(self._site_counts),
        }

    def __repr__(self) -> str:
        scope = f", scope={self.scope!r}" if self.scope is not None else ""
        return (f"FaultPlan(seed={self.seed}{scope}, specs={len(self.specs)}, "
                f"fired={len(self.fired)})")


def parse_fault_spec(text: str):
    """Parse the compact ``ACCELERATE_FAULTS`` clause string →
    ``(specs, seed-or-None)``.

    Grammar: semicolon-separated clauses; ``seed=N`` sets the plan seed; every
    other clause is ``site:kind[:prob][,key=value...]`` with keys
    ``start``/``stop``/``max``/``uid``/``hang_s``/``attributed``. Example::

        seed=7; serving.decode:error:0.1,max=3; ckpt.save:crash,start=2
    """
    specs: List[FaultSpec] = []
    seed = None
    for clause in text.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if clause.startswith("seed="):
            seed = int(clause[len("seed="):])
            continue
        head, _, tail = clause.partition(",")
        parts = head.strip().split(":")
        if len(parts) < 2:
            raise ValueError(
                f"fault clause {clause!r}: expected site:kind[:prob][,k=v...]"
            )
        kw = {"site": parts[0].strip(), "kind": parts[1].strip()}
        if len(parts) > 2:
            kw["prob"] = float(parts[2])
        if len(parts) > 3:
            raise ValueError(f"fault clause {clause!r}: too many ':' fields")
        for item in tail.split(","):
            item = item.strip()
            if not item:
                continue
            key, _, value = item.partition("=")
            key = key.strip()
            value = value.strip()
            if key == "start":
                kw["start"] = int(value)
            elif key == "stop":
                kw["stop"] = int(value)
            elif key == "max":
                kw["max_fires"] = int(value)
            elif key == "uid":
                kw["match_uid"] = int(value)
            elif key == "hang_s":
                kw["hang_s"] = float(value)
            elif key == "attributed":
                kw["attributed"] = value.lower() in ("1", "true", "yes")
            else:
                raise ValueError(
                    f"fault clause {clause!r}: unknown key {key!r}"
                )
        specs.append(FaultSpec(**kw))
    return specs, seed


class StepWatchdog:
    """Wall-clock budget for one dispatch: ``open()`` before, ``check()``
    after the device sync — raises :class:`StepTimeout` when the dispatch
    (including any injected hang) overran.

    The check runs BEFORE any token is appended or streamed, so a timed-out
    step emits nothing and the recovery rebuild replays it cleanly — a hang
    converts into exactly the step-failure path (docs/resilience.md). The
    clock is injectable for tests.

    **Post-hoc by design**: the check fires only once the dispatch RETURNS —
    an overrun that eventually completes (transient device stall, injected
    hang) is caught and replayed, but a dispatch that never returns is never
    checked and blocks the process. Protection against truly-wedged processes
    is the supervisor layer's job (``ElasticSupervisor(attempt_timeout=...)``,
    which tears the whole gang down from outside)."""

    def __init__(self, budget_s: float, clock=None):
        if budget_s <= 0:
            raise ValueError(f"budget_s={budget_s} must be > 0")
        self.budget_s = float(budget_s)
        self._clock = resolve_clock(clock)
        self.timeouts = 0

    def open(self) -> float:
        return self._clock()

    def check(self, t0: float, site: str = "serving.decode") -> None:
        elapsed = self._clock() - t0
        if elapsed > self.budget_s:
            self.timeouts += 1
            raise StepTimeout(site, elapsed, self.budget_s)
