"""Fault tolerance: deterministic fault injection + recovery primitives.

The serving/training stack built in PRs 5-8 assumed the happy path: one
exception inside ``ContinuousBatcher.step()`` killed the process and every
in-flight request with it, checkpoints had no integrity story, and the elastic
supervisor hammered restarts back-to-back. This package is the failure-path
counterpart (docs/resilience.md):

- :mod:`~accelerate_tpu.resilience.faults` — a seed-driven :class:`FaultPlan`
  that injects failures (step exceptions, dispatch hangs, non-finite values,
  KV-pool allocation failures, checkpoint corruption) at named sites, so every
  recovery path in the stack is exercised deterministically in CI instead of
  discovered in production. Threaded via ``ACCELERATE_FAULTS`` / a
  ``FaultConfig`` riding ``AcceleratorState`` like the telemetry/gateway
  configs; zero overhead when disabled.

The recovery machinery itself lives where the state lives: the serving engine's
fault boundary + quarantine/bisection (``serving.ContinuousBatcher``), the
gateway's circuit breaker + request replay (``serving_gateway``), verified
checkpoints (``checkpointing``), and supervisor backoff/liveness
(``elastic``). ``serve-bench --chaos`` replays a workload trace under an
injected plan and stamps the recovery evidence into ``BENCH_CHAOS.json``.
"""

from .faults import (
    EngineCrashed,
    FaultError,
    FaultPlan,
    FaultSpec,
    InjectedFault,
    NonFiniteStepError,
    StageCrashed,
    StepTimeout,
    StepWatchdog,
    parse_fault_spec,
)

__all__ = [
    "EngineCrashed",
    "FaultError",
    "FaultPlan",
    "FaultSpec",
    "InjectedFault",
    "NonFiniteStepError",
    "StageCrashed",
    "StepTimeout",
    "StepWatchdog",
    "parse_fault_spec",
]
