"""Scheduler wrapper (reference ``scheduler.py``, 98 LoC).

Two scheduler styles are supported:

- **optax schedules** (functions ``step -> lr``) baked into the transformation: nothing to
  wrap — the schedule reads the optimizer step count, which only advances on sync steps, so
  the reference's "don't step the LR during accumulation" behavior (:54) is automatic.
- **stateful schedulers** (objects with ``.step()``/``.get_last_lr()``, e.g. torch or
  user-written): ``AcceleratedScheduler`` steps them only when the optimizer really stepped,
  and ``num_processes``× when the batch size scales with world size
  (``split_batches=False``, reference ``:70-82``).
"""

from __future__ import annotations

from .state import AcceleratorState, GradientState

__all__ = ["AcceleratedScheduler"]


class AcceleratedScheduler:
    def __init__(
        self,
        scheduler,
        optimizers,
        step_with_optimizer: bool = True,
        split_batches: bool = False,
    ):
        self.scheduler = scheduler
        self.optimizers = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
        self.split_batches = split_batches
        self.step_with_optimizer = step_with_optimizer
        self.gradient_state = GradientState()

    def step(self, *args, **kwargs):
        if not self.step_with_optimizer:
            self.scheduler.step(*args, **kwargs)
            return
        if not self.gradient_state.sync_gradients:
            # Keep torch-style schedulers' internal call counter in step with the number of
            # .step() calls even when the LR update is skipped (reference scheduler.py:63).
            if self.gradient_state.adjust_scheduler and hasattr(self.scheduler, "_step_count"):
                self.scheduler._step_count += 1
            return
        # Skip if any wrapped optimizer skipped (overflow).
        for opt in self.optimizers:
            if getattr(opt, "step_was_skipped", False):
                return
        if self.split_batches:
            self.scheduler.step(*args, **kwargs)
        else:
            num_processes = AcceleratorState().num_processes if AcceleratorState._shared_state else 1
            for _ in range(num_processes):
                self.scheduler.step(*args, **kwargs)

    def get_last_lr(self):
        return self.scheduler.get_last_lr()

    def state_dict(self):
        return self.scheduler.state_dict()

    def load_state_dict(self, state_dict):
        self.scheduler.load_state_dict(state_dict)

    def get_lr(self):
        return self.scheduler.get_lr()

    def __getattr__(self, name):
        return getattr(self.__dict__["scheduler"], name)
