"""Local SGD: skip cross-host synchronization for K steps, then average parameters.

Reference: ``local_sgd.py`` (``LocalSGD`` ctx manager, ``_sync_and_avg_model_params``
``local_sgd.py:102``) — there it enters ``no_sync()`` so DDP's bucketed all-reduce is skipped
and periodically all-reduce-averages ``model.parameters()``.

TPU-native translation: inside one jitted GSPMD program over a global mesh the gradient
all-reduce is inserted by XLA and is effectively free over ICI — there is nothing to skip.
What local SGD buys on TPU pods is *skipping the DCN hop*: each host trains on its local
devices (a host-local mesh / independent train state) and every ``local_sgd_steps`` steps the
parameter pytrees are averaged across hosts over DCN. This class implements that contract: it
counts steps and, at each boundary (and on exit), mean-reduces the provided train state's
params across processes via the host-level collective layer (``utils.operations.reduce``).

On a single process (or when ``enabled=False``) every operation is a no-op, matching the
reference's behavior under ``DistributedType.NO``.
"""

from __future__ import annotations

from typing import Any, Optional

from .state import PartialState
from .utils.operations import reduce as _reduce


class LocalSGD:
    """Context manager mirroring reference ``local_sgd.py:20``.

    Usage::

        with LocalSGD(accelerator=acc, state_getter=lambda: state,
                      state_setter=new, local_sgd_steps=8) as local_sgd:
            for batch in dl:
                state, metrics = step(state, batch)
                state = local_sgd.step(state)
    """

    def __init__(
        self,
        accelerator=None,
        model: Any = None,
        local_sgd_steps: int = 8,
        enabled: bool = True,
    ):
        partial = PartialState()
        self.enabled = enabled and partial.use_distributed and partial.num_processes > 1
        self.num_steps = 0
        self.accelerator = accelerator
        self.model = model
        if self.enabled:
            self.local_sgd_steps = local_sgd_steps

    def __enter__(self):
        if self.enabled:
            self.model_sync_obj = self.model
        return self

    def __exit__(self, type, value, tb):
        if self.enabled:
            # Ensure hosts end on identical parameters (reference ``local_sgd.py:58``).
            self._last = self._sync_and_avg_model_params(self._last) if hasattr(self, "_last") else None

    def step(self, state_or_params: Optional[Any] = None):
        """Count one optimizer step; average params across hosts at each boundary.

        Returns the (possibly averaged) state/params so the functional training loop can
        carry it forward — the one deviation from the reference's in-place API.
        """
        self.num_steps += 1
        if not self.enabled:
            return state_or_params
        self._last = state_or_params
        if self.num_steps % self.local_sgd_steps == 0:
            out = self._sync_and_avg_model_params(state_or_params)
            self._last = out
            return out
        return state_or_params

    def _sync_and_avg_model_params(self, state_or_params):
        """Mean of the parameter pytree across processes (reference ``local_sgd.py:102``)."""
        if state_or_params is None:
            return None
        if hasattr(state_or_params, "params") and hasattr(state_or_params, "replace"):
            averaged = _reduce(state_or_params.params, reduction="mean")
            return state_or_params.replace(params=averaged)
        return _reduce(state_or_params, reduction="mean")
