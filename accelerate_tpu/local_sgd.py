"""Local SGD: skip cross-host synchronization for K steps, then average parameters.

Reference: ``local_sgd.py`` (``LocalSGD`` ctx manager, ``_sync_and_avg_model_params``
``local_sgd.py:102``) — there it enters ``no_sync()`` so DDP's bucketed all-reduce is skipped
and periodically all-reduce-averages ``model.parameters()``.

TPU-native translation: inside one jitted GSPMD program over a global mesh the gradient
all-reduce is inserted by XLA and is effectively free over ICI — there is nothing to skip.
What local SGD buys on TPU pods is *skipping the DCN hop*: each host trains on its local
devices (a host-local mesh / independent train state) and every ``local_sgd_steps`` steps the
parameter pytrees are averaged across hosts over DCN. The averaging is a host-level collective
on fully process-addressable leaves (device_get → byte all-gather → mean → device_put back with
each leaf's original sharding), so it is correct for leaves that are sharded across the host's
local devices — unlike a batch-style ``reduce``, which reinterprets the leading dim.

On a single process (or when ``enabled=False``) every operation is a no-op, matching the
reference's behavior under ``DistributedType.NO``.
"""

from __future__ import annotations

import pickle
from typing import Any, Optional

import numpy as np

from .state import PartialState


class LocalSGD:
    """Context manager mirroring reference ``local_sgd.py:20``.

    Usage::

        with LocalSGD(accelerator=acc, local_sgd_steps=8) as local_sgd:
            for batch in dl:
                state, metrics = step(state, batch)
                state = local_sgd.step(state)
        state = local_sgd.final_state or state  # hosts end on identical parameters

    The functional deviation from the reference's in-place API: ``step`` *returns* the
    (possibly averaged) state, and the exit-time final sync is exposed as ``final_state``
    (a context manager's ``__exit__`` cannot rebind the caller's variable).
    """

    def __init__(
        self,
        accelerator=None,
        model: Any = None,
        local_sgd_steps: int = 8,
        enabled: bool = True,
    ):
        partial = PartialState()
        self.enabled = enabled and partial.use_distributed and partial.num_processes > 1
        self.num_steps = 0
        self.accelerator = accelerator
        self.model = model
        self.final_state = None
        self._last = None
        if self.enabled:
            self.local_sgd_steps = local_sgd_steps

    def __enter__(self):
        if self.enabled:
            self.model_sync_obj = self.model
        return self

    def __exit__(self, type, value, tb):
        if self.enabled and self._last is not None:
            # Ensure hosts end on identical parameters (reference ``local_sgd.py:58``).
            # Exposed as .final_state — callers carry it into their loop variable.
            self.final_state = self._sync_and_avg_model_params(self._last)

    def step(self, state_or_params: Optional[Any] = None):
        """Count one optimizer step; average params across hosts at each boundary.

        Returns the (possibly averaged) state/params so the functional training loop can
        carry it forward.
        """
        self.num_steps += 1
        if not self.enabled:
            return state_or_params
        self._last = state_or_params
        if self.num_steps % self.local_sgd_steps == 0:
            out = self._sync_and_avg_model_params(state_or_params)
            self._last = out
            return out
        return state_or_params

    def sync(self, state_or_params):
        """Force a cross-host parameter average now (explicit final-sync helper)."""
        out = self._sync_and_avg_model_params(state_or_params)
        self._last = out
        return out

    def _sync_and_avg_model_params(self, state_or_params):
        """Mean of the parameter pytree across processes (reference ``local_sgd.py:102``)."""
        if state_or_params is None or not self.enabled:
            return state_or_params
        if hasattr(state_or_params, "params") and hasattr(state_or_params, "replace"):
            averaged = _mean_params_across_processes(state_or_params.params)
            return state_or_params.replace(params=averaged)
        return _mean_params_across_processes(state_or_params)


def _mean_params_across_processes(params):
    """Sharding-preserving cross-process mean of a parameter pytree.

    All leaves are pulled to host (host-local meshes are fully addressable per process) and
    byte-all-gathered in ONE collective — a whole-pytree payload, not one round-trip per leaf —
    then averaged in fp32 and put back with each leaf's original sharding.
    """
    import jax

    from .utils.operations import _allgather_bytes

    leaves, treedef = jax.tree_util.tree_flatten(params)
    host = [np.asarray(jax.device_get(x)) if hasattr(x, "shape") else x for x in leaves]
    gathered = [pickle.loads(p) for p in _allgather_bytes(pickle.dumps(host))]
    if len(gathered) == 1:
        return params

    averaged = []
    for i, leaf in enumerate(leaves):
        if not hasattr(leaf, "shape"):
            averaged.append(leaf)
            continue
        stack = np.stack([np.asarray(g[i], dtype=np.float32) for g in gathered])
        mean = np.mean(stack, axis=0).astype(host[i].dtype)
        if isinstance(leaf, jax.Array):
            mean = jax.device_put(mean, leaf.sharding)
        averaged.append(mean)
    return jax.tree_util.tree_unflatten(treedef, averaged)
