"""Function launchers: ``notebook_launcher`` / ``debug_launcher`` (reference ``launchers.py:40,268``).

TPU-native semantics: on a machine with TPU chips attached, ONE process drives all local chips
through the mesh, so ``notebook_launcher`` simply calls the function (the reference's
``xmp.spawn`` fork-vs-spawn dance does not exist under JAX). Multi-*process* spawning — the
reference's multi-GPU path — remains for CPU-backend simulation of multi-host topologies:
N processes rendezvous through a localhost JAX coordinator (the torchrun-elastic analog, with
``max_restarts`` retries of the whole group).
"""

from __future__ import annotations

import os
import sys
import time
from typing import Any, Optional

from .utils.launch import PrepareForLaunch
from .utils.other import get_free_port

__all__ = ["notebook_launcher", "debug_launcher"]


def notebook_launcher(
    function,
    args: tuple = (),
    num_processes: Optional[int] = None,
    mixed_precision: str = "no",
    use_port: str | int | None = None,
    master_addr: str = "127.0.0.1",
    node_rank: int = 0,
    num_nodes: int = 1,
    max_restarts: int = 0,
    monitor_interval: float = 0.1,
    devices_per_process: Optional[int] = None,
    **kwargs: Any,
) -> None:
    """Launch ``function(*args)`` for (notebook) training.

    - TPU backend present → run in-process: the mesh already spans every local chip.
    - ``num_processes > 1`` on CPU → spawn that many processes with a JAX distributed
      rendezvous (faithful multi-host simulation; reference ``launchers.py:40`` spawns GPUs).
    - ``devices_per_process``: virtual CPU devices per child
      (``--xla_force_host_platform_device_count``) — N processes × M devices simulates an
      N-host M-chip pod, the test substrate for true multi-process collectives.
    """
    in_colab_or_kaggle = "KAGGLE_KERNEL_RUN_TYPE" in os.environ or "COLAB_GPU" in os.environ
    _ = in_colab_or_kaggle  # same environments supported; no special-casing needed under JAX

    if mixed_precision and mixed_precision != "no":
        os.environ["ACCELERATE_MIXED_PRECISION"] = str(mixed_precision).lower()

    backend_is_tpu = False
    try:
        import jax

        backend_is_tpu = jax.default_backend() not in ("cpu",)
    except Exception:  # pragma: no cover
        pass

    if backend_is_tpu or not num_processes or num_processes == 1:
        function(*args)
        return

    import multiprocessing

    port = use_port or get_free_port()
    coordinator = f"{master_addr}:{port}"
    launcher = PrepareForLaunch(
        function,
        num_processes=num_processes,
        coordinator_address=coordinator,
        use_cpu=True,
        devices_per_process=devices_per_process,
    )
    ctx = multiprocessing.get_context("spawn")
    for attempt in range(max_restarts + 1):
        procs = []
        for index in range(num_processes):
            p = ctx.Process(target=launcher, args=(index, *args))
            p.start()
            procs.append(p)
        while any(p.is_alive() for p in procs):
            time.sleep(monitor_interval)
        codes = [p.exitcode for p in procs]
        if all(c == 0 for c in codes):
            return
        if attempt < max_restarts:
            print(f"[notebook_launcher] exit codes {codes}; restart {attempt + 1}/{max_restarts}")
            port = get_free_port()
            launcher.coordinator_address = f"{master_addr}:{port}"
            continue
        raise RuntimeError(f"Launched processes failed with exit codes {codes}")


def debug_launcher(function, args: tuple = (), num_processes: int = 2) -> None:
    """CPU-only multi-process launch for unit tests (reference ``launchers.py:268``)."""
    from .utils.environment import patch_environment

    with patch_environment(ACCELERATE_USE_CPU="true", JAX_PLATFORMS="cpu"):
        notebook_launcher(function, args, num_processes=num_processes)


def _child_main():  # pragma: no cover - executed only in spawned children
    pass


if __name__ == "__main__":  # pragma: no cover
    sys.exit(0)
