"""Persistent AOT compilation cache (L10): kill the cold-start recompile tax.

Every process start used to re-pay every XLA compile (PERF_NOTES: entire TPU
windows were spent compiling never-before-compiled programs; serving cold
starts re-jit prefill/decode per prompt length). This package makes compiled
executables a durable artifact instead:

- :class:`AotCache` / :class:`CachedFunction` (``cache.py``) — content-addressed
  store of serialized executables keyed by lowered-program fingerprint +
  backend environment; wraps the jits built by ``Accelerator.build_train_step``
  / ``build_eval_step`` and the serving programs. Stale entries fall back to
  live compile, never fail a step.
- :mod:`.fingerprint` — the cache key anatomy (docs/compile_cache.md).
- :mod:`.buckets` — shape-bucket selection for bucketed serving prefill.
- :mod:`.warmup` — ``python -m accelerate_tpu warmup``: enumerate + pre-compile
  a config's programs so a tunnel window or serving replica starts hot.

Enable via ``Accelerator(compile_cache_config=CompileCacheConfig(enabled=True))``
or ``ACCELERATE_COMPILE_CACHE=1`` (a path value also sets the directory).
"""

from ..utils.dataclasses import CompileCacheConfig
from .buckets import pick_bucket
from .cache import AotCache, CachedFunction, as_cached
from .fingerprint import backend_environment, fingerprint, signature_key
from .warmup import build_drafter, build_model_config, run_warmup

__all__ = [
    "AotCache",
    "CachedFunction",
    "CompileCacheConfig",
    "as_cached",
    "backend_environment",
    "build_drafter",
    "build_model_config",
    "fingerprint",
    "pick_bucket",
    "run_warmup",
    "signature_key",
]
