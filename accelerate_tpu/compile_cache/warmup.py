"""Warmup manifests: enumerate + pre-compile a config's programs into the cache.

``python -m accelerate_tpu warmup`` drives this. For one (model config, batch
geometry, serving geometry) it builds the exact programs a training run or a
serving replica would compile lazily — train micro/apply (or fused) step, eval
step, one prefill per shape bucket, the chunk-append program, the decode step,
the per-slot row inserts — and pushes each through ``AotCache`` WITHOUT
executing them (``lower().compile()`` + serialize, never dispatch). A tunnel
window or replica that starts afterwards deserializes instead of compiling:
cold start stops scaling with program count.

The resulting manifest (``<cache_dir>/warmup_manifest.json`` by default) lists
every program's label, cache key and status — the auditable record of what a
cache directory is warm FOR.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

import numpy as np

from ..logging import get_logger
from ..utils.dataclasses import CompileCacheConfig

logger = get_logger(__name__)

__all__ = ["build_model_config", "build_drafter", "run_warmup", "write_manifest"]

MANIFEST_SCHEMA = "accelerate_tpu.compile_cache.warmup/v1"
MANIFEST_NAME = "warmup_manifest.json"


def build_model_config(preset: str, seq_len: int):
    """A llama config for ``preset`` (a ``llama.CONFIGS`` key, or ``smoke`` — the
    bench.py CI shape) with ``max_seq`` set for the warmed geometry."""
    import jax.numpy as jnp

    from ..models import llama

    if preset == "smoke":
        cfg = dataclasses.replace(
            llama.CONFIGS["tiny"], vocab_size=512, d_model=128, n_layers=2,
            n_heads=4, n_kv_heads=2, d_ff=256,
        )
    elif preset in llama.CONFIGS:
        cfg = llama.CONFIGS[preset]
    else:
        raise ValueError(
            f"unknown preset {preset!r}; expected 'smoke' or one of "
            f"{sorted(llama.CONFIGS)}"
        )
    if cfg.dtype == jnp.bfloat16 and preset == "smoke":
        cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    return dataclasses.replace(cfg, max_seq=seq_len)


def build_drafter(spec_draft: Optional[str], target_params, target_cfg):
    """A ``spec_decode.DraftSource`` for one warmup/bench geometry: ``None``/"ngram"
    → the model-free prompt-lookup drafter (no extra programs); ``"half"`` → a
    half-depth copy of the target config with fresh params (vocabulary-compatible by
    construction — the standard CI shape for exercising the draft-model program
    surface without a second checkpoint)."""
    from ..spec_decode import ModelDrafter, NgramDrafter

    if spec_draft in (None, "ngram"):
        return NgramDrafter()
    if spec_draft == "half":
        from ..models import llama

        d_cfg = dataclasses.replace(
            target_cfg, n_layers=max(1, target_cfg.n_layers // 2)
        )
        return ModelDrafter(llama.init_params(d_cfg), d_cfg)
    raise ValueError(f"spec_draft={spec_draft!r}: expected 'ngram' or 'half'")


def run_warmup(
    *,
    preset: str = "smoke",
    batch_size: int = 8,
    seq_len: int = 128,
    fused_steps: int = 1,
    grad_accum: int = 1,
    mixed_precision: Optional[str] = None,
    train: bool = True,
    eval_step: bool = False,
    serve: bool = False,
    max_slots: int = 4,
    max_len: Optional[int] = None,
    max_new_tokens: int = 32,
    spec_k: int = 0,
    spec_draft: Optional[str] = None,
    page_size: int = 0,
    kv_pages: Optional[int] = None,
    prefix_cache: int = 0,
    role: str = "mixed",
    decode_steps: int = 1,
    cache_config: Optional[CompileCacheConfig] = None,
    manifest_path: Optional[str] = None,
    cache=None,
    emit_manifest: bool = True,
) -> dict:
    """Pre-compile the programs for one config into the AOT cache.

    Returns the manifest dict (also written to ``manifest_path`` /
    ``<cache_dir>/warmup_manifest.json``). Uses concrete dummy inputs placed
    through the SAME data paths the real run uses (mesh-sharded batches, engine
    cache layouts), so the fingerprints match what ``Accelerator`` /
    ``ContinuousBatcher`` will look up.

    ``cache`` injects a pre-built ``AotCache`` (the program auditor passes a
    ``LowerOnlyCache`` so the SAME enumeration feeds graftaudit without
    compiling anything); ``emit_manifest=False`` skips the manifest file for
    such in-memory uses. Every program's audit provenance (collective
    inventory, donation effectiveness) is stamped into the manifest under
    ``program_audit`` and emitted as telemetry records when telemetry is on.
    """
    from ..accelerator import Accelerator
    from ..models import llama

    config = cache.config if cache is not None else (
        cache_config or CompileCacheConfig(enabled=True)
    )
    if not config.enabled:
        raise ValueError("warmup needs an enabled CompileCacheConfig")

    if spec_k and not serve:
        raise ValueError(
            "spec_k was given but serve=False: no verify/draft programs would be "
            "warmed and the manifest would silently stamp spec_k=0 — pass "
            "serve=True (--serve) to warm the speculative surface"
        )
    if (page_size or prefix_cache) and not serve:
        raise ValueError(
            "page_size/prefix_cache were given but serve=False: no paged/prefix "
            "serving programs would be warmed — pass serve=True (--serve)"
        )
    if role != "mixed" and not serve:
        raise ValueError(
            f"role={role!r} was given but serve=False: no role-sliced serving "
            "programs would be warmed — pass serve=True (--serve)"
        )
    if decode_steps > 1 and not serve:
        raise ValueError(
            f"decode_steps={decode_steps} was given but serve=False: no multi-"
            "step super-step programs would be warmed — pass serve=True (--serve)"
        )
    cfg = build_model_config(preset, seq_len)
    entries: list = []

    accelerator = Accelerator(
        mixed_precision=mixed_precision,
        gradient_accumulation_steps=grad_accum,
        compile_cache_config=config,
    )
    if cache is not None:
        # Injected cache (audit / tests): every jit the accelerator wraps from
        # here on routes through it instead of the one built from the config.
        accelerator.compile_cache = cache
    else:
        cache = accelerator.compile_cache
    if cache.capture is None:
        cache.capture = []  # arm program capture: the manifest stamps audit provenance
    if not cache.enabled:
        # An unsupported jax degrades the cache to live compiles — fine for a
        # training run, but a warmup whose whole purpose is priming the cache
        # must fail loudly, not exit 0 with an empty manifest.
        raise RuntimeError(
            "warmup cannot populate the compile cache: this jax exposes no "
            "executable serialization API (jax.experimental.serialize_executable)"
        )
    params = llama.init_params(cfg)

    eval_params = None
    if train:
        import optax

        state = accelerator.create_train_state(params, optax.adamw(1e-4))
        step = accelerator.build_train_step(
            lambda p, b: llama.loss_fn(p, b, cfg),
            max_grad_norm=1.0,
            fused_steps=fused_steps,
        )
        tokens = np.zeros((batch_size, seq_len + 1), np.int32)
        if fused_steps > 1:
            batches = [{"tokens": tokens} for _ in range(fused_steps)]
            entries.extend(step.warm(state, batches))
        else:
            from ..data_loader import assemble_global_batch

            batch = assemble_global_batch({"tokens": tokens}, accelerator.mesh)
            entries.extend(step.warm(state, batch))
        eval_params = state.params
    if eval_step:
        from ..data_loader import assemble_global_batch

        if eval_params is None:
            # --no-train: prepare params exactly as create_train_state would, so
            # the eval fingerprint matches a real run's state.params.
            eval_params = accelerator.prepare_params(params)
        evaluate = accelerator.build_eval_step(lambda p, b: llama.loss_fn(p, b, cfg))
        batch = assemble_global_batch(
            {"tokens": np.zeros((batch_size, seq_len + 1), np.int32)},
            accelerator.mesh,
        )
        entries.append(evaluate.warm(eval_params, batch))

    if serve:
        from ..serving import ContinuousBatcher

        engine_len = max_len if max_len is not None else seq_len
        # Speculative serving surface: ``spec_k > 0`` adds the fused [B, spec_k+1]
        # verify program and — with ``spec_draft="half"`` — a half-depth draft model's
        # prefill/decode/insert programs. Both ride the same bucket ladder and land in
        # this manifest, so a spec-enabled replica restart compiles nothing.
        drafter = build_drafter(spec_draft, params, cfg) if spec_k else None
        # ``page_size > 0`` warms the PAGED serving surface (block-table decode/
        # verify, dynamic-slot page scatter, prefix gather/copy) — the manifest
        # stamps the page geometry so a cache directory is auditable for which
        # KV layout it is warm FOR.
        # ``role`` warms one DISAGG slice of the surface (docs/
        # disaggregated_serving.md): a decode-role replica's directory holds
        # NO prefill programs at all (handoff import + COW copy + lane-valid
        # setup instead), a prefill-role one swaps decode/verify for the page
        # export gather — the manifest records which slice it is warm FOR.
        # ``decode_steps > 1`` adds the multi-step super-step pair (both sample
        # variants, dense or paged per the layout above) to the warmed surface;
        # combined with ``spec_k > 0`` and a resident drafter it ALSO warms the
        # fused speculative super-step pair (``serving.spec_multi[_paged]``) —
        # the manifest's ``spec_fused`` records which geometry that is.
        engine = ContinuousBatcher(
            params, cfg, max_slots=max_slots, max_len=engine_len,
            compile_cache=cache, spec_k=spec_k, drafter=drafter,
            page_size=page_size, kv_pages=kv_pages, prefix_cache=prefix_cache,
            role=role, decode_steps=decode_steps,
        )
        entries.extend(engine.warm_programs(max_new_tokens=max_new_tokens))

    # Per-program audit provenance: the captures recorded at lowering carry the
    # jaxpr + StableHLO (and compiled HLO on misses), so the manifest records
    # what the cached executables actually DO — collective counts/bytes and
    # whether donation aliased — not just that they exist.
    from ..analysis.program.audit import audit_summaries

    summaries = audit_summaries(cache.capture)
    _emit_audit_telemetry(accelerator, summaries)

    manifest = {
        "schema": MANIFEST_SCHEMA,
        "preset": preset,
        "batch_size": batch_size,
        "seq_len": seq_len,
        "fused_steps": fused_steps,
        "grad_accum": grad_accum,
        "mixed_precision": mixed_precision,
        "serve": serve,
        "max_slots": max_slots,
        "max_len": max_len if max_len is not None else seq_len,
        "spec_k": spec_k if serve else 0,
        "spec_draft": (spec_draft or "ngram") if serve and spec_k else None,
        # Fused speculative super-step geometry: True when this cache directory
        # is warm for ``serving.spec_multi[_paged]`` (spec_k > 0, decode_steps
        # > 1, resident drafter) — the program such an engine dispatches.
        "spec_fused": engine._spec_fused() if serve and spec_k else False,
        "page_size": page_size if serve else 0,
        "kv_pages": (
            engine.block_mgr.num_pages if serve and page_size else None
        ),
        "prefix_cache": prefix_cache if serve else 0,
        "role": role if serve else "mixed",
        "decode_steps": decode_steps if serve else 1,
        "cache_dir": cache.cache_dir,
        "cache_stats": cache.stats(),
        "programs": [e for e in entries if e],
        "program_audit": summaries,
    }
    if emit_manifest:
        write_manifest(
            manifest, manifest_path or os.path.join(cache.cache_dir, MANIFEST_NAME)
        )
    return manifest


def _emit_audit_telemetry(accelerator, summaries: list) -> None:
    """Route per-program audit summaries into telemetry (bench rows diff comms
    across PRs from these records). No-op when telemetry is off."""
    telemetry = getattr(accelerator, "telemetry", None)
    if telemetry is None or not getattr(telemetry, "enabled", False):
        return
    from ..telemetry.schemas import AUDIT_PROGRAM_SCHEMA

    for s in summaries:
        telemetry.emit({
            "schema": AUDIT_PROGRAM_SCHEMA,
            "label": s["label"],
            "collectives": s["collectives"],
            "donation": s["donation"],
            "memory": s["memory"],
        })


def write_manifest(manifest: dict, path: str) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as f:
        json.dump(manifest, f, indent=2)
    logger.info("warmup manifest written to %s (%d programs)",
                path, len(manifest["programs"]))

