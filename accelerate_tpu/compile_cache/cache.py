"""Persistent AOT executable cache: compile once per fleet, not once per process.

Every process start re-pays every XLA compile (PERF_NOTES: two full TPU windows
were lost to compiles of never-before-compiled programs). This module closes
that hole at the executable level:

- :class:`AotCache` — a content-addressed store of serialized compiled
  executables under ``CompileCacheConfig.cache_dir``. Keys come from
  :mod:`.fingerprint` (lowered StableHLO + jax/jaxlib versions + backend
  topology + compiler flags), so a key hit is safe to execute and anything
  environment-drifted is a clean miss.
- :class:`CachedFunction` — the callable ``AotCache.wrap`` returns around a
  ``jax.jit`` object. First call per signature lowers the program (cheap —
  tracing, no XLA), consults the cache, and thereafter dispatches straight to
  the loaded/compiled executable. Any deserialize/topology/dispatch mismatch
  falls back to the live ``jax.jit`` path — a stale cache can never fail a
  step.

Cache events (hit/miss + deserialize time) flow into the telemetry pipeline via
``telemetry.compile_monitor.dispatch_cache_event`` so ``CompileMonitor``
snapshots attribute cold-start spend.
"""

from __future__ import annotations

import os
import pickle
import tempfile
import time
from typing import Any, Callable, Optional

from ..logging import get_logger
from ..utils.dataclasses import CompileCacheConfig
from ..utils.jax_compat import (
    deserialize_executable,
    executable_serialization_supported,
    serialize_executable,
)
from .fingerprint import backend_environment, fingerprint, signature_key

logger = get_logger(__name__)

__all__ = ["AotCache", "CachedFunction"]

#: On-disk entry schema; bump on any layout change (old entries become misses).
ENTRY_SCHEMA = "accelerate_tpu.compile_cache/v1"

#: Per-signature sentinel: this signature permanently uses the live jit path.
_LIVE = object()


def _dispatch_cache_event(hit: bool, deserialize_s: float = 0.0) -> None:
    """Route a cache event into live CompileMonitors (no-op without telemetry)."""
    try:
        from ..telemetry.compile_monitor import dispatch_cache_event
    except ImportError:  # pragma: no cover - telemetry always ships alongside
        return
    dispatch_cache_event(hit, deserialize_s)


class AotCache:
    """Content-addressed persistent store of serialized XLA executables.

    Construction is cheap and never touches disk; the directory is created on
    the first write. A disabled config (or a jax without executable
    serialization) makes :meth:`wrap` the identity — zero overhead, zero
    behavior change.
    """

    def __init__(self, config: Optional[CompileCacheConfig] = None):
        self.config = config or CompileCacheConfig()
        self.supported = executable_serialization_supported()
        self.enabled = bool(self.config.enabled) and self.supported
        if self.config.enabled and not self.supported:
            logger.warning(
                "compile cache requested but this jax exposes no executable "
                "serialization API; running with live compiles"
            )
        self.cache_dir = self.config.cache_dir
        # Counters (mirrored into telemetry CompileMonitor snapshots).
        self.hits = 0
        self.misses = 0
        self.failures = 0          # poisoned/mismatched entries that fell back
        self.deserialize_ms = 0.0
        self.compile_s = 0.0
        self._memo: dict = {}      # fingerprint -> loaded executable (cross-wrapper)
        #: When a list, every lowering routed through this cache appends a
        #: ``analysis.program.ProgramCapture`` — the hook the program auditor
        #: (graftaudit) and the warmup manifest's audit stamp hang off.
        self.capture = None

    # ------------------------------------------------------------------ public API
    def stats(self) -> dict:
        """JSON-serializable counter snapshot (bench rows, telemetry records)."""
        return {
            "enabled": self.enabled,
            "hits": self.hits,
            "misses": self.misses,
            "failures": self.failures,
            "deserialize_ms": round(self.deserialize_ms, 3),
            "compile_s": round(self.compile_s, 3),
        }

    def wrap(self, jitted, label: str, static_argnames: tuple = ()):
        """Wrap a ``jax.jit`` callable so its executables round-trip the cache.

        Disabled caches return ``jitted`` unchanged (the hot path stays the
        C++ jit dispatch). ``static_argnames`` must list the jit's static
        parameters — at call sites they are expected as keywords (the package
        convention), and are stripped before dispatching to the AOT executable
        (statics are baked into it).
        """
        if not self.enabled:
            return jitted
        return CachedFunction(jitted, self, label=label, static_argnames=static_argnames)

    def warm(self, cached_fn: "CachedFunction", *args, **kwargs) -> dict:
        """Populate the cache for one call signature WITHOUT executing.

        Returns the manifest entry: ``{label, key, status, seconds}`` where
        status is ``hit`` (already cached), ``miss`` (compiled + stored) or
        ``live`` (could not be cached; would live-compile at first call).
        """
        return cached_fn.warm(*args, **kwargs)

    def entry_path(self, key: str) -> str:
        return os.path.join(self.cache_dir, f"{key}.aotx")

    # ------------------------------------------------------------------ lowering
    def _lower(self, jitted, args, kwargs, label: str):
        """Lower one call signature, feeding the program-capture hook when armed.

        With ``self.capture`` set (a list), the traced jaxpr and any lower-time
        warnings (jax's "donated buffers were not usable" fires here) are
        recorded alongside the lowered program — the raw material of the
        graftaudit rules (``analysis/program/``)."""
        if self.capture is None:
            return jitted.lower(*args, **kwargs)
        from ..analysis.program.capture import capture_lowering

        lowered, entry = capture_lowering(jitted, args, kwargs, label)
        self.capture.append(entry)
        return lowered

    # ------------------------------------------------------------------ internals
    def _load_or_compile(self, jitted, args, kwargs, label: str):
        """(executable_or_None, manifest_info). Never raises: every failure path
        degrades to live compile (None) or a fresh compile overwriting the bad
        entry."""
        try:
            lowered = self._lower(jitted, args, kwargs, label)
            key = fingerprint(lowered.as_text())
        except Exception as exc:  # noqa: BLE001 - any unlowerable call goes live
            logger.warning("compile cache: lowering %s failed (%s); using live jit",
                           label, type(exc).__name__)
            return None, {"label": label, "key": None, "status": "live", "seconds": 0.0}
        memo = self._memo.get(key)
        if memo is not None:
            return memo, {"label": label, "key": key, "status": "memo", "seconds": 0.0}

        path = self.entry_path(key)
        if os.path.exists(path):
            t0 = time.perf_counter()
            try:
                with open(path, "rb") as f:
                    entry = pickle.load(f)
                if entry.get("schema") != ENTRY_SCHEMA or entry.get("key") != key:
                    raise ValueError("entry schema/key mismatch")
                exe = deserialize_executable(
                    entry["payload"], entry["in_tree"], entry["out_tree"]
                )
                dt = time.perf_counter() - t0
                self.hits += 1
                self.deserialize_ms += dt * 1e3
                self._memo[key] = exe
                self._attach_compiled(lowered, exe)
                _dispatch_cache_event(hit=True, deserialize_s=dt)
                return exe, {
                    "label": label, "key": key, "status": "hit",
                    "seconds": round(dt, 6),
                }
            except Exception as exc:  # noqa: BLE001 - poisoned entry: fall through
                self.failures += 1
                logger.warning(
                    "compile cache: entry %s for %s unusable (%s: %s); recompiling",
                    key, label, type(exc).__name__, exc,
                )
        t0 = time.perf_counter()
        try:
            compiled = lowered.compile()
        except Exception as exc:  # noqa: BLE001 - AOT compile refused: live path
            logger.warning("compile cache: AOT compile of %s failed (%s); using live jit",
                           label, type(exc).__name__)
            return None, {"label": label, "key": key, "status": "live", "seconds": 0.0}
        dt = time.perf_counter() - t0
        self.misses += 1
        self.compile_s += dt
        _dispatch_cache_event(hit=False)
        self._memo[key] = compiled
        self._attach_compiled(lowered, compiled)
        self._store(key, label, compiled)
        return compiled, {
            "label": label, "key": key, "status": "miss", "seconds": round(dt, 6),
        }

    def _attach_compiled(self, lowered, executable) -> None:
        """Hand the post-SPMD executable text to the matching capture entry —
        the only representation in which GSPMD-inserted collectives exist."""
        if self.capture is None:
            return
        for entry in reversed(self.capture):
            if entry.lowered is lowered and entry.compiled_text is None:
                try:
                    entry.compiled_text = executable.as_text()
                except Exception:  # noqa: BLE001 - e.g. deserialized exe w/o HLO
                    pass
                return

    def _store(self, key: str, label: str, compiled) -> None:
        """Serialize + atomic-write one entry; storage failures only cost
        persistence, never correctness."""
        try:
            payload, in_tree, out_tree = serialize_executable(compiled)
            # Validate before persisting: an executable that was itself LOADED from
            # jax's persistent compilation cache serializes to an incomplete payload
            # on the CPU backend (object code absent — "Symbols not found" at load).
            # Writing it would poison every later process; skipping just means this
            # program stays served by jax's own cache.
            deserialize_executable(payload, in_tree, out_tree)
            entry = {
                "schema": ENTRY_SCHEMA,
                "key": key,
                "label": label,
                "payload": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
                "env": backend_environment(),
            }
            os.makedirs(self.cache_dir, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self.cache_dir, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    pickle.dump(entry, f)
                os.replace(tmp, self.entry_path(key))  # atomic vs concurrent writers
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except Exception as exc:  # noqa: BLE001 - e.g. unserializable backend
            logger.warning("compile cache: could not persist %s (%s: %s)",
                           label, type(exc).__name__, exc)


class CachedFunction:
    """Callable facade over (jitted, AotCache): per-signature AOT dispatch.

    The first call with a new abstract signature lowers the program and asks
    the cache for its executable; subsequent calls with that signature dispatch
    directly to it. Signatures that cannot be cached (unlowerable, statics
    passed positionally, aval/sharding drift at dispatch) permanently fall back
    to the wrapped ``jax.jit`` for correctness.
    """

    def __init__(self, jitted, cache: AotCache, label: str, static_argnames: tuple = ()):
        self._jitted = jitted
        self._cache = cache
        self.label = label
        self._static = tuple(static_argnames)
        self._execs: dict = {}  # signature key -> executable | _LIVE

    def _dynamic(self, args, kwargs):
        """Strip static keywords (baked into the executable). Returns None when a
        static was passed positionally — we cannot identify it, so the caller
        must use the live path."""
        if not self._static:
            return args, kwargs
        if any(name not in kwargs for name in self._static):
            return None
        return args, {k: v for k, v in kwargs.items() if k not in self._static}

    def _lookup(self, args, kwargs):
        sig = signature_key(args, kwargs)
        exe = self._execs.get(sig)
        if exe is None:
            if self._dynamic(args, kwargs) is None:
                logger.warning(
                    "compile cache: %s called with static args passed positionally; "
                    "using live jit for this signature", self.label,
                )
                exe = _LIVE
            else:
                loaded, _ = self._cache._load_or_compile(
                    self._jitted, args, kwargs, self.label
                )
                exe = loaded if loaded is not None else _LIVE
            self._execs[sig] = exe
        return sig, exe

    def __call__(self, *args, **kwargs):
        sig, exe = self._lookup(args, kwargs)
        if exe is _LIVE:
            return self._jitted(*args, **kwargs)
        dyn = self._dynamic(args, kwargs)
        try:
            return exe(*dyn[0], **dyn[1])
        except (TypeError, ValueError) as exc:
            # Dispatch-time aval/sharding mismatch (raised before execution, so
            # donated buffers are intact): pin this signature to the live path.
            logger.warning(
                "compile cache: cached executable for %s rejected its inputs "
                "(%s: %s); falling back to live jit", self.label,
                type(exc).__name__, exc,
            )
            self._execs[sig] = _LIVE
            return self._jitted(*args, **kwargs)

    def warm(self, *args, **kwargs) -> dict:
        """Prime cache + in-memory dispatch for this signature without executing."""
        sig = signature_key(args, kwargs)
        exe = self._execs.get(sig)
        if exe is not None and exe is not _LIVE:
            return {"label": self.label, "key": None, "status": "memo", "seconds": 0.0}
        if self._dynamic(args, kwargs) is None:
            return {"label": self.label, "key": None, "status": "live", "seconds": 0.0}
        loaded, info = self._cache._load_or_compile(self._jitted, args, kwargs, self.label)
        self._execs[sig] = loaded if loaded is not None else _LIVE
        return info

    # Introspection parity with jax.jit objects used around the codebase.
    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def __repr__(self):
        return f"CachedFunction({self.label!r}, signatures={len(self._execs)})"


def as_cached(fn: Callable, cache: Optional[AotCache], label: str,
              static_argnames: tuple = ()) -> Any:
    """``cache.wrap`` that tolerates ``cache=None`` (returns ``fn`` unchanged)."""
    if cache is None:
        return fn
    return cache.wrap(fn, label, static_argnames=static_argnames)
