"""Deterministic cache keys for AOT-compiled executables.

The key must change whenever the *compiled artifact* could differ and must NOT
change otherwise — a false hit executes the wrong program, a false miss just
re-pays compile. Content-addressing on the lowered StableHLO text gets both
almost for free: the jaxpr, abstract shapes/dtypes and sharding annotations are
all in the text, so any change to the traced program or its layout moves the
key. What the text does NOT carry is the environment the executable was built
against — jax/jaxlib versions, backend platform and device kind, topology
(device/process counts — a 4-chip executable must never load on 8), and the
compiler flag surface — so those are hashed in alongside.
"""

from __future__ import annotations

import hashlib
import os

import jax

__all__ = ["backend_environment", "fingerprint", "signature_key"]

#: Env vars that change what XLA emits; part of every fingerprint.
_COMPILER_ENV_VARS = ("XLA_FLAGS", "LIBTPU_INIT_ARGS")


def backend_environment() -> dict:
    """The environment facts an executable is only valid under."""
    import jaxlib

    device = jax.devices()[0]
    env = {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
        "device_kind": str(getattr(device, "device_kind", "unknown")),
        "device_count": jax.device_count(),
        "process_count": jax.process_count(),
    }
    for var in _COMPILER_ENV_VARS:
        env[var.lower()] = os.environ.get(var, "")
    return env


def fingerprint(lowered_text: str, extra: str = "") -> str:
    """Hex key for a lowered program under the current backend environment."""
    h = hashlib.blake2b(digest_size=20)
    h.update(lowered_text.encode())
    for key, value in sorted(backend_environment().items()):
        h.update(f"{key}={value};".encode())
    if extra:
        h.update(extra.encode())
    return h.hexdigest()


def signature_key(args, kwargs) -> tuple:
    """Hashable per-call signature: abstract (aval, sharding) per array leaf
    plus the leaf itself (or its repr when unhashable) for everything else,
    with the pytree structure.

    This is the in-memory dispatch key a :class:`~.cache.CachedFunction` pays
    on EVERY call (so lowering/fingerprinting runs once per distinct
    signature). Avals, shardings and treedefs hash at C level — the same
    objects jax's own jit dispatch keys on — so no per-call string building.
    """
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = []
    for leaf in leaves:
        if isinstance(leaf, jax.Array):
            sig.append((leaf.aval, leaf.sharding))
        elif hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            # numpy arrays and ShapeDtypeStructs: shape+dtype is their full
            # identity (checked BEFORE hashability — hash(ndarray) raises but
            # repr'ing a large array would be the real cost).
            sig.append((tuple(leaf.shape), str(leaf.dtype)))
        else:
            try:
                hash(leaf)
            except TypeError:
                sig.append(repr(leaf))
            else:
                sig.append(leaf)
    return (treedef, tuple(sig))
