"""Shape-bucket helpers for serving prefill.

A prompt padded to the smallest bucket of a geometric ladder compiles one
prefill executable per *bucket* instead of one per *length* — the ladder is
the whole compile surface, enumerable ahead of time by the warmup manifest.
"""

from __future__ import annotations

from typing import Optional, Sequence

__all__ = ["pick_bucket"]


def pick_bucket(length: int, ladder: Sequence[int]) -> Optional[int]:
    """Smallest ladder bucket that fits ``length`` tokens, or None when the
    prompt exceeds the largest bucket (caller falls back to chunked prefill)."""
    for bucket in ladder:
        if length <= bucket:
            return bucket
    return None
