"""torch ↔ JAX interop bridge — state-dict conversion for migrating reference users.

The reference prepares live torch modules; under a jit/mesh runtime the *computation* must
be a JAX function, so what migrates is the STATE: these helpers convert any torch module's
parameters to a numpy/JAX pytree (nested by the module tree, linear weights transposed to
the ``x @ w`` convention on request) and back. For the shipped model families use the
exact, logits-parity-tested converters in ``models.hf_interop`` instead
(LlamaForCausalLM, GPT2LMHeadModel).
"""

from __future__ import annotations

from typing import Any, Mapping, Optional

import numpy as np

from .utils.imports import is_torch_available

__all__ = [
    "torch_state_dict_to_pytree",
    "pytree_to_torch_state_dict",
    "torch_module_to_pytree",
    "linear_weight_keys",
]


def torch_state_dict_to_pytree(
    state_dict: Mapping[str, Any],
    sep: str = ".",
    linear_keys: Optional[set[str]] = None,
) -> dict:
    """Flat ``{"a.b.weight": tensor}`` → nested ``{"a": {"b": {"weight": array}}}``.

    ``linear_keys``: full key names whose tensors are torch ``Linear`` weights (``[out,
    in]``) to transpose into the ``x @ w`` convention. It must be explicit — "every 2-D
    'weight'" would also transpose embeddings and similar tables, which silently corrupts
    lookups. :func:`torch_module_to_pytree` derives the set from the module types.
    """
    linear_keys = linear_keys or set()
    nested: dict = {}
    for key, value in state_dict.items():
        arr = value.detach().cpu().numpy() if hasattr(value, "detach") else np.asarray(value)
        if key in linear_keys:
            if arr.ndim != 2:
                raise ValueError(f"linear key {key!r} has ndim {arr.ndim}, expected 2")
            arr = arr.T
        node = nested
        parts = key.split(sep)
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = arr
    return nested


def pytree_to_torch_state_dict(
    tree: Any, sep: str = ".", linear_keys: Optional[set[str]] = None
) -> dict:
    """Inverse of :func:`torch_state_dict_to_pytree` (returns torch tensors)."""
    if not is_torch_available():
        raise ImportError("torch is required for pytree_to_torch_state_dict")
    import torch

    linear_keys = linear_keys or set()
    flat: dict = {}

    def walk(node, prefix):
        if isinstance(node, Mapping):
            for k, v in node.items():
                walk(v, f"{prefix}{sep}{k}" if prefix else str(k))
            return
        arr = np.asarray(node)
        if prefix in linear_keys:
            arr = arr.T
        flat[prefix] = torch.from_numpy(np.ascontiguousarray(arr))

    walk(tree, "")
    return flat


def linear_weight_keys(module) -> set[str]:
    """Full state-dict keys of ``nn.Linear`` weights in a module tree."""
    import torch

    return {
        f"{name}.weight" if name else "weight"
        for name, sub in module.named_modules()
        if isinstance(sub, torch.nn.Linear)
    }


def torch_module_to_pytree(module, transpose_linear: bool = False) -> dict:
    """``nn.Module`` → nested numpy pytree of its parameters and buffers.

    ``transpose_linear=True`` transposes exactly the ``nn.Linear`` weights (identified from
    the module types, so embeddings and other 2-D tables are untouched).
    """
    keys = linear_weight_keys(module) if transpose_linear else None
    return torch_state_dict_to_pytree(module.state_dict(), linear_keys=keys)
