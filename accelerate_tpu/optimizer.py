"""Optimizer wrapper (reference ``optimizer.py``, 212 LoC).

``AcceleratedOptimizer`` wraps an ``optax.GradientTransformation``. The reference's core
behaviors map as follows:

- *skip step during accumulation* (reference ``:161``): the jitted train step only applies the
  optax update on sync steps, so the wrapper's ``step()`` is bookkeeping — it mirrors
  ``GradientState.sync_gradients`` and advances the host-side step counter for schedulers.
- *XLA grad all-reduce before step* (reference ``:148-154``): obsolete — GSPMD inserts the
  gradient psum/reduce-scatter automatically from the shardings.
- *GradScaler skipped-step detection* (reference ``:161-176``): the functional dynamic-scale
  path (``precision.DynamicScale``) records ``optimizer_step_was_skipped`` into the train
  state; the wrapper exposes it.
- *device placement of optimizer state* (reference ``:68-74``): opt state is created sharded
  (inherits param shardings — ZeRO-1) by ``Accelerator.prepare``.
"""

from __future__ import annotations



from .state import AcceleratorState, GradientState

__all__ = ["AcceleratedOptimizer"]


def _is_optax_transformation(obj) -> bool:
    return hasattr(obj, "init") and hasattr(obj, "update") and not hasattr(obj, "apply")


class AcceleratedOptimizer:
    """Facade over an optax transformation, carrying Accelerate's optimizer API surface.

    The actual ``update`` runs inside the jitted train step (``Accelerator.build_train_step``);
    this object owns the transformation, the host-side step counter, and param-group-style
    hyperparameter access (via ``optax.inject_hyperparams`` when present).
    """

    def __init__(self, optimizer, device_placement: bool = True, scaler=None):
        self.optimizer = optimizer  # optax.GradientTransformation
        self.scaler = scaler
        self.accelerator_state = AcceleratorState() if AcceleratorState._shared_state else None
        self.gradient_state = GradientState()
        self.device_placement = device_placement
        self._step_count = 0
        self._is_overflow = False
        self._opt_state_ref = None  # set by Accelerator after train-state creation

    # ------------------------------------------------------------------ optax delegation
    def init(self, params):
        return self.optimizer.init(params)

    def update(self, grads, opt_state, params=None, **kwargs):
        return self.optimizer.update(grads, opt_state, params, **kwargs)

    # --------------------------------------------------------------- torch-like surface
    @property
    def state(self):
        return self._opt_state_ref

    @property
    def param_groups(self):
        """Hyperparameters, when the transformation was built with inject_hyperparams."""
        hp = getattr(self._opt_state_ref, "hyperparams", None)
        if hp is not None:
            return [dict(hp)]
        return []

    def step(self, closure=None) -> None:
        """Host-side mirror of the in-jit conditional update.

        Counts an optimizer step only on sync steps — exactly the reference's skip behavior
        (``optimizer.py:161``), so scheduler logic downstream agrees with the device.
        """
        if self.gradient_state.sync_gradients:
            self._step_count += 1
            self._is_overflow = False

    def zero_grad(self, set_to_none: bool = True) -> None:
        """No-op: gradients are function outputs under JAX, never stored fields."""

    @property
    def step_was_skipped(self) -> bool:
        """Whether the last step was skipped (dynamic-scale overflow or accumulation)."""
        return self._is_overflow or not self.gradient_state.sync_gradients

    @property
    def optimizer_step_was_skipped(self) -> bool:  # reference property name
        return self.step_was_skipped

    def state_dict(self):
        return {"step_count": self._step_count}

    def load_state_dict(self, state_dict):
        self._step_count = state_dict.get("step_count", 0)

    def __repr__(self):
        return f"AcceleratedOptimizer({self.optimizer!r}, steps={self._step_count})"
