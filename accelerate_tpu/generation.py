"""Autoregressive generation: KV-cache decode loop + sampling (the inference hot loop).

The reference has no generation engine of its own — every published baseline number it has is
``model.generate()`` s/token through transformers over its dispatched models
(``/root/reference/benchmarks/big_model_inference/README.md:25-37``,
``examples/inference/pippy/llama.py``).  This module is the TPU-native counterpart: a
**jit-compiled ``lax.scan`` decode loop** over a model-provided (prefill, decode) pair, with
greedy / temperature / top-k / top-p sampling, EOS early-stop masking, and static shapes
throughout (prompt left-padded to a fixed width, fixed ``max_new_tokens`` — XLA never sees a
dynamic shape).

Model contract (see ``models/llama.py`` for the flagship wiring):

- ``prefill_fn(params, prompt, prompt_mask) -> (last_logits [B,V], cache)`` — consume the
  padded prompt, fill the KV cache.
- ``decode_fn(params, cache, token [B]) -> (logits [B,V], cache)`` — one cached decode step.

The fns are jit-static (pass stable identities — build them once per config, not per call);
``params`` is a traced argument so weights are runtime inputs, never baked-in constants.

Because the whole loop is one XLA program, weights stay pinned in HBM and every decode step is
a handful of fused HLOs — this is the design reason a single v5e chip beats the reference's
multi-GPU hook-dispatch decode (0.05 s/token GPT-J-6B fp16, BASELINE.md) by orders of
magnitude on models that fit.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp

__all__ = [
    "GenerationConfig",
    "sample_logits",
    "sampling_core",
    "sampling_core_dyn_k",
    "speculative_accept",
    "speculative_accept_batch",
    "speculative_prefix_accept",
    "generate_loop",
    "streamed_generate_loop",
]


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    """Decode-time knobs (the transformers ``GenerationConfig`` analog, jit-static)."""

    max_new_tokens: int = 128
    temperature: float = 0.0  # 0.0 → greedy (argmax)
    top_k: int = 0            # 0 → disabled
    top_p: float = 1.0        # 1.0 → disabled
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0


def filtered_logits(logits: jax.Array, temperature, top_p, top_k: int,
                    apply_top_p: bool = True) -> jax.Array:
    """Temperature / top-k / top-p filtered logits [.., V] fp32 (filtered entries -inf).

    The single source of sampling semantics: ``sampling_core`` draws categorically from
    these, and speculative sampling compares softmax(filtered) between draft and target —
    sharing this function is what makes the speculative output distribution provably the
    target's."""
    logits = logits.astype(jnp.float32) / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if apply_top_p:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep the smallest prefix with cumulative prob >= top_p (always keep the best token).
        keep_sorted = cum - probs < top_p
        threshold = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < threshold, -jnp.inf, logits)
    return logits


def sampling_core(logits: jax.Array, rng: jax.Array, temperature, top_p, top_k: int,
                  apply_top_p: bool = True) -> jax.Array:
    """Temperature / top-k / top-p draw with SCALAR-traceable temperature/top_p (only the
    shape-affecting ``top_k`` and ``apply_top_p`` must be static). Single source for
    ``sample_logits`` and the serving engine's jitted per-request draw, so their outputs
    can never drift.

    ``apply_top_p=False`` statically traces out the nucleus filter (an O(V log V) sort +
    softmax/cumsum per token): callers whose top_p is a static 1.0 skip the cost — and the
    float hazard where a cumsum prefix rounds to exactly 1.0 and masks live tail tokens.
    The serving engine keeps it on (its per-request top_p is traced)."""
    logits = filtered_logits(logits, temperature, top_p, top_k, apply_top_p)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def sampling_core_dyn_k(logits: jax.Array, rng: jax.Array, temperature, top_p,
                        top_k: jax.Array) -> jax.Array:
    """:func:`sampling_core` with a TRACED ``top_k`` (0 disables, like the static one).

    The multi-step decode scan samples every lane inside ONE program, so per-lane
    ``top_k`` cannot be a static trace constant without one compile per distinct k.
    This variant filters bitwise-identically to the static path: the k-th threshold is
    the (k−1)-th element of the descending sort — the exact value ``lax.top_k`` returns
    as its last element (both are exact selections, no arithmetic) — and the mask is
    gated by ``top_k > 0`` so a disabled filter matches the static path's skipped
    branch. The top-p block is the same ops as :func:`filtered_logits` verbatim.
    Asserted bitwise against ``sampling_core`` across k in tests/test_multistep_decode.py."""
    x = logits.astype(jnp.float32) / temperature
    sorted_desc = jnp.sort(x, axis=-1)[..., ::-1]
    k_idx = jnp.broadcast_to(
        (jnp.maximum(top_k, 1) - 1).astype(jnp.int32), x.shape[:-1]
    )[..., None]
    kth = jnp.take_along_axis(sorted_desc, k_idx, axis=-1)
    x = jnp.where((top_k > 0) & (x < kth), -jnp.inf, x)
    sorted_logits = jnp.sort(x, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = cum - probs < top_p
    threshold = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    x = jnp.where(x < threshold, -jnp.inf, x)
    return jax.random.categorical(rng, x, axis=-1).astype(jnp.int32)


def speculative_accept(p_probs: jax.Array, q_probs: jax.Array, draft_token,
                       key: jax.Array):
    """One speculative-sampling accept/reject (Leviathan et al. 2022): the draft proposed
    ``draft_token`` from q; the target distribution is p. Accept with min(1, p/q); on
    rejection return a token from the residual norm(max(p − q, 0)). The marginal output
    distribution is EXACTLY p — asserted distributionally in tests.

    Returns (accepted bool[], token int32[]) as 0-d arrays; jit/vmap-friendly."""
    p_probs = p_probs.astype(jnp.float32)
    q_probs = q_probs.astype(jnp.float32)
    k_accept, k_resid = jax.random.split(key)
    p_tok = p_probs[draft_token]
    q_tok = q_probs[draft_token]
    ratio = p_tok / jnp.maximum(q_tok, 1e-30)
    accepted = jax.random.uniform(k_accept) < jnp.minimum(1.0, ratio)
    residual = jnp.maximum(p_probs - q_probs, 0.0)
    # On acceptance the residual draw is unused; guard the degenerate all-zero residual
    # (p == q exactly) so categorical never sees -inf everywhere.
    denom = jnp.sum(residual)
    safe = jnp.where(denom > 0, residual / jnp.maximum(denom, 1e-30), p_probs)
    resid_tok = jax.random.categorical(k_resid, jnp.log(jnp.maximum(safe, 1e-30)))
    token = jnp.where(accepted, draft_token, resid_tok).astype(jnp.int32)
    return accepted, token


def speculative_accept_batch(p_probs: jax.Array, q_probs: jax.Array, draft_tokens,
                             keys: jax.Array):
    """Vectorized :func:`speculative_accept`: N independent accept/reject tests in ONE
    dispatch — ``p_probs``/``q_probs`` [N, V], ``draft_tokens`` [N], ``keys`` [N] →
    (accepted bool[N], tokens int32[N]). Each row's marginal output distribution is
    exactly its target row p (the scalar function vmapped, so the math cannot drift).

    This is the serving engine's residual accept mode: all k proposals of a slot (or a
    whole batch of slots) are tested at once, and the caller takes the leading-accept
    prefix — test j's token is the residual re-draw that ends the round when j is the
    first rejection. Tokens at positions AFTER the first rejection are computed but
    discarded; their keys are never consumed by any retained draw, so the sequential
    accept-chain semantics (and the losslessness proof) are unchanged."""
    return jax.vmap(speculative_accept)(p_probs, q_probs, draft_tokens, keys)


def speculative_prefix_accept(proposals: jax.Array, ref: jax.Array, live: jax.Array,
                              limits: jax.Array, eos_ids: jax.Array):
    """Batched greedy-prefix acceptance as a scan-compatible primitive: the
    accept/truncate walk of the serving engine's replay/greedy speculative round
    (``serving._spec_step``), vectorized over lanes so it can run INSIDE the
    fused multi-round decode scan with no host involvement.

    ``proposals`` [B, k] int32 — the drafter's k proposed tokens per lane;
    ``ref`` [B, k+1] int32 — the reference tokens the verify pass selected at
    each of the k+1 positions (position j conditioned on proposals[:, :j]);
    ``live`` bool[B] — lanes participating this round; ``limits`` int32[B] —
    remaining generation budget per lane (emissions this round are capped at
    ``min(k+1, max(limits, 1))``); ``eos_ids`` int32[B] — per-lane EOS id, −1
    disables (matching the multi-step scan's convention).

    Per lane: accept the longest prefix where proposal j == ref j, emit those
    plus ref's correction/bonus token (so 1..k+1 emissions), truncate at the
    budget, then truncate AT the first emitted EOS inclusive. Emitted tokens
    never depend on proposals — position j is only emitted when proposals
    [0..j−1] matched ref[0..j−1] exactly — which is the losslessness argument
    that makes the fused path bitwise-identical to the host loop for ANY
    deterministic drafter.

    Returns ``(n_emit int32[B], last_tok int32[B], hit_eos bool[B],
    n_accepted int32[B])``: emission count (0 for dead lanes), the last emitted
    token (undefined where n_emit == 0), whether the lane's round ended on its
    EOS, and how many of the emissions were accepted draft proposals (the
    telemetry accept-rate numerator, identical to the host loop's count).
    """
    k = proposals.shape[1]
    match = (proposals == ref[:, :k]).astype(jnp.int32)
    # Longest all-match prefix: sum of the cumulative product over positions.
    n_match = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
    m = jnp.minimum(n_match + 1, jnp.maximum(limits, 1))
    is_eos = (eos_ids[:, None] >= 0) & (ref == eos_ids[:, None])
    within = jnp.arange(k + 1)[None, :] < m[:, None]
    has_eos = jnp.any(is_eos & within, axis=1)
    first_eos = jnp.argmax(is_eos & within, axis=1).astype(jnp.int32)
    m = jnp.where(has_eos, first_eos + 1, m)
    n_emit = jnp.where(live, m, 0).astype(jnp.int32)
    last_idx = jnp.clip(n_emit - 1, 0, k)
    last_tok = jnp.take_along_axis(ref, last_idx[:, None], axis=1)[:, 0]
    hit_eos = has_eos & live
    n_accepted = jnp.minimum(n_match, n_emit).astype(jnp.int32)
    return n_emit, last_tok.astype(jnp.int32), hit_eos, n_accepted


def sample_logits(logits: jax.Array, gen: GenerationConfig, rng: Optional[jax.Array]) -> jax.Array:
    """logits [B, V] → token ids [B] via greedy / temperature / top-k / top-p."""
    if gen.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if rng is None:
        raise ValueError("temperature sampling needs an rng key")
    # gen is jit-static here, so top_p == 1.0 removes the nucleus pass at trace time.
    return sampling_core(
        logits, rng, gen.temperature, gen.top_p, gen.top_k, apply_top_p=gen.top_p < 1.0
    )


@partial(jax.jit, static_argnames=("prefill_fn", "decode_fn", "gen"))
def generate_loop(
    prefill_fn: Callable,
    decode_fn: Callable,
    params,
    prompt: jax.Array,
    prompt_mask: jax.Array,
    gen: GenerationConfig,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Run prefill + ``max_new_tokens`` cached decode steps as one compiled program.

    ``prompt`` [B, S0] int32, left-padded; ``prompt_mask`` [B, S0] bool (False on pads).
    Returns generated ids [B, max_new_tokens]; positions after an EOS are ``pad_token_id``.
    """
    last_logits, cache = prefill_fn(params, prompt, prompt_mask)
    if rng is None:
        rng = jax.random.PRNGKey(0)  # graftlint: disable=rng-key-reuse(documented deterministic default; pass rng for real entropy)
    # Use-once key discipline: every draw gets its own split; the parent key is never
    # consumed directly.
    step_rngs = jax.random.split(rng, gen.max_new_tokens)
    first = sample_logits(last_logits, gen, step_rngs[0])
    done0 = jnp.zeros((prompt.shape[0],), jnp.bool_)
    if gen.eos_token_id is not None:
        done0 = first == gen.eos_token_id  # the EOS itself is emitted; later slots are padded

    def body(carry, step_rng):
        cache, token, done = carry
        logits, cache = decode_fn(params, cache, token)
        nxt = sample_logits(logits, gen, step_rng)
        if gen.eos_token_id is not None:
            emitted = jnp.where(done, jnp.int32(gen.pad_token_id), nxt)
            done = done | (nxt == gen.eos_token_id)
        else:
            emitted = nxt
        # Feed the raw sample back in; finished rows keep decoding but their output is masked.
        return (cache, nxt, done), emitted

    (_, _, _), rest = jax.lax.scan(
        body, (cache, first, done0), step_rngs[1:], length=gen.max_new_tokens - 1
    )
    out = jnp.concatenate([first[None, :], rest], axis=0)  # [T, B]
    return jnp.swapaxes(out, 0, 1)


def streamed_generate_loop(
    one_pass: Callable,
    prompt: jax.Array,
    prompt_mask: Optional[jax.Array],
    gen: GenerationConfig,
    rng: Optional[jax.Array] = None,
    pass_times: Optional[list] = None,
) -> jax.Array:
    """Host-driven decode loop for weight-streamed models (shared by the llama/gpt
    ``generate_streamed`` paths).

    ``one_pass(tokens [B,T], cache_or_None, token_mask [B,T]) -> (last_logits [B,V], cache)``
    runs a full forward with block weights streamed from host/disk; the first call (cache =
    None) is the prefill. Unlike ``generate_loop``, this cannot be one compiled scan —
    weights arrive per block per pass — so EOS handling early-exits the Python loop once
    every row has finished.

    ``pass_times``: pass a list to receive per-pass wall seconds (prefill first, then one
    entry per decode step, each blocked on its logits). Streamed decode re-streams the
    whole model every pass, so steady-state s/token is measurable from ONE call's tail
    entries — the big-model bench uses this instead of paying a second full-streaming run.
    """

    def timed(*args):
        if pass_times is None:
            return one_pass(*args)
        t0 = time.perf_counter()
        out = one_pass(*args)
        jax.block_until_ready(out[0])  # graftlint: disable=host-sync-in-hot-path(pass_times contract: each pass is timed blocked on its logits)
        pass_times.append(time.perf_counter() - t0)
        return out

    prompt = jnp.asarray(prompt, jnp.int32)
    B, S0 = prompt.shape
    if prompt_mask is None:
        prompt_mask = jnp.ones((B, S0), jnp.bool_)
    if rng is None:
        rng = jax.random.PRNGKey(0)  # graftlint: disable=rng-key-reuse(documented deterministic default; pass rng for real entropy)
    step_rngs = jax.random.split(rng, gen.max_new_tokens)
    logits, cache = timed(prompt, None, prompt_mask)
    token = sample_logits(logits, gen, step_rngs[0])
    done = (
        token == gen.eos_token_id if gen.eos_token_id is not None
        else jnp.zeros((B,), jnp.bool_)
    )
    out = [token]
    for t in range(1, gen.max_new_tokens):
        logits, cache = timed(token[:, None], cache, jnp.ones((B, 1), jnp.bool_))
        nxt = sample_logits(logits, gen, step_rngs[t])
        if gen.eos_token_id is not None:
            out.append(jnp.where(done, jnp.int32(gen.pad_token_id), nxt))
            done = done | (nxt == gen.eos_token_id)
            if bool(jnp.all(done)):
                pad = jnp.full((B,), gen.pad_token_id, jnp.int32)
                out.extend([pad] * (gen.max_new_tokens - len(out)))
                break
        else:
            out.append(nxt)
        token = nxt
    return jnp.stack(out, axis=1)
