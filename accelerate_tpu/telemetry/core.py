"""The step-level telemetry pipeline: fenced timing + steady-state + counters → records.

One ``Telemetry`` object rides on the ``Accelerator``; when enabled,
``build_train_step``'s dispatcher brackets every step with ``_step_begin`` /
``_step_end`` and a JSON-serializable record flows to every sink (a JSONL file under
``TelemetryConfig.jsonl_dir``, plus whatever trackers the Accelerator wires in). The
serving engine pushes its counter records through :meth:`Telemetry.emit` — one
pipeline for training and serving observability.

Contract when **disabled** (the default): ``enabled`` is False, no listener is
registered, no file is opened, and the hot path performs exactly two attribute reads
per step — zero host syncs, zero extra ``block_until_ready`` (asserted by
``tests/test_telemetry.py``).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, List, Optional

from .compile_monitor import CompileMonitor, compile_label
from .derived import derived_rates
from .memory import device_memory_stats
from .schemas import STEP_RECORD_SCHEMA
from .steady import SteadyStateDetector, TELEMETRY_REV
from .timing import StepTimer

__all__ = ["Telemetry", "STEP_RECORD_SCHEMA"]

#: Columns every step record carries (derived-rate and memory columns are
#: best-effort: absent when their inputs are unknown on this backend/workload).
REQUIRED_STEP_COLUMNS = (
    "schema",
    "telemetry_rev",
    "step",
    "wall_s",
    "dispatch_s",
    "fence_s",
    "steady",
    "warmup_steps_detected",
    "compiles_total",
    "compile_s_total",
    "compiles_delta",
)


def _infer_batch_counts(
    batch: Any, drop_leading: int = 0
) -> tuple[Optional[int], Optional[int]]:
    """(examples, tokens) per step from host-visible batch SHAPES — never values, so
    this costs a few attribute reads and no device sync. Token count comes from the
    conventional ``[batch, seq]`` id leaf (``tokens``/``input_ids``); examples from
    the leading batch dim. ``drop_leading`` strips stacked dispatch dims (the fused
    ``[M, B, S]`` layout) before reading."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(batch)
    except Exception:
        return None, None
    examples = tokens = None
    if isinstance(batch, dict):
        for key in ("tokens", "input_ids"):
            shape = getattr(batch.get(key), "shape", None)
            if shape is not None and len(shape) >= 2 + drop_leading:
                b, s = shape[drop_leading], shape[drop_leading + 1]
                tokens = int(b * s)
                break
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape is not None and len(shape) >= 1 + drop_leading:
            if shape[drop_leading] > 0:
                examples = int(shape[drop_leading])
                break
    return examples, tokens


class Telemetry:
    """Aggregates the telemetry pieces behind one enable flag.

    Sinks are ``record -> None`` callables; :meth:`emit` fans every record out and
    keeps a bounded history (``records``) plus ``last_step_record`` for
    ``Accelerator.log`` column merging.
    """

    def __init__(self, config=None):
        if config is None:
            from ..utils.dataclasses import TelemetryConfig

            config = TelemetryConfig()
        self.config = config
        self.enabled: bool = bool(config.enabled)
        self.records: List[dict] = []
        self.last_step_record: Optional[dict] = None
        self.sinks: List[Callable[[dict], None]] = []
        self.timer = StepTimer()
        self.detector = SteadyStateDetector(
            k=config.steady_k, rtol=config.steady_rtol, max_windows=config.steady_cap
        )
        self.compile_monitor = CompileMonitor()
        self._compile_seen = 0  # totals at last record, for per-step deltas
        self._compile_seen_s = 0.0
        self._label_ctx = None
        self._jsonl_file = None
        self._step_count = 0
        # Throughput hints: explicit values win over per-batch shape inference.
        self.flops_per_step: Optional[float] = config.flops_per_step
        self.tokens_per_step: Optional[float] = config.tokens_per_step
        self.examples_per_step: Optional[float] = config.examples_per_step
        self._jsonl_path = None
        #: The flight recorder (telemetry/recorder.py) when config.recorder is
        #: set — always None while disabled, so the attribute read stays free.
        self.recorder = None
        if self.enabled:
            if config.compile_events:
                self.compile_monitor.start()
            if config.jsonl_dir:
                os.makedirs(config.jsonl_dir, exist_ok=True)
                self._jsonl_path = os.path.join(config.jsonl_dir, "telemetry.jsonl")
                self._jsonl_file = open(self._jsonl_path, "a")
            if getattr(config, "recorder", False):
                from .recorder import FlightRecorder

                capsule_dir = getattr(config, "capsule_dir", None)
                if capsule_dir:
                    os.makedirs(capsule_dir, exist_ok=True)
                self.recorder = FlightRecorder(
                    self,
                    ring_size=getattr(config, "recorder_ring", 2048),
                    snapshot_every=getattr(config, "recorder_snapshot_every", 256),
                    capsule_dir=capsule_dir,
                    capsule_cooldown_s=getattr(config, "capsule_cooldown_s", 30.0),
                )

    # ------------------------------------------------------------------ hints
    def set_throughput_hints(
        self,
        flops_per_step: Optional[float] = None,
        tokens_per_step: Optional[float] = None,
        examples_per_step: Optional[float] = None,
    ) -> None:
        """Static per-step costs for the derived rates (MFU needs ``flops_per_step``)."""
        if flops_per_step is not None:
            self.flops_per_step = flops_per_step
        if tokens_per_step is not None:
            self.tokens_per_step = tokens_per_step
        if examples_per_step is not None:
            self.examples_per_step = examples_per_step

    # ------------------------------------------------------------------ step scope
    def _step_begin(self, label: str = "train_step") -> None:
        """Start the fenced timer and the compile-attribution label. Only called on
        the enabled path (the dispatcher guards with one bool read)."""
        self._label_ctx = compile_label(label)
        self._label_ctx.__enter__()
        self.timer.start()

    def _step_abort(self) -> None:
        """Unwind a step bracket whose body raised: exit the compile label and drop
        the running timer, so a failed step never leaks attribution state (a leaked
        label would mis-credit every later compile to 'train_step')."""
        if self._label_ctx is not None:
            self._label_ctx.__exit__(None, None, None)
            self._label_ctx = None
        self.timer._t0 = None

    def _step_end(
        self, fence_on: Any, batch: Any = None, n_steps: int = 1, drop_leading: int = 0
    ) -> dict:
        """Fence, measure, observe steadiness, snapshot counters, emit one record."""
        timing = self.timer.stop(fence_on=fence_on)
        if self._label_ctx is not None:
            self._label_ctx.__exit__(None, None, None)
            self._label_ctx = None
        self._step_count += n_steps
        self.detector.observe(timing.wall_s / max(n_steps, 1))

        mon = self.compile_monitor
        compiles_delta = mon.count - self._compile_seen
        compile_s_delta = mon.seconds - self._compile_seen_s
        self._compile_seen = mon.count
        self._compile_seen_s = mon.seconds

        record = {
            "schema": STEP_RECORD_SCHEMA,
            "telemetry_rev": TELEMETRY_REV,
            "step": self._step_count,
            "wall_s": round(timing.wall_s, 6),
            "dispatch_s": round(timing.dispatch_s, 6),
            "fence_s": round(timing.fence_s, 6),
            "steady": self.detector.steady,
            "warmup_steps_detected": self.detector.warmup_steps_detected,
            "compiles_total": mon.count,
            "compile_s_total": round(mon.seconds, 6),
            "compiles_delta": compiles_delta,
            "compile_s_delta": round(compile_s_delta, 6),
        }
        if self.config.memory_stats:
            mem = device_memory_stats(device_index=self.config.device_index)
            if mem:
                record["memory"] = mem
        examples, tokens = (None, None)
        if batch is not None:
            examples, tokens = _infer_batch_counts(batch, drop_leading=drop_leading)
        # Window totals: explicit per-step hints win over shape inference; either way
        # the rate divides the whole fenced window (which covers n_steps steps).
        tokens_window = (
            self.tokens_per_step * n_steps
            if self.tokens_per_step is not None
            else (tokens * n_steps if tokens is not None else None)
        )
        examples_window = (
            self.examples_per_step * n_steps
            if self.examples_per_step is not None
            else (examples * n_steps if examples is not None else None)
        )
        rates = derived_rates(
            timing.wall_s,
            tokens_per_step=tokens_window,
            examples_per_step=examples_window,
            flops_per_step=(
                self.flops_per_step * n_steps if self.flops_per_step is not None else None
            ),
            n_chips=self._n_chips(),
            device=self._device(),
        )
        for key, value in rates.items():
            record[key] = round(value, 6)
        self.last_step_record = record
        self.emit(record)
        return record

    def _device(self):
        try:
            import jax

            return jax.local_devices()[self.config.device_index]
        except Exception:
            return None

    def _n_chips(self) -> int:
        try:
            import jax

            return jax.device_count()
        except Exception:
            return 1

    # ------------------------------------------------------------------ pipeline
    def emit(self, record: dict) -> None:
        """Route one record (step, serving counter, throughput, ...) to history,
        the JSONL file, and every registered sink. No-op while disabled."""
        if not self.enabled:
            return
        self.records.append(record)
        cap = self.config.max_records
        if cap and len(self.records) > cap:
            del self.records[: len(self.records) - cap]
        if self._jsonl_file is not None:
            self._jsonl_file.write(json.dumps(record, default=float) + "\n")
            self._jsonl_file.flush()
            # Size-based rotation (config.rotate_bytes > 0): the active file
            # rolls to telemetry.<n>.jsonl once it crosses the bound, so a
            # long chaos run never grows one unbounded file. Zero-padded n —
            # lexical order IS chronological, which is the contract the
            # multi-file readers (trace-report, metrics-dump) sort by.
            rotate = self.config.rotate_bytes
            if rotate and self._jsonl_file.tell() >= rotate:
                self._rotate_jsonl()
        for sink in self.sinks:
            sink(record)

    def _rotate_jsonl(self) -> None:
        self._jsonl_file.close()
        directory = os.path.dirname(self._jsonl_path)
        # max(existing)+1, NOT first-free-slot: an operator deleting an old
        # rotated file to reclaim disk must not make the next rotation reuse
        # its low index — the readers sort lexically and the newest records
        # would land first. (Also one listdir instead of an O(n) exists scan.)
        taken = [-1]
        for fname in os.listdir(directory):
            if fname.startswith("telemetry.") and fname.endswith(".jsonl"):
                mid = fname[len("telemetry."):-len(".jsonl")]
                if mid.isdigit():
                    taken.append(int(mid))
        rolled = os.path.join(directory, f"telemetry.{max(taken) + 1:05d}.jsonl")
        os.replace(self._jsonl_path, rolled)
        self._jsonl_file = open(self._jsonl_path, "a")

    def log_columns(self, prefix: str = "telemetry/") -> dict:
        """The last step record flattened to scalar columns for tracker merging."""
        rec = self.last_step_record
        if not rec:
            return {}
        out = {}
        for key, value in rec.items():
            if key == "schema":
                continue
            if isinstance(value, dict):
                for sub, sval in value.items():
                    if isinstance(sval, (int, float, bool)):
                        out[f"{prefix}{key}/{sub}"] = sval
            elif isinstance(value, (int, float, bool)) and value is not None:
                out[f"{prefix}{key}"] = value
        return out

    def close(self) -> None:
        self.compile_monitor.stop()
        if self._jsonl_file is not None:
            self._jsonl_file.close()
            self._jsonl_file = None

    def __repr__(self) -> str:
        return (
            f"Telemetry(enabled={self.enabled}, steps={self._step_count}, "
            f"steady={self.detector.steady}, records={len(self.records)})"
        )
