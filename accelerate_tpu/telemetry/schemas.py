"""The telemetry schema registry: every record schema id, in ONE place.

Every record the pipeline emits carries a ``"schema"`` column naming its format
(``accelerate_tpu.telemetry.<stream>/v<rev>``). Before this module those ids were
string literals scattered across the emit sites — a typo'd stream name shipped
silently, and nothing enumerated what a consumer could expect to find in a JSONL
run directory. This registry is the single source of truth:

- Every schema id is a **constant here** (emit sites import it; graftlint's
  ``telemetry-schema-literal`` rule flags a bare string-literal schema anywhere
  else in the library sources).
- Each registration carries its **required key set** — the columns a consumer may
  rely on unconditionally — plus the emitter and a one-line description.
  :func:`validate_record` checks a record against its registration (tests pin
  every emit site through it).
- The schema table in ``docs/telemetry.md`` is **generated** from this registry
  (:func:`schema_table_markdown`) and drift-gated by ``scripts/check.sh``
  (``python -m accelerate_tpu.telemetry.schemas --check``; ``--write`` refreshes
  the docs block).

Stdlib-only by design: the registry must be importable from stripped CLI
contexts (trace-report, the docs gate) without jax.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping

__all__ = [
    "STEP_RECORD_SCHEMA",
    "SERVING_SCHEMA",
    "SERVING_THROUGHPUT_SCHEMA",
    "SERVING_KV_SCHEMA",
    "SERVING_SPEC_SCHEMA",
    "SERVING_HANDOFF_SCHEMA",
    "GATEWAY_REQUEST_SCHEMA",
    "GATEWAY_SLO_SCHEMA",
    "REPLICA_HEALTH_SCHEMA",
    "FLEET_ROUTE_SCHEMA",
    "FLEET_SCALE_SCHEMA",
    "ELASTIC_RESTART_SCHEMA",
    "MPMD_TRANSFER_SCHEMA",
    "MPMD_BARRIER_SCHEMA",
    "MPMD_STAGE_STEP_SCHEMA",
    "AUDIT_PROGRAM_SCHEMA",
    "TRACE_SPAN_SCHEMA",
    "FAULT_SCHEMA",
    "RECOVERY_SCHEMA",
    "ALERT_SCHEMA",
    "METRICS_SNAPSHOT_SCHEMA",
    "CAPSULE_SCHEMA",
    "RecordSchema",
    "SCHEMA_REGISTRY",
    "registered_schemas",
    "validate_record",
    "schema_table_markdown",
]

# --------------------------------------------------------------------- schema ids
#: Per-step training/eval record (``Telemetry._step_end``); bump on breaking
#: column changes.
STEP_RECORD_SCHEMA = "accelerate_tpu.telemetry.step/v1"

#: Per-decode-step serving engine counter record (``ContinuousBatcher``).
SERVING_SCHEMA = "accelerate_tpu.telemetry.serving/v1"

#: One aggregate per ``ContinuousBatcher.run(report_throughput=True)`` drain.
SERVING_THROUGHPUT_SCHEMA = "accelerate_tpu.telemetry.serving.throughput/v1"

#: Per-decode-step page-pool record (paged KV engines only).
SERVING_KV_SCHEMA = "accelerate_tpu.telemetry.serving.kv/v1"

#: Per-decode-step speculative-decoding record (``spec_k > 0`` engines only).
SERVING_SPEC_SCHEMA = "accelerate_tpu.telemetry.serving.spec/v1"

#: One record per cross-engine KV page handoff (disaggregated serving,
#: ``ops.collectives.kv_page_transfer``): which prefill replica exported, which
#: decode replica adopted, the request uid, page count, wire bytes and
#: synchronously-measured transfer latency — joined into trace-report timelines
#: as the ``handoff`` span.
SERVING_HANDOFF_SCHEMA = "accelerate_tpu.telemetry.serving.handoff/v1"

#: One record per gateway request reaching a terminal state (done/rejected/shed/
#: expired/cancelled/evicted): uid, status, machine-readable reason, tenant,
#: priority, queue_wait_s / ttft_s / tpot_s, tokens generated, deadline_met.
GATEWAY_REQUEST_SCHEMA = "accelerate_tpu.telemetry.gateway.request/v1"

#: Aggregate gateway summary: terminal counts by status plus the per-metric
#: p50/p95/p99 blocks produced by ``telemetry.slo.slo_summary``.
GATEWAY_SLO_SCHEMA = "accelerate_tpu.telemetry.gateway.slo/v1"

#: One record per fleet replica per router step: health score, replica state
#: (active/draining/restarting/retired), breaker state, load (active lanes,
#: internal queue) and the failure counters the score is computed from —
#: the per-replica signal behind health-driven routing (``serving_gateway.fleet``).
REPLICA_HEALTH_SCHEMA = "accelerate_tpu.telemetry.replica.health/v1"

#: One record per fleet routing decision: which replica got the request and why
#: (``dispatch``/``probe``), plus the health/free-lane snapshot it won on —
#: and one per migration (``migrate``) when failover moves a request away.
FLEET_ROUTE_SCHEMA = "accelerate_tpu.telemetry.fleet.route/v1"

#: One record per autoscaler decision (``serving_gateway.autoscaler.
#: Autoscaler``): ``action`` is ``scale_up``/``scale_down``/``rebalance``,
#: ``reason`` the alert rule or forecast that triggered it, ``replicas`` the
#: fleet size AFTER the action, plus the per-role census, cumulative
#: replica-hours and the router-clock timestamp — the decision audit trail
#: the autoscale bench replays deterministically under a virtual clock.
FLEET_SCALE_SCHEMA = "accelerate_tpu.telemetry.fleet.scale/v1"

#: Emitted on every gang restart (attempt index, the exit codes that triggered
#: the teardown, the restart budget) by ``ElasticSupervisor`` — ``gang_id``
#: names WHICH gang, so one record stream can carry a whole fleet's restarts
#: (``FleetSupervisor`` keeps independent per-gang budgets).
ELASTIC_RESTART_SCHEMA = "accelerate_tpu.telemetry.elastic.restart/v1"

#: One record per inter-stage DCN transfer in MPMD multi-slice training
#: (``ops.collectives.stage_transfer``): which stage boundary the payload
#: crossed (``src_stage``/``dst_stage``), the direction (``fwd`` activation /
#: ``bwd`` cotangent), bytes and synchronously-measured latency, causally
#: joined to the training step/microbatch.
MPMD_TRANSFER_SCHEMA = "accelerate_tpu.telemetry.mpmd.transfer/v1"

#: One record per gang-of-gangs barrier action (``elastic.GangOfGangs``): a
#: healthy stage gang HOLDING at the recovery barrier while a crashed peer
#: restarts, and its RELEASE when the pipeline replays — ``gang_id`` names the
#: holding gang, ``peer`` the crashed one, ``action`` is ``hold``/``release``,
#: ``step`` the global training step the pipeline held at.
MPMD_BARRIER_SCHEMA = "accelerate_tpu.telemetry.mpmd.barrier/v1"

#: One record per MPMD stage per training step (``parallel.mpmd.StageProcess``):
#: host-fenced per-phase compute seconds (``fwd_s``/``bwd_s``/``apply_s``,
#: summed as ``busy_s``) between the step's wall-clock bounds ``t0``/``t1`` —
#: the per-stage timeline ``trace-report --train`` reconstructs pipeline
#: bubbles and straggler attribution from.
MPMD_STAGE_STEP_SCHEMA = "accelerate_tpu.telemetry.mpmd.stage_step/v1"

#: One record per warmup-precompiled program: graftaudit collective inventory,
#: donation effectiveness, and the graftmem static memory/comms estimate
#: (``compile_cache.warmup``).
AUDIT_PROGRAM_SCHEMA = "accelerate_tpu.telemetry.audit.program/v1"

#: One span per request-lifecycle phase (``telemetry.tracing``): queue wait,
#: admission, prefill, each decode round, retries/preemptions, terminal state —
#: causally linked to the step/kv/spec records via the engine ``step`` index.
TRACE_SPAN_SCHEMA = "accelerate_tpu.telemetry.trace.span/v1"

#: One record per fault observed by a recovery boundary (injected OR real):
#: the site it fired at, the fault kind/reason, the attributed request uid
#: (None when attribution needed bisection) and the engine step index.
FAULT_SCHEMA = "accelerate_tpu.telemetry.fault/v1"

#: One record per recovery action: poison-request quarantine, survivor
#: rebuild, bisection round, circuit-breaker transition, checkpoint fallback.
#: ``action`` is machine-readable; the other columns are action-specific.
RECOVERY_SCHEMA = "accelerate_tpu.telemetry.recovery/v1"

#: One record per alert-state transition (``telemetry.alerts.AlertEngine``):
#: ``rule`` names the :class:`~.alerts.AlertRule`, ``state`` is
#: ``firing``/``resolved``, ``kind`` is ``threshold``/``burn_rate``, ``value``
#: the observed aggregate and ``threshold`` the bound it crossed — the live
#: trigger surface an SLO-driven autoscaler subscribes to (ROADMAP item 5).
ALERT_SCHEMA = "accelerate_tpu.telemetry.alert/v1"

#: One point-in-time dump of the whole metrics plane
#: (``telemetry.metrics.MetricsPlane.snapshot_record``): every counter, gauge
#: and sliding-window histogram summary plus the SLO event-window block —
#: what bench rows stamp and ``metrics-dump`` prints.
METRICS_SNAPSHOT_SCHEMA = "accelerate_tpu.telemetry.metrics.snapshot/v1"

#: The manifest of one incident capsule (``telemetry.recorder.FlightRecorder``):
#: what triggered the dump (``trigger`` is a stable dedupe key like
#: ``alert:step-failure-burst`` or ``fault:serving.decode``), the triggering
#: record itself, when (recorder clock), how much of the flight ring was
#: captured vs dropped, which state snapshots rode along and the provenance
#: stamp — everything ``capsule-report`` needs to rebuild the incident from the
#: capsule directory alone.
CAPSULE_SCHEMA = "accelerate_tpu.telemetry.capsule/v1"


# --------------------------------------------------------------------- registry
@dataclasses.dataclass(frozen=True)
class RecordSchema:
    """One registered record format: id, the key set a consumer may rely on
    unconditionally, who emits it, and what it is for. Emitters may add optional
    columns freely (memory stats, derived rates, kind-specific span attrs);
    required keys only ratchet UP within a ``/v<rev>``."""

    schema: str
    required: frozenset
    emitter: str
    description: str


def _reg(schema: str, required, emitter: str, description: str) -> RecordSchema:
    return RecordSchema(schema, frozenset(required) | {"schema"}, emitter, description)


#: Every record format the pipeline emits, keyed by schema id.
SCHEMA_REGISTRY: Dict[str, RecordSchema] = {
    s.schema: s
    for s in (
        _reg(
            STEP_RECORD_SCHEMA,
            ("telemetry_rev", "step", "wall_s", "dispatch_s", "fence_s", "steady",
             "warmup_steps_detected", "compiles_total", "compile_s_total",
             "compiles_delta"),
            "Telemetry._step_end",
            "fenced per-step timing, steadiness, compile counters",
        ),
        _reg(
            SERVING_SCHEMA,
            ("telemetry_rev", "queued", "active_slots", "max_slots",
             "slot_occupancy", "admitted", "evicted", "decode_steps",
             "decode_tokens"),
            "ContinuousBatcher.step",
            "per-decode-step engine counters (queue, lanes, prefix cache)",
        ),
        _reg(
            SERVING_THROUGHPUT_SCHEMA,
            ("wall_s", "tokens_generated", "requests_finished", "tokens_per_sec"),
            "ContinuousBatcher.run",
            "aggregate tokens/s for one drained workload",
        ),
        _reg(
            SERVING_KV_SCHEMA,
            ("telemetry_rev", "step", "page_size", "pages_total", "pages_in_use",
             "page_occupancy", "kv_bytes_in_use", "kv_bytes_total",
             "kv_shared_pages", "kv_alloc_count", "kv_free_count", "kv_cow_count",
             "kv_adopt_count", "kv_defer_count"),
            "ContinuousBatcher.step (paged)",
            "page-pool occupancy/bytes/sharing/churn per decode step",
        ),
        _reg(
            SERVING_SPEC_SCHEMA,
            ("telemetry_rev", "step", "spec_k", "rounds", "active_slots",
             "step_proposed", "step_accepted", "step_tokens", "proposed_total",
             "accepted_total"),
            "ContinuousBatcher._spec_step / _spec_multi",
            "speculative proposal/acceptance per dispatch (rounds=1 host loop; "
            "rounds=N fused super-step)",
        ),
        _reg(
            SERVING_HANDOFF_SCHEMA,
            ("src_replica", "dst_replica", "uid", "pages", "nbytes", "dur_s"),
            "ops.collectives.kv_page_transfer",
            "one cross-engine KV page handoff (prefill -> decode replica)",
        ),
        _reg(
            GATEWAY_REQUEST_SCHEMA,
            ("uid", "status", "reason", "tenant", "priority", "n_tokens",
             "retries_used", "queue_wait_s", "ttft_s", "tpot_s", "deadline_met"),
            "ServingGateway._finalize",
            "one record per request reaching a terminal state",
        ),
        _reg(
            GATEWAY_SLO_SCHEMA,
            ("policy", "submitted", "admitted", "done", "rejected", "shed",
             "cancelled", "expired", "evicted", "retried", "failed",
             "replayed", "slo"),
            "ServingGateway.emit_slo_record",
            "aggregate SLO percentiles + admission accounting",
        ),
        _reg(
            REPLICA_HEALTH_SCHEMA,
            ("replica", "state", "role", "health", "breaker_state",
             "active_slots", "queued", "step_failures"),
            "FleetRouter.step",
            "per-replica health score, state, role and load per router step",
        ),
        _reg(
            FLEET_ROUTE_SCHEMA,
            ("uid", "replica", "reason", "health", "free_lanes"),
            "FleetRouter",
            "one routing decision: request -> replica (dispatch/probe/migrate)",
        ),
        _reg(
            FLEET_SCALE_SCHEMA,
            ("action", "reason", "replicas", "t"),
            "serving_gateway.autoscaler.Autoscaler",
            "one autoscaler decision (scale_up/scale_down/rebalance) with the "
            "post-action fleet census",
        ),
        _reg(
            ELASTIC_RESTART_SCHEMA,
            ("gang_id", "attempt", "attempts_used", "max_restarts",
             "exit_codes"),
            "ElasticSupervisor / FleetSupervisor",
            "one record per gang restart (gang_id names which gang)",
        ),
        _reg(
            MPMD_TRANSFER_SCHEMA,
            ("src_stage", "dst_stage", "direction", "nbytes", "dur_s", "step",
             "microbatch"),
            "ops.collectives.stage_transfer",
            "one inter-stage DCN transfer (activation fwd / cotangent bwd)",
        ),
        _reg(
            MPMD_BARRIER_SCHEMA,
            ("gang_id", "peer", "action", "step"),
            "elastic.GangOfGangs",
            "a healthy gang holding at / released from the recovery barrier",
        ),
        _reg(
            MPMD_STAGE_STEP_SCHEMA,
            ("gang_id", "stage", "step", "t0", "t1", "busy_s", "fwd_s",
             "bwd_s", "apply_s", "microbatches"),
            "parallel.mpmd.StageProcess",
            "one stage's fenced per-phase compute seconds for one train step",
        ),
        _reg(
            AUDIT_PROGRAM_SCHEMA,
            # "memory" rode a required-key ratchet-UP within /v1 (the allowed
            # direction): the graftmem static peak-HBM + priced ICI/DCN block.
            ("label", "collectives", "donation", "memory"),
            "compile_cache.warmup",
            "per-program graftaudit inventory (collectives, donation, memory)",
        ),
        _reg(
            TRACE_SPAN_SCHEMA,
            ("trace_id", "uid", "span", "t0", "t1", "dur_s"),
            "telemetry.tracing.Tracer",
            "request-scoped lifecycle span (queue/admit/prefill/decode/terminal)",
        ),
        _reg(
            FAULT_SCHEMA,
            ("site", "kind"),
            "recovery boundaries (serving/training/checkpointing)",
            "one fault observed at a recovery boundary (injected or real)",
        ),
        _reg(
            RECOVERY_SCHEMA,
            ("action",),
            "recovery boundaries (engine/gateway/checkpointing)",
            "one recovery action (quarantine/rebuild/bisect/circuit/fallback)",
        ),
        _reg(
            ALERT_SCHEMA,
            ("rule", "state", "severity", "kind", "t"),
            "telemetry.alerts.AlertEngine",
            "one alert-state transition (firing/resolved) over plane aggregates",
        ),
        _reg(
            METRICS_SNAPSHOT_SCHEMA,
            ("t", "counters", "gauges", "histograms", "slo"),
            "telemetry.metrics.MetricsPlane.snapshot_record",
            "one point-in-time dump of every live counter/gauge/histogram",
        ),
        _reg(
            CAPSULE_SCHEMA,
            ("trigger", "t", "ring_records", "ring_dropped", "state_keys",
             "provenance"),
            "telemetry.recorder.FlightRecorder",
            "one incident capsule manifest (trigger, ring/state accounting)",
        ),
    )
}


def registered_schemas() -> List[str]:
    """Every registered schema id, sorted."""
    return sorted(SCHEMA_REGISTRY)


def validate_record(record: Mapping) -> List[str]:
    """Problems with one record against its registration (empty = valid):
    unknown/missing schema id, or registered required keys the record lacks."""
    schema = record.get("schema")
    if schema is None:
        return ["record has no 'schema' key"]
    reg = SCHEMA_REGISTRY.get(schema)
    if reg is None:
        return [f"unregistered schema {schema!r} (register it in telemetry/schemas.py)"]
    missing = sorted(reg.required - set(record))
    return [f"{schema}: missing required keys {missing}"] if missing else []


# ------------------------------------------------------------------- docs drift
#: Markers bounding the generated block in docs/telemetry.md.
_DOCS_BEGIN = "<!-- BEGIN GENERATED SCHEMA TABLE (python -m accelerate_tpu.telemetry.schemas --write) -->"
_DOCS_END = "<!-- END GENERATED SCHEMA TABLE -->"


def schema_table_markdown() -> str:
    """The generated registry table (including its drift-gate markers)."""
    lines = [
        _DOCS_BEGIN,
        "| schema | emitter | required keys | purpose |",
        "|---|---|---|---|",
    ]
    for sid in registered_schemas():
        reg = SCHEMA_REGISTRY[sid]
        keys = ", ".join(f"`{k}`" for k in sorted(reg.required - {"schema"}))
        lines.append(f"| `{sid}` | {reg.emitter} | {keys} | {reg.description} |")
    lines.append(_DOCS_END)
    return "\n".join(lines) + "\n"


def _docs_path() -> str:
    import os

    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, "docs", "telemetry.md")


def docs_table_is_fresh(path: str = None) -> bool:
    """True when docs/telemetry.md's generated block matches this registry."""
    return _splice_docs(path or _docs_path(), write=False)


def write_docs_table(path: str = None) -> None:
    """Refresh docs/telemetry.md's generated block in place."""
    _splice_docs(path or _docs_path(), write=True)


def _splice_docs(path: str, write: bool) -> bool:
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    begin = text.find(_DOCS_BEGIN)
    end = text.find(_DOCS_END)
    if begin < 0 or end < 0:
        raise RuntimeError(
            f"{path} lacks the generated schema-table markers "
            f"({_DOCS_BEGIN!r} ... {_DOCS_END!r})"
        )
    end += len(_DOCS_END) + 1  # the block's trailing newline
    fresh = text[:begin] + schema_table_markdown() + text[end:]
    if write:
        with open(path, "w", encoding="utf-8") as f:
            f.write(fresh)
        return True
    return fresh == text


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        "python -m accelerate_tpu.telemetry.schemas",
        description="Telemetry schema registry: list, check or regenerate the "
        "generated table in docs/telemetry.md.",
    )
    parser.add_argument("--check", action="store_true",
                        help="exit 1 when the docs table drifted from the registry")
    parser.add_argument("--write", action="store_true",
                        help="rewrite the docs table from the registry")
    args = parser.parse_args(argv)
    if args.write:
        write_docs_table()
        print(f"schema table written to {_docs_path()}")
        return 0
    if args.check:
        if docs_table_is_fresh():
            print(f"schema table: {len(SCHEMA_REGISTRY)} registered schemas, docs fresh")
            return 0
        print("schema table in docs/telemetry.md drifted — run "
              "`python -m accelerate_tpu.telemetry.schemas --write`")
        return 1
    for sid in registered_schemas():
        print(f"{sid}  [{SCHEMA_REGISTRY[sid].emitter}]")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
