"""Artifact provenance: which code, config and workload produced this number?

Every BENCH_*.json artifact and serve-bench row is a claim about a specific
(commit, model config, backend, workload) tuple — but until this module nothing
recorded the tuple, so two artifacts could silently disagree because they were
built from different states. :func:`provenance_stamp` is the one shared stamp:

- ``git_commit`` — HEAD of the repo the package runs from (None outside a
  checkout; a dirty tree is flagged with ``-dirty``).
- ``config_fingerprint`` — a content hash of the model config *under the current
  backend environment*, reusing ``compile_cache.fingerprint`` (the same
  jax/jaxlib/backend/topology/XLA_FLAGS facts that decide whether two compiled
  artifacts are comparable decide whether two bench rows are).
- ``jax``/``backend`` — the headline environment facts inlined for humans.

Workload-trace replays additionally stamp the trace content hash
(``serving_gateway.workload.trace_hash``) so a curve can be reproduced from the
exact same arrival process, not a same-named file.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
from typing import Optional

__all__ = ["git_commit", "config_fingerprint", "provenance_stamp"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def git_commit(root: str = _REPO_ROOT) -> Optional[str]:
    """Short HEAD hash of the checkout at ``root`` (``-dirty`` suffixed when the
    working tree differs), or None when ``root`` is not a git repo / git is
    unavailable — artifacts built from a tarball honestly say so."""
    try:
        head = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=root, capture_output=True, text=True, timeout=10,
        )
        if head.returncode != 0:
            return None
        commit = head.stdout.strip()
        dirty = subprocess.run(
            ["git", "status", "--porcelain"],
            cwd=root, capture_output=True, text=True, timeout=10,
        )
        if dirty.returncode == 0 and dirty.stdout.strip():
            commit += "-dirty"
        return commit or None
    except (OSError, subprocess.SubprocessError):
        return None


def config_fingerprint(cfg=None, extra: str = "") -> str:
    """Content hash of ``cfg`` (its repr — dataclass reprs enumerate every
    field) under the current backend environment, via the compile cache's own
    fingerprint so "same config" means the same thing for bench rows as it does
    for cached executables. Works with ``cfg=None`` (environment-only hash)."""
    from ..compile_cache.fingerprint import fingerprint

    return fingerprint(repr(cfg), extra=extra)[:20]


def provenance_stamp(cfg=None) -> dict:
    """The provenance block bench.py / serve-bench stamp into every artifact."""
    import jax

    return {
        "git_commit": git_commit(),
        "config_fingerprint": config_fingerprint(cfg),
        "jax": jax.__version__,
        "backend": jax.default_backend(),
    }
