"""The live metrics plane: the record stream folded into queryable aggregates.

Everything the stack knows about itself used to be post-hoc: 20 record schemas
land in JSONL and are only readable after the fact (``trace-report``, bench
artifacts). :class:`MetricsPlane` is the live layer — a ``Telemetry`` **sink**
(zero new emit sites: it consumes the exact records the pipeline already
produces) that maintains counters, gauges and bounded sliding-window histograms
while the workload runs:

- serving: queue depth, slot/page-pool occupancy, KV bytes, tokens
- gateway: per-status request totals, TTFT/TPOT/queue-wait windows, the SLO
  good/bad event window burn-rate alerting reads
- fleet: per-replica health/load gauges, routing + migration counters
- resilience: fault/recovery counters (breaker transitions included),
  per-gang restart budgets
- training: step-time window, MPMD per-stage step latency, DCN transfer bytes

Exposed three ways: :meth:`MetricsPlane.stats` (live dict, the programmatic
surface the ROADMAP-5 autoscaler polls), the Prometheus text endpoint
(``telemetry.exporter``, off by default) and ``accelerate-tpu metrics-dump``
(offline aggregation of a JSONL run directory — pull-less scraping).
:class:`~.alerts.AlertEngine` evaluates burn-rate/threshold rules over the
same aggregates and emits ``alert/v1`` records through the same pipeline.

**Metric names are minted HERE** — :data:`METRIC_REGISTRY` is the single
source of truth, mirroring the schema registry: call sites import the
``M_*`` constants (graftlint's ``metric-name-literal`` rule flags a bare
``accelerate_tpu_*`` literal anywhere else), the catalog table in
``docs/telemetry.md`` is generated from it (``--check``/``--write``), and
:meth:`MetricsPlane.inc`/``set_gauge``/``observe`` reject unregistered names
at runtime.

Contract when **disabled** (the default, same as ``Telemetry``/``Tracer``):
``enabled`` is False, the plane never registers as a sink, and every public
method is a guarded no-op — zero clock calls, zero dict writes (asserted by
``tests/test_metrics.py``).

Stdlib-only by design (no jax, no numpy): the plane must be importable from
stripped CLI contexts (``metrics-dump`` over a recorded run directory).
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from .clocks import resolve_clock
from .schemas import (
    ALERT_SCHEMA,
    ELASTIC_RESTART_SCHEMA,
    FAULT_SCHEMA,
    FLEET_ROUTE_SCHEMA,
    FLEET_SCALE_SCHEMA,
    GATEWAY_REQUEST_SCHEMA,
    GATEWAY_SLO_SCHEMA,
    METRICS_SNAPSHOT_SCHEMA,
    MPMD_STAGE_STEP_SCHEMA,
    MPMD_TRANSFER_SCHEMA,
    RECOVERY_SCHEMA,
    REPLICA_HEALTH_SCHEMA,
    SERVING_HANDOFF_SCHEMA,
    SERVING_KV_SCHEMA,
    SERVING_SCHEMA,
    SERVING_SPEC_SCHEMA,
    STEP_RECORD_SCHEMA,
)
from .slo import latency_summary

__all__ = [
    "MetricSpec",
    "METRIC_REGISTRY",
    "MetricsPlane",
    "registered_metrics",
    "metric_table_markdown",
    # counters
    "M_REQUESTS_TOTAL",
    "M_TENANT_REQUESTS_TOTAL",
    "M_TENANT_SLO_GOOD_TOTAL",
    "M_TENANT_SLO_BAD_TOTAL",
    "M_TOKENS_TOTAL",
    "M_FAULTS_TOTAL",
    "M_RECOVERY_ACTIONS_TOTAL",
    "M_GANG_RESTARTS_TOTAL",
    "M_ROUTE_DECISIONS_TOTAL",
    "M_DCN_BYTES_TOTAL",
    "M_HANDOFF_BYTES_TOTAL",
    "M_ALERTS_TOTAL",
    "M_FLEET_SCALE_EVENTS_TOTAL",
    "M_FLEET_REPLICA_HOURS_TOTAL",
    "M_RECORDER_DROPPED_TOTAL",
    "M_EXPORTER_SCRAPES_TOTAL",
    # gauges
    "M_QUEUE_DEPTH",
    "M_FLEET_REPLICAS_ACTIVE",
    "M_SLOT_OCCUPANCY",
    "M_PAGE_OCCUPANCY",
    "M_KV_BYTES_IN_USE",
    "M_SPEC_ACCEPT_RATE",
    "M_REPLICA_HEALTH",
    "M_REPLICA_ACTIVE_SLOTS",
    "M_REPLICA_QUEUED",
    "M_BREAKER_CLOSED",
    "M_GANG_RESTART_BUDGET_REMAINING",
    "M_SLO_ATTAINMENT",
    "M_SLO_WINDOW_GOOD",
    "M_SLO_WINDOW_BAD",
    "M_TOKENS_PER_SECOND",
    # histograms (sliding windows)
    "M_TTFT_SECONDS",
    "M_TPOT_SECONDS",
    "M_QUEUE_WAIT_SECONDS",
    "M_TRAIN_STEP_SECONDS",
    "M_STAGE_STEP_SECONDS",
    "M_DCN_TRANSFER_SECONDS",
]

# ------------------------------------------------------------------ metric names
# Prometheus naming: one ``accelerate_tpu_`` namespace, unit-suffixed where the
# unit is not obvious, ``_total`` suffix on counters. These constants are the
# ONLY place the names are spelled (graftlint ``metric-name-literal``).

M_REQUESTS_TOTAL = "accelerate_tpu_gateway_requests_total"
M_TENANT_REQUESTS_TOTAL = "accelerate_tpu_gateway_tenant_requests_total"
M_TENANT_SLO_GOOD_TOTAL = "accelerate_tpu_gateway_tenant_slo_good_total"
M_TENANT_SLO_BAD_TOTAL = "accelerate_tpu_gateway_tenant_slo_bad_total"
M_TOKENS_TOTAL = "accelerate_tpu_serving_tokens_total"
M_FAULTS_TOTAL = "accelerate_tpu_faults_total"
M_RECOVERY_ACTIONS_TOTAL = "accelerate_tpu_recovery_actions_total"
M_GANG_RESTARTS_TOTAL = "accelerate_tpu_gang_restarts_total"
M_ROUTE_DECISIONS_TOTAL = "accelerate_tpu_fleet_route_decisions_total"
M_DCN_BYTES_TOTAL = "accelerate_tpu_mpmd_dcn_bytes_total"
M_HANDOFF_BYTES_TOTAL = "accelerate_tpu_kv_handoff_bytes_total"
M_ALERTS_TOTAL = "accelerate_tpu_alerts_total"
M_FLEET_SCALE_EVENTS_TOTAL = "accelerate_tpu_fleet_scale_events_total"
M_FLEET_REPLICA_HOURS_TOTAL = "accelerate_tpu_fleet_replica_hours_total"
M_RECORDER_DROPPED_TOTAL = "accelerate_tpu_recorder_dropped_total"
M_EXPORTER_SCRAPES_TOTAL = "accelerate_tpu_exporter_scrapes_total"

M_QUEUE_DEPTH = "accelerate_tpu_serving_queue_depth"
M_FLEET_REPLICAS_ACTIVE = "accelerate_tpu_fleet_replicas_active"
M_SLOT_OCCUPANCY = "accelerate_tpu_serving_slot_occupancy"
M_PAGE_OCCUPANCY = "accelerate_tpu_kv_page_occupancy"
M_KV_BYTES_IN_USE = "accelerate_tpu_kv_bytes_in_use"
M_SPEC_ACCEPT_RATE = "accelerate_tpu_spec_accept_rate"
M_REPLICA_HEALTH = "accelerate_tpu_replica_health"
M_REPLICA_ACTIVE_SLOTS = "accelerate_tpu_replica_active_slots"
M_REPLICA_QUEUED = "accelerate_tpu_replica_queued"
M_BREAKER_CLOSED = "accelerate_tpu_breaker_closed"
M_GANG_RESTART_BUDGET_REMAINING = "accelerate_tpu_gang_restart_budget_remaining"
M_SLO_ATTAINMENT = "accelerate_tpu_slo_attainment"
M_SLO_WINDOW_GOOD = "accelerate_tpu_slo_window_good"
M_SLO_WINDOW_BAD = "accelerate_tpu_slo_window_bad"
M_TOKENS_PER_SECOND = "accelerate_tpu_serving_tokens_per_second"

M_TTFT_SECONDS = "accelerate_tpu_gateway_ttft_seconds"
M_TPOT_SECONDS = "accelerate_tpu_gateway_tpot_seconds"
M_QUEUE_WAIT_SECONDS = "accelerate_tpu_gateway_queue_wait_seconds"
M_TRAIN_STEP_SECONDS = "accelerate_tpu_train_step_seconds"
M_STAGE_STEP_SECONDS = "accelerate_tpu_mpmd_stage_step_seconds"
M_DCN_TRANSFER_SECONDS = "accelerate_tpu_mpmd_dcn_transfer_seconds"


# ------------------------------------------------------------------- registry
@dataclasses.dataclass(frozen=True)
class MetricSpec:
    """One registered metric: name, kind, label keys it may carry, which
    record schema feeds it (``derived`` for values computed at snapshot
    time), and what it means."""

    name: str
    kind: str                       # counter | gauge | histogram
    labels: Tuple[str, ...]
    source: str                     # feeding schema id, or "derived"
    description: str


def _m(name: str, kind: str, labels, source: str, description: str) -> MetricSpec:
    return MetricSpec(name, kind, tuple(labels), source, description)


#: Every metric the plane maintains, keyed by name — the single source of
#: truth call sites, the docs catalog, alert rules and the exporter share.
METRIC_REGISTRY: Dict[str, MetricSpec] = {
    s.name: s
    for s in (
        _m(M_REQUESTS_TOTAL, "counter", ("status",), GATEWAY_REQUEST_SCHEMA,
           "terminal gateway requests by status"),
        _m(M_TENANT_REQUESTS_TOTAL, "counter", ("tenant", "status"),
           GATEWAY_REQUEST_SCHEMA,
           "terminal gateway requests by tenant and status"),
        _m(M_TENANT_SLO_GOOD_TOTAL, "counter", ("tenant",),
           GATEWAY_REQUEST_SCHEMA,
           "per-tenant terminal requests that met the SLO"),
        _m(M_TENANT_SLO_BAD_TOTAL, "counter", ("tenant",),
           GATEWAY_REQUEST_SCHEMA,
           "per-tenant terminal requests that violated the SLO"),
        _m(M_TOKENS_TOTAL, "counter", (), GATEWAY_REQUEST_SCHEMA,
           "tokens delivered by terminal requests"),
        _m(M_FAULTS_TOTAL, "counter", ("site",), FAULT_SCHEMA,
           "faults observed at recovery boundaries (injected or real)"),
        _m(M_RECOVERY_ACTIONS_TOTAL, "counter", ("action",), RECOVERY_SCHEMA,
           "recovery actions (quarantine/rebuild/circuit transitions/...)"),
        _m(M_GANG_RESTARTS_TOTAL, "counter", ("gang",), ELASTIC_RESTART_SCHEMA,
           "gang restart attempts"),
        _m(M_ROUTE_DECISIONS_TOTAL, "counter", ("reason",), FLEET_ROUTE_SCHEMA,
           "fleet routing decisions (dispatch/probe/migrate/handoff)"),
        _m(M_DCN_BYTES_TOTAL, "counter", ("direction",), MPMD_TRANSFER_SCHEMA,
           "inter-stage DCN payload bytes (fwd activations / bwd cotangents)"),
        _m(M_HANDOFF_BYTES_TOTAL, "counter", (), SERVING_HANDOFF_SCHEMA,
           "cross-engine KV page handoff wire bytes"),
        _m(M_ALERTS_TOTAL, "counter", ("rule", "state"), ALERT_SCHEMA,
           "alert-state transitions seen on the record stream"),
        _m(M_FLEET_SCALE_EVENTS_TOTAL, "counter", ("action",),
           FLEET_SCALE_SCHEMA,
           "autoscaler decisions (scale_up/scale_down/rebalance)"),
        _m(M_FLEET_REPLICA_HOURS_TOTAL, "counter", (), FLEET_SCALE_SCHEMA,
           "cumulative replica-hours accrued by the fleet (the cost axis of "
           "attainment-per-replica-hour)"),
        _m(M_RECORDER_DROPPED_TOTAL, "counter", (), "derived",
           "flight-ring records evicted before any capsule captured them"),
        _m(M_EXPORTER_SCRAPES_TOTAL, "counter", ("endpoint",), "derived",
           "HTTP scrapes served by the Prometheus exporter"),
        _m(M_QUEUE_DEPTH, "gauge", (), SERVING_SCHEMA,
           "engine-internal queued requests (last decode step)"),
        _m(M_FLEET_REPLICAS_ACTIVE, "gauge", ("role",), FLEET_SCALE_SCHEMA,
           "live (non-retired, non-draining-out) replicas per role after the "
           "latest autoscaler decision"),
        _m(M_SLOT_OCCUPANCY, "gauge", (), SERVING_SCHEMA,
           "decode-lane occupancy in [0,1] (last decode step)"),
        _m(M_PAGE_OCCUPANCY, "gauge", (), SERVING_KV_SCHEMA,
           "KV page-pool occupancy in [0,1] — the admission-pressure signal"),
        _m(M_KV_BYTES_IN_USE, "gauge", (), SERVING_KV_SCHEMA,
           "KV pool bytes currently allocated"),
        _m(M_SPEC_ACCEPT_RATE, "gauge", (), SERVING_SPEC_SCHEMA,
           "cumulative speculative acceptance rate"),
        _m(M_REPLICA_HEALTH, "gauge", ("replica",), REPLICA_HEALTH_SCHEMA,
           "per-replica health score in [0,1]"),
        _m(M_REPLICA_ACTIVE_SLOTS, "gauge", ("replica",), REPLICA_HEALTH_SCHEMA,
           "per-replica active decode lanes"),
        _m(M_REPLICA_QUEUED, "gauge", ("replica",), REPLICA_HEALTH_SCHEMA,
           "per-replica engine-internal queue depth"),
        _m(M_BREAKER_CLOSED, "gauge", ("replica",), REPLICA_HEALTH_SCHEMA,
           "1 when the (replica's) circuit breaker is closed, else 0"),
        _m(M_GANG_RESTART_BUDGET_REMAINING, "gauge", ("gang",),
           ELASTIC_RESTART_SCHEMA,
           "restart attempts left before the gang's budget exhausts"),
        _m(M_SLO_ATTAINMENT, "gauge", (), "derived",
           "good/(good+bad) over the SLO event window (None with no events)"),
        _m(M_SLO_WINDOW_GOOD, "gauge", (), "derived",
           "terminal requests meeting the SLO inside the window"),
        _m(M_SLO_WINDOW_BAD, "gauge", (), "derived",
           "terminal requests violating the SLO inside the window"),
        _m(M_TOKENS_PER_SECOND, "gauge", (), "derived",
           "windowed token delivery rate (terminal-request tokens / window)"),
        _m(M_TTFT_SECONDS, "histogram", (), GATEWAY_REQUEST_SCHEMA,
           "time to first token, sliding window"),
        _m(M_TPOT_SECONDS, "histogram", (), GATEWAY_REQUEST_SCHEMA,
           "mean inter-token gap, sliding window"),
        _m(M_QUEUE_WAIT_SECONDS, "histogram", (), GATEWAY_REQUEST_SCHEMA,
           "scheduler queue wait, sliding window"),
        _m(M_TRAIN_STEP_SECONDS, "histogram", (), STEP_RECORD_SCHEMA,
           "fenced train-step wall seconds, sliding window"),
        _m(M_STAGE_STEP_SECONDS, "histogram", ("stage",),
           MPMD_STAGE_STEP_SCHEMA,
           "per-MPMD-stage busy seconds per train step, sliding window"),
        _m(M_DCN_TRANSFER_SECONDS, "histogram", (), MPMD_TRANSFER_SCHEMA,
           "inter-stage DCN transfer latency, sliding window"),
    )
}


def registered_metrics() -> List[str]:
    """Every registered metric name, sorted."""
    return sorted(METRIC_REGISTRY)


LabelSet = Tuple[Tuple[str, str], ...]

#: Sentinel distinguishing "not a derived gauge" from a derived gauge whose
#: live value is legitimately None (no traffic in the window).
_NO_DERIVED = object()


def _label_key(labels: Mapping[str, object]) -> LabelSet:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def render_name(name: str, labels: LabelSet = ()) -> str:
    """``name{key="value",...}`` — the Prometheus series spelling, also used
    as the stable key in :meth:`MetricsPlane.stats` dicts."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{inner}}}"


class MetricsPlane:
    """Live aggregates over the telemetry record stream.

    Construction over an enabled ``Telemetry`` registers the plane as a sink;
    every record the pipeline emits is folded into the aggregate tables by a
    per-schema handler. ``clock`` is injectable (virtual-clock replays hand
    the gateway's clock in, so sliding windows share the workload's time
    domain). ``window_s`` bounds every sliding window in time; ``window_cap``
    bounds it in entries (a hot serving loop must not grow per-event state
    without bound — both bounds always apply).

    The plane never emits on its own: :meth:`snapshot_record` *builds* the
    ``metrics.snapshot/v1`` record and only routes it through telemetry when
    asked (``emit=True``), so consuming and producing stay visibly separate.
    """

    def __init__(self, telemetry=None, clock: Optional[Callable[[], float]] = None,
                 window_s: float = 300.0, window_cap: int = 4096,
                 enabled: Optional[bool] = None):
        self.telemetry = telemetry
        #: The ONE flag every public method guards on (the Telemetry contract).
        self.enabled = bool(enabled) if enabled is not None else (
            telemetry is not None and getattr(telemetry, "enabled", False)
        )
        self._clock = resolve_clock(clock)
        self.window_s = float(window_s)
        self.window_cap = int(window_cap)
        self.records_consumed = 0
        self._counters: Dict[Tuple[str, LabelSet], float] = {}
        #: Per-counter event log (t, delta) — windowed-increase reads for
        #: alert rules ("K step failures in 60 s"), bounded like histograms.
        self._counter_events: Dict[Tuple[str, LabelSet], deque] = {}
        self._gauges: Dict[Tuple[str, LabelSet], float] = {}
        self._hists: Dict[Tuple[str, LabelSet], deque] = {}
        #: SLO event window: (t, good) per terminal request — burn-rate input.
        self._slo_events: deque = deque(maxlen=window_cap)
        #: Token-delivery window: (t, n_tokens) per terminal request.
        self._token_events: deque = deque(maxlen=window_cap)
        #: Alert engines polling this plane (``alerts.AlertEngine`` registers
        #: itself); polled after every consumed record, throttled per engine.
        self.alert_engines: List[object] = []
        self._handlers = {
            SERVING_SCHEMA: self._on_serving,
            SERVING_KV_SCHEMA: self._on_kv,
            SERVING_SPEC_SCHEMA: self._on_spec,
            GATEWAY_REQUEST_SCHEMA: self._on_request,
            REPLICA_HEALTH_SCHEMA: self._on_replica_health,
            FLEET_ROUTE_SCHEMA: self._on_route,
            ELASTIC_RESTART_SCHEMA: self._on_restart,
            MPMD_TRANSFER_SCHEMA: self._on_transfer,
            MPMD_STAGE_STEP_SCHEMA: self._on_stage_step,
            STEP_RECORD_SCHEMA: self._on_train_step,
            SERVING_HANDOFF_SCHEMA: self._on_handoff,
            FAULT_SCHEMA: self._on_fault,
            RECOVERY_SCHEMA: self._on_recovery,
            ALERT_SCHEMA: self._on_alert,
            FLEET_SCALE_SCHEMA: self._on_scale,
        }
        #: Last cumulative replica-hours seen on a ``fleet.scale/v1`` record —
        #: the counter is fed by DELTAS of the record's monotone value.
        self._replica_hours_seen = 0.0
        if self.enabled and telemetry is not None:
            telemetry.sinks.append(self._consume)

    # -------------------------------------------------------------- primitives
    def _check(self, name: str, kind: str) -> None:
        spec = METRIC_REGISTRY.get(name)
        if spec is None:
            raise KeyError(
                f"unregistered metric {name!r} — mint it in "
                "telemetry/metrics.py (METRIC_REGISTRY) first"
            )
        if spec.kind != kind:
            raise ValueError(f"{name} is a {spec.kind}, used as a {kind}")

    def inc(self, name: str, value: float = 1.0, t: Optional[float] = None,
            **labels) -> None:
        """Add ``value`` to counter ``name`` (and its windowed event log)."""
        if not self.enabled:
            return
        self._check(name, "counter")
        key = (name, _label_key(labels))
        self._counters[key] = self._counters.get(key, 0.0) + value
        events = self._counter_events.get(key)
        if events is None:
            events = self._counter_events[key] = deque(maxlen=self.window_cap)
        events.append((self._clock() if t is None else t, value))

    def set_gauge(self, name: str, value: float, **labels) -> None:
        if not self.enabled:
            return
        self._check(name, "gauge")
        self._gauges[(name, _label_key(labels))] = float(value)

    def observe(self, name: str, value: float, t: Optional[float] = None,
                **labels) -> None:
        """Append one observation to histogram ``name``'s sliding window."""
        if not self.enabled:
            return
        self._check(name, "histogram")
        key = (name, _label_key(labels))
        window = self._hists.get(key)
        if window is None:
            window = self._hists[key] = deque(maxlen=self.window_cap)
        window.append((self._clock() if t is None else t, float(value)))

    def _trim(self, window: deque, now: float, horizon: Optional[float] = None) -> None:
        horizon = self.window_s if horizon is None else horizon
        while window and now - window[0][0] > horizon:
            window.popleft()

    # ----------------------------------------------------------- record intake
    def consume(self, record: Mapping) -> None:
        """Fold one record into the aggregates (the sink entry point; public
        so offline consumers — ``metrics-dump`` — can replay a JSONL file
        through the identical path)."""
        if not self.enabled:
            return
        self._consume(record)

    def _consume(self, record: Mapping) -> None:
        self.records_consumed += 1
        handler = self._handlers.get(record.get("schema"))
        if handler is not None:
            handler(record)
        for engine in self.alert_engines:
            engine.poll()

    def replay(self, records) -> int:
        """Offline intake: feed a recorded stream through :meth:`consume`.
        Returns the number of records consumed."""
        n = 0
        for record in records:
            self.consume(record)
            n += 1
        return n

    # ------------------------------------------------------- per-schema handlers
    def _on_serving(self, r: Mapping) -> None:
        if "queued" in r:
            self.set_gauge(M_QUEUE_DEPTH, r["queued"])
        if "slot_occupancy" in r:
            self.set_gauge(M_SLOT_OCCUPANCY, r["slot_occupancy"])

    def _on_kv(self, r: Mapping) -> None:
        if "page_occupancy" in r:
            self.set_gauge(M_PAGE_OCCUPANCY, r["page_occupancy"])
        if "kv_bytes_in_use" in r:
            self.set_gauge(M_KV_BYTES_IN_USE, r["kv_bytes_in_use"])

    def _on_spec(self, r: Mapping) -> None:
        proposed = r.get("proposed_total") or 0
        if proposed:
            self.set_gauge(M_SPEC_ACCEPT_RATE,
                           (r.get("accepted_total") or 0) / proposed)

    #: Terminal statuses that count AGAINST the SLO (a cancel is the client's
    #: own doing — neither good nor bad).
    _SLO_BAD = frozenset({"failed", "expired", "evicted", "shed", "rejected"})

    def _on_request(self, r: Mapping) -> None:
        now = self._clock()
        status = r.get("status")
        tenant = r.get("tenant") or "default"
        self.inc(M_REQUESTS_TOTAL, t=now, status=status)
        self.inc(M_TENANT_REQUESTS_TOTAL, t=now, tenant=tenant, status=status)
        tokens = r.get("n_tokens") or 0
        if tokens:
            self.inc(M_TOKENS_TOTAL, float(tokens), t=now)
            self._token_events.append((now, float(tokens)))
        for metric, column in ((M_TTFT_SECONDS, "ttft_s"),
                               (M_TPOT_SECONDS, "tpot_s"),
                               (M_QUEUE_WAIT_SECONDS, "queue_wait_s")):
            value = r.get(column)
            if value is not None:
                self.observe(metric, value, t=now)
        if status == "done":
            # deadline_met None = no deadline declared: delivered = good.
            good = r.get("deadline_met") is not False
            self._slo_events.append((now, good))
            self.inc(M_TENANT_SLO_GOOD_TOTAL if good else M_TENANT_SLO_BAD_TOTAL,
                     t=now, tenant=tenant)
        elif status in self._SLO_BAD:
            self._slo_events.append((now, False))
            self.inc(M_TENANT_SLO_BAD_TOTAL, t=now, tenant=tenant)

    def _on_replica_health(self, r: Mapping) -> None:
        rid = r.get("replica")
        self.set_gauge(M_REPLICA_HEALTH, r.get("health") or 0.0, replica=rid)
        self.set_gauge(M_REPLICA_ACTIVE_SLOTS, r.get("active_slots") or 0,
                       replica=rid)
        self.set_gauge(M_REPLICA_QUEUED, r.get("queued") or 0, replica=rid)
        self.set_gauge(M_BREAKER_CLOSED,
                       1.0 if r.get("breaker_state") == "closed" else 0.0,
                       replica=rid)

    def _on_route(self, r: Mapping) -> None:
        self.inc(M_ROUTE_DECISIONS_TOTAL, reason=r.get("reason"))

    def _on_restart(self, r: Mapping) -> None:
        gang = r.get("gang_id")
        self.inc(M_GANG_RESTARTS_TOTAL, gang=gang)
        used = r.get("attempts_used")
        budget = r.get("max_restarts")
        if used is not None and budget is not None:
            self.set_gauge(M_GANG_RESTART_BUDGET_REMAINING,
                           max(0, int(budget) - int(used)), gang=gang)

    def _on_transfer(self, r: Mapping) -> None:
        self.inc(M_DCN_BYTES_TOTAL, float(r.get("nbytes") or 0),
                 direction=r.get("direction"))
        if r.get("dur_s") is not None:
            self.observe(M_DCN_TRANSFER_SECONDS, r["dur_s"])

    def _on_stage_step(self, r: Mapping) -> None:
        if r.get("busy_s") is not None:
            self.observe(M_STAGE_STEP_SECONDS, r["busy_s"], stage=r.get("stage"))

    def _on_train_step(self, r: Mapping) -> None:
        if r.get("wall_s") is not None:
            self.observe(M_TRAIN_STEP_SECONDS, r["wall_s"])

    def _on_handoff(self, r: Mapping) -> None:
        self.inc(M_HANDOFF_BYTES_TOTAL, float(r.get("nbytes") or 0))

    def _on_fault(self, r: Mapping) -> None:
        self.inc(M_FAULTS_TOTAL, site=r.get("site"))

    def _on_recovery(self, r: Mapping) -> None:
        self.inc(M_RECOVERY_ACTIONS_TOTAL, action=r.get("action"))

    def _on_alert(self, r: Mapping) -> None:
        self.inc(M_ALERTS_TOTAL, rule=r.get("rule"), state=r.get("state"))

    def _on_scale(self, r: Mapping) -> None:
        self.inc(M_FLEET_SCALE_EVENTS_TOTAL, action=r.get("action"))
        for role, count in (r.get("replicas_by_role") or {}).items():
            self.set_gauge(M_FLEET_REPLICAS_ACTIVE, count, role=role)
        hours = r.get("replica_hours")
        if hours is not None and float(hours) > self._replica_hours_seen:
            self.inc(M_FLEET_REPLICA_HOURS_TOTAL,
                     float(hours) - self._replica_hours_seen,
                     t=r.get("t"))
            self._replica_hours_seen = float(hours)

    # ------------------------------------------------------------ aggregate reads
    def counter_value(self, name: str, **labels) -> float:
        """Cumulative counter value (0.0 when never incremented). With a
        LABELED counter and no labels given, sums across every label set."""
        if labels or not METRIC_REGISTRY[name].labels:
            return self._counters.get((name, _label_key(labels)), 0.0)
        return sum(v for (n, _), v in self._counters.items() if n == name)

    def _sub_horizon(self, window_s: Optional[float]) -> float:
        """A requested sub-window, capped at the plane horizon. Event logs are
        only ever TRIMMED at ``self.window_s`` — a shorter read must filter,
        never pop, or a fast-window read would destroy the slow window's
        events (the multiwindow burn-rate bug this method exists to prevent)."""
        if window_s is None:
            return self.window_s
        return min(float(window_s), self.window_s)

    def window_increase(self, name: str, window_s: Optional[float] = None,
                        now: Optional[float] = None, **labels) -> float:
        """Counter increase inside the trailing window — the rate-style read
        threshold alert rules use. Labeled counters sum across label sets
        when no labels are given (same convention as :meth:`counter_value`)."""
        now = self._clock() if now is None else now
        cutoff = now - self._sub_horizon(window_s)
        keys = (
            [(name, _label_key(labels))]
            if labels or not METRIC_REGISTRY[name].labels
            else [k for k in self._counter_events if k[0] == name]
        )
        total = 0.0
        for key in keys:
            events = self._counter_events.get(key)
            if events is None:
                continue
            self._trim(events, now)
            total += sum(delta for t, delta in events if t >= cutoff)
        return total

    def gauge_value(self, name: str, now: Optional[float] = None, **labels):
        """Current gauge value — None when never set. With a LABELED gauge and
        no labels given, returns ``{rendered_series: value}`` for every label
        set (alert rules reduce with min/max). DERIVED gauges (attainment,
        tokens/s, the SLO window counts) are computed live here — they never
        land in the stored table, and an alert rule naming one must read the
        real value, not permanent None."""
        derived = self._derived_gauge(name, now)
        if derived is not _NO_DERIVED:
            return derived
        if labels or not METRIC_REGISTRY[name].labels:
            return self._gauges.get((name, _label_key(labels)))
        return {
            render_name(n, lk): v
            for (n, lk), v in self._gauges.items() if n == name
        }

    def _derived_gauge(self, name: str, now: Optional[float] = None):
        """The live value of a ``source == "derived"`` gauge, or
        :data:`_NO_DERIVED` for stored metrics."""
        if name == M_SLO_ATTAINMENT:
            return self.attainment(now=now)
        if name == M_TOKENS_PER_SECOND:
            return self.tokens_per_second(now=now)
        if name == M_SLO_WINDOW_GOOD:
            return float(self.slo_window(now=now)[0])
        if name == M_SLO_WINDOW_BAD:
            return float(self.slo_window(now=now)[1])
        return _NO_DERIVED

    def histogram_summary(self, name: str, now: Optional[float] = None,
                          **labels) -> dict:
        """``latency_summary`` block over the trailing window of ``name``."""
        now = self._clock() if now is None else now
        window = self._hists.get((name, _label_key(labels)))
        if window is None:
            return {"count": 0}
        self._trim(window, now)
        return latency_summary([v for _, v in window])

    def slo_window(self, window_s: Optional[float] = None,
                   now: Optional[float] = None) -> Tuple[int, int]:
        """(good, bad) terminal-request counts inside the trailing window —
        the burn-rate numerator/denominator. Sub-windows filter in place (see
        :meth:`_sub_horizon`) so one event log serves every window."""
        now = self._clock() if now is None else now
        cutoff = now - self._sub_horizon(window_s)
        self._trim(self._slo_events, now)
        good = bad = 0
        for t, ok in self._slo_events:
            if t >= cutoff:
                good, bad = (good + 1, bad) if ok else (good, bad + 1)
        return good, bad

    def error_rate(self, window_s: Optional[float] = None,
                   now: Optional[float] = None) -> Optional[float]:
        """bad/(good+bad) over the window; None when no events landed (no
        traffic is not an outage — burn-rate rules skip, not fire)."""
        good, bad = self.slo_window(window_s, now)
        total = good + bad
        return None if total == 0 else bad / total

    def attainment(self, window_s: Optional[float] = None,
                   now: Optional[float] = None) -> Optional[float]:
        """good/(good+bad) over the window (None with no events)."""
        rate = self.error_rate(window_s, now)
        return None if rate is None else 1.0 - rate

    def tokens_per_second(self, window_s: Optional[float] = None,
                          now: Optional[float] = None) -> float:
        """Windowed token delivery rate (terminal-request tokens / window)."""
        now = self._clock() if now is None else now
        horizon = self._sub_horizon(window_s)
        cutoff = now - horizon
        self._trim(self._token_events, now)
        return (sum(n for t, n in self._token_events if t >= cutoff)
                / max(horizon, 1e-9))

    # ------------------------------------------------------------------ snapshots
    def stats(self, now: Optional[float] = None) -> dict:
        """The whole plane as one dict: cumulative counters, current gauges,
        windowed histogram summaries, the SLO block and derived rates —
        keys are Prometheus series spellings (``name{label="v"}``)."""
        if not self.enabled:
            return {"enabled": False}
        now = self._clock() if now is None else now
        counters = {
            render_name(n, lk): v
            for (n, lk), v in sorted(self._counters.items())
        }
        gauges = {
            render_name(n, lk): v
            for (n, lk), v in sorted(self._gauges.items())
        }
        good, bad = self.slo_window(now=now)
        att = self.attainment(now=now)
        if att is not None:
            gauges[M_SLO_ATTAINMENT] = round(att, 6)
        gauges[M_SLO_WINDOW_GOOD] = good
        gauges[M_SLO_WINDOW_BAD] = bad
        gauges[M_TOKENS_PER_SECOND] = round(self.tokens_per_second(now=now), 6)
        histograms = {
            render_name(n, lk): self.histogram_summary(n, now=now, **dict(lk))
            for (n, lk) in sorted(self._hists)
        }
        return {
            "enabled": True,
            "window_s": self.window_s,
            "records_consumed": self.records_consumed,
            "counters": counters,
            "gauges": gauges,
            "histograms": histograms,
            "slo": {
                "window_good": good,
                "window_bad": bad,
                "attainment": None if att is None else round(att, 6),
            },
        }

    def snapshot_record(self, now: Optional[float] = None,
                        emit: bool = False) -> dict:
        """The ``metrics.snapshot/v1`` record (bench rows stamp it; with
        ``emit=True`` it also rides the telemetry pipeline)."""
        now = self._clock() if now is None else now
        stats = self.stats(now=now)
        record = {
            "schema": METRICS_SNAPSHOT_SCHEMA,
            "t": round(now, 6),
            "counters": stats.get("counters", {}),
            "gauges": stats.get("gauges", {}),
            "histograms": stats.get("histograms", {}),
            "slo": stats.get("slo", {}),
        }
        if emit and self.telemetry is not None:
            self.telemetry.emit(record)
        return record

    def __repr__(self) -> str:
        return (
            f"MetricsPlane(enabled={self.enabled}, "
            f"records_consumed={self.records_consumed}, "
            f"series={len(self._counters) + len(self._gauges) + len(self._hists)})"
        )


# ------------------------------------------------------------------- docs drift
_DOCS_BEGIN = "<!-- BEGIN GENERATED METRIC CATALOG (python -m accelerate_tpu.telemetry.metrics --write) -->"
_DOCS_END = "<!-- END GENERATED METRIC CATALOG -->"


def metric_table_markdown() -> str:
    """The generated metric catalog (including its drift-gate markers)."""
    lines = [
        _DOCS_BEGIN,
        "| metric | kind | labels | fed by | meaning |",
        "|---|---|---|---|---|",
    ]
    for name in registered_metrics():
        spec = METRIC_REGISTRY[name]
        labels = ", ".join(f"`{l}`" for l in spec.labels) or "—"
        source = "derived" if spec.source == "derived" else f"`{spec.source}`"
        lines.append(
            f"| `{spec.name}` | {spec.kind} | {labels} | {source} "
            f"| {spec.description} |"
        )
    lines.append(_DOCS_END)
    return "\n".join(lines) + "\n"


def _docs_path() -> str:
    import os

    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, "docs", "telemetry.md")


def docs_catalog_is_fresh(path: str = None) -> bool:
    """True when docs/telemetry.md's generated catalog matches the registry."""
    return _splice_docs(path or _docs_path(), write=False)


def write_docs_catalog(path: str = None) -> None:
    """Refresh docs/telemetry.md's generated catalog in place."""
    _splice_docs(path or _docs_path(), write=True)


def _splice_docs(path: str, write: bool) -> bool:
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    begin = text.find(_DOCS_BEGIN)
    end = text.find(_DOCS_END)
    if begin < 0 or end < 0:
        raise RuntimeError(
            f"{path} lacks the generated metric-catalog markers "
            f"({_DOCS_BEGIN!r} ... {_DOCS_END!r})"
        )
    end += len(_DOCS_END) + 1  # the block's trailing newline
    fresh = text[:begin] + metric_table_markdown() + text[end:]
    if write:
        with open(path, "w", encoding="utf-8") as f:
            f.write(fresh)
        return True
    return fresh == text


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        "python -m accelerate_tpu.telemetry.metrics",
        description="Metric registry: list, check or regenerate the generated "
        "catalog table in docs/telemetry.md.",
    )
    parser.add_argument("--check", action="store_true",
                        help="exit 1 when the docs catalog drifted from the registry")
    parser.add_argument("--write", action="store_true",
                        help="rewrite the docs catalog from the registry")
    args = parser.parse_args(argv)
    if args.write:
        write_docs_catalog()
        print(f"metric catalog written to {_docs_path()}")
        return 0
    if args.check:
        if docs_catalog_is_fresh():
            print(f"metric catalog: {len(METRIC_REGISTRY)} registered metrics, "
                  "docs fresh")
            return 0
        print("metric catalog in docs/telemetry.md drifted — run "
              "`python -m accelerate_tpu.telemetry.metrics --write`")
        return 1
    for name in registered_metrics():
        spec = METRIC_REGISTRY[name]
        print(f"{name}  [{spec.kind}]  <- {spec.source}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
