"""The flight recorder: an always-on in-memory black box + incident capsules.

The stack's two existing observability modes are both wrong for an incident at
scale: the full JSONL firehose is unaffordable per-request, and with telemetry
off a 3am failure leaves nothing to debug. :class:`FlightRecorder` is the tier
between them — a ``Telemetry`` **sink** (zero new emit sites) holding a bounded
in-memory ring of the most recent records, periodic metrics-plane snapshots,
and the span buffer tail-sampled tracing promotes from:

- **Ring**: every record the pipeline emits lands in a ``deque(maxlen=ring_size)``;
  evictions are counted (``dropped``) and surfaced through the registered
  ``accelerate_tpu_recorder_dropped_total`` metric when a plane is bound.
- **Tail sampling buffer**: a :class:`~.tracing.Tracer` with head sampling
  armed routes unsampled traces' spans here (:meth:`buffer`) instead of the
  JSONL pipeline — they exist ONLY as ring entries until :meth:`promote`
  replays them through ``Telemetry.emit`` (a request that ended badly becomes
  a full trace after the fact; span records are re-emitted verbatim, so
  reconstructed TTFT is exact).
- **Incident capsules**: on a trigger record (alert firing, fault, breaker
  open, quarantine, replica death, gang restart) — or an explicit
  :meth:`capture` call — the ring + every registered state provider's snapshot
  + provenance are dumped atomically into a self-contained gzip capsule
  directory (``capsule/v1`` manifest, :data:`~.schemas.CAPSULE_SCHEMA`).
  Per-trigger cooldown/dedupe keeps an alert storm at one capsule, not
  hundreds.

Overhead contract (same as ``Telemetry``/``Tracer``/``MetricsPlane``):
**disabled = two attribute reads, zero clock calls** — construction over a
disabled ``Telemetry`` never registers the sink and every public method is a
guarded no-op. The clock is injectable (virtual-clock replays hand the
workload clock in, so cooldowns and snapshot timestamps live in the same time
domain as the spans).

Stdlib-only by design: capsules must be writable from the serving loop and
readable from stripped CLI contexts (``capsule-report``) without jax.
"""

from __future__ import annotations

import gzip
import itertools
import json
import os
import re
from collections import deque
from typing import Callable, Dict, List, Mapping, Optional

from .clocks import resolve_clock
from .metrics import M_RECORDER_DROPPED_TOTAL
from .schemas import (
    ALERT_SCHEMA,
    CAPSULE_SCHEMA,
    ELASTIC_RESTART_SCHEMA,
    FAULT_SCHEMA,
    RECOVERY_SCHEMA,
    TRACE_SPAN_SCHEMA,
)

__all__ = ["FlightRecorder", "load_capsule", "list_capsules"]

#: Recovery actions that mark an incident (quarantine, breaker open, replica
#: death). Routine recovery bookkeeping (bisect rounds, rebuilds, breaker
#: close/half-open, replays) must NOT cut capsules — a clean replay of a
#: faulted trace performs none of these, so clean arms stay at zero.
_RECOVERY_TRIGGERS = frozenset({"circuit_open", "quarantine", "replica_died"})

#: Capsule directory names: ``capsule-<seq>-<trigger slug>``.
_SLUG_RE = re.compile(r"[^A-Za-z0-9_.-]+")


class FlightRecorder:
    """Bounded in-memory record ring + tail-sampling buffer + capsule writer.

    ``telemetry`` supplies both the enable flag and the sink registration;
    ``metrics`` (a :class:`~.metrics.MetricsPlane`, bindable later via
    :meth:`bind_metrics`) powers the drop counter and the periodic snapshots;
    ``capsule_dir`` arms capsule capture (None = ring-only recorder).
    """

    def __init__(self, telemetry=None, clock: Optional[Callable[[], float]] = None,
                 ring_size: int = 2048, snapshot_every: int = 256,
                 capsule_dir: Optional[str] = None,
                 capsule_cooldown_s: float = 30.0,
                 metrics=None, enabled: Optional[bool] = None):
        self.telemetry = telemetry
        #: The ONE flag every public method guards on (the Telemetry contract).
        self.enabled = bool(enabled) if enabled is not None else (
            telemetry is not None and getattr(telemetry, "enabled", False)
        )
        # Inherit the bound plane's time domain when no clock is injected:
        # capsule cooldowns and manifest timestamps must live in the same
        # (possibly virtual) time as the snapshots the plane stamps — the
        # PR-17 mixing bug started exactly here.
        self._clock_injected = clock is not None
        self._clock = resolve_clock(clock, getattr(metrics, "_clock", None))
        self.ring: deque = deque(maxlen=int(ring_size))
        self.snapshot_every = int(snapshot_every)
        self.capsule_dir = capsule_dir
        self.capsule_cooldown_s = float(capsule_cooldown_s)
        self.metrics = metrics
        self.records_seen = 0
        self.dropped = 0
        self.promoted_traces = 0
        self.capsules_written = 0
        self.capsules_suppressed = 0
        #: Written capsule manifests (each carries its ``path``), in order.
        self.capsules: List[dict] = []
        self._last_capture: Dict[str, float] = {}   # trigger → last capture t
        self._capsule_seq = itertools.count()
        self._state_providers: Dict[str, Callable[[], dict]] = {}
        #: True while a promotion/capture replays records through telemetry —
        #: the recorder's own sink must not re-ingest its own flush.
        self._replaying = False
        if self.enabled and telemetry is not None:
            telemetry.sinks.append(self._consume)

    # ------------------------------------------------------------------- intake
    def _consume(self, record: Mapping) -> None:
        """The sink entry point: ring every record, snapshot periodically,
        trigger capsule capture on incident records."""
        if self._replaying:
            return
        self.records_seen += 1
        self._append(record)
        if (self.snapshot_every and self.metrics is not None
                and self.records_seen % self.snapshot_every == 0):
            # The plane stamps (and window-trims) with ITS OWN clock — never
            # this recorder's: mixing time domains would purge a virtual-clock
            # plane's sliding windows with wall-clock timestamps.
            self._append(self.metrics.snapshot_record())
        trigger = self._trigger_for(record)
        if trigger is not None:
            self.capture(trigger, record=record)

    def _append(self, record: Mapping) -> None:
        ring = self.ring
        if ring.maxlen is not None and len(ring) == ring.maxlen:
            self.dropped += 1
            if self.metrics is not None:
                self.metrics.inc(M_RECORDER_DROPPED_TOTAL)
        ring.append(record)

    def buffer(self, record: Mapping) -> None:
        """Hold an UNSAMPLED trace's span as a ring entry only — no JSONL, no
        sinks, no per-trace side table (the zero-overhead contract for the
        happy path). :meth:`promote` replays it if the request ends badly."""
        if not self.enabled:
            return
        self.records_seen += 1
        self._append(record)

    def bind_metrics(self, plane) -> None:
        """Late-bind the metrics plane (the gateway builds its plane after the
        recorder exists); powers drop accounting and periodic snapshots."""
        if not self.enabled:
            return
        if plane is not None and getattr(plane, "enabled", False):
            self.metrics = plane
            # Late-bound plane: adopt its time domain unless a clock was
            # explicitly injected (same coherence contract as construction).
            if not self._clock_injected:
                self._clock = resolve_clock(None, getattr(plane, "_clock", None))

    def bind_clock(self, clock: Callable[[], float]) -> None:
        """Late-bind the time domain (the gateway hands its own clock in, so
        capsule cooldowns and manifest timestamps live in the same — possibly
        virtual — time as the records they frame)."""
        if not self.enabled:
            return
        if clock is not None:
            self._clock = clock
            self._clock_injected = True

    # ---------------------------------------------------------- tail promotion
    def promote(self, trace_id: str) -> int:
        """Replay one trace's ring-buffered spans through ``Telemetry.emit``
        (in ring = chronological order), turning a sampled-out request into a
        full trace. Each span is re-emitted VERBATIM plus a ``promoted`` mark,
        so a reconstruction from the promoted stream matches full tracing to
        the digit. Returns the number of spans promoted; idempotent (a span
        promotes once)."""
        if not self.enabled or self.telemetry is None:
            return 0
        spans = [r for r in self.ring
                 if r.get("schema") == TRACE_SPAN_SCHEMA
                 and r.get("trace_id") == trace_id
                 and not r.get("promoted")]
        if not spans:
            return 0
        self.promoted_traces += 1
        self._replaying = True
        try:
            for rec in spans:
                rec["promoted"] = True
                self.telemetry.emit(rec)
        finally:
            self._replaying = False
        return len(spans)

    # --------------------------------------------------------------- capsules
    def add_state_provider(self, name: str, fn: Callable[[], dict]) -> None:
        """Register a zero-arg callable whose dict snapshot rides every capsule
        (gateway stats, engine lane table, fault-plan fire history...). A
        provider that raises at capture time is recorded as an error string,
        never aborts the dump."""
        if not self.enabled:
            return
        self._state_providers[name] = fn

    def _trigger_for(self, record: Mapping) -> Optional[str]:
        """The capsule trigger/dedupe key for an incident record, or None for
        routine traffic."""
        schema = record.get("schema")
        if schema == ALERT_SCHEMA and record.get("state") == "firing":
            return f"alert:{record.get('rule')}"
        if schema == FAULT_SCHEMA:
            return f"fault:{record.get('site')}"
        if schema == RECOVERY_SCHEMA and record.get("action") in _RECOVERY_TRIGGERS:
            return f"recovery:{record.get('action')}"
        if schema == ELASTIC_RESTART_SCHEMA:
            return f"restart:{record.get('gang_id')}"
        return None

    def capture(self, trigger: str, record: Optional[Mapping] = None,
                now: Optional[float] = None, force: bool = False) -> Optional[str]:
        """Dump ring + state + provenance into one capsule dir, unless the same
        ``trigger`` captured within the cooldown (an alert storm writes ONE
        capsule). Returns the capsule path, or None when unarmed/suppressed."""
        if not self.enabled or self.capsule_dir is None:
            return None
        now = self._clock() if now is None else now
        last = self._last_capture.get(trigger)
        if not force and last is not None and now - last < self.capsule_cooldown_s:
            self.capsules_suppressed += 1
            return None
        self._last_capture[trigger] = now
        return self._write_capsule(trigger, record, now)

    def _state_snapshot(self) -> dict:
        state = {}
        for name, fn in self._state_providers.items():
            try:
                state[name] = fn()
            except Exception as exc:  # a broken provider must not lose the dump
                state[name] = {"error": f"{type(exc).__name__}: {exc}"}
        return state

    def _provenance(self) -> dict:
        """Capture-time provenance, degrading gracefully: the git commit needs
        only a subprocess; the jax block is skipped in stripped contexts."""
        from .provenance import git_commit

        prov = {"git_commit": git_commit()}
        try:
            import jax

            prov["jax"] = jax.__version__
            prov["backend"] = jax.default_backend()
        except Exception:
            pass
        return prov

    def _write_capsule(self, trigger: str, record: Optional[Mapping],
                       now: float) -> str:
        ring_records = list(self.ring)
        state = self._state_snapshot()
        manifest = {
            "schema": CAPSULE_SCHEMA,
            "trigger": trigger,
            "t": round(now, 9),
            "reason": dict(record) if record is not None else None,
            "ring_records": len(ring_records),
            "ring_dropped": self.dropped,
            "records_seen": self.records_seen,
            "promoted_traces": self.promoted_traces,
            "state_keys": sorted(state),
            "provenance": self._provenance(),
        }
        slug = _SLUG_RE.sub("-", trigger).strip("-") or "capture"
        name = f"capsule-{next(self._capsule_seq):04d}-{slug}"
        final = os.path.join(self.capsule_dir, name)
        tmp = final + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        with open(os.path.join(tmp, "manifest.json"), "w", encoding="utf-8") as f:
            json.dump(manifest, f, indent=2)
        with gzip.open(os.path.join(tmp, "ring.jsonl.gz"), "wt",
                       encoding="utf-8") as f:
            for rec in ring_records:
                f.write(json.dumps(rec) + "\n")
        with gzip.open(os.path.join(tmp, "state.json.gz"), "wt",
                       encoding="utf-8") as f:
            json.dump(state, f, indent=2)
        # The rename IS the commit: a reader never sees a half-written capsule.
        os.replace(tmp, final)
        self.capsules_written += 1
        self.capsules.append({**manifest, "path": final})
        if self.telemetry is not None:
            # Note the cut on the record stream itself (guarded: the manifest
            # must not re-enter the ring and trigger another capture).
            self._replaying = True
            try:
                self.telemetry.emit(manifest)
            finally:
                self._replaying = False
        return final

    def stats(self) -> dict:
        return {
            "enabled": self.enabled,
            "ring_size": self.ring.maxlen,
            "ring_len": len(self.ring),
            "records_seen": self.records_seen,
            "dropped": self.dropped,
            "promoted_traces": self.promoted_traces,
            "capsules_written": self.capsules_written,
            "capsules_suppressed": self.capsules_suppressed,
        }

    def __repr__(self) -> str:
        return (
            f"FlightRecorder(enabled={self.enabled}, ring={len(self.ring)}/"
            f"{self.ring.maxlen}, dropped={self.dropped}, "
            f"capsules={self.capsules_written})"
        )


# ------------------------------------------------------------------ capsule IO
def load_capsule(path: str) -> dict:
    """Read one capsule directory back: ``{"manifest", "ring", "state"}`` —
    everything ``capsule-report`` reconstructs from, with no live process."""
    with open(os.path.join(path, "manifest.json"), "r", encoding="utf-8") as f:
        manifest = json.load(f)
    ring: List[dict] = []
    ring_path = os.path.join(path, "ring.jsonl.gz")
    if os.path.exists(ring_path):
        with gzip.open(ring_path, "rt", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    ring.append(json.loads(line))
    state = {}
    state_path = os.path.join(path, "state.json.gz")
    if os.path.exists(state_path):
        with gzip.open(state_path, "rt", encoding="utf-8") as f:
            state = json.load(f)
    return {"manifest": manifest, "ring": ring, "state": state, "path": path}


def list_capsules(root: str) -> List[str]:
    """Capsule directories under ``root``, in capture order (a capsule dir
    itself passes through as a one-element list)."""
    if os.path.isfile(os.path.join(root, "manifest.json")):
        return [root]
    out = []
    try:
        entries = sorted(os.listdir(root))
    except FileNotFoundError:
        return []
    for entry in entries:
        full = os.path.join(root, entry)
        if os.path.isfile(os.path.join(full, "manifest.json")):
            out.append(full)
    return out
