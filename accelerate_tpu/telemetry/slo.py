"""SLO record schemas and latency summaries for the serving tier.

The gateway (``accelerate_tpu.serving_gateway``) measures three per-request
latencies — queue wait (submit → slot), TTFT (submit → first token, prefill
included) and TPOT (mean inter-token gap after the first) — and reports them as
p50/p95/p99 summaries. The summary math lives here, beside the other derived
rates, so bench.py, ``serve-bench`` and the gateway all stamp identical numbers
from one implementation (the telemetry package's founding rule: measurement code
is shared, not folklore).

All helpers are pure host-side float math — no jax imports, no device syncs.
"""

from __future__ import annotations

from typing import Dict, Iterable, Mapping, Optional, Sequence

from .schemas import (
    ELASTIC_RESTART_SCHEMA,
    GATEWAY_REQUEST_SCHEMA,
    GATEWAY_SLO_SCHEMA,
)

__all__ = [
    "GATEWAY_REQUEST_SCHEMA",
    "GATEWAY_SLO_SCHEMA",
    "ELASTIC_RESTART_SCHEMA",
    "percentile",
    "latency_summary",
    "slo_summary",
    "slo_attainment",
]

#: The percentiles every summary block carries.
SLO_PERCENTILES = (50, 95, 99)


def _percentile_sorted(ordered: Sequence[float], q: float) -> float:
    """Linear-interpolated percentile of an ALREADY-SORTED non-empty sequence."""
    if len(ordered) == 1:
        return float(ordered[0])
    rank = (q / 100.0) * (len(ordered) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(ordered) - 1)
    frac = rank - lo
    return float(ordered[lo] * (1.0 - frac) + ordered[hi] * frac)


def percentile(values: Sequence[float], q: float) -> float:
    """The ``q``-th percentile of ``values`` (linear interpolation, the numpy
    default), without importing numpy — summaries must work in stripped CLI
    contexts. ``values`` need not be sorted; it must be non-empty."""
    if not values:
        raise ValueError("percentile of an empty sequence")
    if not 0 <= q <= 100:
        raise ValueError(f"q={q} must lie in [0, 100]")
    return _percentile_sorted(sorted(values), q)


def latency_summary(
    values: Iterable[Optional[float]], percentiles: Sequence[float] = SLO_PERCENTILES
) -> dict:
    """``{count, mean, p50, p95, p99}`` over the non-None entries; ``{"count": 0}``
    when nothing was measured (a request rejected at admission has no TTFT —
    absence is the honest value, not 0.0).

    Sorts ONCE and reads every percentile off the ordered list — this runs per
    decode step inside ``ContinuousBatcher.stats()`` when telemetry is enabled,
    so the per-percentile re-sort ``percentile()`` would pay is not acceptable
    there."""
    vals = sorted(float(v) for v in values if v is not None)
    if not vals:
        return {"count": 0}
    out = {"count": len(vals), "mean": round(sum(vals) / len(vals), 6)}
    for q in percentiles:
        out[f"p{q:g}"] = round(_percentile_sorted(vals, q), 6)
    return out


def slo_summary(latencies: Mapping[str, Iterable[Optional[float]]]) -> Dict[str, dict]:
    """One :func:`latency_summary` block per metric name, e.g.
    ``{"ttft_s": {...}, "tpot_s": {...}, "queue_wait_s": {...}}``."""
    return {name: latency_summary(vals) for name, vals in latencies.items()}


def slo_attainment(values: Iterable[Optional[float]], target_s: float) -> Optional[float]:
    """Fraction of measured values at or under ``target_s`` (None when nothing was
    measured). The classic SLO statement "p95 TTFT <= 200 ms" is
    ``slo_attainment(ttfts, 0.2) >= 0.95``."""
    vals = [float(v) for v in values if v is not None]
    if not vals:
        return None
    return sum(v <= target_s for v in vals) / len(vals)
